"""Pallas TPU kernel for the masked median — the hot reduction of the
surgical-scrub scalers (reference ``/root/reference/iterative_cleaner.py:234-240,
249-255``; SURVEY.md section 7 layer 4).

Instead of sorting each line (XLA sort is O(n log^2 n) with poor lane
utilisation on TPU), the kernel finds the two middle order statistics
exactly by *radix bisection*: float32 values are mapped to an
order-preserving int32 key, and 32 fixed count-passes binary-search the key
domain for the k-th smallest element.  Every pass is a dense VPU
compare-and-sum over the whole tile, so the kernel is pure vector work with
no data-dependent shapes.

Exactness: the bisection recovers the exact bit patterns of the two middle
order statistics, and the final ``0.5 * (lo + hi)`` is the same float op the
sort-based path performs — the two implementations agree bit-for-bit
(locked in by tests/test_pallas_stats.py), so final-mask parity between
``median_impl='sort'`` and ``'pallas'`` is exact.

Semantics match :func:`iterative_cleaner_tpu.stats.masked_jax.masked_median`
(``np.ma.median``): median over unmasked entries, even counts average the
two middle values, fully-masked lines yield 0.0.  Masked entries carry the
key of +inf — the same sentinel the sort path pads with — so both
implementations share one total order (reals < inf == masked < NaN) and
agree bit-for-bit on every input, NaNs included.  Only float32 is
supported (the key mapping is 32-bit); callers fall back to the sort path
for other dtypes.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os as _os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams to CompilerParams; accept either spelling
# so the kernels load on both sides of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

_INT32_MIN = np.int32(-2147483648)
_INT32_MAX = np.int32(2147483647)
# key of +inf: the masked sentinel, chosen to equal the sort path's +inf
# padding so both implementations share one total order (reals < inf ==
# masked < NaN) and stay bit-identical even for NaN-bearing inputs.
_KEY_MASKED = np.int32(0x7F800000)

# Lane tile over the line axis; the reduction axis stays whole in VMEM.
_TILE_LINES = 128

# Whether a launch should run in interpret mode is a property of the
# devices the program actually TARGETS, not of the process default —
# jax.devices()[0] is wrong the moment a live-TPU process builds a CPU
# mesh (the multichip dryrun: entry() initialises the TPU backend, the
# cpu platform pin then fails, and every kernel traced for the explicit
# CPU mesh would lower non-interpreted and die in XLA:CPU).  Callers that
# know the target (parallel/shard_stats knows its mesh) scope an override
# around the traced call; everything else falls back to the default
# platform.
_INTERPRET_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "iclean_pallas_interpret", default=None)


def _interpret_default() -> bool:
    ov = _INTERPRET_OVERRIDE.get()
    if ov is not None:
        return ov
    return jax.devices()[0].platform != "tpu"


@contextlib.contextmanager
def pallas_interpret(value: bool):
    """Scope an explicit interpret-mode decision over any pallas launches
    traced inside the block (True = interpret; False = compile Mosaic)."""
    token = _INTERPRET_OVERRIDE.set(bool(value))
    try:
        yield
    finally:
        _INTERPRET_OVERRIDE.reset(token)


def _ordered_key(x):
    """Map float32 bits to int32 keys whose signed order matches float order
    (NaNs sort above +inf, mirroring XLA's total-order sort)."""
    b = jax.lax.bitcast_convert_type(x, jnp.int32)
    return b ^ ((b >> 31) & np.int32(0x7FFFFFFF))


def _key_to_float(o):
    # The transform is an involution.
    b = o ^ ((o >> 31) & np.int32(0x7FFFFFFF))
    return jax.lax.bitcast_convert_type(b, jnp.float32)


def _median4(a, b, c, d):
    """``jnp.median`` of four stacked planes (axis 0), elementwise, as a
    min/max network on the ordered keys: for ``x = max(min(a,b),
    min(c,d))`` and ``y = min(max(a,b), max(c,d))`` the two middle order
    statistics of {a,b,c,d} are ``min(x,y)`` and ``max(x,y)``.  Matches
    ``jnp.median`` bit-for-bit: any-NaN lanes poison to NaN first (the
    quantile path patches NaN columns before its sort), the key order
    equals the sort's total order (-0 < +0; no NaNs survive the patch),
    and the final ``lo*0.5 + hi*0.5`` is quantile's method='linear'
    arithmetic — NOT ``0.5*(lo+hi)``, whose pre-rounded sum is the
    midpoint method's different float."""
    any_nan = (jnp.isnan(a) | jnp.isnan(b)) | (jnp.isnan(c) | jnp.isnan(d))
    ka, kb, kc, kd = (_ordered_key(v) for v in (a, b, c, d))
    x = jnp.maximum(jnp.minimum(ka, kb), jnp.minimum(kc, kd))
    y = jnp.minimum(jnp.maximum(ka, kb), jnp.maximum(kc, kd))
    med = (_key_to_float(jnp.minimum(x, y)) * np.float32(0.5)
           + _key_to_float(jnp.maximum(x, y)) * np.float32(0.5))
    return jnp.where(any_nan, np.float32(np.nan), med)


def _line_fold(axis, B, S, C, keepdims=False):
    """(fold, unfold) for batching a line-local (B, S, C) launch: the batch
    folds into the LINE axis of one 2-D launch (lines are independent, so
    B archives are just B times the lanes).  ``keepdims`` unfolds a
    reduced (1, lines) output (the median) instead of a full one."""
    n_keep = 1 if keepdims else (S if axis == 0 else C)
    if axis == 0:   # reduce subints; lines = B*C channels
        fold = lambda x: x.transpose(1, 0, 2).reshape(S, B * C)
        unfold = lambda o: o.reshape(n_keep, B, C).transpose(1, 0, 2)
    else:           # reduce channels; lines = B*S subints
        fold = lambda x: x.transpose(2, 0, 1).reshape(C, B * S)
        unfold = lambda o: o.reshape(n_keep, B, S).transpose(1, 2, 0)
    return fold, unfold


def _select_kth(keys, k, reduce_sum=None):
    """Exact k-th (0-indexed) smallest int32 key per lane.

    keys: (n, t) int32; k: (t,) int32 in [0, n).  32 bisection steps, each a
    count of keys <= mid down the sublane axis.

    ``reduce_sum`` merges the per-step counts across shards of the sublane
    axis (``lax.psum`` over a mesh axis): every device bisects on the
    *global* counts, so all devices converge on the identical k-th key of
    the union — integer adds are exact regardless of reduction order, so
    the distributed select is bit-equal with the single-device one by
    construction.  ``None`` (the kernel default) is the local count.
    """

    def body(_, state):
        lo, hi = state
        # overflow-free signed midpoint, floor-rounded
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        cnt = jnp.sum((keys <= mid[None, :]).astype(jnp.int32), axis=0,
                      dtype=jnp.int32)
        if reduce_sum is not None:
            cnt = reduce_sum(cnt)
        go_low = cnt >= k + 1
        return jnp.where(go_low, lo, mid + 1), jnp.where(go_low, mid, hi)

    lo = jnp.full_like(k, _INT32_MIN)
    hi = jnp.full_like(k, _INT32_MAX)
    lo, _ = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return lo


def _select_adjacent(keys, k_lo, k_hi, reduce_sum=None, reduce_min=None):
    """The ``k_lo``-th and ``k_hi``-th smallest keys where ``k_hi`` is
    ``k_lo`` or ``k_lo + 1`` (the median's two middle ranks).

    One 32-step bisection finds the ``k_lo``-th key; the successor rank
    then needs only two more passes: if more than ``k_hi`` keys are <= the
    found key, rank ``k_hi`` is the same key (duplicates straddle the
    middle), otherwise it is the smallest key strictly greater.  ~34 passes
    over the tile instead of the 64 two independent bisections cost — the
    dominant VPU work of every median/MAD launch.

    ``reduce_sum``/``reduce_min`` merge the counts and the successor key
    across shards of the sublane axis (psum/pmin collectives) for the
    tree-reduced distributed form; both merges are integer ops, so the
    selected key bit patterns match the single-device select exactly."""
    lo_key = _select_kth(keys, k_lo, reduce_sum)
    cnt_le = jnp.sum((keys <= lo_key[None, :]).astype(jnp.int32), axis=0,
                     dtype=jnp.int32)
    above = jnp.where(keys > lo_key[None, :], keys, _INT32_MAX)
    succ = jnp.min(above, axis=0)
    if reduce_sum is not None:
        cnt_le = reduce_sum(cnt_le)
        succ = reduce_min(succ)
    hi_key = jnp.where(cnt_le > k_hi, lo_key, succ)
    return lo_key, hi_key


def _masked_median_lanes(values, mask, reduce_sum=None, reduce_min=None):
    """Median of the unmasked entries down the sublane axis of one tile:
    the shared core of the standalone median kernel and the fused scaler
    kernel.  Returns the (t,) medians (0.0 where a line is fully masked).

    With ``reduce_sum``/``reduce_min`` the sublane axis may be sharded
    across devices: ranks and counts come from globally merged integers,
    the float epilogue (``0.5*(lo+hi)``) runs on identical keys on every
    device — the distributed median is bit-equal with the single-device
    one."""
    keys = jnp.where(mask, _KEY_MASKED, _ordered_key(values))
    n_valid = jnp.sum((~mask).astype(jnp.int32), axis=0, dtype=jnp.int32)
    if reduce_sum is not None:
        n_valid = reduce_sum(n_valid)
    k_lo = jnp.maximum(n_valid - 1, 0) // 2
    k_hi = n_valid // 2
    lo_key, hi_key = _select_adjacent(keys, k_lo, k_hi, reduce_sum,
                                      reduce_min)
    med = np.float32(0.5) * (_key_to_float(lo_key) + _key_to_float(hi_key))
    return jnp.where(n_valid == 0, np.float32(0.0), med), n_valid


def _median_kernel(v_ref, m_ref, out_ref):
    med, _ = _masked_median_lanes(v_ref[:], m_ref[:])
    out_ref[0, :] = med


def _scaled_sides_body(d0, d1, d2, d3, mask, thresh, plain_mask=None,
                       reduce_sum=None, reduce_min=None, reduce_any=None):
    """One orientation of the whole scaler stage for all four diagnostics
    on (n_reduce, T_lines) VMEM arrays: median -> centring -> MAD ->
    epilogue.

    The epilogues are the *shared* helpers of the XLA route
    (:func:`masked_jax._masked_side` rules 1-4 for the three masked
    diagnostics; :func:`masked_jax._patch_nan_lines` + the plain IEEE
    inf/nan flow for the rFFT one — they are pure jnp ops and trace fine
    inside the kernel), so the outputs are bit-identical to the unfused
    kernel+XLA route by construction, while collapsing two median launches
    plus the XLA elementwise middle into a single pass over the tile.

    ``plain_mask`` drops entries from the rFFT diagnostic's *rank
    selection* the way cropping would (the sweep kernel's grid-padding
    rows, which the unpadded route never sees); the default all-false
    mask IS the existing plain path — rank over every entry.

    ``reduce_sum``/``reduce_min``/``reduce_any`` distribute the reduction
    axis over a mesh axis (psum counts, pmin successor keys, global
    NaN-presence OR).  Only the integer rank machinery crosses devices;
    every float op runs locally on identical operands, so the distributed
    orientation is bit-equal with this single-device body."""
    from iterative_cleaner_tpu.stats.masked_jax import (
        _masked_side,
        _patch_nan_lines,
    )

    def patch_nan(stat, values):
        # _patch_nan_lines with a cross-device NaN presence test: a line
        # whose NaN lives on another shard must patch on every shard.
        if reduce_any is None:
            return _patch_nan_lines(stat, values, 0)
        has_nan = reduce_any(jnp.any(jnp.isnan(values), axis=0,
                                     keepdims=True))
        return jnp.where(has_nan, np.float32(np.nan), stat)

    t = np.float32(thresh)
    outs = []
    for d in (d0, d1, d2):
        med, n_valid = _masked_median_lanes(d, mask, reduce_sum, reduce_min)
        centred = jnp.where(mask, d, d - med[None, :])
        mad, _ = _masked_median_lanes(jnp.abs(centred), mask, reduce_sum,
                                      reduce_min)
        outs.append(_masked_side(centred, mad[None, :], mask,
                                 n_valid[None, :], t))
    # the rFFT diagnostic: plain path (quirk 5) — no mask, NaN-bearing
    # lines median to NaN (matching jnp.median propagation), zero MAD
    # yields IEEE inf/nan that flow onward
    if plain_mask is None:
        plain_mask = jnp.zeros_like(mask)
    med, _ = _masked_median_lanes(d3, plain_mask, reduce_sum, reduce_min)
    centred = d3 - patch_nan(med[None, :], d3)
    absc = jnp.abs(centred)
    mad, _ = _masked_median_lanes(absc, plain_mask, reduce_sum, reduce_min)
    outs.append(jnp.abs(centred / patch_nan(mad[None, :], absc)) / t)
    return outs


def _scaled_sides_kernel(d0_ref, d1_ref, d2_ref, d3_ref, m_ref,
                         o0_ref, o1_ref, o2_ref, o3_ref, *, thresh):
    outs = _scaled_sides_body(d0_ref[0], d1_ref[0], d2_ref[0], d3_ref[0],
                              m_ref[0], thresh)
    for o_ref, o in zip((o0_ref, o1_ref, o2_ref, o3_ref), outs):
        o_ref[0] = o


def _scaled_sides_t_kernel(d0_ref, d1_ref, d2_ref, d3_ref, m_ref,
                           o0_ref, o1_ref, o2_ref, o3_ref, *, thresh):
    """Transposed-orientation launch: blocks arrive (T_lines, n_reduce)
    straight from the UNtransposed HBM arrays and are flipped in VMEM —
    the previous scheme transposed five 16 MB inputs and four outputs
    through HBM per launch (a relayout XLA cannot fuse), which measured
    5.45 ms vs 0.05 ms for the other orientation at 1024x4096.  The body
    (and so the outputs) is bit-identical: a transpose moves values, it
    does not round them."""
    outs = _scaled_sides_body(d0_ref[:].T, d1_ref[:].T, d2_ref[:].T,
                              d3_ref[:].T, m_ref[:].T, thresh)
    for o_ref, o in zip((o0_ref, o1_ref, o2_ref, o3_ref), outs):
        o_ref[...] = o.T


# Scoped-VMEM ceiling for the fused scaler launch (v5e has 128 MB VMEM;
# Mosaic's default scoped limit is 16 MB).  The kernel's live set at
# n=4096 is ~9 lane-padded (n, 128) f32 block buffers (double-buffered)
# plus ~16 bisection temporaries ≈ 70 MB, measured on hardware 2026-07-31.
_SCALER_VMEM_BYTES = min(120, max(32, int(
    _os.environ.get("ICLEAN_SCALER_VMEM_MB", "100")))) * 2**20


def _scaler_tile_lines(n: int) -> int:
    """Lane-tile width for the fused scaler launch: always one full
    128-lane tile.

    Hardware lesson (2026-07-31, v5e): TPU lane tiling pads the last block
    dim to 128 lanes, so a (n, 32) float32 block occupies the same VMEM as
    a (n, 128) one — the earlier scheme of shrinking T for long reduction
    axes (T=64 at n<=2048, T=32 beyond) saved nothing and cut per-step
    work 4x; it still blew the default 16 MB scoped-VMEM limit at n=4096
    (32 MB stack allocation).  The real lever is the scoped-VMEM ceiling,
    raised via ``CompilerParams(vmem_limit_bytes=...)`` on the launch."""
    del n
    return _TILE_LINES


@functools.partial(jax.jit, static_argnames=("thresh", "interpret"))
def _scaled_sides_axis0(d0, d1, d2, d3, mask, thresh, interpret):
    n, m = d0.shape
    tile = _scaler_tile_lines(n)
    pad = (-m) % tile
    if pad:
        d0, d1, d2, d3 = (jnp.pad(d, ((0, 0), (0, pad)))
                          for d in (d0, d1, d2, d3))
        mask = jnp.pad(mask, ((0, 0), (0, pad)), constant_values=True)
    mp = m + pad
    grid = mp // tile

    def chunked(x):
        # (n, mp) -> (mp/T, n, T): blocks (1, n, T) keep the last dim equal
        # to the full (reshaped) array dim, satisfying Mosaic's lane-tiling
        # rule for T < 128 (same trick as _FusedScaffold.to_cellrows)
        return x.reshape(n, grid, tile).swapaxes(0, 1)

    spec = pl.BlockSpec((1, n, tile), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        functools.partial(_scaled_sides_kernel, thresh=thresh),
        out_shape=[jax.ShapeDtypeStruct((grid, n, tile), jnp.float32)] * 4,
        grid=(grid,),
        in_specs=[spec] * 5,
        out_specs=[spec] * 4,
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_SCALER_VMEM_BYTES),
    )(*(chunked(d) for d in (d0, d1, d2, d3)), chunked(mask))
    return tuple(o.swapaxes(0, 1).reshape(n, mp)[:, :m] for o in outs)


@functools.partial(jax.jit, static_argnames=("thresh", "interpret"))
def _scaled_sides_axis1(d0, d1, d2, d3, mask, thresh, interpret):
    """Subint-scaler orientation on the natural (n_lines, m_reduce)
    layout: lines ride the sublane axis of (TILE, m) blocks and each
    block is transposed in VMEM (see :func:`_scaled_sides_t_kernel`) —
    no HBM transposes of the five inputs / four outputs."""
    n, m = d0.shape
    tile = _TILE_LINES
    pad = (-n) % tile
    if pad:
        d0, d1, d2, d3 = (jnp.pad(d, ((0, pad), (0, 0)))
                          for d in (d0, d1, d2, d3))
        mask = jnp.pad(mask, ((0, pad), (0, 0)), constant_values=True)
    np_ = n + pad
    grid = np_ // tile
    # last block dim == full array dim: Mosaic's lane-tiling rule is
    # satisfied for any m (same trick as the axis-0 launch's reshape)
    spec = pl.BlockSpec((tile, m), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        functools.partial(_scaled_sides_t_kernel, thresh=thresh),
        out_shape=[jax.ShapeDtypeStruct((np_, m), jnp.float32)] * 4,
        grid=(grid,),
        in_specs=[spec] * 5,
        out_specs=[spec] * 4,
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_SCALER_VMEM_BYTES),
    )(d0, d1, d2, d3, mask)
    return tuple(o[:n] for o in outs)


@functools.lru_cache(maxsize=64)
def _scaled_sides_fn(axis: int, thresh: float):
    """The one-orientation scaler launch wrapped in ``custom_vmap``: under
    ``vmap`` (the batched-archive engine, parallel/batch.py) the batch
    axis FOLDS INTO THE LINE AXIS of a single launch instead of
    serialising the pallas_call over a grid axis — per-line math is
    line-local, so B archives' scalers are just B times the lanes."""
    from jax.custom_batching import custom_vmap

    @custom_vmap
    def f(d0, d1, d2, d3, mask):
        interpret = _interpret_default()
        if axis == 0:
            return _scaled_sides_axis0(d0, d1, d2, d3, mask, thresh,
                                       interpret)
        return _scaled_sides_axis1(d0, d1, d2, d3, mask, thresh, interpret)

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        d0, d1, d2, d3, mask = _batch_args(axis_size, in_batched, *args)
        B, S, C = d0.shape
        interpret = _interpret_default()
        if axis == 1:
            # lines are (archive, subint) rows reducing over channels: the
            # fold is a METADATA-ONLY reshape (B, S, C) -> (B*S, C) into
            # the transpose-free axis-1 launch — _line_fold's transpose
            # fold into the axis-0 launch would relayout every operand
            # through HBM, the cost this launch exists to remove
            outs = _scaled_sides_axis1(
                d0.reshape(B * S, C), d1.reshape(B * S, C),
                d2.reshape(B * S, C), d3.reshape(B * S, C),
                mask.reshape(B * S, C), thresh, interpret)
            return tuple(o.reshape(B, S, C) for o in outs), (True,) * 4
        fold, unfold = _line_fold(axis, B, S, C)
        outs = _scaled_sides_axis0(fold(d0), fold(d1), fold(d2), fold(d3),
                                   fold(mask), thresh, interpret)
        return tuple(unfold(o) for o in outs), (True,) * 4

    return f


def scaled_sides_pallas(diagnostics, cell_mask, axis, thresh):
    """All four scaled sides of one orientation in ONE launch (float32).

    ``axis=0`` scales every channel's line down the subint axis (the
    channel scaler); ``axis=1`` the transpose.  Bit-identical to routing
    each diagnostic through :func:`masked_median_pallas` + the XLA
    epilogues *under jit* — the production mode; the engine compiles
    everything — and locked in by tests/test_pallas_stats.py.  (Eager XLA
    simplifies scalar divisions differently from its own jitted output at
    the 1-ulp level, so eager-vs-kernel comparisons can wobble for
    non-power-of-two thresholds; that is an XLA eager/jit artifact, not a
    kernel property.)  Batches under ``vmap`` by folding the batch into
    the line axis (one launch for the whole batch)."""
    if diagnostics[0].dtype != jnp.float32:
        raise TypeError("scaled_sides_pallas requires float32, got %s"
                        % diagnostics[0].dtype)
    if axis not in (0, 1):
        raise ValueError("axis must be 0 or 1 for 2-D diagnostics")
    return _scaled_sides_fn(axis, float(thresh))(*diagnostics, cell_mask)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _median_axis0(values, mask, interpret):
    n, m = values.shape
    pad = (-m) % _TILE_LINES
    if pad:
        values = jnp.pad(values, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)), constant_values=True)
    mp = m + pad
    grid = mp // _TILE_LINES
    out = pl.pallas_call(
        _median_kernel,
        out_shape=jax.ShapeDtypeStruct((1, mp), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((n, _TILE_LINES), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n, _TILE_LINES), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, _TILE_LINES), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(values, mask)
    return out[:, :m]


# ---------------------------------------------------------------------------
# Fused per-cell diagnostics
# ---------------------------------------------------------------------------
#
# One kernel for the whole per-cell half of an iteration (reference
# :275-296 + :206-212): template-amplitude fit, residual construction,
# weighting, and all four surgical-scrub diagnostics.  Everything after the
# global template is row-local to a (subint, channel) cell, so a single
# VMEM-resident pass over ded/disp_base replaces what XLA schedules as ~6
# separate HBM passes (fit reduce, moment reduces, and two cube-sized DFT
# spectra materialisations); the rFFT magnitudes ride the MXU against
# cos/sin bases and their max never leaves VMEM.

# Subints per fused-kernel block (sublane-friendly).  Overridable for the
# hardware tier sweep (benchmarks/tpu_validation_pass.sh step 5): larger
# blocks mean more rows per DFT matmul — better MXU utilisation at long
# nbin where the C_BLK tiers shrink — until the VMEM budget trips the
# Mosaic compile.  Only the "cell" default has been hardware-validated.
_S_BLK = _os.environ.get("ICLEAN_FUSED_SBLK", "")
_C_BLK_SCALE = int(_os.environ.get("ICLEAN_FUSED_CBLK_SCALE", "1"))
# tier strategy (VERDICT r3 #4): how the cell block sheds VMEM as profiles
# lengthen.  "cell" (default, hardware-validated) keeps S_BLK=8 and shrinks
# the CHANNEL block — the round-2 capture shows it falling to 155 GB/s at
# 512 bins (vs XLA's 326) as the lane-dim tiles go half-empty.  "sublane"
# keeps the channel block at one full 128-lane tile and sheds VMEM by
# shrinking the SUBINT block instead, holding cells-per-step (and so VMEM)
# equal to the "cell" tier at every nbin; the DFT matmul row count is
# unchanged, only the block aspect ratio moves.  Interpret-tested for
# parity at every tier; the A/B lives in tpu_validation_pass.sh step 5b.
_TIER = _os.environ.get("ICLEAN_FUSED_TIER", "cell")


def _cell_blocks(nbin: int):
    """(S_BLK, C_BLK) cell-block shape for one fused-kernel grid step.

    VMEM per step scales as ``S_BLK * C_BLK * nbin`` (two cube blocks +
    the flat intermediates) on top of the O(nbin^2) DFT tables, so the
    cell block shrinks as profiles lengthen — the footprint stays
    roughly flat from 256 to 1024 bins (measured on a v5e: C_BLK=128
    with S_BLK=8 overflows VMEM at 512 bins, these tiers compile and run
    at all sizes).  Which *axis* shrinks is the ``ICLEAN_FUSED_TIER``
    strategy above.

    This is deliberately cell-axis tiling, not bin-axis tiling: the
    closed-form amplitude needs a full-bin reduction *before* the residual
    exists, so bin tiles would force either a second pass over the cube
    (a third HBM read — exactly what the fused kernel exists to avoid) or
    cross-grid-step accumulators for six partial statistics.  Shrinking
    the cell block keeps the single-pass two-read structure at every nbin;
    bin reductions stay whole-line on the VPU lanes.

    Mosaic legality at C_BLK < 128: a (S_BLK, C_BLK) block over the
    (nsub, nchan) cell-plane arrays would violate the lane-tiling rule
    (last block dim must be a multiple of 128 or the full array dim), so
    the scaffold reshapes those arrays to (nchan/C_BLK, nsub, C_BLK) —
    blocks (1, S_BLK, C_BLK) whose last dim IS the full (reshaped) array
    dim.  Cube blocks are unaffected: their last dim is the whole bin
    axis, and C_BLK sits second-to-last where a multiple of 8 suffices.
    """
    if _TIER == "sublane":
        # full 128-lane channel tile at every nbin; subint block sheds the
        # VMEM.  Cells-per-step match the "cell" tiers (512/256/128) except
        # at 4096 bins, where the channel block drops to 64 so the flat
        # (S*C, nbin) intermediates stay within the "cell" tier's budget.
        if nbin <= 256:
            s, c = 8, 128
        elif nbin <= 512:
            s, c = 4, 128
        elif nbin <= 1024:
            s, c = 2, 128
        elif nbin <= 2048:
            s, c = 1, 128
        else:
            s, c = 1, 64
        if _S_BLK:
            s = int(_S_BLK)
        return s, c
    if nbin <= 256:
        c = 128
    elif nbin <= 512:
        c = 64
    elif nbin <= 1024:
        c = 32
    elif nbin <= 2048:
        c = 16
    else:
        c = 8
    # the sweep knobs override/multiply the tier (capped at one lane
    # tile); padding keeps correctness for any block shape, so the sweep
    # is purely a compile-legality + throughput question
    return (int(_S_BLK) if _S_BLK else 8), \
        min(128, c * max(1, _C_BLK_SCALE))


def _k_chunk(nbin: int, nk_pad: int) -> int:
    """DFT-table columns per grid step.  Up to 1024 bins the whole padded
    table fits VMEM and one step preserves the measured single-matmul
    schedule; past that the O(nbin^2) tables are the VMEM blocker, so the
    spectrum is swept in 128-column chunks by a third (innermost) grid
    dimension — the cube blocks' index map ignores it, so they stay
    resident in VMEM across the sweep and the cube is still read from HBM
    exactly once per cell block."""
    return nk_pad if nbin <= 1024 else 128


# np.ma's float fill value (masked ptp, quirk 4), shared with the XLA path.
from iterative_cleaner_tpu.stats.masked_jax import MA_FILL  # noqa: E402

_MA_FILL_F32 = np.float32(MA_FILL)

# Past 1024 bins the whole O(nbin^2) DFT tables blow the VMEM budget, so
# the spectrum is swept in 128-column chunks over a third grid dimension
# (_k_chunk) with shrinking cell blocks (_cell_blocks); 4096 is where the
# per-chunk table slices (2 x nbin x 128 f32) themselves reach ~4 MB and
# the cell block hits the 8-sublane floor.  Longer profiles fall back to
# the XLA path.
FUSED_STATS_MAX_NBIN = 4096

# What 'auto' trusts (resolve_stats_impl): real-TPU Mosaic lowering has
# been validated through 1024 bins (2026-07-30, v5e); the k-chunked
# 2048/4096 path is interpret-mode-verified only — explicit
# stats_impl='fused' reaches it, 'auto' won't until a hardware run
# confirms the lowering (interpret mode cannot check Mosaic constraints).
# ICLEAN_FUSED_AUTO_MAX_NBIN overrides WITHOUT a source edit so the
# hardware validation pass (step 2b) can exercise the lift the moment the
# 2048/4096 lowering check passes; commit the new default afterwards.
# Clamped to the kernel's own VMEM bound: past it 'auto' must keep its
# silently-pick-a-working-impl contract (fall back to xla), never crash.
FUSED_STATS_AUTO_MAX_NBIN = min(FUSED_STATS_MAX_NBIN, int(_os.environ.get(
    "ICLEAN_FUSED_AUTO_MAX_NBIN", "1024")))


# MXU precision of the fused kernel's DFT-spectrum matmuls — the kernel's
# FLOPs hotspot.  "highest" (default) is the 6-pass bf16 f32-exact mode;
# ICLEAN_DFT_PRECISION=high selects the 3-pass mode (~f32-accurate to
# ~1e-6 relative, the same tolerated noise class as every kernel/XLA fp
# regrouping; the full-size f32 gate's borderline band is 1e-2 wide) and
# =default the chip's fastest.  A hardware A/B knob
# (benchmarks/tpu_validation_pass.sh) — flip the default here only with a
# measured win AND a clean full-size parity check.
_DFT_PRECISION_CHOICES = {
    "highest": jax.lax.Precision.HIGHEST,
    "high": jax.lax.Precision.HIGH,
    "default": jax.lax.Precision.DEFAULT,
}
_DFT_PRECISION_NAME = _os.environ.get("ICLEAN_DFT_PRECISION", "highest")
if _DFT_PRECISION_NAME not in _DFT_PRECISION_CHOICES:
    raise ValueError(
        f"ICLEAN_DFT_PRECISION={_DFT_PRECISION_NAME!r}: valid values are "
        + "/".join(_DFT_PRECISION_CHOICES))
_DFT_PRECISION = _DFT_PRECISION_CHOICES[_DFT_PRECISION_NAME]


def _marginals_kernel(disp_ref, w_ref, a_ref, t1_ref, a_acc, t1_acc):
    """Both weighted marginals of the dispersed cube in ONE sweep: the
    per-channel profiles ``A[c] = sum_s w*disp`` and the per-subint totals
    ``t1[s] = sum_c w*disp`` (ops.dsp.weighted_marginal_totals — two XLA
    dots would read the cube twice; TPU does not fuse sibling dots).

    The full (nc, nbin) / (ns, nbin) accumulators live in VMEM scratch
    for the whole launch (grid steps are sequential on TPU, so the
    accumulation order is deterministic: s-blocks outer, c-blocks inner);
    each (S_BLK, C_BLK, nbin) cube block contributes one weighted sum to
    each.  The outputs are written from scratch on the final step."""
    i, j = pl.program_id(0), pl.program_id(1)
    s_blk, c_blk, _ = disp_ref.shape

    @pl.when((i == 0) & (j == 0))
    def _zero():
        a_acc[...] = jnp.zeros_like(a_acc)
        t1_acc[...] = jnp.zeros_like(t1_acc)

    # bf16-stored cubes upcast per staged block: accumulation stays f32
    wx = disp_ref[:].astype(jnp.float32) * w_ref[0][:, :, None]  # (S, C, B)
    a_acc[pl.ds(j * c_blk, c_blk), :] += jnp.sum(wx, axis=0)
    t1_acc[pl.ds(i * s_blk, s_blk), :] += jnp.sum(wx, axis=1)

    @pl.when((i == pl.num_programs(0) - 1) & (j == pl.num_programs(1) - 1))
    def _writeout():
        a_ref[...] = a_acc[...]
        t1_ref[...] = t1_acc[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _marginals_call(disp, weights, interpret):
    nsub, nchan, nbin = disp.shape
    s_blk, c_blk = 8, 128
    pad_s, pad_c = (-nsub) % s_blk, (-nchan) % c_blk
    if pad_s or pad_c:
        disp = jnp.pad(disp, ((0, pad_s), (0, pad_c), (0, 0)))
        weights = jnp.pad(weights, ((0, pad_s), (0, pad_c)))
    ns, nc = nsub + pad_s, nchan + pad_c
    grid = (ns // s_blk, nc // c_blk)
    # weights travel chunk-major like the fused kernels' cell planes so
    # the (1, S_BLK, C_BLK) block's last dim is a full (reshaped) dim
    w_rows = weights.reshape(ns, nc // c_blk, c_blk).swapaxes(0, 1)
    a, t1 = pl.pallas_call(
        _marginals_kernel,
        out_shape=[jax.ShapeDtypeStruct((nc, nbin), jnp.float32),
                   jax.ShapeDtypeStruct((ns, nbin), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((s_blk, c_blk, nbin), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_blk, c_blk), lambda i, j: (j, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((nc, nbin), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ns, nbin), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((nc, nbin), jnp.float32),
                        pltpu.VMEM((ns, nbin), jnp.float32)],
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_SCALER_VMEM_BYTES),
    )(disp, w_rows)
    return a[:nchan], t1[:nsub]


# the accumulators (and their output twins) must all fit VMEM alongside a
# cube block; past this the engine falls back to the two-dot XLA form
MARGINALS_PALLAS_MAX_BYTES = 24 * 2**20


def marginals_pallas_eligible(nsub: int, nchan: int, nbin: int) -> bool:
    """THE eligibility predicate for :func:`weighted_marginals_pallas` —
    callers (engine/loop.py, bench.py's bytes-moved model) must use this,
    not re-derive the scratch formula: scratch + out accumulators =
    ``2 * (nchan + nsub) * nbin * 4`` bytes, capped so they fit VMEM
    alongside a cube block."""
    return 2 * (nchan + nsub) * nbin * 4 <= MARGINALS_PALLAS_MAX_BYTES


@functools.lru_cache(maxsize=1)
def _marginals_fn():
    from jax.custom_batching import custom_vmap as _custom_vmap

    @_custom_vmap
    def f(disp, weights):
        return _marginals_call(disp, weights, _interpret_default())

    @f.def_vmap
    def _rule(axis_size, in_batched, disp, weights):
        # batched archives: the XLA dual-dot form — a vmapped pallas_call
        # would prepend a batch grid dim and silently break the kernel's
        # program_id bookkeeping
        from iterative_cleaner_tpu.ops.dsp import weighted_marginal_totals

        disp, weights = _batch_args(axis_size, in_batched, disp, weights)
        outs = jax.vmap(
            lambda d, w: weighted_marginal_totals(
                d.astype(jnp.float32) if d.dtype == jnp.bfloat16 else d,
                w, jnp))(disp, weights)
        return outs, (True, True)

    return f


def weighted_marginals_pallas(disp, weights):
    """One-read (A, t1) weighted marginals of a float32 dispersed cube —
    the Pallas twin of :func:`ops.dsp.weighted_marginal_totals` for the
    dispersed-frame iteration's template stage.  Accumulation order is
    deterministic (sequential grid) but regrouped vs the XLA dots — the
    same already-tolerated ulp class as every other kernel/XLA pairing.
    Callers must check :data:`MARGINALS_PALLAS_MAX_BYTES` (scratch =
    2 * (nchan + nsub) * nbin * 4 bytes) and fall back to the XLA form.
    Under ``vmap`` the XLA form takes over (see the custom_vmap rule)."""
    if disp.dtype not in (jnp.float32, jnp.bfloat16):
        raise TypeError("weighted_marginals_pallas requires float32 (or a "
                        "bf16-stored f32 pipeline), got %s" % disp.dtype)
    return _marginals_fn()(disp, weights.astype(jnp.float32))


class _RefSink:
    """Diagnostics destination of the standalone fused kernels: each
    statistic goes straight to its (1, S, C) output block ref (slots
    0..3 = std, mean, ptp, fft)."""

    def __init__(self, std_ref, mean_ref, ptp_ref, fft_ref):
        self.refs = (std_ref, mean_ref, ptp_ref, fft_ref)

    def store(self, slot, value):
        self.refs[slot][0] = value

    def load_fft(self):
        return self.refs[3][0]


class _SliceSink:
    """Diagnostics destination of the sweep kernels: statistics accumulate
    into per-archive (S_pad, nc) VMEM scratch planes at this grid step's
    cell-block slice, so the final grid step can run the whole scaler +
    combine + zap stage on the resident planes without another HBM trip."""

    def __init__(self, accs, row, col, s_blk, c_blk):
        self.accs = accs
        self.idx = (pl.ds(row, s_blk), pl.ds(col, c_blk))

    def store(self, slot, value):
        self.accs[slot][self.idx] = value

    def load_fft(self):
        return self.accs[3][self.idx]


def _diag_tail(wres, mask, cos_ref, sin_ref, num_k, sink):
    """Shared diagnostics tail: the four per-cell statistics of a weighted
    residual tile (S, C, B), stored through ``sink`` (output refs for the
    standalone kernels, scratch-plane slices for the sweep kernels — ONE
    op sequence, so the two stay bit-identical by construction).

    The DFT spectrum is swept over ``num_k`` grid steps (innermost grid
    dim; one step when the table fits VMEM whole, see :func:`_k_chunk`):
    each step sees one (B, K_CHUNK) table slice, the k-independent
    moments are written on the first step only, and the fft slot holds
    the running |spectrum|^2 maximum until the last step takes the sqrt."""
    kk = pl.program_id(2)
    nbin = wres.shape[-1]
    inv_n = np.float32(1.0 / nbin)
    mean = jnp.sum(wres, axis=2) * inv_n

    @pl.when(kk == 0)
    def _moments():
        sink.store(1, jnp.where(mask, np.float32(0.0), mean))
        ptp = jnp.max(wres, axis=2) - jnp.min(wres, axis=2)
        sink.store(2, jnp.where(mask, _MA_FILL_F32, ptp))

    # mask-aware mean subtraction (reference :210-211); the tile is
    # VMEM-resident, so the two-pass centred variance (jnp.std's stable
    # form — no cancellation for |mean| >> std cells) costs no extra HBM
    # traffic.  Masked cells' centring skew is irrelevant: their std is
    # patched to 0.
    centred = wres - jnp.where(mask, np.float32(0.0), mean)[:, :, None]

    @pl.when(kk == 0)
    def _variance():
        var = jnp.sum(centred * centred, axis=2) * inv_n
        sink.store(0, jnp.where(mask, np.float32(0.0), jnp.sqrt(var)))

    flat = centred.reshape(-1, nbin)                # (S*C, B)
    re = jax.lax.dot_general(flat, cos_ref[:], (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=_DFT_PRECISION)
    im = jax.lax.dot_general(flat, sin_ref[:], (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=_DFT_PRECISION)
    mag2 = re * re + im * im                        # (S*C, K_CHUNK)
    chunk_max = jnp.max(mag2, axis=1).reshape(mask.shape)

    @pl.when(kk == 0)
    def _init_fft():
        sink.store(3, chunk_max)

    @pl.when(kk > 0)
    def _acc_fft():
        sink.store(3, jnp.maximum(sink.load_fft(), chunk_max))

    @pl.when(kk == num_k - 1)
    def _final_fft():
        sink.store(3, jnp.sqrt(sink.load_fft()))


def _write_diags(wres, mask, cos_ref, sin_ref,
                 std_ref, mean_ref, ptp_ref, fft_ref, num_k):
    """:func:`_diag_tail` onto the (1, S, C) output block refs."""
    _diag_tail(wres, mask, cos_ref, sin_ref, num_k,
               _RefSink(std_ref, mean_ref, ptp_ref, fft_ref))


def _cell_stats_kernel(ded_ref, disp_ref, rott_ref, t_ref, w_ref, m_ref,
                       cos_ref, sin_ref, tt_ref,
                       std_ref, mean_ref, ptp_ref, fft_ref, *, num_k):
    t = t_ref[0]                                    # (B,)
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    # bf16-stored cubes upcast per staged (VMEM) block: the fit/residual
    # arithmetic below is always fp32 (identity astype on f32 cubes)
    ded = ded_ref[:].astype(jnp.float32)            # (S, C, B)
    # closed-form fit (dsp.fit_template_amplitudes, same ops/order)
    tp = jnp.sum(ded * t[None, None, :], axis=2)
    amp = jnp.where(tt_zero != 0, jnp.ones_like(tp), tp / tt_safe)
    resid = amp[:, :, None] * rott_ref[0][None] - disp_ref[:].astype(
        jnp.float32)
    wres = resid * w_ref[0][:, :, None]             # apply_weights
    _write_diags(wres, m_ref[0], cos_ref, sin_ref,
                 std_ref, mean_ref, ptp_ref, fft_ref, num_k)


def _wres_disp(disp, rott, nyq, tt_safe, tt_zero, w, *, apply_nyq):
    """Dispersed-frame one-read weighted residual of a (S, C, B) cube
    block: fit against the rotated template, Nyquist round-trip
    correction, weighting.  The shared body of
    :func:`_cell_stats_disp_kernel` and the sweep kernel — one op
    sequence, bit-identical residuals by construction.

    The single upcast point of the mixed-precision mode: a bf16-stored
    cube block becomes fp32 here, INSIDE the kernel (after the HBM read /
    DMA stage, before any arithmetic), so the sweep, multi-kernel and DMA
    routes all inherit bf16 support from this one line — and the f32
    routes are bit-unchanged (astype to the same dtype is a no-op)."""
    disp = disp.astype(jnp.float32)
    tp = jnp.sum(disp * rott[None], axis=2)
    amp = jnp.where(tt_zero != 0, jnp.ones_like(tp), tp / tt_safe)
    base = disp
    if apply_nyq:
        nbin = disp.shape[-1]
        alt = (1.0 - 2.0 * (jax.lax.broadcasted_iota(
            jnp.int32, (nbin,), 0) % 2)).astype(disp.dtype)
        nyqcoef = jnp.sum(disp * alt[None, None, :], axis=2)
        base = disp + nyqcoef[:, :, None] * nyq[None]
    resid = amp[:, :, None] * rott[None] - base
    return resid * w[:, :, None]                    # apply_weights


def _wres_dedisp(ded, t, win, tt_safe, tt_zero, w):
    """Dedispersed-frame weighted residual of a (S, C, B) cube block:
    ``(amp*t - ded) * window``, weighted.  Shared by
    :func:`_cell_stats_dedisp_kernel` and the sweep kernel.  Like
    :func:`_wres_disp`, the bf16 storage mode upcasts here — one line
    covers every route that stages this body's cube blocks."""
    ded = ded.astype(jnp.float32)
    tp = jnp.sum(ded * t[None, None, :], axis=2)
    amp = jnp.where(tt_zero != 0, jnp.ones_like(tp), tp / tt_safe)
    resid = (amp[:, :, None] * t[None, None, :] - ded) * win[None, None, :]
    return resid * w[:, :, None]                    # apply_weights


def _cell_stats_disp_kernel(disp_ref, rott_ref, nyq_ref, w_ref, m_ref,
                            cos_ref, sin_ref, tt_ref,
                            std_ref, mean_ref, ptp_ref, fft_ref, *, num_k,
                            apply_nyq):
    """Dispersed-frame ONE-read variant (pulse window inactive): the fit
    inner product moves into the dispersed frame — ``<ded, t>`` equals
    ``<disp, rot_c(t)>`` EXACTLY (rotation is self-adjoint up to shift
    sign, Nyquist attenuation included) — so the dedispersed cube is
    never read.  Normalisation stays the dedispersed ``<t, t>`` scalar
    (ops.dsp.fit_template_amplitudes_disp).

    The reference-faithful residual base is the round-tripped cube
    ``R(s)R(-s)disp = disp + (cos^2(pi s)-1)*nyq(disp)`` (fourier
    fractional shifts attenuate the Nyquist bin; engine/loop.py
    disp_iteration): with ``apply_nyq`` the rank-one correction costs one
    alternating-sign reduction per VMEM-resident cell — ``nyq_ref`` rows
    carry ``(gamma_c / nbin) * (-1)^b``.  Roll rotation / odd nbin
    round-trip exactly: the static flag compiles the term away."""
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    wres = _wres_disp(disp_ref[:], rott_ref[0], nyq_ref[0], tt_safe,
                      tt_zero, w_ref[0], apply_nyq=apply_nyq)
    _write_diags(wres, m_ref[0], cos_ref, sin_ref,
                 std_ref, mean_ref, ptp_ref, fft_ref, num_k)


def _cell_stats_dedisp_kernel(ded_ref, t_ref, win_ref, w_ref, m_ref,
                              cos_ref, sin_ref, tt_ref,
                              std_ref, mean_ref, ptp_ref, fft_ref, *, num_k):
    """Dedispersed-frame variant: one cube read.  The residual never leaves
    the dedispersed frame, so there is no disp_base input and no per-channel
    rotated template — ``resid = (amp*t - ded) * window``."""
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    wres = _wres_dedisp(ded_ref[:], t_ref[0], win_ref[0], tt_safe, tt_zero,
                        w_ref[0])
    _write_diags(wres, m_ref[0], cos_ref, sin_ref,
                 std_ref, mean_ref, ptp_ref, fft_ref, num_k)


class _FusedScaffold:
    """Shared launch scaffolding for the fused cell kernels: pads the
    cell-grid inputs to block multiples (padding cells masked), and owns
    the grid/specs/out-slicing both kernels must agree on.

    Cell-plane arrays (weights, mask, the four outputs) travel reshaped as
    (nc/C_BLK, nsub_padded, C_BLK) so their (1, S_BLK, C_BLK) blocks keep
    the last dim equal to the full (reshaped) array dim — Mosaic's lane
    tiling otherwise demands a multiple of 128, which the VMEM-driven
    C_BLK tiers of :func:`_cell_blocks` break past 256 bins.

    ``batch > 1`` folds B archives into the subint axis of ONE launch
    (each archive's subints padded to a block multiple first, so no block
    straddles archives); the per-archive inputs — template, rotated
    template, tt_info — carry a leading batch dim and their index maps
    select the owning archive from the subint-block index.  This is how
    the batched engine (parallel/batch.py) keeps the fused kernel instead
    of letting ``vmap`` serialise the pallas_call."""

    def __init__(self, nsub, nchan, nbin, num_k, batch=1, blocks=None):
        self.batch = batch
        self.nsub, self.nchan, self.nbin = nsub, nchan, nbin
        self.num_k = num_k
        # blocks arrives as a STATIC jit argument from the callers (so a
        # tier-strategy change can never hit a stale jit cache entry keyed
        # only on shapes); None keeps the env-selected tier for direct use
        s_blk, c_blk = blocks or _cell_blocks(nbin)
        self.s_blk = s_blk
        self.c_blk = c_blk
        self.pad_s = (-nsub) % s_blk
        self.pad_c = (-nchan) % c_blk
        self.s_pad = nsub + self.pad_s          # per-archive padded subints
        self.ns = batch * self.s_pad            # folded subint axis
        self.nc = nchan + self.pad_c
        bpa = self.s_pad // s_blk               # subint blocks per archive
        self.bpa = bpa
        # kk innermost: the cube/cell blocks' index maps ignore it, so
        # those blocks stay resident in VMEM across the spectrum sweep
        self.grid = (self.ns // s_blk, self.nc // c_blk, num_k)
        # whole-archive (S_pad, nc) plane in PLAIN layout: the last block
        # dim is the full array dim, so lane tiling is satisfied without
        # the chunk-major reshape the small cell blocks need
        self.plane_spec = pl.BlockSpec((self.s_pad, self.nc),
                                       lambda i, j, kk: (i // bpa, 0),
                                       memory_space=pltpu.VMEM)
        self.cell_spec = pl.BlockSpec((1, s_blk, c_blk),
                                      lambda i, j, kk: (j, i, 0),
                                      memory_space=pltpu.VMEM)
        self.cube_spec = pl.BlockSpec((s_blk, c_blk, nbin),
                                      lambda i, j, kk: (i, j, 0),
                                      memory_space=pltpu.VMEM)
        self.chan_row_spec = pl.BlockSpec((1, c_blk, nbin),
                                          lambda i, j, kk: (i // bpa, j, 0),
                                          memory_space=pltpu.VMEM)
        self.row_spec = pl.BlockSpec((1, nbin),
                                     lambda i, j, kk: (i // bpa, 0),
                                     memory_space=pltpu.VMEM)
        self.tt_spec = pl.BlockSpec((1, 2), lambda i, j, kk: (i // bpa, 0),
                                    memory_space=pltpu.SMEM)

    def pad_cube(self, x):
        """(B, S, C, nbin) -> folded (B*S_pad, nc, nbin)."""
        x = jnp.pad(x, ((0, 0), (0, self.pad_s), (0, self.pad_c), (0, 0))) \
            if self.pad_s or self.pad_c else x
        return x.reshape(self.ns, self.nc, self.nbin)

    def pad_chan_row(self, x):
        """(B, C, nbin) per-archive channel rows, channel-padded."""
        return jnp.pad(x, ((0, 0), (0, self.pad_c), (0, 0))) \
            if self.pad_c else x

    def to_cellrows(self, x):
        """(ns, nc) cell plane -> (nc/C_BLK, ns, C_BLK) chunk-major form."""
        return x.reshape(self.ns, self.nc // self.c_blk,
                         self.c_blk).swapaxes(0, 1)

    def pad_cells(self, weights, cell_mask):
        """(B, S, C) planes -> folded chunk-major; padding cells masked."""
        pads = ((0, 0), (0, self.pad_s), (0, self.pad_c))
        if self.pad_s or self.pad_c:
            weights = jnp.pad(weights, pads)
            cell_mask = jnp.pad(cell_mask, pads, constant_values=True)
        fold = (self.ns, self.nc)
        return (self.to_cellrows(weights.reshape(fold)),
                self.to_cellrows(cell_mask.reshape(fold)))

    def pad_plane(self, x, masked=False):
        """(B, S, C) cell plane -> folded PLAIN-layout (ns, nc) for the
        whole-archive ``plane_spec`` blocks; padding cells masked/zero."""
        pads = ((0, 0), (0, self.pad_s), (0, self.pad_c))
        if self.pad_s or self.pad_c:
            x = jnp.pad(x, pads, constant_values=masked)
        return x.reshape(self.ns, self.nc)

    def launch(self, kernel, inputs, in_specs, cos_t, sin_t, tt_info,
               interpret, scratch_shapes=()):
        outs = pl.pallas_call(
            functools.partial(kernel, num_k=self.num_k),
            out_shape=[jax.ShapeDtypeStruct(
                (self.nc // self.c_blk, self.ns, self.c_blk),
                jnp.float32)] * 4,
            grid=self.grid,
            in_specs=list(in_specs) + self._table_specs(cos_t, sin_t),
            out_specs=[self.cell_spec] * 4,
            scratch_shapes=list(scratch_shapes),
            interpret=interpret,
        )(*inputs, cos_t, sin_t, tt_info)
        return tuple(
            o.swapaxes(0, 1).reshape(self.batch, self.s_pad, self.nc)
            [:, : self.nsub, : self.nchan]
            for o in outs)

    def _table_specs(self, cos_t, sin_t):
        k_chunk = cos_t.shape[1] // self.num_k
        return [
            pl.BlockSpec((cos_t.shape[0], k_chunk),
                         lambda i, j, kk: (0, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((sin_t.shape[0], k_chunk),
                         lambda i, j, kk: (0, kk),
                         memory_space=pltpu.VMEM),
            self.tt_spec,
        ]

    def launch_sweep(self, kernel, inputs, in_specs, cos_t, sin_t, tt_info,
                     interpret):
        """Sweep-kernel launch: same grid/blocking as :meth:`launch`, but
        the per-step diagnostics accumulate into four per-archive
        (S_pad, nc) scratch planes (reused across archives — the TPU grid
        is sequential) and the outputs are the three whole-archive planes
        the final grid step of each archive writes: new weights, scores,
        and the residual-std diagnostic (the engine's telemetry plane)."""
        plane = pl.BlockSpec((self.s_pad, self.nc),
                             lambda i, j, kk, bpa=self.bpa: (i // bpa, 0),
                             memory_space=pltpu.VMEM)
        outs = pl.pallas_call(
            functools.partial(kernel, num_k=self.num_k, bpa=self.bpa,
                              nsub=self.nsub, nchan=self.nchan),
            out_shape=[jax.ShapeDtypeStruct((self.ns, self.nc),
                                            jnp.float32)] * 3,
            grid=self.grid,
            in_specs=list(in_specs) + self._table_specs(cos_t, sin_t),
            out_specs=[plane] * 3,
            scratch_shapes=[pltpu.VMEM((self.s_pad, self.nc),
                                       jnp.float32)] * 4,
            interpret=interpret,
            compiler_params=_CompilerParams(
                vmem_limit_bytes=_SCALER_VMEM_BYTES),
        )(*inputs, cos_t, sin_t, tt_info)
        return tuple(
            o.reshape(self.batch, self.s_pad, self.nc)
            [:, : self.nsub, : self.nchan]
            for o in outs)


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks"))
def _cell_stats_call(ded, disp_base, rot_t, template, tt_info, weights,
                     cell_mask, cos_t, sin_t, num_k, interpret, blocks):
    """Batched-shape launch: ded/disp (B, S, C, nbin), rot_t (B, C, nbin),
    template/tt per archive; B archives fold into one grid."""
    sc = _FusedScaffold(*ded.shape[1:], num_k, batch=ded.shape[0],
                        blocks=blocks)
    weights, cell_mask = sc.pad_cells(weights, cell_mask)
    return sc.launch(
        _cell_stats_kernel,
        (sc.pad_cube(ded), sc.pad_cube(disp_base), sc.pad_chan_row(rot_t),
         template, weights, cell_mask),
        (sc.cube_spec, sc.cube_spec, sc.chan_row_spec, sc.row_spec,
         sc.cell_spec, sc.cell_spec),
        cos_t, sin_t, tt_info, interpret,
    )


def _fused_tables(nbin, dtype):
    """Shared validation + DFT tables for the fused kernels.
    Returns (cos_t, sin_t, num_k, interpret).

    bf16 is admitted alongside f32: it is the mixed-precision STORAGE
    dtype of an f32 pipeline — the kernel bodies upcast each staged cube
    block (:func:`_wres_disp`/:func:`_wres_dedisp`) and every
    table/output/accumulator here stays f32."""
    if dtype not in (jnp.float32, jnp.bfloat16):
        raise TypeError("fused cell diagnostics require float32 (or a "
                        "bf16-stored f32 pipeline), got %s" % dtype)
    if nbin > FUSED_STATS_MAX_NBIN:
        raise ValueError(
            f"fused cell diagnostics support nbin <= {FUSED_STATS_MAX_NBIN} "
            f"(VMEM budget), got {nbin}; use stats_impl='xla' (or 'auto', "
            "which checks this)")
    nk = nbin // 2 + 1
    pad_k = (-nk) % 128  # zero columns: magnitude 0, never the max
    b = jnp.arange(nbin, dtype=jnp.float32)
    k = jnp.arange(nk, dtype=jnp.float32)
    ang = (-2.0 * np.pi / nbin) * jnp.outer(b, k)
    cos_t = jnp.pad(jnp.cos(ang), ((0, 0), (0, pad_k)))
    sin_t = jnp.pad(jnp.sin(ang), ((0, 0), (0, pad_k)))
    num_k = cos_t.shape[1] // _k_chunk(nbin, cos_t.shape[1])
    interpret = _interpret_default()
    return cos_t, sin_t, num_k, interpret


def _tt_info(template):
    """(B, nbin) templates -> (B, 2) [safe ||t||^2, is-zero] SMEM rows."""
    tt = jnp.sum(template * template, axis=-1)
    return jnp.stack(
        [jnp.where(tt == 0, jnp.float32(1.0), tt),
         (tt == 0).astype(jnp.float32)], axis=-1)


def _batch_args(axis_size, in_batched, *args):
    """Broadcast any unbatched custom_vmap operand to the batch."""
    return tuple(
        x if b else jnp.broadcast_to(x[None], (axis_size,) + x.shape)
        for x, b in zip(args, in_batched))


def _fused_dispersed_batched(ded, disp_base, rot_t, template, weights,
                             cell_mask):
    cos_t, sin_t, num_k, interpret = _fused_tables(ded.shape[-1], ded.dtype)
    return _cell_stats_call(ded, disp_base, rot_t, template,
                            _tt_info(template),
                            weights.astype(jnp.float32), cell_mask,
                            cos_t, sin_t, num_k, interpret,
                            _cell_blocks(ded.shape[-1]))


from jax.custom_batching import custom_vmap  # noqa: E402


@custom_vmap
def _fused_dispersed(ded, disp_base, rot_t, template, weights, cell_mask):
    outs = _fused_dispersed_batched(
        ded[None], disp_base[None], rot_t[None], template[None],
        weights[None], cell_mask[None])
    return tuple(o[0] for o in outs)


@_fused_dispersed.def_vmap
def _fused_dispersed_rule(axis_size, in_batched, *args):
    # the batched-archive engine lands here: B archives become ONE launch
    # with the batch folded into the subint grid (see _FusedScaffold)
    return (_fused_dispersed_batched(
        *_batch_args(axis_size, in_batched, *args)), (True,) * 4)


def cell_diagnostics_pallas(ded, disp_base, rot_t, template, weights,
                            cell_mask):
    """Fused fit + residual + diagnostics (float32, TPU; interpreted
    elsewhere).  Returns (d_std, d_mean, d_ptp, d_fft), each (nsub, nchan),
    with the same masked-cell patches as the XLA path
    (:func:`masked_jax.surgical_scores_jax`) and DFT-flavoured rFFT
    magnitudes (:func:`masked_jax.rfft_magnitudes` mode='dft').  Under
    ``vmap`` the batch folds into the launch grid instead of serialising
    the pallas_call."""
    return _fused_dispersed(ded, disp_base, rot_t, template,
                            weights.astype(jnp.float32), cell_mask)


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks",
                                    "apply_nyq"))
def _cell_stats_disp_call(disp, rot_t, nyq_row, tt_info, weights,
                          cell_mask, cos_t, sin_t, num_k, interpret,
                          blocks, apply_nyq):
    sc = _FusedScaffold(*disp.shape[1:], num_k, batch=disp.shape[0],
                        blocks=blocks)
    weights, cell_mask = sc.pad_cells(weights, cell_mask)
    return sc.launch(
        functools.partial(_cell_stats_disp_kernel, apply_nyq=apply_nyq),
        (sc.pad_cube(disp), sc.pad_chan_row(rot_t),
         sc.pad_chan_row(nyq_row), weights, cell_mask),
        (sc.cube_spec, sc.chan_row_spec, sc.chan_row_spec, sc.cell_spec,
         sc.cell_spec),
        cos_t, sin_t, tt_info, interpret,
    )


def _fused_disp_batched(disp, rot_t, nyq_row, template, weights, cell_mask,
                        apply_nyq):
    cos_t, sin_t, num_k, interpret = _fused_tables(disp.shape[-1],
                                                   disp.dtype)
    return _cell_stats_disp_call(disp, rot_t, nyq_row, _tt_info(template),
                                 weights.astype(jnp.float32), cell_mask,
                                 cos_t, sin_t, num_k, interpret,
                                 _cell_blocks(disp.shape[-1]), apply_nyq)


@functools.lru_cache(maxsize=2)
def _fused_disp_fn(apply_nyq: bool):
    from jax.custom_batching import custom_vmap as _custom_vmap

    @_custom_vmap
    def f(disp, rot_t, nyq_row, template, weights, cell_mask):
        outs = _fused_disp_batched(disp[None], rot_t[None], nyq_row[None],
                                   template[None], weights[None],
                                   cell_mask[None], apply_nyq)
        return tuple(o[0] for o in outs)

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        return (_fused_disp_batched(
            *_batch_args(axis_size, in_batched, *args), apply_nyq),
            (True,) * 4)

    return f


def cell_diagnostics_pallas_disp(disp, rot_t, nyq_row, template, weights,
                                 cell_mask):
    """Dispersed-frame ONE-read fused diagnostics (pulse window inactive):
    fit + residual + four diagnostics with the fit evaluated against the
    per-channel rotated template, so the dedispersed cube is never read
    (engine/loop.py ``disp_iteration``).  ``nyq_row`` is the per-channel
    Nyquist-correction row (``None`` for roll rotation / odd nbin, where
    the rotation round-trips exactly).  Returns (d_std, d_mean, d_ptp,
    d_fft); batches under ``vmap`` like :func:`cell_diagnostics_pallas`."""
    apply_nyq = nyq_row is not None
    if nyq_row is None:
        nyq_row = jnp.zeros_like(rot_t)
    return _fused_disp_fn(apply_nyq)(
        disp, rot_t, nyq_row, template,
        weights.astype(jnp.float32), cell_mask)


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks"))
def _cell_stats_dedisp_call(ded, template, window, tt_info, weights,
                            cell_mask, cos_t, sin_t, num_k, interpret,
                            blocks):
    sc = _FusedScaffold(*ded.shape[1:], num_k, batch=ded.shape[0],
                        blocks=blocks)
    weights, cell_mask = sc.pad_cells(weights, cell_mask)
    return sc.launch(
        _cell_stats_dedisp_kernel,
        (sc.pad_cube(ded), template, window, weights, cell_mask),
        (sc.cube_spec, sc.row_spec, sc.row_spec, sc.cell_spec, sc.cell_spec),
        cos_t, sin_t, tt_info, interpret,
    )


def _fused_dedisp_batched(ded, template, window, weights, cell_mask):
    cos_t, sin_t, num_k, interpret = _fused_tables(ded.shape[-1], ded.dtype)
    return _cell_stats_dedisp_call(ded, template, window,
                                   _tt_info(template),
                                   weights.astype(jnp.float32), cell_mask,
                                   cos_t, sin_t, num_k, interpret,
                                   _cell_blocks(ded.shape[-1]))


@custom_vmap
def _fused_dedisp(ded, template, window, weights, cell_mask):
    outs = _fused_dedisp_batched(ded[None], template[None], window[None],
                                 weights[None], cell_mask[None])
    return tuple(o[0] for o in outs)


@_fused_dedisp.def_vmap
def _fused_dedisp_rule(axis_size, in_batched, *args):
    return (_fused_dedisp_batched(
        *_batch_args(axis_size, in_batched, *args)), (True,) * 4)


def cell_diagnostics_pallas_dedisp(ded, template, window, weights, cell_mask):
    """Dedispersed-frame fused diagnostics: one cube read per iteration
    instead of two (engine stats_frame='dedispersed').  ``window`` is the
    (nbin,) pulse-region multiplier (all ones when inactive).  Batches
    under ``vmap`` like :func:`cell_diagnostics_pallas`."""
    return _fused_dedisp(ded, template, window.astype(jnp.float32),
                         weights.astype(jnp.float32), cell_mask)


# ---------------------------------------------------------------------------
# Per-shard diagnostics with a double-buffered HBM→VMEM DMA pipeline
# ---------------------------------------------------------------------------
#
# The sharded fused sweep (parallel/shard_sweep.py) runs these per-shard:
# the local cube stays in HBM (memory_space=ANY) and the kernel drives its
# own two-slot DMA pipeline over the (s_blk, c_blk, nbin) tiles — tile
# t+1's fetch is issued while tile t computes, the emit_pipeline idiom
# hand-rolled so the fetch schedule is explicit in the kernel (and so the
# cube keeps exactly ONE read site for the jaxpr contract: both dma_start
# sites target the same VMEM scratch buffer).  The kk spectrum axis stays
# innermost and reuses the resident tile, so each cube byte still crosses
# the HBM bus exactly once per iteration.

# Env mirror ICLEAN_SWEEP_DMA: 'auto'/'on' drive the per-shard cube fetch
# through the manual DMA pipeline; 'off' is the escape hatch back to the
# BlockSpec-pipelined route (same values, different fetch schedule).
def _sweep_dma_default(value=None) -> bool:
    v = value
    if v is None:
        v = _os.environ.get("ICLEAN_SWEEP_DMA", "auto")
    if isinstance(v, bool):
        return v
    v = str(v).lower()
    if v not in ("auto", "on", "off"):
        raise ValueError(f"ICLEAN_SWEEP_DMA must be auto/on/off, got {v!r}")
    return v != "off"


def _fetch_cube_tile(hbm_ref, buf, sem, i, j, kk, nj, n_tiles):
    """Double-buffered fetch of cube tile (i, j) into VMEM scratch.

    ``buf`` is (2, s_blk, c_blk, nbin) VMEM, ``sem`` a 2-slot DMA
    semaphore.  Tiles are numbered t = i*nj + j in grid order; tile t
    lives in slot t % 2.  At each tile's first spectrum step (kk == 0)
    the kernel waits for tile t (started by the warmup at t == 0, or by
    tile t-1's prefetch) and immediately starts tile t+1 into the other
    slot, so the next fetch overlaps this tile's whole compute —
    including all num_k spectrum steps.  The sequential TPU grid makes
    slot reuse safe: tile t-1's compute finished before tile t+1's
    prefetch is issued."""
    s_blk, c_blk = buf.shape[1], buf.shape[2]
    t = i * nj + j

    def copy(ti, slot):
        ii = ti // nj
        jj = ti % nj
        return pltpu.make_async_copy(
            hbm_ref.at[pl.ds(ii * s_blk, s_blk), pl.ds(jj * c_blk, c_blk)],
            buf.at[slot], sem.at[slot])

    @pl.when((kk == 0) & (t == 0))
    def _warmup():
        copy(t, t % 2).start()

    @pl.when(kk == 0)
    def _advance():
        copy(t, t % 2).wait()

        @pl.when(t + 1 < n_tiles)
        def _prefetch():
            copy(t + 1, (t + 1) % 2).start()

    return buf[t % 2]


def _dma_disp_kernel(disp_hbm, rott_ref, nyq_ref, w_ref, m_ref,
                     cos_ref, sin_ref, tt_ref,
                     std_ref, mean_ref, ptp_ref, fft_ref,
                     cube_buf, dma_sem, *, num_k, apply_nyq, nj, n_tiles):
    """:func:`_cell_stats_disp_kernel` with the cube tile arriving through
    the manual DMA pipeline instead of a BlockSpec; the compute body is
    the same function, so the outputs are bit-identical."""
    i, j, kk = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block = _fetch_cube_tile(disp_hbm, cube_buf, dma_sem, i, j, kk, nj,
                             n_tiles)
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    wres = _wres_disp(block, rott_ref[0], nyq_ref[0], tt_safe, tt_zero,
                      w_ref[0], apply_nyq=apply_nyq)
    _write_diags(wres, m_ref[0], cos_ref, sin_ref,
                 std_ref, mean_ref, ptp_ref, fft_ref, num_k)


def _dma_dedisp_kernel(ded_hbm, t_ref, win_ref, w_ref, m_ref,
                       cos_ref, sin_ref, tt_ref,
                       std_ref, mean_ref, ptp_ref, fft_ref,
                       cube_buf, dma_sem, *, num_k, nj, n_tiles):
    """:func:`_cell_stats_dedisp_kernel` with the DMA-pipelined cube."""
    i, j, kk = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block = _fetch_cube_tile(ded_hbm, cube_buf, dma_sem, i, j, kk, nj,
                             n_tiles)
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    wres = _wres_dedisp(block, t_ref[0], win_ref[0], tt_safe, tt_zero,
                        w_ref[0])
    _write_diags(wres, m_ref[0], cos_ref, sin_ref,
                 std_ref, mean_ref, ptp_ref, fft_ref, num_k)


def _dma_scratch(sc, dtype=jnp.float32):
    # the staging buffer matches the cube's STORAGE dtype (bf16 under the
    # mixed-precision mode — the DMA moves narrow bytes; the kernel body
    # upcasts after the wait), not the f32 compute dtype
    return [pltpu.VMEM((2, sc.s_blk, sc.c_blk, sc.nbin), dtype),
            pltpu.SemaphoreType.DMA((2,))]


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks",
                                    "apply_nyq"))
def _shard_diags_disp_call(disp, rot_t, nyq_row, tt_info, weights,
                           cell_mask, cos_t, sin_t, num_k, interpret,
                           blocks, apply_nyq):
    sc = _FusedScaffold(*disp.shape[1:], num_k, batch=disp.shape[0],
                        blocks=blocks)
    weights, cell_mask = sc.pad_cells(weights, cell_mask)
    nj = sc.nc // sc.c_blk
    kernel = functools.partial(_dma_disp_kernel, apply_nyq=apply_nyq,
                               nj=nj, n_tiles=(sc.ns // sc.s_blk) * nj)
    return sc.launch(
        kernel,
        (sc.pad_cube(disp), sc.pad_chan_row(rot_t),
         sc.pad_chan_row(nyq_row), weights, cell_mask),
        (pl.BlockSpec(memory_space=pltpu.ANY), sc.chan_row_spec,
         sc.chan_row_spec, sc.cell_spec, sc.cell_spec),
        cos_t, sin_t, tt_info, interpret,
        scratch_shapes=_dma_scratch(sc, disp.dtype),
    )


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks"))
def _shard_diags_dedisp_call(ded, template, window, tt_info, weights,
                             cell_mask, cos_t, sin_t, num_k, interpret,
                             blocks):
    sc = _FusedScaffold(*ded.shape[1:], num_k, batch=ded.shape[0],
                        blocks=blocks)
    weights, cell_mask = sc.pad_cells(weights, cell_mask)
    nj = sc.nc // sc.c_blk
    kernel = functools.partial(_dma_dedisp_kernel, nj=nj,
                               n_tiles=(sc.ns // sc.s_blk) * nj)
    return sc.launch(
        kernel,
        (sc.pad_cube(ded), template, window, weights, cell_mask),
        (pl.BlockSpec(memory_space=pltpu.ANY), sc.row_spec, sc.row_spec,
         sc.cell_spec, sc.cell_spec),
        cos_t, sin_t, tt_info, interpret,
        scratch_shapes=_dma_scratch(sc, ded.dtype),
    )


def sweep_shard_diags_disp(disp, rot_t, nyq_row, template, weights,
                           cell_mask, dma=None):
    """Per-shard dispersed-frame one-read diagnostics for the sharded
    fused sweep: same values as :func:`cell_diagnostics_pallas_disp` with
    the cube fetched through the double-buffered DMA pipeline (``dma``
    None resolves the ICLEAN_SWEEP_DMA env mirror; 'off' keeps the
    BlockSpec route).  Unbatched — the sharded engine runs one archive
    per shard_map body."""
    if not _sweep_dma_default(dma):
        return cell_diagnostics_pallas_disp(disp, rot_t, nyq_row, template,
                                            weights, cell_mask)
    apply_nyq = nyq_row is not None
    if nyq_row is None:
        nyq_row = jnp.zeros_like(rot_t)
    cos_t, sin_t, num_k, interpret = _fused_tables(disp.shape[-1],
                                                   disp.dtype)
    outs = _shard_diags_disp_call(
        disp[None], rot_t[None], nyq_row[None], _tt_info(template[None]),
        weights[None].astype(jnp.float32), cell_mask[None], cos_t, sin_t,
        num_k, interpret, _cell_blocks(disp.shape[-1]), apply_nyq)
    return tuple(o[0] for o in outs)


def sweep_shard_diags_dedisp(ded, template, window, weights, cell_mask,
                             dma=None):
    """Per-shard dedispersed-frame twin of
    :func:`sweep_shard_diags_disp`."""
    if not _sweep_dma_default(dma):
        return cell_diagnostics_pallas_dedisp(ded, template, window,
                                              weights, cell_mask)
    cos_t, sin_t, num_k, interpret = _fused_tables(ded.shape[-1], ded.dtype)
    outs = _shard_diags_dedisp_call(
        ded[None], template[None], window.astype(jnp.float32)[None],
        _tt_info(template[None]), weights[None].astype(jnp.float32),
        cell_mask[None], cos_t, sin_t, num_k, interpret,
        _cell_blocks(ded.shape[-1]))
    return tuple(o[0] for o in outs)


# ---------------------------------------------------------------------------
# Fused sweep: diagnostics + scaler + combine + zap, one cube read
# ---------------------------------------------------------------------------
#
# The fused cell kernels above still hand their four diagnostic planes back
# to XLA for the scaler/combine/zap stages — three more launches plus four
# plane round-trips through HBM per iteration.  The sweep kernels keep the
# per-archive diagnostic planes in VMEM scratch for the whole launch
# (sequential TPU grid, same idiom as _marginals_kernel) and, on each
# archive's final grid step, run the entire remaining iteration tail —
# both scaler orientations (_scaled_sides_body), the 4-way median
# (_median4), and the threshold/zap — on the resident planes.  One kernel,
# one cube-tile read per iteration; outputs are the new weights, the
# scores, and the residual-std plane (the engine's telemetry input).
#
# Bit-equality with the unfused route is by construction: the residual and
# diagnostics bodies are the SAME functions the standalone kernels trace
# (_wres_disp/_wres_dedisp, _diag_tail), and the combine tail reuses the
# scaler body already locked in as bit-identical to the sort/XLA route.
# Hardware status: interpret-verified; Mosaic lowering of the combine tail
# awaits a TPU validation pass (same class as the k-chunked 2048/4096
# path) — the engine knob's 'auto' is gated on the fused-stats resolution,
# not on a separate hardware allowlist.

# The sweep kernel's whole-archive VMEM set: four scratch planes, three
# output planes, the two plain-layout input planes, plus the combine
# stage's plane-sized bisection temporaries — conservatively budgeted as
# 12 resident (S_pad, nc) float32 planes against a 24 MiB cap (the same
# budget class as MARGINALS_PALLAS_MAX_BYTES).  Bigger cell planes keep
# the multi-kernel route.
FUSED_SWEEP_MAX_BYTES = 24 * 2**20


def fused_sweep_eligible(nsub: int, nchan: int, nbin: int) -> bool:
    """THE eligibility predicate for the fused sweep kernels — callers
    (engine/loop.py, online/session.py, bench.py's bytes-moved model)
    must use this, not re-derive the plane budget.  Geometry-only: the
    float32/backend/knob gates live with the caller (engine routes also
    require ``stats_impl='fused'`` and an unsharded program)."""
    if nbin > FUSED_STATS_MAX_NBIN:
        return False
    s_blk, c_blk = _cell_blocks(nbin)
    s_pad = nsub + (-nsub) % s_blk
    nc = nchan + (-nchan) % c_blk
    return 12 * s_pad * nc * 4 <= FUSED_SWEEP_MAX_BYTES


def _combine_zap(d0, d1, d2, d3, mask, worig, chanthresh, subintthresh,
                 pad_mask):
    """The iteration tail on whole (S, C) VMEM planes: both scaler
    orientations, the 4-way median, and the threshold/zap.  One op
    sequence shared by the sweep kernels' final step and the standalone
    :func:`fused_combine_pallas` launch.

    ``pad_mask`` marks grid-padding cells (None when the planes are
    unpadded): they are already True in ``mask`` (masked medians skip
    them), and the rFFT diagnostic's plain path gets them as
    ``plain_mask`` — rank selection over exactly the real cells, the way
    cropping would — with the plane zeroed at pads first so the
    NaN-propagation patch (which scans whole lines) sees finite values
    there.  Outputs at padding cells are garbage and must be cropped."""
    if pad_mask is not None:
        d3 = jnp.where(pad_mask, np.float32(0.0), d3)
    chan = _scaled_sides_body(d0, d1, d2, d3, mask, chanthresh,
                              plain_mask=pad_mask)
    sub_pm = None if pad_mask is None else pad_mask.T
    # transposed orientation in VMEM (the _scaled_sides_t_kernel trick: a
    # transpose moves values, it does not round them)
    sub = _scaled_sides_body(d0.T, d1.T, d2.T, d3.T, mask.T, subintthresh,
                             plain_mask=sub_pm)
    per = [jnp.maximum(c, s.T) for c, s in zip(chan, sub)]
    scores = _median4(*per)
    new_w = jnp.where(scores >= np.float32(1.0), np.float32(0.0), worig)
    return new_w, scores


def _sweep_combine(i, j, kk, bpa, num_k, nsub, nchan, accs, mplane_ref,
                   worig_ref, neww_ref, scores_ref, dstd_ref,
                   chanthresh, subintthresh):
    """Shared final-step tail of the sweep kernels: on each archive's last
    grid step, combine the resident scratch planes and write the three
    whole-archive output planes."""
    i_loc = i % bpa

    @pl.when((i_loc == bpa - 1) & (j == pl.num_programs(1) - 1)
             & (kk == num_k - 1))
    def _combine():
        m = mplane_ref[:]
        s_pad, nc = m.shape
        pad_mask = None
        if s_pad != nsub or nc != nchan:
            rows = jax.lax.broadcasted_iota(jnp.int32, (s_pad, nc), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (s_pad, nc), 1)
            pad_mask = (rows >= nsub) | (cols >= nchan)
        new_w, scores = _combine_zap(
            accs[0][...], accs[1][...], accs[2][...], accs[3][...],
            m, worig_ref[:], chanthresh, subintthresh, pad_mask)
        neww_ref[...] = new_w
        scores_ref[...] = scores
        dstd_ref[...] = accs[0][...]


def _sweep_disp_kernel(disp_ref, rott_ref, nyq_ref, w_ref, m_ref,
                       mplane_ref, worig_ref, cos_ref, sin_ref, tt_ref,
                       neww_ref, scores_ref, dstd_ref,
                       std_acc, mean_acc, ptp_acc, fft_acc, *, num_k, bpa,
                       nsub, nchan, apply_nyq, chanthresh, subintthresh):
    """Dispersed-frame one-read SWEEP: :func:`_cell_stats_disp_kernel`'s
    per-step body accumulating into per-archive scratch planes, plus the
    combine/zap tail on each archive's final grid step."""
    i, j, kk = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    wres = _wres_disp(disp_ref[:], rott_ref[0], nyq_ref[0], tt_safe,
                      tt_zero, w_ref[0], apply_nyq=apply_nyq)
    accs = (std_acc, mean_acc, ptp_acc, fft_acc)
    s_blk, c_blk = disp_ref.shape[0], disp_ref.shape[1]
    _diag_tail(wres, m_ref[0], cos_ref, sin_ref, num_k,
               _SliceSink(accs, (i % bpa) * s_blk, j * c_blk, s_blk, c_blk))
    _sweep_combine(i, j, kk, bpa, num_k, nsub, nchan, accs, mplane_ref,
                   worig_ref, neww_ref, scores_ref, dstd_ref,
                   chanthresh, subintthresh)


def _sweep_dedisp_kernel(ded_ref, t_ref, win_ref, w_ref, m_ref,
                         mplane_ref, worig_ref, cos_ref, sin_ref, tt_ref,
                         neww_ref, scores_ref, dstd_ref,
                         std_acc, mean_acc, ptp_acc, fft_acc, *, num_k, bpa,
                         nsub, nchan, chanthresh, subintthresh):
    """Dedispersed-frame SWEEP twin of :func:`_cell_stats_dedisp_kernel`."""
    i, j, kk = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    tt_safe, tt_zero = tt_ref[0, 0], tt_ref[0, 1]
    wres = _wres_dedisp(ded_ref[:], t_ref[0], win_ref[0], tt_safe, tt_zero,
                        w_ref[0])
    accs = (std_acc, mean_acc, ptp_acc, fft_acc)
    s_blk, c_blk = ded_ref.shape[0], ded_ref.shape[1]
    _diag_tail(wres, m_ref[0], cos_ref, sin_ref, num_k,
               _SliceSink(accs, (i % bpa) * s_blk, j * c_blk, s_blk, c_blk))
    _sweep_combine(i, j, kk, bpa, num_k, nsub, nchan, accs, mplane_ref,
                   worig_ref, neww_ref, scores_ref, dstd_ref,
                   chanthresh, subintthresh)


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks",
                                    "apply_nyq", "chanthresh",
                                    "subintthresh"))
def _sweep_disp_call(disp, rot_t, nyq_row, tt_info, weights, cell_mask,
                     cos_t, sin_t, num_k, interpret, blocks, apply_nyq,
                     chanthresh, subintthresh):
    sc = _FusedScaffold(*disp.shape[1:], num_k, batch=disp.shape[0],
                        blocks=blocks)
    w_cells, m_cells = sc.pad_cells(weights, cell_mask)
    kernel = functools.partial(_sweep_disp_kernel, apply_nyq=apply_nyq,
                               chanthresh=chanthresh,
                               subintthresh=subintthresh)
    return sc.launch_sweep(
        kernel,
        (sc.pad_cube(disp), sc.pad_chan_row(rot_t),
         sc.pad_chan_row(nyq_row), w_cells, m_cells,
         sc.pad_plane(cell_mask, masked=True), sc.pad_plane(weights)),
        (sc.cube_spec, sc.chan_row_spec, sc.chan_row_spec, sc.cell_spec,
         sc.cell_spec, sc.plane_spec, sc.plane_spec),
        cos_t, sin_t, tt_info, interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_k", "interpret", "blocks",
                                    "chanthresh", "subintthresh"))
def _sweep_dedisp_call(ded, template, window, tt_info, weights, cell_mask,
                       cos_t, sin_t, num_k, interpret, blocks, chanthresh,
                       subintthresh):
    sc = _FusedScaffold(*ded.shape[1:], num_k, batch=ded.shape[0],
                        blocks=blocks)
    w_cells, m_cells = sc.pad_cells(weights, cell_mask)
    kernel = functools.partial(_sweep_dedisp_kernel, chanthresh=chanthresh,
                               subintthresh=subintthresh)
    return sc.launch_sweep(
        kernel,
        (sc.pad_cube(ded), template, window, w_cells, m_cells,
         sc.pad_plane(cell_mask, masked=True), sc.pad_plane(weights)),
        (sc.cube_spec, sc.row_spec, sc.row_spec, sc.cell_spec,
         sc.cell_spec, sc.plane_spec, sc.plane_spec),
        cos_t, sin_t, tt_info, interpret)


def _fused_sweep_disp_batched(disp, rot_t, nyq_row, template, weights,
                              cell_mask, apply_nyq, chanthresh,
                              subintthresh):
    cos_t, sin_t, num_k, interpret = _fused_tables(disp.shape[-1],
                                                   disp.dtype)
    return _sweep_disp_call(disp, rot_t, nyq_row, _tt_info(template),
                            weights.astype(jnp.float32), cell_mask,
                            cos_t, sin_t, num_k, interpret,
                            _cell_blocks(disp.shape[-1]), apply_nyq,
                            chanthresh, subintthresh)


@functools.lru_cache(maxsize=16)
def _fused_sweep_disp_fn(apply_nyq: bool, chanthresh: float,
                         subintthresh: float):
    from jax.custom_batching import custom_vmap as _custom_vmap

    @_custom_vmap
    def f(disp, rot_t, nyq_row, template, weights, cell_mask):
        outs = _fused_sweep_disp_batched(
            disp[None], rot_t[None], nyq_row[None], template[None],
            weights[None], cell_mask[None], apply_nyq, chanthresh,
            subintthresh)
        return tuple(o[0] for o in outs)

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        # batched archives fold into the subint grid of ONE launch; the
        # per-archive combine fires on each archive's final grid step
        return (_fused_sweep_disp_batched(
            *_batch_args(axis_size, in_batched, *args), apply_nyq,
            chanthresh, subintthresh), (True,) * 3)

    return f


def fused_sweep_pallas(disp, rot_t, nyq_row, template, weights, cell_mask,
                       chanthresh, subintthresh):
    """Dispersed-frame one-read fused SWEEP (float32; interpreted off-TPU):
    fit + residual + diagnostics + scaler + combine + zap in ONE kernel
    reading each cube tile exactly once.  ``weights`` is the plane the
    residual is weighted by AND the zap edits — the engine's
    ``orig_weights`` (reference :112: zaps re-derive from the original
    weights each round).  Returns (new_weights, scores, d_std), each
    (nsub, nchan) float32, bit-equal to the unfused
    :func:`cell_diagnostics_pallas_disp` +
    :func:`masked_jax.scale_and_combine` + threshold route.  Batches
    under ``vmap`` by folding archives into the launch grid."""
    apply_nyq = nyq_row is not None
    if nyq_row is None:
        nyq_row = jnp.zeros_like(rot_t)
    return _fused_sweep_disp_fn(apply_nyq, float(chanthresh),
                                float(subintthresh))(
        disp, rot_t, nyq_row, template, weights.astype(jnp.float32),
        cell_mask)


def _fused_sweep_dedisp_batched(ded, template, window, weights, cell_mask,
                                chanthresh, subintthresh):
    cos_t, sin_t, num_k, interpret = _fused_tables(ded.shape[-1], ded.dtype)
    return _sweep_dedisp_call(ded, template, window, _tt_info(template),
                              weights.astype(jnp.float32), cell_mask,
                              cos_t, sin_t, num_k, interpret,
                              _cell_blocks(ded.shape[-1]), chanthresh,
                              subintthresh)


@functools.lru_cache(maxsize=16)
def _fused_sweep_dedisp_fn(chanthresh: float, subintthresh: float):
    from jax.custom_batching import custom_vmap as _custom_vmap

    @_custom_vmap
    def f(ded, template, window, weights, cell_mask):
        outs = _fused_sweep_dedisp_batched(
            ded[None], template[None], window[None], weights[None],
            cell_mask[None], chanthresh, subintthresh)
        return tuple(o[0] for o in outs)

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        return (_fused_sweep_dedisp_batched(
            *_batch_args(axis_size, in_batched, *args), chanthresh,
            subintthresh), (True,) * 3)

    return f


def fused_sweep_pallas_dedisp(ded, template, window, weights, cell_mask,
                              chanthresh, subintthresh):
    """Dedispersed-frame fused SWEEP twin of :func:`fused_sweep_pallas`:
    one cube read, returns (new_weights, scores, d_std).  ``window`` is
    the (nbin,) pulse-region multiplier (all ones when inactive)."""
    return _fused_sweep_dedisp_fn(float(chanthresh), float(subintthresh))(
        ded, template, window.astype(jnp.float32),
        weights.astype(jnp.float32), cell_mask)


def _fused_combine_kernel(d0_ref, d1_ref, d2_ref, d3_ref, m_ref, worig_ref,
                          neww_ref, scores_ref, *, nsub, nchan, chanthresh,
                          subintthresh):
    s_pad, nc = m_ref.shape
    pad_mask = None
    if s_pad != nsub or nc != nchan:
        rows = jax.lax.broadcasted_iota(jnp.int32, (s_pad, nc), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s_pad, nc), 1)
        pad_mask = (rows >= nsub) | (cols >= nchan)
    new_w, scores = _combine_zap(d0_ref[:], d1_ref[:], d2_ref[:], d3_ref[:],
                                 m_ref[:], worig_ref[:], chanthresh,
                                 subintthresh, pad_mask)
    neww_ref[...] = new_w
    scores_ref[...] = scores


@functools.partial(jax.jit,
                   static_argnames=("chanthresh", "subintthresh",
                                    "interpret"))
def _fused_combine_call(d0, d1, d2, d3, cell_mask, worig, chanthresh,
                        subintthresh, interpret):
    nsub, nchan = d0.shape
    pad_s, pad_c = (-nsub) % 8, (-nchan) % 128
    if pad_s or pad_c:
        pads = ((0, pad_s), (0, pad_c))
        d0, d1, d2, d3, worig = (jnp.pad(x, pads)
                                 for x in (d0, d1, d2, d3, worig))
        cell_mask = jnp.pad(cell_mask, pads, constant_values=True)
    shape = d0.shape
    spec = pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
    kernel = functools.partial(_fused_combine_kernel, nsub=nsub,
                               nchan=nchan, chanthresh=chanthresh,
                               subintthresh=subintthresh)
    outs = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(shape, jnp.float32)] * 2,
        grid=(1,),
        in_specs=[spec] * 6,
        out_specs=[spec] * 2,
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_SCALER_VMEM_BYTES),
    )(d0, d1, d2, d3, cell_mask, worig)
    return tuple(o[:nsub, :nchan] for o in outs)


def fused_combine_pallas(diagnostics, cell_mask, orig_weights, chanthresh,
                         subintthresh):
    """The iteration tail — both scaler orientations, 4-way median,
    threshold/zap — as ONE launch on already-computed diagnostic planes
    (float32; interpreted off-TPU).  Returns (new_weights, scores),
    bit-equal to :func:`masked_jax.scale_and_combine` (any median_impl)
    plus the threshold.  Built for exact streaming's per-iteration
    combine, where the planes are device-resident tile concatenations and
    the multi-launch scaler route would round-trip them through HBM (and,
    host-side, back over the interconnect) every iteration."""
    d0, d1, d2, d3 = diagnostics
    if d0.dtype != jnp.float32:
        raise TypeError("fused_combine_pallas requires float32, got %s"
                        % d0.dtype)
    return _fused_combine_call(d0, d1, d2, d3, cell_mask,
                               orig_weights.astype(jnp.float32),
                               float(chanthresh), float(subintthresh),
                               _interpret_default())


@functools.lru_cache(maxsize=8)
def _masked_median_fn(axis: int):
    """``masked_median_pallas`` for one axis under ``custom_vmap``: a
    vmapped call folds the batch into the line axis of a single launch
    (same scheme as :func:`_scaled_sides_fn`)."""
    from jax.custom_batching import custom_vmap

    @custom_vmap
    def f(values, mask):
        interpret = _interpret_default()
        if axis == 0:
            return _median_axis0(values, mask, interpret)
        return _median_axis0(values.T, mask.T, interpret).T

    @f.def_vmap
    def _rule(axis_size, in_batched, values, mask):
        values, mask = _batch_args(axis_size, in_batched, values, mask)
        B, S, C = values.shape
        fold, unfold = _line_fold(axis, B, S, C, keepdims=True)
        interpret = _interpret_default()
        out = _median_axis0(fold(values), fold(mask), interpret)
        return unfold(out), True

    return f


def masked_median_pallas(values, mask, axis):
    """Drop-in for :func:`masked_jax.masked_median` (keepdims semantics),
    float32 only.  axis 0 reduces down subints (channel scaler), axis 1 down
    channels (subint scaler; handled by transposing the tile).  Batches
    under ``vmap`` by folding the batch into the line axis."""
    if values.dtype != jnp.float32:
        raise TypeError("masked_median_pallas requires float32, got %s"
                        % values.dtype)
    if axis not in (0, 1):
        raise ValueError("axis must be 0 or 1 for 2-D diagnostics")
    return _masked_median_fn(axis)(values, mask)

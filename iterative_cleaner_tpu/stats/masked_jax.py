"""Detection statistics, compiled path (JAX).

The same observable semantics as :mod:`iterative_cleaner_tpu.stats.masked_numpy`
(reference ``/root/reference/iterative_cleaner.py:181-256``), with the
``numpy.ma`` behaviour made explicit over (value, mask) pairs.  The effective
rules, established empirically against numpy and locked in by
tests/test_stats_parity.py:

1. Binary ops leave masked entries' ``.data`` untouched (pass-through);
   unary ``abs`` computes on all data.
2. A zero-MAD or empty line masks the whole line, leaving the centred
   numerator as ``.data`` (undivided).
3. The final ``/threshold`` does not touch masked entries' data.
4. Fully-masked reductions leave ``.data`` 0 for std/mean and the ``np.ma``
   float fill 1e20 for ptp.
5. The rFFT diagnostic drops masks entirely: it is scaled on the *plain*
   path where zero MAD produces IEEE inf/nan.
6. The ``np.max`` stacking and the final 4-way median run on raw data.

Masks here are always cell-uniform across pulse bins (they come from the
(nsub, nchan) weight matrix, reference :115-117), which keeps the bin-axis
reductions mask-free.

The hot reductions are the masked medians over lines of the (nsub, nchan)
diagnostic matrices; `masked_median` is sort-based (+inf padding, count
indexing) which XLA maps well to TPU; a Pallas kernel can slot in behind the
same signature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# numpy.ma default float fill value, observable through quirk 4.
MA_FILL = 1e20


def masked_median(values, mask, axis, impl="sort"):
    """``np.ma.median`` semantics: median over unmasked entries along axis.

    Even counts average the two middle order statistics.  Lines with no valid
    entries return 0.0 — callers must handle them via the count (np.ma would
    return ``masked``; the 0.0 placeholder is never observable because those
    lines are fully masked downstream).  Keeps the reduced axis (keepdims).

    impl="pallas" routes to the radix-bisection TPU kernel
    (:mod:`iterative_cleaner_tpu.stats.pallas_kernels`), which agrees with
    the sort path bit-for-bit.
    """
    if impl == "pallas":
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            masked_median_pallas,
        )

        return masked_median_pallas(values, mask, axis)
    sentinel = jnp.asarray(jnp.inf, dtype=values.dtype)
    ordered = jnp.sort(jnp.where(mask, sentinel, values), axis=axis)
    n = jnp.sum(~mask, axis=axis, keepdims=True)
    size = values.shape[axis]
    lo = jnp.take_along_axis(ordered, jnp.clip((n - 1) // 2, 0, size - 1), axis=axis)
    hi = jnp.take_along_axis(ordered, jnp.clip(n // 2, 0, size - 1), axis=axis)
    med = 0.5 * (lo + hi)
    return jnp.where(n == 0, jnp.zeros_like(med), med)


def _masked_side(centred, mad, mask, n, thresh):
    """Shared masked-path epilogue (rules 1-4): zero-MAD/empty lines go
    dead (centred data passes through undivided), live entries are
    ``|centred/mad| / thresh``.  Single source of truth for the
    per-diagnostic route AND the fused scaler kernel, which traces this
    same function inside the Pallas launch
    (pallas_kernels._scaled_sides_kernel)."""
    line_dead = (mad == 0) | (n == 0)
    safe_mad = jnp.where(line_dead, jnp.ones_like(mad), mad)
    dead = mask | line_dead
    mag = jnp.abs(jnp.where(dead, centred, centred / safe_mad))
    return jnp.where(dead, mag, mag / thresh)


def scale_lines_masked(diag, mask, axis, thresh, median_impl="sort"):
    """Masked-path line normalisation, post |.|/threshold.

    Returns the raw data that survives the mask-dropping ``np.max`` stacking:
    ``|(x - med)/mad| / thresh`` for live entries, with masked entries
    carrying their (undivided) pass-through data per rules 1-3.
    """
    n = jnp.sum(~mask, axis=axis, keepdims=True)
    med = masked_median(diag, mask, axis, impl=median_impl)
    centred = jnp.where(mask, diag, diag - med)
    mad = masked_median(jnp.abs(centred), mask, axis, impl=median_impl)
    return _masked_side(centred, mad, mask, n, thresh)


def _patch_nan_lines(med, values, axis):
    """NaN-bearing lines median to NaN (``jnp.median`` propagation) — the
    Pallas kernel instead sorts NaN keys above +inf, so its plain-median
    users patch through this single helper."""
    has_nan = jnp.any(jnp.isnan(values), axis=axis, keepdims=True)
    return jnp.where(has_nan, jnp.nan, med)


def _plain_median(diag, axis, median_impl):
    """``jnp.median`` (keepdims), optionally via the Pallas kernel with an
    all-false mask — the two share XLA's sort total order, so non-NaN lines
    agree bit-for-bit (verified in tests), and NaN-bearing lines are patched
    to NaN to match ``jnp.median``'s propagation; the kernel avoids two full
    sorts per scaler."""
    if median_impl == "pallas":
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            masked_median_pallas,
        )

        med = masked_median_pallas(diag, jnp.zeros(diag.shape, bool), axis)
        return _patch_nan_lines(med, diag, axis)
    return jnp.median(diag, axis=axis, keepdims=True)


def scale_lines_plain(diag, axis, thresh, median_impl="sort"):
    """Plain-path normalisation (the rFFT diagnostic): IEEE semantics, no
    masking — zero MAD yields inf/nan that flow onward (quirk 5)."""
    med = _plain_median(diag, axis, median_impl)
    centred = diag - med
    mad = _plain_median(jnp.abs(centred), axis, median_impl)
    return jnp.abs(centred / mad) / thresh


def rfft_magnitudes(x, mode="fft"):
    """|rfft| along the last axis.

    mode="fft" uses the FFT; mode="dft" computes the same magnitudes with two
    real matmuls against a cos/sin basis — mathematically identical, maps
    onto the TPU MXU (where XLA's FFT is comparatively weak), and avoids the
    XLA:CPU fft-thunk layout restriction under sharding.
    """
    if mode == "fft":
        return jnp.abs(jnp.fft.rfft(x, axis=-1))
    if mode != "dft":
        raise ValueError(f"unknown fft mode {mode!r}")
    nbin = x.shape[-1]
    ang = (-2.0 * jnp.pi / nbin) * jnp.outer(
        jnp.arange(nbin, dtype=x.dtype), jnp.arange(nbin // 2 + 1, dtype=x.dtype)
    )
    re = jax.lax.dot_general(
        x, jnp.cos(ang), (((x.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )
    im = jax.lax.dot_general(
        x, jnp.sin(ang), (((x.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )
    return jnp.sqrt(re * re + im * im)


def cell_diagnostics_jax(resid_weighted, cell_mask, fft_mode="fft"):
    """The four per-cell diagnostics of reference :206-212 as (nsub, nchan)
    matrices: (d_std, d_mean, d_ptp, d_fft).

    Since the cell mask is bin-uniform and masked cells' data is exactly
    zero (``apply_weights`` zeroed them, reference :296), bin-axis
    reductions are computed plainly and patched per rule 4.
    """
    x = resid_weighted
    m = cell_mask

    # two passes over the cube: a mean pass, then one fused pass computing
    # the centred moments and the rFFT magnitudes off the shared ``centred``
    # (jnp.std's stable two-pass variance — the single-pass identity
    # catastrophically cancels for |mean| >> std cells).  Masked cells'
    # centring skew is irrelevant: their std is patched to 0.
    n = x.shape[2]
    mean_b = jnp.sum(x, axis=2) / n
    d_mean = jnp.where(m, 0.0, mean_b)
    centred = x - jnp.where(m, 0.0, mean_b)[..., None]
    var = jnp.sum(centred * centred, axis=2) / n
    d_std = jnp.where(m, 0.0, jnp.sqrt(var))
    d_ptp = jnp.where(m, jnp.asarray(MA_FILL, x.dtype),
                      jnp.max(x, axis=2) - jnp.min(x, axis=2))
    d_fft = jnp.max(rfft_magnitudes(centred, fft_mode), axis=2)
    return d_std, d_mean, d_ptp, d_fft


def _scaled_sides_fused_pallas(diagnostics, cell_mask, axis, thresh):
    """One orientation of all four scalers in ONE Pallas launch
    (:func:`iterative_cleaner_tpu.stats.pallas_kernels.scaled_sides_pallas`):
    median, centring, MAD and epilogue fused in VMEM.  The kernel
    replicates the `_masked_side`/`_patch_nan_lines` op sequences exactly,
    so it stays bit-identical to the unfused route (locked in by
    tests/test_pallas_stats.py)."""
    from iterative_cleaner_tpu.stats.pallas_kernels import (
        scaled_sides_pallas,
    )

    return list(scaled_sides_pallas(diagnostics, cell_mask, axis, thresh))


def scale_and_combine(diagnostics, cell_mask, chanthresh, subintthresh,
                      median_impl="sort"):
    """Channel/subint scaling + 4-way median (reference :220-226) over
    precomputed diagnostics (from :func:`cell_diagnostics_jax` or the fused
    Pallas kernel)."""
    d_std, d_mean, d_ptp, d_fft = diagnostics
    m = cell_mask
    if median_impl == "pallas" and d_fft.dtype == jnp.float32:
        chan = _scaled_sides_fused_pallas(diagnostics, m, 0, chanthresh)
        subint = _scaled_sides_fused_pallas(diagnostics, m, 1,
                                            subintthresh)
        per_diag = [jnp.maximum(c, s) for c, s in zip(chan, subint)]
        return jnp.median(jnp.stack(per_diag), axis=0)
    per_diag = []
    for diag in (d_std, d_mean, d_ptp):
        chan_side = scale_lines_masked(diag, m, 0, chanthresh, median_impl)
        subint_side = scale_lines_masked(diag, m, 1, subintthresh, median_impl)
        per_diag.append(jnp.maximum(chan_side, subint_side))
    fft_impl = median_impl if d_fft.dtype == jnp.float32 else "sort"
    per_diag.append(
        jnp.maximum(scale_lines_plain(d_fft, 0, chanthresh, fft_impl),
                    scale_lines_plain(d_fft, 1, subintthresh, fft_impl))
    )
    return jnp.median(jnp.stack(per_diag), axis=0)


def _masked_median_1gather(values, mask, axis, n):
    """:func:`masked_median` (sort impl) with the two order-statistic picks
    in ONE ``take_along_axis`` — indices concatenated along the sort axis,
    the pair split back off afterwards.  Gathers copy elements, so the
    result is bit-identical; one gather op instead of two matters only for
    program compile latency (see :func:`scale_and_combine_compact`).
    ``n`` is the caller's precomputed unmasked count (keepdims)."""
    sentinel = jnp.asarray(jnp.inf, dtype=values.dtype)
    ordered = jnp.sort(jnp.where(mask, sentinel, values), axis=axis)
    size = values.shape[axis]
    idx = jnp.concatenate([jnp.clip((n - 1) // 2, 0, size - 1),
                           jnp.clip(n // 2, 0, size - 1)], axis=axis)
    picks = jnp.take_along_axis(ordered, idx, axis=axis)
    lo = jax.lax.slice_in_dim(picks, 0, 1, axis=axis)
    hi = jax.lax.slice_in_dim(picks, 1, 2, axis=axis)
    med = 0.5 * (lo + hi)
    return jnp.where(n == 0, jnp.zeros_like(med), med)


def _scaled_sides_stacked(diagnostics, mask, axis, thresh, median_impl):
    """One orientation of all four scalers over a STACKED (4, nsub, nchan)
    array: the two medians inside cost one sort each instead of one per
    diagnostic.  Sort, take_along_axis and every elementwise op act per
    line, so each slice is bit-identical to the unstacked route — the
    masked slices to :func:`scale_lines_masked`, the rFFT slice to
    :func:`scale_lines_plain` (its ``jnp.median`` equals the all-false-mask
    ``masked_median`` with NaN-bearing lines patched; locked in by
    tests/test_stats_parity.py)."""
    stacked = jnp.stack(diagnostics)
    mask4 = jnp.concatenate([
        jnp.broadcast_to(mask, (3,) + mask.shape),
        jnp.zeros((1,) + mask.shape, dtype=bool),  # rFFT: plain path
    ])
    ax = axis + 1
    n = jnp.sum(~mask4, axis=ax, keepdims=True)
    # quirk-5 NaN patches apply to the plain slice only; a broadcast
    # selector keeps them as cheap `where`s instead of scatter updates
    plain = jnp.arange(4).reshape((4,) + (1,) * mask.ndim) == 3
    med = _masked_median_1gather(stacked, mask4, ax, n)
    med = jnp.where(
        plain & jnp.any(jnp.isnan(stacked), axis=ax, keepdims=True),
        jnp.nan, med)
    centred = jnp.where(mask4, stacked, stacked - med)
    abs_centred = jnp.abs(centred)
    mad = _masked_median_1gather(abs_centred, mask4, ax, n)
    mad = jnp.where(
        plain & jnp.any(jnp.isnan(abs_centred), axis=ax, keepdims=True),
        jnp.nan, mad)
    masked_out = _masked_side(centred[:3], mad[:3], mask4[:3], n[:3], thresh)
    plain_out = jnp.abs(centred[3] / mad[3]) / thresh
    return [masked_out[0], masked_out[1], masked_out[2], plain_out]


def scale_and_combine_compact(diagnostics, cell_mask, chanthresh,
                              subintthresh, median_impl="sort"):
    """:func:`scale_and_combine` with the four diagnostics stacked so each
    orientation costs TWO sort ops instead of eight — bit-identical output
    (see :func:`_scaled_sides_stacked`).

    Built for callers that compile the combine step as its own standalone
    XLA program: exact streaming's per-iteration combine
    (parallel/streaming_exact.py), where program compile latency is paid
    on the first iteration's critical path and scales with the op count.
    The whole-archive engines keep :func:`scale_and_combine` — their
    combine lowers inside one monolithic program where XLA's own CSE and
    fusion absorb the duplicate sorts and the compile is a single
    up-front cost.
    """
    if median_impl == "pallas":
        # the fused Pallas scaler is already a single launch per
        # orientation — nothing left to stack (and the non-float32 rFFT
        # fallback would need per-slice impls the stacked call can't mix)
        return scale_and_combine(diagnostics, cell_mask, chanthresh,
                                 subintthresh, median_impl)
    chan = _scaled_sides_stacked(diagnostics, cell_mask, 0, chanthresh,
                                 median_impl)
    subint = _scaled_sides_stacked(diagnostics, cell_mask, 1, subintthresh,
                                   median_impl)
    per_diag = [jnp.maximum(c, s) for c, s in zip(chan, subint)]
    return jnp.median(jnp.stack(per_diag), axis=0)


def surgical_scores_jax(resid_weighted, cell_mask, chanthresh, subintthresh,
                        fft_mode="fft", median_impl="sort"):
    """Zap scores for every (subint, channel) cell; score >= 1 means zap.

    Mirrors reference :202-226 under the explicit-mask rules above.
    """
    return scale_and_combine(
        cell_diagnostics_jax(resid_weighted, cell_mask, fft_mode),
        cell_mask, chanthresh, subintthresh, median_impl,
    )

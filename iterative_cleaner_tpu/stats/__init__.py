"""Surgical-scrub detection statistics.

Two implementations of the same observable semantics (reference
``comprehensive_stats``/``channel_scaler``/``subint_scaler`` at
``/root/reference/iterative_cleaner.py:181-256``):

- :mod:`iterative_cleaner_tpu.stats.masked_numpy` — the float64 oracle, built
  directly on ``numpy.ma`` so every masked-array quirk of the reference
  (SURVEY.md section 2.4, quirks 6-9) is inherited rather than re-derived.
- :mod:`iterative_cleaner_tpu.stats.masked_jax` — the compiled path, with the
  ``np.ma`` rules made explicit over (value, mask) pairs (empirically
  verified: see tests/test_stats_parity.py).
"""

from iterative_cleaner_tpu.stats.masked_numpy import surgical_scores_numpy  # noqa: F401
from iterative_cleaner_tpu.stats.masked_jax import surgical_scores_jax  # noqa: F401

"""Long-lived cleaning service: crash-safe queue, admission control,
deadlines, backpressure, graceful drain (``--serve``).

The daemon keeps the process — and with it the AOT bucket memo, the batch
builders' caches and the persistent compilation cache handshake — alive
across requests, so repeat-geometry requests serve warm.  See
:mod:`iterative_cleaner_tpu.serve.daemon` for the request lifecycle.
"""

from iterative_cleaner_tpu.serve.daemon import (  # noqa: F401
    ServeDaemon,
    default_out_path,
    run_serve,
)
from iterative_cleaner_tpu.serve.membership import (  # noqa: F401
    PoolMembership,
)
from iterative_cleaner_tpu.serve.request import (  # noqa: F401
    OVERRIDABLE,
    RequestError,
    ServeRequest,
    parse_request,
    request_key,
    request_work_key,
)
from iterative_cleaner_tpu.serve.result_cache import (  # noqa: F401
    ResultCache,
)
from iterative_cleaner_tpu.serve.scheduler import (  # noqa: F401
    Rejection,
    ServeScheduler,
)
from iterative_cleaner_tpu.serve.spool import (  # noqa: F401
    ACCEPTED_SUFFIX,
    REJECTED_SUFFIX,
    SpoolWatcher,
)

"""Coordinator-free pool membership for the elastic serving tier.

A pool is whatever set of daemons shares one journal: each member
announces itself with journaled membership lines (``--join``), and the
roster is derived by folding the journal
(:meth:`~iterative_cleaner_tpu.resilience.journal.FleetJournal.member_table`)
— no registry service, no leader, no gossip.  Membership reuses the
claim-lease grammar: a member IS a lease on pool membership, granted by
'join', extended by 'hb' and ended by 'leave'.

Liveness is the lease: a SIGKILLed member stops heartbeating and its
lease expires after ``ttl_s``.  Eviction is not an action anyone takes —
it is an observation every surviving member makes independently from
the same journal fold (and journal compaction drops the lapsed member's
lines, so a compacted roster carries no ghosts).  The first time THIS
process observes a previously-live member lapse it counts
``serve_members_evicted`` once, which is the signal the failover bench
and the chaos drill assert on.

Member ids are per-incarnation (pid + random tag): a restarted daemon
re-joins under a fresh id and its dead predecessor simply expires —
the same rule as claim nonces, and for the same reason (a new process
must never inherit a lease it cannot know the state of).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


class PoolMembership:
    """One daemon's view of (and presence in) the pool.

    :meth:`heartbeat` and :meth:`evict_lapsed` are called from the
    daemon loop (which ticks every ``poll_s``) and throttle themselves.
    The loop alone is not enough, though: the daemon executes requests
    INLINE, so a member mid-way through a long clean would stop beating
    and be spuriously evicted by its peers.  :meth:`start_auto_beat`
    therefore runs the same throttled heartbeat from a background
    thread (the :class:`~iterative_cleaner_tpu.parallel.fleet.ClaimHeartbeat`
    pattern), stopped explicitly before :meth:`leave` so nothing can
    re-grant the lease after a drain departed."""

    def __init__(self, journal, *, ttl_s: float = 15.0,
                 member_id: Optional[str] = None,
                 host: Optional[int] = None, registry=None) -> None:
        self.journal = journal
        self.ttl_s = float(ttl_s)
        self.host = int(os.getpid() if host is None else host)
        # per-incarnation identity, never inherited across restarts
        self.member_id = (str(member_id) if member_id
                          else "m%d-%s" % (self.host, os.urandom(3).hex()))
        self.registry = registry
        self._last_beat = 0.0
        self._joined = False
        self._beat_stop: Optional[threading.Event] = None
        self._beat_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # members this process has seen live — the eviction edge detector
        self._seen_live: set = set()
        self._evicted: set = set()

    # ------------------------------------------------------------ lease
    def join(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self.journal.record_member(self.member_id, "join",
                                   host=self.host, ttl_s=self.ttl_s,
                                   now=now)
        # under the same lock heartbeat() takes: an auto-beat thread
        # started early must see join's throttle stamp, not a torn pair
        with self._lock:
            self._joined = True
            self._last_beat = now
        self._seen_live.add(self.member_id)
        self._gauge(now)

    def heartbeat(self, now: Optional[float] = None) -> bool:
        """Extend this member's lease; self-throttled to ``ttl/3`` (the
        claim-heartbeat cadence) so the daemon loop and the auto-beat
        thread can both call it freely.  Returns True when a line was
        actually appended."""
        now = time.time() if now is None else now
        with self._lock:
            if (not self._joined
                    or now - self._last_beat < self.ttl_s / 3.0):
                return False
            self._last_beat = now
        self.journal.record_member(self.member_id, "hb",
                                   host=self.host, ttl_s=self.ttl_s,
                                   now=now)
        return True

    def start_auto_beat(self, registry=None) -> None:
        """Keep the membership lease alive from a background thread while
        the daemon loop is blocked executing a request inline — a busy
        member must read as live, not evictable.  Idempotent; errors
        count ``serve_heartbeat_errors`` (a missed beat only risks a
        spurious eviction, and eviction is an observation peers revisit
        on the next fold)."""
        if self._beat_thread is not None:
            return
        self._beat_stop = threading.Event()
        stop, reg = self._beat_stop, registry or self.registry

        def beat() -> None:
            while not stop.wait(self.ttl_s / 3.0):
                try:
                    self.heartbeat()
                except Exception:
                    if reg is not None:
                        reg.counter_inc("serve_heartbeat_errors")

        self._beat_thread = threading.Thread(target=beat, daemon=True,
                                             name="icln-member-hb")
        self._beat_thread.start()

    def stop_auto_beat(self) -> None:
        thread, self._beat_thread = self._beat_thread, None
        if thread is not None:
            self._beat_stop.set()
            thread.join(timeout=5.0)

    def leave(self, now: Optional[float] = None) -> None:
        """Graceful departure (drain): the roster forgets us immediately
        instead of after a ttl, so a drained member never counts as
        evicted.  Stops the auto-beat first — nothing may re-grant a
        lease the member just gave up."""
        self.stop_auto_beat()
        with self._lock:
            if not self._joined:
                return
            self._joined = False
        self.journal.record_member(self.member_id, "leave",
                                   host=self.host, ttl_s=0.0, now=now)

    # ------------------------------------------------------ maintenance
    def claim_maintenance(self, shard: int,
                          now: Optional[float] = None) -> bool:
        """Try to win the ``maint:<shard>`` lease — the segmented
        journal's background maintenance role, taken through the
        ordinary claim grammar so ANY member may grind any shard and
        two members never compact the same shard concurrently.  The
        ttl covers one compaction pass; a member that dies mid-grind
        simply lets the lease lapse and a peer takes over."""
        return self.journal.try_claim(
            "maint:%d" % int(shard), host=self.host,
            nonce=self.member_id, ttl_s=max(self.ttl_s, 30.0), now=now)

    def release_maintenance(self, shard: int,
                            now: Optional[float] = None) -> None:
        self.journal.release("maint:%d" % int(shard), host=self.host,
                             nonce=self.member_id, now=now)

    # ------------------------------------------------------------- view
    def members(self, now: Optional[float] = None) -> Dict[str, dict]:
        """The folded roster: member-id -> ``{"host", "expires", "live"}``."""
        return self.journal.member_table(now=now)

    def live_members(self, now: Optional[float] = None) -> List[str]:
        table = self.members(now=now)
        return sorted(m for m, lease in table.items() if lease["live"])

    def evict_lapsed(self, now: Optional[float] = None) -> List[str]:
        """Observe the roster and report members whose lease lapsed since
        THIS process last saw them live — each counted
        ``serve_members_evicted`` exactly once per incarnation.  Also
        keeps the ``serve_members`` gauge current.  Returns the newly
        evicted ids (the caller logs and steals their work through the
        ordinary claim-lease rules)."""
        now = time.time() if now is None else now
        table = self.members(now=now)
        evicted: List[str] = []
        for member, lease in table.items():
            if member == self.member_id:
                continue  # self-eviction is meaningless (we ARE running)
            if lease["live"]:
                self._seen_live.add(member)
                self._evicted.discard(member)
            elif member in self._seen_live and member not in self._evicted:
                self._evicted.add(member)
                evicted.append(member)
        if evicted and self.registry is not None:
            self.registry.counter_inc("serve_members_evicted", len(evicted))
        self._gauge(now, table=table)
        return evicted

    def _gauge(self, now: float, table: Optional[dict] = None) -> None:
        if self.registry is None:
            return
        if table is None:
            table = self.members(now=now)
        self.registry.gauge_set(
            "serve_members",
            float(sum(1 for lease in table.values() if lease["live"])))

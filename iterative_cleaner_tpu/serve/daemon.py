"""The long-lived cleaning service: ``python -m iterative_cleaner_tpu --serve``.

One process, alive across requests, so everything the batch CLI pays per
invocation is paid once: jax initialisation, the persistent compilation
cache handshake, and — because the AOT bucket memo and the batch builders'
caches are process-global — the compiled executables themselves.  A
repeat-geometry request on a warm daemon is served entirely from
``fleet_precompile_hits`` with zero new compile-cache entries.

Lifecycle (one request)::

    intake (spool scan / HTTP POST)          [intake fault site]
      -> admission  (ServeScheduler.submit)  -> 429/.rejected on pressure
      -> journal    "accepted" (+ full request description)
      -> scheduler  priority + earliest-deadline pop  [sched fault site]
      -> journal    "running"
      -> clean_fleet(resume=True, shared journal)  [peek/load/compile/
                                                    execute/write sites]
      -> journal    "done" | "failed"

Crash safety is the journal: a ``kill -9`` at ANY point restarts into
:meth:`ServeDaemon.recover`, which re-enqueues every request whose last
journaled state is non-terminal; the re-run goes through the fleet's
``resume`` path, so archives whose per-path 'done' entries verify are
skipped — zero duplicated cleans, byte-identical outputs.

Drain (SIGTERM/SIGINT): intake stops (HTTP 503, spool files untouched),
the in-flight request finishes and journals, queued requests stay
journaled 'accepted' for the next start, telemetry flushes, exit 0.
A second signal force-exits non-zero immediately.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import time
from typing import Dict, Optional

from iterative_cleaner_tpu.config import CleanConfig, ServeConfig
from iterative_cleaner_tpu.serve.request import (
    RequestError,
    ServeRequest,
)
from iterative_cleaner_tpu.serve.scheduler import Rejection, ServeScheduler
from iterative_cleaner_tpu.serve.spool import SpoolWatcher

FORCE_EXIT_CODE = 70  # second signal mid-drain: EX_SOFTWARE-ish, non-zero

# journal/request fields safe to echo back over GET /requests/<id>
_STATUS_FIELDS = ("state", "tenant", "priority", "deadline_ts",
                  "submitted_ts", "paths", "error", "n_cleaned",
                  "n_skipped", "n_failed", "duration_s")


def default_out_path(p: str) -> str:
    """The CLI's default output naming (``--output ""``): daemon outputs
    are bit-identical to a batch-CLI run over the same archives."""
    return p + "_cleaned" + (os.path.splitext(p)[1] or ".npz")


class ServeDaemon:
    """Composes ServeConfig + CleanConfig + scheduler + intakes + journal
    around a single-worker serve loop (device compute is serialized by
    design — one TPU, one fleet at a time; concurrency lives in the
    fleet's own IO pools)."""

    def __init__(self, serve_config: ServeConfig, base_config: CleanConfig,
                 *, registry=None, faults=None, retry=None,
                 stage_timeout_s: Optional[float] = None,
                 io_workers: Optional[int] = None,
                 quiet: bool = False) -> None:
        from iterative_cleaner_tpu.resilience import (
            FleetJournal,
            RetryPolicy,
            resolve_retries,
            resolve_stage_timeout,
        )
        from iterative_cleaner_tpu.telemetry import MetricsRegistry

        self.serve_config = serve_config
        self.base_config = base_config
        self.registry = registry if registry is not None else MetricsRegistry()
        self.faults = faults
        if self.faults is not None:
            self.faults.bind(self.registry)
        self.retry = retry if retry is not None else RetryPolicy(
            max_retries=resolve_retries(
                getattr(base_config, "fleet_retries", None)))
        self.stage_timeout_s = resolve_stage_timeout(
            stage_timeout_s if stage_timeout_s is not None
            else getattr(base_config, "stage_timeout_s", None))
        self.io_workers = io_workers
        self.quiet = quiet
        self.journal = FleetJournal(serve_config.journal_path)
        self.scheduler = ServeScheduler(
            queue_limit=serve_config.queue_limit,
            max_inflight=serve_config.max_inflight,
            registry=self.registry, faults=self.faults)
        self.spool = (SpoolWatcher(
            serve_config.spool_dir,
            on_request=lambda req, _path: self.admit(req, source="spool"),
            base_config=base_config, registry=self.registry,
            faults=self.faults)
            if serve_config.spool_dir else None)
        self._httpd = None
        self._http_thread = None
        self._signals = 0
        self._started_ts = time.time()
        self._running_id: Optional[str] = None

    # ------------------------------------------------------------- intake
    def admit(self, req: ServeRequest, source: str) -> None:
        """Admission + journal, in that order: a rejected request never
        reaches the journal (a restart must not resurrect it), and a
        crash after admission but before the journal append loses only a
        request its submitter never saw acknowledged (the HTTP 200 /
        spool ``.accepted`` rename both happen strictly after this
        returns) — so the submitter's retry is correct."""
        self.scheduler.submit(req)
        self.journal.record_request(req.request_id, "accepted",
                                    source=source, **req.journal_fields())
        self._say("serve: accepted %s (%s, tenant=%s, %d path%s)"
                  % (req.request_id, source, req.tenant, len(req.paths),
                     "" if len(req.paths) == 1 else "s"))

    def recover(self) -> int:
        """Re-enqueue every journaled request whose last state is
        non-terminal (the crash-restart path).  Returns how many."""
        from iterative_cleaner_tpu.resilience.journal import REQUEST_TERMINAL

        n = 0
        for rid, view in sorted(self.journal.request_states().items()):
            if view.get("state") in REQUEST_TERMINAL:
                continue
            try:
                req = ServeRequest.from_journal_entry(rid, view)
                self.scheduler.submit(req, already_journaled=True)
            except (RequestError, Rejection) as exc:
                # un-replayable (compacted away, corrupt, or beyond the
                # queue bound): fail it terminally rather than loop on it
                self.journal.record_request(rid, "failed",
                                            error=f"unrecoverable: {exc}")
                self.registry.counter_inc("serve_failed")
                continue
            n += 1
        if n:
            self.registry.counter_inc("serve_recovered", n)
            self._say("serve: recovered %d journaled request%s"
                      % (n, "" if n == 1 else "s"))
        return n

    # ------------------------------------------------------ observability
    def health(self) -> dict:
        snap = self.registry.snapshot()
        counters = snap.get("counters", {})
        return {
            "status": "draining" if self.scheduler.draining else "ok",
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self._started_ts, 3),
            "queued": self.scheduler.depth(),
            "running": self._running_id,
            "accepted": int(counters.get("serve_accepted", 0)),
            "completed": int(counters.get("serve_completed", 0)),
            "failed": int(counters.get("serve_failed", 0)),
            "rejected": int(counters.get("serve_rejected", 0)),
            "deadline_expired": int(
                counters.get("serve_deadline_expired", 0)),
        }

    def request_state(self, request_id: str) -> Optional[dict]:
        """The journaled lifecycle view of one request (GET
        /requests/<id>) — reading the journal means the answer survives
        restarts and never races the worker loop."""
        view = self.journal.request_states().get(request_id)
        if view is None:
            return None
        doc = {k: view[k] for k in _STATUS_FIELDS if k in view}
        doc["id"] = request_id
        return doc

    def _say(self, msg: str) -> None:
        if not self.quiet:
            print(msg, flush=True)

    # ------------------------------------------------------------ serving
    def _execute(self, req: ServeRequest) -> None:
        """Run one admitted request through the fleet.  Every archive-level
        recovery (retry ladder, OOM splits, degradation) happens inside
        clean_fleet; here a request only ends 'done' (all paths cleaned or
        journal-skipped) or 'failed' (any path failed, or the overrides/
        setup raised)."""
        from iterative_cleaner_tpu.parallel.fleet import clean_fleet
        from iterative_cleaner_tpu.resilience import ResiliencePlan

        self._running_id = req.request_id
        self.journal.record_request(req.request_id, "running")
        mark = self.registry.counters_mark()
        t0 = time.perf_counter()
        try:
            cfg = req.effective_config(self.base_config)
            plan = ResiliencePlan(
                faults=self.faults, retry=self.retry,
                stage_timeout_s=self.stage_timeout_s,
                journal=self.journal, resume=True)
            report = clean_fleet(
                req.paths, cfg, registry=self.registry,
                io_workers=self.io_workers,
                write_fn=self._write_one, resilience=plan,
                out_path_fn=default_out_path)
        except Exception as exc:  # setup/override errors, not per-archive
            dt = time.perf_counter() - t0
            self.journal.record_request(
                req.request_id, "failed",
                error=f"{type(exc).__name__}: {exc}",
                duration_s=round(dt, 6))
            self.registry.counter_inc("serve_failed")
            self.registry.histogram_observe("serve_request_s", dt)
            self._say("serve: failed %s: %s" % (req.request_id, exc))
            return
        finally:
            self._running_id = None
        dt = time.perf_counter() - t0
        delta = self.registry.counters_since(mark)
        fields = {
            "n_cleaned": len(report.results),
            "n_skipped": len(report.skipped),
            "n_failed": len(report.failures),
            "duration_s": round(dt, 6),
        }
        self.registry.histogram_observe("serve_request_s", dt)
        if report.ok:
            self.journal.record_request(req.request_id, "done", **fields)
            self.registry.counter_inc("serve_completed")
            self._say("serve: done %s (%d cleaned, %d resumed, %.2fs, "
                      "%d precompile hits)"
                      % (req.request_id, len(report.results),
                         len(report.skipped), dt,
                         int(delta.get("fleet_precompile_hits", 0))))
        else:
            stages = ", ".join("%s@%s" % (os.path.basename(p), stage)
                               for p, stage, _exc in report.failures[:4])
            self.journal.record_request(
                req.request_id, "failed",
                error=f"{len(report.failures)} archive(s) failed: {stages}",
                **fields)
            self.registry.counter_inc("serve_failed")
            self._say("serve: failed %s (%d of %d archives)"
                      % (req.request_id, len(report.failures),
                         len(req.paths)))

    def _write_one(self, path, ar, result) -> None:
        from iterative_cleaner_tpu import io as ar_io

        out = dataclasses.replace(
            ar, weights=result.final_weights.astype(ar.weights.dtype))
        ar_io.save_archive(out, default_out_path(path))

    def _fail_expired(self, expired) -> None:
        for req in expired:
            self.journal.record_request(
                req.request_id, "failed",
                error="deadline expired before scheduling")
            self.registry.counter_inc("serve_failed")
            self.scheduler.mark_done(req)
            self._say("serve: deadline expired for %s" % req.request_id)

    # -------------------------------------------------------- maintenance
    def _maintain(self) -> None:
        """Idle-time growth bounds: compact the journal and trim clean.log
        once they cross their configured sizes.  Both operations hold the
        appenders' flock, so maintenance is safe under live traffic."""
        from iterative_cleaner_tpu.utils.logging import trim_log

        cfg = self.serve_config
        try:
            jsz = os.path.getsize(self.journal.path)
        except OSError:
            jsz = 0
        if jsz > cfg.journal_max_mb * 1e6:
            if self.journal.compact():
                self.registry.counter_inc("serve_journal_compactions")
                self._say("serve: compacted journal (%d -> %d bytes)"
                          % (jsz, os.path.getsize(self.journal.path)))
        if trim_log("clean.log", int(cfg.log_max_mb * 1e6)):
            self.registry.counter_inc("serve_log_trims")

    # ------------------------------------------------------------ signals
    def _on_signal(self, signum, _frame) -> None:
        self._signals += 1
        if self._signals >= 2:
            # a stuck drain must still be killable without SIGKILL
            print("serve: second signal, forcing exit", flush=True)
            os._exit(FORCE_EXIT_CODE)
        print("serve: %s received, draining (queued requests stay "
              "journaled; signal again to force exit)"
              % signal.Signals(signum).name, flush=True)
        self.scheduler.start_drain()

    # ---------------------------------------------------------------- run
    def run(self) -> int:
        """The daemon main loop; returns the process exit code (0 for a
        clean drain)."""
        import threading

        if threading.current_thread() is threading.main_thread():
            # in-process tests drive run() from a worker thread and
            # deliver "signals" by calling _on_signal directly
            signal.signal(signal.SIGTERM, self._on_signal)
            signal.signal(signal.SIGINT, self._on_signal)
        self.recover()
        if self.serve_config.http_port is not None:
            from iterative_cleaner_tpu.serve.http import (
                make_server,
                start_server_thread,
            )

            self._httpd = make_server(self, self.serve_config.http_port)
            self._http_thread = start_server_thread(self._httpd)
            # fixed grep-able format: tests and scripts parse the port
            print("serve: http listening on 127.0.0.1:%d"
                  % self._httpd.server_address[1], flush=True)
        if self.spool is not None:
            print("serve: watching spool %s" % self.spool.spool_dir,
                  flush=True)
        print("serve: ready (journal %s, max_inflight %d, queue %d)"
              % (self.journal.path, self.serve_config.max_inflight,
                 self.serve_config.queue_limit), flush=True)
        try:
            while True:
                draining = self.scheduler.draining
                if self.spool is not None:
                    self.spool.scan_once(stop_intake=draining)
                req, expired = self.scheduler.pop(
                    timeout=self.serve_config.poll_s)
                self._fail_expired(expired)
                if self.scheduler.draining:
                    # anything just popped stays journaled 'accepted' and
                    # re-enqueues on the next start — drain only finishes
                    # work that already reached 'running'
                    break
                if req is None:
                    self._maintain()
                    continue
                try:
                    self._execute(req)
                finally:
                    self.scheduler.mark_done(req)
        finally:
            self._shutdown()
        return 0

    def _shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        queued = self.scheduler.depth()
        self.journal.compact()
        snap = self.registry.snapshot()
        print("serve: drained (%d request%s left journaled) %s"
              % (queued, "" if queued == 1 else "s",
                 json.dumps({k: v for k, v in
                             sorted(snap.get("counters", {}).items())
                             if k.startswith("serve_")},
                            sort_keys=True)),
              flush=True)


def run_serve(serve_config: ServeConfig, base_config: CleanConfig, *,
              registry=None, faults=None, io_workers=None,
              quiet: bool = False) -> int:
    """CLI entry: build and run a daemon; returns its exit code."""
    daemon = ServeDaemon(serve_config, base_config, registry=registry,
                         faults=faults, io_workers=io_workers, quiet=quiet)
    return daemon.run()

"""The long-lived cleaning service: ``python -m iterative_cleaner_tpu --serve``.

One process, alive across requests, so everything the batch CLI pays per
invocation is paid once: jax initialisation, the persistent compilation
cache handshake, and — because the AOT bucket memo and the batch builders'
caches are process-global — the compiled executables themselves.  A
repeat-geometry request on a warm daemon is served entirely from
``fleet_precompile_hits`` with zero new compile-cache entries.

Lifecycle (one request)::

    intake (spool scan / HTTP POST)          [intake fault site]
      -> admission  (ServeScheduler.submit)  -> 429/.rejected on pressure
      -> journal    "accepted" (+ full request description)
      -> scheduler  priority + earliest-deadline pop  [sched fault site]
      -> journal    "running"
      -> clean_fleet(resume=True, shared journal)  [peek/load/compile/
                                                    execute/write sites]
      -> journal    "done" | "failed"

Crash safety is the journal: a ``kill -9`` at ANY point restarts into
:meth:`ServeDaemon.recover`, which re-enqueues every request whose last
journaled state is non-terminal; the re-run goes through the fleet's
``resume`` path, so archives whose per-path 'done' entries verify are
skipped — zero duplicated cleans, byte-identical outputs.

Drain (SIGTERM/SIGINT): intake stops (HTTP 503, spool files untouched),
the in-flight request finishes and journals, queued requests stay
journaled 'accepted' for the next start, telemetry flushes, exit 0.
A second signal force-exits non-zero immediately.

**Elastic pool** (``--join``): daemons sharing one journal form a
coordinator-free pool.  Each member announces itself with journaled
membership leases (serve/membership.py), adopts journaled 'accepted'
requests from the shared fold — so ANY member may run the HTTP/spool
front door, and whichever healthy member pops a request first runs it —
and leases each request's execution through the journal's claim grammar
before running it, so two members popping the same request resolve to
exactly one winner.  A SIGKILLed member stops heartbeating: survivors
evict it (``serve_members_evicted``), steal its leased requests
(``serve_requests_stolen``, latency in ``serve_failover_s``) and the
fleet's per-archive journal entries keep the re-run exactly-once.  With
``--result-cache`` a completed request also indexes its outputs under
(input signature × config hash); an identical resubmission is answered
from the verified index with zero device work (serve/result_cache.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

from iterative_cleaner_tpu.config import CleanConfig, ServeConfig
from iterative_cleaner_tpu.serve.request import (
    RequestError,
    ServeRequest,
    request_work_key,
)
from iterative_cleaner_tpu.serve.scheduler import Rejection, ServeScheduler
from iterative_cleaner_tpu.serve.spool import SpoolWatcher

FORCE_EXIT_CODE = 70  # second signal mid-drain: EX_SOFTWARE-ish, non-zero

# journal/request fields safe to echo back over GET /requests/<id>
_STATUS_FIELDS = ("state", "tenant", "priority", "deadline_ts",
                  "submitted_ts", "paths", "error", "n_cleaned",
                  "n_skipped", "n_failed", "n_cached", "duration_s",
                  "trace_id", "kind", "chunks", "n_ingested", "closed",
                  "n_subints", "out", "mask_drift", "reconciles",
                  "recompiles_steady", "subint_p99_ms", "member")


@dataclasses.dataclass
class _StreamState:
    """One open ``kind: "stream"`` request: its in-memory session plus
    the chunk/dedup bookkeeping mirrored into the journal.  ``lock``
    serializes the HTTP intake threads per stream (chunks within one
    stream are ordered; different streams ingest concurrently)."""

    req: ServeRequest
    session: object = None          # OnlineSession, built on first chunk
    chunks: List[str] = dataclasses.field(default_factory=list)
    keys: set = dataclasses.field(default_factory=set)
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    closed: bool = False


def default_out_path(p: str) -> str:
    """The CLI's default output naming (``--output ""``): daemon outputs
    are bit-identical to a batch-CLI run over the same archives."""
    return p + "_cleaned" + (os.path.splitext(p)[1] or ".npz")


class ServeDaemon:
    """Composes ServeConfig + CleanConfig + scheduler + intakes + journal
    around a single-worker serve loop (device compute is serialized by
    design — one TPU, one fleet at a time; concurrency lives in the
    fleet's own IO pools)."""

    def __init__(self, serve_config: ServeConfig, base_config: CleanConfig,
                 *, registry=None, faults=None, retry=None,
                 stage_timeout_s: Optional[float] = None,
                 io_workers: Optional[int] = None,
                 quiet: bool = False, events=None) -> None:
        from iterative_cleaner_tpu.resilience import (
            FleetJournal,
            RetryPolicy,
            resolve_retries,
            resolve_stage_timeout,
        )
        from iterative_cleaner_tpu.telemetry import MetricsRegistry
        from iterative_cleaner_tpu.telemetry.recorder import (
            FlightRecorder,
            set_active,
        )
        from iterative_cleaner_tpu.telemetry.tracing import (
            Tracer,
            spool_path_for,
        )

        self.serve_config = serve_config
        self.base_config = base_config
        self.registry = registry if registry is not None else MetricsRegistry()
        self.faults = faults
        if self.faults is not None:
            self.faults.bind(self.registry)
        self.retry = retry if retry is not None else RetryPolicy(
            max_retries=resolve_retries(
                getattr(base_config, "fleet_retries", None)))
        self.stage_timeout_s = resolve_stage_timeout(
            stage_timeout_s if stage_timeout_s is not None
            else getattr(base_config, "stage_timeout_s", None))
        self.io_workers = io_workers
        self.quiet = quiet
        self.events = events
        self.journal = FleetJournal(
            serve_config.journal_path, registry=self.registry,
            segment_mb=getattr(serve_config, "journal_segment_mb", None))
        # the black box: always armed in a daemon (a crash with no dump
        # is the failure mode this PR exists to kill); path "" disables.
        self.recorder = (FlightRecorder(path=serve_config.flight_recorder)
                         if serve_config.flight_recorder else None)
        set_active(self.recorder)
        # spans are always recorded in-memory (bounded; they feed
        # GET /trace/<id> and the flight recorder); the spool/Perfetto
        # export only exists under --trace-out, the event-log export
        # only under an events sink.
        self.trace_out = serve_config.trace_out or None
        self.tracer = Tracer(
            host="serve",
            spool_path=(spool_path_for(self.trace_out)
                        if self.trace_out else None),
            events=events, recorder=self.recorder)
        # leaf lock for daemon-local maps written from both the HTTP
        # handler threads and the worker loop (_streams, _root_spans,
        # _pool_fold, _journal_read_ts): held only around the dict/
        # scalar op itself, NEVER across a journal or scheduler call
        self._state_lock = threading.Lock()
        self._root_spans: Dict[str, object] = {}
        # elastic pool membership (--join): None for a standalone daemon
        self.membership = None
        if serve_config.join:
            from iterative_cleaner_tpu.serve.membership import PoolMembership

            self.membership = PoolMembership(
                self.journal, ttl_s=serve_config.member_ttl_s,
                registry=self.registry)
        # content-addressed result cache (--result-cache)
        self.result_cache = None
        if serve_config.result_cache:
            from iterative_cleaner_tpu.serve.result_cache import ResultCache

            self.result_cache = ResultCache(self.journal,
                                            registry=self.registry)
        self.scheduler = ServeScheduler(
            queue_limit=serve_config.queue_limit,
            max_inflight=serve_config.max_inflight,
            registry=self.registry, faults=self.faults,
            tracer=self.tracer,
            pool_inflight=(self._pool_tenant_inflight
                           if self.membership is not None else None))
        self.spool = (SpoolWatcher(
            serve_config.spool_dir,
            on_request=lambda req, _path: self.admit(req, source="spool"),
            base_config=base_config, registry=self.registry,
            faults=self.faults)
            if serve_config.spool_dir else None)
        self._httpd = None
        self._http_thread = None
        self._signals = 0
        self._started_ts = time.time()
        self._running_id: Optional[str] = None
        # when this process last derived state from the journal fold —
        # /healthz reports the age as journal_lag_s (in a pool, the
        # liveness of the eviction/adoption scanner)
        self._journal_read_ts: Optional[float] = None
        self._last_pool_scan = 0.0
        # adoption/eviction scan cadence: a fraction of the membership
        # ttl so a lapsed member is noticed well within one lease
        self._pool_scan_s = min(1.0, serve_config.member_ttl_s / 3.0)
        # memoized request-state fold for pool admission: every submit
        # consults the pool-wide tenant view under the scheduler lock,
        # and re-reading the whole journal per request would make
        # admission latency grow with journal size.  A briefly stale
        # fold is safe — the scheduler takes max(local, pool), so the
        # local counter still bounds what the fold hasn't seen yet.
        self._pool_fold = (0.0, None)
        self._pool_fold_ttl_s = min(0.5, self._pool_scan_s)
        # the running request's execution-lease heartbeat (elastic only)
        self._exec_hb = None
        # open online streams by request id (kind: "stream"); entries
        # leave at finalize (worker pop after close) or terminal failure
        self._streams: Dict[str, _StreamState] = {}
        # stream multiplexer (--mux): all kind:"stream" requests share
        # one StreamMux — concurrent streams' subints coalesce into one
        # batched dispatch per tick instead of one launch per stream.
        # Journal/dedup/replay semantics are untouched: the mux sits
        # strictly between the (already journaled) ingest and the device
        self.mux = None
        if serve_config.mux:
            from iterative_cleaner_tpu.online.mux import StreamMux

            self.mux = StreamMux(
                max_batch=serve_config.mux_max_batch,
                max_wait_ms=serve_config.mux_max_wait_ms,
                registry=self.registry, tracer=self.tracer)
        # POST /profile serialization: jax.profiler supports one trace at
        # a time, so a second capture while one runs is a 409, not a queue
        self._profile_lock = threading.Lock()

    # ------------------------------------------------------------- intake
    def admit(self, req: ServeRequest, source: str) -> None:
        """Admission + journal, in that order: a rejected request never
        reaches the journal (a restart must not resurrect it), and a
        crash after admission but before the journal append loses only a
        request its submitter never saw acknowledged (the HTTP 200 /
        spool ``.accepted`` rename both happen strictly after this
        returns) — so the submitter's retry is correct.

        The worker queue is fed strictly AFTER the 'accepted' line lands:
        admission takes the slot without enqueueing, the journal append
        happens, then the request becomes poppable.  A result-cache hit
        finishes in microseconds, so enqueueing first would let the
        worker's 'running'/'done' lines race ahead of this thread's
        'accepted' line — and a journal whose last line says 'accepted'
        reads as unfinished forever (and adoptable by pool peers)."""
        self._open_root_span(req, source=source)
        try:
            # a stream is admitted (slot taken, backpressure counted) but
            # never queued here: the worker only runs it once it closes
            self.scheduler.submit(req, enqueue=False)
        except Rejection:
            with self._state_lock:
                self._root_spans.pop(req.request_id, None)  # never admitted
            raise
        if req.kind == "stream":
            with self._state_lock:
                self._streams[req.request_id] = _StreamState(req=req)
        extra = {}
        if self.membership is not None:
            # which member's front door accepted it — pool members use
            # this to leave a LIVE acceptor's streams alone
            extra["member"] = self.membership.member_id
        try:
            self.journal.record_request(req.request_id, "accepted",
                                        source=source, **extra,
                                        **req.journal_fields())
        except Exception:
            # the journal append failed (disk full, I/O error): the
            # request was never acknowledged, so roll the admission all
            # the way back — otherwise the tenant slot leaks forever and
            # the id stays in the known set, poisoning the submitter's
            # documented-correct retry as a 'duplicate'
            with self._state_lock:
                self._streams.pop(req.request_id, None)
            self.scheduler.mark_done(req)
            self.scheduler.forget(req.request_id)
            self._close_root_span(req, "error")
            raise
        if req.kind != "stream":
            self.scheduler.enqueue_admitted(req)
        if req.kind == "stream":
            self._say("serve: opened stream %s (%s, tenant=%s)"
                      % (req.request_id, source, req.tenant))
        else:
            self._say("serve: accepted %s (%s, tenant=%s, %d path%s)"
                      % (req.request_id, source, req.tenant, len(req.paths),
                         "" if len(req.paths) == 1 else "s"))

    def recover(self) -> int:
        """Re-enqueue every journaled request whose last state is
        non-terminal (the crash-restart path).  Returns how many.

        In a pool the journal also holds OTHER members' work: requests
        under a live member's execution lease, and streams whose
        accepting member is alive, stay theirs (the adoption scan picks
        them up later if that member lapses); everything else — a dead
        member's requests included — re-enqueues here exactly like our
        own."""
        from iterative_cleaner_tpu.resilience.journal import REQUEST_TERMINAL

        n = 0
        roster: Dict[str, dict] = {}
        claims: Dict[str, dict] = {}
        if self.membership is not None:
            now = time.time()
            roster = self.membership.members(now=now)
            claims = self.journal.claim_table(now=now)
            with self._state_lock:
                self._journal_read_ts = now
        for rid, view in sorted(self.journal.request_states().items()):
            if view.get("state") in REQUEST_TERMINAL:
                continue
            if self._owned_elsewhere(rid, view, roster, claims):
                continue
            try:
                req = ServeRequest.from_journal_entry(rid, view)
                if req.kind == "stream":
                    n += self._recover_stream(rid, req, view)
                    continue
                self._open_root_span(req, source="recover")
                self.scheduler.submit(req, already_journaled=True)
            except (RequestError, Rejection) as exc:
                # un-replayable (compacted away, corrupt, or beyond the
                # queue bound): fail it terminally rather than loop on it
                with self._state_lock:
                    self._root_spans.pop(rid, None)
                self.journal.record_request(rid, "failed",
                                            error=f"unrecoverable: {exc}")
                self.registry.counter_inc("serve_failed")
                continue
            n += 1
        if n:
            self.registry.counter_inc("serve_recovered", n)
            self._say("serve: recovered %d journaled request%s"
                      % (n, "" if n == 1 else "s"))
        return n

    # ------------------------------------------------------ elastic pool
    def _owned_elsewhere(self, rid: str, view: dict, roster: dict,
                         claims: dict) -> bool:
        """Is this journaled request another LIVE member's to run?

        A live execution lease held by a foreign nonce always wins.  A
        stream additionally belongs to its accepting member while that
        member lives (its session is in-memory there; chunks keep
        POSTing to its front door) — but a dead acceptor's stream is
        adoptable, replayed from its journaled chunk files."""
        if self.membership is None:
            return False
        owner = claims.get(request_work_key(rid))
        if (owner is not None and owner.get("live")
                and owner.get("nonce") != self.membership.member_id):
            return True
        if (view.get("kind") or "clean") == "stream":
            member = view.get("member")
            if member and member != self.membership.member_id:
                lease = roster.get(member)
                if lease is not None and lease.get("live"):
                    return True
        return False

    def _pool_tenant_inflight(self, tenant: str) -> int:
        """The scheduler's pool-wide fair-share view: how many of this
        tenant's requests are journaled non-terminal anywhere in the
        pool (every member's front door folds the same journal).  The
        fold is memoized for ``_pool_fold_ttl_s`` so a submission burst
        costs one journal read, not one per request."""
        from iterative_cleaner_tpu.resilience.journal import REQUEST_TERMINAL

        now = time.time()
        with self._state_lock:
            ts, states = self._pool_fold
        if states is None or now - ts > self._pool_fold_ttl_s:
            states = self.journal.request_states()
            with self._state_lock:
                self._pool_fold = (now, states)
                self._journal_read_ts = now
        return sum(1 for view in states.values()
                   if view.get("state") not in REQUEST_TERMINAL
                   and str(view.get("tenant") or "default") == str(tenant))

    def _elastic_tick(self) -> None:
        """One pool-maintenance pass from the daemon loop: heartbeat our
        membership lease (self-throttled), then — on the scan cadence —
        observe evictions and adopt adoptable journaled requests."""
        if self.membership is None:
            return
        now = time.time()
        self.membership.heartbeat(now=now)
        if now - self._last_pool_scan < self._pool_scan_s:
            return
        self._last_pool_scan = now
        for member in self.membership.evict_lapsed(now=now):
            self._say("serve: evicted member %s (heartbeat lapsed; "
                      "its requests are now stealable)" % member)
        self._poll_pool(now)

    def _poll_pool(self, now: float) -> None:
        """Adopt journaled 'accepted'/'running' requests this member can
        run: anything non-terminal, not already known here, and not
        another live member's (:meth:`_owned_elsewhere`).  This is both
        halves of elasticity in one scan — load sharing (a healthy
        peer's queued intake is claimed by whoever pops first) and
        failover (a dead member's leases expired, so its requests stop
        being owned elsewhere).  Hash affinity only ORDERS adoption
        (members prefer their own shard of the id space, shrinking
        claim races); any member takes any request once it is free."""
        from iterative_cleaner_tpu.parallel.distributed import shard_owner
        from iterative_cleaner_tpu.resilience.journal import REQUEST_TERMINAL

        states = self.journal.request_states()
        claims = self.journal.claim_table(now=now)
        roster = self.membership.members(now=now)
        with self._state_lock:
            self._journal_read_ts = now
        live = [m for m, lease in roster.items() if lease["live"]]
        candidates = []
        for rid, view in states.items():
            if view.get("state") in REQUEST_TERMINAL:
                continue
            if self.scheduler.knows(rid):
                continue
            if self._owned_elsewhere(rid, view, roster, claims):
                continue
            candidates.append(rid)
        candidates.sort(key=lambda rid: (
            0 if shard_owner(rid, live) == self.membership.member_id else 1,
            rid))
        for rid in candidates:
            if (states[rid].get("kind") or "clean") == "stream":
                # a stream reaching here lost its acceptor (the member
                # lease on its 'member' field lapsed — a live acceptor
                # is _owned_elsewhere): replay it from journaled chunks
                self._adopt_stream(rid, states[rid], now)
                continue
            try:
                req = ServeRequest.from_journal_entry(rid, states[rid])
                self._open_root_span(req, source="pool")
                self.scheduler.submit(req, already_journaled=True)
            except RequestError as exc:
                with self._state_lock:
                    self._root_spans.pop(rid, None)
                self.journal.record_request(rid, "failed",
                                            error=f"unrecoverable: {exc}")
                self.registry.counter_inc("serve_failed")
                continue
            except Rejection:
                # our queue is full right now; the request stays
                # journaled and the next scan (or another member) takes it
                with self._state_lock:
                    self._root_spans.pop(rid, None)
                break
            self.registry.counter_inc("serve_pool_adopted")
            self._say("serve: adopted %s from the pool" % rid)

    def _adopt_stream(self, rid: str, view: dict, now: float) -> None:
        """Adopt a dead acceptor's stream at loop time — the in-memory
        session died with its member, so replay the journaled chunks
        into a fresh one exactly like the restart path, then journal a
        'running' line re-homing the stream's ``member`` field so peers
        see the new live acceptor (and the client's re-POSTed chunks,
        re-routed to any surviving front door, dedup against the
        restored keys).  Without this, a stream whose acceptor crash-
        restarted under a fresh member id — leaving the stale lease to
        block recover() — would stay non-terminal forever.

        Two survivors scanning concurrently are serialized through the
        claim grammar: exactly one wins the adoption lease; it is
        released once the re-home line landed (ownership rides the
        member field + our live membership lease from then on)."""
        work = request_work_key(rid)
        won = self.journal.try_claim(
            work, host=self.membership.host,
            nonce=self.membership.member_id,
            ttl_s=self.serve_config.member_ttl_s, now=now,
            trace=({"trace_id": view["trace_id"]}
                   if view.get("trace_id") else None))
        if not won:
            self.registry.counter_inc("serve_claim_lost")
            return
        try:
            try:
                req = ServeRequest.from_journal_entry(rid, view)
            except RequestError as exc:
                self.journal.record_request(rid, "failed",
                                            error=f"unrecoverable: {exc}")
                self.registry.counter_inc("serve_failed")
                return
            if not self._recover_stream(rid, req, view, source="pool",
                                        fail_on_reject=False):
                return
            self.journal.record_request(rid, "running",
                                        member=self.membership.member_id)
            self.registry.counter_inc("serve_pool_adopted")
            self._say("serve: adopted stream %s from the pool" % rid)
        finally:
            try:
                # stamp with the same scan clock as the claim: a release
                # stamped behind its own claim line breaks the journal's
                # lease monotonicity (fsck flags it as replayed lines)
                self.journal.release(work, host=self.membership.host,
                                     nonce=self.membership.member_id,
                                     now=now)
            except OSError:
                pass  # an unreleased adoption lease merely expires

    def _claim_for_execute(self, req: ServeRequest) -> bool:
        """Lease this request's execution through the journal before
        running it (pool members only; streams are session-local and a
        standalone daemon is its own pool).  Returns False when another
        member holds the lease — the caller drops the request and lets
        the winner run it.  Winning a lease a LAPSED member held is a
        steal: counted, timed (``serve_failover_s`` measures now minus
        the victim's last sign of life) and re-parented under the
        originating trace exactly like stolen fleet buckets."""
        if self.membership is None or req.kind == "stream":
            return True
        work = request_work_key(req.request_id)
        now = time.time()
        prev = self.journal.claim_table(now=now).get(work)
        won = self.journal.try_claim(
            work, host=self.membership.host,
            nonce=self.membership.member_id,
            ttl_s=self.serve_config.member_ttl_s, now=now,
            trace={"trace_id": req.trace_id,
                   "span_id": req.root_span_id})
        if not won:
            self.registry.counter_inc("serve_claim_lost")
            return False
        if (prev is not None
                and prev.get("nonce") != self.membership.member_id
                and prev.get("expires", 0.0) <= now):
            from iterative_cleaner_tpu.telemetry.registry import SECONDS

            failover = max(
                now - (prev["expires"] - prev.get("ttl", 0.0)), 0.0)
            self.registry.counter_inc("serve_requests_stolen")
            self.registry.histogram_observe("serve_failover_s", failover,
                                            buckets=SECONDS)
            self.registry.gauge_set("serve_last_failover_s",
                                    round(failover, 3))
            self._say("serve: stole %s from lapsed member (%.1fs since "
                      "its last heartbeat)" % (req.request_id, failover))
        from iterative_cleaner_tpu.parallel.fleet import ClaimHeartbeat

        self._exec_hb = ClaimHeartbeat(
            self.journal, work, self.membership.host,
            self.membership.member_id, self.serve_config.member_ttl_s,
            registry=self.registry, counter="serve_heartbeat_errors")
        return True

    def _release_execute_claim(self, req: ServeRequest) -> None:
        hb, self._exec_hb = self._exec_hb, None
        if hb is not None:
            hb.stop()
        if self.membership is None or req.kind == "stream":
            return
        try:
            self.journal.release(request_work_key(req.request_id),
                                 host=self.membership.host,
                                 nonce=self.membership.member_id)
        except OSError:
            pass  # an unreleased lease merely expires

    # ------------------------------------------------------ observability
    def _open_root_span(self, req: ServeRequest, *, source: str) -> None:
        """The request's root span: intake → terminal state.  Everything
        else (queue wait, execute, every fleet stage on every host)
        parents under it via ``req.trace_id``/``req.root_span_id``."""
        root = self.tracer.start(
            "request", trace_id=req.trace_id, subsystem="serve",
            lane="serve", request_id=req.request_id, tenant=req.tenant,
            source=source, n_paths=len(req.paths))
        req.root_span_id = root.span_id
        with self._state_lock:
            self._root_spans[req.request_id] = root

    def _close_root_span(self, req: ServeRequest, status: str) -> None:
        with self._state_lock:
            root = self._root_spans.pop(req.request_id, None)
        if root is not None:
            root.end(status=status)

    def health(self) -> dict:
        """GET /healthz: one signal shared by the pool's eviction logic
        and external load balancers — liveness, drain state, this
        member's roster view and how stale its journal fold is."""
        snap = self.registry.snapshot()
        counters = snap.get("counters", {})
        draining = self.scheduler.draining
        now = time.time()
        if self.membership is not None:
            table = self.membership.members(now=now)
            members = {
                "n": sum(1 for lease in table.values() if lease["live"]),
                "self": "draining" if draining else "member",
                "id": self.membership.member_id,
                "evicted": int(counters.get("serve_members_evicted", 0)),
            }
        else:
            members = {"n": 1,
                       "self": "draining" if draining else "standalone",
                       "id": None, "evicted": 0}
        mux = None
        if self.mux is not None:
            mux = {
                "streams": len(self.mux.streams()),
                "pending": self.mux.pending(),
                "dispatches": self.mux.dispatches,
                "max_batch": self.mux.max_batch,
                "max_wait_ms": self.mux.max_wait_ms,
                "recompiles_steady": self.mux.recompiles_steady,
            }
        return {
            "status": "draining" if draining else "ok",
            "draining": draining,
            "pid": os.getpid(),
            "uptime_s": round(now - self._started_ts, 3),
            "queued": self.scheduler.depth(),
            "running": self._running_id,
            "streams": len(self._streams),
            "mux": mux,
            "members": members,
            # age of this process's last journal fold: None before the
            # first fold, else how far behind the shared state the
            # eviction/adoption scanner is running
            "journal_lag_s": (round(now - self._journal_read_ts, 3)
                              if self._journal_read_ts is not None
                              else None),
            # which JournalLog backend this pool folds over, and — for
            # the segmented one — the live sealed-segment count per
            # shard (the shape a maintenance-role stall shows up in)
            "journal_backend": self.journal.backend,
            "journal_segments": ({str(k): v for k, v in
                                  sorted(self.journal.segment_counts()
                                         .items())}
                                 if self.journal.backend == "segmented"
                                 else None),
            "accepted": int(counters.get("serve_accepted", 0)),
            "completed": int(counters.get("serve_completed", 0)),
            "failed": int(counters.get("serve_failed", 0)),
            "rejected": int(counters.get("serve_rejected", 0)),
            "deadline_expired": int(
                counters.get("serve_deadline_expired", 0)),
        }

    def request_state(self, request_id: str) -> Optional[dict]:
        """The journaled lifecycle view of one request (GET
        /requests/<id>) — reading the journal means the answer survives
        restarts and never races the worker loop."""
        view = self.journal.request_states().get(request_id)
        with self._state_lock:
            self._journal_read_ts = time.time()
        if view is None:
            return None
        doc = {k: view[k] for k in _STATUS_FIELDS if k in view}
        doc["id"] = request_id
        return doc

    def trace_view(self, trace_or_request_id: str) -> Optional[dict]:
        """GET /trace/<id>: the finished spans of one trace, accepting
        either the trace id itself or a request id (resolved through the
        journal, so it works after the in-memory request map moved on)."""
        spans = self.tracer.spans_for(trace_or_request_id)
        trace_id = trace_or_request_id
        if not spans:
            view = self.journal.request_states().get(trace_or_request_id)
            if view is None or not view.get("trace_id"):
                return None
            trace_id = str(view["trace_id"])
            spans = self.tracer.spans_for(trace_id)
        return {"trace_id": trace_id, "n_spans": len(spans), "spans": spans}

    def debug_vars(self) -> dict:
        """GET /debug/vars: one scrape with everything a live debugging
        session starts from — health, config, counters, recent spans."""
        snap = self.registry.snapshot()
        return {
            "health": self.health(),
            "serve_config": dataclasses.asdict(self.serve_config),
            "counters": snap.get("counters", {}),
            "gauges": snap.get("gauges", {}),
            "recent_spans": self.tracer.recent(50),
            "flight_recorder": getattr(self.recorder, "path", None),
            "trace_out": self.trace_out,
            "program_costs": self._program_costs(),
        }

    @staticmethod
    def _program_costs() -> dict:
        from iterative_cleaner_tpu.telemetry import profiling

        return profiling.costs_snapshot()  # already plain dicts

    def profile_capture(self, seconds: float) -> dict:
        """POST /profile: capture ``seconds`` of ``jax.profiler`` trace
        into the configured ``profile_dir`` and publish it atomically.
        Runs on the handler's own thread (ThreadingHTTPServer), so other
        scrapes keep flowing while the capture sleeps; a concurrent
        second capture is refused (jax.profiler allows one trace at a
        time), not queued."""
        from iterative_cleaner_tpu.telemetry import profiling

        if not self.serve_config.profile_dir:
            raise RequestError(
                "profiling is disabled: start the daemon with "
                "--profile-dir/ICLEAN_PROFILE_DIR to enable POST /profile")
        if not 0 < seconds <= 60:
            raise RequestError(
                f"seconds must be in (0, 60], got {seconds}")
        if not self._profile_lock.acquire(blocking=False):
            raise Rejection("profile_busy",
                            "a profile capture is already in progress")
        try:
            out_dir = profiling.capture_for(
                self.serve_config.profile_dir, seconds,
                registry=self.registry, label="on-demand")
        finally:
            self._profile_lock.release()
        self.registry.counter_inc("serve_profile_captures")
        return {"profile_dir": out_dir, "seconds": seconds}

    def quality_view(self) -> dict:
        """GET /quality: per-stream quality summaries (zap fraction,
        drift baseline, alerts) for every open online session, plus the
        registry's quality_* series.  Stream list is copied under the
        state lock; each session's summary is read without holding any
        daemon lock (QualityMonitor methods only touch its own state)."""
        with self._state_lock:
            streams = list(self._streams.items())
        per_stream = {}
        for rid, st in streams:
            sess = st.session
            mon = getattr(sess, "quality", None) if sess else None
            if mon is not None:
                per_stream[rid] = mon.summary()
        snap = self.registry.snapshot()
        series = {}
        for group in ("counters", "gauges"):
            for k, v in snap.get(group, {}).items():
                if k.startswith("quality_"):
                    series[k] = v
        return {"streams": per_stream, "series": series}

    def _say(self, msg: str) -> None:
        if not self.quiet:
            print(msg, flush=True)

    # ------------------------------------------------------------ serving
    def _execute(self, req: ServeRequest) -> None:
        """Run one admitted request through the fleet.  Every archive-level
        recovery (retry ladder, OOM splits, degradation) happens inside
        clean_fleet; here a request only ends 'done' (all paths cleaned or
        journal-skipped) or 'failed' (any path failed, or the overrides/
        setup raised)."""
        from iterative_cleaner_tpu.parallel.fleet import clean_fleet
        from iterative_cleaner_tpu.resilience import ResiliencePlan

        if req.kind == "stream":
            self._execute_stream(req)
            return
        self._running_id = req.request_id
        self.journal.record_request(req.request_id, "running")
        mark = self.registry.counters_mark()
        t0 = time.perf_counter()
        span = self.tracer.start(
            "execute", trace_id=req.trace_id,
            parent_id=req.root_span_id, subsystem="serve", lane="serve",
            request_id=req.request_id, tenant=req.tenant)
        cfg_hash = None
        try:
            cfg = req.effective_config(self.base_config)
            if self.result_cache is not None:
                from iterative_cleaner_tpu.utils.checkpoint import (
                    config_hash,
                )

                cfg_hash = config_hash(cfg)
                hits = self.result_cache.lookup(req.paths, cfg_hash)
                if hits is not None:
                    # every path's output verified against its recorded
                    # signatures: answer without touching the device —
                    # no load, no compile, no execute, no fleet spans
                    dt = time.perf_counter() - t0
                    span.set("cached", True)
                    span.set("n_cached", len(hits))
                    span.end(status="ok")
                    self.journal.record_request(
                        req.request_id, "done", n_cached=len(hits),
                        n_cleaned=0, n_skipped=0, n_failed=0,
                        duration_s=round(dt, 6))
                    self.registry.counter_inc("serve_completed")
                    self._observe_latency(req, dt)
                    self._close_root_span(req, "ok")
                    self._say("serve: done %s from result cache "
                              "(%d path%s, %.3fs, zero device work)"
                              % (req.request_id, len(hits),
                                 "" if len(hits) == 1 else "s", dt))
                    return
            plan = ResiliencePlan(
                faults=self.faults, retry=self.retry,
                stage_timeout_s=self.stage_timeout_s,
                journal=self.journal, resume=True)
            report = clean_fleet(
                req.paths, cfg, registry=self.registry,
                io_workers=self.io_workers,
                write_fn=self._write_one, resilience=plan,
                out_path_fn=default_out_path,
                tracer=self.tracer, trace=span.context())
        except Exception as exc:  # setup/override errors, not per-archive
            dt = time.perf_counter() - t0
            span.event("error", type=type(exc).__name__,
                       message=str(exc)[:200])
            span.end(status="error")
            self.journal.record_request(
                req.request_id, "failed",
                error=f"{type(exc).__name__}: {exc}",
                duration_s=round(dt, 6))
            self.registry.counter_inc("serve_failed")
            self._observe_latency(req, dt)
            self._close_root_span(req, "failed")
            self._say("serve: failed %s: %s" % (req.request_id, exc))
            return
        finally:
            self._running_id = None
        dt = time.perf_counter() - t0
        delta = self.registry.counters_since(mark)
        fields = {
            "n_cleaned": len(report.results),
            "n_skipped": len(report.skipped),
            "n_failed": len(report.failures),
            "duration_s": round(dt, 6),
        }
        span.set("n_cleaned", len(report.results))
        span.set("n_failed", len(report.failures))
        span.end(status="ok" if report.ok else "failed")
        self._observe_latency(req, dt)
        if report.ok:
            self.journal.record_request(req.request_id, "done", **fields)
            self.registry.counter_inc("serve_completed")
            if self.result_cache is not None and cfg_hash is not None:
                # index the finished outputs so an identical resubmission
                # anywhere in the pool answers with zero device work
                self.result_cache.publish(
                    req.paths, cfg_hash, out_path_fn=default_out_path,
                    trace={"trace_id": req.trace_id,
                           "span_id": req.root_span_id})
            self._close_root_span(req, "ok")
            self._say("serve: done %s (%d cleaned, %d resumed, %.2fs, "
                      "%d precompile hits)"
                      % (req.request_id, len(report.results),
                         len(report.skipped), dt,
                         int(delta.get("fleet_precompile_hits", 0))))
        else:
            stages = ", ".join("%s@%s" % (os.path.basename(p), stage)
                               for p, stage, _exc in report.failures[:4])
            self.journal.record_request(
                req.request_id, "failed",
                error=f"{len(report.failures)} archive(s) failed: {stages}",
                **fields)
            self.registry.counter_inc("serve_failed")
            self._close_root_span(req, "failed")
            self._say("serve: failed %s (%d of %d archives)"
                      % (req.request_id, len(report.failures),
                         len(req.paths)))

    # ------------------------------------------------------------ streams
    def stream_ingest(self, request_id: str, chunk_path: str,
                      seq=None) -> dict:
        """One subint chunk into an open stream (POST /stream/<id>/subint).

        Dedup key = ``seq`` (client sequence number) when given, else the
        chunk path.  A key already journaled answers ``duplicate: true``
        WITHOUT re-ingesting — so a client blindly re-POSTing after a
        daemon restart is idempotent, and the SIGKILL-resume test can
        assert zero duplicate ingests.  The journal 'running' entry
        carries the CUMULATIVE chunk list: compaction keeps one merged
        line per request, so state must never ride deltas."""
        st = self._streams.get(request_id)
        if st is None:
            raise RequestError(
                f"no open stream {request_id!r} (not opened, already "
                f"closed, or finished)")
        with st.lock:
            if st.closed:
                raise RequestError(
                    f"stream {request_id!r} is closed; no further subints")
            if self.scheduler.draining:
                raise Rejection("draining",
                                "daemon is draining; resubmit later")
            key = str(seq) if seq is not None else str(chunk_path)
            if key in st.keys:
                self.registry.counter_inc("online_duplicate_subints")
                return {"duplicate": True, "id": request_id, "seq": seq,
                        "n_ingested": len(st.chunks)}
            n = self._ingest_chunk(st, str(chunk_path))
            st.chunks.append(str(chunk_path))
            st.keys.add(key)
            # an open stream is acceptor-local while the acceptor's
            # MEMBERSHIP lease lives (peers see it as owned via the
            # 'member' field); the execution claim exists from close on
            # icln: ignore[journal-append-without-claim] -- acceptor-owned line
            self.journal.record_request(
                request_id, "running", chunks=list(st.chunks),
                keys=sorted(st.keys), n_ingested=len(st.chunks))
            return {"ingested": True, "id": request_id, "seq": seq,
                    "n_ingested": len(st.chunks), "n_subints": n}

    def stream_close(self, request_id: str) -> dict:
        """End an open stream (POST /stream/<id>/close): the request now
        queues for the worker, whose pop runs the close reconciliation
        and writes the cleaned archive.  Idempotent — a repeat close
        answers ``duplicate: true``."""
        st = self._streams.get(request_id)
        if st is None:
            raise RequestError(
                f"no open stream {request_id!r} (not opened, already "
                f"closed, or finished)")
        with st.lock:
            if st.closed:
                return {"closed": True, "duplicate": True,
                        "id": request_id, "n_ingested": len(st.chunks)}
            if not st.chunks:
                raise RequestError(
                    f"stream {request_id!r} has no ingested subints; "
                    f"POST at least one chunk before closing")
            st.closed = True
            # the close line is still the acceptor's (membership lease,
            # not execution claim): the worker claims when it pops
            # icln: ignore[journal-append-without-claim] -- acceptor-owned line
            self.journal.record_request(
                request_id, "running", closed=True,
                chunks=list(st.chunks), keys=sorted(st.keys),
                n_ingested=len(st.chunks))
        self.scheduler.enqueue_admitted(st.req)
        self._say("serve: closed stream %s (%d subints), queued for "
                  "reconcile" % (request_id, len(st.chunks)))
        return {"closed": True, "id": request_id,
                "n_ingested": len(st.chunks)}

    def _ingest_chunk(self, st: _StreamState, chunk_path: str) -> int:
        """Load one chunk file and feed it to the stream's session
        (created lazily on the first chunk, with the request's effective
        config).  IO and geometry errors become RequestError — a bad
        chunk 400s, it never kills the daemon."""
        from iterative_cleaner_tpu.online.chunks import StreamMeta, load_chunk
        from iterative_cleaner_tpu.online.session import OnlineSession

        meta = None
        if st.session is not None:
            meta = st.session.meta
        elif st.req.meta:
            meta = StreamMeta.from_dict(st.req.meta)
        try:
            data, weights, meta = load_chunk(chunk_path, meta)
        except (OSError, ValueError) as exc:
            raise RequestError(
                f"chunk {os.path.basename(chunk_path)!r}: {exc}") from exc
        if st.session is None:
            cfg = st.req.effective_config(self.base_config)
            if self.mux is not None:
                st.session = self.mux.open(
                    st.req.request_id, meta, cfg,
                    trace_id=st.req.trace_id,
                    parent_span_id=st.req.root_span_id,
                    profile=(True if self.serve_config.profile_dir
                             else None))
            else:
                st.session = OnlineSession(
                    meta, cfg, registry=self.registry, tracer=self.tracer,
                    trace_id=st.req.trace_id,
                    parent_span_id=st.req.root_span_id,
                    stream_id=st.req.request_id,
                    profile=(True if self.serve_config.profile_dir
                             else None))
        if self.mux is not None:
            # journaled ingest never drops: a full ring applies
            # backpressure (the HTTP response waits) instead of 429ing
            # a chunk the journal already recorded
            self.mux.ingest(st.req.request_id, data, weights,
                            label=os.path.basename(chunk_path), block=True)
            return st.session.n_subints + self.mux.pending(
                st.req.request_id)
        return st.session.ingest(
            data, weights, label=os.path.basename(chunk_path))

    def _stream_out_path(self, req: ServeRequest, st: _StreamState) -> str:
        """Cleaned-stream output: next to the first chunk, named by the
        request id (chunk names are per-subint, so the batch naming rule
        would label the output after one arbitrary subint)."""
        base = os.path.dirname(os.path.abspath(st.chunks[0]))
        return os.path.join(base, req.request_id + "_cleaned.npz")

    def _execute_stream(self, req: ServeRequest) -> None:
        """Finalize a closed stream: close-reconcile the session (the
        offline batch clean over the full assembled cube — bit-equal with
        batch by construction) and write the cleaned archive."""
        from iterative_cleaner_tpu import io as ar_io

        with self._state_lock:
            st = self._streams.pop(req.request_id, None)
        self._running_id = req.request_id
        self.journal.record_request(req.request_id, "running")
        t0 = time.perf_counter()
        span = self.tracer.start(
            "execute", trace_id=req.trace_id,
            parent_id=req.root_span_id, subsystem="serve", lane="serve",
            request_id=req.request_id, tenant=req.tenant, kind="stream")
        try:
            if st is None or st.session is None or not st.chunks:
                raise RequestError(
                    f"stream {req.request_id!r} reached the worker with "
                    f"no ingested subints")
            if self.mux is not None:
                # drain the stream's pending subints (partial batches
                # become due immediately) then close — the mux returns
                # the same OnlineResult the solo session would
                result = self.mux.close_stream(req.request_id)
            else:
                result = st.session.close()
            out = self._stream_out_path(req, st)
            ar_io.save_archive(result.archive, out)
        except Exception as exc:
            if self.mux is not None:
                self.mux.abandon_stream(req.request_id)
            dt = time.perf_counter() - t0
            span.event("error", type=type(exc).__name__,
                       message=str(exc)[:200])
            span.end(status="error")
            self.journal.record_request(
                req.request_id, "failed",
                error=f"{type(exc).__name__}: {exc}",
                duration_s=round(dt, 6))
            self.registry.counter_inc("serve_failed")
            self._observe_latency(req, dt)
            self._close_root_span(req, "failed")
            self._say("serve: failed stream %s: %s" % (req.request_id, exc))
            return
        finally:
            self._running_id = None
        dt = time.perf_counter() - t0
        fields = {
            "n_subints": result.n_subints,
            "out": out,
            "mask_drift": int(result.mask_drift + result.final_drift),
            "reconciles": int(result.reconciles),
            "recompiles_steady": int(result.recompiles_steady),
            "subint_p99_ms": round(result.p99_ms(), 3),
            "duration_s": round(dt, 6),
        }
        span.set("n_subints", result.n_subints)
        span.set("recompiles_steady", int(result.recompiles_steady))
        span.end(status="ok")
        self._observe_latency(req, dt)
        self.journal.record_request(req.request_id, "done", **fields)
        self.registry.counter_inc("serve_completed")
        self._close_root_span(req, "ok")
        self._say("serve: done stream %s (%d subints, %.2fs, p99 %.1fms, "
                  "%d steady recompiles)"
                  % (req.request_id, result.n_subints, dt,
                     fields["subint_p99_ms"], fields["recompiles_steady"]))

    def _recover_stream(self, rid: str, req: ServeRequest,
                        view: dict, source: str = "recover",
                        fail_on_reject: bool = True) -> int:
        """Restart path for a journaled open stream (also the pool
        adoption path, ``source="pool"``): re-admit (no queue), replay
        its journaled chunk files from disk into a fresh session —
        counted ``online_replayed_subints``, never as new ingests — and
        restore the dedup keys so a client's re-POST of an already-
        journaled subint answers ``duplicate``.  A stream journaled
        closed re-queues for the worker immediately.

        ``fail_on_reject=False`` (the adoption path) treats an admission
        Rejection as transient pressure: the stream stays journaled for
        the next scan instead of failing terminally."""
        self._open_root_span(req, source=source)
        try:
            self.scheduler.submit(req, already_journaled=True,
                                  enqueue=False)
        except Rejection as exc:
            with self._state_lock:
                self._root_spans.pop(rid, None)
            if not fail_on_reject:
                return 0
            self.journal.record_request(rid, "failed",
                                        error=f"unrecoverable: {exc}")
            self.registry.counter_inc("serve_failed")
            return 0
        st = _StreamState(req=req)
        with self._state_lock:
            self._streams[rid] = st
        chunks = [str(c) for c in (view.get("chunks") or [])]
        try:
            for chunk in chunks:
                self._ingest_chunk(st, chunk)
                st.chunks.append(chunk)
        except (RequestError, Rejection) as exc:
            with self._state_lock:
                self._streams.pop(rid, None)
            if self.mux is not None:
                self.mux.abandon_stream(rid)
            self.scheduler.mark_done(req)
            self._close_root_span(req, "failed")
            self.journal.record_request(
                rid, "failed", error=f"unrecoverable stream: {exc}")
            self.registry.counter_inc("serve_failed")
            return 0
        st.keys = set(str(k) for k in (view.get("keys") or [])) \
            or set(st.chunks)
        if self.mux is not None and st.session is not None:
            # replayed subints must be committed (not pending) before
            # the replay counter reads n_subints — and recovery may run
            # before the dispatcher thread starts
            self.mux.drain(rid)
        if st.session is not None:
            self.registry.counter_inc("online_replayed_subints",
                                      st.session.n_subints)
        if view.get("closed"):
            st.closed = True
            self.scheduler.enqueue_admitted(req)
        self._say("serve: recovered stream %s (%d chunk%s replayed%s)"
                  % (rid, len(chunks), "" if len(chunks) == 1 else "s",
                     ", closed" if st.closed else ""))
        return 1

    def request_index(self) -> dict:
        """GET /requests: every journaled request's id/state/kind/tenant
        (the journal is the source of truth, so the index survives
        restarts and includes terminal requests)."""
        states = self.journal.request_states()
        with self._state_lock:
            self._journal_read_ts = time.time()
        return {
            "n": len(states),
            "requests": [
                {"id": rid,
                 "state": view.get("state"),
                 "kind": view.get("kind") or "clean",
                 "tenant": view.get("tenant") or "default"}
                for rid, view in sorted(states.items())
            ],
        }

    def _observe_latency(self, req: ServeRequest, run_s: float) -> None:
        """The SLO signals: run duration, plus end-to-end (submit →
        terminal, queue wait included) both global and per-tenant via the
        label-suffix convention — ``serve_e2e_s{tenant=...}`` renders as
        a real Prometheus label on /metrics."""
        from iterative_cleaner_tpu.telemetry.registry import SECONDS, labeled

        e2e = max(time.time() - req.submitted_ts, 0.0)
        self.registry.histogram_observe("serve_request_s", run_s,
                                        buckets=SECONDS)
        self.registry.histogram_observe("serve_e2e_s", e2e, buckets=SECONDS)
        self.registry.histogram_observe(
            labeled("serve_e2e_s", tenant=req.tenant), e2e, buckets=SECONDS)

    def _write_one(self, path, ar, result) -> None:
        from iterative_cleaner_tpu import io as ar_io

        out = dataclasses.replace(
            ar, weights=result.final_weights.astype(ar.weights.dtype))
        ar_io.save_archive(out, default_out_path(path))

    def _fail_expired(self, expired) -> None:
        for req in expired:
            self.journal.record_request(
                req.request_id, "failed",
                error="deadline expired before scheduling")
            self.registry.counter_inc("serve_failed")
            self._close_root_span(req, "expired")
            self.scheduler.mark_done(req)
            self._say("serve: deadline expired for %s" % req.request_id)

    # -------------------------------------------------------- maintenance
    def _maintain(self) -> None:
        """Idle-time growth bounds: compact the journal, trim clean.log
        and rotate the event log once they cross their configured sizes.
        Single-file journal compaction holds the appenders' flock (safe
        under live traffic); segmented compaction touches only sealed
        segments, so it does not even contend — pool members coordinate
        per shard through ``maint:<shard>`` leases instead
        (:meth:`_maintain_segments`)."""
        from iterative_cleaner_tpu.telemetry.registry import labeled
        from iterative_cleaner_tpu.utils.logging import rotate_log, trim_log

        cfg = self.serve_config
        jsz = self.journal.size_bytes()
        self.registry.gauge_set("journal_live_bytes", float(jsz))
        seg_counts = self.journal.segment_counts()
        for shard, n in sorted(seg_counts.items()):
            self.registry.gauge_set(
                labeled("journal_segments", shard=str(shard)), float(n))
        if self.journal.backend == "segmented":
            self._maintain_segments(
                seg_counts, force=jsz > cfg.journal_max_mb * 1e6)
        elif jsz > cfg.journal_max_mb * 1e6:
            if self.journal.compact():
                self.registry.counter_inc("serve_journal_compactions")
                self._say("serve: compacted journal (%d -> %d bytes)"
                          % (jsz, self.journal.size_bytes()))
        if trim_log("clean.log", int(cfg.log_max_mb * 1e6)):
            self.registry.counter_inc("serve_log_trims")
        # the event log is append-only spans/events: unlike clean.log its
        # old lines matter (they are the trace export), so rotation keeps
        # one full previous generation (.1) instead of trimming in place
        ev_path = getattr(self.events, "path", None)
        if ev_path and rotate_log(ev_path, int(cfg.log_max_mb * 1e6)):
            self.registry.counter_inc("serve_eventlog_rotations")
            self._say("serve: rotated event log %s -> %s.1"
                      % (ev_path, ev_path))

    def _maintain_segments(self, seg_counts: Dict[int, int],
                           force: bool) -> None:
        """The segmented journal's background maintenance role: compact
        any shard with a sealed backlog (≥ 2 live segments; with
        ``force`` — live bytes over ``--journal-max-mb`` — a lone
        uncompacted segment qualifies too).  In a pool, a member only
        grinds a shard after winning its ``maint:<shard>`` lease through
        the ordinary claim grammar, so concurrent members shard the
        maintenance work instead of duplicating it; compaction itself
        touches only sealed segments, concurrent with everyone's live
        appends."""
        for shard, n in sorted(seg_counts.items()):
            if n < (1 if force else 2):
                continue
            if self.membership is not None:
                if not self.membership.claim_maintenance(shard):
                    continue  # another member holds this shard's lease
                try:
                    self._compact_one_shard(shard)
                finally:
                    self.membership.release_maintenance(shard)
            else:
                self._compact_one_shard(shard)

    def _compact_one_shard(self, shard: int) -> None:
        if self.journal.compact_shard(shard):
            self.registry.counter_inc("serve_journal_compactions")
            self._say("serve: compacted journal shard %d" % shard)

    # ------------------------------------------------------------ signals
    def _on_signal(self, signum, _frame) -> None:
        self._signals += 1
        if self._signals >= 2:
            # a stuck drain must still be killable without SIGKILL; this
            # is the one exit where atexit never runs, so the black box
            # dumps here or not at all
            if self.recorder is not None:
                self.recorder.dump("force-exit")
            print("serve: second signal, forcing exit", flush=True)
            os._exit(FORCE_EXIT_CODE)
        print("serve: %s received, draining (queued requests stay "
              "journaled; signal again to force exit)"
              % signal.Signals(signum).name, flush=True)
        self.scheduler.start_drain()

    # ---------------------------------------------------------------- run
    def run(self) -> int:
        """The daemon main loop; returns the process exit code (0 for a
        clean drain)."""
        import threading

        from iterative_cleaner_tpu.telemetry.recorder import install_sigquit

        if threading.current_thread() is threading.main_thread():
            # in-process tests drive run() from a worker thread and
            # deliver "signals" by calling _on_signal directly
            signal.signal(signal.SIGTERM, self._on_signal)
            signal.signal(signal.SIGINT, self._on_signal)
            install_sigquit()  # kill -QUIT: live black-box snapshot
        if self.membership is not None:
            self.membership.join()
            # the loop executes requests inline, so a background beat
            # keeps a busy member's lease alive (stopped by leave())
            self.membership.start_auto_beat(registry=self.registry)
            print("serve: joined pool as %s (member ttl %.1fs)"
                  % (self.membership.member_id,
                     self.serve_config.member_ttl_s), flush=True)
        if self.mux is not None:
            # dispatcher up BEFORE recovery: replayed chunks flow through
            # the same ring, and a blocked (backpressured) replay needs a
            # consumer
            self.mux.start()
            print("serve: stream mux on (max batch %d, SLO %.1fms)"
                  % (self.mux.max_batch, self.mux.max_wait_ms), flush=True)
        self.recover()
        if self.serve_config.http_port is not None:
            from iterative_cleaner_tpu.serve.http import (
                make_server,
                start_server_thread,
            )

            self._httpd = make_server(self, self.serve_config.http_port)
            self._http_thread = start_server_thread(self._httpd)
            # fixed grep-able format: tests and scripts parse the port
            print("serve: http listening on 127.0.0.1:%d"
                  % self._httpd.server_address[1], flush=True)
        if self.spool is not None:
            print("serve: watching spool %s" % self.spool.spool_dir,
                  flush=True)
        print("serve: ready (journal %s, max_inflight %d, queue %d)"
              % (self.journal.path, self.serve_config.max_inflight,
                 self.serve_config.queue_limit), flush=True)
        try:
            while True:
                draining = self.scheduler.draining
                if self.spool is not None:
                    self.spool.scan_once(stop_intake=draining)
                if not draining:
                    self._elastic_tick()
                req, expired = self.scheduler.pop(
                    timeout=self.serve_config.poll_s)
                self._fail_expired(expired)
                if self.scheduler.draining:
                    # anything just popped stays journaled 'accepted' and
                    # re-enqueues on the next start — drain only finishes
                    # work that already reached 'running'
                    break
                if req is None:
                    self._maintain()
                    continue
                if not self._claim_for_execute(req):
                    # another member leased this request first: drop it
                    # here (and forget the id so it is re-adoptable if
                    # that member dies) — the winner journals its fate
                    self.scheduler.mark_done(req)
                    self.scheduler.forget(req.request_id)
                    self._close_root_span(req, "lost")
                    self._say("serve: %s is leased by another member, "
                              "skipping" % req.request_id)
                    continue
                try:
                    self._execute(req)
                finally:
                    self._release_execute_claim(req)
                    self.scheduler.mark_done(req)
        except Exception:
            # an exception escaping the serve loop is exactly what the
            # flight recorder exists for: dump, then die loudly
            if self.recorder is not None:
                self.recorder.dump("daemon-exception")
            raise
        finally:
            self._shutdown()
        return 0

    def _shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self.mux is not None:
            # stop dispatching; open streams stay journaled and replay on
            # the next start (the same abandoned-stream contract as the
            # per-session path)
            self.mux.stop()
        if self.membership is not None:
            # leave BEFORE compacting: the roster forgets a drained
            # member immediately (never "evicted") and the compaction
            # below drops our membership lines with us
            self.membership.leave()
            self._say("serve: left pool (%s)"
                      % self.membership.member_id)
        queued = self.scheduler.depth()
        self.journal.compact()
        if self.trace_out:
            try:
                self.tracer.flush_perfetto(self.trace_out)
                self._say("serve: wrote trace %s" % self.trace_out)
            except OSError as exc:
                print("serve: trace export failed: %s" % exc, flush=True)
        snap = self.registry.snapshot()
        print("serve: drained (%d request%s left journaled) %s"
              % (queued, "" if queued == 1 else "s",
                 json.dumps({k: v for k, v in
                             sorted(snap.get("counters", {}).items())
                             if k.startswith("serve_")},
                            sort_keys=True)),
              flush=True)
        from iterative_cleaner_tpu.telemetry.recorder import (
            get_active,
            set_active,
        )

        # release the process-global black box if it is still ours: an
        # embedder outliving this daemon (the in-process tests) must not
        # have ITS later watchdog trips dumped to our recorder path
        if self.recorder is not None and get_active() is self.recorder:
            set_active(None)


def run_serve(serve_config: ServeConfig, base_config: CleanConfig, *,
              registry=None, faults=None, io_workers=None,
              quiet: bool = False, events=None) -> int:
    """CLI entry: build and run a daemon; returns its exit code."""
    daemon = ServeDaemon(serve_config, base_config, registry=registry,
                         faults=faults, io_workers=io_workers, quiet=quiet,
                         events=events)
    return daemon.run()

"""Admission control + priority/deadline scheduling for the serve daemon.

The scheduler is the daemon's backpressure boundary.  Admission
(:meth:`ServeScheduler.submit`) is synchronous and cheap — the HTTP
thread and the spool watcher both call it — and can refuse: a full global
queue or a tenant at its in-flight cap returns a
:class:`Rejection` (HTTP 429 / spool ``.rejected``) instead of queueing
unboundedly, and the ``serve_rejected`` counter records it.  Accepted
requests order by ``(priority desc, deadline asc, arrival)`` —
:func:`~iterative_cleaner_tpu.serve.request.request_key` — and a request
whose deadline passed while it queued is failed fast at pop time
(``serve_deadline_expired``), never cleaned late.

Multi-tenancy: ``max_inflight`` bounds each tenant's ADMITTED-BUT-
UNFINISHED requests (queued + running).  One greedy tenant saturates its
own cap and starts drawing 429s while other tenants' requests keep
flowing — the per-tenant fairness floor, without a full weighted-share
scheduler.

In an elastic pool the bound is POOL-wide: ``pool_inflight`` (a
callable ``tenant -> count``, backed by the shared journal's request
fold) lets admission see the tenant's unfinished requests across every
member, so a greedy tenant cannot multiply its cap by spraying
submissions at each member's front door.  A failing pool view falls
back to the local count — admission degrades to per-host fairness,
it never wedges intake.  The fold is backend-agnostic: on a segmented
journal it reads only manifest-listed live segments, so admission
latency stays flat as the journal ages (sealed history is compacted
away underneath it, concurrently with this very fold).

``kind: "stream"`` requests pass admission here (``submit`` with
``enqueue=False`` — the per-tenant cap counts an OPEN stream as one
in-flight unit for its whole lifetime) but their per-subint flow is
not this scheduler's: subints go straight to the stream's session, or
under ``--mux`` onto the shared multiplexer ring, whose bounded
capacity + latency SLO is a second, finer backpressure boundary
(:mod:`iterative_cleaner_tpu.online.mux`).  Only the close
reconciliation re-enters the queue (``enqueue_admitted``) to compete
with batch work for the single device worker.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Tuple

from iterative_cleaner_tpu.serve.request import ServeRequest, request_key


class Rejection(Exception):
    """An admission refusal: ``reason`` is one of ``queue_full``,
    ``tenant_limit``, ``draining``, ``duplicate``."""

    def __init__(self, reason: str, detail: str) -> None:
        super().__init__(detail)
        self.reason = reason
        self.detail = detail


class ServeScheduler:
    """Bounded priority/EDF queue with per-tenant admission control.

    Thread-safe; producers (HTTP handler threads, the spool watcher) call
    :meth:`submit`, the single worker loop calls :meth:`pop` /
    :meth:`mark_done`.  ``registry`` (a MetricsRegistry) receives the
    ``serve_*`` counters and queue-depth gauges."""

    def __init__(self, *, queue_limit: int, max_inflight: int,
                 registry=None, faults=None, tracer=None,
                 pool_inflight=None) -> None:
        self.queue_limit = int(queue_limit)
        self.max_inflight = int(max_inflight)
        self.registry = registry
        self.faults = faults
        self.tracer = tracer
        # elastic pools: tenant -> unfinished count across ALL members
        # (journal-backed); None keeps admission per-host
        self.pool_inflight = pool_inflight
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._heap: List[Tuple[Tuple, ServeRequest]] = []
        self._seq = 0
        # tenant -> admitted-but-unfinished count (queued + running)
        self._inflight: Dict[str, int] = {}
        self._known_ids: set = set()
        self._draining = False
        # request_id -> open queue-wait span (submit opens, pop closes)
        self._queue_spans: Dict[str, object] = {}

    # ------------------------------------------------------------ helpers
    def _count(self, name: str, n: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, n)

    def _gauges(self) -> None:
        if self.registry is not None:
            self.registry.gauge_set("serve_queue_depth", len(self._heap))
            self.registry.gauge_set(
                "serve_requests_inflight",
                float(sum(self._inflight.values())))

    def _open_queue_span(self, req: ServeRequest) -> None:
        """Start the queue-wait span at admission: its duration IS the
        request's scheduling delay, stitched under the daemon's root
        request span (``req.root_span_id``, set by the daemon's admit)."""
        if self.tracer is None:
            return
        self._queue_spans[req.request_id] = self.tracer.start(
            "queue", trace_id=req.trace_id,
            parent_id=getattr(req, "root_span_id", None),
            subsystem="sched", lane="sched",
            request_id=req.request_id, tenant=req.tenant,
            priority=req.priority)

    def _close_queue_span(self, req: ServeRequest, status: str) -> None:
        span = self._queue_spans.pop(req.request_id, None)
        if span is not None:
            span.end(status=status)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def start_drain(self) -> None:
        """Refuse all further admissions and wake any popper."""
        with self._lock:
            self._draining = True
            self._not_empty.notify_all()

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def queued_requests(self) -> List[ServeRequest]:
        """The still-queued requests (drain reporting; no pop)."""
        with self._lock:
            return [req for _k, req in sorted(self._heap)]

    # ---------------------------------------------------------- admission
    def submit(self, req: ServeRequest,
               already_journaled: bool = False,
               enqueue: bool = True) -> None:
        """Admit or raise :class:`Rejection`.  ``already_journaled``
        (restart re-enqueue) bypasses the duplicate check — the id is
        known precisely because the journal recorded it.

        ``enqueue=False`` admits WITHOUT queueing for the worker: the
        request passes every admission check and takes its tenant
        in-flight slot, but stays out of the heap.  This is the open
        phase of a stream request — it must count against backpressure
        from acceptance (an open stream is real admitted work), yet the
        single worker only runs it at close (:meth:`enqueue_admitted`)."""
        with self._lock:
            if self._draining:
                self._count("serve_rejected")
                raise Rejection("draining",
                                "daemon is draining; resubmit later")
            if not already_journaled and req.request_id in self._known_ids:
                self._count("serve_rejected")
                raise Rejection(
                    "duplicate",
                    f"request id {req.request_id!r} already admitted")
            if len(self._heap) >= self.queue_limit:
                self._count("serve_rejected")
                raise Rejection(
                    "queue_full",
                    f"queue at its bound ({self.queue_limit}); backpressure")
            inflight = self._inflight.get(req.tenant, 0)
            # the admission check may see a larger POOL-wide count, but
            # the stored counter stays strictly local: it only ever
            # decrements on local mark_done, so folding pool work into
            # it would inflate it permanently (spurious tenant_limit
            # 429s long after the pool went idle)
            effective = inflight
            if self.pool_inflight is not None and not already_journaled:
                # fair-share across the POOL: the journal sees every
                # member's unfinished requests; take the larger of the
                # two views (the local one includes admitted-but-not-
                # yet-journaled work the fold can't see yet)
                try:
                    effective = max(inflight,
                                    int(self.pool_inflight(req.tenant)))
                except Exception:
                    # a torn journal read must not wedge admission:
                    # degrade to the per-host view
                    self._count("serve_pool_view_errors")
            if effective >= self.max_inflight:
                self._count("serve_rejected")
                raise Rejection(
                    "tenant_limit",
                    f"tenant {req.tenant!r} at its in-flight cap "
                    f"({self.max_inflight})")
            self._known_ids.add(req.request_id)
            self._inflight[req.tenant] = inflight + 1
            self._count("serve_accepted")
            if enqueue:
                self._seq += 1
                heapq.heappush(self._heap,
                               (request_key(req, self._seq), req))
                self._open_queue_span(req)
                self._not_empty.notify()
            self._gauges()

    def enqueue_admitted(self, req: ServeRequest) -> None:
        """Queue a request previously admitted with ``enqueue=False`` (a
        stream reaching close).  No admission re-checks and no second
        accounting: the slot was taken at open."""
        with self._lock:
            self._seq += 1
            heapq.heappush(self._heap, (request_key(req, self._seq), req))
            self._open_queue_span(req)
            self._gauges()
            self._not_empty.notify()

    # ------------------------------------------------------------ serving
    def pop(self, timeout: Optional[float] = None
            ) -> Tuple[Optional[ServeRequest], List[ServeRequest]]:
        """Next request to run, blocking up to ``timeout`` seconds.

        Returns ``(request | None, expired)``: ``expired`` are requests
        whose deadline passed while queued — already charged
        (``serve_deadline_expired``) and removed; the caller journals them
        failed.  ``None`` request means timeout or drain with an empty
        queue.  The ``sched`` fault site fires here: an injected
        scheduler fault surfaces as a normal empty pop plus a
        ``serve_retries`` count — the daemon's loop simply comes back."""
        expired: List[ServeRequest] = []
        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            while True:
                if self.faults is not None:
                    try:
                        self.faults.fire("sched")
                    except Exception:
                        # a faulty scheduler pass never wedges or kills the
                        # daemon: charge a retry, hand back to the loop
                        self._count("serve_retries")
                        return None, expired
                now = time.time()
                while self._heap:
                    key, req = self._heap[0]
                    if req.expired(now):
                        heapq.heappop(self._heap)
                        self._count("serve_deadline_expired")
                        self._close_queue_span(req, "expired")
                        expired.append(req)
                        continue
                    break
                if self._heap:
                    _key, req = heapq.heappop(self._heap)
                    self._close_queue_span(req, "ok")
                    self._gauges()
                    return req, expired
                if self._draining:
                    return None, expired
                remaining = (None if deadline is None
                             else deadline - time.time())
                if remaining is not None and remaining <= 0:
                    return None, expired
                self._not_empty.wait(remaining)

    def mark_done(self, req: ServeRequest) -> None:
        """Release the tenant's in-flight slot (done, failed or expired —
        every admitted request must be marked exactly once)."""
        with self._lock:
            n = self._inflight.get(req.tenant, 0)
            if n <= 1:
                self._inflight.pop(req.tenant, None)
            else:
                self._inflight[req.tenant] = n - 1
            self._gauges()

    # ------------------------------------------------------ elastic pool
    def knows(self, request_id: str) -> bool:
        """Has this scheduler ever admitted ``request_id``?  The pool
        adoption scan uses this to skip requests already queued, running
        or finished HERE (the journal says what finished anywhere)."""
        with self._lock:
            return request_id in self._known_ids

    def forget(self, request_id: str) -> None:
        """Drop a request id from the admitted set — the claim-lost
        path: another member won the execution lease, so THIS member
        must be able to re-adopt the id later if that member dies
        (``already_journaled`` re-admission would also bypass the
        duplicate check, but a forgotten id keeps the set's size honest
        in a long-lived pool)."""
        with self._lock:
            self._known_ids.discard(request_id)

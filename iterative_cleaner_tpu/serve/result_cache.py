"""Content-addressed result cache over the fleet journal.

The journal already carries the cleaner's resume identity: an archive's
input ``file_signature`` and the mask-identity ``config_hash``.  The
result cache indexes completed outputs under exactly that pair
(``event: "cache"`` lines, :meth:`FleetJournal.record_cache`), so a
repeat submission of the same archive under the same config
short-circuits to the recorded cleaned output with zero device work —
no load, no compile, no execute.

Trust ladder (the PR 5 degradation pattern — verify, then fall back):
an index entry is a CLAIM, not proof.  Before serving from cache the
lookup re-verifies, per path,

1. the entry was recorded for THIS path (a ``cp -p`` copy or hardlink
   of a cleaned input carries the same signature, but its output lives
   next to the ORIGINAL path — a cross-path "hit" would answer done
   without materializing this path's output; it misses instead),
2. the input still matches the recorded signature (the key embeds it,
   and :func:`entry_is_current` re-checks — a rewritten input misses),
3. the recorded output still exists,
4. the output still matches its recorded signature (a truncated or
   hand-edited output is a corruption, not a hit).

Any rung failing counts ``serve_cache_rejected`` and the request falls
through to a real clean — a broken cache can cost time, never
correctness.  A request is served from cache only when EVERY path
verifies (all-or-nothing): partial hits run the fleet, whose journaled
resume skips the already-done archives anyway.

The index fold (:meth:`FleetJournal.cache_index`) is backend-agnostic:
cache lines hash to one shard of a segmented journal by their cache
key, so compaction retires superseded entries per shard without the
cache ever seeing a torn index.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from iterative_cleaner_tpu.resilience.journal import entry_is_current


class ResultCache:
    """Read/write view of the journal's cache index for one daemon."""

    def __init__(self, journal, registry=None) -> None:
        self.journal = journal
        self.registry = registry

    def _count(self, name: str, n: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, n)

    def lookup(self, paths: List[str],
               config_hash: str) -> Optional[Dict[str, dict]]:
        """path -> verified cache entry for EVERY path, or None.

        None means "run the real clean": either some path has no index
        entry (a plain miss, ``serve_cache_misses``) or an entry failed
        signature verification (``serve_cache_rejected`` — the
        corruption counter the chaos drill asserts on)."""
        from iterative_cleaner_tpu.utils.checkpoint import file_signature

        index = self.journal.cache_index()
        hits: Dict[str, dict] = {}
        for p in paths:
            try:
                sig = file_signature(p)
            except OSError:
                self._count("serve_cache_misses")
                return None  # unreadable input: let the fleet report it
            entry = index.get(self.journal.cache_key(sig, config_hash))
            if entry is None:
                self._count("serve_cache_misses")
                return None
            if entry.get("path") != os.path.abspath(p):
                # same content, different path (a cp -p copy or hardlink
                # of a cleaned input): the recorded output belongs to the
                # ORIGINAL path — serving it would journal this request
                # done without ever materializing THIS path's output.
                # A plain miss: the real clean writes the right file.
                self._count("serve_cache_misses")
                return None
            if not entry.get("out") or not entry_is_current(entry):
                # indexed but no longer trustworthy: input rewritten,
                # output missing, or output signature drifted
                self._count("serve_cache_rejected")
                return None
            hits[p] = entry
        self._count("serve_cache_hits", len(hits))
        return hits

    def publish(self, paths: List[str], config_hash: str, *,
                out_path_fn, trace: Optional[dict] = None) -> int:
        """Index every path whose output landed (called after a request
        finished ok).  Signatures are taken now — after the atomic
        output writes — so an entry existing implies the output was
        whole when indexed.  A path whose files moved underneath us is
        skipped (``serve_cache_publish_errors``), never fatal: the cache
        is an accelerator, not a ledger."""
        n = 0
        for p in paths:
            out = out_path_fn(p)
            try:
                if not os.path.exists(out):
                    raise OSError(f"output missing: {out}")
                self.journal.record_cache(p, config_hash=config_hash,
                                          out_path=out, trace=trace)
                n += 1
            except OSError:
                self._count("serve_cache_publish_errors")
        return n

"""Minimal HTTP/JSON intake + live observability (stdlib only).

``http.server`` from the standard library — no new dependencies — bound
to localhost: this is the pod-/host-local control surface (a fronting
proxy owns TLS/authn, exactly like node_exporter's model).  Endpoints::

    POST /submit        JSON request body -> 200 {"accepted": true, ...}
                        429 on backpressure (queue full / tenant cap),
                        503 while draining, 400 malformed
    POST /stream/<id>/subint  {"path": "/data/chunk0.npy", "seq": 0}
                        -> 200 {"ingested": true} | {"duplicate": true};
                        404 unknown stream, 400 bad chunk.  Under --mux
                        the subint lands on the shared multiplexer ring
                        (a full ring backpressures the response instead
                        of dropping a journaled chunk) and is batched
                        with other live streams' subints into one
                        device dispatch
    POST /stream/<id>/close   -> 200 {"closed": true}; the stream queues
                        for close reconciliation + output write (under
                        --mux the worker drains the stream's pending
                        ring entries first)
    GET  /healthz       200 {"status": "ok" | "draining", ...counts;
                        "mux": {streams, pending, dispatches, ...} when
                        --mux is on, else null}
    GET  /requests      200 {"n": ..., "requests": [{id, state, kind,
                        tenant}, ...]} — the journaled request index
    GET  /requests/<id> 200 {"state": ...} from the journaled lifecycle
    GET  /metrics       Prometheus text exposition of the LIVE registry
                        (the PR 1 exporter, served instead of
                        textfile-only)
    GET  /trace/<id>    200 {"trace_id", "spans": [...]} — the finished
                        spans of one trace, by trace id OR request id
                        (the daemon's bounded in-memory span store; no
                        --trace-out required)
    GET  /debug/vars    200 one-scrape debugging state: health, config,
                        counters, program costs, the most recent spans
    GET  /quality       200 {"streams": {id: quality summary}, "series":
                        {...}} — per-stream zap/drift state for every
                        open online session plus the registry's
                        quality_* series
    POST /profile?seconds=N  capture N seconds (default 1, max 60) of
                        jax.profiler trace into the daemon's
                        --profile-dir; 200 {"profile_dir": ...}, 400
                        without --profile-dir or bad N, 409 while a
                        capture is already running

The server runs on daemon threads (`ThreadingHTTPServer`): submissions
land in the scheduler under its own lock, so the single worker loop never
blocks intake and vice versa.  The ``intake`` fault site fires per
/submit: an injected transient returns a 503 with ``Retry-After`` — the
client's retry is the recovery path, and the daemon never wedges.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from iterative_cleaner_tpu.serve.request import RequestError, parse_request
from iterative_cleaner_tpu.serve.scheduler import Rejection

MAX_BODY_BYTES = 1 << 20  # a request is paths + knobs, never data

_REJECTION_STATUS = {
    "queue_full": 429,
    "tenant_limit": 429,
    "duplicate": 409,
    "draining": 503,
    # one jax.profiler trace at a time: a concurrent capture conflicts
    # rather than queueing (the client retries after the first finishes)
    "profile_busy": 409,
}


class _Handler(BaseHTTPRequestHandler):
    """One request class per daemon (built by :func:`make_server`); the
    daemon object rides on the server instance."""

    server_version = "icln-serve/1"
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------ plumbing
    def log_message(self, fmt, *args):  # stdout belongs to the daemon
        pass

    def _send(self, status: int, body: bytes, ctype: str,
              extra_headers=()) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _send_json(self, status: int, doc: dict, extra_headers=()) -> None:
        body = (json.dumps(doc, sort_keys=True) + "\n").encode()
        self._send(status, body, "application/json", extra_headers)

    # ------------------------------------------------------------- routes
    def do_GET(self):  # noqa: N802 (stdlib handler naming)
        daemon = self.server.daemon
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/healthz":
            self._send_json(200, daemon.health())
        elif path == "/metrics":
            from iterative_cleaner_tpu.telemetry import (
                metrics_to_prometheus,
            )

            text = metrics_to_prometheus(daemon.registry.snapshot())
            self._send(200, text.encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/requests":
            self._send_json(200, daemon.request_index())
        elif path.startswith("/requests/"):
            rid = path[len("/requests/"):]
            state = daemon.request_state(rid)
            if state is None:
                self._send_json(404, {"error": f"unknown request {rid!r}"})
            else:
                self._send_json(200, state)
        elif path.startswith("/trace/"):
            tid = path[len("/trace/"):]
            view = daemon.trace_view(tid)
            if view is None:
                self._send_json(404, {"error": f"unknown trace {tid!r}"})
            else:
                self._send_json(200, view)
        elif path == "/debug/vars":
            self._send_json(200, daemon.debug_vars())
        elif path == "/quality":
            self._send_json(200, daemon.quality_view())
        else:
            self._send_json(404, {"error": f"no route {path!r}"})

    def do_POST(self):  # noqa: N802
        daemon = self.server.daemon
        path = self.path.split("?", 1)[0].rstrip("/")
        if path.startswith("/stream/"):
            self._post_stream(daemon, path)
            return
        if path == "/profile":
            self._post_profile(daemon)
            return
        if path != "/submit":
            self._send_json(404, {"error": f"no route {path!r}"})
            return
        if daemon.faults is not None:
            try:
                daemon.faults.fire("intake", detail="http")
            except Exception:
                # transient intake fault: the client retries; the daemon
                # keeps serving
                daemon.registry.counter_inc("serve_retries")
                self._send_json(503, {"error": "transient intake fault; "
                                               "retry"},
                                extra_headers=(("Retry-After", "1"),))
                return
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = -1
        if not 0 < length <= MAX_BODY_BYTES:
            self._send_json(400, {"error": "Content-Length required and "
                                           "<= %d" % MAX_BODY_BYTES})
            return
        body = self.rfile.read(length)
        try:
            req = parse_request(body, base_config=daemon.base_config)
        except RequestError as exc:
            self._send_json(400, {"error": str(exc)})
            return
        try:
            daemon.admit(req, source="http")
        except Rejection as exc:
            status = _REJECTION_STATUS.get(exc.reason, 429)
            headers = (("Retry-After", "1"),) if status in (429, 503) else ()
            self._send_json(status, {"rejected": True, "reason": exc.reason,
                                     "error": exc.detail},
                            extra_headers=headers)
            return
        self._send_json(200, {"accepted": True, "id": req.request_id,
                              "tenant": req.tenant})

    def _post_profile(self, daemon) -> None:
        """POST /profile?seconds=N — on-demand jax.profiler capture.
        The duration rides the query string (the dispatch above discards
        it from ``path``, so it is re-parsed here); the capture blocks
        THIS handler thread only — ThreadingHTTPServer keeps /metrics
        and the stream endpoints live for the duration."""
        from urllib.parse import parse_qs, urlsplit

        query = parse_qs(urlsplit(self.path).query)
        raw = query.get("seconds", ["1"])[-1]
        try:
            seconds = float(raw)
        except ValueError:
            self._send_json(400, {"error": f"seconds must be a number, "
                                           f"got {raw!r}"})
            return
        try:
            self._send_json(200, daemon.profile_capture(seconds))
        except RequestError as exc:
            self._send_json(400, {"error": str(exc)})
        except Rejection as exc:
            status = _REJECTION_STATUS.get(exc.reason, 429)
            self._send_json(status, {"rejected": True, "reason": exc.reason,
                                     "error": exc.detail})

    def _post_stream(self, daemon, path: str) -> None:
        """POST /stream/<id>/subint and /stream/<id>/close — the online
        ingest surface.  Chunk DATA never crosses HTTP: the body names a
        file ('path') the daemon reads itself, keeping the intake within
        MAX_BODY_BYTES and the data path zero-copy on the host."""
        parts = path.split("/")  # ["", "stream", "<id>", "<verb>"]
        if len(parts) != 4 or not parts[2] \
                or parts[3] not in ("subint", "close"):
            self._send_json(404, {"error": f"no route {path!r}"})
            return
        rid, verb = parts[2], parts[3]
        doc = {}
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = -1
        if length < 0 or length > MAX_BODY_BYTES:
            self._send_json(400, {"error": "Content-Length required and "
                                           "<= %d" % MAX_BODY_BYTES})
            return
        if length:
            try:
                doc = json.loads(self.rfile.read(length).decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as exc:
                self._send_json(400, {"error": f"body is not JSON: {exc}"})
                return
            if not isinstance(doc, dict):
                self._send_json(400, {"error": "body must be a JSON "
                                               "object"})
                return
        try:
            if verb == "close":
                self._send_json(200, daemon.stream_close(rid))
                return
            chunk = doc.get("path")
            if not isinstance(chunk, str) or not chunk:
                self._send_json(400, {"error": "'path' (chunk file path "
                                               "string) is required"})
                return
            seq = doc.get("seq")
            if seq is not None:
                try:
                    seq = int(seq)
                except (TypeError, ValueError):
                    self._send_json(400, {"error": "'seq' must be an "
                                                   "integer"})
                    return
            self._send_json(200, daemon.stream_ingest(rid, chunk, seq=seq))
        except RequestError as exc:
            status = 404 if "no open stream" in str(exc) else 400
            self._send_json(status, {"error": str(exc)})
        except Rejection as exc:
            status = _REJECTION_STATUS.get(exc.reason, 429)
            headers = (("Retry-After", "1"),) if status in (429, 503) else ()
            self._send_json(status, {"rejected": True, "reason": exc.reason,
                                     "error": exc.detail},
                            extra_headers=headers)


def make_server(daemon, port: int,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral) and return the server with ``daemon``
    attached; the caller starts ``serve_forever`` on a thread and reads
    ``server.server_address`` for the actual port."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.daemon = daemon
    return server


def start_server_thread(server) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.1},
                         name="icln-serve-http", daemon=True)
    t.start()
    return t

"""The serve daemon's request model and JSON wire format.

One request = one journaled unit of work: a list of archive paths plus
per-request cleaning overrides, a tenant, a priority and an optional
deadline.  Requests arrive as JSON objects — a spool file's content or an
HTTP POST body::

    {"paths": ["/data/a.npz", "/data/b.npz"],
     "tenant": "survey-A",            # optional, default "default"
     "priority": 5,                   # optional, higher serves sooner
     "deadline_s": 120.0,             # optional, relative to acceptance
     "overrides": {"max_iter": 3},    # optional CleanConfig overrides
     "trace": "req-7f3a"}             # optional client trace id (minted
                                      # at intake when absent)

A second request kind serves live streams (the online/ subsystem)::

    {"kind": "stream", "id": "obs-42",
     "meta": {...StreamMeta.to_dict()...}}  # needed for bare .npy chunks

A stream opens with no paths; per-subint chunk files arrive through
``POST /stream/<id>/subint`` and ``POST /stream/<id>/close`` ends it.

``overrides`` may only name whitelisted :class:`CleanConfig` fields — the
mask-relevant per-request knobs.  Output/IO/resilience knobs stay
daemon-level: a request must not redirect outputs or disable the journal.
Every parse failure raises :class:`RequestError` with a message fit for a
400 response or a spool ``.rejected`` marker — a malformed submission
must never take the daemon down.
"""

from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Dict, List, Optional, Tuple

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.telemetry.tracing import new_trace_id, valid_trace_id

# CleanConfig fields a request may override: the per-request cleaning
# semantics, nothing that changes where outputs land or how the daemon
# survives.  (backend is included: a tenant may ask for the numpy oracle.)
OVERRIDABLE = (
    "chanthresh", "subintthresh", "max_iter", "pulse_region",
    "bad_chan", "bad_subint", "backend", "rotation", "fft_mode",
    "median_impl", "stats_impl", "stats_frame", "baseline_mode",
    "stream_reconcile_every", "stream_ew_alpha",
)

# request kinds: a batch "clean" (paths known up front) or an online
# "stream" (kind: "stream"; subints arrive via POST /stream/<id>/subint
# and the payload grows until /close)
KINDS = ("clean", "stream")


class RequestError(ValueError):
    """A submission that cannot become a request (HTTP 400 material)."""


@dataclasses.dataclass
class ServeRequest:
    """One admitted unit of work; ``deadline_ts`` is absolute (unix
    seconds) so it survives the journal round trip unchanged."""

    request_id: str
    paths: List[str]
    # "clean" (batch, the default) or "stream" (online/: paths start
    # empty and chunk files accumulate through the stream endpoints)
    kind: str = "clean"
    # stream metadata (online/chunks.py StreamMeta.to_dict()) for bare
    # .npy chunks; empty for "clean" requests and archive-container chunks
    meta: Dict[str, object] = dataclasses.field(default_factory=dict)
    tenant: str = "default"
    priority: int = 0
    deadline_ts: Optional[float] = None
    overrides: Dict[str, object] = dataclasses.field(default_factory=dict)
    submitted_ts: float = dataclasses.field(default_factory=time.time)
    # distributed-tracing root for this request: minted at intake unless
    # the client supplied one ('trace' wire field) — every span the
    # request generates, on any host, carries this id.
    trace_id: str = dataclasses.field(default_factory=new_trace_id)
    # process-local: the daemon's root request span id, set at admission
    # so child spans (queue wait, execute) parent under it.  Never
    # journaled — a restarted daemon opens a fresh root span.
    root_span_id: Optional[str] = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_ts is None:
            return False
        return (time.time() if now is None else now) >= self.deadline_ts

    def effective_config(self, base: CleanConfig) -> CleanConfig:
        """The request's cleaning config: daemon base + overrides.  The
        CleanConfig validators run here, so an override combination the
        config rejects fails the REQUEST, not the daemon."""
        if not self.overrides:
            return base
        try:
            return dataclasses.replace(base, **self.overrides)
        except (TypeError, ValueError) as exc:
            raise RequestError(f"invalid overrides: {exc}") from exc

    def journal_fields(self) -> dict:
        """What the 'accepted' journal entry records — everything needed
        to re-run this request after a daemon restart."""
        return {
            "paths": list(self.paths),
            "kind": self.kind,
            "meta": dict(self.meta),
            "tenant": self.tenant,
            "priority": self.priority,
            "deadline_ts": self.deadline_ts,
            "overrides": dict(self.overrides),
            "submitted_ts": self.submitted_ts,
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_journal_entry(cls, request_id: str,
                           entry: dict) -> "ServeRequest":
        """Rebuild a request from its merged journal lifecycle view (the
        restart path).  Overrides re-validate: a journal edited into an
        invalid state raises :class:`RequestError` and the daemon fails
        that request instead of crashing."""
        kind = str(entry.get("kind") or "clean")
        if kind not in KINDS:
            raise RequestError(
                f"journaled request {request_id!r} has unknown kind "
                f"{kind!r}")
        paths = entry.get("paths")
        if paths is None and kind == "stream":
            paths = []  # a stream's paths are its journaled chunks
        if not isinstance(paths, list) or (not paths and kind != "stream"):
            raise RequestError(
                f"journaled request {request_id!r} carries no paths "
                f"(compacted away or foreign entry)")
        overrides = entry.get("overrides") or {}
        _check_overrides(overrides)
        meta = entry.get("meta") or {}
        if not isinstance(meta, dict):
            raise RequestError(
                f"journaled request {request_id!r} has non-object meta")
        return cls(
            request_id=request_id,
            paths=[str(p) for p in paths],
            kind=kind,
            meta=meta,
            tenant=str(entry.get("tenant") or "default"),
            priority=int(entry.get("priority") or 0),
            deadline_ts=(float(entry["deadline_ts"])
                         if entry.get("deadline_ts") is not None else None),
            overrides=overrides,
            submitted_ts=float(entry.get("submitted_ts") or time.time()),
            # a pre-tracing journal has no trace_id: mint one so the
            # recovered re-run still traces end to end
            trace_id=(str(entry["trace_id"]) if entry.get("trace_id")
                      else new_trace_id()),
        )


def _check_overrides(overrides: dict) -> dict:
    if not isinstance(overrides, dict):
        raise RequestError("'overrides' must be a JSON object")
    bad = sorted(set(overrides) - set(OVERRIDABLE))
    if bad:
        raise RequestError(
            f"overrides {', '.join(bad)} are not request-overridable; "
            f"allowed: {', '.join(OVERRIDABLE)}")
    # pulse_region arrives as a JSON list; CleanConfig stores a tuple
    if "pulse_region" in overrides:
        try:
            overrides["pulse_region"] = tuple(
                float(v) for v in overrides["pulse_region"])
        except (TypeError, ValueError):
            raise RequestError("pulse_region must be three numbers")
    return overrides


def parse_request(payload, *, request_id: Optional[str] = None,
                  base_config: Optional[CleanConfig] = None,
                  now: Optional[float] = None) -> ServeRequest:
    """JSON text/bytes/dict -> validated :class:`ServeRequest`.

    ``request_id`` (e.g. a spool file's stem) wins over a payload ``id``;
    absent both, a fresh uuid suffix is minted.  With ``base_config`` the
    overrides are validated against the real CleanConfig constructors at
    parse time — rejection happens at intake, not mid-clean."""
    if isinstance(payload, (bytes, bytearray)):
        try:
            payload = payload.decode("utf-8")
        except UnicodeDecodeError as exc:
            raise RequestError(f"request body is not UTF-8: {exc}") from exc
    if isinstance(payload, str):
        try:
            payload = json.loads(payload)
        except ValueError as exc:
            raise RequestError(f"request body is not JSON: {exc}") from exc
    if not isinstance(payload, dict):
        raise RequestError("request must be a JSON object")

    kind = payload.get("kind", "clean")
    if kind not in KINDS:
        raise RequestError(
            f"'kind' must be one of {', '.join(KINDS)}, got {kind!r}")

    paths = payload.get("paths")
    if isinstance(paths, str):
        paths = [paths]
    if kind == "stream":
        # a stream opens empty: chunk paths arrive via the stream
        # endpoints, never in the opening submission
        if paths:
            raise RequestError(
                "a stream request opens with no 'paths'; POST chunks to "
                "/stream/<id>/subint instead")
        paths = []
    elif not isinstance(paths, list) or not paths \
            or not all(isinstance(p, str) and p for p in paths):
        raise RequestError("'paths' must be a non-empty list of archive "
                           "path strings")

    meta = payload.get("meta") or {}
    if not isinstance(meta, dict):
        raise RequestError("'meta' must be a JSON object")
    if meta and kind != "stream":
        raise RequestError("'meta' only applies to stream requests")
    if meta:
        from iterative_cleaner_tpu.online.chunks import StreamMeta

        try:
            StreamMeta.from_dict(meta)  # validate at intake, not mid-ingest
        except ValueError as exc:
            raise RequestError(str(exc)) from None

    rid = request_id or payload.get("id") or uuid.uuid4().hex[:12]
    rid = str(rid)
    if not rid or len(rid) > 128 or any(c in rid for c in "\n\r/\\"):
        raise RequestError(f"invalid request id {rid!r}")

    try:
        priority = int(payload.get("priority", 0))
    except (TypeError, ValueError):
        raise RequestError("'priority' must be an integer")

    tenant = payload.get("tenant", "default")
    if not isinstance(tenant, str) or not tenant:
        raise RequestError("'tenant' must be a non-empty string")

    deadline_ts = None
    if payload.get("deadline_s") is not None:
        try:
            deadline_s = float(payload["deadline_s"])
        except (TypeError, ValueError):
            raise RequestError("'deadline_s' must be a number of seconds")
        if deadline_s <= 0:
            raise RequestError("'deadline_s' must be > 0")
        deadline_ts = (time.time() if now is None else now) + deadline_s

    overrides = _check_overrides(payload.get("overrides") or {})

    trace_id = payload.get("trace")
    if trace_id is not None and not valid_trace_id(trace_id):
        raise RequestError("'trace' must be a short alphanumeric trace id")

    known = {"paths", "id", "priority", "tenant", "deadline_s", "overrides",
             "trace", "kind", "meta"}
    unknown = sorted(set(payload) - known)
    if unknown:
        raise RequestError(f"unknown request fields: {', '.join(unknown)}")

    req = ServeRequest(request_id=rid, paths=list(paths), kind=kind,
                       meta=dict(meta), tenant=tenant,
                       priority=priority, deadline_ts=deadline_ts,
                       overrides=overrides,
                       trace_id=(str(trace_id) if trace_id
                                 else new_trace_id()))
    if base_config is not None:
        req.effective_config(base_config)  # validate now, reject at intake
    return req


def request_key(req: ServeRequest, seq: int) -> Tuple:
    """The scheduler's heap key: higher priority first, then earliest
    deadline, then submission order — a total order, so scheduling is
    deterministic for a given intake sequence."""
    deadline = req.deadline_ts if req.deadline_ts is not None else float("inf")
    return (-req.priority, deadline, seq)


def request_work_key(request_id: str) -> str:
    """The journal claim-lease key under which an elastic pool member
    leases one request's EXECUTION (the fleet's bucket keys play the
    same role one layer down).  Namespaced so request leases and bucket
    leases can never collide in a shared journal."""
    return "req:" + str(request_id)

"""Watched-spool intake: drop a ``.json`` file, get a cleaning request.

The zero-dependency submission path (LOFAR-pipeline shaped: an upstream
stage writes archives plus a request file into a shared directory).  The
watcher scans ``spool_dir`` every ``poll_s`` for ``*.json`` files and
claims each by RENAMING it before parsing — rename is atomic on a POSIX
filesystem, so a file is ingested exactly once even if a second daemon
watches the same spool.  Outcomes are visible in the directory itself::

    req1.json            pending (a mid-drain submission stays like this)
    req1.json.accepted   admitted; lifecycle continues in the journal
    req1.json.rejected   refused (backpressure or malformed; reason inside
                         a trailing "#" comment-line is NOT added — the
                         journal and daemon log carry the reason)

Producers should write-then-rename into the spool themselves (write
``.tmp``, rename to ``.json``) so the watcher never claims a
half-written file.  For producers that don't, the watcher tells the two
failure shapes apart: a file whose JSON breaks mid-document is truly
malformed and is rejected (rejection is visible and debuggable; a silent
retry loop on it would spin forever), while a file that is empty or
whose JSON simply STOPS — truncated at end-of-buffer, the signature of a
write still in flight — is unclaimed back to ``.json`` for the next scan
(``serve_spool_torn``) so a slow writer's request is never lost.  The
``intake`` fault site fires per scanned file: an injected transient
skips the file this scan (``serve_retries``) and the next scan retries
it — intake faults never wedge or kill the daemon.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional

from iterative_cleaner_tpu.serve.request import (
    RequestError,
    ServeRequest,
    parse_request,
)

ACCEPTED_SUFFIX = ".accepted"
REJECTED_SUFFIX = ".rejected"


def _json_truncated(raw: bytes) -> bool:
    """Does ``raw`` look like a JSON document cut off mid-write?  True
    for empty/whitespace-only content and for JSON whose parse error sits
    at the end of the buffer (the document just STOPS — ``{"paths": ["/a``)
    rather than at a syntax error mid-document (``{"paths": [}`` — that
    file will never become valid, so it must reject, not retry)."""
    import json

    text = raw.decode("utf-8", errors="replace")
    if not text.strip():
        return True
    try:
        json.loads(text)
    except json.JSONDecodeError as exc:
        if exc.pos >= len(text.rstrip()):
            return True
        # an unterminated string always runs to end-of-input: the error
        # anchors at its opening quote, but the tear is at EOF
        return exc.msg.startswith("Unterminated string")
    return False  # valid JSON that failed request validation: malformed


class SpoolWatcher:
    """One scan pass at a time (the daemon loop calls :meth:`scan_once`
    between queue polls; no thread of its own — the daemon owns timing).

    ``on_request(req, claimed_path)`` admits the parsed request and
    returns normally, or raises
    :class:`~iterative_cleaner_tpu.serve.scheduler.Rejection`; the
    watcher renames the claimed file to match the outcome."""

    def __init__(self, spool_dir: str, *,
                 on_request: Callable[[ServeRequest, str], None],
                 base_config=None, registry=None, faults=None) -> None:
        self.spool_dir = os.path.abspath(spool_dir)
        self.on_request = on_request
        self.base_config = base_config
        self.registry = registry
        self.faults = faults
        os.makedirs(self.spool_dir, exist_ok=True)

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name)

    def pending_files(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError:
            return []
        return [os.path.join(self.spool_dir, n) for n in names
                if n.endswith(".json") and not n.startswith(".")]

    def scan_once(self, stop_intake: bool = False) -> int:
        """Claim and submit every pending spool file; returns how many
        were admitted.  With ``stop_intake`` (draining) the scan is a
        no-op: mid-drain submissions stay untouched ``.json`` files for
        the next daemon start."""
        if stop_intake:
            return 0
        admitted = 0
        for path in self.pending_files():
            admitted += self._ingest(path)
        return admitted

    def _ingest(self, path: str) -> int:
        from iterative_cleaner_tpu.serve.scheduler import Rejection

        if self.faults is not None:
            try:
                self.faults.fire("intake", detail=os.path.basename(path))
            except Exception:
                # transient intake fault: leave the file for the next
                # scan — submissions are never lost to a flaky intake
                self._count("serve_retries")
                return 0
        claimed = path + ".claimed"
        try:
            os.rename(path, claimed)  # atomic claim: exactly-once intake
        except OSError:
            return 0                  # raced another claimer / withdrawn
        stem = os.path.basename(path)[:-len(".json")]
        try:
            with open(claimed, "rb") as f:
                raw = f.read()
        except OSError as exc:
            self._reject(claimed, f"unreadable: {exc}")
            return 0
        try:
            req = parse_request(raw, request_id=stem,
                                base_config=self.base_config)
        except RequestError as exc:
            if _json_truncated(raw):
                # torn write: the producer is mid-rename-less write (or
                # crashed mid-write); unclaim so the next scan retries
                # once the file is whole — never reject a partial file
                self._count("serve_spool_torn")
                try:
                    os.rename(claimed, path)
                except OSError:
                    pass
                return 0
            self._reject(claimed, f"malformed: {exc}")
            return 0
        try:
            self.on_request(req, claimed)
        except Rejection as exc:
            self._reject(claimed, exc.detail)
            return 0
        # icln: ignore[atomic-write] -- state-machine rename between two existing spool names (.claimed -> .accepted), not a file publish
        os.replace(claimed, path + ACCEPTED_SUFFIX)
        return 1

    def _reject(self, claimed: str, detail: str) -> None:
        self._count("serve_rejected_spool")
        print(f"serve: rejected spool file "
              f"{os.path.basename(claimed)}: {detail}", flush=True)
        try:
            # icln: ignore[atomic-write] -- state-machine rename between two existing spool names (.claimed -> .rejected), not a file publish
            os.replace(claimed, claimed[:-len(".claimed")] + REJECTED_SUFFIX)
        except OSError:
            pass

"""Watched-spool intake: drop a ``.json`` file, get a cleaning request.

The zero-dependency submission path (LOFAR-pipeline shaped: an upstream
stage writes archives plus a request file into a shared directory).  The
watcher scans ``spool_dir`` every ``poll_s`` for ``*.json`` files and
claims each by RENAMING it before parsing — rename is atomic on a POSIX
filesystem, so a file is ingested exactly once even if a second daemon
watches the same spool.  Outcomes are visible in the directory itself::

    req1.json            pending (a mid-drain submission stays like this)
    req1.json.accepted   admitted; lifecycle continues in the journal
    req1.json.rejected   refused (backpressure or malformed; reason inside
                         a trailing "#" comment-line is NOT added — the
                         journal and daemon log carry the reason)

Producers should write-then-rename into the spool themselves (write
``.tmp``, rename to ``.json``) so the watcher never claims a
half-written file — a file that does not parse is rejected, not
retried (rejection is visible and debuggable; a silent retry loop on a
truly malformed file would spin forever).  The ``intake`` fault
site fires per scanned file: an injected transient skips the file this
scan (``serve_retries``) and the next scan retries it — intake faults
never wedge or kill the daemon.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional

from iterative_cleaner_tpu.serve.request import (
    RequestError,
    ServeRequest,
    parse_request,
)

ACCEPTED_SUFFIX = ".accepted"
REJECTED_SUFFIX = ".rejected"


class SpoolWatcher:
    """One scan pass at a time (the daemon loop calls :meth:`scan_once`
    between queue polls; no thread of its own — the daemon owns timing).

    ``on_request(req, claimed_path)`` admits the parsed request and
    returns normally, or raises
    :class:`~iterative_cleaner_tpu.serve.scheduler.Rejection`; the
    watcher renames the claimed file to match the outcome."""

    def __init__(self, spool_dir: str, *,
                 on_request: Callable[[ServeRequest, str], None],
                 base_config=None, registry=None, faults=None) -> None:
        self.spool_dir = os.path.abspath(spool_dir)
        self.on_request = on_request
        self.base_config = base_config
        self.registry = registry
        self.faults = faults
        os.makedirs(self.spool_dir, exist_ok=True)

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name)

    def pending_files(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError:
            return []
        return [os.path.join(self.spool_dir, n) for n in names
                if n.endswith(".json") and not n.startswith(".")]

    def scan_once(self, stop_intake: bool = False) -> int:
        """Claim and submit every pending spool file; returns how many
        were admitted.  With ``stop_intake`` (draining) the scan is a
        no-op: mid-drain submissions stay untouched ``.json`` files for
        the next daemon start."""
        if stop_intake:
            return 0
        admitted = 0
        for path in self.pending_files():
            admitted += self._ingest(path)
        return admitted

    def _ingest(self, path: str) -> int:
        from iterative_cleaner_tpu.serve.scheduler import Rejection

        if self.faults is not None:
            try:
                self.faults.fire("intake", detail=os.path.basename(path))
            except Exception:
                # transient intake fault: leave the file for the next
                # scan — submissions are never lost to a flaky intake
                self._count("serve_retries")
                return 0
        claimed = path + ".claimed"
        try:
            os.rename(path, claimed)  # atomic claim: exactly-once intake
        except OSError:
            return 0                  # raced another claimer / withdrawn
        stem = os.path.basename(path)[:-len(".json")]
        try:
            with open(claimed, "rb") as f:
                req = parse_request(f.read(), request_id=stem,
                                    base_config=self.base_config)
        except RequestError as exc:
            self._reject(claimed, f"malformed: {exc}")
            return 0
        except OSError as exc:
            self._reject(claimed, f"unreadable: {exc}")
            return 0
        try:
            self.on_request(req, claimed)
        except Rejection as exc:
            self._reject(claimed, exc.detail)
            return 0
        os.replace(claimed, path + ACCEPTED_SUFFIX)
        return 1

    def _reject(self, claimed: str, detail: str) -> None:
        self._count("serve_rejected_spool")
        print(f"serve: rejected spool file "
              f"{os.path.basename(claimed)}: {detail}", flush=True)
        try:
            os.replace(claimed, claimed[:-len(".claimed")] + REJECTED_SUFFIX)
        except OSError:
            pass

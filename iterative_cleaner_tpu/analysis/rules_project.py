"""Repo-wide invariants: exception accounting, config identity, drift.

These rules diff the code against its own contracts: every broad
exception handler must leave a trace (log line or registry counter),
every ``CleanConfig`` field must be deliberately classified for the
checkpoint identity hash, and the three user surfaces (``ICLEAN_*`` env
mirrors, ``--flags``, MIGRATION/README docs) must not drift apart.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from iterative_cleaner_tpu.analysis.core import (
    FileContext,
    RepoContext,
    RepoRule,
    Rule,
)

#: env knobs that deliberately have no CLI flag mirror (internal tuning
#: or test-harness toggles); they still need a MIGRATION.md row
ENV_ONLY = frozenset({
    "ICLEAN_PLATFORM",          # process-level backend pin (conftest)
    "ICLEAN_SERVE_QUEUE",       # daemon queue depth (ServeConfig.from_env)
    "ICLEAN_STREAM_IDLE_S",     # online-mode idle shutdown
    "ICLEAN_PROBE_TIMEOUT",     # device probe budget
    "ICLEAN_DFT_PRECISION",     # matmul-DFT precision tier
    "ICLEAN_FUSED_TIER",        # fused-stats lowering tier
    "ICLEAN_FUSED_AUTO_MAX_NBIN",
    "ICLEAN_FUSED_SBLK",
    "ICLEAN_FUSED_CBLK_SCALE",
    "ICLEAN_SCALER_VMEM_MB",
    "ICLEAN_SWEEP_DMA",         # per-shard DMA-vs-BlockSpec escape hatch
                                # (hardware debugging; masks bit-equal, so
                                # no user-facing flag is warranted)
    "ICLEAN_BUILDER_CACHE",     # lru_cache bound for the batch builders
    "ICLEAN_FAULT_HANG_S",      # fault-injection hang duration
    "ICLEAN_RACE_BUDGET_S",     # model-checker sweep wall-clock budget
})

_ENV_RE = re.compile(r"\bICLEAN_[A-Z0-9_]+\b")


class BroadExceptRule(Rule):
    """``except Exception:`` must log-or-count, not swallow."""

    id = "broad-except"
    severity = "warning"
    description = ("a broad handler whose body neither raises nor calls "
                   "anything swallows the error invisibly; count it via "
                   "the registry or log it (or suppress with a reason)")

    BROAD = {"Exception", "BaseException"}

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            broad = (t is None
                     or (isinstance(t, ast.Name) and t.id in self.BROAD)
                     or (isinstance(t, ast.Attribute)
                         and t.attr in self.BROAD))
            if not broad:
                continue
            acts = any(isinstance(n, (ast.Raise, ast.Call))
                       for b in node.body for n in ast.walk(b))
            if not acts:
                yield (node.lineno,
                       "broad except swallows the error with no log "
                       "line or registry counter: count it "
                       "(*_errors counter), log it, or suppress with "
                       "a reason")


def _set_literal_names(node: ast.AST) -> Optional[Set[str]]:
    """String elements of a set/frozenset literal, or None."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "frozenset" and node.args:
        node = node.args[0]
    if isinstance(node, ast.Set):
        out = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return out
    return None


class ConfigIdentityRule(RepoRule):
    """Every CleanConfig field is classified identity or excluded.

    The checkpoint identity hash (utils/checkpoint.py) decides when a
    resumed run may reuse prior results; a field that silently joins the
    dataclass without a classification either invalidates every
    checkpoint (over-keying) or lets a behaviour-changing option reuse
    stale results (under-keying).  ``_IDENTITY_FIELDS`` and
    ``_IDENTITY_EXCLUDE`` in utils/checkpoint.py must partition the
    dataclass exactly."""

    id = "config-identity"
    severity = "error"
    description = ("CleanConfig fields must appear in exactly one of "
                   "utils/checkpoint.py's _IDENTITY_FIELDS / "
                   "_IDENTITY_EXCLUDE")

    def check_repo(self, repo: RepoContext):
        cfg = repo.file("iterative_cleaner_tpu/config.py")
        chk = repo.file("iterative_cleaner_tpu/utils/checkpoint.py")
        if cfg is None or chk is None or cfg.tree is None \
                or chk.tree is None:
            return
        fields: Dict[str, int] = {}
        for node in ast.walk(cfg.tree):
            if isinstance(node, ast.ClassDef) and node.name == "CleanConfig":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        fields[stmt.target.id] = stmt.lineno
        include: Optional[Set[str]] = None
        exclude: Optional[Set[str]] = None
        inc_line = exc_line = 1
        for node in ast.walk(chk.tree):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id == "_IDENTITY_FIELDS":
                    include = _set_literal_names(node.value)
                    inc_line = node.lineno
                elif t.id == "_IDENTITY_EXCLUDE":
                    exclude = _set_literal_names(node.value)
                    exc_line = node.lineno
        if exclude is None:
            yield (chk, 1, "utils/checkpoint.py must define "
                   "_IDENTITY_EXCLUDE as a literal set of field names")
            return
        if include is None:
            yield (chk, exc_line, "utils/checkpoint.py must define "
                   "_IDENTITY_FIELDS: the explicit identity half of the "
                   "CleanConfig partition (new fields then fail loudly "
                   "here instead of silently joining the hash)")
            return
        for name, line in fields.items():
            in_i, in_e = name in include, name in exclude
            if in_i and in_e:
                yield (chk, inc_line,
                       f"CleanConfig.{name} is in both _IDENTITY_FIELDS "
                       "and _IDENTITY_EXCLUDE")
            elif not in_i and not in_e:
                yield (cfg, line,
                       f"CleanConfig.{name} is classified neither "
                       "checkpoint-identity (_IDENTITY_FIELDS) nor "
                       "excluded (_IDENTITY_EXCLUDE) in "
                       "utils/checkpoint.py")
        for name in sorted((include | exclude) - set(fields)):
            yield (chk, inc_line if name in include else exc_line,
                   f"{name!r} is classified in utils/checkpoint.py but "
                   "is not a CleanConfig field (stale entry)")


class EnvDriftRule(RepoRule):
    """Every ``ICLEAN_*`` env read is documented and flag-mirrored."""

    id = "env-drift"
    severity = "error"
    description = ("each ICLEAN_* env var needs a MIGRATION.md row and "
                   "a --flag mirror (or an entry in the analyzer's "
                   "ENV_ONLY allowlist)")

    def check_repo(self, repo: RepoContext):
        migration = repo.docs.get("MIGRATION.md")
        if migration is None:
            return
        flags = _cli_flags(repo)
        seen: Dict[str, Tuple[FileContext, int]] = {}
        for ctx in repo.files:
            for lineno, text in enumerate(ctx.lines, start=1):
                for m in _ENV_RE.finditer(text):
                    seen.setdefault(m.group(0), (ctx, lineno))
        for name in sorted(seen):
            ctx, line = seen[name]
            if name not in migration:
                yield (ctx, line,
                       f"{name} has no MIGRATION.md row: document the "
                       "knob where users look for it")
            mirror = "--" + name[len("ICLEAN_"):].lower().replace("_", "-")
            if name in ENV_ONLY:
                continue
            if mirror not in flags:
                yield (ctx, line,
                       f"{name} has no CLI mirror ({mirror}): add the "
                       "flag, or allowlist it in the analyzer's "
                       "ENV_ONLY with a why-comment")


class FlagDocsRule(RepoRule):
    """Every ``--flag`` the parser accepts is documented."""

    id = "flag-docs"
    severity = "warning"
    description = ("each cli.py --flag must appear in README.md or "
                   "MIGRATION.md (dash/underscore spellings count as "
                   "one flag)")

    def check_repo(self, repo: RepoContext):
        docs = "\n".join(repo.docs.get(n, "")
                         for n in ("README.md", "MIGRATION.md"))
        if not docs.strip():
            return
        cli = repo.file("iterative_cleaner_tpu/cli.py")
        if cli is None or cli.tree is None:
            return
        norm_docs = docs.replace("_", "-")
        for flag, line in sorted(_flag_lines(cli).items()):
            if flag.replace("_", "-") not in norm_docs:
                yield (cli, line,
                       f"{flag} is not mentioned in README.md or "
                       "MIGRATION.md: every user-facing flag needs a "
                       "documented home")


def _flag_lines(cli: FileContext) -> Dict[str, int]:
    """--flag -> add_argument line, dash/underscore twins collapsed."""
    out: Dict[str, int] = {}
    for node in ast.walk(cli.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and arg.value.startswith("--"):
                key = arg.value.replace("_", "-")
                if key not in out:
                    out[key] = node.lineno
    return out


def _cli_flags(repo: RepoContext) -> Set[str]:
    cli = repo.file("iterative_cleaner_tpu/cli.py")
    if cli is None or cli.tree is None:
        return set()
    return set(_flag_lines(cli))

"""icln-lint: project-invariant static analysis + jaxpr contract checks.

Two halves:

* An AST lint engine (:mod:`.core`) with project-specific rules
  (:mod:`.rules_io`, :mod:`.rules_jit`, :mod:`.rules_project`) that turn
  the codebase's conventions — atomic writes through ``io/atomic.py``,
  flock'd appends through ``utils/logging.py``, donation safety, jit
  purity, registry-counted exception handling, config-identity
  exhaustiveness, env/flag/doc drift — into machine-checked invariants.
* A jaxpr contract verifier (:mod:`.jaxpr_contracts`) that lowers the
  registered hot programs on the CPU backend and asserts structural
  contracts (no host callbacks, no float64 promotion, donation aliasing
  realized, bounded equation count).

Entry points: the ``icln-lint`` console script and
``python -m iterative_cleaner_tpu --selfcheck`` (:mod:`.cli`).
"""

from iterative_cleaner_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintReport,
    Rule,
    RepoRule,
    default_rules,
    lint_paths,
    lint_source,
    record_findings,
)

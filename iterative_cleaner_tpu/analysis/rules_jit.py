"""jit-surface rules: purity, static-arg hashability, donation safety.

The zero-steady-state-recompile and bit-equal-mask guarantees only hold
if the traced functions are pure (tracing bakes host state in at compile
time and silently never re-reads it), the lru-cached builder keys stay
hashable (an unhashable key raises; a fresh-per-call key recompiles
every dispatch), and donated buffers are never touched again by the
caller (XLA reuses the memory; reads return garbage or raise).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from iterative_cleaner_tpu.analysis.core import FileContext, Rule

#: dotted-prefix -> why it is impure inside a traced body
IMPURE_PREFIXES = (
    ("time.", "host clock reads trace to a constant"),
    ("datetime.", "host clock reads trace to a constant"),
    ("np.random", "host RNG traces to a constant; use jax.random"),
    ("numpy.random", "host RNG traces to a constant; use jax.random"),
    ("random.", "host RNG traces to a constant; use jax.random"),
    ("os.environ", "env reads trace to a constant"),
    ("os.getenv", "env reads trace to a constant"),
)

#: call leaves that are host callbacks / side effects in a traced body
IMPURE_LEAVES = {
    "print": "print() inside a jitted body becomes a host callback (or "
             "traces silently); use jax.debug.print only behind a debug "
             "flag, outside the hot programs",
    "pure_callback": "host callback on the hot path breaks the "
                     "no-host-callback contract",
    "io_callback": "host callback on the hot path breaks the "
                   "no-host-callback contract",
    "open": "filesystem I/O inside a traced body",
}

#: lru_cache'd builders whose arguments form the cache key: every
#: argument must be hashable or the call raises / recompiles
CACHED_BUILDERS = frozenset({
    "build_clean_fn", "build_batched_clean_fn", "build_batch_shardmap_fn",
})

#: files allowed to introduce donate_argnums sites (each audited here)
DONATION_FILES = (
    "backends/jax_backend.py",
    "parallel/batch.py",
)


def _attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _collect_fn_names(node: ast.AST, out: Set[str]) -> None:
    """Names referenced by a jit(...) argument expression, descending
    through wrapper calls (vmap(one), shard_map(f, ...), partial(f))."""
    if isinstance(node, ast.Name):
        out.add(node.id)
    elif isinstance(node, ast.Call):
        for arg in node.args:
            _collect_fn_names(arg, out)
    elif isinstance(node, ast.Attribute):
        # jitting a bound method / module attr: flag by its leaf name
        out.add(node.attr)


def _jitted_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain.split(".")[-1] == "jit" and node.args:
                _collect_fn_names(node.args[0], names)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                chain = _attr_chain(target)
                if chain.split(".")[-1] == "jit":
                    names.add(node.name)
                if chain.endswith("partial") and isinstance(dec, ast.Call):
                    for arg in dec.args:
                        if _attr_chain(arg).split(".")[-1] == "jit":
                            names.add(node.name)
    return names


class JitPurityRule(Rule):
    """No host state or side effects inside a traced body."""

    id = "jit-purity"
    severity = "error"
    description = ("jitted bodies must be pure: no clocks, host RNG, "
                   "env/file/stdout access, callbacks, or global "
                   "mutation")

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        jitted = _jitted_names(ctx.tree)
        if not jitted:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name not in jitted:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    yield (node.lineno,
                           f"global mutation inside jitted {fn.name}(): "
                           "traced once, never re-run per dispatch")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                leaf = chain.split(".")[-1]
                if leaf in IMPURE_LEAVES and (chain == leaf
                                              or "." in chain):
                    yield (node.lineno,
                           f"{chain}() inside jitted {fn.name}(): "
                           + IMPURE_LEAVES[leaf])
                    continue
                for prefix, why in IMPURE_PREFIXES:
                    if chain.startswith(prefix) or chain == prefix[:-1]:
                        yield (node.lineno,
                               f"{chain}() inside jitted {fn.name}(): "
                               + why)
                        break


class StaticHashableRule(Rule):
    """Arguments to the lru-cached builders must be hashable literals."""

    id = "static-hashable"
    severity = "error"
    description = ("list/dict/set arguments to an lru_cache'd builder "
                   "raise TypeError (or defeat the cache): pass tuples "
                   "or scalars")

    UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                  ast.SetComp)

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _attr_chain(node.func).split(".")[-1]
            if leaf not in CACHED_BUILDERS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, self.UNHASHABLE):
                    yield (arg.lineno,
                           f"unhashable {type(arg).__name__.lower()} "
                           f"argument to {leaf}(): the lru_cache key "
                           "raises TypeError; pass a tuple/frozenset")


class DonationSafetyRule(Rule):
    """Donated buffers must not be reused, and new donation sites must
    be deliberate.

    (a) any ``donate_argnums=`` outside the audited builder files is
    flagged — donation silently invalidates caller buffers, so each new
    site needs review (add the file to DONATION_FILES once audited);
    (b) a call through a builder handle constructed with ``donate=True``
    must not reuse the Name it passed as cube/weights afterwards — the
    backing buffer is gone."""

    id = "donation-safety"
    severity = "error"
    description = ("donate_argnums sites live in the audited builder "
                   "files; arrays passed to a donate=True program are "
                   "dead after the call")

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        in_builder_file = any(ctx.rel.endswith(s) for s in DONATION_FILES)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not in_builder_file:
                for kw in node.keywords:
                    if kw.arg == "donate_argnums":
                        yield (node.lineno,
                               "new donate_argnums site outside the "
                               "audited builder files: donation "
                               "invalidates caller buffers; build "
                               "through backends/jax_backend.py or "
                               "parallel/batch.py (or audit this file "
                               "into the analyzer's DONATION_FILES)")
        yield from self._reuse_after_donation(ctx)

    def _reuse_after_donation(self, ctx: FileContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            donating: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    leaf = _attr_chain(node.value.func).split(".")[-1]
                    if leaf in CACHED_BUILDERS and any(
                            kw.arg == "donate"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True
                            for kw in node.value.keywords):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                donating.add(t.id)
            if not donating:
                continue
            calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Name)
                     and n.func.id in donating]
            loads: Dict[str, List[int]] = {}
            for n in ast.walk(fn):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    loads.setdefault(n.id, []).append(n.lineno)
            for call in calls:
                for arg in call.args[:2]:  # donate_argnums=(0, 1)
                    if not isinstance(arg, ast.Name):
                        continue
                    end = getattr(call, "end_lineno", call.lineno)
                    later = [ln for ln in loads.get(arg.id, ())
                             if ln > end]
                    if later:
                        yield (later[0],
                               f"{arg.id!r} was donated into "
                               f"{call.func.id}() on line {call.lineno} "
                               "and read again here: the buffer is "
                               "invalidated by donation")

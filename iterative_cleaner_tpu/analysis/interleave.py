"""Deterministic interleaving model checker for the journal-lease protocol.

Unit tests exercise one interleaving — whichever the OS scheduler
happens to produce — and the PR-12 review showed that is exactly how
protocol races (the admit-ordering duplicate-clean hazard, the
pool-count leak) survive a green suite.  This module runs the REAL
protocol code (``resilience/journal.py``, ``serve/membership.py``,
``serve/scheduler.py``) under a loom-style cooperative scheduler and
explores schedules systematically instead:

* Actor programs run on real threads, but every shared-state operation
  parks at an instrumented **step point** (:meth:`Env.step`;
  :class:`InstrumentedJournal` adds one automatically around every
  journal append and fold) and only proceeds when the controller
  schedules it.  Exactly one actor runs between step points, so a
  schedule — the sequence of actor choices — fully determines the
  execution, and any failing schedule replays exactly.
* :func:`explore` enumerates schedules depth-first (exhaustive for the
  2–3-actor scenarios here), with a lex-min partial-order reduction —
  two adjacent steps touching different resources (or both reading)
  commute, so only the canonical order of each commuting pair is
  explored — and a seeded bounded-random mode for depth beyond the
  exhaustive horizon.
* Invariants are machine-checked after every step and at quiescence:
  exactly one ``try_claim`` winner, fold determinism under compaction
  at any prefix, accepted-strictly-before-enqueue (via the journal
  fsck's request state machine), no terminal request pool-adoptable,
  member eviction edge-fires once per incarnation, tenant slots fully
  released.  A violation is minimized (greedy context-switch
  reduction, replayed each pass) and rendered as a numbered schedule.

Seeded-bug scenarios (:func:`build_scenario` with ``bug=...``) revert
known fixes in memory — the PR-12 admit-ordering and pool-count fixes
among them — and the test suite asserts the checker catches every one;
the CI gate runs the clean variants and must come back green.

Every scenario is additionally parameterized over the journal
**backend** (``build_scenario(..., backend="segmented")`` /
``sweep(backends=...)``): the same actors, invariants and seeded bugs
run against a segmented journal directory with a few-hundred-byte seal
threshold, so schedules constantly cross seal and compaction boundaries
— the machine-checked form of the fold-equivalence contract the
segmented backend claims (resilience/segmented.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from iterative_cleaner_tpu.resilience.journal import FleetJournal

#: journal backends every scenario can run against
BACKENDS = ("file", "segmented")

#: a few hundred bytes: segmented scenarios seal every couple of lines,
#: so every schedule crosses seal/compaction boundaries mid-protocol
_SEGMENT_MB = 0.0003

#: scenario name -> the seeded bugs build_scenario() accepts for it
SCENARIOS: Dict[str, Tuple[str, ...]] = {
    "claim-race": ("no-readback",),
    "admit-order": ("admit-order",),
    "pool-count": ("pool-count",),
    "eviction-edge": ("eviction-edge",),
    "compact-prefix": ("compact-last-claim",),
}

_STEP_TIMEOUT_S = 20.0  # watchdog: a step that parks nothing this long
#                         is a real deadlock/hang, not a slow machine


class InvariantViolation(AssertionError):
    """A protocol invariant failed under some schedule."""


class Hang(RuntimeError):
    """An actor neither parked nor finished within the watchdog — the
    schedule drove the real code into a deadlock or unbounded wait."""


class _Abort(BaseException):
    """Internal: unwind actor threads after a failure (never caught by
    scenario code — derives from BaseException on purpose)."""


@dataclasses.dataclass(frozen=True)
class Op:
    """One atomic step: what ``actor`` did between two park points."""

    actor: int
    resource: str
    kind: str          # "read" | "write"
    note: str = ""

    def render(self) -> str:
        return f"A{self.actor}  {self.resource}.{self.kind}" + (
            f"  {self.note}" if self.note else "")

    def independent(self, other: "Op") -> bool:
        """Two steps commute when they touch different resources or
        both only read — swapping them cannot change any outcome."""
        return (self.resource != other.resource
                or (self.kind == "read" and other.kind == "read"))


@dataclasses.dataclass
class Decision:
    """One scheduling decision: who ran, who else was ready, what each
    was about to do, and who was asleep (sleep-set POR bookkeeping)."""

    chosen: int
    enabled: Tuple[int, ...]
    pending: Dict[int, Op]
    sleep: Tuple[int, ...] = ()

    @property
    def op(self) -> Op:
        return self.pending[self.chosen]


class VirtualClock:
    """The scenario's time source: starts at the real ``time.time()``
    (journal compaction internally stamps with real time, so virtual
    stamps must live in the same epoch) and only moves when a scenario
    actor advances it — lease expiry becomes a deterministic, schedulable
    event instead of a sleep."""

    def __init__(self) -> None:
        self._base = time.time()
        self._offset = 0.0

    def now(self) -> float:
        return self._base + self._offset

    def advance(self, dt: float) -> None:
        self._offset += float(dt)


class Env:
    """Everything a scenario shares: the virtual clock, the journal
    (instrumented — its appends and folds are step points), a scratch
    dict for results, and :meth:`step` for explicit step points around
    in-memory operations (scheduler calls, clock advances)."""

    def __init__(self, controller: "_Controller", path: str,
                 tmpdir: str, backend: str = "file") -> None:
        self._controller = controller
        self.path = path
        self.tmpdir = tmpdir
        self.backend = backend
        #: how to build a journal over ``path`` with this backend —
        #: scenarios that substitute their own journal subclass reuse it
        self.journal_kwargs: Dict[str, object] = (
            {"backend": "segmented", "segment_mb": _SEGMENT_MB}
            if backend == "segmented" else {})
        self.clock = VirtualClock()
        self.journal = InstrumentedJournal(path, **self.journal_kwargs)
        self.journal._env = self
        self.data: Dict[str, object] = {}

    def step(self, resource: str, kind: str, note: str = "") -> None:
        self._controller.park(Op(self._controller.current_actor(),
                                 resource, kind, note))

    def plain_journal(self) -> FleetJournal:
        """An UNinstrumented journal over the same file — invariant
        checks read through this so they never generate steps."""
        return FleetJournal(self.path)


class InstrumentedJournal(FleetJournal):
    """The real journal with a step point before every append and every
    fold-producing read.  ``try_claim`` therefore decomposes into its
    true atomic parts — the flock'd append and the separate read-back —
    and the checker explores interleavings between them, which is
    exactly where the one-winner guarantee has to hold."""

    _env: Optional[Env] = None

    def _step(self, kind: str, note: str) -> None:
        if self._env is not None:
            self._env.step("journal", kind, note)

    def _append(self, entry: dict) -> None:
        note = entry.get("event", "?")
        if entry.get("event") == "req":
            note = f"req:{entry.get('state')}:{entry.get('req')}"
        elif entry.get("event") == "claim":
            note = f"claim:{entry.get('state')}:{entry.get('work')}"
        elif entry.get("event") == "member":
            note = f"member:{entry.get('state')}:{entry.get('member')}"
        self._step("write", note)
        FleetJournal._append(self, entry)

    def request_states(self):
        self._step("read", "fold:req")
        return FleetJournal.request_states(self)

    def claim_table(self, now=None):
        self._step("read", "fold:claim")
        return FleetJournal.claim_table(self, now=now)

    def member_table(self, now=None):
        self._step("read", "fold:member")
        return FleetJournal.member_table(self, now=now)

    def completed(self, config_hash):
        self._step("read", "fold:done")
        return FleetJournal.completed(self, config_hash)

    def cache_index(self):
        self._step("read", "fold:cache")
        return FleetJournal.cache_index(self)

    def compact(self):
        self._step("write", "compact")
        return FleetJournal.compact(self)


@dataclasses.dataclass
class Scenario:
    """One checkable protocol drill: ``setup`` builds the shared
    objects onto the env, each actor is a callable ``(env, actor_id)``
    run as one cooperative thread, and the invariants raise
    :class:`InvariantViolation`."""

    name: str
    actors: Sequence[Callable[[Env, int], None]]
    setup: Optional[Callable[[Env], None]] = None
    invariant_step: Optional[Callable[[Env], None]] = None
    invariant_final: Optional[Callable[[Env], None]] = None
    bug: Optional[str] = None
    backend: str = "file"


@dataclasses.dataclass
class RunResult:
    choices: Tuple[int, ...]
    decisions: List[Decision]
    failure: Optional[dict] = None   # {"type", "message", "step"}
    redundant: bool = False          # aborted: only sleeping actors left

    @property
    def ok(self) -> bool:
        return self.failure is None

    def context_switches(self) -> int:
        return sum(1 for a, b in zip(self.choices, self.choices[1:])
                   if a != b)


class _Controller:
    """Runs ONE schedule: actors park at step points, the controller
    releases exactly one at a time (replaying a choice prefix, then
    following a deterministic or seeded-random policy)."""

    def __init__(self, scenario: Scenario, *,
                 prefix: Sequence[int] = (),
                 sleep0: Sequence[int] = (),
                 rng=None, max_steps: int = 400) -> None:
        self.scenario = scenario
        self.prefix = tuple(prefix)
        self.sleep0 = frozenset(sleep0)
        self.rng = rng
        self.max_steps = max_steps
        self._lock = threading.Condition()
        self._pending: Dict[int, Op] = {}
        self._resume: Set[int] = set()
        self._finished: Set[int] = set()
        self._errors: Dict[int, BaseException] = {}
        self._abort = False
        self._local = threading.local()

    # ---------------------------------------------------- actor side
    def current_actor(self) -> int:
        return self._local.actor_id

    def park(self, op: Op) -> None:
        with self._lock:
            self._pending[op.actor] = op
            self._lock.notify_all()
            while op.actor not in self._resume and not self._abort:
                self._lock.wait(1.0)
            self._resume.discard(op.actor)
            if self._abort:
                raise _Abort()

    def _actor_main(self, aid: int,
                    fn: Callable[[Env, int], None], env: Env) -> None:
        self._local.actor_id = aid
        try:
            # every actor parks before its first instruction, so the
            # schedule controls program-start order too
            self.park(Op(aid, f"start:{aid}", "read", "start"))
            fn(env, aid)
        except _Abort:
            pass
        # icln: ignore[broad-except] -- recorded in _errors, rethrown by the controller as the schedule's failure
        except BaseException as exc:
            with self._lock:
                self._errors[aid] = exc
        finally:
            with self._lock:
                self._finished.add(aid)
                self._pending.pop(aid, None)
                self._lock.notify_all()

    # ----------------------------------------------- controller side
    def run(self, tmpdir: str) -> RunResult:
        backend = self.scenario.backend
        path = os.path.join(tmpdir, "journal.d" if backend == "segmented"
                            else "journal.jsonl")
        env = Env(self, path, tmpdir, backend=backend)
        if self.scenario.setup is not None:
            self.scenario.setup(env)
        threads = []
        n = len(self.scenario.actors)
        for aid, fn in enumerate(self.scenario.actors):
            t = threading.Thread(target=self._actor_main,
                                 args=(aid, fn, env),
                                 name=f"icln-race-a{aid}", daemon=True)
            threads.append(t)
            t.start()
        choices: List[int] = []
        decisions: List[Decision] = []
        failure: Optional[dict] = None
        redundant = False
        # sleep-set POR state: actors whose scheduling here would only
        # replay an already-explored commuting order.  Active beyond the
        # replayed prefix; an executed op WAKES every sleeper whose
        # pending op depends on it (the orders stopped commuting).
        sleep: Set[int] = set(self.sleep0)
        try:
            while True:
                with self._lock:
                    deadline = time.monotonic() + _STEP_TIMEOUT_S
                    while True:
                        live = set(range(n)) - self._finished
                        if self._errors:
                            raise next(iter(self._errors.values()))
                        if not live:
                            break
                        if live <= set(self._pending):
                            break
                        if time.monotonic() > deadline:
                            raise Hang(
                                f"actors {sorted(live - set(self._pending))} "
                                f"neither parked nor finished within "
                                f"{_STEP_TIMEOUT_S:g}s — the schedule "
                                f"{tuple(choices)} wedged the real code")
                        self._lock.wait(0.2)
                    if not live:
                        break
                    enabled = tuple(sorted(self._pending))
                    i = len(choices)
                    in_prefix = i < len(self.prefix)
                    if in_prefix:
                        chosen = self.prefix[i]
                        if chosen not in enabled:
                            raise Hang(
                                f"replay diverged: prefix chose A{chosen} "
                                f"at step {i} but enabled={enabled}")
                    else:
                        sleep &= set(enabled)
                        eligible = tuple(a for a in enabled
                                         if a not in sleep)
                        if not eligible:
                            # every enabled actor is asleep: this whole
                            # subtree re-explores commuting orders only
                            redundant = True
                            break
                        if self.rng is not None:
                            chosen = self.rng.choice(eligible)
                        else:
                            chosen = eligible[0]
                    decisions.append(Decision(
                        chosen, enabled, dict(self._pending),
                        sleep=() if in_prefix else tuple(sorted(sleep))))
                    choices.append(chosen)
                    if len(choices) > self.max_steps:
                        raise Hang(
                            f"schedule exceeded max_steps={self.max_steps} "
                            f"without quiescing")
                    if not in_prefix:
                        executed = decisions[-1].op
                        sleep = {b for b in sleep
                                 if b in self._pending and b != chosen
                                 and self._pending[b].independent(executed)}
                    self._pending.pop(chosen)
                    self._resume.add(chosen)
                    self._lock.notify_all()
                # out of the lock: let the chosen actor run to its next
                # park point, then re-check invariants on the new state
                if self.scenario.invariant_step is not None:
                    self._await_parked(chosen)
                    self.scenario.invariant_step(env)
            if not redundant and self.scenario.invariant_final is not None:
                self.scenario.invariant_final(env)
        except InvariantViolation as exc:
            failure = {"type": "invariant", "message": str(exc),
                       "step": len(choices)}
        except Hang as exc:
            failure = {"type": "hang", "message": str(exc),
                       "step": len(choices)}
        except BaseException as exc:  # noqa: BLE001 - reported as failure
            failure = {"type": type(exc).__name__, "message": str(exc),
                       "step": len(choices)}
        finally:
            with self._lock:
                self._abort = True
                self._lock.notify_all()
            for t in threads:
                t.join(timeout=2.0)
        return RunResult(tuple(choices), decisions, failure,
                         redundant=redundant)

    def _await_parked(self, aid: int) -> None:
        """Wait until ``aid`` parked again or finished, so a step
        invariant observes the state AFTER its op, not mid-flight."""
        deadline = time.monotonic() + _STEP_TIMEOUT_S
        with self._lock:
            while (aid not in self._pending
                    and aid not in self._finished):
                if self._errors.get(aid) is not None:
                    return
                if time.monotonic() > deadline:
                    raise Hang(f"A{aid} never re-parked after its step")
                self._lock.wait(0.2)


def run_schedule(scenario: Scenario, prefix: Sequence[int] = (), *,
                 sleep0: Sequence[int] = (), rng=None,
                 max_steps: int = 400) -> RunResult:
    """Execute one schedule (replay ``prefix``, then lex-min policy
    among non-sleeping actors — or seeded-random when ``rng`` is given)
    in a fresh temp journal."""
    tmpdir = tempfile.mkdtemp(prefix="icln-race-")
    try:
        return _Controller(scenario, prefix=prefix, sleep0=sleep0,
                           rng=rng, max_steps=max_steps).run(tmpdir)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


@dataclasses.dataclass
class ExploreResult:
    scenario: str
    bug: Optional[str]
    ok: bool
    schedules: int
    elapsed_s: float
    budget_exhausted: bool = False
    counterexample: Optional[RunResult] = None
    backend: str = "file"

    def render(self) -> str:
        plural = "" if self.schedules == 1 else "s"
        head = (f"{self.scenario}"
                + (f" [bug={self.bug}]" if self.bug else "")
                + (f" [backend={self.backend}]"
                   if self.backend != "file" else "")
                + f": {'ok' if self.ok else 'FAILED'}, "
                + f"{self.schedules} schedule{plural} "
                + f"in {self.elapsed_s:.2f}s"
                + (" (budget exhausted)" if self.budget_exhausted else ""))
        if self.counterexample is None:
            return head
        return head + "\n" + render_counterexample(self.counterexample)


def render_counterexample(res: RunResult) -> str:
    """The minimized failing schedule, numbered step by step — the
    artifact CI uploads and a human replays."""
    out = [f"counterexample: {len(res.choices)} steps, "
           f"{res.context_switches()} context switches, "
           f"schedule={list(res.choices)}"]
    for i, d in enumerate(res.decisions, start=1):
        out.append(f"  step {i:3d}: {d.op.render()}")
    if res.failure is not None:
        out.append(f"  -> {res.failure['type']}: {res.failure['message']}")
    return "\n".join(out)


def minimize(scenario: Scenario, res: RunResult, *,
             max_steps: int = 400, max_passes: int = 8) -> RunResult:
    """Greedy context-switch reduction: repeatedly try extending the
    previous actor's block by one step (replaying the candidate prefix);
    keep any variant that still fails with fewer switches.  Bounded and
    deterministic — a smaller schedule is a nicer artifact, not a
    soundness requirement."""
    best = res
    for _pass in range(max_passes):
        improved = False
        for i in range(1, len(best.choices)):
            if best.choices[i] == best.choices[i - 1]:
                continue
            cand = best.choices[:i] + (best.choices[i - 1],)
            trial = run_schedule(scenario, cand, max_steps=max_steps)
            # the SAME failure must reproduce — a replay divergence or
            # an unrelated error is not a smaller counterexample
            if (trial.failure is not None
                    and trial.failure["type"] == res.failure["type"]
                    and trial.context_switches() < best.context_switches()):
                best = trial
                improved = True
                break
        if not improved:
            break
    return best


def explore(scenario: Scenario, *, mode: str = "dfs",
            max_schedules: int = 2000, max_steps: int = 400,
            seed: int = 0, budget_s: float = 60.0,
            por: bool = True) -> ExploreResult:
    """Explore schedules until a failure, exhaustion (DFS), or budget.

    DFS is stateless-model-checking style: run a schedule to the end,
    then branch on every decision point where another actor was enabled
    (minus POR-pruned branches); the prefix replays deterministically
    because the scenario code is deterministic.  ``mode="random"``
    draws ``max_schedules`` seeded-random schedules instead — the
    depth-beyond-exhaustion mode."""
    t0 = time.monotonic()
    schedules = 0
    budget_exhausted = False

    def out_of_budget() -> bool:
        return (time.monotonic() - t0) > budget_s

    if mode == "random":
        import random

        rng = random.Random(seed)
        seen: Set[Tuple[int, ...]] = set()
        while schedules < max_schedules:
            if out_of_budget():
                budget_exhausted = True
                break
            res = run_schedule(scenario, rng=rng, max_steps=max_steps)
            schedules += 1
            if res.choices in seen:
                continue
            seen.add(res.choices)
            if res.failure is not None:
                res = minimize(scenario, res, max_steps=max_steps)
                return ExploreResult(scenario.name, scenario.bug, False,
                                     schedules,
                                     time.monotonic() - t0,
                                     counterexample=res,
                                     backend=scenario.backend)
        return ExploreResult(scenario.name, scenario.bug, True, schedules,
                             time.monotonic() - t0,
                             budget_exhausted=budget_exhausted,
                             backend=scenario.backend)

    if mode != "dfs":
        raise ValueError(f"unknown mode {mode!r}")
    # stack of (choice prefix, sleep set in force after that prefix);
    # the sleep set (Godefroid-style) holds actors whose next op
    # commutes with every already-explored alternative at the branch
    # node — scheduling them would re-explore the same Mazurkiewicz
    # trace, so the run prunes the subtree (redundant abort)
    stack: List[Tuple[Tuple[int, ...], frozenset]] = [((), frozenset())]
    visited: Set[Tuple[int, ...]] = set()
    while stack and schedules < max_schedules:
        if out_of_budget():
            budget_exhausted = True
            break
        prefix, sleep0 = stack.pop()
        res = run_schedule(scenario, prefix,
                           sleep0=sleep0 if por else (),
                           max_steps=max_steps)
        schedules += 1
        if res.failure is not None:
            res = minimize(scenario, res, max_steps=max_steps)
            return ExploreResult(scenario.name, scenario.bug, False,
                                 schedules, time.monotonic() - t0,
                                 counterexample=res,
                                 backend=scenario.backend)
        for i in range(len(prefix), len(res.decisions)):
            d = res.decisions[i]
            explored = [d.chosen]
            for alt in d.enabled:
                if alt == d.chosen or (por and alt in d.sleep):
                    continue
                branch = res.choices[:i] + (alt,)
                if branch in visited:
                    explored.append(alt)
                    continue
                visited.add(branch)
                if por:
                    # siblings explored before `alt` at this node (and
                    # inherited sleepers) stay asleep in the new branch
                    # iff their op commutes with alt's — dependence
                    # means the orders genuinely differ, so they wake
                    alt_op = d.pending[alt]
                    new_sleep = frozenset(
                        b for b in set(d.sleep) | set(explored)
                        if b in d.pending
                        and d.pending[b].independent(alt_op))
                else:
                    new_sleep = frozenset()
                stack.append((branch, new_sleep))
                explored.append(alt)
    else:
        if stack:
            budget_exhausted = True
    return ExploreResult(scenario.name, scenario.bug, True, schedules,
                         time.monotonic() - t0,
                         budget_exhausted=budget_exhausted,
                         backend=scenario.backend)


# --------------------------------------------------------------------------
# scenarios: the protocol drills and their seeded bugs
# --------------------------------------------------------------------------

def _fsck_step(env: Env) -> None:
    """Every prefix of the journal must satisfy the fsck state machine
    — 'accepted' strictly precedes 'running'/'done' in FILE order, no
    line after terminal, leases monotone.  This is the live bridge
    between the model checker and ``--journal-fsck`` (which handles
    segment directories natively, manifest and shard routing included)."""
    from iterative_cleaner_tpu.analysis.journal_fsck import fsck_journal

    if not os.path.exists(env.path):
        return
    report = fsck_journal(env.path)
    if report.errors:
        raise InvariantViolation(
            "journal fsck failed mid-schedule: "
            + report.errors[0].render())


def _scenario_claim_race(bug: Optional[str]) -> Scenario:
    """Two actors race ``try_claim`` for the same work item: the flock'd
    append order must yield EXACTLY one winner under every interleaving
    of the append and read-back halves."""

    def setup(env: Env) -> None:
        env.data["won"] = {}

    def contender(env: Env, aid: int) -> None:
        if bug == "no-readback":
            # seeded bug: trust the append alone — "my line landed, so
            # the work is mine" — skipping the fold read-back that
            # makes the loser notice it lost
            env.journal.record_claim("w0", host=aid, nonce=f"n{aid}",
                                     ttl_s=1000.0, now=env.clock.now())
            env.data["won"][aid] = True
        else:
            env.data["won"][aid] = env.journal.try_claim(
                "w0", host=aid, nonce=f"n{aid}", ttl_s=1000.0,
                now=env.clock.now())

    def final(env: Env) -> None:
        winners = sorted(a for a, w in env.data["won"].items() if w)
        if len(winners) != 1:
            raise InvariantViolation(
                f"exactly-one-winner violated: winners={winners} "
                f"(each actor's try_claim verdict for the same work)")
        own = env.plain_journal().claim_table(
            now=env.clock.now()).get("w0")
        if own is None or own["nonce"] != f"n{winners[0]}":
            raise InvariantViolation(
                f"fold owner {own and own['nonce']!r} disagrees with "
                f"the try_claim winner n{winners[0]}")

    return Scenario("claim-race", [contender, contender], setup=setup,
                    invariant_step=_fsck_step, invariant_final=final,
                    bug=bug)


def _scenario_admit_order(bug: Optional[str]) -> Scenario:
    """The PR-12 admit-ordering fix, as a machine-checked property: the
    acceptor journals 'accepted' strictly BEFORE the request becomes
    poppable.  The seeded bug re-orders enqueue before the append —
    a fast worker (result-cache hit) then journals 'running'/'done'
    first, the fold reads the finished request as non-terminal forever,
    and a pool peer would adopt and duplicate-clean it."""
    from iterative_cleaner_tpu.serve.request import ServeRequest
    from iterative_cleaner_tpu.serve.scheduler import ServeScheduler

    def setup(env: Env) -> None:
        env.data["sched"] = ServeScheduler(queue_limit=8, max_inflight=4)
        env.data["executed"] = []

    def acceptor(env: Env, aid: int) -> None:
        sched: ServeScheduler = env.data["sched"]
        req = ServeRequest(request_id="r0", paths=["/x.npz"])
        env.step("sched", "write", "slot:r0")
        sched.submit(req, enqueue=False)
        if bug == "admit-order":
            # seeded bug (PR-12 revert): feed the worker queue before
            # the 'accepted' line lands
            env.step("sched", "write", "enqueue:r0")
            sched.enqueue_admitted(req)
            env.journal.record_request("r0", "accepted",
                                       paths=list(req.paths))
        else:
            env.journal.record_request("r0", "accepted",
                                       paths=list(req.paths))
            env.step("sched", "write", "enqueue:r0")
            sched.enqueue_admitted(req)

    def worker(env: Env, aid: int) -> None:
        sched: ServeScheduler = env.data["sched"]
        for _ in range(4):
            env.step("sched", "read", "pop")
            req, _expired = sched.pop(timeout=0)
            if req is None:
                continue
            env.journal.record_request(req.request_id, "running")
            # the "execution" is a result-cache hit: terminal in
            # microseconds — the racy-fast path of the real hazard
            env.journal.record_request(req.request_id, "done")
            env.step("sched", "write", "mark_done")
            sched.mark_done(req)
            env.data["executed"].append(req.request_id)
            return

    def final(env: Env) -> None:
        states = env.plain_journal().request_states()
        for rid in env.data["executed"]:
            state = (states.get(rid) or {}).get("state")
            if state not in ("done", "failed"):
                raise InvariantViolation(
                    f"executed request {rid!r} folds non-terminal "
                    f"({state!r}): it reads as unfinished forever and "
                    f"a pool peer would adopt it — duplicate clean")

    return Scenario("admit-order", [acceptor, worker], setup=setup,
                    invariant_step=_fsck_step, invariant_final=final,
                    bug=bug)


def _scenario_pool_count(bug: Optional[str]) -> Scenario:
    """The PR-12 pool-count fix: admission may CHECK the pool-wide
    tenant view, but the stored in-flight counter stays strictly local
    — it only ever decrements on local mark_done, so folding the pool
    count in inflates it permanently.  Two members admit+finish one
    request each for the same tenant; afterwards every slot must be
    released on both."""
    from iterative_cleaner_tpu.serve.request import ServeRequest
    from iterative_cleaner_tpu.serve.scheduler import ServeScheduler

    def make_sched(env: Env) -> ServeScheduler:
        plain = env.plain_journal()

        def pool_view(tenant: str) -> int:
            from iterative_cleaner_tpu.resilience.journal import (
                REQUEST_TERMINAL,
            )

            states = plain.request_states()
            return sum(1 for v in states.values()
                       if v.get("state") not in REQUEST_TERMINAL
                       and (v.get("tenant") or "default") == tenant)

        sched = ServeScheduler(queue_limit=8, max_inflight=4,
                               pool_inflight=pool_view)
        if bug == "pool-count":
            # seeded bug (PR-12 revert): store the pool-wide EFFECTIVE
            # count (max of local and the journal fold, plus this
            # request) into the local counter at admission — but only
            # local mark_done ever decrements it, so any pool overlap
            # at admission time leaks a slot forever
            real_submit = sched.submit

            def leaky_submit(req, already_journaled=False, enqueue=True):
                with sched._lock:
                    local = sched._inflight.get(req.tenant, 0)
                pool = int(pool_view(req.tenant))
                real_submit(req, already_journaled=already_journaled,
                            enqueue=enqueue)
                with sched._lock:
                    sched._inflight[req.tenant] = max(local, pool) + 1
            sched.submit = leaky_submit
        return sched

    def setup(env: Env) -> None:
        env.data["scheds"] = {}

    def member(env: Env, aid: int) -> None:
        sched = make_sched(env)
        env.data["scheds"][aid] = sched
        rid = f"r{aid}"
        req = ServeRequest(request_id=rid, paths=[f"/{rid}.npz"],
                           tenant="t")
        # the daemon's admission order: slot (checking the pool fold),
        # then the 'accepted' line, then the worker queue.  Each member
        # owns a PRIVATE scheduler (resource "sched:<aid>") — only the
        # journal is shared, and POR knows it
        env.step("journal", "read", "fold:req")
        env.step(f"sched:{aid}", "write", f"slot:{rid}")
        sched.submit(req, enqueue=False)
        env.journal.record_request(rid, "accepted", tenant="t",
                                   paths=list(req.paths))
        sched.enqueue_admitted(req)
        got, _expired = sched.pop(timeout=0)
        if got is not None:
            env.journal.record_request(got.request_id, "running")
            env.journal.record_request(got.request_id, "done")
            env.step(f"sched:{aid}", "write", "mark_done")
            sched.mark_done(got)

    def final(env: Env) -> None:
        for aid, sched in sorted(env.data["scheds"].items()):
            with sched._lock:
                leaked = dict(sched._inflight)
            if leaked:
                raise InvariantViolation(
                    f"member {aid}: tenant in-flight slots leaked after "
                    f"every local mark_done: {leaked} — admission will "
                    f"throw spurious tenant_limit 429s forever")

    return Scenario("pool-count", [member, member], setup=setup,
                    invariant_step=_fsck_step, invariant_final=final,
                    bug=bug)


def _scenario_eviction_edge(bug: Optional[str]) -> Scenario:
    """Member eviction must edge-fire once per incarnation: the watcher
    counts a lapsed member the FIRST time it observes the lapse, and
    repeat scans stay silent.  The seeded bug reverts the edge detector
    (every scan re-reports, inflating ``serve_members_evicted`` and
    re-triggering steal logic)."""
    from iterative_cleaner_tpu.serve.membership import PoolMembership

    ttl = 30.0

    def make_membership(env: Env, member_id: str) -> PoolMembership:
        m = PoolMembership(env.journal, ttl_s=ttl, member_id=member_id,
                           host=1)
        if bug == "eviction-edge":
            # seeded bug: forget the edge — report every lapsed member
            # on every scan
            def lapse_scan(now=None):
                now = env.clock.now() if now is None else now
                table = m.members(now=now)
                return [mid for mid, lease in table.items()
                        if mid != m.member_id and not lease["live"]]
            m.evict_lapsed = lapse_scan
        return m

    def setup(env: Env) -> None:
        env.data["evictions"] = []

    def mortal(env: Env, aid: int) -> None:
        peer = PoolMembership(env.journal, ttl_s=ttl, member_id="mB",
                              host=2)
        peer.join(now=env.clock.now())
        peer.heartbeat(now=env.clock.now() + ttl / 2)
        # ...and dies: no leave line, the lease just stops being fed

    def watcher(env: Env, aid: int) -> None:
        w = make_membership(env, "mA")
        w.join(now=env.clock.now())
        for i in range(4):
            if i == 1:
                env.step("clock", "write", f"advance:{ttl * 2:g}")
                env.clock.advance(ttl * 2)
            env.step("member", "read", "evict-scan")
            got = w.evict_lapsed(now=env.clock.now())
            env.data["evictions"].extend(got)

    def final(env: Env) -> None:
        fired = [m for m in env.data["evictions"] if m == "mB"]
        if len(fired) > 1:
            raise InvariantViolation(
                f"eviction edge fired {len(fired)} times for one "
                f"incarnation of mB — steal/alert logic would re-run "
                f"per scan instead of once")
        # liveness must be bounded by the lease: far enough past the
        # last possible beat, mB folds dead under EVERY schedule (the
        # clock may have advanced before mB joined, so "now" alone is
        # not necessarily past its lease)
        horizon = env.clock.now() + 3.0 * ttl
        roster = env.plain_journal().member_table(now=horizon)
        if roster.get("mB", {}).get("live"):
            raise InvariantViolation("mB still folds live 3 ttls past "
                                     "the last possible heartbeat")

    return Scenario("eviction-edge", [mortal, watcher], setup=setup,
                    invariant_step=_fsck_step, invariant_final=final,
                    bug=bug)


def _scenario_compact_prefix(bug: Optional[str]) -> Scenario:
    """Fold determinism under compaction at any prefix: compacting the
    journal between ANY two steps must leave every fold (requests,
    claims, members) exactly as the uncompacted text folds it.  The
    seeded bug compacts claims down to their last line — a lease whose
    surviving line is a lone 'hb' folds to UNOWNED, so a compaction
    running behind a heartbeat silently un-grants the lease."""

    class _MirroredJournal(InstrumentedJournal):
        """Every append also lands in an append-only MIRROR file that
        compaction never touches — the ground truth the folds of the
        (possibly compacted) real journal are compared against."""

        _mirror: str = ""

        def _append(self, entry: dict) -> None:
            InstrumentedJournal._append(self, entry)
            # icln: ignore[flock-discipline] -- scratch mirror: the cooperative scheduler admits exactly one writer at a time
            with open(self._mirror, "a", encoding="utf-8") as f:
                f.write(json.dumps(entry, sort_keys=True) + "\n")

        def live_lines(self, text, now=None):
            lines = InstrumentedJournal.live_lines(self, text, now=now)
            if bug != "compact-last-claim":
                return lines
            # seeded bug: keep only the LAST claim line per work — a
            # surviving lone 'hb' folds to unowned
            last_claim: Dict[str, str] = {}
            out: List[str] = []
            for ln in lines:
                entry = json.loads(ln)
                if entry.get("event") == "claim":
                    last_claim[entry["work"]] = ln
                else:
                    out.append(ln)
            return out + list(last_claim.values())

    def setup(env: Env) -> None:
        journal = _MirroredJournal(env.path, **env.journal_kwargs)
        journal._mirror = os.path.join(env.tmpdir, "mirror.jsonl")
        journal._env = env
        env.journal = journal

    def worker(env: Env, aid: int) -> None:
        nowf = env.clock.now
        env.journal.record_request("r0", "accepted", tenant="t",
                                   paths=["/a.npz"])
        env.journal.try_claim("req:r0", host=1, nonce="n1",
                              ttl_s=1000.0, now=nowf())
        env.journal.heartbeat("req:r0", host=1, nonce="n1",
                              ttl_s=1000.0, now=nowf() + 1.0)
        env.journal.record_request("r0", "running")

    def compactor(env: Env, aid: int) -> None:
        for _ in range(2):
            env.journal.compact()

    def check_folds(env: Env) -> None:
        _fsck_step(env)
        mirror = getattr(env.journal, "_mirror", "")
        if not mirror or not os.path.exists(mirror):
            return
        # ground truth: fold the append-only mirror (never compacted);
        # the real journal — compacted at whatever prefix the schedule
        # chose — must fold IDENTICALLY
        now = env.clock.now() + 2.0
        truth = FleetJournal(mirror)
        real = env.plain_journal()
        checks = (
            ("request fold", lambda j: j.request_states()),
            ("claim fold", lambda j: j.claim_table(now=now)),
            ("member fold", lambda j: j.member_table(now=now)),
        )
        for name, fold in checks:
            want, got = fold(truth), fold(real)
            if want != got:
                raise InvariantViolation(
                    f"compaction changed the {name}: expected {want!r} "
                    f"from the full history, journal folds {got!r} — "
                    f"a compact must never change what readers see")

    return Scenario("compact-prefix", [worker, compactor], setup=setup,
                    invariant_step=check_folds, invariant_final=check_folds,
                    bug=bug)


_BUILDERS = {
    "claim-race": _scenario_claim_race,
    "admit-order": _scenario_admit_order,
    "pool-count": _scenario_pool_count,
    "eviction-edge": _scenario_eviction_edge,
    "compact-prefix": _scenario_compact_prefix,
}


def build_scenario(name: str, bug: Optional[str] = None,
                   backend: str = "file") -> Scenario:
    """A scenario by name; ``bug`` seeds the named in-memory revert
    (must be one of ``SCENARIOS[name]``); ``backend`` picks the journal
    storage the drill runs against (one of ``BACKENDS``)."""
    if name not in _BUILDERS:
        raise ValueError(
            f"unknown scenario {name!r} (known: {', '.join(sorted(_BUILDERS))})")
    if bug is not None and bug not in SCENARIOS[name]:
        raise ValueError(
            f"scenario {name!r} has no seeded bug {bug!r} "
            f"(known: {', '.join(SCENARIOS[name])})")
    if backend not in BACKENDS:
        raise ValueError(
            f"unknown journal backend {backend!r} "
            f"(known: {', '.join(BACKENDS)})")
    scenario = _BUILDERS[name](bug)
    scenario.backend = backend
    return scenario


def sweep(*, max_schedules: int = 2000, max_steps: int = 400,
          budget_s: float = 60.0, seed: int = 0,
          backends: Sequence[str] = BACKENDS,
          stream=None) -> List[ExploreResult]:
    """The CI gate: exhaustively explore every CLEAN scenario against
    every journal backend (plus a short seeded-random tail for depth)
    within one shared budget.  All results must be ok; any
    counterexample is the caller's artifact."""
    t0 = time.monotonic()
    results: List[ExploreResult] = []
    for name in sorted(SCENARIOS):
        for backend in backends:
            remaining = max(budget_s - (time.monotonic() - t0), 1.0)
            res = explore(build_scenario(name, backend=backend),
                          mode="dfs",
                          max_schedules=max_schedules,
                          max_steps=max_steps,
                          budget_s=remaining, seed=seed)
            if res.ok and not res.budget_exhausted:
                remaining = max(budget_s - (time.monotonic() - t0), 1.0)
                tail = explore(build_scenario(name, backend=backend),
                               mode="random",
                               max_schedules=25, max_steps=max_steps,
                               budget_s=min(remaining, budget_s / 10.0),
                               seed=seed + 1)
                if not tail.ok:
                    res = tail
            results.append(res)
            if stream is not None:
                print(res.render(), file=stream)
    return results

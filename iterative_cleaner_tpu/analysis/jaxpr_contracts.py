"""Structural contracts on the registered hot jit programs.

The dynamic suites prove the programs *compute* the right masks; these
checks pin what the programs *are*, so the planned hot-surface rewrites
(fused Pallas sweep kernel, bf16 mixed precision, operator-graph
refactor — ROADMAP.md) inherit an executable spec instead of a
reviewer's memory:

* **no-host-callbacks** — a `pure_callback`/`io_callback`/debug print
  on the compiled path serialises every dispatch through Python and
  breaks multi-host SPMD;
* **no-f64** — a silent float64 promotion doubles HBM traffic and
  detonates on TPU (which emulates f64 in software);
* **donation-realized** — `donate_argnums=(0, 1)` is only a request;
  if a rewrite breaks the aliasing (shape change, copy inserted), the
  engine silently double-buffers its largest arrays again;
* **dispatch-bound** — total jaxpr equation count stays under a pinned
  ceiling per program, so an accidental `while`→unroll or a
  per-iteration re-trace shows up as a count explosion, not a slow
  production bench three weeks later.

Everything lowers on the CPU backend (`JAX_PLATFORMS=cpu` in CI): the
contracts are structural, not numerical, and identical across backends
except where noted.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

#: primitive-name fragments that mean "the host is on the hot path"
CALLBACK_TOKENS = ("callback", "outside_call", "infeed", "outfeed",
                   "debug_print")

#: dtypes banned on the compiled path (no-f64 contract)
WIDE_DTYPES = ("float64", "complex128")

#: geometry every program is verified at — small enough to trace in
#: milliseconds, large enough that nothing degenerates to scalars
NSUB, NCHAN, NBIN, BATCH = 4, 8, 32, 2


@dataclasses.dataclass(frozen=True)
class ContractViolation:
    program: str
    contract: str
    detail: str

    def render(self) -> str:
        return f"{self.program}: {self.contract}: {self.detail}"


@dataclasses.dataclass
class ProgramReport:
    program: str
    eqn_count: int
    alias_bytes: int
    violations: List[ContractViolation]

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "program": self.program,
            "eqn_count": self.eqn_count,
            "alias_bytes": self.alias_bytes,
            "violations": [dataclasses.asdict(v) for v in self.violations],
        }


def iter_eqns(jaxpr) -> Iterator:
    """Every equation, descending into sub-jaxprs (while/cond/pjit/scan
    bodies) — the callback and dtype contracts must see the whole
    program, not the top-level wrapper's single pjit equation."""
    for eqn in jaxpr.eqns:
        yield eqn
        for value in eqn.params.values():
            items = value if isinstance(value, (list, tuple)) else [value]
            for item in items:
                sub = getattr(item, "jaxpr", None)
                if sub is not None and hasattr(sub, "eqns"):
                    yield from iter_eqns(sub)
                elif hasattr(item, "eqns"):
                    yield from iter_eqns(item)


def check_jaxpr(program: str, closed_jaxpr, *, max_eqns: int,
                allow_f64: bool = False) -> Tuple[int,
                                                  List[ContractViolation]]:
    """Callback / dtype / equation-count contracts on one traced jaxpr."""
    violations: List[ContractViolation] = []
    count = 0
    wide_seen = set()
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        count += 1
        name = eqn.primitive.name
        if any(tok in name for tok in CALLBACK_TOKENS):
            violations.append(ContractViolation(
                program, "no-host-callbacks",
                f"primitive {name!r} puts the host on the compiled "
                "path"))
        if allow_f64:
            continue
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dtype = str(getattr(aval, "dtype", ""))
            if dtype in WIDE_DTYPES and (name, dtype) not in wide_seen:
                wide_seen.add((name, dtype))
                violations.append(ContractViolation(
                    program, "no-f64",
                    f"{dtype} value flows through primitive {name!r}: "
                    "the hot path promised single precision"))
    if count > max_eqns:
        violations.append(ContractViolation(
            program, "dispatch-bound",
            f"{count} equations exceeds the pinned ceiling {max_eqns}: "
            "a loop unrolled or a stage re-traced; re-pin deliberately "
            "if the growth is intended"))
    return count, violations


def check_donation(program: str, lowered, compiled, *,
                   min_alias_bytes: int) -> Tuple[int,
                                                  List[ContractViolation]]:
    """Donation must be *realized*: the compiled artifact actually
    aliases at least the donated weights' bytes input→output (the cube
    half is backend-dependent — CPU refuses the cube alias — so the pin
    is the always-aliasable half)."""
    alias = 0
    try:
        ma = compiled.memory_analysis()
        alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    except Exception:  # icln: ignore[broad-except] -- memory_analysis is optional on some backends; fall through to the lowering-text probe
        alias = 0
    if alias >= min_alias_bytes:
        return alias, []
    # backend lacks memory_analysis (or reports zero): fall back to the
    # StableHLO donation attribute, which the lowering carries even when
    # the runtime analysis is unavailable
    try:
        text = lowered.as_text()
    except Exception:  # icln: ignore[broad-except] -- no text form either; report against the analysis numbers
        text = ""
    if "tf.aliasing_output" in text or "jax.buffer_donor" in text:
        return alias, []
    return alias, [ContractViolation(
        program, "donation-realized",
        f"compiled artifact aliases {alias} bytes (< {min_alias_bytes}): "
        "donate_argnums=(0, 1) no longer takes effect; the engine is "
        "double-buffering its largest arrays")]


def verify_fn(program: str, fn, avals, *, max_eqns: int,
              min_alias_bytes: int = 0,
              allow_f64: bool = False) -> ProgramReport:
    """Trace + lower one jitted callable and run every contract."""
    import jax

    closed = jax.make_jaxpr(fn)(*avals)
    count, violations = check_jaxpr(program, closed, max_eqns=max_eqns,
                                    allow_f64=allow_f64)
    alias = 0
    if min_alias_bytes > 0:
        lowered = fn.lower(*avals)
        compiled = lowered.compile()
        alias, dviol = check_donation(program, lowered, compiled,
                                      min_alias_bytes=min_alias_bytes)
        violations.extend(dviol)
    return ProgramReport(program, count, alias, violations)


def _default_config():
    from iterative_cleaner_tpu.config import CleanConfig

    return CleanConfig()


def _clean_fn_program() -> ProgramReport:
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        build_clean_fn,
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )

    c = _default_config()
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)
    fn = build_clean_fn(
        c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
        c.pulse_scale, c.pulse_region_active, c.rotation, c.baseline_duty,
        c.unload_res, fft_mode, resolve_median_impl(c.median_impl, dtype),
        resolve_stats_impl(c.stats_impl, dtype, NBIN, fft_mode),
        resolve_stats_frame(c.stats_frame, dtype), False, c.baseline_mode,
        donate=True)
    f32 = jnp.float32
    avals = (jax.ShapeDtypeStruct((NSUB, NCHAN, NBIN), f32),
             jax.ShapeDtypeStruct((NSUB, NCHAN), f32),
             jax.ShapeDtypeStruct((NCHAN,), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32))
    weights_bytes = NSUB * NCHAN * 4
    return verify_fn("build_clean_fn", fn, avals, max_eqns=1800,
                     min_alias_bytes=weights_bytes)


def _batched_fn_program() -> ProgramReport:
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.parallel.batch import (
        batch_abstract_inputs,
        build_batched_clean_fn,
        resolve_batch_build_args,
    )

    c = _default_config()
    build_args, _ = resolve_batch_build_args(c, NBIN, False)
    fn = build_batched_clean_fn(*build_args, donate=True)
    avals = batch_abstract_inputs(BATCH, NSUB, NCHAN, NBIN, jnp.float32)
    weights_bytes = BATCH * NSUB * NCHAN * 4
    return verify_fn("build_batched_clean_fn", fn, avals, max_eqns=1900,
                     min_alias_bytes=weights_bytes)


def _online_step_program() -> ProgramReport:
    import jax

    from iterative_cleaner_tpu.online.step import (
        build_subint_step,
        subint_step_avals,
    )

    step, dtype = build_subint_step(_default_config(), NCHAN, NBIN,
                                    False, 0.0)
    avals = subint_step_avals(NCHAN, NBIN, dtype)
    return verify_fn("online_step", jax.jit(step), avals, max_eqns=1400)


def _mux_step_program() -> ProgramReport:
    """The multiplexer's batched per-subint step: the vmapped online
    step at a representative rung.  Beyond the standard hot-program
    contracts (callback-free, no f64, pinned equation ceiling), the
    fused sweep's single-read budget must survive the vmap — the
    batched kernel still reads its (now batch-folded) cube tile ref
    exactly once."""
    import jax

    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.online.step import (
        batched_step_avals,
        build_subint_step,
    )

    # force the fused-sweep route (same knobs as _fused_sweep_program):
    # the mux serves its hottest traffic through this program, and the
    # single-read contract is only meaningful with the sweep in it
    c = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                    fft_mode="dft", median_impl="pallas")
    step, dtype = build_subint_step(c, NCHAN, NBIN, False, 0.0)
    avals = batched_step_avals(BATCH, NCHAN, NBIN, dtype)
    fn = jax.jit(jax.vmap(step))
    report = verify_fn("mux_step", fn, avals, max_eqns=2000)
    closed = jax.make_jaxpr(fn)(*avals)
    reads = _count_cube_ref_reads(closed)
    if reads != [1]:
        report.violations.append(ContractViolation(
            "mux_step", "single-cube-read",
            f"batched sweep kernel read counts {reads}: vmapping the "
            "step must fold the batch into the launch grid and read "
            "the cube tile ref exactly once"))
    return report


def _dma_cube_read_sites(kernel, cube_ref) -> int:
    """DMA-staged read sites on the cube ref: the number of DISTINCT
    VMEM destination buffers that receive ``dma_start`` copies sourced
    from the cube, with var identity tracked through ``cond``
    boundaries (``pl.when`` lowers to cond, and the double-buffered
    fetch's warmup/prefetch starts live in separate branches).

    This is the single-read normalization for a manual DMA pipeline:
    the two syntactic start sites of a double-buffered fetch target ONE
    scratch buffer — each cube byte still crosses the HBM bus exactly
    once — so one destination buffer counts as one read site.  A second
    destination buffer would mean a second staging path (a true second
    read of the cube)."""
    dsts = set()

    def walk(jaxpr, canon):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dma_start" and eqn.invars:
                src = canon.get(eqn.invars[0], eqn.invars[0])
                if src is cube_ref:
                    for v in eqn.invars[1:]:
                        aval = getattr(v, "aval", None)
                        if getattr(aval, "shape", ()) \
                                and str(getattr(aval, "dtype", "")) \
                                != "int16":
                            dsts.add(canon.get(v, v))
                            break
            for branch in eqn.params.get("branches", ()):
                sub = getattr(branch, "jaxpr", branch)
                if not hasattr(sub, "eqns"):
                    continue
                sub_canon = dict(canon)
                # cond: invars[0] is the branch index; the rest align
                # positionally with each branch jaxpr's invars
                for outer, inner in zip(eqn.invars[1:], sub.invars):
                    sub_canon[inner] = canon.get(outer, outer)
                walk(sub, sub_canon)

    walk(kernel, {})
    return len(dsts)


def _count_cube_ref_reads(closed_jaxpr) -> List[int]:
    """Per sweep ``pallas_call``, how many loads its kernel issues on the
    cube tile ref.  Both sweep kernels take the cube ref as kernel invar
    0 (the only rank-3 ref whose last axis is nbin); the read count is
    the number of ``get``-family equations bound to that ref at any
    nesting depth.  A kernel with NO direct loads on the cube ref may
    instead stage it through a manual DMA pipeline (the sharded sweep's
    double-buffered HBM→VMEM fetch): there the count is the number of
    distinct DMA destination buffers (:func:`_dma_cube_read_sites`).
    Returns one count per matching launch."""
    counts = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        kernel = eqn.params.get("jaxpr")
        kernel = getattr(kernel, "jaxpr", kernel)
        if kernel is None or not getattr(kernel, "invars", None):
            continue
        cube_ref = kernel.invars[0]
        shape = getattr(getattr(cube_ref, "aval", None), "shape", ())
        if len(shape) != 3 or shape[0] == 1:
            continue  # not a cube-tiled kernel (cell tables are (1,s,c))
        reads = 0
        for sub in iter_eqns(kernel):
            if sub.primitive.name in ("get", "masked_load", "load") \
                    and sub.invars and sub.invars[0] is cube_ref:
                reads += 1
        if reads == 0:
            reads = _dma_cube_read_sites(kernel, cube_ref)
        counts.append(reads)
    return counts


def _fused_sweep_program() -> ProgramReport:
    """The fused sweep route (--fused-sweep on): the engine program must
    strictly SHRINK against the multi-kernel route it replaces (same
    config, median_impl=pallas — the machinery the sweep absorbs), and
    each sweep kernel must read its cube tile ref exactly ONCE — the
    single-read budget that makes the fusion a bandwidth win, not just a
    launch-count win."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        build_clean_fn,
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.stats import pallas_kernels as pk

    c = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                    fft_mode="dft", median_impl="pallas")
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)

    def build(fused_sweep):
        return build_clean_fn(
            c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
            c.pulse_scale, c.pulse_region_active, c.rotation,
            c.baseline_duty, c.unload_res, fft_mode,
            resolve_median_impl(c.median_impl, dtype),
            resolve_stats_impl(c.stats_impl, dtype, NBIN, fft_mode),
            resolve_stats_frame(c.stats_frame, dtype), False,
            c.baseline_mode, donate=True, fused_sweep=fused_sweep)

    f32 = jnp.float32
    avals = (jax.ShapeDtypeStruct((NSUB, NCHAN, NBIN), f32),
             jax.ShapeDtypeStruct((NSUB, NCHAN), f32),
             jax.ShapeDtypeStruct((NCHAN,), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32))
    fused = jax.make_jaxpr(build("on"))(*avals)
    count, violations = check_jaxpr("fused_sweep", fused, max_eqns=2600)
    unfused_count = sum(1 for _ in iter_eqns(
        jax.make_jaxpr(build("off"))(*avals).jaxpr))
    if count >= unfused_count:
        violations.append(ContractViolation(
            "fused_sweep", "dispatch-bound",
            f"fused program has {count} equations vs {unfused_count} "
            "unfused: the sweep no longer shrinks the per-iteration "
            "program it exists to replace"))
    # single-read budget, proven on BOTH sweep kernels traced standalone
    plane = jax.ShapeDtypeStruct((NSUB, NCHAN), f32)
    mask = jax.ShapeDtypeStruct((NSUB, NCHAN), jnp.bool_)
    row = jax.ShapeDtypeStruct((NBIN,), f32)
    chan_rows = jax.ShapeDtypeStruct((NCHAN, NBIN), f32)
    cube = jax.ShapeDtypeStruct((NSUB, NCHAN, NBIN), f32)
    traced = {
        "fused_sweep_pallas_dedisp": jax.make_jaxpr(
            lambda d, t, win, w, m: pk.fused_sweep_pallas_dedisp(
                d, t, win, w, m, 5.0, 5.0))(cube, row, row, plane, mask),
        "fused_sweep_pallas": jax.make_jaxpr(
            lambda d, rt, nq, t, w, m: pk.fused_sweep_pallas(
                d, rt, nq, t, w, m, 5.0, 5.0))(
                    cube, chan_rows, chan_rows, row, plane, mask),
    }
    for name, closed in traced.items():
        reads = _count_cube_ref_reads(closed)
        if reads != [1]:
            violations.append(ContractViolation(
                "fused_sweep", "single-cube-read",
                f"{name}: expected exactly one sweep kernel reading its "
                f"cube tile ref exactly once, found read counts "
                f"{reads}"))
    return ProgramReport("fused_sweep", count, 0, violations)


def _sharded_sweep_program() -> ProgramReport:
    """The pod-scale sharded fused sweep (--mesh cell --fused-sweep on):
    callback-free, f32-only, donation realized on the sharded program
    (cube + weights donated into the loop carry so the sharded cube
    never re-materialises in HBM), and each per-shard sweep kernel keeps
    the single-cube-read budget — counted through the manual
    double-buffered DMA pipeline (both dma_start sites target ONE VMEM
    scratch buffer) exactly as a BlockSpec load would count.  Verified
    on ``cell_mesh(min(4, n_devices))`` so the selfcheck holds at any
    device count (CI forces 4 CPU devices; a bare interpreter gets 1)."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh
    from iterative_cleaner_tpu.parallel.shard_sweep import (
        sharded_sweep_eligible,
    )
    from iterative_cleaner_tpu.parallel.sharding import (
        build_sharded_clean_fn,
    )
    from iterative_cleaner_tpu.stats import pallas_kernels as pk

    c = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                    fft_mode="dft", median_impl="pallas")
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)
    mesh = cell_mesh(min(4, len(jax.devices())))
    violations: List[ContractViolation] = []
    if not sharded_sweep_eligible(mesh, NSUB, NCHAN, NBIN):
        violations.append(ContractViolation(
            "sharded_sweep", "mesh-eligible",
            f"contract geometry {NSUB}x{NCHAN}x{NBIN} fell off the mesh "
            f"rung on {dict(mesh.shape)}: the verifier no longer "
            "exercises the sharded sweep"))
        return ProgramReport("sharded_sweep", 0, 0, violations)
    fn, cube_sh, w_sh, rep = build_sharded_clean_fn(
        mesh, c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
        c.pulse_scale, c.pulse_region_active, c.rotation, c.baseline_duty,
        fft_mode, resolve_median_impl(c.median_impl, dtype),
        resolve_stats_frame(c.stats_frame, dtype), False,
        resolve_stats_impl(c.stats_impl, dtype, NBIN, fft_mode),
        c.baseline_mode, fused_sweep="on", donate=True)
    f32 = jnp.float32
    avals = (jax.ShapeDtypeStruct((NSUB, NCHAN, NBIN), f32),
             jax.ShapeDtypeStruct((NSUB, NCHAN), f32),
             jax.ShapeDtypeStruct((NCHAN,), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32))
    weights_bytes = NSUB * NCHAN * 4
    report = verify_fn("sharded_sweep", fn, avals, max_eqns=2600,
                       min_alias_bytes=weights_bytes)
    violations.extend(report.violations)
    # single-read budget on the per-shard DMA-pipelined kernels, traced
    # standalone at one shard's local geometry
    s_loc = NSUB // int(mesh.shape["sub"])
    c_loc = NCHAN // int(mesh.shape["chan"])
    plane = jax.ShapeDtypeStruct((s_loc, c_loc), f32)
    mask = jax.ShapeDtypeStruct((s_loc, c_loc), jnp.bool_)
    row = jax.ShapeDtypeStruct((NBIN,), f32)
    chan_rows = jax.ShapeDtypeStruct((c_loc, NBIN), f32)
    cube = jax.ShapeDtypeStruct((s_loc, c_loc, NBIN), f32)
    traced = {
        "sweep_shard_diags_dedisp": jax.make_jaxpr(
            lambda d, t, win, w, m: pk.sweep_shard_diags_dedisp(
                d, t, win, w, m, dma=True))(cube, row, row, plane, mask),
        "sweep_shard_diags_disp": jax.make_jaxpr(
            lambda d, rt, nq, t, w, m: pk.sweep_shard_diags_disp(
                d, rt, nq, t, w, m, dma=True))(
                    cube, chan_rows, chan_rows, row, plane, mask),
    }
    for name, closed in traced.items():
        reads = _count_cube_ref_reads(closed)
        if reads != [1]:
            violations.append(ContractViolation(
                "sharded_sweep", "single-cube-read",
                f"{name}: expected exactly one per-shard kernel reading "
                f"(or DMA-staging) its cube ref exactly once, found read "
                f"counts {reads}"))
    return ProgramReport("sharded_sweep", report.eqn_count,
                         report.alias_bytes, violations)


def _cube_pallas_read_bytes(closed_jaxpr) -> int:
    """Deterministic cube-traffic measure: over every cube-tiled
    ``pallas_call`` (same launch filter as :func:`_count_cube_ref_reads`),
    read sites x the cube ref's block aval bytes.  Trace-level and
    platform-independent — unlike ``cost_analysis()``, whose CPU
    numbers can ATTRIBUTE the bf16→f32 convert as extra traffic — so the
    bf16 storage win (half the bytes per read site) is assertable in CI
    on any backend."""
    total = 0
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        kernel = eqn.params.get("jaxpr")
        kernel = getattr(kernel, "jaxpr", kernel)
        if kernel is None or not getattr(kernel, "invars", None):
            continue
        cube_ref = kernel.invars[0]
        aval = getattr(cube_ref, "aval", None)
        shape = getattr(aval, "shape", ())
        if len(shape) != 3 or shape[0] == 1:
            continue
        reads = 0
        for sub in iter_eqns(kernel):
            if sub.primitive.name in ("get", "masked_load", "load") \
                    and sub.invars and sub.invars[0] is cube_ref:
                reads += 1
        if reads == 0:
            reads = _dma_cube_read_sites(kernel, cube_ref)
        import numpy as np

        nbytes = int(np.prod(shape)) * np.dtype(aval.dtype).itemsize
        total += reads * nbytes
    return total


def _fused_sweep_bf16_program() -> ProgramReport:
    """The mixed-precision hot program (--compute-dtype bfloat16
    --fused-sweep on): everything the fp32 fused program promises —
    callback-free, no f64, pinned equation ceiling, single-cube-read —
    PLUS the storage contract: the sweep kernel's cube operand aval is
    bfloat16 (the fp32 upcast happens per staged tile inside the kernel
    body, never in HBM), so the trace-level cube read bytes land at
    half the fp32 program's."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        build_clean_fn,
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.config import CleanConfig

    c = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                    fft_mode="dft", median_impl="pallas",
                    compute_dtype="bfloat16")
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)

    def build(compute_dtype):
        return build_clean_fn(
            c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
            c.pulse_scale, c.pulse_region_active, c.rotation,
            c.baseline_duty, c.unload_res, fft_mode,
            resolve_median_impl(c.median_impl, dtype),
            resolve_stats_impl(c.stats_impl, dtype, NBIN, fft_mode),
            resolve_stats_frame(c.stats_frame, dtype), False,
            c.baseline_mode, donate=True, fused_sweep="on",
            compute_dtype=compute_dtype)

    f32 = jnp.float32
    avals = (jax.ShapeDtypeStruct((NSUB, NCHAN, NBIN), f32),
             jax.ShapeDtypeStruct((NSUB, NCHAN), f32),
             jax.ShapeDtypeStruct((NCHAN,), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32))
    closed = jax.make_jaxpr(build("bfloat16"))(*avals)
    count, violations = check_jaxpr("fused_sweep_bf16", closed,
                                    max_eqns=2600)
    # the full engine program holds TWO cube-tiled launches per
    # iteration — the template marginals pass and the sweep — and each
    # must read its cube tile ref exactly once
    reads = _count_cube_ref_reads(closed)
    if not reads or any(r != 1 for r in reads):
        violations.append(ContractViolation(
            "fused_sweep_bf16", "single-cube-read",
            f"every cube-tiled kernel must read its cube tile ref "
            f"exactly once, found read counts {reads}"))
    # the storage contract: the sweep kernel's cube operand is bf16
    cube_dtypes = []
    for eqn in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        kernel = eqn.params.get("jaxpr")
        kernel = getattr(kernel, "jaxpr", kernel)
        if kernel is None or not getattr(kernel, "invars", None):
            continue
        aval = getattr(kernel.invars[0], "aval", None)
        shape = getattr(aval, "shape", ())
        if len(shape) == 3 and shape[0] != 1:
            cube_dtypes.append(str(aval.dtype))
    if not cube_dtypes or set(cube_dtypes) != {"bfloat16"}:
        violations.append(ContractViolation(
            "fused_sweep_bf16", "bf16-cube-storage",
            f"cube-tiled kernel operand dtypes {cube_dtypes}: the "
            "mixed-precision program must hand every cube-reading "
            "kernel a bfloat16 HBM cube and upcast inside the body"))
    bf16_bytes = _cube_pallas_read_bytes(closed)
    f32_bytes = _cube_pallas_read_bytes(jax.make_jaxpr(
        build("float32"))(*avals))
    if not (0 < bf16_bytes <= 0.6 * f32_bytes):
        violations.append(ContractViolation(
            "fused_sweep_bf16", "cube-bytes-ratio",
            f"trace-level sweep cube read bytes {bf16_bytes} vs fp32 "
            f"{f32_bytes}: bf16 storage must at least halve the cube "
            "bytes per iteration (ratio <= 0.6)"))
    return ProgramReport("fused_sweep_bf16", count, 0, violations)


def _mesh_padded_sweep_program() -> ProgramReport:
    """The pad-and-crop rung of the sharded path: a deliberately
    mesh-indivisible cell grid, padded exactly as
    :func:`~iterative_cleaner_tpu.parallel.sharding.clean_cube_sharded`
    pads it, must still build the ONE-LAUNCH sharded sweep program and
    honour every hot-program contract at the padded geometry."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh
    from iterative_cleaner_tpu.parallel.shard_stats import shard_divisible
    from iterative_cleaner_tpu.parallel.shard_sweep import (
        sharded_sweep_eligible,
    )
    from iterative_cleaner_tpu.parallel.sharding import (
        build_sharded_clean_fn,
    )

    c = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                    fft_mode="dft", median_impl="pallas")
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)
    mesh = cell_mesh(min(4, len(jax.devices())))
    ssub, schan = int(mesh.shape["sub"]), int(mesh.shape["chan"])
    # one row / one channel past the contract geometry: indivisible on
    # every mesh with an axis > 1, exactly divisible after the pad
    raw_s, raw_c = NSUB + 1, NCHAN + 1
    pad_s, pad_c = (-raw_s) % ssub, (-raw_c) % schan
    ps, pc = raw_s + pad_s, raw_c + pad_c
    violations: List[ContractViolation] = []
    if not shard_divisible(mesh, ps, pc):
        violations.append(ContractViolation(
            "mesh_padded_sweep", "pad-geometry",
            f"padded grid {ps}x{pc} is still indivisible on "
            f"{dict(mesh.shape)}: the pad arithmetic drifted from "
            "clean_cube_sharded's"))
        return ProgramReport("mesh_padded_sweep", 0, 0, violations)
    if not sharded_sweep_eligible(mesh, ps, pc, NBIN):
        violations.append(ContractViolation(
            "mesh_padded_sweep", "mesh-eligible",
            f"padded geometry {ps}x{pc}x{NBIN} fell off the mesh rung "
            f"on {dict(mesh.shape)}: padding no longer rescues the "
            "one-launch sweep"))
        return ProgramReport("mesh_padded_sweep", 0, 0, violations)
    fn, cube_sh, w_sh, rep = build_sharded_clean_fn(
        mesh, c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
        c.pulse_scale, c.pulse_region_active, c.rotation, c.baseline_duty,
        fft_mode, resolve_median_impl(c.median_impl, dtype),
        resolve_stats_frame(c.stats_frame, dtype), False,
        resolve_stats_impl(c.stats_impl, dtype, NBIN, fft_mode),
        c.baseline_mode, fused_sweep="on", donate=True)
    f32 = jnp.float32
    avals = (jax.ShapeDtypeStruct((ps, pc, NBIN), f32),
             jax.ShapeDtypeStruct((ps, pc), f32),
             jax.ShapeDtypeStruct((pc,), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32),
             jax.ShapeDtypeStruct((), f32))
    report = verify_fn("mesh_padded_sweep", fn, avals, max_eqns=2600,
                       min_alias_bytes=ps * pc * 4)
    violations.extend(report.violations)
    return ProgramReport("mesh_padded_sweep", report.eqn_count,
                         report.alias_bytes, violations)


#: the registered hot programs — every builder whose output owns a
#: steady-state dispatch loop must appear here (the shardmap builder is
#: covered through build_batched_clean_fn, which it jit-wraps 1:1)
HOT_PROGRAMS = (
    ("build_clean_fn", _clean_fn_program),
    ("build_batched_clean_fn", _batched_fn_program),
    ("online_step", _online_step_program),
    ("mux_step", _mux_step_program),
    ("fused_sweep", _fused_sweep_program),
    ("fused_sweep_bf16", _fused_sweep_bf16_program),
    ("sharded_sweep", _sharded_sweep_program),
    ("mesh_padded_sweep", _mesh_padded_sweep_program),
)


def verify_hot_programs(names: Optional[List[str]] = None) \
        -> List[ProgramReport]:
    reports = []
    for name, make in HOT_PROGRAMS:
        if names and name not in names:
            continue
        try:
            reports.append(make())
        except Exception as exc:
            reports.append(ProgramReport(name, 0, 0, [ContractViolation(
                name, "verifier-error",
                f"{type(exc).__name__}: {exc}")]))
    return reports

"""`icln-lint` console entry point and the --selfcheck driver.

Exit codes: 0 clean (suppressed findings allowed), 1 unsuppressed
findings or jaxpr contract violations, 2 usage/internal error — so CI
can gate on the bare exit status.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from iterative_cleaner_tpu.analysis.core import (
    LintReport,
    lint_paths,
    record_findings,
    report_json,
)


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="icln-lint",
        description="Project-invariant static analyzer for "
                    "iterative_cleaner_tpu (AST rules + jaxpr contract "
                    "verifier). Zero unsuppressed findings = exit 0.")
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint (default: the "
                        "installed iterative_cleaner_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="finding output format (default: text)")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr contract verifier (AST rules "
                        "only; the default when explicit paths are "
                        "given)")
    p.add_argument("--jaxpr", action="store_true",
                   help="force the jaxpr contract verifier even with "
                        "explicit paths")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    return p


def run_selfcheck(*, paths: Optional[Sequence[str]] = None,
                  fmt: str = "text", jaxpr: bool = True,
                  show_suppressed: bool = False,
                  registry=None, stream=None) -> int:
    """Lint + (optionally) verify the jaxpr contracts; render a report.

    ``registry`` receives ``lint_findings{rule=...}`` counters when
    given, so the serve daemon and the --precompile session export
    analyzer results alongside their run metrics."""
    out = stream if stream is not None else sys.stdout
    report = lint_paths(paths)
    program_reports = []
    if jaxpr:
        from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
            verify_hot_programs,
        )

        program_reports = verify_hot_programs()
    violations = [v for r in program_reports for v in r.violations]
    if registry is not None:
        record_findings(registry, report)
        from iterative_cleaner_tpu.telemetry.registry import labeled

        for v in violations:
            registry.counter_inc(labeled("lint_findings",
                                         rule="jaxpr-" + v.contract))
        if jaxpr:
            registry.gauge_set("jaxpr_contract_violations",
                               len(violations))
    ok = report.ok and not violations
    if fmt == "json":
        print(report_json(report, {
            "jaxpr": [r.to_dict() for r in program_reports],
            "ok": ok,
        }), file=out)
    else:
        text = report.render_text(show_suppressed=show_suppressed)
        if text:
            print(text, file=out)
        for r in program_reports:
            status = "ok" if r.ok else "FAIL"
            print(f"jaxpr {r.program}: {status} "
                  f"({r.eqn_count} eqns, alias {r.alias_bytes} B)",
                  file=out)
            for v in r.violations:
                print("  " + v.render(), file=out)
    return 0 if ok else 1


def record_package_lint(registry, *, quiet: bool = True):
    """AST-lint the installed package straight into a registry — no jaxpr
    pass, so it costs ~a second at daemon/precompile startup.  Serve's
    live ``/metrics`` and the --precompile session's exporters then carry
    ``lint_findings{rule=...}`` / ``lint_ok`` for the build that is
    actually running.  Never raises: an analyzer crash must not take the
    daemon down (it is counted as ``lint_run_errors``)."""
    try:
        report = lint_paths()
        record_findings(registry, report)
        if not report.ok and not quiet:
            print("WARNING: icln-lint: %d unsuppressed finding(s) in the "
                  "running build; run --selfcheck for details"
                  % len(report.unsuppressed), file=sys.stderr)
        return report
    except Exception as exc:  # icln: ignore[broad-except] -- startup analyzer pass is advisory: counted, warned, never fatal to the daemon
        registry.counter_inc("lint_run_errors")
        if not quiet:
            print(f"WARNING: icln-lint startup pass failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    jaxpr = not args.no_jaxpr if not args.paths else args.jaxpr
    if args.jaxpr and args.no_jaxpr:
        build_arg_parser().error("--jaxpr and --no-jaxpr conflict")
    try:
        return run_selfcheck(paths=args.paths or None, fmt=args.format,
                             jaxpr=jaxpr,
                             show_suppressed=args.show_suppressed)
    except (OSError, SyntaxError) as exc:
        print(f"icln-lint: error: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

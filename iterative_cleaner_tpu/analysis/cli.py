"""`icln-lint` console entry point and the --selfcheck driver.

Besides the AST rules and the jaxpr contract verifier, two concurrency
gates live here: ``--journal-fsck PATH`` validates an on-disk fleet
journal against the protocol state machine
(:mod:`~iterative_cleaner_tpu.analysis.journal_fsck`), and
``--race-sweep`` runs the deterministic interleaving model checker
(:mod:`~iterative_cleaner_tpu.analysis.interleave`) over every protocol
scenario — a failing schedule is minimized and written to
``--race-out`` as the CI artifact.

Exit codes: 0 clean (suppressed findings allowed), 1 unsuppressed
findings, contract violations, fsck errors or a race counterexample,
2 usage/internal error — so CI can gate on the bare exit status.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

from iterative_cleaner_tpu.analysis.core import (
    LintReport,
    lint_paths,
    record_findings,
    report_json,
)


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="icln-lint",
        description="Project-invariant static analyzer for "
                    "iterative_cleaner_tpu (AST rules + jaxpr contract "
                    "verifier). Zero unsuppressed findings = exit 0.")
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint (default: the "
                        "installed iterative_cleaner_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="finding output format (default: text)")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr contract verifier (AST rules "
                        "only; the default when explicit paths are "
                        "given)")
    p.add_argument("--jaxpr", action="store_true",
                   help="force the jaxpr contract verifier even with "
                        "explicit paths")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--journal-fsck", action="append", default=[],
                   metavar="JOURNAL",
                   help="validate a fleet journal — a file, or a "
                        "segmented journal directory (manifest grammar "
                        "+ every segment + shard routing) — against "
                        "the protocol state machine (grammar, request "
                        "lifecycle, lease monotonicity, torn tail); "
                        "repeatable; standalone — skips the lint pass")
    p.add_argument("--race-sweep", action="store_true",
                   help="run the deterministic interleaving model "
                        "checker over every journal-lease protocol "
                        "scenario (exhaustive DFS + seeded random "
                        "tail); standalone — skips the lint pass")
    p.add_argument("--race-schedules", type=int, default=5000,
                   help="max schedules explored per scenario "
                        "(default: 5000)")
    p.add_argument("--race-budget", type=float, default=None,
                   help="wall-clock budget in seconds for the whole "
                        "sweep (default: $ICLEAN_RACE_BUDGET_S or 120)")
    p.add_argument("--race-seed", type=int, default=0,
                   help="seed for the bounded-random tail (default: 0)")
    p.add_argument("--race-out", metavar="PATH", default=None,
                   help="write the minimized counterexample schedule "
                        "here when the sweep fails (the CI artifact)")
    return p


def run_journal_fsck(paths: Sequence[str], *, fmt: str = "text",
                     stream=None, registry=None) -> int:
    """Fsck each journal; exit 0 only when every one is error-free."""
    out = stream if stream is not None else sys.stdout
    from iterative_cleaner_tpu.analysis.journal_fsck import (
        fsck_journal,
        record_fsck,
    )

    ok = True
    reports = []
    for path in paths:
        report = fsck_journal(path)
        reports.append(report)
        ok = ok and report.ok
        if registry is not None:
            record_fsck(registry, report)
        if fmt != "json":
            print(report.render_text(), file=out)
    if fmt == "json":
        import json

        print(json.dumps({"ok": ok,
                          "journals": [r.to_dict() for r in reports]},
                         indent=2, sort_keys=True), file=out)
    return 0 if ok else 1


def run_race_sweep(*, max_schedules: int = 5000,
                   budget_s: Optional[float] = None, seed: int = 0,
                   out_path: Optional[str] = None, stream=None) -> int:
    """Model-check every clean protocol scenario; on failure, write the
    minimized counterexample schedule to ``out_path``."""
    out = stream if stream is not None else sys.stdout
    if budget_s is None:
        budget_s = float(os.environ.get("ICLEAN_RACE_BUDGET_S", "120"))
    from iterative_cleaner_tpu.analysis.interleave import sweep

    results = sweep(max_schedules=max_schedules, budget_s=budget_s,
                    seed=seed, stream=out)
    failed = [r for r in results if not r.ok]
    if failed and out_path:
        from iterative_cleaner_tpu.io.atomic import atomic_output

        with atomic_output(out_path) as tmp:
            with open(tmp, "w", encoding="utf-8") as f:
                for r in failed:
                    f.write(r.render() + "\n")
        print(f"race-sweep: counterexample written to {out_path}",
              file=out)
    if not failed and all(not r.budget_exhausted for r in results):
        print("race-sweep: all scenarios explored exhaustively",
              file=out)
    return 0 if not failed else 1


def run_selfcheck(*, paths: Optional[Sequence[str]] = None,
                  fmt: str = "text", jaxpr: bool = True,
                  show_suppressed: bool = False,
                  journal_fsck: Sequence[str] = (),
                  registry=None, stream=None) -> int:
    """Lint + (optionally) verify the jaxpr contracts; render a report.

    ``journal_fsck`` paths are additionally validated against the
    journal state machine and count toward the exit status.
    ``registry`` receives ``lint_findings{rule=...}`` counters when
    given, so the serve daemon and the --precompile session export
    analyzer results alongside their run metrics."""
    out = stream if stream is not None else sys.stdout
    fsck_rc = 0
    if journal_fsck:
        fsck_rc = run_journal_fsck(journal_fsck, fmt="text", stream=out,
                                   registry=registry)
    report = lint_paths(paths)
    program_reports = []
    if jaxpr:
        from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
            verify_hot_programs,
        )

        program_reports = verify_hot_programs()
    violations = [v for r in program_reports for v in r.violations]
    if registry is not None:
        record_findings(registry, report)
        from iterative_cleaner_tpu.telemetry.registry import labeled

        for v in violations:
            registry.counter_inc(labeled("lint_findings",
                                         rule="jaxpr-" + v.contract))
        if jaxpr:
            registry.gauge_set("jaxpr_contract_violations",
                               len(violations))
    ok = report.ok and not violations and fsck_rc == 0
    if fmt == "json":
        print(report_json(report, {
            "jaxpr": [r.to_dict() for r in program_reports],
            "ok": ok,
        }), file=out)
    else:
        text = report.render_text(show_suppressed=show_suppressed)
        if text:
            print(text, file=out)
        for r in program_reports:
            status = "ok" if r.ok else "FAIL"
            print(f"jaxpr {r.program}: {status} "
                  f"({r.eqn_count} eqns, alias {r.alias_bytes} B)",
                  file=out)
            for v in r.violations:
                print("  " + v.render(), file=out)
    return 0 if ok else 1


def record_package_lint(registry, *, quiet: bool = True):
    """AST-lint the installed package straight into a registry — no jaxpr
    pass, so it costs ~a second at daemon/precompile startup.  Serve's
    live ``/metrics`` and the --precompile session's exporters then carry
    ``lint_findings{rule=...}`` / ``lint_ok`` for the build that is
    actually running.  Never raises: an analyzer crash must not take the
    daemon down (it is counted as ``lint_run_errors``)."""
    try:
        report = lint_paths()
        record_findings(registry, report)
        if not report.ok and not quiet:
            print("WARNING: icln-lint: %d unsuppressed finding(s) in the "
                  "running build; run --selfcheck for details"
                  % len(report.unsuppressed), file=sys.stderr)
        return report
    except Exception as exc:  # icln: ignore[broad-except] -- startup analyzer pass is advisory: counted, warned, never fatal to the daemon
        registry.counter_inc("lint_run_errors")
        if not quiet:
            print(f"WARNING: icln-lint startup pass failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    jaxpr = not args.no_jaxpr if not args.paths else args.jaxpr
    if args.jaxpr and args.no_jaxpr:
        build_arg_parser().error("--jaxpr and --no-jaxpr conflict")
    if (args.journal_fsck or args.race_sweep) and args.paths:
        build_arg_parser().error(
            "--journal-fsck/--race-sweep are standalone gates and take "
            "no lint paths")
    try:
        if args.journal_fsck or args.race_sweep:
            rc = 0
            if args.journal_fsck:
                rc = max(rc, run_journal_fsck(args.journal_fsck,
                                              fmt=args.format))
            if args.race_sweep:
                rc = max(rc, run_race_sweep(
                    max_schedules=args.race_schedules,
                    budget_s=args.race_budget, seed=args.race_seed,
                    out_path=args.race_out))
            return rc
        return run_selfcheck(paths=args.paths or None, fmt=args.format,
                             jaxpr=jaxpr,
                             show_suppressed=args.show_suppressed)
    except (OSError, SyntaxError) as exc:
        print(f"icln-lint: error: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())

"""Rule framework for icln-lint.

A :class:`Rule` inspects one parsed file and yields findings; a
:class:`RepoRule` sees the whole repository at once (cross-file
invariants like env/flag drift).  Findings carry a stable rule id and a
severity, and any finding can be silenced in place with::

    something_flagged()  # icln: ignore[rule-id] -- short reason

on the finding's line or the line directly above it (comma-separate ids
to silence several rules at one site).  Suppressed findings stay in the
report — they are counted separately (``lint_suppressed{rule=...}``)
so a suppression creep shows up on /metrics — but they do not fail the
``--selfcheck`` gate.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")

#: package subtree the default lint pass covers
PACKAGE_NAME = "iterative_cleaner_tpu"

_SUPPRESS_RE = re.compile(
    r"#\s*icln:\s*ignore\[([A-Za-z0-9_\-,\s]+)\]"
    r"(?:\s*--\s*(?P<reason>.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, suppressed or not."""

    rule: str
    severity: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""   # the suppression's ``-- reason`` text, if any

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if not self.suppressed:
            del d["reason"]
        return d

    def render(self) -> str:
        mark = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.rule}] {self.message}{mark}")


def parse_suppressions(source: str) -> Dict[int, Tuple[set, str]]:
    """Map line number -> (rule ids silenced there, reason text)."""
    out: Dict[int, Tuple[set, str]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
        out[lineno] = (rules, (m.group("reason") or "").strip())
    return out


class FileContext:
    """One source file: path, text, parsed tree (with parent links)."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.suppressions = parse_suppressions(source)
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(source, filename=rel)
        except SyntaxError as exc:
            self.tree = None
            self.parse_error = str(exc)
        if self.tree is not None:
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    child._icln_parent = node  # type: ignore[attr-defined]

    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_icln_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_icln_parent", None)

    def enclosing_function(self, node: ast.AST):
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return p
        return None


class RepoContext:
    """The whole checkout: every package FileContext plus the doc files
    cross-file rules diff against (absent docs disable those rules —
    an installed wheel has no README to drift from)."""

    def __init__(self, root: str, files: Sequence[FileContext]):
        self.root = root
        self.files = list(files)
        self.docs: Dict[str, str] = {}
        for name in ("README.md", "MIGRATION.md", "ARCHITECTURE.md"):
            p = os.path.join(root, name)
            if os.path.isfile(p):
                with open(p, encoding="utf-8", errors="replace") as f:
                    self.docs[name] = f.read()

    def file(self, rel: str) -> Optional[FileContext]:
        rel = rel.replace(os.sep, "/")
        for ctx in self.files:
            if ctx.rel == rel or ctx.rel.endswith("/" + rel):
                return ctx
        return None


class Rule:
    """Per-file rule: subclass and implement :meth:`check`."""

    id: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        raise NotImplementedError

    def findings(self, ctx: FileContext) -> Iterator[Finding]:
        for line, message in self.check(ctx):
            yield _resolve(self, ctx, line, message)


class RepoRule(Rule):
    """Cross-file rule: sees the whole :class:`RepoContext`."""

    def check_repo(self, repo: RepoContext) \
            -> Iterable[Tuple[FileContext, int, str]]:
        raise NotImplementedError

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        return ()

    def repo_findings(self, repo: RepoContext) -> Iterator[Finding]:
        for ctx, line, message in self.check_repo(repo):
            yield _resolve(self, ctx, line, message)


def _resolve(rule: Rule, ctx: FileContext, line: int, message: str) -> Finding:
    """Apply the file's suppression comments to one raw finding."""
    for probe in (line, line - 1):
        entry = ctx.suppressions.get(probe)
        if entry and rule.id in entry[0]:
            return Finding(rule.id, rule.severity, ctx.rel, line, message,
                           suppressed=True, reason=entry[1])
    return Finding(rule.id, rule.severity, ctx.rel, line, message)


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]
    files_scanned: int
    parse_errors: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.unsuppressed and not self.parse_errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.to_dict() for f in self.unsuppressed],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "parse_errors": [{"path": p, "error": e}
                             for p, e in self.parse_errors],
        }

    def render_text(self, *, show_suppressed: bool = False) -> str:
        out = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line)):
            if f.suppressed and not show_suppressed:
                continue
            out.append(f.render())
        for path, err in self.parse_errors:
            out.append(f"{path}:0: error [parse] {err}")
        out.append("%d file%s scanned: %d finding%s, %d suppressed"
                   % (self.files_scanned,
                      "" if self.files_scanned == 1 else "s",
                      len(self.unsuppressed),
                      "" if len(self.unsuppressed) == 1 else "s",
                      len(self.suppressed)))
        return "\n".join(out)


def default_rules() -> List[Rule]:
    from iterative_cleaner_tpu.analysis import (
        rules_io,
        rules_jit,
        rules_project,
        rules_threads,
    )

    return [
        rules_io.AtomicWriteRule(),
        rules_io.FlockDisciplineRule(),
        rules_io.LockOrderRule(),
        rules_jit.JitPurityRule(),
        rules_jit.StaticHashableRule(),
        rules_jit.DonationSafetyRule(),
        rules_project.BroadExceptRule(),
        rules_project.ConfigIdentityRule(),
        rules_project.EnvDriftRule(),
        rules_project.FlagDocsRule(),
        rules_threads.ThreadSharedStateRule(),
        rules_threads.ThreadLockOrderRule(),
        rules_threads.JournalClaimRule(),
    ]


def find_repo_root(start: Optional[str] = None) -> str:
    """The directory that holds the ``iterative_cleaner_tpu`` package."""
    here = start or os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    if os.path.basename(here) == PACKAGE_NAME:
        return os.path.dirname(here)
    return here


def iter_python_files(root: str) -> Iterator[str]:
    pkg = os.path.join(root, PACKAGE_NAME)
    base = pkg if os.path.isdir(pkg) else root
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _load(path: str, root: str) -> FileContext:
    rel = os.path.relpath(path, root)
    with open(path, encoding="utf-8", errors="replace") as f:
        return FileContext(path, rel, f.read())


def lint_paths(paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None,
               rules: Optional[Sequence[Rule]] = None) -> LintReport:
    """Lint files (default: the whole package) and return a report."""
    root = os.path.abspath(root or find_repo_root())
    if paths:
        targets: List[str] = []
        for p in paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(d for d in dirnames
                                         if d not in ("__pycache__", ".git"))
                    targets.extend(os.path.join(dirpath, n)
                                   for n in sorted(filenames)
                                   if n.endswith(".py"))
            else:
                targets.append(p)
    else:
        targets = list(iter_python_files(root))
    files = [_load(p, root) for p in targets]
    return lint_files(files, root, rules)


def lint_files(files: Sequence[FileContext], root: str,
               rules: Optional[Sequence[Rule]] = None) -> LintReport:
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    parse_errors = [(ctx.rel, ctx.parse_error) for ctx in files
                    if ctx.parse_error]
    for rule in rules:
        if isinstance(rule, RepoRule):
            continue
        for ctx in files:
            if ctx.tree is None:
                continue
            findings.extend(rule.findings(ctx))
    repo = RepoContext(root, files)
    for rule in rules:
        if isinstance(rule, RepoRule):
            findings.extend(rule.repo_findings(repo))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintReport(findings, files_scanned=len(files),
                      parse_errors=parse_errors)


def lint_source(source: str, *, rel: str = "snippet.py",
                rules: Optional[Sequence[Rule]] = None,
                root: Optional[str] = None) -> LintReport:
    """Lint one in-memory snippet (the unit-test entry point).  Repo
    rules are skipped unless an explicit ``root`` provides the docs and
    sibling files they diff against."""
    ctx = FileContext(rel, rel, source)
    use = [r for r in (rules if rules is not None else default_rules())
           if root is not None or not isinstance(r, RepoRule)]
    return lint_files([ctx], root or os.getcwd(), use)


def record_findings(registry, report: LintReport) -> None:
    """Publish a report into a MetricsRegistry: ``lint_findings{rule=r}``
    per unsuppressed finding, ``lint_suppressed{rule=r}`` per suppressed
    one, plus ``lint_files_scanned`` — the counters serve's /metrics and
    the --prom-textfile/--metrics-json exporters pick up."""
    from iterative_cleaner_tpu.telemetry.registry import labeled

    registry.gauge_set("lint_files_scanned", report.files_scanned)
    registry.gauge_set("lint_ok", 1 if report.ok else 0)
    for f in report.findings:
        name = "lint_suppressed" if f.suppressed else "lint_findings"
        registry.counter_inc(labeled(name, rule=f.rule))


def report_json(report: LintReport, extra: Optional[dict] = None) -> str:
    d = report.to_dict()
    if extra:
        d.update(extra)
    return json.dumps(d, indent=2, sort_keys=True)

"""Filesystem-discipline rules: atomic writes, flock'd appends, lock order.

The project's durability story rests on two chokepoints:

* every output file lands via ``io/atomic.py``'s :func:`atomic_output`
  (same-directory temp + ``os.replace``), so a kill -9 never leaves a
  torn file where a consumer expects a whole one;
* every shared append (journal, spool ledger, clean log) goes through
  ``utils/logging.py``'s ``locked_append``/``compact_under_lock``
  (flock + inode-swap recheck), so concurrent hosts never interleave
  partial records.

These rules make bypassing either chokepoint a lint error.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from iterative_cleaner_tpu.analysis.core import FileContext, Rule

#: the sanctioned implementation sites (repo-relative suffixes)
ATOMIC_IMPL = ("io/atomic.py",)
FLOCK_IMPL = ("utils/logging.py",)

#: helpers that take the per-file flock internally
LOCK_HELPERS = frozenset({
    "locked_append", "compact_under_lock", "seal_log", "trim_log",
    "rotate_log", "append_clean_log",
})


def _is_impl(ctx: FileContext, suffixes) -> bool:
    return any(ctx.rel.endswith(s) for s in suffixes)


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an Attribute/Name chain ('' when not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _open_mode(call: ast.Call) -> str:
    """The literal mode string of an ``open()`` call, or '' if unknown."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return ""


def _atomic_output_names(tree: ast.AST) -> List[Tuple[str, int, int]]:
    """(name, first_line, last_line) for every ``with atomic_output(...)
    as NAME`` block — writes to NAME inside the block are sanctioned."""
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            chain = _attr_chain(call.func)
            if chain.split(".")[-1] != "atomic_output":
                continue
            if isinstance(item.optional_vars, ast.Name):
                end = getattr(node, "end_lineno", node.lineno)
                spans.append((item.optional_vars.id, node.lineno, end))
    return spans


class AtomicWriteRule(Rule):
    """Output files must be written through ``io/atomic.py``."""

    id = "atomic-write"
    severity = "error"
    description = ("os.replace and write-mode open() belong in "
                   "io/atomic.py; write outputs inside "
                   "`with atomic_output(path) as tmp:`")

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        if _is_impl(ctx, ATOMIC_IMPL) or _is_impl(ctx, FLOCK_IMPL):
            return
        sanctioned = _atomic_output_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain == "os.replace":
                yield (node.lineno,
                       "direct os.replace bypasses io/atomic.py: write "
                       "through `with atomic_output(path) as tmp:` (or "
                       "suppress if this is a rename between existing "
                       "files, not a publish)")
                continue
            if chain not in ("open", "io.open"):
                continue
            mode = _open_mode(node)
            if not any(c in mode for c in "wx+"):
                continue
            target = node.args[0] if node.args else None
            if isinstance(target, ast.Name) and any(
                    target.id == name and lo <= node.lineno <= hi
                    for name, lo, hi in sanctioned):
                continue
            yield (node.lineno,
                   f"open(..., {mode!r}) outside an atomic_output block: "
                   "a crash mid-write leaves a torn file; route through "
                   "io/atomic.py")


class FlockDisciplineRule(Rule):
    """Shared appends and flock use belong in ``utils/logging.py``."""

    id = "flock-discipline"
    severity = "error"
    description = ("fcntl locking and append-mode open() belong in "
                   "utils/logging.py (locked_append / "
                   "compact_under_lock)")

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        if _is_impl(ctx, FLOCK_IMPL):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                if any(a.name == "fcntl" for a in node.names):
                    yield (node.lineno,
                           "direct fcntl use outside utils/logging.py: "
                           "take file locks through locked_append/"
                           "compact_under_lock so lock ordering stays "
                           "auditable")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "fcntl":
                    yield (node.lineno,
                           "direct fcntl use outside utils/logging.py: "
                           "take file locks through locked_append/"
                           "compact_under_lock so lock ordering stays "
                           "auditable")
            elif isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain not in ("open", "io.open"):
                    continue
                if "a" in _open_mode(node):
                    yield (node.lineno,
                           "append-mode open() outside utils/logging.py: "
                           "concurrent writers interleave partial "
                           "records; use locked_append")


class LockOrderRule(Rule):
    """No nested acquisition of the per-file flock.

    Two shapes deadlock (flock is not re-entrant across fds on some
    filesystems, and a second EX acquisition under the first self-blocks
    with LOCK_NB disabled): a function that calls ``fcntl.flock`` AND one
    of the lock-taking helpers, and a rewrite callback handed to
    ``compact_under_lock`` that itself calls a lock-taking helper (the
    callback runs under the compact lock)."""

    id = "lock-order"
    severity = "error"
    description = ("never call a lock-taking helper while already "
                   "holding the file flock")

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            flock_line = None
            helper = None
            local_defs = {}
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and node is not fn:
                    local_defs[node.name] = node
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                leaf = chain.split(".")[-1]
                if leaf == "flock":
                    flock_line = flock_line or node.lineno
                elif leaf in LOCK_HELPERS:
                    helper = helper or (node.lineno, leaf)
                if leaf == "compact_under_lock" and node.args:
                    for arg in node.args:
                        if isinstance(arg, ast.Name) \
                                and arg.id in local_defs:
                            cb = local_defs[arg.id]
                            for inner in ast.walk(cb):
                                if isinstance(inner, ast.Call):
                                    ileaf = _attr_chain(
                                        inner.func).split(".")[-1]
                                    if ileaf in LOCK_HELPERS \
                                            or ileaf == "flock":
                                        yield (inner.lineno,
                                               f"rewrite callback "
                                               f"{arg.id!r} runs under "
                                               f"the compact lock but "
                                               f"calls {ileaf}(): nested "
                                               f"flock self-deadlocks")
            if flock_line is not None and helper is not None:
                yield (helper[0],
                       f"{fn.name}() holds a raw flock and calls "
                       f"{helper[1]}(), which takes the same lock again: "
                       "nested flock self-deadlocks")

"""Concurrency-discipline rules: shared state, lock order, claim coverage.

PRs 8–12 moved the system's correctness onto a concurrent protocol —
flock'd journal folds, claim/membership leases, heartbeat threads, an
HTTP intake running on per-request threads against a single worker
loop.  The PR-12 review found exactly the bug class unit tests miss
(interleaving races), so these rules make the thread structure itself
a linted artifact:

* :class:`ThreadSharedStateRule` inventories thread entrypoints —
  ``threading.Thread(target=...)``, executor ``submit`` targets,
  methods handed out by reference as callbacks, and daemon methods the
  ``BaseHTTPRequestHandler`` subclasses invoke from per-request
  threads — propagates those entrypoint labels through each class's
  ``self.``-call graph, and flags instance state written from two or
  more distinct entrypoints without one common lock.
* :class:`ThreadLockOrderRule` extends PR 11's ``lock-order`` across
  lock TYPES: the sanctioned nesting is threading-lock OUTER, file
  flock INNER (``stream_ingest`` holds the stream lock while its
  journal append takes the flock).  If any code path ever acquires a
  threading lock while holding the flock, both directions exist and
  every participating site is flagged — the classic two-lock deadlock
  needs both orders, so the rule stays silent until someone writes the
  inversion.
* :class:`JournalClaimRule` (``journal-append-without-claim``): in a
  file that participates in the claim-lease protocol, execution
  lifecycle lines ('running'/'done'/'failed' request states, archive
  'done' lines) may only be appended from code reachable from a claim
  acquisition — an unclaimed writer is exactly the duplicate-clean
  hazard the lease exists to prevent.  Raw ``journal._append`` calls
  outside ``resilience/journal.py`` bypass the grammar and are always
  flagged.

All three silence the usual way: ``# icln: ignore[rule-id] -- reason``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from iterative_cleaner_tpu.analysis.core import (
    FileContext,
    RepoContext,
    RepoRule,
    Rule,
)
from iterative_cleaner_tpu.analysis.rules_io import LOCK_HELPERS, _attr_chain

#: journal mutators whose lines carry execution-lifecycle meaning
CLAIM_ACQUIRERS = frozenset({"try_claim", "_claim_for_execute"})

#: journal calls that take the per-file flock internally (any of these
#: inside a held threading lock is a T->F nesting site)
JOURNAL_MUTATORS = frozenset({
    "record_done", "record_request", "record_claim", "record_member",
    "record_cache", "record_host_stats", "try_claim", "heartbeat",
    "release", "compact", "compact_shard", "seal",
})

#: request states only the execution-claim holder may journal
EXECUTION_STATES = ("running", "done", "failed")


def _is_lockish(chain: str) -> bool:
    """Does a with-context chain look like a threading lock?  The
    project's locks all carry 'lock' in the attribute name (``_lock``,
    ``st.lock``, ``_state_lock``), which keeps this a naming convention
    the lint both relies on and enforces by construction."""
    leaf = chain.split(".")[-1].lower()
    return "lock" in leaf


def _with_locks(ctx: FileContext, node: ast.AST) -> Set[str]:
    """The threading-lock context chains held at ``node`` (lexically)."""
    held: Set[str] = set()
    for p in ctx.parents(node):
        if not isinstance(p, (ast.With, ast.AsyncWith)):
            continue
        for item in p.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                chain = _attr_chain(expr.func) + "()"
            else:
                chain = _attr_chain(expr)
            if chain and _is_lockish(chain):
                held.add(chain)
    return held


def _walk_unit(root: ast.AST, skip: Set[ast.AST]) -> Iterator[ast.AST]:
    """Walk ``root`` without descending into other analysis units: a
    nested function that runs on its own thread executes NONE of its
    body when the enclosing method runs."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if child in skip and child is not root:
                continue
            stack.append(child)


#: instance-attribute method calls that mutate their receiver
_MUTATORS = frozenset({
    "append", "extend", "add", "discard", "remove", "pop", "popitem",
    "clear", "update", "setdefault", "insert",
})


class _Unit:
    """One analysis unit: a method or a nested function used as a thread
    entrypoint.  Carries the self-call edges, the instance-attribute
    write sites and the entrypoint labels propagated onto it."""

    def __init__(self, name: str, node: ast.AST) -> None:
        self.name = name
        self.node = node
        self.calls: Set[str] = set()       # leaf names of self.M() calls
        self.local_calls: Set[str] = set()  # bare-name calls to units
        # attr -> [(line, locks held)]
        self.writes: Dict[str, List[Tuple[int, Set[str]]]] = {}
        self.labels: Set[str] = set()

    def add_write(self, attr: str, line: int, locks: Set[str]) -> None:
        self.writes.setdefault(attr, []).append((line, locks))


def _target_name(node: ast.AST) -> Tuple[str, str]:
    """Resolve a callable reference: returns ('method', M) for
    ``self.M``, ('name', N) for a bare name, ('', '') otherwise."""
    chain = _attr_chain(node)
    if chain.startswith("self.") and chain.count(".") == 1:
        return "method", chain.split(".", 1)[1]
    if isinstance(node, ast.Name):
        return "name", node.id
    return "", ""


class _ScopeAnalysis:
    """Shared-state analysis of one class (or of the module top level,
    where 'self.' attrs give way to ``global``-declared names)."""

    def __init__(self, ctx: FileContext, body: List[ast.stmt],
                 http_names: Set[str], *, is_module: bool) -> None:
        self.ctx = ctx
        self.is_module = is_module
        self.units: Dict[str, _Unit] = {}
        self.unit_nodes: Set[ast.AST] = set()
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._register(stmt)
        # second pass: nested defs become units too (thread targets and
        # inline helpers both), now that the full set is known
        for stmt in list(self.units.values()):
            for node in ast.walk(stmt.node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node not in self.unit_nodes:
                    self._register(node)
        for unit in self.units.values():
            self._scan_unit(unit)
        self._label_roots(http_names)
        self._propagate()

    def _register(self, node) -> None:
        # leaf-name keyed; a duplicate name keeps the first definition
        # (good enough for labeling — both would get the same labels)
        self.unit_nodes.add(node)
        self.units.setdefault(node.name, _Unit(node.name, node))

    # ------------------------------------------------------------ scanning
    def _scan_unit(self, unit: _Unit) -> None:
        if unit.name == "__init__":
            return  # construction precedes every thread
        globals_here: Set[str] = set()
        for node in _walk_unit(unit.node, self.unit_nodes):
            if isinstance(node, ast.Global):
                globals_here.update(node.names)
        for node in _walk_unit(unit.node, self.unit_nodes):
            if isinstance(node, ast.Call):
                kind, name = _target_name(node.func)
                if kind == "method" and name in self.units:
                    unit.calls.add(name)
                elif kind == "name" and name in self.units:
                    unit.local_calls.add(name)
                chain = _attr_chain(node.func)
                parts = chain.split(".")
                if (len(parts) == 3 and parts[0] == "self"
                        and parts[2] in _MUTATORS):
                    unit.add_write(parts[1], node.lineno,
                                   _with_locks(self.ctx, node))
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                chain = _attr_chain(base)
                if chain.startswith("self.") and chain.count(".") == 1:
                    unit.add_write(chain.split(".", 1)[1], t.lineno,
                                   _with_locks(self.ctx, t))
                elif (self.is_module and isinstance(base, ast.Name)
                        and base.id in globals_here):
                    unit.add_write(base.id, t.lineno,
                                   _with_locks(self.ctx, t))

    # ------------------------------------------------------------ labeling
    def _label_roots(self, http_names: Set[str]) -> None:
        consumed: Set[ast.AST] = set()
        for unit in self.units.values():
            for node in _walk_unit(unit.node, self.unit_nodes):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _attr_chain(node.func).split(".")[-1]
                if leaf == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            self._mark(kw.value, "thread")
                            consumed.add(kw.value)
                elif leaf == "submit" and node.args:
                    self._mark(node.args[0], "pool")
                    consumed.add(node.args[0])
        # a method handed out by REFERENCE (not called) becomes someone
        # else's entrypoint: scheduler callbacks, hooks — wherever the
        # reference escapes to, it may run on that something's thread
        for unit in self.units.values():
            for node in _walk_unit(unit.node, self.unit_nodes):
                if not isinstance(node, ast.Attribute) or node in consumed:
                    continue
                kind, name = _target_name(node)
                if kind != "method" or name not in self.units:
                    continue
                parent = getattr(node, "_icln_parent", None)
                if isinstance(parent, ast.Call) and parent.func is node:
                    continue  # being called, not handed out
                self.units[name].labels.add(f"callback:{name}")
        for name, unit in self.units.items():
            if name in http_names:
                unit.labels.add("http")
            if not name.startswith("_") and not self.is_module:
                # public surface: callable from the process's own
                # (main/worker) context
                unit.labels.add("main")
            if self.is_module and not name.startswith("_"):
                unit.labels.add("main")

    def _mark(self, value: ast.AST, what: str) -> None:
        kind, name = _target_name(value)
        if name in self.units:
            self.units[name].labels.add(f"{what}:{name}")

    def _propagate(self) -> None:
        changed = True
        while changed:
            changed = False
            for unit in self.units.values():
                for callee in unit.calls | unit.local_calls:
                    tgt = self.units.get(callee)
                    if tgt is not None and not unit.labels <= tgt.labels:
                        tgt.labels |= unit.labels
                        changed = True

    # ------------------------------------------------------------ verdicts
    def findings(self) -> Iterator[Tuple[int, str]]:
        # attr -> [(line, locks, labels, unit name)]
        sites: Dict[str, List[Tuple[int, Set[str], Set[str], str]]] = {}
        for unit in self.units.values():
            if not unit.labels:
                continue  # unreachable from any entrypoint
            for attr, writes in unit.writes.items():
                for line, locks in writes:
                    sites.setdefault(attr, []).append(
                        (line, locks, unit.labels, unit.name))
        for attr, rows in sorted(sites.items()):
            labels: Set[str] = set()
            for _line, _locks, ls, _u in rows:
                labels |= ls
            if len(labels) < 2:
                continue
            common = set(rows[0][1])
            for _line, locks, _ls, _u in rows[1:]:
                common &= locks
            if common:
                continue
            unlocked = [r for r in rows if not r[1]]
            line = (min(r[0] for r in unlocked) if unlocked
                    else min(r[0] for r in rows))
            where = ", ".join(
                "%s:%d%s" % (u, ln, "" if lk else " (unlocked)")
                for ln, lk, _ls, u in sorted(rows))
            yield (line,
                   f"{'global' if self.is_module else 'attribute'} "
                   f"{attr!r} is written from {len(labels)} thread "
                   f"entrypoints ({', '.join(sorted(labels))}) without "
                   f"one common lock — writes at {where}; guard every "
                   f"write with the same lock or confine the state to "
                   f"one thread")


def _http_called_names(repo: RepoContext) -> Set[str]:
    """Method names the HTTP handler classes invoke on the daemon —
    each runs on a per-request thread (``ThreadingHTTPServer``)."""
    out: Set[str] = set()
    for ctx in repo.files:
        if ctx.tree is None:
            continue
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not any("BaseHTTPRequestHandler" in _attr_chain(b)
                       for b in cls.bases):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                parts = chain.split(".")
                if len(parts) >= 2 and "daemon" in parts[:-1]:
                    out.add(parts[-1])
    return out


class ThreadSharedStateRule(RepoRule):
    """Instance/module state written from ≥2 thread entrypoints must
    share one lock."""

    id = "thread-shared-state"
    severity = "error"
    description = ("state written from two thread entrypoints without a "
                   "common lock is a data race; guard every write with "
                   "the same lock or confine the state to one thread")

    def check_repo(self, repo: RepoContext) \
            -> Iterable[Tuple[FileContext, int, str]]:
        http_names = _http_called_names(repo)
        for ctx in repo.files:
            if ctx.tree is None:
                continue
            if ctx.rel.endswith("serve/http.py"):
                # the handler class IS the thread boundary; its state is
                # per-request by construction
                continue
            for cls in ast.walk(ctx.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                scope = _ScopeAnalysis(ctx, cls.body, http_names,
                                       is_module=False)
                for line, msg in scope.findings():
                    yield ctx, line, f"{cls.name}.{msg}"
            module_scope = _ScopeAnalysis(
                ctx, [s for s in ctx.tree.body], set(), is_module=True)
            for line, msg in module_scope.findings():
                yield ctx, line, msg


class ThreadLockOrderRule(RepoRule):
    """Threading locks nest OUTSIDE the file flock, never inside.

    The repo's one sanctioned direction is T->F: ``stream_ingest`` holds
    the per-stream threading lock while its journal append takes the
    flock.  The moment any code path acquires a threading lock while
    holding the flock (F->T), both orders exist in one process and two
    threads can deadlock across the pair — so this rule collects both
    kinds of site repo-wide and flags ALL of them only when both
    directions are present, naming the opposite site."""

    id = "thread-lock-order"
    severity = "error"
    description = ("acquiring a threading lock under the file flock "
                   "inverts the sanctioned T->F order and can deadlock "
                   "against any locked journal append")

    def check_repo(self, repo: RepoContext) \
            -> Iterable[Tuple[FileContext, int, str]]:
        t_to_f: List[Tuple[FileContext, int, str]] = []
        f_to_t: List[Tuple[FileContext, int, str]] = []
        for ctx in repo.files:
            if ctx.tree is None or ctx.rel.endswith("utils/logging.py"):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                leaf = chain.split(".")[-1]
                if leaf in LOCK_HELPERS or leaf in JOURNAL_MUTATORS:
                    held = _with_locks(ctx, node)
                    if held:
                        t_to_f.append(
                            (ctx, node.lineno,
                             f"{leaf}() under threading lock "
                             f"{sorted(held)[0]!r}"))
                if leaf == "flock" or leaf == "compact_under_lock":
                    fn = ctx.enclosing_function(node)
                    if fn is None:
                        continue
                    for inner in ast.walk(fn):
                        acquires = None
                        if isinstance(inner, (ast.With, ast.AsyncWith)):
                            for item in inner.items:
                                c = _attr_chain(item.context_expr)
                                if c and _is_lockish(c):
                                    acquires = (item.context_expr.lineno,
                                                c)
                        elif isinstance(inner, ast.Call):
                            c = _attr_chain(inner.func)
                            if (c.endswith(".acquire")
                                    and _is_lockish(c[:-8])):
                                acquires = (inner.lineno, c)
                        if acquires and acquires[0] > node.lineno:
                            f_to_t.append(
                                (ctx, acquires[0],
                                 f"threading lock {acquires[1]!r} "
                                 f"acquired after {leaf}() in "
                                 f"{fn.name}()"))
        if not (t_to_f and f_to_t):
            return
        other_f = f"{f_to_t[0][0].rel}:{f_to_t[0][1]}"
        other_t = f"{t_to_f[0][0].rel}:{t_to_f[0][1]}"
        for ctx, line, what in t_to_f:
            yield (ctx, line,
                   f"{what}: the flock nests inside a threading lock "
                   f"here while {other_f} nests a threading lock inside "
                   f"the flock — both orders in one process deadlock")
        for ctx, line, what in f_to_t:
            yield (ctx, line,
                   f"{what}: inverts the sanctioned T->F order "
                   f"(e.g. {other_t}) — both orders in one process "
                   f"deadlock")


def _state_const(call: ast.Call) -> Optional[str]:
    """The literal request state of a ``record_request`` call."""
    cand: Optional[ast.AST] = None
    if len(call.args) >= 2:
        cand = call.args[1]
    for kw in call.keywords:
        if kw.arg == "state":
            cand = kw.value
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return cand.value
    return None


class JournalClaimRule(Rule):
    """Execution-lifecycle journal lines require the execution claim."""

    id = "journal-append-without-claim"
    severity = "error"
    description = ("'running'/'done'/'failed' journal lines outside the "
                   "claim-lease discipline are the duplicate-clean "
                   "hazard the lease exists to prevent")

    def check(self, ctx: FileContext) -> Iterable[Tuple[int, str]]:
        if ctx.rel.endswith("resilience/journal.py") \
                or ctx.rel.endswith("resilience/segmented.py") \
                or "/analysis/" in ctx.rel:
            return
        # grammar bypass: raw _append anywhere outside the journal impl
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain.split(".")[-1] == "_append" and "." in chain:
                    yield (node.lineno,
                           "raw journal._append bypasses the line "
                           "grammar (and fsck); use the record_* "
                           "methods")
        funcs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, []).append(node)
        claimful = False
        holders: Set[str] = set()
        calls: Dict[str, Set[str]] = {name: set() for name in funcs}

        def owner(node: ast.AST):
            fn = ctx.enclosing_function(node)
            while isinstance(fn, ast.Lambda):
                fn = ctx.enclosing_function(fn)
            return fn

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _attr_chain(node.func).split(".")[-1]
            fn = owner(node)
            if leaf in CLAIM_ACQUIRERS:
                claimful = True
                if fn is not None:
                    holders.add(fn.name)
            if fn is not None and leaf in funcs:
                calls[fn.name].add(leaf)
        if not claimful:
            return
        covered = set(holders)
        frontier = list(holders)
        while frontier:
            for callee in calls.get(frontier.pop(), ()):
                if callee not in covered:
                    covered.add(callee)
                    frontier.append(callee)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _attr_chain(node.func).split(".")[-1]
            state = None
            if leaf == "record_request":
                state = _state_const(node)
                if state not in EXECUTION_STATES:
                    continue
            elif leaf != "record_done":
                continue
            fn = owner(node)
            if fn is not None and fn.name in covered:
                continue
            what = (f"record_request(state={state!r})" if state
                    else "record_done()")
            name = fn.name if fn is not None else "<module>"
            yield (node.lineno,
                   f"{what} in {name}() is not reachable from any "
                   f"claim acquisition ({'/'.join(sorted(CLAIM_ACQUIRERS))})"
                   f" in this file: an unclaimed writer of execution "
                   f"lifecycle lines can duplicate another member's "
                   f"work")

"""Journal fsck: validate a fleet journal against its explicit grammar.

The journal (resilience/journal.py) is the system's single source of
truth for exactly-once cleaning, pool membership and failover — so a
malformed journal is not a logging bug, it is a correctness bug.  This
module encodes the six line kinds as an explicit state machine and
checks any journal file against it:

* **grammar** — every parseable line must carry the schema tag, a known
  ``event`` and that event's required fields with the right types
  (``done`` needs path/sig/config; ``claim`` needs work/host/nonce/
  state/t/ttl; and so on).  A JSON line under a foreign schema is an
  error: the journal is exclusively ours.
* **request state machine** — per request id, states may only move
  forward (``accepted`` → ``running`` → ``done``/``failed``).  A
  regression (a 'running' or terminal line followed by 'accepted') is
  exactly the admit-ordering hazard PR 12 fixed: the fold would read
  the finished request as unfinished forever, and a pool peer would
  adopt and duplicate-clean it.  A line after a terminal state is an
  error for the same reason.
* **torn-tail healing** — an unparseable line is a WARNING, not an
  error: a writer killed mid-line leaves one, and the next appender
  heals it by prefixing a newline (the reader skips the garbage).  The
  state machine therefore accepts garbage lines and blank lines
  anywhere; what it refuses is structurally valid JSON that lies about
  its shape.
* **lease monotonicity** — claim and member lease lines are appended
  under the file flock by processes reading a monotonic clock, so per
  work item / member id the ``t`` stamps must be non-decreasing (up to
  ``skew_s`` for cross-host clock skew).  A backwards stamp means a
  writer bypassed the locked append path or replayed stale lines —
  either breaks the fold's "everyone reads the same order" guarantee.

* **segment directories** — a segmented journal (``--journal DIR``,
  resilience/segmented.py) is checked as a whole: the manifest must
  parse under its own schema and only name well-formed segment files of
  the right shard; each shard's stream (live sealed segments in
  sequence order, then the active segment) runs through the same state
  machine — per-key total order is preserved within a shard, so the
  lifecycle and lease checks stay valid verbatim; and every line must
  actually ROUTE to the shard it lives in (``entry_key`` →
  ``stable_shard``), because a mis-routed line breaks the per-key
  ordering guarantee every fold depends on.

Entry points: :func:`fsck_journal` (one file or segment directory →
:class:`FsckReport`), ``icln-lint --journal-fsck PATH`` (analysis/cli.py)
and :func:`record_fsck` (counters for /metrics — the CI gate and the serve
daemon both publish the verdict of the journals they actually produced).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from iterative_cleaner_tpu.resilience.journal import (
    CLAIM_STATES,
    MEMBER_STATES,
    REQUEST_TERMINAL,
    SCHEMA,
)

#: the six journal line kinds, in the order they entered the grammar
EVENT_KINDS = ("done", "req", "claim", "stats", "member", "cache")

#: request lifecycle rank: transitions may never lower it
_REQ_RANK = {"accepted": 0, "running": 1, "done": 2, "failed": 2}

_REQUEST_STATES = ("accepted", "running") + REQUEST_TERMINAL


@dataclasses.dataclass(frozen=True)
class FsckIssue:
    """One violation (``severity == "error"``) or accepted anomaly
    (``severity == "warning"``, e.g. a healed torn line)."""

    line: int
    kind: str
    severity: str
    message: str

    def render(self) -> str:
        return f"line {self.line}: {self.severity} [{self.kind}] {self.message}"


@dataclasses.dataclass
class FsckReport:
    path: str
    n_lines: int = 0
    #: segment files examined (0 for a single-file journal)
    n_segments: int = 0
    counts: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {k: 0 for k in EVENT_KINDS})
    issues: List[FsckIssue] = dataclasses.field(default_factory=list)

    @property
    def errors(self) -> List[FsckIssue]:
        return [i for i in self.issues if i.severity == "error"]

    @property
    def warnings(self) -> List[FsckIssue]:
        return [i for i in self.issues if i.severity == "warning"]

    @property
    def ok(self) -> bool:
        """Warnings (torn lines the readers heal) do not fail the gate;
        grammar/state-machine/lease errors do."""
        return not self.errors

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "ok": self.ok,
            "n_lines": self.n_lines,
            "n_segments": self.n_segments,
            "counts": dict(self.counts),
            "errors": [dataclasses.asdict(i) for i in self.errors],
            "warnings": [dataclasses.asdict(i) for i in self.warnings],
        }

    def render_text(self) -> str:
        out = [i.render() for i in self.issues]
        tally = ", ".join("%d %s" % (self.counts[k], k)
                          for k in EVENT_KINDS if self.counts[k])
        seg = ("" if not self.n_segments
               else " in %d segment%s" % (self.n_segments,
                                          "" if self.n_segments == 1
                                          else "s"))
        out.append("%s: %s — %d line%s%s (%s), %d error%s, %d warning%s"
                   % (self.path, "ok" if self.ok else "FAILED",
                      self.n_lines, "" if self.n_lines == 1 else "s", seg,
                      tally or "empty",
                      len(self.errors), "" if len(self.errors) == 1 else "s",
                      len(self.warnings),
                      "" if len(self.warnings) == 1 else "s"))
        return "\n".join(out)


def _type_name(value) -> str:
    return type(value).__name__


def _check_fields(entry: dict, spec: Dict[str, tuple],
                  lineno: int, issues: List[FsckIssue]) -> bool:
    """Required-field presence + type check; returns True when all hold
    (transition checks only run on grammatically whole lines)."""
    ok = True
    for field, types in spec.items():
        if field not in entry:
            issues.append(FsckIssue(
                lineno, "grammar", "error",
                f"{entry.get('event')} line is missing required field "
                f"{field!r}"))
            ok = False
        elif not isinstance(entry[field], types):
            issues.append(FsckIssue(
                lineno, "grammar", "error",
                f"{entry.get('event')} field {field!r} has type "
                f"{_type_name(entry[field])}, expected "
                f"{'/'.join(t.__name__ for t in types)}"))
            ok = False
    return ok


_NUM = (int, float)

#: required fields (and types) per event kind — bool is an int subclass,
#: so numeric fields explicitly refuse it where a bool would be a lie
_FIELD_SPECS: Dict[str, Dict[str, tuple]] = {
    "done": {"path": (str,), "sig": (str,), "config": (str,)},
    "req": {"req": (str,), "state": (str,)},
    "claim": {"work": (str,), "host": (int,), "nonce": (str,),
              "state": (str,), "t": _NUM, "ttl": _NUM},
    "stats": {"host": (int,), "counters": (dict,)},
    "member": {"member": (str,), "host": (int,), "state": (str,),
               "t": _NUM, "ttl": _NUM},
    "cache": {"key": (str,), "path": (str,), "sig": (str,),
              "config": (str,), "out": (str,), "out_sig": (str,)},
}


class _LeaseMonotony:
    """Per-key non-decreasing ``t`` check for claim/member lines."""

    def __init__(self, what: str, skew_s: float) -> None:
        self.what = what
        self.skew_s = skew_s
        self.last: Dict[str, Tuple[float, int]] = {}

    def observe(self, key: str, t: float, lineno: int,
                issues: List[FsckIssue]) -> None:
        prev = self.last.get(key)
        if prev is not None and t < prev[0] - self.skew_s:
            issues.append(FsckIssue(
                lineno, "lease-monotonicity", "error",
                f"{self.what} {key!r} lease stamp went backwards "
                f"(t={t:g} after t={prev[0]:g} on line {prev[1]}): "
                f"flock-serialized appends of a monotonic clock can "
                f"never do this — a writer bypassed the locked append "
                f"or replayed stale lines"))
        if prev is None or t > prev[0]:
            self.last[key] = (t, lineno)


def fsck_text(text: str, *, skew_s: float = 0.0) -> Tuple[
        List[FsckIssue], Dict[str, int], int]:
    """Validate journal ``text``; returns (issues, per-kind counts,
    n_lines).  Pure function of the text — the model checker and the
    unit tests call it on synthetic journals."""
    issues: List[FsckIssue] = []
    counts = {k: 0 for k in EVENT_KINDS}
    lines = text.splitlines()
    # request lifecycle: rid -> (rank, state, lineno of last transition)
    req_state: Dict[str, Tuple[int, str, int]] = {}
    claim_mono = _LeaseMonotony("claim work", skew_s)
    member_mono = _LeaseMonotony("member", skew_s)
    last_content = 0
    for i, raw in enumerate(lines, start=1):
        if raw.strip():
            last_content = i
    for lineno, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line:
            continue  # heal probes leave blank lines; readers skip them
        try:
            entry = json.loads(line)
        except ValueError:
            where = ("torn tail" if lineno == last_content
                     else "healed torn line")
            issues.append(FsckIssue(
                lineno, "torn-line", "warning",
                f"unparseable line ({where}): a writer died mid-append; "
                f"readers skip it and the next append healed it"))
            continue
        if not isinstance(entry, dict):
            issues.append(FsckIssue(
                lineno, "grammar", "error",
                f"parseable JSON but not an object "
                f"({_type_name(entry)}): not a journal line"))
            continue
        if entry.get("schema") != SCHEMA:
            issues.append(FsckIssue(
                lineno, "grammar", "error",
                f"foreign or missing schema tag {entry.get('schema')!r} "
                f"(expected {SCHEMA!r}): the journal file is exclusively "
                f"the fleet's"))
            continue
        event = entry.get("event")
        if event not in EVENT_KINDS:
            issues.append(FsckIssue(
                lineno, "grammar", "error",
                f"unknown event {event!r} (known: "
                f"{', '.join(EVENT_KINDS)})"))
            continue
        counts[event] += 1
        if not _check_fields(entry, _FIELD_SPECS[event], lineno, issues):
            continue
        if event == "done":
            if bool(entry.get("out")) != bool(entry.get("out_sig")):
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    "done line has 'out' without 'out_sig' (or vice "
                    "versa): a recorded output must carry the signature "
                    "a resume re-verifies"))
        elif event == "req":
            state = entry["state"]
            if state not in _REQUEST_STATES:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"request state {state!r} is not one of "
                    f"{'/'.join(_REQUEST_STATES)}"))
                continue
            rid = entry["req"]
            rank = _REQ_RANK[state]
            prev = req_state.get(rid)
            if prev is not None:
                prev_rank, prev_state, prev_line = prev
                if prev_rank >= _REQ_RANK["done"] and state != prev_state:
                    issues.append(FsckIssue(
                        lineno, "state-machine", "error",
                        f"request {rid!r}: {state!r} after terminal "
                        f"{prev_state!r} (line {prev_line}) — a finished "
                        f"request's lifecycle is closed"))
                elif (prev_rank >= _REQ_RANK["done"]
                        and state == prev_state):
                    issues.append(FsckIssue(
                        lineno, "state-machine", "error",
                        f"request {rid!r}: duplicate terminal "
                        f"{state!r} (first on line {prev_line}) — "
                        f"exactly-once means one terminal line"))
                elif rank < prev_rank:
                    issues.append(FsckIssue(
                        lineno, "state-machine", "error",
                        f"request {rid!r}: state regressed "
                        f"{prev_state!r} (line {prev_line}) -> {state!r} "
                        f"— the admit-ordering hazard: the fold now "
                        f"reads a finished request as unfinished and a "
                        f"pool peer would duplicate-clean it"))
            if prev is None or rank >= prev[0]:
                req_state[rid] = (rank, state, lineno)
        elif event == "claim":
            if entry["state"] not in CLAIM_STATES:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"claim state {entry['state']!r} is not one of "
                    f"{'/'.join(CLAIM_STATES)}"))
                continue
            if entry["ttl"] < 0:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"claim ttl is negative ({entry['ttl']:g}): a lease "
                    f"cannot expire before it was granted"))
            claim_mono.observe(entry["work"], float(entry["t"]),
                               lineno, issues)
        elif event == "member":
            if entry["state"] not in MEMBER_STATES:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"member state {entry['state']!r} is not one of "
                    f"{'/'.join(MEMBER_STATES)}"))
                continue
            if entry["ttl"] < 0:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"member ttl is negative ({entry['ttl']:g})"))
            member_mono.observe(entry["member"], float(entry["t"]),
                                lineno, issues)
        elif event == "stats":
            bad = [k for k, v in entry["counters"].items()
                   if not isinstance(v, _NUM) or isinstance(v, bool)]
            if bad:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"stats counters {sorted(bad)!r} are not numeric"))
        elif event == "cache":
            want = f"{entry['sig']}|{entry['config']}"
            if entry["key"] != want:
                issues.append(FsckIssue(
                    lineno, "grammar", "error",
                    f"cache key {entry['key']!r} != sig|config "
                    f"({want!r}): a mis-keyed entry can serve the wrong "
                    f"output to a matching lookup"))
    return issues, counts, len(lines)


def _check_manifest(path: str, report: FsckReport) -> Optional[dict]:
    """Validate a segment directory's manifest grammar; returns the
    parsed manifest, or None when it is too broken to fold over (the
    errors are already on the report)."""
    from iterative_cleaner_tpu.resilience.segmented import (
        MANIFEST_NAME, MANIFEST_SCHEMA, segment_parts)

    man_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.isfile(man_path):
        report.issues.append(FsckIssue(
            0, "manifest", "error",
            f"segment directory has no {MANIFEST_NAME}: not a segmented "
            f"journal (or its atomic initial write never landed)"))
        return None
    try:
        with open(man_path, "r", encoding="utf-8") as f:
            man = json.load(f)
    except ValueError as exc:
        report.issues.append(FsckIssue(
            0, "manifest", "error",
            f"{MANIFEST_NAME} is not valid JSON ({exc}): manifest "
            f"rewrites are atomic, so a torn manifest means a writer "
            f"bypassed the locked rewrite path"))
        return None
    if not isinstance(man, dict) or man.get("schema") != MANIFEST_SCHEMA:
        report.issues.append(FsckIssue(
            0, "manifest", "error",
            f"{MANIFEST_NAME} schema is "
            f"{man.get('schema') if isinstance(man, dict) else man!r}, "
            f"expected {MANIFEST_SCHEMA!r}"))
        return None
    n_shards = man.get("n_shards")
    if not isinstance(n_shards, int) or isinstance(n_shards, bool) \
            or n_shards <= 0:
        report.issues.append(FsckIssue(
            0, "manifest", "error",
            f"n_shards is {n_shards!r}, expected a positive int"))
        return None
    shards = man.get("shards")
    if not isinstance(shards, dict):
        report.issues.append(FsckIssue(
            0, "manifest", "error",
            f"shards is {_type_name(shards)}, expected an object"))
        return None
    ok = True
    for key in sorted(shards):
        ent = shards[key]
        if not (key.isdigit() and int(key) < n_shards):
            report.issues.append(FsckIssue(
                0, "manifest", "error",
                f"shard key {key!r} is not a decimal index in "
                f"[0, {n_shards})"))
            ok = False
            continue
        if not isinstance(ent, dict):
            report.issues.append(FsckIssue(
                0, "manifest", "error",
                f"shard {key} entry is {_type_name(ent)}, expected an "
                f"object"))
            ok = False
            continue
        for field in ("segments", "dead"):
            names = ent.get(field)
            if not isinstance(names, list):
                report.issues.append(FsckIssue(
                    0, "manifest", "error",
                    f"shard {key} {field!r} is "
                    f"{_type_name(names)}, expected a list"))
                ok = False
                continue
            for name in names:
                parts = (segment_parts(name)
                         if isinstance(name, str) else None)
                if parts is None or parts[1] != int(key):
                    report.issues.append(FsckIssue(
                        0, "manifest", "error",
                        f"shard {key} {field} entry {name!r} is not a "
                        f"segment name of this shard"))
                    ok = False
    return man if ok else None


def _fsck_segment_dir(path: str, *, skew_s: float) -> FsckReport:
    """Validate a segmented journal directory: manifest grammar, every
    shard's stream through the single-file state machine (per-key order
    is preserved within a shard, so lifecycle/lease checks carry over
    verbatim), plus the shard-routing invariant."""
    from iterative_cleaner_tpu.parallel.distributed import stable_shard
    from iterative_cleaner_tpu.resilience.journal import entry_key
    from iterative_cleaner_tpu.resilience.segmented import SegmentedLog

    report = FsckReport(path=path)
    man = _check_manifest(path, report)
    if man is None:
        return report
    log = SegmentedLog(path)  # manifest exists: read-only construction
    n_shards = log.n_shards
    names = log._names_on_disk()
    for shard in range(n_shards):
        chunks = []
        for name in log._effective(shard, man, names):
            seg_path = os.path.join(path, name)
            try:
                chunks.append(log._read_file(seg_path))
                report.n_segments += 1
            except OSError:
                report.issues.append(FsckIssue(
                    0, "manifest", "error",
                    f"shard {shard}: listed segment {name} is missing "
                    f"on disk (and not on the dead list) — a manifest "
                    f"swap retired it without listing it dead"))
        try:
            chunks.append(log._read_file(log._active_path(shard)))
            report.n_segments += 1
        except OSError:
            pass  # no active segment: this shard is fully sealed
        text = "".join(chunks)
        issues, counts, n_lines = fsck_text(text, skew_s=skew_s)
        report.issues.extend(dataclasses.replace(
            i, message=f"shard {shard}: {i.message}") for i in issues)
        for kind, n in counts.items():
            report.counts[kind] += n
        report.n_lines += n_lines
        for lineno, raw in enumerate(text.splitlines(), start=1):
            line = raw.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                continue  # torn line: already a warning above
            if not isinstance(entry, dict) \
                    or entry.get("event") not in EVENT_KINDS:
                continue  # grammar error: already reported above
            want = stable_shard(entry_key(entry), n_shards)
            if want != shard:
                report.issues.append(FsckIssue(
                    lineno, "shard-routing", "error",
                    f"shard {shard}: {entry.get('event')} line with key "
                    f"{entry_key(entry)!r} routes to shard {want} — a "
                    f"mis-routed line breaks per-key total order, the "
                    f"one property every fold depends on"))
    return report


def fsck_journal(path: str, *, skew_s: float = 0.0) -> FsckReport:
    """Validate one journal — a single file, or a segmented journal
    directory (dispatches on ``os.path.isdir``).  A missing path is an
    error (the gate is pointed at journals a drill claims to have
    produced)."""
    if os.path.isdir(path):
        return _fsck_segment_dir(path, skew_s=skew_s)
    report = FsckReport(path=path)
    if not os.path.isfile(path):
        report.issues.append(FsckIssue(
            0, "grammar", "error", f"journal file not found: {path}"))
        return report
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    report.issues, report.counts, report.n_lines = fsck_text(
        text, skew_s=skew_s)
    return report


def record_fsck(registry, report: FsckReport) -> None:
    """Publish one fsck verdict into a MetricsRegistry alongside the
    lint counters: ``journal_fsck_errors{kind=...}`` /
    ``journal_fsck_warnings{kind=...}`` per issue, plus the ok gauge."""
    from iterative_cleaner_tpu.telemetry.registry import labeled

    registry.gauge_set("journal_fsck_ok", 1 if report.ok else 0)
    registry.gauge_set("journal_fsck_lines", report.n_lines)
    registry.gauge_set("journal_fsck_segments", report.n_segments)
    for issue in report.issues:
        name = ("journal_fsck_errors" if issue.severity == "error"
                else "journal_fsck_warnings")
        registry.counter_inc(labeled(name, kind=issue.kind))

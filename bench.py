#!/usr/bin/env python
"""Benchmark: surgical-scrub cleaning throughput, jax/TPU vs the numpy oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "cell-iters/s", "vs_baseline": N,
   "platform": "tpu"|"cpu"|...}

"platform" records the device the jax number actually came from — when the
default accelerator is unreachable (dead tunnel) the bench falls back to a
small CPU run instead of hanging, and that must be distinguishable.
Env knobs: BENCH_SMALL=1 shrinks everything; BENCH_TIMEOUT (s) arms the
hang watchdog; BENCH_PROBE_TIMEOUT (s) bounds the device probe.

- value: per-iteration cell throughput (nsub * nchan / sec-per-iteration)
  for the compiled jax path on the high-res config (BASELINE.md config 3:
  1024 subints x 4096 channels), steady-state with the cube resident in
  HBM (the north star's "load once into HBM" model).  Per-iteration time
  is measured *differentially inside one program*: the whole clean runs K
  times in a fori_loop (optimization_barrier against CSE), one scalar
  leaves the device, and (t_K - t_1)/(K - 1) removes the tunnel's jittery
  ~20-100 ms per-dispatch cost (amortised over K-1 cleans and min-of-
  repeats — residual error a few ms); a second chained program subtracts
  the preamble so the figure is the iteration loop alone.  (Comparing two
  max_iter programs — the previous methodology — amortised nothing and
  overstated ms/iteration by ~2x.)  Falls back to the raw single-dispatch
  rate if the differential is noise.
- vs_baseline: that rate divided by the numpy oracle's rate.  On the
  full-size config the denominator is the RECORDED full-size oracle rate
  (1.54e4 cell-iters/s = 273.3 s/iteration, BASELINE.md "Measured
  baselines") — the honest headline methodology; a live 1/16-slice oracle
  still runs as an environment sanity check and its (cache-friendlier,
  ~2-3x higher) rate is reported on stderr.  Small/fallback configs divide
  by the live-measured rate instead (the recorded constant only describes
  the full-size config).
- hbm_util: achieved HBM bytes/s over the chip's peak bandwidth — the
  workload is bandwidth-bound (the fused path reads the cube 3x per
  iteration: template einsum + the two kernel reads), so this is the
  roofline number that distinguishes "fast" from "merely faster than
  numpy".  null off TPU or when the chip's bandwidth is unknown.

Environment knobs: BENCH_SMALL=1 shrinks everything for a quick smoke run;
BENCH_TIMEOUT (s) arms the hang watchdog; BENCH_PROBE_TIMEOUT (s) bounds
the device probe.
"""

import json
import os
import sys
import time

import numpy as np


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def oracle_full_rate():
    """Recorded full-size oracle rate (cell-iters/s), single-sourced from
    BASELINE.md's "Measured baselines" table (the config-3 row's
    "NNN s/iteration" figure — ~273.3 s/iteration as of round 1) so a
    re-measured oracle cannot silently diverge from the bench denominator.
    Resolved lazily: only the full-size headline branch needs it, and a
    small/fallback run must not die on a missing/reworded BASELINE.md.
    tests/test_bench_config.py guards the parse."""
    import re

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.md")
    with open(path) as fh:
        text = fh.read()
    m = re.search(r"config 3, full size[^\n]*?\(([\d.]+) s/iteration\)", text)
    if not m:
        raise RuntimeError(
            "could not parse the full-size oracle s/iteration figure from "
            "BASELINE.md 'Measured baselines' (config 3 row); bench.py's "
            "vs_baseline denominator is single-sourced there")
    return 1024 * 4096 / float(m.group(1))

# Peak HBM bandwidth by device_kind substring, bytes/s — single-sourced
# from the profiler's DEVICE_PEAKS table (telemetry/profiling.py), which
# is also the denominator behind prof_hbm_util on /metrics: the bench's
# hbm_util column and the live gauge must agree by construction.
from iterative_cleaner_tpu.telemetry.profiling import (  # noqa: E402
    hbm_peak as _hbm_peak,
)


def _cube_passes(stats_impl, stats_frame, baseline_mode="integration",
                 shape=None):
    """HBM cube reads per iteration for the bytes-moved model.

    The DEFAULT config (integration baseline + dispersed stats frame +
    pulse window off) runs the dispersed-frame iteration
    (engine/loop.py ``disp_iteration``): the one-read Pallas marginal
    pass over disp_clean covers the template AND the consensus
    correction, and the fused one-read kernel covers fit + residual +
    diagnostics — 2 cube passes total.  When the marginal kernel is
    ineligible (``shape`` beyond its VMEM cap, or no shape given) the
    dual-dot fallback reads the cube twice: 3.  The dedispersed frame
    keeps its own one-read kernel plus the template einsum (2) + the
    correction pass (1).  XLA paths use the dual-dot marginals (2) and
    additionally materialise the residual cube (write + two stat-pass
    reads on top of the fit read)."""
    if baseline_mode == "integration" and stats_frame == "dispersed":
        # disp_iteration (the default engine path)
        marginal = 2.0
        if stats_impl == "fused" and shape is not None:
            from iterative_cleaner_tpu.stats.pallas_kernels import (
                marginals_pallas_eligible,
            )

            if marginals_pallas_eligible(*shape):
                marginal = 1.0
        if stats_impl == "fused":
            return marginal + 1.0            # + the one-read cell kernel
        # XLA twin: marginals + fit read + resid write + 2 stat reads
        return marginal + 4.0
    base = 1.0 if baseline_mode == "integration" else 0.0
    if stats_impl == "fused":
        return base + (2.0 if stats_frame == "dedispersed" else 3.0)
    # template + fit read + base read + resid write + 2 stat reads
    return base + 6.0


def _sweep_cube_reads(cfg, nsub, nchan, nbin):
    """Per-iteration cube-tile reads by the sweep stage (template
    subtraction -> robust stats -> threshold/zap) for the route ``cfg``
    resolves to at this geometry.

    When the fused sweep engages the count is PROVEN, not narrated: the
    kernel is traced and its cube-ref loads counted by the same helper
    ``--selfcheck``'s single-read contract uses (anything but 1 is a
    broken contract and raises).  The multi-kernel route materialises
    the residual (one cube read) and reads it back for the diagnostics
    — two cube-sized HBM round trips per iteration: 2."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
        _count_cube_ref_reads,
    )
    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_fft_mode,
        resolve_fused_sweep,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.stats import pallas_kernels as pk

    dtype = jnp.dtype(cfg.dtype)
    fft_mode = resolve_fft_mode(cfg.fft_mode, dtype)
    stats_impl = resolve_stats_impl(cfg.stats_impl, dtype, nbin, fft_mode)
    if not (dtype == jnp.float32
            and resolve_fused_sweep(cfg.fused_sweep, stats_impl) == "on"
            and pk.fused_sweep_eligible(nsub, nchan, nbin)):
        return 2
    # trace at >= 2 subints: the kernel program is nsub-independent, and
    # the contract counter needs shape[0] != 1 to tell the cube ref from
    # the (1, s, c) cell tables
    ns = max(int(nsub), 2)
    f32 = jnp.float32
    cube = jax.ShapeDtypeStruct((ns, nchan, nbin), f32)
    plane = jax.ShapeDtypeStruct((ns, nchan), f32)
    mask = jax.ShapeDtypeStruct((ns, nchan), jnp.bool_)
    row = jax.ShapeDtypeStruct((nbin,), f32)
    closed = jax.make_jaxpr(
        lambda d, t, win, w, m: pk.fused_sweep_pallas_dedisp(
            d, t, win, w, m, float(cfg.chanthresh),
            float(cfg.subintthresh)))(cube, row, row, plane, mask)
    reads = _count_cube_ref_reads(closed)
    assert reads == [1], (
        "fused sweep kernel broke its single-read budget: %r" % (reads,))
    return reads[0]


def _arm_watchdog(seconds: float):
    """Hard-exit if the bench wedges (e.g. an unreachable device tunnel
    blocks inside PJRT init, which no Python signal can interrupt)."""
    import threading

    def boom():
        _log(f"bench watchdog: no result after {seconds:.0f}s, aborting")
        os._exit(3)

    t = threading.Timer(seconds, boom)
    t.daemon = True
    t.start()
    return t


def bench_jax(nsub, nchan, nbin, max_iter=5, repeats=4):
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        build_clean_fn,
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.engine.loop import (
        dispersed_residual_base,
        prepare_cube_jax,
    )
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )

    ar, truth = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin, **bench_rfi_density(nsub, nchan),
        seed=0, dtype=np.float32, disperse=False,
    )
    median_impl = resolve_median_impl("auto", jnp.float32)
    fft_mode = resolve_fft_mode("auto", jnp.float32)
    stats_impl = resolve_stats_impl("auto", jnp.float32, nbin, fft_mode)
    _log(f"median impl: {median_impl}, fft mode: {fft_mode}, "
         f"stats impl: {stats_impl}")
    # defaults of CleanConfig: dispersed stats frame, integration baseline
    fn = build_clean_fn(max_iter, 5.0, 5.0, (0, 0), 1.0, False, "fourier",
                        0.15, False, fft_mode, median_impl, stats_impl,
                        "dispersed", False, "integration")
    dev = jax.devices()[0]
    _log(f"jax device: {dev.platform} {getattr(dev, 'device_kind', '?')}")

    t0 = time.perf_counter()
    cube = jax.device_put(jnp.asarray(ar.total_intensity()), dev)
    weights = jax.device_put(jnp.asarray(ar.weights), dev)
    freqs = jax.device_put(jnp.asarray(ar.freqs_mhz), dev)
    args = (cube, weights, freqs,
            jnp.float32(ar.dm), jnp.float32(ar.centre_freq_mhz),
            jnp.float32(ar.period_s))
    cube.block_until_ready()
    h2d = time.perf_counter() - t0
    _log(f"H2D transfer of {cube.nbytes / 1e9:.2f} GB cube: {h2d:.3f}s")

    t0 = time.perf_counter()
    outs, _ = fn(*args)
    outs.final_weights.block_until_ready()
    compile_and_first = time.perf_counter() - t0
    loops = int(outs.loops)
    _log(f"compile+first run: {compile_and_first:.2f}s, loops={loops}, "
         f"rfi_frac={float((np.asarray(outs.final_weights) == 0).mean()):.4f}")

    # cleaning-quality scorecard against the injected truth (the run just
    # happened; scoring the mask is free) — reported alongside throughput
    # so a fast-but-wrong regression cannot hide in the headline number
    from iterative_cleaner_tpu.utils.quality import zap_quality

    quality = {
        k: (None if v is None else round(v, 4))
        for k, v in zap_quality(np.asarray(outs.final_weights), truth).items()
    }
    _log(f"zap quality vs injected truth: {quality}")

    # --- differential timing, robust to the tunnel ---------------------
    # The axon tunnel adds a large, *jittery* fixed cost per execute+fetch
    # (~20-100 ms) and its block_until_ready does not force execution, so
    # per-call wall clocks measure mostly noise.  Instead the whole clean
    # is applied K times inside ONE program (fori_loop; optimization_barrier
    # stops CSE/hoisting), one scalar leaves the device, and
    # (t_K - t_1)/(K - 1) removes the fixed cost.  The two programs are
    # still separate dispatches, so the jitter does not cancel exactly —
    # it is amortised over the K-1 extra cleans and the min over repeats;
    # residual error is ~jitter/(K-1)/repeats, a few ms at K=6.  A second
    # chained program measures the preamble (baseline removal +
    # dedispersion + disp_base) so the per-iteration cost can be separated
    # from the per-clean cost.

    def chained(inner, k):
        @jax.jit
        def run(*a):
            def body(_, c):
                a, acc = c
                a = jax.lax.optimization_barrier(a)
                return a, acc + inner(*a)
            return jax.lax.fori_loop(0, k, body, (a, jnp.float32(0)))[1]
        return run

    def clean_scalar(*a):
        outs, _ = fn(*a)
        return jnp.sum(outs.final_weights).astype(jnp.float32)

    def preamble_scalar(cube_, weights_, freqs_, dm_, ref_, period_):
        ded, shifts = prepare_cube_jax(
            cube_, freqs_, dm_, ref_, period_, baseline_duty=0.15,
            rotation="fourier")
        base = dispersed_residual_base(
            ded, shifts, pulse_slice=(0, 0), pulse_scale=1.0,
            pulse_active=False, rotation="fourier")
        # barrier: the tiny scalar must not let XLA dead-code the cubes
        ded, base = jax.lax.optimization_barrier((ded, base))
        return (ded[0, 0, 0] + base[0, 0, 0]).astype(jnp.float32)

    def diff_time(inner, k_lo=1, k_hi=6):
        lo, hi = chained(inner, k_lo), chained(inner, k_hi)
        float(lo(*args))  # compile + warm
        float(hi(*args))
        best_lo = best_hi = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(lo(*args))
            best_lo = min(best_lo, time.perf_counter() - t0)
            t0 = time.perf_counter()
            float(hi(*args))
            best_hi = min(best_hi, time.perf_counter() - t0)
        return (best_hi - best_lo) / (k_hi - k_lo), best_lo

    per_clean, t_single = diff_time(clean_scalar)
    per_preamble, _ = diff_time(preamble_scalar)
    raw_rate = nsub * nchan * loops / t_single
    _log(f"whole clean: {per_clean * 1e3:.1f} ms in-program "
         f"({t_single * 1e3:.1f} ms as a single dispatch incl. tunnel "
         f"round trip); preamble {per_preamble * 1e3:.1f} ms")
    if loops >= 1 and per_clean > per_preamble > 0:
        per_iter = (per_clean - per_preamble) / loops
        rate = nsub * nchan / per_iter
        _log(f"per-iteration: {per_iter * 1e3:.1f} ms over {loops} loops "
             f"-> {rate:.3e} cell-iters/s (fixed dispatch cost and "
             "preamble removed)")
    else:
        per_iter = None  # raw time still carries the fixed dispatch cost
        rate = raw_rate
        _log("differential timing unavailable (timer noise); reporting "
             "the raw single-dispatch rate")

    hbm_util = None
    peak = _hbm_peak(str(getattr(dev, "device_kind", "")))
    if peak and dev.platform == "tpu" and per_iter is not None:
        # Only meaningful on the differential time: the raw per-clean time
        # contains the ~20-100 ms fixed dispatch/D2H cost that would
        # silently skew the utilisation figure low.
        stats_frame = "dispersed"  # build_clean_fn default above
        passes = _cube_passes(stats_impl, stats_frame, "integration",
                              shape=(nsub, nchan, nbin))
        bytes_per_iter = passes * cube.nbytes
        achieved = bytes_per_iter / per_iter
        hbm_util = achieved / peak
        _log(f"modelled HBM traffic: {bytes_per_iter / 1e9:.2f} GB/iteration "
             f"({passes:.0f} cube passes, stats_impl={stats_impl}) -> "
             f"{achieved / 1e9:.0f} GB/s achieved / {peak / 1e9:.0f} GB/s "
             f"peak = {hbm_util:.2f} HBM utilisation")
    elif per_iter is None:
        _log("hbm_util omitted: no clean differential per-iteration time")
    extras = {
        "ms_per_iter": None if per_iter is None else round(per_iter * 1e3, 2),
        "loops": loops,
    }
    # convergence trajectory from the engine's on-device iteration history
    # (telemetry tentpole): lets a bench JSON line show *how* the run
    # converged, not just how fast it went
    im = np.asarray(outs.iter_metrics)[:loops]
    if im.size:
        extras["iter_history"] = {
            "zap_count": [int(v) for v in im[:, 0]],
            "mask_churn": [int(v) for v in im[:, 1]],
            "residual_std_final": round(float(im[-1, 2]), 4),
            "template_peak_final": round(float(im[-1, 3]), 4),
        }
    return rate, dev.platform, hbm_util, quality, extras


def bench_streaming(nsub, nchan, nbin, chunk, max_iter=3):
    """Exact-streaming device-efficiency row (VERDICT r3 #7).

    Exact mode pays one H2D per tile per pass — 3 passes/iteration under
    the default integration baseline (template partial + correction
    partial + diagnostics), parallel/streaming_exact.py — so its cost
    model is transfer-bound where the whole-archive path is HBM-bound.
    Reports tiles/s, effective transfer GB/s, and the wall-clock ratio
    vs the whole-archive clean of the SAME archive.
    ``streaming_eff_gbps`` is MEASURED: the tile cache
    (parallel/tile_cache.py) counts every H2D byte it actually moves into
    the run's MetricsRegistry (``stream_h2d_bytes``), so the figure
    reflects residency — a cache that pins tiles across iterations moves
    fewer bytes and the rate drops with wall time, as it should.  (The
    old cube-tile-upload model rode along one release as a ``modeled_``
    companion key and is gone.)  Wall-clock (not
    in-program differential) is the honest denominator here: the per-tile
    dispatch+H2D cost IS the thing being measured, amortised over
    loops x tiles x passes dispatches.
    """
    import math

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.parallel import clean_streaming_exact
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    t0 = time.perf_counter()
    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin, **bench_rfi_density(nsub, nchan),
        seed=0, dtype=np.float32,
    )
    _log(f"streaming stage: archive generated in "
         f"{time.perf_counter() - t0:.1f}s")
    cfg = CleanConfig(backend="jax", max_iter=max_iter)

    t0 = time.perf_counter()
    whole = clean_archive(ar.clone(), cfg)
    t_whole = time.perf_counter() - t0
    _log(f"streaming stage: whole-archive clean {t_whole:.1f}s "
         f"(loops={whole.loops})")

    reg = MetricsRegistry()
    t0 = time.perf_counter()
    stream = clean_streaming_exact(ar.clone(), chunk, cfg, registry=reg)
    t_stream = time.perf_counter() - t0
    assert np.array_equal(whole.final_weights == 0,
                          stream.final_weights == 0), \
        "exact streaming mask diverged from whole-archive (bench fixture)"

    n_tiles = math.ceil(nsub / chunk)
    passes = 3 if cfg.baseline_mode == "integration" else 2
    tiles_per_s = n_tiles * stream.loops * passes / t_stream
    h2d = int(reg.counters.get("stream_h2d_bytes", 0))
    eff_gbps = h2d / t_stream / 1e9
    hits = int(reg.counters.get("stream_cache_hits", 0))
    _log(f"streaming-exact ({nsub}x{nchan}x{nbin}, chunk {chunk}): "
         f"{t_stream:.2f}s vs whole {t_whole:.2f}s "
         f"({t_stream / t_whole:.2f}x), {tiles_per_s:.1f} tile-passes/s, "
         f"{eff_gbps:.3f} GB/s measured H2D ({h2d} bytes, {hits} cache "
         f"hits)")
    import jax

    return {
        # geometry + platform recorded so captures from hosts that fell
        # down the OOM ladder (smaller shape) or whose streaming
        # subprocess fell back to CPU while the headline ran on TPU are
        # never compared as regressions
        "streaming_geometry": f"{nsub}x{nchan}x{nbin}/chunk{chunk}",
        "streaming_platform": jax.default_backend(),
        "streaming_tile_passes_per_s": round(tiles_per_s, 1),
        "streaming_eff_gbps": round(eff_gbps, 3),
        "streaming_h2d_bytes": h2d,
        "streaming_vs_whole": round(t_stream / t_whole, 2),
        # per-iteration cube-tile reads of the sweep stage for this row's
        # resolved route (1 when the fused sweep engages, proven by the
        # --selfcheck contract counter; 2 on the multi-kernel route)
        "streaming_sweep_cube_reads": _sweep_cube_reads(
            cfg, min(chunk, nsub), nchan, nbin),
    }


def bench_batch(n_archives, nsub, nchan, nbin, max_iter=3):
    """Batch-mode row: N equal-shaped archives through one compiled
    vmap program (parallel/batch.py, BASELINE.md config 4) vs the same N
    cleaned sequentially with per-archive ``clean_archive`` calls.

    The sequential denominator reuses one compiled program across the
    loop (equal shapes hit the jit cache after archive 0), so the ratio
    isolates what batching actually buys: one dispatch + one H2D instead
    of N, and device parallelism across the batch axis where available.
    Masks must match the sequential path bit-for-bit (batch.py compiles
    the same per-archive math under vmap).  ``batch_h2d_bytes`` is the
    measured stacked-input upload size from the registry counter the
    batch path maintains.
    """
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.parallel import clean_archives_batched
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    t0 = time.perf_counter()
    archives = []
    for i in range(n_archives):
        ar, _ = make_synthetic_archive(
            nsub=nsub, nchan=nchan, nbin=nbin,
            **bench_rfi_density(nsub, nchan), seed=i, dtype=np.float32,
        )
        archives.append(ar)
    _log(f"batch stage: {n_archives} archives generated in "
         f"{time.perf_counter() - t0:.1f}s")
    cfg = CleanConfig(backend="jax", max_iter=max_iter)

    t0 = time.perf_counter()
    seq = [clean_archive(a.clone(), cfg) for a in archives]
    t_seq = time.perf_counter() - t0
    _log(f"batch stage: sequential x{n_archives} in {t_seq:.2f}s")

    reg = MetricsRegistry()
    t0 = time.perf_counter()
    batched = clean_archives_batched(archives, cfg, registry=reg)
    t_batch = time.perf_counter() - t0
    for i, (s, b) in enumerate(zip(seq, batched)):
        assert np.array_equal(s.final_weights == 0, b.final_weights == 0), \
            f"batched mask diverged from sequential (archive {i})"

    loops = max(b.loops for b in batched)
    rate = n_archives * nsub * nchan * loops / t_batch
    _log(f"batch ({n_archives} x {nsub}x{nchan}x{nbin}): {t_batch:.2f}s vs "
         f"sequential {t_seq:.2f}s ({t_batch / t_seq:.2f}x), "
         f"{rate:.3e} cell-iters/s")
    import jax

    return {
        "batch_n": n_archives,
        "batch_geometry": f"{nsub}x{nchan}x{nbin}",
        "batch_platform": jax.default_backend(),
        "batch_cell_iters_per_s": round(rate, 1),
        "batch_vs_sequential": round(t_batch / t_seq, 2),
        "batch_per_archive_ms": round(t_batch / n_archives * 1e3, 1),
        "batch_h2d_bytes": int(reg.counters.get("batch_h2d_bytes", 0)),
    }


def bench_fleet(n_archives, geometries, max_iter=3, group_size=8,
                io_workers=2):
    """Mixed-shape fleet row: n archives spread round-robin over several
    geometries, written to disk, then served end-to-end (load + clean +
    write) two ways — the sequential per-archive loop the CLI runs today,
    and the shape-bucketed fleet scheduler (parallel/fleet.py).

    Both paths run twice and the SECOND pass is timed: warm-vs-warm
    isolates the serving-pipeline win (batched dispatch + IO/compute
    overlap) from one-off compile cost, which the in-process jit caches
    would otherwise charge to whichever path ran first.  The cold fleet
    pass feeds the compile-amortization contract instead:
    ``fleet_compiles`` must equal ``fleet_buckets`` (one program per
    bucket — K shapes, K compiles, however many archives).  Masks must be
    bit-equal to the sequential path for every archive (quantization off;
    the assert is the rc-7 parity contract of the subprocess row).

    The warm-restart contract rides on top: the same fleet is served
    twice through the real CLI (two fresh processes) sharing a
    ``--compile-cache`` directory.  The second process must reload every
    bucket executable from the persistent cache — ``fleet_warm_compiles``
    (new cache entries written by the warm run) must be ZERO, and its
    serve time must beat the cold process's (``fleet_cold_vs_warm`` < 1,
    from each run's ``fleet_serve_s`` gauge so process startup and import
    cost don't pollute the ratio).  Warm-run output masks must stay
    bit-equal to the in-process sequential results — config drift between
    the CLI defaults and this stage's CleanConfig would surface here.
    """
    import dataclasses
    import shutil
    import tempfile

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io import load_archive, save_archive
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.parallel.fleet import clean_fleet
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        t0 = time.perf_counter()
        paths = []
        for i in range(n_archives):
            nsub, nchan, nbin = geometries[i % len(geometries)]
            ar, _ = make_synthetic_archive(
                nsub=nsub, nchan=nchan, nbin=nbin,
                **bench_rfi_density(nsub, nchan), seed=i, dtype=np.float32)
            p = os.path.join(tmp, "fleet_%03d.npz" % i)
            save_archive(ar, p)
            paths.append(p)
        _log(f"fleet stage: {n_archives} archives x "
             f"{len(geometries)} geometries generated in "
             f"{time.perf_counter() - t0:.1f}s")
        cfg = CleanConfig(backend="jax", max_iter=max_iter)

        def write_out(path, ar, result):
            out = dataclasses.replace(
                ar, weights=result.final_weights.astype(ar.weights.dtype))
            save_archive(out, path + "_cleaned.npz")

        def run_sequential():
            out = {}
            for p in paths:
                ar = load_archive(p)
                res = clean_archive(ar, cfg)
                write_out(p, ar, res)
                out[p] = res
            return out

        def run_fleet(reg):
            rep = clean_fleet(paths, cfg, registry=reg,
                              group_size=group_size, io_workers=io_workers,
                              write_fn=write_out)
            assert not rep.failures, rep.failures
            return rep

        run_sequential()                        # warm the per-archive jits
        cold_reg = MetricsRegistry()
        cold = run_fleet(cold_reg)              # cold: one compile/bucket
        # Timed passes interleave (seq, fleet, seq, fleet) and keep each
        # side's best: back-to-back blocks would charge container CPU
        # drift (cgroup burst credits draining over the run) to whichever
        # path happened to run last.
        t_seq = t_fleet = None
        seq = fleet = warm_reg = None
        for _ in range(2):
            t0 = time.perf_counter()
            seq = run_sequential()
            dt = time.perf_counter() - t0
            t_seq = dt if t_seq is None else min(t_seq, dt)
            warm_reg = MetricsRegistry()
            t0 = time.perf_counter()
            fleet = run_fleet(warm_reg)
            dt = time.perf_counter() - t0
            t_fleet = dt if t_fleet is None else min(t_fleet, dt)
        _log(f"fleet stage: sequential x{n_archives} warm in {t_seq:.2f}s")
        n_buckets = cold.n_buckets
        n_compiles = cold.n_compiles
        _log(f"fleet stage: {n_buckets} buckets, {n_compiles} compiles "
             f"(cold), warm serve {t_fleet:.2f}s vs sequential {t_seq:.2f}s "
             f"({t_fleet / t_seq:.2f}x)")
        for i, p in enumerate(paths):
            assert np.array_equal(seq[p].final_weights == 0,
                                  fleet.results[p].final_weights == 0), \
                f"fleet mask diverged from sequential (archive {i})"
        # the warm in-process passes must be served from the background
        # precompile pool's memo — a hit count of zero would mean the
        # pool is dead weight and every group paid inline compilation
        pre_hits = int(warm_reg.counters.get("fleet_precompile_hits", 0))
        pre_misses = int(warm_reg.counters.get("fleet_precompile_misses", 0))
        assert pre_hits >= 1, \
            f"warm fleet pass took {pre_hits} precompile hits " \
            f"({pre_misses} misses); background pool not serving"

        import subprocess

        import jax

        # Warm-restart contract through the real CLI: two fresh processes
        # over the SAME explicit path list (never a glob — it would sweep
        # up the *_cleaned outputs and silently change the fleet), sharing
        # one persistent compile-cache directory.
        cache_dir = os.path.join(tmp, "compile_cache")
        os.makedirs(cache_dir)

        def run_fleet_cli(tag):
            metrics_path = os.path.join(tmp, f"metrics_{tag}.json")
            cmd = [sys.executable, "-m", "iterative_cleaner_tpu", "-q",
                   "--fleet", "--batch", str(group_size),
                   "--io-workers", str(io_workers),
                   "--max_iter", str(max_iter),
                   "--compile-cache", cache_dir,
                   "--metrics-json", metrics_path] + paths
            env = {**os.environ,
                   "ICLEAN_PLATFORM": jax.default_backend(),
                   "ICLEAN_PROBE_TIMEOUT": "0",
                   "PYTHONPATH": os.pathsep.join(
                       [os.path.dirname(os.path.abspath(__file__))]
                       + os.environ.get("PYTHONPATH", "").split(os.pathsep)
                   ).rstrip(os.pathsep)}
            subprocess.run(cmd, env=env, check=True,
                           stdout=subprocess.DEVNULL)
            with open(metrics_path) as fh:
                return json.load(fh)

        cold_cli = run_fleet_cli("cold")
        n_cache_entries = len(os.listdir(cache_dir))
        warm_cli = run_fleet_cli("warm")
        warm_compiles = len(os.listdir(cache_dir)) - n_cache_entries
        cold_serve = float(cold_cli["gauges"]["fleet_serve_s"])
        warm_serve = float(warm_cli["gauges"]["fleet_serve_s"])
        _log(f"fleet stage: CLI restart serve {cold_serve:.2f}s cold -> "
             f"{warm_serve:.2f}s warm ({warm_serve / cold_serve:.2f}x), "
             f"{warm_compiles} cache entries written by the warm run")
        assert warm_compiles == 0, \
            f"warm CLI restart wrote {warm_compiles} new compile-cache " \
            "entries; persistent-cache keys are unstable across processes"
        assert warm_serve < cold_serve, \
            f"warm CLI restart served in {warm_serve:.2f}s vs cold " \
            f"{cold_serve:.2f}s; persistent cache bought nothing"
        for i, p in enumerate(paths):
            out = load_archive(p + "_cleaned.npz")
            assert np.array_equal(seq[p].final_weights == 0,
                                  out.weights == 0), \
                f"warm CLI mask diverged from sequential (archive {i})"

        # Resilience contract: the same fleet served under injected faults
        # (a transient load failure + a synthetic device OOM on the first
        # batched execute) must complete with ZERO failures and bit-equal
        # masks — the retry ladder absorbs the transient, the OOM ladder
        # splits the batch.  Keys pin that the drills actually fired.
        from iterative_cleaner_tpu.resilience import (
            FaultInjector,
            ResiliencePlan,
            RetryPolicy,
        )

        fault_reg = MetricsRegistry()
        fault_plan = ResiliencePlan(
            faults=FaultInjector("load:err@2,execute:oom@1", seed=1),
            retry=RetryPolicy(max_retries=3, backoff_base_s=0.01))
        t0 = time.perf_counter()
        fault_rep = clean_fleet(paths, cfg, registry=fault_reg,
                                group_size=group_size,
                                io_workers=io_workers,
                                resilience=fault_plan)
        fault_dt = time.perf_counter() - t0
        assert not fault_rep.failures, \
            f"faulted fleet serve leaked failures: {fault_rep.failures}"
        for i, p in enumerate(paths):
            assert np.array_equal(seq[p].final_weights == 0,
                                  fault_rep.results[p].final_weights == 0), \
                f"faulted fleet mask diverged from sequential (archive {i})"
        _log(f"fleet stage: faulted serve recovered in {fault_dt:.2f}s "
             f"({fault_rep.n_retries} retries, "
             f"{fault_rep.n_oom_splits} OOM splits, "
             f"{fault_rep.n_degraded} degraded)")
        assert fault_rep.n_retries >= 1, \
            "injected transient load fault never retried"
        assert fault_rep.n_oom_splits >= 1, \
            "injected execute OOM never split the batch"

        return {
            "fleet_n": n_archives,
            "fleet_geometries": "+".join(
                "%dx%dx%d" % tuple(g) for g in geometries),
            "fleet_platform": jax.default_backend(),
            "fleet_buckets": n_buckets,
            "fleet_compiles": n_compiles,
            "fleet_vs_sequential": round(t_fleet / t_seq, 2),
            "fleet_per_archive_ms": round(t_fleet / n_archives * 1e3, 1),
            "fleet_h2d_bytes": int(
                warm_reg.counters.get("batch_h2d_bytes", 0)),
            "fleet_precompile_hits": pre_hits,
            "fleet_precompile_misses": pre_misses,
            "fleet_cold_vs_warm": round(warm_serve / cold_serve, 2),
            "fleet_warm_compiles": warm_compiles,
            "fleet_retries": fault_rep.n_retries,
            "fleet_oom_splits": fault_rep.n_oom_splits,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_serve(n_requests, geometries, max_iter=3, io_workers=2,
                max_inflight=2, burst=10):
    """Service-daemon row: a real ``--serve`` CLI subprocess measured on
    its request lifecycle — submit-to-done latency warm vs cold, explicit
    backpressure under a saturation burst, and graceful-drain time.

    Phase A (latency): ``n_requests`` single-archive HTTP submissions,
    each awaited to its journaled terminal state before the next.  The
    first request pays the daemon's compiles (``serve_cold_ms``); the
    median of the rest is the steady-state figure
    (``serve_submit_to_done_ms``) — the number a pipeline scheduling
    against the daemon actually budgets.

    Phase B (saturation): ``max_inflight`` plug requests on fresh
    geometries pin the tenant at its admission cap for their whole
    seconds-long compiles, then ``burst`` submissions fire back-to-back;
    the daemon must answer the overflow with 429s
    (``serve_burst_rejected`` >= 1 — backpressure is explicit, never an
    unbounded queue) while every ACCEPTED request still completes, and a
    bounced id resubmitted after the plugs drain must be admitted.

    Masks must stay bit-equal to an in-process `clean_archive` over the
    same inputs (the rows' shared parity-is-fatal contract), and SIGTERM
    must drain to exit 0 (``serve_drain_s``).
    """
    import dataclasses  # noqa: F401  (parity uses archives, kept symmetric)
    import shutil
    import signal
    import subprocess
    import tempfile
    import urllib.error
    import urllib.request

    import jax

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io import load_archive, save_archive
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )

    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    proc = None
    try:
        cfg = CleanConfig(backend="jax", max_iter=max_iter)
        paths, want_masks = [], {}
        for i in range(n_requests):
            nsub, nchan, nbin = geometries[i % len(geometries)]
            ar, _ = make_synthetic_archive(
                nsub=nsub, nchan=nchan, nbin=nbin,
                **bench_rfi_density(nsub, nchan), seed=i, dtype=np.float32)
            p = os.path.join(tmp, "serve_%03d.npz" % i)
            save_archive(ar, p)
            paths.append(p)
            want_masks[p] = clean_archive(ar, cfg).final_weights == 0

        env = {**os.environ,
               "ICLEAN_PLATFORM": jax.default_backend(),
               "ICLEAN_PROBE_TIMEOUT": "0",
               "PYTHONPATH": os.pathsep.join(
                   [os.path.dirname(os.path.abspath(__file__))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)
               ).rstrip(os.pathsep)}
        out_path = os.path.join(tmp, "daemon.out")
        outf = open(out_path, "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "iterative_cleaner_tpu", "--serve",
             "--spool", "spool", "--http-port", "0",
             "--max-inflight", str(max_inflight),
             "--max_iter", str(max_iter),
             "--io-workers", str(io_workers), "-q"],
            env=env, cwd=tmp, stdout=outf, stderr=subprocess.STDOUT)
        needle = "serve: http listening on 127.0.0.1:"
        deadline = time.time() + 120
        port = None
        while time.time() < deadline and port is None:
            for line in open(out_path).read().splitlines():
                if line.startswith(needle):
                    port = int(line[len(needle):])
                    break
            if proc.poll() is not None:
                raise RuntimeError("serve daemon exited before binding:\n"
                                   + open(out_path).read()[-2000:])
            time.sleep(0.05)
        if port is None:
            raise RuntimeError("serve daemon never printed its port")
        url = "http://127.0.0.1:%d" % port

        def post(doc):
            req = urllib.request.Request(
                url + "/submit", data=json.dumps(doc).encode())
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status
            except urllib.error.HTTPError as exc:
                return exc.code

        def wait_done(rid, timeout_s=300):
            end = time.time() + timeout_s
            while time.time() < end:
                try:
                    with urllib.request.urlopen(
                            url + "/requests/" + rid, timeout=10) as r:
                        state = json.loads(r.read()).get("state")
                except urllib.error.HTTPError:
                    state = None
                if state in ("done", "failed"):
                    return state
                time.sleep(0.01)
            raise RuntimeError(f"request {rid} never finished")

        def span_breakdown(rid):
            """Pull the request's finished spans from the daemon's
            in-memory store (GET /trace/<id> needs no --trace-out) and
            split its wall-clock into the queue wait, the fleet execute
            time, and the rest of the bucket-group work (pad + compile
            stall + bookkeeping) — the trace-derived stage attribution
            of ``serve_submit_to_done_ms``."""
            with urllib.request.urlopen(url + "/trace/" + rid,
                                        timeout=10) as r:
                spans = json.loads(r.read()).get("spans", [])

            def total(pred):
                return sum((s["end_ts"] - s["start_ts"]) * 1e3
                           for s in spans if pred(s) and s.get("end_ts"))

            queue = total(lambda s: s["name"] == "queue")
            execute = total(lambda s: s["name"] == "execute"
                            and s.get("subsystem") == "fleet")
            groups = total(lambda s: s["name"] == "group")
            return queue, execute, max(groups - execute, 0.0)

        # phase A: sequential submit->done latency, cold then warm
        lat_ms, span_rows = [], []
        for i, p in enumerate(paths):
            rid = "lat%03d" % i
            t0 = time.perf_counter()
            status = post({"paths": [p], "id": rid})
            assert status == 200, f"submit {rid} answered {status}"
            assert wait_done(rid) == "done", f"request {rid} failed"
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            span_rows.append(span_breakdown(rid))
        cold_ms = lat_ms[0]
        warm = sorted(lat_ms[1:]) or [cold_ms]
        warm_ms = warm[len(warm) // 2]
        assert all(any(v > 0 for v in row) for row in span_rows), \
            "a served request produced no spans; /trace/<id> is broken"

        def med(vals):
            vals = sorted(vals)
            return vals[len(vals) // 2]

        warm_rows = span_rows[1:] or span_rows
        span_queue_ms = med([r[0] for r in warm_rows])
        span_execute_ms = med([r[1] for r in warm_rows])
        # compile/stall overhead is a COLD phenomenon (the warm daemon's
        # whole point is that it vanishes): report the first request's
        span_compile_ms = span_rows[0][2]
        _log(f"serve stage: {n_requests} sequential requests, "
             f"cold {cold_ms:.0f}ms -> warm median {warm_ms:.0f}ms "
             f"(spans: queue {span_queue_ms:.1f}ms, execute "
             f"{span_execute_ms:.1f}ms, cold compile+pad "
             f"{span_compile_ms:.1f}ms)")

        # phase B: saturation burst against the per-tenant cap.  The cap
        # is an ADMISSION-time budget (inflight counts from accept to
        # done), so ``max_inflight`` "plug" requests on FRESH geometries
        # pin the tenant at its cap for the full seconds-long compile —
        # the millisecond burst that follows then draws 429s
        # deterministically, with no race against warm completions.
        plug_ids = []
        for j in range(max_inflight):
            plug_ar, _ = make_synthetic_archive(
                nsub=32 + 8 * j, nchan=48, nbin=48,
                **bench_rfi_density(32 + 8 * j, 48),
                seed=999 - j, dtype=np.float32)
            plug_p = os.path.join(tmp, "serve_plug_%d.npz" % j)
            save_archive(plug_ar, plug_p)
            want_masks[plug_p] = \
                clean_archive(plug_ar, cfg).final_weights == 0
            paths.append(plug_p)
            pid = "plug%d" % j
            assert post({"paths": [plug_p], "id": pid}) == 200, \
                f"plug {pid} was not admitted"
            plug_ids.append(pid)
        accepted, bounced = [], []
        for i in range(burst):
            rid = "burst%03d" % i
            status = post({"paths": [paths[i % len(paths)]], "id": rid})
            if status == 200:
                accepted.append(rid)
            else:
                assert status == 429, f"burst overflow answered {status}"
                bounced.append(rid)
        for pid in plug_ids:
            assert wait_done(pid) == "done", f"plug {pid} failed"
        for rid in accepted:
            assert wait_done(rid) == "done", f"burst {rid} failed"
        rejected = len(bounced)
        assert rejected >= 1, \
            f"burst of {burst} at cap {max_inflight} drew no 429s; " \
            "backpressure is not engaging"
        # a 429 is backpressure, not a ban: the same id resubmitted
        # once the plugs drain must be admitted and complete
        assert post({"paths": [paths[0]], "id": bounced[0]}) == 200, \
            "rejected id was not admitted after the burst drained"
        assert wait_done(bounced[0]) == "done", \
            f"resubmitted {bounced[0]} failed"
        _log(f"serve stage: burst {burst} -> {len(accepted)} accepted, "
             f"{rejected} rejected (cap {max_inflight})")

        with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["failed"] == 0, health

        t0 = time.perf_counter()
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        drain_s = time.perf_counter() - t0
        assert rc == 0, f"drain exited {rc}:\n{open(out_path).read()[-2000:]}"
        _log(f"serve stage: drained in {drain_s:.2f}s (exit 0)")

        for i, p in enumerate(paths):
            got = load_archive(p + "_cleaned.npz")
            assert np.array_equal(want_masks[p], got.weights == 0), \
                f"serve mask diverged from in-process clean (archive {i})"

        return {
            "serve_n": n_requests,
            "serve_platform": jax.default_backend(),
            "serve_cold_ms": round(cold_ms, 1),
            "serve_submit_to_done_ms": round(warm_ms, 1),
            "serve_burst": burst,
            "serve_burst_rejected": rejected,
            "serve_drain_s": round(drain_s, 2),
            "serve_span_queue_ms": round(span_queue_ms, 2),
            "serve_span_execute_ms": round(span_execute_ms, 2),
            "serve_span_compile_ms": round(span_compile_ms, 2),
        }
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_multihost(n_archives, geometries, max_iter=2, claim_ttl=5.0):
    """Multi-host fleet row: the SAME archive list served by one
    ``--fleet`` process and by two cooperating ``--hosts 2`` processes
    sharing a journal (the pod-slice topology, degenerately on one
    machine — exactly how CI verifies it).

    Scenario A (scaling + parity): both host processes run to
    completion concurrently.  ``fleet_multihost_vs_single`` is the ratio
    of the slice's serve time (max of the two hosts' ``fleet_serve_s``
    gauges — the straggler defines the slice) to the single process's;
    on a multi-core host it must come in under 1.0 (each process
    compiles and serves only its hash-affine buckets), while on a single
    core the two processes merely timeshare, so the assert is gated on
    ``os.cpu_count()``.  Every output mask must be bit-equal to the
    single-process run's and every archive journaled 'done' exactly once
    — zero duplicate cleans (the rows' shared parity-is-fatal contract).

    Scenario B (host death): a fresh journal is pre-seeded with an
    EXPIRED claim from a fabricated dead host 1 (claimed, heartbeats
    stopped — the on-disk state an actual mid-serve SIGKILL leaves
    behind), then host 0 serves alone under ``--hosts 2``.  It must
    steal every host-1 bucket (``fleet_stolen`` >= 1), re-serve with
    bit-equal masks, and journal each archive done exactly once.
    """
    import shutil
    import subprocess
    import tempfile

    import jax

    from iterative_cleaner_tpu.io import load_archive, save_archive
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.parallel.fleet import (
        bucket_host,
        bucket_work_key,
    )
    from iterative_cleaner_tpu.resilience import FleetJournal

    tmp = tempfile.mkdtemp(prefix="bench_multihost_")
    try:
        t0 = time.perf_counter()
        paths, keys = [], set()
        for i in range(n_archives):
            nsub, nchan, nbin = geometries[i % len(geometries)]
            ar, _ = make_synthetic_archive(
                nsub=nsub, nchan=nchan, nbin=nbin,
                **bench_rfi_density(nsub, nchan), seed=i, dtype=np.float32)
            p = os.path.join(tmp, "mh_%03d.npz" % i)
            save_archive(ar, p)
            paths.append(p)
            keys.add((nsub, nchan, nbin, bool(ar.dedispersed)))
        owners = {bucket_host(k, 2) for k in keys}
        assert owners == {0, 1}, \
            f"geometry list hashes to hosts {owners}; pick shapes that " \
            "split across both hosts or the row measures nothing"
        _log(f"multihost stage: {n_archives} archives x {len(keys)} "
             f"buckets generated in {time.perf_counter() - t0:.1f}s")

        env = {**os.environ,
               "ICLEAN_PLATFORM": jax.default_backend(),
               "ICLEAN_PROBE_TIMEOUT": "0",
               "PYTHONPATH": os.pathsep.join(
                   [os.path.dirname(os.path.abspath(__file__))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)
               ).rstrip(os.pathsep)}

        # BENCH_TRACE_OUT=PATH also exports the multi-host Perfetto trace:
        # every host process spools spans to PATH.spans.jsonl and the last
        # finisher renders PATH with one lane group per host, including
        # the scenario-B steal stitched under the dead host's trace
        trace_out = os.environ.get("BENCH_TRACE_OUT", "")

        def fleet_cmd(tag, extra):
            metrics = os.path.join(tmp, f"metrics_{tag}.json")
            traced = (["--trace-out", trace_out] if trace_out
                      and tag != "single" else [])
            return metrics, [sys.executable, "-m", "iterative_cleaner_tpu",
                             "-q", "--fleet", "--max_iter", str(max_iter),
                             "--metrics-json", metrics] + traced \
                + extra + paths

        def read_metrics(path):
            with open(path) as fh:
                return json.load(fh)

        def collect_outputs():
            """Snapshot then DELETE the cleaned outputs, so each scenario
            proves its own writes (never a predecessor's leftovers)."""
            out = {}
            for p in paths:
                op = p + "_cleaned.npz"
                out[p] = load_archive(op).weights.copy()
                os.unlink(op)
            return out

        def assert_done_once(jpath):
            n_done = {}
            with open(jpath) as fh:
                for line in fh:
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(e, dict) and e.get("event") == "done":
                        n_done[e["path"]] = n_done.get(e["path"], 0) + 1
            dup = {p: n for p, n in n_done.items() if n != 1}
            assert not dup, f"duplicate cleans journaled: {dup}"
            assert len(n_done) == len(paths), \
                f"{len(n_done)}/{len(paths)} archives journaled done"

        # -- single-process reference ---------------------------------
        metrics_1, cmd = fleet_cmd("single", [])
        subprocess.run(cmd, env=env, check=True, stdout=subprocess.DEVNULL)
        serve_1 = float(read_metrics(metrics_1)["gauges"]["fleet_serve_s"])
        want = collect_outputs()

        # -- scenario A: two cooperating processes --------------------
        j_multi = os.path.join(tmp, "journal_multi.jsonl")
        procs = []
        for hid in (0, 1):
            metrics, cmd = fleet_cmd(
                f"h{hid}", ["--journal", j_multi, "--hosts", "2",
                            "--host-id", str(hid),
                            "--claim-ttl", str(claim_ttl)])
            procs.append((metrics, subprocess.Popen(
                cmd, env=env, stdout=subprocess.DEVNULL)))
        for _metrics, proc in procs:
            assert proc.wait(timeout=600) == 0, \
                f"multihost fleet process exited rc={proc.returncode}"
        serve_2 = max(
            float(read_metrics(m)["gauges"]["fleet_serve_s"])
            for m, _p in procs)
        got = collect_outputs()
        for i, p in enumerate(paths):
            assert np.array_equal(want[p], got[p]), \
                f"2-process masks diverged from single process (archive {i})"
        assert_done_once(j_multi)
        ratio = serve_2 / serve_1
        cores = os.cpu_count() or 1
        _log(f"multihost stage: slice serve {serve_2:.2f}s (2 procs) vs "
             f"{serve_1:.2f}s (1 proc) -> {ratio:.2f}x on {cores} cores")
        if cores >= 2:
            assert ratio < 1.0, \
                f"2 processes served in {serve_2:.2f}s vs single " \
                f"{serve_1:.2f}s on {cores} cores; sharding bought nothing"

        # -- scenario B: dead host's buckets stolen -------------------
        j_steal = os.path.join(tmp, "journal_steal.jsonl")
        dead = FleetJournal(j_steal)
        for k in keys:
            if bucket_host(k, 2) == 1:
                dead.record_claim(bucket_work_key(k), host=1,
                                  nonce="h1-dead-0-00000000", ttl_s=1.0,
                                  now=time.time() - 60.0)
        metrics_s, cmd = fleet_cmd(
            "steal", ["--journal", j_steal, "--hosts", "2", "--host-id",
                      "0", "--claim-ttl", str(claim_ttl)])
        subprocess.run(cmd, env=env, check=True, stdout=subprocess.DEVNULL)
        doc = read_metrics(metrics_s)
        stolen = int(doc["counters"].get("fleet_stolen", 0))
        assert stolen >= 1, \
            "survivor host stole no buckets from the dead host"
        got = collect_outputs()
        for i, p in enumerate(paths):
            assert np.array_equal(want[p], got[p]), \
                f"stolen re-serve masks diverged (archive {i})"
        assert_done_once(j_steal)
        _log(f"multihost stage: survivor stole {stolen} bucket(s) from "
             "the dead host, masks bit-equal, zero duplicate cleans")

        if trace_out:
            with open(trace_out) as fh:
                tdoc = json.load(fh)
            tev = tdoc["traceEvents"]
            hosts_seen = {e["pid"] for e in tev if e.get("ph") == "X"}
            assert len(hosts_seen) >= 2, \
                f"trace file covers {len(hosts_seen)} host lane(s); " \
                "expected spans from both fleet processes"
            _log(f"multihost stage: {trace_out} holds "
                 f"{sum(1 for e in tev if e.get('ph') == 'X')} spans "
                 f"across {len(hosts_seen)} host lanes")

        return {
            "fleet_hosts": 2,
            "fleet_multihost_platform": jax.default_backend(),
            "fleet_multihost_cores": cores,
            "fleet_multihost_vs_single": round(ratio, 2),
            "fleet_multihost_serve_s": round(serve_2, 2),
            "fleet_singlehost_serve_s": round(serve_1, 2),
            "fleet_stolen": stolen,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_elastic(geometries, max_iter=3, member_ttl=2.0,
                  journal_backend="segmented"):
    """Elastic-pool row: two ``--join`` daemons sharing one journal, then
    ``kill -9`` on the front door mid-burst — the drill ISSUE/ROADMAP call
    the pool's crash contract, measured instead of merely asserted.

    ``journal_backend`` selects the pool journal's storage: "segmented"
    (the default — the failover drill then doubles as the segmented
    backend's exactly-once/byte-parity proof under kill -9, with fsck
    run over the surviving directory) or "file" (the single-file
    backend the drill originally shipped against).

    Sequencing (proven in tests/test_elastic.py's chaos drill): member A
    is the front door with a ``load:hang@3`` fault, so request "big"
    (4 archives, 2 geometry buckets) journals its first bucket and
    wedges while "extra" waits behind it.  Member B joins mid-wedge and
    adopts "extra" from the shared journal (pool intake is shared even
    while the acceptor lives); "big" stays with A, whose execution lease
    is still heartbeating.  SIGKILL A: B observes the lapsed membership
    lease, evicts A, steals "big"'s claim and finishes it — resuming
    A's journaled bucket rather than re-cleaning it.

    Reported figures:

    * ``serve_failover_s`` — B's ``icln_serve_last_failover_s`` gauge:
      time from A's last heartbeat to the steal, the window a request
      can sit orphaned (bounded by the membership ttl).
    * ``cache_hit_vs_clean`` — a fresh-geometry request timed cold
      (real clean, including its compile), then the identical payload
      resubmitted and answered from the result cache (``n_cached`` == 1,
      zero device work); the ratio is what the cache buys.

    Fatal contracts (rc 7 via the *_ONLY branch): every accepted request
    completes, each archive journals 'done' exactly once across both
    members, and every mask is bit-equal to an in-process
    ``clean_archive`` over the same inputs.
    """
    import shutil
    import signal
    import subprocess
    import tempfile
    import urllib.request

    import jax

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io import load_archive, save_archive
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.resilience import FleetJournal
    from iterative_cleaner_tpu.telemetry import parse_prometheus_text

    # [g_a, g_a, g_b, g_b] -> request "big" spans two hash buckets (the
    # hang@3 fault wedges A BETWEEN them); g_a again for "extra"; g_cold
    # is a geometry nobody compiled, so the cold timing includes the
    # compile a real first-encounter clean pays
    g_a, g_b, g_cold = (tuple(g) for g in geometries[:3])
    shapes = [g_a, g_a, g_b, g_b, g_a, g_cold]
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    procs = []
    try:
        cfg = CleanConfig(backend="jax", max_iter=max_iter,
                          rotation="roll", fft_mode="dft")
        paths, want_masks = [], {}
        for i, (nsub, nchan, nbin) in enumerate(shapes):
            ar, _ = make_synthetic_archive(
                nsub=nsub, nchan=nchan, nbin=nbin,
                **bench_rfi_density(nsub, nchan), seed=100 + i,
                dtype=np.float32)
            p = os.path.join(tmp, "el_%03d.npz" % i)
            save_archive(ar, p)
            paths.append(p)
            want_masks[p] = clean_archive(ar, cfg).final_weights == 0

        if journal_backend == "segmented":
            # pre-create the directory (manifest included) so every
            # member auto-detects the backend from the path alone; a
            # small segment threshold makes the drill actually seal
            jpath = os.path.join(tmp, "pool.journal.d")
            FleetJournal(jpath + os.sep)
            jflags = ["--journal-segment-mb", "0.05"]
        else:
            jpath = os.path.join(tmp, "pool.journal.jsonl")
            jflags = []
        env = {**os.environ,
               "ICLEAN_PLATFORM": jax.default_backend(),
               "ICLEAN_PROBE_TIMEOUT": "0",
               "PYTHONPATH": os.pathsep.join(
                   [os.path.dirname(os.path.abspath(__file__))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)
               ).rstrip(os.pathsep)}

        def start_member(tag, extra=(), **env_extra):
            out_path = os.path.join(tmp, "member_%s.out" % tag)
            outf = open(out_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "iterative_cleaner_tpu", "--serve",
                 "--http-port", "0", "--rotation", "roll",
                 "--fft_mode", "dft", "--max_iter", str(max_iter),
                 "--io-workers", "1", "--join",
                 "--member-ttl", str(member_ttl), "--result-cache",
                 "--journal", jpath, *jflags,
                 "--spool", "spool_%s" % tag,
                 "--flight-recorder", "fr_%s.json" % tag, *extra],
                env={**env, **env_extra}, cwd=tmp,
                stdout=outf, stderr=subprocess.STDOUT)
            procs.append(proc)
            return proc, out_path

        def member_port(proc, out_path, timeout=120):
            needle = "serve: http listening on 127.0.0.1:"
            deadline = time.time() + timeout
            while time.time() < deadline:
                text = (open(out_path).read()
                        if os.path.exists(out_path) else "")
                for line in text.splitlines():
                    if line.startswith(needle):
                        return int(line[len(needle):])
                if proc.poll() is not None:
                    raise RuntimeError(
                        "member exited before binding (rc %s):\n%s"
                        % (proc.returncode, text[-2000:]))
                time.sleep(0.05)
            raise RuntimeError("member never printed its port")

        def spool_submit(tag, name, payload):
            spool = os.path.join(tmp, "spool_%s" % tag)
            os.makedirs(spool, exist_ok=True)
            tmp_name = os.path.join(spool, ".%s.tmp" % name)
            with open(tmp_name, "w") as f:
                f.write(json.dumps(payload))
            os.replace(tmp_name, os.path.join(spool, name + ".json"))

        def wait_request(rid, proc, timeout_s=300, tick=0.02):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                if os.path.exists(jpath):
                    state = FleetJournal(jpath).request_states().get(
                        rid, {}).get("state")
                    if state in ("done", "failed"):
                        return state
                assert proc.poll() is None, \
                    f"member exited (rc {proc.returncode}) before {rid}"
                time.sleep(tick)
            raise RuntimeError(f"request {rid} never reached terminal")

        def done_paths():
            if not os.path.exists(jpath):
                return []
            out = []
            # scan through the backend (dir-aware), not a raw file read
            for ln in FleetJournal(jpath).log.scan_text().splitlines():
                try:
                    e = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(e, dict) and e.get("event") == "done":
                    out.append(e["path"])
            return out

        # member A (front door): 3rd archive load hangs 600s -> "big"
        # journals its first bucket (2 archives), then wedges; "extra"
        # stays journaled 'accepted' behind it
        proc_a, out_a = start_member(
            "a", extra=["--faults", "load:hang@3"],
            ICLEAN_FAULT_HANG_S="600")
        member_port(proc_a, out_a)
        spool_submit("a", "big", {"paths": paths[:4]})
        spool_submit("a", "extra", {"paths": [paths[4]]})
        deadline = time.time() + 300
        while len(set(done_paths()) & set(paths[:4])) < 2:
            assert proc_a.poll() is None, \
                "front door exited before wedging:\n" \
                + open(out_a).read()[-2000:]
            assert time.time() < deadline, \
                "journal never showed per-archive progress"
            time.sleep(0.2)

        # member B joins mid-wedge and adopts the queued intake ("extra"
        # holds no execution lease; "big" does, and A is still live)
        proc_b, out_b = start_member("b")
        port_b = member_port(proc_b, out_b)
        assert wait_request("extra", proc_b) == "done", "adopted failed"

        # kill -9 the front door; the survivor evicts, steals, finishes
        t_kill = time.perf_counter()
        os.kill(proc_a.pid, signal.SIGKILL)
        proc_a.wait(timeout=60)
        assert wait_request("big", proc_b) == "done", "stolen failed"
        takeover_s = time.perf_counter() - t_kill

        url_b = "http://127.0.0.1:%d" % port_b
        parsed = parse_prometheus_text(urllib.request.urlopen(
            url_b + "/metrics", timeout=10).read().decode())
        evicted = int(parsed["icln_serve_members_evicted_total"])
        stolen = int(parsed["icln_serve_requests_stolen_total"])
        failover_s = float(parsed["icln_serve_last_failover_s"])
        assert evicted >= 1 and stolen >= 1 and failover_s > 0.0, parsed
        _log(f"elastic stage: survivor evicted {evicted} member(s), "
             f"stole {stolen} request(s); failover {failover_s:.2f}s "
             f"(kill -> big done {takeover_s:.2f}s)")

        # cache hit vs a real clean: a never-seen geometry timed cold
        # (compile + clean), then the identical payload again -> served
        # from the result cache with zero device work
        t0 = time.perf_counter()
        spool_submit("b", "cold", {"paths": [paths[5]]})
        assert wait_request("cold", proc_b) == "done", "cold clean failed"
        clean_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        spool_submit("b", "rerun", {"paths": [paths[5]]})
        assert wait_request("rerun", proc_b) == "done", "rerun failed"
        cached_s = time.perf_counter() - t0
        parsed = parse_prometheus_text(urllib.request.urlopen(
            url_b + "/metrics", timeout=10).read().decode())
        cache_hits = int(parsed.get("icln_serve_cache_hits_total", 0))
        assert cache_hits >= 1, "resubmission drew no cache hit"
        ratio = clean_s / max(cached_s, 1e-3)
        _log(f"elastic stage: cold clean {clean_s:.2f}s vs cached "
             f"{cached_s:.2f}s ({ratio:.1f}x)")

        proc_b.send_signal(signal.SIGTERM)
        rc = proc_b.wait(timeout=120)
        assert rc == 0, \
            f"drain exited {rc}:\n{open(out_b).read()[-2000:]}"

        # exactly-once + parity: one 'done' line per archive across both
        # members' lifetimes, every mask bit-equal to in-process cleans
        done = done_paths()
        assert len(done) == len(paths) and len(set(done)) == len(paths), \
            f"{len(done)} done lines over {len(set(done))} archives; " \
            "duplicate or missing cleans"
        states = FleetJournal(jpath).request_states()
        assert states["big"]["n_skipped"] == 2, states["big"]
        assert states["big"]["n_cleaned"] == 2, states["big"]
        assert states["rerun"].get("n_cached") == 1, states["rerun"]
        for i, p in enumerate(paths):
            got = load_archive(p + "_cleaned.npz")
            assert np.array_equal(want_masks[p], got.weights == 0), \
                f"elastic mask diverged from in-process clean (archive {i})"

        if journal_backend == "segmented":
            # the directory that survived a kill -9 must fsck green
            from iterative_cleaner_tpu.analysis.journal_fsck import (
                fsck_journal,
            )

            report = fsck_journal(jpath)
            assert report.ok, \
                "segmented journal fsck after the drill:\n" \
                + report.render_text()

        return {
            "elastic_journal_backend": journal_backend,
            "elastic_members": 2,
            "elastic_platform": jax.default_backend(),
            "serve_failover_s": round(failover_s, 2),
            "members_evicted": evicted,
            "requests_stolen": stolen,
            "elastic_takeover_s": round(takeover_s, 2),
            "cache_hits": cache_hits,
            "cache_hit_vs_clean": round(ratio, 1),
            "cache_clean_s": round(clean_s, 2),
            "cache_served_s": round(cached_s, 2),
        }
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_journal(n_members=50, n_requests=100000, segment_mb=1.0,
                  probe=300, n_paths=500):
    """Segmented-journal scale row: one journal aged by a synthetic
    ``n_members``-member pool through ``n_requests`` request lifecycles
    (plus membership and claim-lease churn) while a maintenance thread
    holding ``maint:<shard>`` leases seals and compacts CONCURRENTLY
    with the writes — the long-lived pool's steady state, measured.

    The headline is admission latency vs journal age: the front door's
    per-request work is one flocked append (the memoized pool fold runs
    on the daemon's ttl cadence, timed separately here), and on the
    segmented backend compaction only ever touches sealed segments, so
    an append never waits behind a whole-journal rewrite the way the
    single-file backend's flocked compaction makes it.  The row probes
    the same admission burst against the fresh journal and against the
    aged one (compactor still running both times) and reports the
    ratio — the ISSUE's tolerance band lives in benchtrack.

    Fatal contracts (rc 7 via the *_ONLY branch): after all the
    concurrent seal/compact churn the fold still sees EVERY request
    exactly once and the full roster — concurrent compaction lost
    nothing — and no torn-tail heal fired (single process: a heal here
    would mean the backend corrupted its own active segment)."""
    import shutil
    import tempfile
    import threading

    from iterative_cleaner_tpu.parallel.distributed import stable_shard
    from iterative_cleaner_tpu.resilience.journal import (
        SCHEMA,
        FleetJournal,
        entry_key,
    )
    from iterative_cleaner_tpu.serve.membership import PoolMembership
    from iterative_cleaner_tpu.telemetry import MetricsRegistry
    from iterative_cleaner_tpu.utils.logging import locked_append

    tmp = tempfile.mkdtemp(prefix="bench_journal_")
    stop = threading.Event()
    maint_thread = None
    try:
        reg = MetricsRegistry()
        j = FleetJournal(os.path.join(tmp, "journal.d") + os.sep,
                         segment_mb=segment_mb, registry=reg)
        nsh = j.n_shards()
        t_base = time.time()

        # -- the maintenance role: claim maint:<shard>, grind, release —
        # exactly the daemon's _maintain_segments loop, kept running
        # through aging AND both probes so every measurement includes
        # live concurrent compaction
        maint = PoolMembership(j, ttl_s=30.0, member_id="bench-maint",
                               host=10_000)
        compactions = {"n": 0}

        def grind():
            while not stop.is_set():
                j.seal()
                for shard in range(nsh):
                    if stop.is_set():
                        return
                    if not maint.claim_maintenance(shard):
                        continue
                    try:
                        if j.compact_shard(shard):
                            compactions["n"] += 1
                    finally:
                        maint.release_maintenance(shard)
                stop.wait(0.05)

        maint_thread = threading.Thread(target=grind, daemon=True,
                                        name="bench-journal-maint")
        maint_thread.start()

        def probe_admissions(tag):
            """One admission burst: per request, the front door's
            journal work (the accept append; the done append closes the
            lifecycle but is not timed — it happens after the clean).
            Returns (mean_ms, p99_ms, fold_s) with the full pool fold
            timed once, the daemon's memoized cadence."""
            t0 = time.perf_counter()
            states = j.request_states()
            fold_s = time.perf_counter() - t0
            lat = []
            for i in range(probe):
                rid = "probe-%s-%05d" % (tag, i)
                assert rid not in states
                t0 = time.perf_counter()
                j.record_request(rid, "accepted", tenant="bench")
                lat.append(time.perf_counter() - t0)
                j.record_request(rid, "done")
            lat.sort()
            mean_ms = 1000.0 * sum(lat) / len(lat)
            p99_ms = 1000.0 * lat[min(len(lat) - 1,
                                      int(0.99 * len(lat)))]
            return mean_ms, p99_ms, fold_s

        admit_fresh_ms, admit_fresh_p99, fold_fresh_s = \
            probe_admissions("fresh")

        # -- age the journal: n_requests lifecycles from a 50-member
        # pool, bulk-written in per-shard chunks (the line format and
        # routing are exactly FleetJournal's; one flock per chunk keeps
        # the aging phase seconds, not minutes)
        log = j.log
        buf = {s: [] for s in range(nsh)}

        def emit(entry):
            buf[stable_shard(entry_key(entry), nsh)].append(
                json.dumps(entry, sort_keys=True) + "\n")

        def flush():
            for s, lines in buf.items():
                if lines:
                    locked_append(log._active_path(s), "".join(lines))
                    del lines[:]

        for m in range(n_members):
            emit({"schema": SCHEMA, "event": "member",
                  "member": "m%03d" % m, "host": m, "state": "join",
                  "t": t_base, "ttl": 86400.0})
        for i in range(n_requests):
            rid = "r%06d" % i
            emit({"schema": SCHEMA, "event": "req", "req": rid,
                  "state": "accepted", "tenant": "t%d" % (i % 7),
                  "paths": ["/pool/in_%04d" % (i % n_paths)]})
            emit({"schema": SCHEMA, "event": "req", "req": rid,
                  "state": "done"})
            if i % 10 == 0:
                # claim-lease churn: granted then released, so
                # compaction drops the pair — pure fold noise while live
                work = "w%05d" % i
                t = t_base + i * 1e-4
                base = {"schema": SCHEMA, "event": "claim",
                        "work": work, "host": i % n_members,
                        "nonce": "n%d" % i}
                emit({**base, "state": "claim", "t": t, "ttl": 30.0})
                emit({**base, "state": "release", "t": t + 1e-5,
                      "ttl": 0.0})
            if i % 25 == 0:
                m = (i // 25) % n_members
                emit({"schema": SCHEMA, "event": "member",
                      "member": "m%03d" % m, "host": m, "state": "hb",
                      "t": t_base + i * 1e-4, "ttl": 86400.0})
            if i % 2000 == 1999:
                flush()
        flush()
        _log("journal stage: aged %d requests over %d members "
             "(%d compactions so far, %.1f MB live)"
             % (n_requests, n_members, compactions["n"],
                j.size_bytes() / 1e6))

        admit_aged_ms, admit_aged_p99, fold_aged_s = \
            probe_admissions("aged")
        stop.set()
        maint_thread.join(timeout=120)

        # concurrent compaction lost NOTHING: every request folds back
        # exactly once, the full roster survives, and no heal fired
        states = j.request_states()
        assert len(states) == n_requests + 2 * probe, \
            "fold lost requests under concurrent compaction: " \
            f"{len(states)} != {n_requests + 2 * probe}"
        assert all(v["state"] == "done" for v in states.values())
        roster = j.member_table(now=t_base + 60.0)
        assert len(roster) == n_members, \
            f"roster lost members: {len(roster)} != {n_members}"
        heals = reg.snapshot()["counters"].get("journal_torn_heals", 0)
        assert heals == 0, f"{heals} torn heals in a single-process run"

        seg_counts = j.segment_counts()
        row = {
            "journal_backend": "segmented",
            "journal_members": n_members,
            "journal_requests": n_requests,
            "journal_admit_fresh_ms": round(admit_fresh_ms, 3),
            "journal_admit_aged_ms": round(admit_aged_ms, 3),
            "journal_admit_aged_vs_fresh": round(
                admit_aged_ms / max(admit_fresh_ms, 1e-6), 2),
            "journal_admit_aged_p99_ms": round(admit_aged_p99, 3),
            "journal_fold_fresh_s": round(fold_fresh_s, 4),
            "journal_fold_aged_s": round(fold_aged_s, 4),
            "journal_live_bytes": int(j.size_bytes()),
            "journal_segments_total": int(sum(seg_counts.values())),
            "journal_compactions": int(compactions["n"]),
        }
        _log("journal stage: admission %.3f ms fresh -> %.3f ms aged "
             "(%.1fx, p99 %.3f ms); fold %.3fs -> %.3fs; "
             "%d compactions, %d live segments"
             % (admit_fresh_ms, admit_aged_ms,
                row["journal_admit_aged_vs_fresh"], admit_aged_p99,
                fold_fresh_s, fold_aged_s, compactions["n"],
                row["journal_segments_total"]))
        return row
    finally:
        stop.set()
        if maint_thread is not None:
            maint_thread.join(timeout=120)
        shutil.rmtree(tmp, ignore_errors=True)


def bench_online(n_subints, nchan, nbin, reconcile_every=4, bucket_pad=8,
                 max_iter=3):
    """Online-mode row (online/session.py): per-subint zap latency for a
    live stream, measured subint by subint through an OnlineSession.

    Three contracts, all fatal when broken:

    * ``online_recompiles_steady`` == 0 — after warm-up (the one step
      compile plus one reconcile compile per capacity bucket) a live
      stream must never hit the compiler again; a recompile in steady
      state IS the latency regression this subsystem exists to prevent.
    * ``online_vs_batch_masks`` — the close reconciliation's mask must be
      bit-equal with ``clean_archive`` over the same subints (the rows'
      shared parity-is-fatal contract, rc 7).
    * ``online_subint_p99_ms`` is computed over post-warm-up subints
      (the first pays the compile; a pipeline budgets the steady tail).
    """
    import jax  # noqa: F401  (the session's step is a compiled program)

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.online import OnlineSession
    from iterative_cleaner_tpu.online.chunks import StreamMeta
    from iterative_cleaner_tpu.online.session import percentile_ms

    ar, _ = make_synthetic_archive(
        nsub=n_subints, nchan=nchan, nbin=nbin,
        **bench_rfi_density(n_subints, nchan), seed=0, dtype=np.float32)
    cfg = CleanConfig(backend="jax", max_iter=max_iter,
                      fleet_bucket_pad=(bucket_pad, 0),
                      stream_reconcile_every=reconcile_every)
    cube = np.asarray(ar.total_intensity(), dtype=np.float64)
    weights = np.asarray(ar.weights, dtype=np.float64)

    session = OnlineSession(StreamMeta.from_archive(ar), cfg)
    t0 = time.perf_counter()
    for i in range(n_subints):
        session.ingest(cube[i], weights[i], label="subint%03d" % i)
    result = session.close()
    dt = time.perf_counter() - t0

    batch_mask = clean_archive(ar, cfg).final_weights == 0
    online_mask = np.asarray(result.archive.weights) == 0
    assert np.array_equal(online_mask, batch_mask), (
        "online close-reconciled mask diverged from the batch clean "
        "(%d cells)" % int(np.sum(online_mask != batch_mask)))
    assert result.recompiles_steady == 0, (
        "online mode recompiled %d time(s) in steady state (warm-up "
        "compiles: %d)" % (result.recompiles_steady,
                           result.warmup_compiles))

    steady = result.latencies_s[1:] or result.latencies_s
    p50 = percentile_ms(steady, 50.0)
    p99 = percentile_ms(steady, 99.0)
    _log(f"online ({n_subints} subints of {nchan}x{nbin}): "
         f"p50 {p50:.1f} ms, p99 {p99:.1f} ms per subint, "
         f"{result.warmup_compiles} warm-up compiles, 0 steady, "
         f"{result.reconciles} reconciles, "
         f"drift {result.mask_drift}+{result.final_drift}, {dt:.2f}s total")
    return {
        "online_n": n_subints,
        "online_subint_p50_ms": round(p50, 3),
        "online_subint_p99_ms": round(p99, 3),
        "online_warmup_compiles": int(result.warmup_compiles),
        "online_recompiles_steady": int(result.recompiles_steady),
        "online_reconciles": int(result.reconciles),
        "online_mask_drift": int(result.mask_drift + result.final_drift),
        "online_vs_batch_masks": "identical",
        # per-subint cube reads of the provisional-zap sweep (nsub=1
        # step): 1 when the fused route engages, 2 multi-kernel
        "online_sweep_cube_reads": _sweep_cube_reads(cfg, 1, nchan, nbin),
    }


def bench_mux(n_streams, n_subints, nchan, nbin, max_batch=None,
              bucket_pad=8, max_iter=3):
    """Multiplexed online-serving row (online/mux.py): a synthetic burst
    of ``n_streams`` live streams fed round-robin through ONE StreamMux
    vs the same subints through N independent OnlineSessions.

    The sequential baseline shares one pre-jitted step across its N
    sessions (the ``step_fn=`` kwarg), so the measured ratio is pure
    dispatch amortization — batching ``max_batch`` streams' heads into
    one device call — not N-1 avoided compiles.  Both paths are warmed
    before timing (the baseline's shared step on a throwaway session;
    the mux's batch rungs with throwaway lanes), so the timed window is
    the steady state both subsystems contract to serve.

    Contracts, fatal when broken (rc 7 through the bench subprocess):

    * ``mux_recompiles_steady`` == 0 — every (bucket, rung) executable
      compiles during warm-up; a steady-state recompile IS the latency
      regression the rung ladder exists to prevent.
    * ``mux_vs_sequential_masks`` — every stream's provisional weights
      must be bit-equal with its independent-session twin, subint by
      subint (scores compared with equal_nan: the nsub=1 channel-median
      degeneracy makes provisional scores NaN on BOTH paths).
    """
    import jax

    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.online import OnlineSession, StreamMux
    from iterative_cleaner_tpu.online.chunks import StreamMeta
    from iterative_cleaner_tpu.online.session import (
        percentile_ms,
        resolve_ew_alpha,
    )
    from iterative_cleaner_tpu.online.step import build_subint_step

    cfg = CleanConfig(backend="jax", max_iter=max_iter,
                      fleet_bucket_pad=(0, bucket_pad),
                      stream_reconcile_every=0)
    streams = []
    for s in range(n_streams):
        ar, _ = make_synthetic_archive(
            nsub=n_subints, nchan=nchan, nbin=nbin,
            **bench_rfi_density(n_subints, nchan), seed=s,
            dtype=np.float32)
        streams.append((StreamMeta.from_archive(ar),
                        np.asarray(ar.total_intensity(), np.float64),
                        np.asarray(ar.weights, np.float64)))

    # ---- sequential baseline: N independent sessions, ONE shared step
    alpha = resolve_ew_alpha(cfg.stream_ew_alpha)
    shared = jax.jit(build_subint_step(cfg, nchan, nbin, False, alpha)[0])
    warm = OnlineSession(streams[0][0], cfg, step_fn=shared)
    warm.ingest(streams[0][1][0], streams[0][2][0], label="warm")
    solo = []
    t0 = time.perf_counter()
    for s, (meta, cube, weights) in enumerate(streams):
        sess = OnlineSession(meta, cfg, step_fn=shared)
        for i in range(n_subints):
            sess.ingest(cube[i], weights[i], label="subint%03d" % i)
        solo.append(sess)
    t_seq = time.perf_counter() - t0

    # ---- multiplexed: one mux, round-robin burst, manual pump
    mux = StreamMux(max_batch=max_batch)
    msess = [mux.open("s%03d" % s, meta, cfg)
             for s, (meta, _c, _w) in enumerate(streams)]
    # warm every batch rung the burst will hit with throwaway lanes:
    # each round pops chunks of max_batch heads plus one tail chunk
    mb = mux.max_batch
    full_rounds, tail = divmod(n_streams, mb)
    warm_pops = set()
    if full_rounds:
        warm_pops.add(mb)
    if tail:
        warm_pops.add(tail)
    warm_meta, warm_cube, warm_w = streams[0]
    wi = 0
    for size in sorted(warm_pops):
        keys = []
        for _ in range(size):
            k = "_warm_%03d" % wi
            wi += 1
            mux.open(k, warm_meta, cfg)
            mux.ingest(k, warm_cube[0], warm_w[0], label="warm")
            keys.append(k)
        mux.pump(force=True)
        for k in keys:
            mux.abandon_stream(k)
    warm_dispatches = mux.dispatches

    t0 = time.perf_counter()
    for i in range(n_subints):
        for s, (_meta, cube, weights) in enumerate(streams):
            mux.ingest("s%03d" % s, cube[i], weights[i],
                       label="subint%03d" % i, block=True)
        mux.pump(force=True)
    mux.drain()
    t_mux = time.perf_counter() - t0

    # ---- contracts
    assert mux.recompiles_steady == 0, (
        "mux recompiled %d time(s) in steady state (warm-up compiles: "
        "%d)" % (mux.recompiles_steady, mux.warmup_compiles))
    for s in range(n_streams):
        a, b = msess[s], solo[s]
        n = a.n_subints
        assert n == b.n_subints == n_subints, (s, n, b.n_subints)
        assert np.array_equal(a._pweights[:n], b._pweights[:n]), (
            "mux provisional weights diverged from the independent "
            "session on stream %d" % s)
        assert np.array_equal(a._pscores[:n], b._pscores[:n],
                              equal_nan=True), (
            "mux provisional scores diverged from the independent "
            "session on stream %d" % s)

    total = n_streams * n_subints
    rate = total / t_mux
    speedup = t_seq / t_mux
    lat = [lt for sess in msess for lt in sess.latencies_s]
    p99 = percentile_ms(lat, 99.0)
    occ_all = mux.batch_occupancies[warm_dispatches:]
    occ = (sum(occ_all) / len(occ_all)) if occ_all else 0.0
    _log(f"mux ({n_streams} streams x {n_subints} subints of "
         f"{nchan}x{nbin}, max_batch {mb}): {rate:.1f} subints/s "
         f"aggregate, {speedup:.1f}x vs sequential "
         f"({t_mux:.2f}s vs {t_seq:.2f}s), p99 {p99:.1f} ms, "
         f"occupancy {occ:.2f}, {mux.warmup_compiles} warm-up "
         f"compiles, 0 steady")
    return {
        "mux_platform": jax.default_backend(),
        "mux_n_streams": int(n_streams),
        "mux_n_subints": int(total),
        "mux_max_batch": int(mb),
        "mux_aggregate_subints_per_s": round(rate, 2),
        "mux_vs_sequential": round(speedup, 3),
        "mux_subint_p99_ms": round(p99, 3),
        "mux_batch_occupancy": round(occ, 4),
        "mux_warmup_compiles": int(mux.warmup_compiles),
        "mux_recompiles_steady": int(mux.recompiles_steady),
        "mux_vs_sequential_masks": "identical",
    }


def bench_fused(nsub, nchan, nbin, max_iter=3, chunk=None):
    """Fused-sweep row (stats/pallas_kernels.py ``fused_sweep_pallas*``):
    the one-launch sweep (``--fused-sweep on``) against the multi-kernel
    route it replaces (``off``), same archive, both warm.

    ``fused_vs_unfused`` is warm best-of-2 wall clock (the compile and a
    first warming run are paid before any timing).  The CPU-provable wins
    ride alongside and ARE asserted, because they are deterministic:
    a strictly smaller per-iteration program (``fused_eqns`` <
    ``fused_unfused_eqns``), strictly fewer streaming H2D bytes (the
    exact-streaming combine tail keeps its diagnostic planes on device),
    and the single-read cube budget (``fused_sweep_cube_reads`` == 1,
    counted from the traced kernel by the --selfcheck contract helper).
    Mask parity between the routes is rc-7 fatal like every row above."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.analysis.jaxpr_contracts import iter_eqns
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.backends.jax_backend import (
        build_clean_fn,
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.parallel import clean_streaming_exact
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin, **bench_rfi_density(nsub, nchan),
        seed=0, dtype=np.float32)
    # median_impl=pallas is the apples-to-apples baseline: the kth-select
    # lane machinery the sweep absorbs (the sort baseline trades kernel
    # equations for one opaque XLA sort and would flatter neither route)
    base = dict(backend="jax", dtype="float32", stats_impl="fused",
                fft_mode="dft", median_impl="pallas", max_iter=max_iter)
    results, times = {}, {}
    for mode in ("on", "off"):
        cfg = CleanConfig(fused_sweep=mode, **base)
        clean_archive(ar.clone(), cfg)          # compile + warm
        for _ in range(2):                      # warm best-of-2
            t0 = time.perf_counter()
            results[mode] = clean_archive(ar.clone(), cfg)
            dt = time.perf_counter() - t0
            times[mode] = min(times.get(mode, dt), dt)
    assert np.array_equal(results["on"].final_weights,
                          results["off"].final_weights), (
        "fused sweep masks diverged from the multi-kernel route (%d cells)"
        % int(np.sum(results["on"].final_weights
                     != results["off"].final_weights)))

    # per-iteration program size, fused vs the route it replaces
    c = CleanConfig(**base)
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)

    def eqns(mode):
        fn = build_clean_fn(
            c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
            c.pulse_scale, c.pulse_region_active, c.rotation,
            c.baseline_duty, c.unload_res, fft_mode,
            resolve_median_impl(c.median_impl, dtype),
            resolve_stats_impl(c.stats_impl, dtype, nbin, fft_mode),
            resolve_stats_frame(c.stats_frame, dtype), False,
            c.baseline_mode, donate=True, fused_sweep=mode)
        f32 = jnp.float32
        avals = (jax.ShapeDtypeStruct((nsub, nchan, nbin), f32),
                 jax.ShapeDtypeStruct((nsub, nchan), f32),
                 jax.ShapeDtypeStruct((nchan,), f32),
                 jax.ShapeDtypeStruct((), f32),
                 jax.ShapeDtypeStruct((), f32),
                 jax.ShapeDtypeStruct((), f32))
        return sum(1 for _ in iter_eqns(jax.make_jaxpr(fn)(*avals).jaxpr))

    e_on, e_off = eqns("on"), eqns("off")
    assert e_on < e_off, (
        "fused program no longer shrinks the multi-kernel route: "
        "%d vs %d equations" % (e_on, e_off))

    # exact-streaming H2D bytes: the fused combine keeps its per-tile
    # diagnostic planes on device instead of re-uploading them
    chunk = chunk or max(4, nsub // 4)
    s_base = dict(base, median_impl="sort")
    h2d, sres = {}, {}
    for mode in ("on", "off"):
        reg = MetricsRegistry()
        sres[mode] = clean_streaming_exact(
            ar.clone(), chunk, CleanConfig(fused_sweep=mode, **s_base),
            registry=reg)
        h2d[mode] = int(reg.counters.get("stream_h2d_bytes", 0))
    assert np.array_equal(sres["on"].final_weights,
                          sres["off"].final_weights), \
        "fused streaming combine masks diverged from the unfused tail"
    assert 0 < h2d["on"] < h2d["off"], (
        "fused streaming route moved no fewer H2D bytes: %d vs %d"
        % (h2d["on"], h2d["off"]))

    reads = _sweep_cube_reads(CleanConfig(fused_sweep="on", **base),
                              nsub, nchan, nbin)
    assert reads == 1, reads

    _log(f"fused ({nsub}x{nchan}x{nbin}): warm best-of-2 "
         f"{times['on'] * 1e3:.1f} ms fused vs {times['off'] * 1e3:.1f} ms "
         f"unfused ({times['on'] / times['off']:.2f}x), "
         f"{e_on} vs {e_off} eqns, stream H2D {h2d['on']} vs "
         f"{h2d['off']} bytes, {reads} cube read(s)/iteration")
    return {
        "fused_geometry": f"{nsub}x{nchan}x{nbin}",
        "fused_platform": jax.default_backend(),
        "fused_vs_unfused": round(times["on"] / times["off"], 3),
        "fused_sweep_cube_reads": int(reads),
        "fused_eqns": int(e_on),
        "fused_unfused_eqns": int(e_off),
        "fused_stream_h2d_bytes": h2d["on"],
        "fused_unfused_stream_h2d_bytes": h2d["off"],
    }


def _bf16_exact_archive(nsub, nchan, nbin, seed=0):
    """Synthetic archive whose WHOLE engine pipeline is bf16-lossless by
    construction, so the fp32 and bf16 compute paths see bit-identical
    values: every sample sits on the bfloat16 grid, dm=0 (zero channel
    shifts; rotation='roll' is then the identity permutation), and the
    last quarter of every profile is exactly zero — with all samples
    non-negative the baseline finder's min-mean window lands on (or ties
    with) that zero run, so the subtracted baseline is exactly 0 and the
    prepared cube equals the raw one.  RFI spikes stay inside the first
    half so they cannot perturb the window, and per-subint/per-channel
    gain slopes keep the cross-cell robust stats non-degenerate."""
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )

    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin, seed=seed, dtype=np.float32,
        dm=0.0, disperse=False)
    rng = np.random.default_rng(seed)
    phase = (np.arange(nbin) + 0.5) / nbin
    profile = np.exp(-0.5 * ((phase - 0.3) / 0.05) ** 2)
    spectrum = 1.0 + 0.5 * np.arange(nchan) / nchan
    gain = 1.0 + 0.3 * np.arange(nsub) / max(1, nsub)
    cube = (30.0 * gain[:, None, None] * spectrum[None, :, None]
            * profile[None, None, :]).astype(np.float32)
    cube[:, :, 3 * nbin // 4:] = 0.0    # the guaranteed-zero window
    n_rfi = bench_rfi_density(nsub, nchan)["n_rfi_cells"]
    cells = rng.choice(nsub * nchan, size=n_rfi, replace=False)
    for s, c in zip(*np.unravel_index(cells, (nsub, nchan))):
        bins = rng.integers(0, nbin // 2, size=max(1, nbin // 16))
        cube[s, c, bins] += 40.0
    import jax.numpy as jnp

    ar.data[:, 0] = np.asarray(
        jnp.asarray(cube, jnp.bfloat16).astype(jnp.float32))
    ar.dm = 0.0
    return ar


def bench_bf16(nsub, nchan, nbin, max_iter=3):
    """Mixed-precision row (``--compute-dtype bfloat16``): the bf16-stored
    cube hot path against the fp32 default, same fused-sweep engine, same
    archive, both warm.

    ``bf16_vs_fp32`` is warm best-of-2 wall clock; on CPU the interpret-
    mode kernels make it an overhead document, not a win claim — the TPU
    number comes from tpu_validation_pass.sh step 9.  The CPU-provable
    wins ARE asserted because they are deterministic: mask parity on a
    bf16-exact archive (storage is lossless there, so every fp32
    accumulation sees identical values and the masks are bit-equal by
    construction — rc-7 fatal), and the traced fused program's cube-tile
    read traffic at <= 0.6x the fp32 program's (``bf16_cube_bytes_ratio``,
    counted from the kernel block avals by the --selfcheck contract
    helper; bf16 tiles are half the bytes, so the true ratio is 0.5)."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
        _cube_pallas_read_bytes,
    )
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.backends.jax_backend import (
        build_clean_fn,
        resolve_compute_dtype,
        resolve_fft_mode,
        resolve_median_impl,
        resolve_stats_frame,
        resolve_stats_impl,
    )
    from iterative_cleaner_tpu.config import CleanConfig

    # the bf16 rung must actually be ON for this row to mean anything: a
    # parity-probe downgrade on this backend IS a parity failure (rc 7)
    resolved = resolve_compute_dtype("bfloat16", jnp.float32, stage="bench")
    assert resolved == "bfloat16", (
        "compute_dtype parity probe downgraded bf16 to %s on this backend"
        % resolved)

    ar = _bf16_exact_archive(nsub, nchan, nbin, seed=0)
    base = dict(backend="jax", dtype="float32", stats_impl="fused",
                fft_mode="dft", median_impl="pallas", fused_sweep="on",
                rotation="roll", max_iter=max_iter)
    results, times = {}, {}
    for mode in ("bfloat16", "float32"):
        cfg = CleanConfig(compute_dtype=mode, **base)
        clean_archive(ar.clone(), cfg)          # compile + warm
        for _ in range(2):                      # warm best-of-2
            t0 = time.perf_counter()
            results[mode] = clean_archive(ar.clone(), cfg)
            dt = time.perf_counter() - t0
            times[mode] = min(times.get(mode, dt), dt)
    assert np.array_equal(results["bfloat16"].final_weights,
                          results["float32"].final_weights), (
        "bf16 masks diverged from fp32 on a bf16-exact archive (%d cells)"
        % int(np.sum(results["bfloat16"].final_weights
                     != results["float32"].final_weights)))

    # trace-level cube read traffic, bf16 storage vs fp32 — deterministic
    # on any backend (cost_analysis would mis-attribute the in-kernel
    # upcast as extra traffic on CPU)
    c = CleanConfig(**base)
    dtype = jnp.dtype(c.dtype)
    fft_mode = resolve_fft_mode(c.fft_mode, dtype)

    def cube_bytes(compute_dtype):
        fn = build_clean_fn(
            c.max_iter, c.chanthresh, c.subintthresh, c.pulse_slice,
            c.pulse_scale, c.pulse_region_active, c.rotation,
            c.baseline_duty, c.unload_res, fft_mode,
            resolve_median_impl(c.median_impl, dtype),
            resolve_stats_impl(c.stats_impl, dtype, nbin, fft_mode),
            resolve_stats_frame(c.stats_frame, dtype), False,
            c.baseline_mode, donate=True, fused_sweep="on",
            compute_dtype=compute_dtype)
        f32 = jnp.float32
        avals = (jax.ShapeDtypeStruct((nsub, nchan, nbin), f32),
                 jax.ShapeDtypeStruct((nsub, nchan), f32),
                 jax.ShapeDtypeStruct((nchan,), f32),
                 jax.ShapeDtypeStruct((), f32),
                 jax.ShapeDtypeStruct((), f32),
                 jax.ShapeDtypeStruct((), f32))
        return _cube_pallas_read_bytes(jax.make_jaxpr(fn)(*avals))

    b_bf16, b_f32 = cube_bytes("bfloat16"), cube_bytes("float32")
    assert 0 < b_bf16 <= 0.6 * b_f32, (
        "bf16 storage no longer shrinks the traced cube read bytes: "
        "%d vs %d" % (b_bf16, b_f32))
    ratio = b_bf16 / b_f32

    _log(f"bf16 ({nsub}x{nchan}x{nbin}): warm best-of-2 "
         f"{times['bfloat16'] * 1e3:.1f} ms bf16 vs "
         f"{times['float32'] * 1e3:.1f} ms fp32 "
         f"({times['bfloat16'] / times['float32']:.2f}x), cube read bytes "
         f"{b_bf16} vs {b_f32} ({ratio:.2f}x), masks bit-equal")
    return {
        "bf16_geometry": f"{nsub}x{nchan}x{nbin}",
        "bf16_platform": jax.default_backend(),
        "bf16_vs_fp32": round(times["bfloat16"] / times["float32"], 3),
        "bf16_cube_bytes_ratio": round(ratio, 3),
        "bf16_cube_read_bytes": int(b_bf16),
        "bf16_fp32_cube_read_bytes": int(b_f32),
    }


def bench_mesh(nsub, nchan, nbin, max_iter=3):
    """Sharded fused-sweep row (parallel/shard_sweep.py): the one-launch
    sweep shard_mapped over a cell mesh vs the same engine on one device,
    same archive, both warm.

    ``mesh_vs_single`` is warm best-of-2 wall clock (on a forced-CPU mesh
    the devices timeshare one core, so the ratio documents overhead, not
    speedup — the TPU number comes from tpu_validation_pass.sh).  Mask
    parity between the routes is rc-7 fatal like every row above, and
    ``mesh_sweep_cube_reads`` is PROVEN per shard: the DMA kernel is
    traced at the local shard geometry and its cube-ref loads counted by
    the --selfcheck contract helper (anything but 1 raises)."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
        _count_cube_ref_reads,
    )
    from iterative_cleaner_tpu.backends.jax_backend import clean_cube
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh
    from iterative_cleaner_tpu.parallel.shard_sweep import (
        sweep_downgrade_reason,
    )
    from iterative_cleaner_tpu.parallel.sharding import clean_cube_sharded
    from iterative_cleaner_tpu.stats import pallas_kernels as pk

    n_dev = len(jax.devices())
    if n_dev < 2:
        _log("mesh stage: single device only (force a CPU mesh with "
             "XLA_FLAGS=--xla_force_host_platform_device_count=4); "
             "skipping the row")
        return None
    mesh = cell_mesh(min(4, n_dev))
    reason = sweep_downgrade_reason(mesh, nsub, nchan, nbin)
    if reason is not None:
        _log(f"mesh stage: {nsub}x{nchan}x{nbin} ineligible on "
             f"{dict(mesh.shape)} ({reason}); skipping the row")
        return None

    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin, **bench_rfi_density(nsub, nchan),
        seed=0, dtype=np.float32)
    cfg = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                      fft_mode="dft", median_impl="pallas",
                      fused_sweep="on", max_iter=max_iter)
    args = (ar.total_intensity(), ar.weights, ar.freqs_mhz, ar.dm,
            ar.centre_freq_mhz, ar.period_s, cfg)
    runs = {"single": lambda: clean_cube(*args),
            "mesh": lambda: clean_cube_sharded(*args, mesh)}
    results, times = {}, {}
    for name, run in runs.items():
        run()                                   # compile + warm
        for _ in range(2):                      # warm best-of-2
            t0 = time.perf_counter()
            results[name] = run()
            dt = time.perf_counter() - t0
            times[name] = min(times.get(name, dt), dt)
    assert np.array_equal(results["single"].final_weights,
                          results["mesh"].final_weights), (
        "sharded sweep masks diverged from the single-device engine "
        "(%d cells)" % int(np.sum(results["single"].final_weights
                                  != results["mesh"].final_weights)))

    # per-shard single-read budget, proven on the traced DMA kernel at
    # the LOCAL shard geometry (what each device actually launches)
    s_loc = nsub // mesh.shape["sub"]
    c_loc = nchan // mesh.shape["chan"]
    f32 = jnp.float32
    cube = jax.ShapeDtypeStruct((s_loc, c_loc, nbin), f32)
    plane = jax.ShapeDtypeStruct((s_loc, c_loc), f32)
    mask = jax.ShapeDtypeStruct((s_loc, c_loc), jnp.bool_)
    row = jax.ShapeDtypeStruct((nbin,), f32)
    closed = jax.make_jaxpr(
        lambda d, t, win, w, m: pk.sweep_shard_diags_dedisp(
            d, t, win, w, m, dma=True))(cube, row, row, plane, mask)
    reads = _count_cube_ref_reads(closed)
    assert reads == [1], (
        "sharded sweep kernel broke its single-read budget: %r" % (reads,))

    ratio = times["mesh"] / times["single"]
    _log(f"mesh ({nsub}x{nchan}x{nbin} over {dict(mesh.shape)}): warm "
         f"best-of-2 {times['mesh'] * 1e3:.1f} ms sharded vs "
         f"{times['single'] * 1e3:.1f} ms single ({ratio:.2f}x), "
         f"{reads[0]} cube read(s)/shard/iteration")
    return {
        "mesh_geometry": f"{nsub}x{nchan}x{nbin}",
        "mesh_platform": jax.default_backend(),
        "mesh_devices": int(mesh.devices.size),
        "mesh_vs_single": round(ratio, 3),
        "mesh_sweep_cube_reads": int(reads[0]),
    }


def bench_numpy(nsub, nchan, nbin, max_iter=5):
    from iterative_cleaner_tpu.backends.numpy_backend import clean_cube
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import (
        bench_rfi_density,
        make_synthetic_archive,
    )

    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin, **bench_rfi_density(nsub, nchan),
        seed=0, dtype=np.float64,
    )
    cfg = CleanConfig(backend="numpy", max_iter=max_iter)
    t0 = time.perf_counter()
    res = clean_cube(ar.total_intensity(), ar.weights, ar.freqs_mhz, ar.dm,
                     ar.centre_freq_mhz, ar.period_s, cfg)
    dt = time.perf_counter() - t0
    rate = nsub * nchan * res.loops / dt
    _log(f"numpy oracle ({nsub}x{nchan}x{nbin}): {dt:.2f}s "
         f"({res.loops} loops) -> {rate:.3e} cell-iters/s")
    return rate


def _bench_row_subprocess(env_key, payload, timeout, label, extra_env=None):
    """Run one bench stage in a KILLABLE subprocess with its own deadline.

    The 2026-07-31 TPU window lost its headline JSON to a wedge inside the
    streaming stage: a C-level stall the in-process watchdog could only
    answer with os._exit(3), taking the already-measured headline numbers
    down with it.  A subprocess bounds the stage without risking the rest
    of the run.  `env_key` selects the child's stage branch in main()
    (BENCH_STREAMING_ONLY / BENCH_BATCH_ONLY), `payload` is its kwargs.
    Returns the row dict, or None on timeout / environment failure; a
    mask-PARITY failure (the stage's assert, signalled by rc 7)
    re-raises — a correctness regression is never benign.
    """
    import subprocess

    env = {**os.environ, **(extra_env or {}), env_key: json.dumps(payload)}
    try:
        # stderr is INHERITED: the child's stage logs stream live (and
        # survive a timeout kill); only the one-line JSON is captured
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        _log(f"{label} bench killed after {timeout:.0f}s (wedged tunnel "
             "dispatch?); headline row unaffected")
        return None
    if out.returncode == 7:
        # the child's dedicated parity-failure code (see the *_ONLY
        # branches): a correctness regression, fatal
        raise AssertionError(
            f"{label} masks diverged from the reference path (subprocess)")
    if out.returncode != 0:
        _log(f"{label} bench subprocess failed (rc={out.returncode}); "
             "skipping the row")
        return None
    try:
        row = json.loads(out.stdout.strip().splitlines()[-1])
        return row if isinstance(row, dict) else None
    except (ValueError, IndexError):
        _log(f"{label} bench subprocess returned no JSON; skipping")
        return None


def main():
    from iterative_cleaner_tpu.utils import fallback_to_cpu_if_unreachable

    for env_key, stage in (("BENCH_STREAMING_ONLY", bench_streaming),
                           ("BENCH_BATCH_ONLY", bench_batch),
                           ("BENCH_FLEET_ONLY", bench_fleet),
                           ("BENCH_SERVE_ONLY", bench_serve),
                           ("BENCH_ONLINE_ONLY", bench_online),
                           ("BENCH_MUX_ONLY", bench_mux),
                           ("BENCH_FUSED_ONLY", bench_fused),
                           ("BENCH_BF16_ONLY", bench_bf16),
                           ("BENCH_MESH_ONLY", bench_mesh),
                           ("BENCH_MULTIHOST_ONLY", bench_multihost),
                           ("BENCH_ELASTIC_ONLY", bench_elastic),
                           ("BENCH_JOURNAL_ONLY", bench_journal)):
        if os.environ.get(env_key):
            geom = json.loads(os.environ[env_key])
            fallback_to_cpu_if_unreachable(
                "BENCH_PROBE_TIMEOUT", log=_log,
                message=f"device unreachable; {stage.__name__} row on CPU")
            try:
                print(json.dumps(stage(**geom)))
            except AssertionError as e:
                # distinct exit code: the parent must treat a mask-parity
                # failure as fatal, but ONLY that — scraping stderr for the
                # word AssertionError would promote unrelated crashes
                _log(f"{stage.__name__} parity failure: {e}")
                sys.exit(7)
            return

    # Dead accelerator tunnel: fall back to CPU so the run still produces
    # a (clearly labelled) number instead of hanging into the watchdog.
    if fallback_to_cpu_if_unreachable(
            "BENCH_PROBE_TIMEOUT", log=_log,
            message="default device unreachable (dead tunnel?); benching "
                    "on CPU — the reported rate is NOT a TPU number"):
        os.environ.setdefault("BENCH_SMALL", "1")
    watchdog = _arm_watchdog(float(os.environ.get("BENCH_TIMEOUT", "1800")))
    small = os.environ.get("BENCH_SMALL") == "1"
    if small:
        jax_cfg = (64, 128, 64)
        np_cfg = (32, 64, 64)
    else:
        jax_cfg = (1024, 4096, 128)   # BASELINE.md config 3
        np_cfg = (256, 1024, 128)     # 1/16 of the cells, same math

    np_rate = bench_numpy(*np_cfg)

    jax_rate = platform = hbm_util = quality = extras = None
    for cfg in (jax_cfg, (512, 4096, 128), (512, 2048, 128)):
        try:
            jax_rate, platform, hbm_util, quality, extras = bench_jax(*cfg)
            jax_cfg = cfg
            break
        except Exception as e:  # OOM fallback ladder
            _log(f"jax bench failed at {cfg}: {type(e).__name__}: {e}")
    if jax_rate is None:
        raise SystemExit("all jax bench configs failed")

    # streaming-exact efficiency row (VERDICT r3 #7), in a killable
    # subprocess with its own deadline so a wedge cannot take the headline
    # row down (2026-07-31); environment failures must not sink the
    # headline number — but a mask-PARITY failure is a correctness
    # regression, never benign (re-raised by the helper)
    # geometry derives from the jax config that actually SUCCEEDED
    # (half its subints): on memory-constrained hosts a hardcoded
    # full-size streaming copy would predictably re-OOM after the main
    # bench already fell down the ladder (ADVICE r4)
    s_nsub, s_nchan, s_nbin = ((32, 64, 64) if small else
                               (max(8, jax_cfg[0] // 2),
                                jax_cfg[1], jax_cfg[2]))
    row = _bench_row_subprocess(
        "BENCH_STREAMING_ONLY",
        {"nsub": s_nsub, "nchan": s_nchan, "nbin": s_nbin,
         "chunk": max(8, s_nsub // 4)},
        timeout=float(os.environ.get("BENCH_STREAMING_TIMEOUT", "600")),
        label="streaming")
    if row:
        extras = {**(extras or {}), **row}

    # batch-mode row (BASELINE.md config 4): 8-32 equal-shaped synthetic
    # archives through parallel/batch.py's one compiled vmap program vs a
    # sequential per-archive loop; same killable-subprocess isolation and
    # parity-is-fatal contract as the streaming row
    b_n, b_geom = ((8, (16, 32, 32)) if small else (32, (64, 1024, 128)))
    row = _bench_row_subprocess(
        "BENCH_BATCH_ONLY",
        {"n_archives": b_n, "nsub": b_geom[0], "nchan": b_geom[1],
         "nbin": b_geom[2]},
        timeout=float(os.environ.get("BENCH_BATCH_TIMEOUT", "600")),
        label="batch")
    if row:
        extras = {**(extras or {}), **row}

    # mixed-shape fleet row (parallel/fleet.py): K geometries round-robin
    # over the archive list, served through the shape-bucketed scheduler
    # vs the sequential per-archive loop — compile count must equal the
    # bucket count and masks must match sequential bit-for-bit (the same
    # parity-is-fatal subprocess contract as the rows above).  BENCH_SMALL
    # doubles as the CI smoke geometry: 6 archives in 2 shapes.
    # The full row stays in the many-modest-archives regime the fleet is
    # for (survey-triage scale): on CPU the win is batched-dispatch
    # amortization — one jit call per group of 8 instead of 24 per-archive
    # calls — which shrinks as per-archive compute grows to dwarf dispatch
    # (~nbin 64 cubes break even on a single core).  24 archives over 3
    # geometries makes three exactly-full groups of 8: no batch-pad lanes,
    # so the measured ratio is pure serving win.  On TPU the same row
    # exercises compile amortization.
    f_n, f_geoms = ((6, [[16, 32, 32], [24, 32, 32]]) if small else
                    (24, [[8, 16, 32], [12, 16, 32], [8, 24, 32]]))
    row = _bench_row_subprocess(
        "BENCH_FLEET_ONLY",
        {"n_archives": f_n, "geometries": f_geoms},
        timeout=float(os.environ.get("BENCH_FLEET_TIMEOUT", "900")),
        label="fleet")
    if row:
        extras = {**(extras or {}), **row}

    # service-daemon row (serve/): submit->done latency through a real
    # --serve process, 429 backpressure under a saturation burst, and
    # SIGTERM drain time — same killable-subprocess + parity-is-fatal
    # contract as the rows above
    sv_n, sv_geoms = ((4, [[16, 32, 32], [24, 32, 32]]) if small else
                      (8, [[8, 16, 32], [12, 16, 32]]))
    row = _bench_row_subprocess(
        "BENCH_SERVE_ONLY",
        {"n_requests": sv_n, "geometries": sv_geoms},
        timeout=float(os.environ.get("BENCH_SERVE_TIMEOUT", "600")),
        label="serve")
    if row:
        extras = {**(extras or {}), **row}

    # online-mode row (online/session.py): per-subint latency for a live
    # stream, zero-steady-recompile and close-reconciliation-parity
    # contracts enforced inside the stage — same killable-subprocess +
    # parity-is-fatal contract as the rows above
    o_n, o_geom = ((8, (16, 32)) if small else (64, (64, 128)))
    row = _bench_row_subprocess(
        "BENCH_ONLINE_ONLY",
        {"n_subints": o_n, "nchan": o_geom[0], "nbin": o_geom[1],
         "reconcile_every": 4, "bucket_pad": 4 if small else 16},
        timeout=float(os.environ.get("BENCH_ONLINE_TIMEOUT", "600")),
        label="online")
    if row:
        extras = {**(extras or {}), **row}

    # multiplexed online row (online/mux.py): a 100-stream synthetic
    # burst through one shared StreamMux vs N independent sessions (the
    # baseline shares one jitted step, so the ratio is pure batched-
    # dispatch amortization).  Zero-steady-recompile and per-stream
    # provisional-mask parity are enforced inside the stage — same
    # killable-subprocess + parity-is-fatal contract as the rows above.
    # max_batch 100 = one full-occupancy dispatch per burst round; at
    # 64 the 100-stream round splits 64 + 36-padded-to-64 (occupancy
    # 0.78) and the ratio drops below the >= 10x contract margin
    mx_streams, mx_n, mx_geom, mx_batch = ((16, 4, (8, 32), 16) if small
                                           else (100, 8, (8, 32), 100))
    row = _bench_row_subprocess(
        "BENCH_MUX_ONLY",
        {"n_streams": mx_streams, "n_subints": mx_n,
         "nchan": mx_geom[0], "nbin": mx_geom[1], "max_batch": mx_batch},
        timeout=float(os.environ.get("BENCH_MUX_TIMEOUT", "600")),
        label="mux")
    if row:
        extras = {**(extras or {}), **row}

    # fused-sweep row (stats/pallas_kernels.py fused_sweep_pallas*): the
    # one-launch sweep vs the multi-kernel route, warm best-of-2, plus
    # the deterministic CPU-provable contracts (program shrink, streaming
    # H2D shrink, single cube read) — parity-is-fatal like the rows above
    fu_geom = (16, 32, 64) if small else (64, 128, 256)
    row = _bench_row_subprocess(
        "BENCH_FUSED_ONLY",
        {"nsub": fu_geom[0], "nchan": fu_geom[1], "nbin": fu_geom[2]},
        timeout=float(os.environ.get("BENCH_FUSED_TIMEOUT", "600")),
        label="fused")
    if row:
        extras = {**(extras or {}), **row}

    # mixed-precision row (--compute-dtype bfloat16): bf16 cube storage vs
    # the fp32 default through the same fused-sweep engine, mask parity on
    # a bf16-exact archive and the deterministic half-the-cube-bytes trace
    # contract — parity-is-fatal like the rows above.  BENCH_SKIP_BF16=1
    # opts out: the stage compiles the engine twice, which the tier-1
    # bench-schema test cannot afford inside its wall-clock budget
    # (tests/test_bench_config.py pins this row's keys in a dedicated
    # slow test instead).
    if os.environ.get("BENCH_SKIP_BF16") != "1":
        bf_geom = (16, 32, 64) if small else (64, 128, 256)
        row = _bench_row_subprocess(
            "BENCH_BF16_ONLY",
            {"nsub": bf_geom[0], "nchan": bf_geom[1], "nbin": bf_geom[2]},
            timeout=float(os.environ.get("BENCH_BF16_TIMEOUT", "600")),
            label="bf16")
        if row:
            extras = {**(extras or {}), **row}

    # sharded fused-sweep row (parallel/shard_sweep.py): the one-launch
    # sweep shard_mapped over a cell mesh vs the single-device engine.
    # The child gets a forced 4-device host platform unless the caller
    # already pinned one (harmless off-CPU: the flag only shapes the
    # host platform, and a real TPU run uses its real devices).
    # BENCH_SKIP_MESH=1 opts out: the stage compiles the sharded program
    # twice, which the tier-1 bench-schema test cannot afford inside its
    # wall-clock budget (tests/test_bench_config.py pins this row's keys
    # in a dedicated slow test instead).
    if os.environ.get("BENCH_SKIP_MESH") != "1":
        me_geom = (16, 32, 64) if small else (64, 128, 256)
        flags = os.environ.get("XLA_FLAGS", "")
        mesh_env = {}
        if "xla_force_host_platform_device_count" not in flags:
            mesh_env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4").strip()
        row = _bench_row_subprocess(
            "BENCH_MESH_ONLY",
            {"nsub": me_geom[0], "nchan": me_geom[1], "nbin": me_geom[2]},
            timeout=float(os.environ.get("BENCH_MESH_TIMEOUT", "600")),
            label="mesh", extra_env=mesh_env)
        if row:
            extras = {**(extras or {}), **row}

    # multi-host fleet row (parallel/fleet.py + resilience/journal.py):
    # the same fleet served by 1 process vs 2 journal-coordinated
    # processes (hash-partitioned buckets, work stealing), plus the
    # dead-host steal drill — parity-is-fatal like every row above.
    # BENCH_SKIP_MULTIHOST=1 opts out: the stage launches four CLI
    # processes, which the tier-1 bench-schema test cannot afford inside
    # its wall-clock budget (tests/test_bench_config.py pins this row's
    # keys in a dedicated slow test instead).
    if os.environ.get("BENCH_SKIP_MULTIHOST") != "1":
        m_n, m_geoms = ((4, [[16, 32, 32], [12, 32, 32]]) if small else
                        (8, [[16, 32, 32], [12, 32, 32]]))
        row = _bench_row_subprocess(
            "BENCH_MULTIHOST_ONLY",
            {"n_archives": m_n, "geometries": m_geoms},
            timeout=float(os.environ.get("BENCH_MULTIHOST_TIMEOUT", "900")),
            label="multihost")
        if row:
            extras = {**(extras or {}), **row}

    # elastic-pool row (serve/membership.py + serve/result_cache.py):
    # two --join daemons on one journal; kill -9 the front door mid-burst
    # and measure the survivor's failover plus the result-cache hit vs a
    # real clean.  Geometries stay tiny regardless of BENCH_SMALL — the
    # row measures failover/caching latency, not throughput.
    # BENCH_SKIP_ELASTIC=1 opts out for the same reason as multihost: the
    # stage launches daemon subprocesses the tier-1 bench-schema test
    # cannot afford (tests/test_bench_config.py pins the row's keys in a
    # dedicated slow test instead).
    if os.environ.get("BENCH_SKIP_ELASTIC") != "1":
        row = _bench_row_subprocess(
            "BENCH_ELASTIC_ONLY",
            {"geometries": [[6, 16, 32], [8, 16, 32], [10, 16, 32]]},
            timeout=float(os.environ.get("BENCH_ELASTIC_TIMEOUT", "900")),
            label="elastic")
        if row:
            extras = {**(extras or {}), **row}

    # BENCH_SKIP_JOURNAL=1 opts out: the stage is device-free (pure
    # journal I/O + folds) but ages a 100k-request journal, which the
    # tier-1 bench-schema test cannot afford; test_bench_config.py pins
    # the row's keys in a dedicated test instead.  BENCH_SMALL shrinks
    # the synthetic pool so the CI smoke exercises the same code path
    # in seconds.
    if os.environ.get("BENCH_SKIP_JOURNAL") != "1":
        j_req = 5000 if small else 100000
        row = _bench_row_subprocess(
            "BENCH_JOURNAL_ONLY",
            {"n_members": 50, "n_requests": j_req},
            timeout=float(os.environ.get("BENCH_JOURNAL_TIMEOUT", "600")),
            label="journal")
        if row:
            extras = {**(extras or {}), **row}

    if not small and jax_cfg == (1024, 4096, 128):
        # Headline methodology (BASELINE.md "Measured baselines"): divide by
        # the recorded FULL-SIZE oracle rate; the live 1/16-slice run above
        # is an environment sanity check (cache-friendlier, so faster).
        denom = oracle_full_rate()
        _log(f"denominator: recorded full-size oracle rate {denom:.3e} "
             f"cell-iters/s ({1024 * 4096 / denom:.1f} s/iteration, "
             f"BASELINE.md); live 1/16 slice sanity check measured "
             f"{np_rate:.3e}")
    else:
        denom = np_rate
        _log(f"denominator: live-measured oracle rate {np_rate:.3e} "
             "cell-iters/s (small/fallback config; the recorded full-size "
             "constant only describes 1024x4096x128)")

    watchdog.cancel()
    out = {
        "metric": "cells_cleaned_per_sec_%dx%d" % (jax_cfg[0], jax_cfg[1]),
        "value": round(jax_rate, 1),
        "unit": "cell-iters/s",
        "vs_baseline": round(jax_rate / denom, 2),
        "platform": platform,
        "hbm_util": None if hbm_util is None else round(hbm_util, 3),
        "quality": quality,
        **(extras or {}),
    }
    if platform != "tpu":
        # Dead-tunnel fallback: surface the most recent committed real-TPU
        # capture (benchmarks/measured/) so a CPU-platform record is never
        # mistaken for "no TPU number exists".
        out["note"] = (
            "off-TPU fallback; round-3 kernel/semantics changes await "
            "hardware numbers — run benchmarks/tpu_validation_pass.sh on "
            "a live chip (BASELINE.md 'Round-3 note' explains comparisons)")
        cap_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "measured")
        try:
            caps = sorted((f for f in os.listdir(cap_dir)
                           if f.startswith("bench_tpu_")
                           and f.endswith(".json")), reverse=True)
            for cap in caps:  # newest VALID capture (skip empty/truncated)
                try:
                    with open(os.path.join(cap_dir, cap)) as fh:
                        payload = json.load(fh)
                except (OSError, ValueError):
                    continue
                if not isinstance(payload, dict):
                    continue
                out["last_tpu_capture"] = {
                    "file": f"benchmarks/measured/{cap}", **payload}
                _log(f"fell back off-TPU; last real-TPU capture attached "
                     f"from benchmarks/measured/{cap}")
                break
        except OSError as e:
            _log(f"could not attach TPU capture: {e}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Stream multiplexer (online/mux.py): ring/SLO semantics, the
head-of-line no-starvation rule, close-drain behaviour, bit-equality
with solo sessions across batch rungs, and per-stream quality-drift
independence when many streams share one registry.

The SLO tests inject a fake clock — the mux stamps ring arrival with
its own (injectable) clock precisely so deadline behaviour is
deterministic under test.
"""

import numpy as np
import pytest

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io import make_synthetic_archive
from iterative_cleaner_tpu.online import OnlineSession, StreamMeta
from iterative_cleaner_tpu.online.mux import MuxRingFull, StreamMux
from iterative_cleaner_tpu.parallel.batch import batch_rungs, next_rung
from iterative_cleaner_tpu.telemetry.registry import (
    MetricsRegistry,
    labeled,
)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


def _cfg(**kw):
    kw.setdefault("backend", "jax")
    kw.setdefault("max_iter", 2)
    # mid-stream reconciles are the session's own concern (covered by
    # test_online); here they would only slow the parity sweeps down
    kw.setdefault("stream_reconcile_every", 0)
    return CleanConfig(**kw)


def _stream(nsub=4, nchan=8, nbin=16, seed=7):
    ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                   seed=seed)
    cube = np.asarray(ar.total_intensity(), dtype=np.float64)
    return StreamMeta.from_archive(ar), cube


# ------------------------------------------------------------- rung ladder

def test_batch_rung_ladder_and_next_rung():
    assert batch_rungs(1) == (1,)
    assert batch_rungs(8) == (1, 2, 4, 8)
    # a non-power-of-two cap tops the ladder as its own rung
    assert batch_rungs(100) == (1, 2, 4, 8, 16, 32, 64, 100)
    for n in range(1, 9):
        r = next_rung(n, 8)
        assert r >= n and r in batch_rungs(8)
    assert next_rung(65, 100) == 100
    with pytest.raises(ValueError):
        next_rung(9, 8)
    with pytest.raises(ValueError):
        batch_rungs(0)


# ------------------------------------------------------------ SLO / ring

def test_partial_batch_dispatches_at_slo_deadline():
    clock = FakeClock()
    mux = StreamMux(max_batch=4, max_wait_ms=50.0, clock=clock)
    meta, cube = _stream()
    mux.open("a", meta, _cfg())
    mux.ingest("a", cube[0])
    # a lone head is not due before the deadline...
    assert mux.pump() == 0
    clock.advance(0.049)
    assert mux.pump() == 0
    assert mux.session("a").n_subints == 0
    # ...and goes out partial the moment the SLO expires
    clock.advance(0.002)
    assert mux.pump() == 1
    assert mux.session("a").n_subints == 1
    assert mux.partial_dispatches == 1
    assert mux.warmup_compiles == 1 and mux.recompiles_steady == 0


def test_full_bucket_dispatches_without_waiting():
    clock = FakeClock()
    mux = StreamMux(max_batch=2, max_wait_ms=60_000.0, clock=clock)
    meta, cube = _stream()
    cfg = _cfg()
    mux.open("a", meta, cfg)
    mux.open("b", meta, cfg)
    mux.ingest("a", cube[0])
    assert mux.pump() == 0          # half a batch, an hour of headroom
    mux.ingest("b", cube[1])
    assert mux.pump() == 1          # full bucket: no SLO wait
    assert mux.partial_dispatches == 0
    assert mux.batch_occupancies == [1.0]


def test_ring_backpressure_nonblocking_and_blocking():
    mux = StreamMux(max_batch=1, max_wait_ms=60_000.0, ring_capacity=2)
    meta, cube = _stream()
    mux.open("a", meta, _cfg())
    mux.ingest("a", cube[0])
    mux.ingest("a", cube[1])
    with pytest.raises(MuxRingFull, match="capacity"):
        mux.ingest("a", cube[2])
    # blocking ingest times out (nothing is draining the ring)
    with pytest.raises(MuxRingFull, match="backpressure"):
        mux.ingest("a", cube[2], block=True, timeout_s=0.15)
    # abandoning the stream frees its ring slots
    mux.abandon_stream("a")
    assert mux.pending() == 0


def test_no_starvation_one_head_per_stream_oldest_first():
    clock = FakeClock()
    mux = StreamMux(max_batch=8, max_wait_ms=5.0, clock=clock)
    meta, cube = _stream(nsub=6)
    cfg = _cfg()
    mux.open("chatty", meta, cfg)
    mux.open("slow", meta, cfg)
    # the chatty stream backlogs five subints before slow's one arrives
    for i in range(5):
        mux.ingest("chatty", cube[i])
        clock.advance(0.001)
    mux.ingest("slow", cube[5])
    # one dispatch cycle: only stream HEADS join the batch, oldest
    # first — the backlog depth buys chatty no extra lanes
    with mux._dispatch_lock:
        picked = mux._select_batch(clock(), True)
        assert picked is not None
        binfo, lanes = picked
        assert [s.key for s, _ in lanes] == ["chatty", "slow"]
        mux._dispatch(binfo, lanes)
    assert mux.session("slow").n_subints == 1
    assert mux.session("chatty").n_subints == 1
    assert mux.pending("chatty") == 4
    # the backlog then drains one lane per dispatch
    assert mux.pump(force=True) == 4
    assert mux.subints == 6


def test_closing_stream_drains_without_stalling_bucket():
    clock = FakeClock()
    mux = StreamMux(max_batch=8, max_wait_ms=60_000.0, clock=clock)
    meta, cube = _stream()
    cfg = _cfg()
    mux.open("a", meta, cfg)
    mux.open("b", meta, cfg)
    mux.ingest("a", cube[0])
    mux.ingest("a", cube[1])
    mux.ingest("b", cube[2])
    assert mux.pump() == 0          # nothing due: partial and fresh
    # closing "a" makes its pending due immediately; "b"'s head rides
    # the same bucket's batches instead of being stalled behind the SLO
    res = mux.close_stream("a")
    assert res.n_subints == 2
    assert res.recompiles_steady == 0
    assert "a" not in mux.streams()
    assert mux.session("b").n_subints == 1
    # and "b" keeps working after its neighbour closed
    mux.ingest("b", cube[3])
    mux.pump(force=True)
    assert mux.close_stream("b").n_subints == 2


# ------------------------------------------------- bit-equality contract

_PARITY_NSUB = 4


@pytest.fixture(scope="module")
def solo_baseline():
    """Reference run shared by every batch-size param: 3 solo sessions
    over one pre-jitted step (the sweep compares masks, not compiles),
    closed once — (streams, [(pweights, pscores, final_weights)])."""
    import jax

    from iterative_cleaner_tpu.online.session import resolve_ew_alpha
    from iterative_cleaner_tpu.online.step import build_subint_step

    cfg = _cfg(fleet_bucket_pad=(0, 8))
    streams = [_stream(nsub=_PARITY_NSUB, nchan=6, nbin=16, seed=100 + s)
               for s in range(3)]
    alpha = resolve_ew_alpha(cfg.stream_ew_alpha)
    shared = jax.jit(build_subint_step(cfg, 6, 16, False, alpha)[0])
    refs = []
    for meta, cube in streams:
        sess = OnlineSession(meta, cfg, step_fn=shared)
        for i in range(_PARITY_NSUB):
            sess.ingest(cube[i])
        pw, ps = sess.provisional_weights, sess.provisional_scores
        refs.append((pw, ps, np.asarray(sess.close().archive.weights)))
    return cfg, streams, refs


@pytest.mark.parametrize("max_batch", [1, 2, 3, 8])
def test_mux_masks_bit_equal_with_solo_sessions(max_batch, solo_baseline):
    # nchan=6 with a chan-step of 8 quantizes up to qchan=8: every
    # dispatch carries padded channels, so this sweep also proves the
    # pad lanes never leak into the true channels.  max_batch=2 forces
    # split dispatches of 3 streams; max_batch=8 forces rung padding
    # (b=3 -> rung 4 with one inert lane).
    cfg, streams, refs = solo_baseline
    mux = StreamMux(max_batch=max_batch, max_wait_ms=0.0)
    for k, (meta, _) in enumerate(streams):
        mux.open(f"s{k}", meta, cfg)
    for i in range(_PARITY_NSUB):
        for k, (_, cube) in enumerate(streams):
            mux.ingest(f"s{k}", cube[i])
        mux.pump(force=True)
    assert mux.recompiles_steady == 0
    for k, (pw, ps, final_w) in enumerate(refs):
        ms = mux.session(f"s{k}")
        np.testing.assert_array_equal(ms.provisional_weights, pw)
        # provisional scores carry NaN where a channel median is
        # degenerate — identical NaN placement is part of the contract
        assert np.array_equal(ms.provisional_scores, ps, equal_nan=True)
        # close reconciles agree too: the archived product is bit-equal
        res_m = mux.close_stream(f"s{k}")
        np.testing.assert_array_equal(np.asarray(res_m.archive.weights),
                                      final_w)


# -------------------------------------------- per-stream quality series

def test_quality_drift_alerts_stay_per_stream_under_mux():
    # Two streams batched through one mux and one registry: only the
    # drifting stream's quality_drift_alerts{stream=} may increment.
    reg = MetricsRegistry()
    cfg = _cfg(quality_window=2, quality_drift=0.25)
    meta, cube = _stream(nsub=4)
    mux = StreamMux(max_batch=4, max_wait_ms=0.0, registry=reg)
    mux.open("quiet", meta, cfg)
    mux.open("noisy", meta, cfg)
    for i in range(2):              # identical baselines fill both windows
        mux.ingest("quiet", cube[i])
        mux.ingest("noisy", cube[i])
        mux.pump(force=True)
    # third subint: noisy arrives with three quarters of its band dead,
    # jumping its zap fraction past the drift band; quiet stays flat
    dead = np.ones(meta.nchan)
    dead[: (3 * meta.nchan) // 4] = 0.0
    mux.ingest("quiet", cube[2])
    mux.ingest("noisy", cube[2], dead)
    mux.pump(force=True)
    noisy = labeled("quality_drift_alerts", stream="noisy")
    quiet = labeled("quality_drift_alerts", stream="quiet")
    assert reg.counters.get(noisy, 0.0) >= 1.0
    assert reg.counters.get(quiet, 0.0) == 0.0

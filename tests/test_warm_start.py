"""AOT warm-start tests: background bucket precompilation and its
exactly-once compile accounting, the persistent compile cache's
warm-restart contract through the real CLI (a fresh process re-serving
the same fleet must do zero real compiles), and buffer-donation safety
(donation must never change a mask, and must never delete a buffer the
caller still owns)."""

import logging
import os
import subprocess
import sys

import numpy as np
import pytest

from iterative_cleaner_tpu.backends import clean_archive
from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io import (
    load_archive,
    make_synthetic_archive,
    save_archive,
)
from iterative_cleaner_tpu.parallel.batch import (
    clean_archives_batched,
    clear_precompile_memo,
    precompile_batched_executable,
)
from iterative_cleaner_tpu.parallel.fleet import clean_fleet
from iterative_cleaner_tpu.telemetry import MetricsRegistry
from tests.conftest import repo_subprocess_env

CFG = CleanConfig(backend="jax", rotation="roll", fft_mode="dft",
                  dtype="float64", max_iter=3)


def _archives(geometries, seed0=60):
    out = []
    for i, (nsub, nchan, nbin) in enumerate(geometries):
        ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                       seed=seed0 + i)
        out.append(ar)
    return out


# ---------------------------------------------------------------------------
# precompile_batched_executable: memo + accounting + result parity


def test_precompile_executable_matches_inline_and_counts_once():
    clear_precompile_memo()
    archives = _archives([(10, 16, 32)] * 3)

    inline_reg = MetricsRegistry()
    inline_stats = {}
    inline = clean_archives_batched(archives, CFG, registry=inline_reg,
                                    stats_out=inline_stats)
    assert inline_stats["compiles"] >= 1
    assert not inline_stats["used_executable"]

    pre_reg = MetricsRegistry()
    pre_stats = {}
    exe = precompile_batched_executable(
        CFG, 10, 16, 32, False, 3, registry=pre_reg, stats_out=pre_stats)
    assert pre_stats["fresh"]
    assert pre_reg.counters["batch_compiles"] == 1

    # serving through the AOT executable must do ZERO further compiles and
    # reproduce the inline path's results bit-for-bit
    serve_reg = MetricsRegistry()
    serve_stats = {}
    served = clean_archives_batched(archives, CFG, registry=serve_reg,
                                    executable=exe, stats_out=serve_stats)
    assert serve_stats["compiles"] == 0
    assert serve_stats["used_executable"]
    assert serve_reg.counters.get("batch_compiles", 0) == 0
    for a, b in zip(inline, served):
        np.testing.assert_array_equal(a.final_weights, b.final_weights)
        assert a.loops == b.loops

    # second precompile of the same geometry is a memo hit, not a compile
    memo_reg = MetricsRegistry()
    memo_stats = {}
    exe2 = precompile_batched_executable(
        CFG, 10, 16, 32, False, 3, registry=memo_reg, stats_out=memo_stats)
    assert exe2 is exe
    assert not memo_stats["fresh"]
    assert memo_reg.counters.get("batch_compiles", 0) == 0


# ---------------------------------------------------------------------------
# fleet: background pool counters, warm re-serve, precompile=False fallback


def _write_fleet(tmp_path, geometries, seed0=70):
    paths = []
    for i, (nsub, nchan, nbin) in enumerate(geometries):
        ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                       seed=seed0 + i)
        p = str(tmp_path / ("warm_%02d.npz" % i))
        save_archive(ar, p)
        paths.append(p)
    return paths


def test_fleet_precompile_counters_cold_then_warm(tmp_path):
    clear_precompile_memo()
    geoms = [(10, 16, 32), (10, 16, 32), (14, 16, 32)]
    paths = _write_fleet(tmp_path, geoms)

    cold_reg = MetricsRegistry()
    cold = clean_fleet(paths, CFG, registry=cold_reg, group_size=2)
    assert not cold.failures
    n_groups = 2                    # bucket A: 2 archives, bucket B: 1
    assert cold_reg.counters["fleet_compiles"] == cold.n_buckets == 2
    assert (cold_reg.counters.get("fleet_precompile_hits", 0)
            + cold_reg.counters.get("fleet_precompile_misses", 0)) == n_groups

    # same process again: every bucket executable comes out of the AOT
    # memo — zero compiles, and the pool serves (near-)instantly
    warm_reg = MetricsRegistry()
    warm = clean_fleet(paths, CFG, registry=warm_reg, group_size=2)
    assert not warm.failures
    assert warm_reg.counters.get("fleet_compiles", 0) == 0
    hits = warm_reg.counters.get("fleet_precompile_hits", 0)
    misses = warm_reg.counters.get("fleet_precompile_misses", 0)
    assert hits + misses == n_groups
    assert hits >= n_groups - 1     # group 0 may race the pool's startup

    for p in paths:
        np.testing.assert_array_equal(cold.results[p].final_weights,
                                      warm.results[p].final_weights)


def test_fleet_precompile_disabled_matches(tmp_path):
    clear_precompile_memo()
    geoms = [(10, 16, 32), (14, 16, 32)]
    paths = _write_fleet(tmp_path, geoms, seed0=80)

    reg_off = MetricsRegistry()
    off = clean_fleet(paths, CFG, registry=reg_off, group_size=2,
                      precompile=False)
    assert not off.failures
    assert reg_off.counters.get("fleet_precompile_hits", 0) == 0
    assert reg_off.counters.get("fleet_precompile_misses", 0) == 0
    assert reg_off.counters["fleet_compiles"] == 2

    on = clean_fleet(paths, CFG, registry=MetricsRegistry(), group_size=2)
    for p in paths:
        np.testing.assert_array_equal(off.results[p].final_weights,
                                      on.results[p].final_weights)


# ---------------------------------------------------------------------------
# warm restart across processes (the persistent-cache contract)


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "iterative_cleaner_tpu", "-q", *args],
        env=repo_subprocess_env(ICLEAN_PROBE_TIMEOUT="0"), cwd=cwd,
        capture_output=True, text=True, timeout=300)


@pytest.mark.slow
def test_warm_restart_cli_zero_real_compiles(tmp_path):
    """Serve the same mixed-shape fleet twice through the real CLI, two
    fresh processes sharing one --compile-cache directory: the second run
    must write ZERO new cache entries (every executable reloaded) and
    produce bit-identical output masks."""
    geoms = [(10, 16, 32), (10, 16, 32), (14, 16, 32)]
    paths = _write_fleet(tmp_path, geoms, seed0=90)
    cache = str(tmp_path / "cache")
    flags = ["--fleet", "--batch", "2", "--max_iter", "3",
             "--rotation", "roll", "--fft_mode", "dft",
             "--compile-cache", cache]

    cold = _run_cli(flags + paths, str(tmp_path))
    assert cold.returncode == 0, cold.stderr[-2000:]
    entries = sorted(os.listdir(cache))
    assert entries, "cold run wrote no persistent-cache entries"
    cold_masks = {p: load_archive(p + "_cleaned.npz").weights == 0
                  for p in paths}

    warm = _run_cli(flags + paths, str(tmp_path))
    assert warm.returncode == 0, warm.stderr[-2000:]
    assert sorted(os.listdir(cache)) == entries, \
        "warm restart wrote new compile-cache entries (real compiles)"
    for p in paths:
        warm_mask = load_archive(p + "_cleaned.npz").weights == 0
        np.testing.assert_array_equal(cold_masks[p], warm_mask)


def test_precompile_cli_warms_cache(tmp_path):
    cache = str(tmp_path / "cache")
    proc = _run_cli(["--precompile", "--compile-cache", cache,
                     "--max_iter", "3", "--rotation", "roll",
                     "--fft_mode", "dft", "16x32x32"], str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.listdir(cache), "precompile wrote no cache entries"


def test_precompile_cli_requires_cache_dir(tmp_path):
    proc = _run_cli(["--precompile", "16x32x32"], str(tmp_path))
    assert proc.returncode == 2
    assert "--compile-cache" in proc.stderr


def test_parse_geometry_spec():
    from iterative_cleaner_tpu.cli import _parse_geometry_spec

    assert _parse_geometry_spec("16x32x128") == (16, 32, 128)
    assert _parse_geometry_spec("not-a-geometry") is None
    assert _parse_geometry_spec("16x32") is None
    assert _parse_geometry_spec("0x32x64") is None


# ---------------------------------------------------------------------------
# donation safety


def test_donation_mask_parity_engine_and_batch():
    ar, _ = make_synthetic_archive(seed=21)
    oracle = clean_archive(ar.clone(),
                           CleanConfig(backend="numpy", dtype="float64"))
    donated = clean_archive(ar.clone(),
                            CleanConfig(backend="jax", dtype="float64",
                                        donate_buffers=True))
    plain = clean_archive(ar.clone(),
                          CleanConfig(backend="jax", dtype="float64",
                                      donate_buffers=False))
    np.testing.assert_array_equal(oracle.final_weights, donated.final_weights)
    np.testing.assert_array_equal(plain.final_weights, donated.final_weights)

    clear_precompile_memo()
    archives = _archives([(10, 16, 32)] * 3, seed0=30)
    import dataclasses

    on = clean_archives_batched(archives, CFG)
    off = clean_archives_batched(
        archives, dataclasses.replace(CFG, donate_buffers=False))
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a.final_weights, b.final_weights)


def test_donation_does_not_consume_caller_arrays():
    """The donate guard: device arrays held by the caller pass through
    jnp.asarray unchanged, so clean_cube must NOT donate them — they stay
    readable after the call (bench_jax replays one upload for repeats)."""
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import clean_cube

    ar, _ = make_synthetic_archive(seed=22, nsub=8, nchan=16, nbin=32)
    cfg = CleanConfig(backend="jax", dtype="float64", donate_buffers=True)
    cube = jnp.asarray(ar.total_intensity(), dtype=jnp.float64)
    weights = jnp.asarray(ar.weights, dtype=jnp.float64)
    host = clean_cube(ar.total_intensity(), ar.weights, ar.freqs_mhz, ar.dm,
                      ar.centre_freq_mhz, ar.period_s, cfg)
    dev = clean_cube(cube, weights, ar.freqs_mhz, ar.dm,
                     ar.centre_freq_mhz, ar.period_s, cfg)
    # caller's buffers survived (a donated buffer raises on use)
    assert float(cube.sum()) == pytest.approx(float(np.sum(
        np.asarray(ar.total_intensity(), dtype=np.float64))), rel=1e-12)
    assert float(weights.sum()) == float(ar.weights.sum())
    np.testing.assert_array_equal(host.final_weights, dev.final_weights)


def test_donation_retrace_after_donated_call():
    """A second call through the SAME cached jit program (donating) with
    fresh host inputs must not touch the first call's deleted buffers."""
    cfg = CleanConfig(backend="jax", dtype="float64", donate_buffers=True)
    results = []
    for seed in (23, 23):           # identical inputs, two fresh uploads
        ar, _ = make_synthetic_archive(seed=seed, nsub=8, nchan=16, nbin=32)
        results.append(clean_archive(ar, cfg))
    np.testing.assert_array_equal(results[0].final_weights,
                                  results[1].final_weights)


@pytest.mark.slow
def test_donation_shrinks_peak_bytes():
    """Donation must show up in the compiled program's memory analysis:
    a non-zero input/output alias and no larger a peak than the
    donate-off twin (advisory gauges — skip if the backend exposes no
    memory analysis)."""
    import dataclasses

    clear_precompile_memo()
    on_reg = MetricsRegistry()
    precompile_batched_executable(
        dataclasses.replace(CFG, dtype="float32"), 16, 32, 32, False, 3,
        registry=on_reg)
    off_reg = MetricsRegistry()
    precompile_batched_executable(
        dataclasses.replace(CFG, dtype="float32", donate_buffers=False),
        16, 32, 32, False, 3, registry=off_reg)
    if ("batch_exec_peak_bytes" not in on_reg.gauges
            or "batch_exec_peak_bytes" not in off_reg.gauges):
        pytest.skip("backend exposes no memory_analysis")
    assert on_reg.gauges["batch_exec_alias_bytes"] > 0
    assert off_reg.gauges["batch_exec_alias_bytes"] == 0
    assert (on_reg.gauges["batch_exec_peak_bytes"]
            <= off_reg.gauges["batch_exec_peak_bytes"])


# ---------------------------------------------------------------------------
# configure_compilation_cache plumbing


def test_configure_compilation_cache_unit(tmp_path, monkeypatch):
    import jax

    from iterative_cleaner_tpu.utils import (
        configure_compilation_cache,
        enable_compile_cache,
    )

    assert enable_compile_cache is configure_compilation_cache
    monkeypatch.delenv("TF_CPP_MIN_LOG_LEVEL", raising=False)
    cache = tmp_path / "cc"
    try:
        configure_compilation_cache(str(cache))
        assert cache.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(cache)
        assert os.environ["TF_CPP_MIN_LOG_LEVEL"] == "1"
        for name in ("jax._src.compilation_cache", "jax._src.compiler"):
            assert (logging.getLogger(name).getEffectiveLevel()
                    >= logging.WARNING)
        # no-op spelling: None leaves the cache configuration untouched
        configure_compilation_cache(None)
        assert jax.config.jax_compilation_cache_dir == str(cache)
    finally:
        jax.config.update("jax_compilation_cache_dir", None)

"""Service-daemon tests (serve/ + cli --serve): request parsing and
admission control units, journal request lifecycle + compaction (including
the compact-while-appending flock race), spool-intake semantics, an
in-process HTTP round trip, and the subprocess contracts — kill -9
restart with zero duplicated cleans, graceful drain on SIGTERM (second
signal force-exits), a serve-layer fault soak, and warm repeat-geometry
serving with zero new compile-cache entries."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from iterative_cleaner_tpu.backends import clean_archive
from iterative_cleaner_tpu.config import CleanConfig, ServeConfig
from iterative_cleaner_tpu.io import (
    load_archive,
    make_synthetic_archive,
    save_archive,
)
from iterative_cleaner_tpu.resilience import FleetJournal
from iterative_cleaner_tpu.serve import (
    RequestError,
    Rejection,
    ServeDaemon,
    ServeRequest,
    ServeScheduler,
    SpoolWatcher,
    parse_request,
    request_key,
)
from iterative_cleaner_tpu.telemetry import MetricsRegistry
from iterative_cleaner_tpu.utils.logging import (
    compact_under_lock,
    locked_append,
    trim_log,
)
from tests.conftest import repo_subprocess_env

NUMPY_BASE = CleanConfig(backend="numpy", max_iter=2)


def _write_fleet(tmp_path, geometries, ext=".npz", seed0=60):
    paths = []
    for i, (nsub, nchan, nbin) in enumerate(geometries):
        ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                       seed=seed0 + i)
        p = str(tmp_path / ("serve_%02d%s" % (i, ext)))
        save_archive(ar, p)
        paths.append(p)
    return paths


# ---------------------------------------------------------------- request

def test_parse_request_full_payload():
    req = parse_request(json.dumps({
        "paths": ["/d/a.npz", "/d/b.npz"], "tenant": "survey",
        "priority": 3, "deadline_s": 60.0,
        "overrides": {"max_iter": 2, "pulse_region": [0.5, 10, 20]},
        "id": "r-1"}).encode(), now=1000.0)
    assert req.request_id == "r-1"
    assert req.paths == ["/d/a.npz", "/d/b.npz"]
    assert req.tenant == "survey" and req.priority == 3
    assert req.deadline_ts == pytest.approx(1060.0)
    assert req.overrides["pulse_region"] == (0.5, 10.0, 20.0)
    assert not req.expired(now=1059.9) and req.expired(now=1060.0)


def test_parse_request_defaults_and_single_path():
    req = parse_request({"paths": "/d/a.npz"})
    assert req.paths == ["/d/a.npz"]
    assert req.tenant == "default" and req.priority == 0
    assert req.deadline_ts is None and req.overrides == {}
    assert req.request_id  # minted


@pytest.mark.parametrize("payload", [
    b"not json", b'["list"]', b'{}', b'{"paths": []}',
    b'{"paths": [1]}', b'{"paths": ["a"], "bogus": 1}',
    b'{"paths": ["a"], "deadline_s": 0}',
    b'{"paths": ["a"], "deadline_s": "soon"}',
    b'{"paths": ["a"], "priority": "high"}',
    b'{"paths": ["a"], "tenant": ""}',
    b'{"paths": ["a"], "overrides": {"compile_cache_dir": "/x"}}',
    b'{"paths": ["a"], "overrides": {"pulse_region": "mid"}}',
    b'{"paths": ["a"], "id": "x/y"}',
])
def test_parse_request_rejects(payload):
    with pytest.raises(RequestError):
        parse_request(payload)


def test_parse_request_validates_overrides_against_config():
    # the whitelist passes 'backend' through, but CleanConfig's own
    # validators still reject a bogus value at parse time
    with pytest.raises(RequestError):
        parse_request({"paths": ["a"], "overrides": {"backend": "cuda"}},
                      base_config=NUMPY_BASE)
    req = parse_request({"paths": ["a"], "overrides": {"max_iter": 7}},
                        base_config=NUMPY_BASE)
    assert req.effective_config(NUMPY_BASE).max_iter == 7
    assert NUMPY_BASE.max_iter == 2  # base untouched


def test_request_key_orders_priority_then_deadline_then_arrival():
    hi = ServeRequest("hi", ["a"], priority=5)
    soon = ServeRequest("soon", ["a"], deadline_ts=100.0)
    late = ServeRequest("late", ["a"], deadline_ts=200.0)
    fifo = ServeRequest("fifo", ["a"])
    order = sorted([(request_key(r, i), r.request_id)
                    for i, r in enumerate([fifo, late, soon, hi])])
    assert [rid for _k, rid in order] == ["hi", "soon", "late", "fifo"]


def test_request_journal_round_trip():
    req = ServeRequest("r1", ["/d/a.npz"], tenant="t", priority=2,
                       deadline_ts=123.0, overrides={"max_iter": 4})
    back = ServeRequest.from_journal_entry("r1", req.journal_fields())
    assert back == req
    with pytest.raises(RequestError):
        ServeRequest.from_journal_entry("r2", {"state": "accepted"})


# -------------------------------------------------------------- scheduler

def _sched(**kw):
    kw.setdefault("queue_limit", 8)
    kw.setdefault("max_inflight", 4)
    kw.setdefault("registry", MetricsRegistry())
    return ServeScheduler(**kw)


def test_scheduler_pops_by_priority_and_deadline():
    s = _sched()
    for req in [ServeRequest("fifo", ["a"]),
                ServeRequest("late", ["a"], deadline_ts=time.time() + 500),
                ServeRequest("soon", ["a"], deadline_ts=time.time() + 400),
                ServeRequest("hi", ["a"], priority=9)]:
        s.submit(req)
    got = [s.pop(timeout=0)[0].request_id for _ in range(4)]
    assert got == ["hi", "soon", "late", "fifo"]


def test_scheduler_tenant_cap_and_release():
    s = _sched(max_inflight=2)
    s.submit(ServeRequest("a1", ["a"], tenant="A"))
    s.submit(ServeRequest("a2", ["a"], tenant="A"))
    with pytest.raises(Rejection) as ei:
        s.submit(ServeRequest("a3", ["a"], tenant="A"))
    assert ei.value.reason == "tenant_limit"
    # other tenants keep flowing past A's cap
    s.submit(ServeRequest("b1", ["a"], tenant="B"))
    # a slot frees only when an admitted request is marked done
    req, _ = s.pop(timeout=0)
    s.mark_done(req)
    s.submit(ServeRequest("a3", ["a"], tenant="A"))
    reg = s.registry
    assert reg.counters["serve_accepted"] == 4
    assert reg.counters["serve_rejected"] == 1


def test_scheduler_queue_bound_and_duplicate():
    s = _sched(queue_limit=2, max_inflight=99)
    s.submit(ServeRequest("r1", ["a"]))
    s.submit(ServeRequest("r2", ["a"]))
    with pytest.raises(Rejection) as ei:
        s.submit(ServeRequest("r3", ["a"]))
    assert ei.value.reason == "queue_full"
    with pytest.raises(Rejection) as ei:
        s.submit(ServeRequest("r1", ["a"], tenant="other"))
    assert ei.value.reason == "duplicate"
    # restart re-enqueue bypasses the duplicate check once dequeued
    req, _ = s.pop(timeout=0)
    s.mark_done(req)
    s.submit(ServeRequest("r1", ["a"]), already_journaled=True)


def test_scheduler_drain_refuses_and_wakes_popper():
    s = _sched()
    s.submit(ServeRequest("r1", ["a"]))
    s.start_drain()
    with pytest.raises(Rejection) as ei:
        s.submit(ServeRequest("r2", ["a"]))
    assert ei.value.reason == "draining"
    # a drained pop still surfaces what was queued, then returns None
    assert s.pop(timeout=0)[0].request_id == "r1"
    t0 = time.perf_counter()
    assert s.pop(timeout=30)[0] is None  # returns immediately: draining
    assert time.perf_counter() - t0 < 5


def test_scheduler_fails_expired_deadlines_fast():
    s = _sched()
    past = ServeRequest("old", ["a"], deadline_ts=time.time() - 1)
    live = ServeRequest("new", ["a"])
    s.submit(past)
    s.submit(live)
    req, expired = s.pop(timeout=0)
    assert req.request_id == "new"
    assert [r.request_id for r in expired] == ["old"]
    assert s.registry.counters["serve_deadline_expired"] == 1


# ------------------------------------------------- journal request events

def test_journal_request_lifecycle_merged_view(tmp_path):
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    j.record_request("r1", "accepted", paths=["/d/a.npz"], tenant="t",
                     priority=1, deadline_ts=None, overrides={},
                     submitted_ts=5.0)
    j.record_request("r1", "running")
    j.record_request("r2", "accepted", paths=["/d/b.npz"])
    j.record_request("r1", "done", n_cleaned=1)
    states = j.request_states()
    assert states["r1"]["state"] == "done"
    assert states["r1"]["paths"] == ["/d/a.npz"]  # accepted fields survive
    assert states["r1"]["n_cleaned"] == 1
    assert states["r2"]["state"] == "accepted"
    with pytest.raises(ValueError):
        j.record_request("r3", "exploded")


def test_journal_compaction_keeps_live_lines(tmp_path):
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    for i in range(3):  # three generations of the same request + path
        j._append({"schema": "icln-fleet-journal/1", "event": "done",
                   "path": "/d/a.npz", "sig": "s%d" % i, "config": "c"})
        j.record_request("r1", "accepted", paths=["/d/a.npz"], gen=i)
        j.record_request("r1", "running")
    j.record_request("r1", "done")
    j._append({"not": "ours"})  # foreign line: dropped by compaction
    n_before = len(open(j.path).read().splitlines())
    assert j.compact()
    lines = open(j.path).read().splitlines()
    assert len(lines) == 2 < n_before
    entries = [json.loads(ln) for ln in lines]
    done = next(e for e in entries if e["event"] == "done")
    assert done["sig"] == "s2"  # last generation won
    req = next(e for e in entries if e["event"] == "req")
    # merged: terminal state AND the accepted entry's description
    assert req["state"] == "done" and req["paths"] == ["/d/a.npz"]
    assert req["gen"] == 2
    # restart view identical across the compaction
    assert j.request_states()["r1"]["state"] == "done"


def test_journal_compact_while_appending_loses_nothing(make_journal):
    """The flock race drill, on both backends: writer threads
    locked_append unique 'done' lines while the main thread compacts
    repeatedly.  Every line is live (unique paths), so none may be lost
    to the inode swap (file) or to a seal/manifest-swap race
    (segmented — the ~2 KB fixture threshold seals constantly here)."""
    j = make_journal()
    N_THREADS, N_EACH = 4, 40
    errors = []

    def writer(t):
        try:
            for i in range(N_EACH):
                j._append({"schema": "icln-fleet-journal/1",
                           "event": "done", "path": "/d/t%d_%d" % (t, i),
                           "sig": "s", "config": "c"})
        except Exception as exc:  # pragma: no cover - failure reporting
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(N_THREADS)]
    for th in threads:
        th.start()
    for _ in range(25):
        j.compact()
        time.sleep(0.002)
    for th in threads:
        th.join()
    assert not errors
    j.compact()
    paths = {json.loads(ln)["path"]
             for ln in j.log.scan_text().splitlines() if ln.strip()}
    assert len(paths) == N_THREADS * N_EACH


# ------------------------------------------------- journal claim leases

def test_journal_claim_grammar(tmp_path):
    """The multi-host lease fold: claim wins on unowned work, a live
    lease blocks a steal, an expired lease allows it, heartbeats extend
    only the owner, release frees the work.  Explicit ``now`` values
    keep every transition deterministic."""
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    assert j.try_claim("w", host=0, nonce="a", ttl_s=10.0, now=100.0)
    # re-claim by the owner: allowed (lease refresh)
    assert j.try_claim("w", host=0, nonce="a", ttl_s=10.0, now=105.0)
    # live lease blocks another nonce
    assert not j.try_claim("w", host=1, nonce="b", ttl_s=10.0, now=109.0)
    own = j.claim_table(now=109.0)["w"]
    assert (own["host"], own["nonce"], own["live"]) == (0, "a", True)
    # expired lease is stealable
    assert not j.claim_table(now=120.0)["w"]["live"]
    assert j.try_claim("w", host=1, nonce="b", ttl_s=10.0, now=120.0)
    assert j.claim_table(now=121.0)["w"]["host"] == 1
    # release frees the work for anyone
    j.release("w", host=1, nonce="b", now=122.0)
    assert "w" not in j.claim_table(now=122.0)
    assert j.try_claim("w", host=0, nonce="c", ttl_s=10.0, now=123.0)
    with pytest.raises(ValueError):
        j.record_claim("w", host=0, nonce="c", ttl_s=1.0, state="bogus")


def test_journal_claim_heartbeat_extends_but_never_steals(tmp_path):
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    assert j.try_claim("w", host=0, nonce="a", ttl_s=10.0, now=100.0)
    j.heartbeat("w", host=0, nonce="a", ttl_s=10.0, now=108.0)
    assert j.claim_table(now=115.0)["w"]["live"]  # extended past 110
    # a loser's heartbeat is a fold no-op, not a takeover
    j.heartbeat("w", host=1, nonce="b", ttl_s=100.0, now=116.0)
    own = j.claim_table(now=117.0)["w"]
    assert (own["host"], own["nonce"]) == (0, "a")
    # an out-of-order claim (timestamp before the owner expired) loses
    assert not j.try_claim("w", host=1, nonce="b", ttl_s=10.0, now=112.0)


def test_journal_claim_torn_tail_tolerated_and_healed(tmp_path):
    """A crash mid-append leaves a torn last line: readers must skip it
    and the next append must heal it (prepend the missing newline) so
    the glued bytes never corrupt a good entry."""
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    assert j.try_claim("w1", host=0, nonce="a", ttl_s=10.0, now=100.0)
    with open(j.path, "a") as f:
        f.write('{"schema": "icln-fleet-journal/1", "event": "cl')
    assert j.claim_table(now=101.0)["w1"]["nonce"] == "a"  # torn: skipped
    assert j.try_claim("w2", host=1, nonce="b", ttl_s=10.0, now=101.0)
    table = j.claim_table(now=102.0)
    assert table["w1"]["nonce"] == "a" and table["w2"]["nonce"] == "b"
    # exactly one unparseable relic (the torn line); everything else is
    # whole json — the heal prepended a newline instead of gluing on
    def parses(ln):
        try:
            json.loads(ln)
            return True
        except ValueError:
            return False

    lines = [ln for ln in open(j.path).read().splitlines() if ln]
    assert sum(1 for ln in lines if not parses(ln)) == 1


def test_journal_compaction_keeps_live_claims_and_stats(tmp_path):
    """Compaction must preserve granted leases and each host's last
    stats snapshot, and drop released works' lines entirely."""
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    assert j.try_claim("held", host=0, nonce="a", ttl_s=1e6, now=100.0)
    j.heartbeat("held", host=0, nonce="a", ttl_s=1e6, now=101.0)
    assert j.try_claim("freed", host=1, nonce="b", ttl_s=1e6, now=100.0)
    j.release("freed", host=1, nonce="b", now=102.0)
    j.record_host_stats(0, {"fleet_cleaned": 1.0})
    j.record_host_stats(0, {"fleet_cleaned": 4.0})  # supersedes
    j.record_host_stats(1, {"fleet_stolen": 2.0})
    assert j.compact()
    table = j.claim_table(now=103.0)
    assert table["held"]["nonce"] == "a" and table["held"]["live"]
    assert "freed" not in table
    assert "freed" not in open(j.path).read()
    stats = j.host_stats()
    assert stats[0] == {"fleet_cleaned": 4.0}
    assert stats[1] == {"fleet_stolen": 2.0}


def test_journal_claim_two_process_flock_race(make_journal):
    """Two fresh processes race try_claim on the same work with distinct
    nonces, on both backends: the flock'd append serializes them, so
    exactly one must win — and the journal must stay fully parseable
    afterwards.  The workers auto-detect the backend from the path."""
    j = make_journal()
    worker = (
        "import sys\n"
        "from iterative_cleaner_tpu.resilience import FleetJournal\n"
        "j = FleetJournal(sys.argv[1])\n"
        "won = j.try_claim('w', host=int(sys.argv[2]),\n"
        "                  nonce=sys.argv[2], ttl_s=60.0)\n"
        "print('WON' if won else 'LOST')\n")
    from tests.conftest import repo_subprocess_env

    env = repo_subprocess_env()
    procs = [subprocess.Popen(
        [sys.executable, "-c", worker, j.path, str(i)],
        env=env, stdout=subprocess.PIPE, text=True) for i in (0, 1)]
    outs = [p.communicate(timeout=60)[0].strip() for p in procs]
    assert all(p.returncode == 0 for p in procs)
    assert sorted(outs) == ["LOST", "WON"], outs
    # the fold agrees with the winner's own read-back
    winner = outs.index("WON")
    assert j.claim_table(now=0.0)["w"]["nonce"] == str(winner)
    for ln in j.log.scan_text().splitlines():
        assert json.loads(ln)["event"] == "claim"


def test_compact_under_lock_missing_file(tmp_path):
    assert not compact_under_lock(str(tmp_path / "absent"), lambda t: t)


def test_trim_log_keeps_tail(tmp_path):
    p = str(tmp_path / "clean.log")
    for i in range(500):
        locked_append(p, "line %04d\n" % i)
    size = os.path.getsize(p)
    assert not trim_log(p, max_bytes=size + 1)  # under bound: no-op
    assert trim_log(p, max_bytes=100, keep_lines=10)
    kept = open(p).read().splitlines()
    assert kept == ["line %04d" % i for i in range(490, 500)]


# ------------------------------------------------------------------ spool

def _spool_submit(spool_dir, name, payload):
    tmp = os.path.join(spool_dir, ".%s.tmp" % name)
    with open(tmp, "w") as f:
        f.write(json.dumps(payload))
    os.replace(tmp, os.path.join(spool_dir, name + ".json"))


def test_spool_watcher_accept_reject_and_drain(tmp_path):
    spool = str(tmp_path / "spool")
    reg = MetricsRegistry()
    seen = []

    def on_request(req, _path):
        if req.tenant == "full":
            raise Rejection("queue_full", "full up")
        seen.append(req.request_id)

    w = SpoolWatcher(spool, on_request=on_request, registry=reg)
    _spool_submit(spool, "good", {"paths": ["/d/a.npz"]})
    _spool_submit(spool, "pressed", {"paths": ["/d/a.npz"],
                                     "tenant": "full"})
    with open(os.path.join(spool, "broken.json"), "w") as f:
        f.write("{half a json")
    assert w.scan_once() == 1
    assert seen == ["good"]  # file stem becomes the request id
    names = sorted(os.listdir(spool))
    assert "good.json.accepted" in names
    assert "pressed.json.rejected" in names
    assert "broken.json.rejected" in names
    assert reg.counters["serve_rejected_spool"] == 2
    # draining: new submissions stay untouched for the next daemon start
    _spool_submit(spool, "later", {"paths": ["/d/a.npz"]})
    assert w.scan_once(stop_intake=True) == 0
    assert "later.json" in os.listdir(spool)
    # dot-prefixed temp files are never claimed
    with open(os.path.join(spool, ".partial.json"), "w") as f:
        f.write("{}")
    assert w.pending_files() == [os.path.join(spool, "later.json")]


def test_spool_intake_fault_leaves_file_for_next_scan(tmp_path):
    from iterative_cleaner_tpu.resilience import FaultInjector

    spool = str(tmp_path / "spool")
    reg = MetricsRegistry()
    seen = []
    w = SpoolWatcher(spool, on_request=lambda r, _p: seen.append(r),
                     registry=reg,
                     faults=FaultInjector("intake:err@1", seed=0,
                                          registry=reg))
    _spool_submit(spool, "r1", {"paths": ["/d/a.npz"]})
    assert w.scan_once() == 0                  # injected: file untouched
    assert "r1.json" in os.listdir(spool)
    assert reg.counters["serve_retries"] == 1
    assert w.scan_once() == 1                  # next scan succeeds
    assert [r.request_id for r in seen] == ["r1"]


# ----------------------------------------------- in-process daemon pieces

def _daemon(tmp_path, **serve_kw):
    serve_kw.setdefault("http_port", 0)
    serve_kw.setdefault("poll_s", 0.02)
    serve_kw.setdefault("journal_path", str(tmp_path / "serve.jsonl"))
    # never the cwd-relative default: an in-process daemon's recorder
    # becomes the process-global active one, and a later watchdog trip
    # anywhere in the suite would dump it into the repo root
    serve_kw.setdefault("flight_recorder",
                        str(tmp_path / "serve.flight.json"))
    cfg = ServeConfig(**serve_kw)
    return ServeDaemon(cfg, NUMPY_BASE, quiet=True)


def _start(daemon):
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    deadline = time.time() + 30
    while daemon._httpd is None:
        assert time.time() < deadline, "daemon never bound its port"
        time.sleep(0.01)
    return t, "http://127.0.0.1:%d" % daemon._httpd.server_address[1]


def _get(url, expect=200):
    try:
        r = urllib.request.urlopen(url, timeout=10)
        assert r.status == expect
        return json.loads(r.read()) if expect == 200 else None
    except urllib.error.HTTPError as exc:
        assert exc.code == expect, (exc.code, exc.read())
        return json.loads(exc.read())


def _post(url, doc, expect=200):
    req = urllib.request.Request(url, data=json.dumps(doc).encode())
    try:
        r = urllib.request.urlopen(req, timeout=10)
        assert r.status == expect
        return json.loads(r.read())
    except urllib.error.HTTPError as exc:
        assert exc.code == expect, (exc.code, exc.read())
        return json.loads(exc.read())


def test_daemon_http_round_trip_in_process(tmp_path):
    ar, _ = make_synthetic_archive(nsub=4, nchan=8, nbin=16, seed=7)
    a = str(tmp_path / "a.npz")
    save_archive(ar, a)
    d = _daemon(tmp_path, spool_dir=str(tmp_path / "spool"))
    t, url = _start(d)
    try:
        got = _post(url + "/submit", {"paths": [a], "id": "r1"})
        assert got == {"accepted": True, "id": "r1", "tenant": "default"}
        deadline = time.time() + 60
        while time.time() < deadline:
            state = _get(url + "/requests/r1")
            if state["state"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert state["state"] == "done", state
        assert state["n_cleaned"] == 1
        assert os.path.exists(a + "_cleaned.npz")
        h = _get(url + "/healthz")
        assert h["status"] == "ok" and h["completed"] == 1
        assert _get(url + "/requests/ghost", expect=404)["error"]
        # /metrics is the live registry in Prometheus exposition format
        text = urllib.request.urlopen(url + "/metrics").read().decode()
        from iterative_cleaner_tpu.telemetry import parse_prometheus_text

        parsed = parse_prometheus_text(text)
        assert parsed["icln_serve_completed_total"] == 1.0
        # malformed submissions answer 400 without touching the daemon
        assert _post(url + "/submit", {"paths": []}, expect=400)["error"]
    finally:
        d._on_signal(signal.SIGTERM, None)
        t.join(30)
    assert not t.is_alive()
    # duplicate of a journaled id stays refused after the fact
    states = d.journal.request_states()
    assert states["r1"]["state"] == "done"


def test_daemon_http_backpressure_429_and_503(tmp_path):
    # no worker loop running: admissions stay queued, so the caps are
    # exercised deterministically
    from iterative_cleaner_tpu.serve.http import make_server

    d = _daemon(tmp_path, max_inflight=1, queue_limit=8)
    server = make_server(d, 0)
    thr = threading.Thread(target=server.serve_forever,
                           kwargs={"poll_interval": 0.05}, daemon=True)
    thr.start()
    url = "http://127.0.0.1:%d" % server.server_address[1]
    try:
        _post(url + "/submit", {"paths": ["/d/a.npz"], "id": "r1"})
        got = _post(url + "/submit", {"paths": ["/d/b.npz"], "id": "r2"},
                    expect=429)
        assert got["reason"] == "tenant_limit"
        got = _post(url + "/submit", {"paths": ["/d/a.npz"], "id": "r1"},
                    expect=409)
        assert got["reason"] == "duplicate"
        d.scheduler.start_drain()
        got = _post(url + "/submit", {"paths": ["/d/c.npz"], "id": "r3",
                                      "tenant": "other"}, expect=503)
        assert got["reason"] == "draining"
        assert d.registry.counters["serve_rejected"] == 3
    finally:
        server.shutdown()
        server.server_close()


def test_daemon_recover_reenqueues_nonterminal(tmp_path):
    j = FleetJournal(str(tmp_path / "serve.jsonl"))
    j.record_request("gone", "accepted", paths=["/d/a.npz"])
    j.record_request("gone", "done")
    j.record_request("mid", "accepted", paths=["/d/b.npz"], priority=1)
    j.record_request("mid", "running")
    j.record_request("fresh", "accepted", paths=["/d/c.npz"])
    j.record_request("broken", "accepted")  # no paths: unrecoverable
    d = _daemon(tmp_path)
    assert d.recover() == 2
    popped = {d.scheduler.pop(timeout=0)[0].request_id for _ in range(2)}
    assert popped == {"mid", "fresh"}
    assert d.scheduler.pop(timeout=0)[0] is None
    states = d.journal.request_states()
    assert states["broken"]["state"] == "failed"
    assert states["gone"]["state"] == "done"  # terminal: not re-run


# ------------------------------------------------- subprocess daemon tests

SERVE_FLAGS = ["--serve", "--http-port", "0", "--rotation", "roll",
               "--fft_mode", "dft", "--max_iter", "3", "--io-workers", "1"]
BATCH_FLAGS = ["--fleet", "--rotation", "roll", "--fft_mode", "dft",
               "--max_iter", "3", "--io-workers", "1", "-q"]


def _start_daemon(tmp_path, extra=(), **env):
    out_path = str(tmp_path / "daemon.out")
    outf = open(out_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "iterative_cleaner_tpu", *SERVE_FLAGS,
         "--spool", "spool", *extra],
        env=repo_subprocess_env(ICLEAN_PROBE_TIMEOUT="0", **env),
        cwd=str(tmp_path), stdout=outf, stderr=subprocess.STDOUT)
    return proc, out_path


def _daemon_port(proc, out_path, timeout=120):
    needle = "serve: http listening on 127.0.0.1:"
    deadline = time.time() + timeout
    while time.time() < deadline:
        text = open(out_path).read() if os.path.exists(out_path) else ""
        for line in text.splitlines():
            if line.startswith(needle):
                return int(line[len(needle):])
        if proc.poll() is not None:
            pytest.fail("daemon exited before binding (rc %s):\n%s"
                        % (proc.returncode, text[-3000:]))
        time.sleep(0.1)
    proc.kill()
    pytest.fail("daemon never printed its port:\n"
                + open(out_path).read()[-3000:])


def _wait_request_done(jpath, rid, proc=None, timeout=240):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(jpath):
            j = FleetJournal(jpath)
            state = j.request_states().get(rid, {}).get("state")
            if state in ("done", "failed"):
                return state
        if proc is not None and proc.poll() is not None:
            pytest.fail("daemon exited early (rc %s)" % proc.returncode)
        time.sleep(0.2)
    pytest.fail("request %s never reached a terminal state" % rid)


def _journal_text(jpath):
    """The journal's full text on either backend (file or segmented
    directory) — raw reads in tests go through here."""
    if os.path.isdir(jpath):
        return FleetJournal(jpath).log.scan_text()
    if not os.path.exists(jpath):
        return ""
    return open(jpath).read()


def _count_done_lines(jpath):
    out = []
    for ln in _journal_text(jpath).splitlines():
        try:
            e = json.loads(ln)
        except ValueError:
            continue
        if e.get("event") == "done":
            out.append(e["path"])
    return out


def _sigterm_and_wait(proc, timeout=120):
    proc.send_signal(signal.SIGTERM)
    return proc.wait(timeout=timeout)


def _run_batch_reference(tmp_path, paths):
    r = subprocess.run(
        [sys.executable, "-m", "iterative_cleaner_tpu", *BATCH_FLAGS,
         *[os.path.basename(p) for p in paths]],
        env=repo_subprocess_env(ICLEAN_PROBE_TIMEOUT="0"),
        cwd=str(tmp_path), capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]


def _assert_outputs_bit_equal(paths, ref_paths, ext):
    for p, rp in zip(paths, ref_paths):
        out, ref = p + "_cleaned" + ext, rp + "_cleaned" + ext
        assert os.path.exists(out), out
        with open(out, "rb") as a, open(ref, "rb") as b:
            assert a.read() == b.read(), os.path.basename(out)


@pytest.mark.slow
def test_serve_kill9_restart_zero_duplicate_cleans(tmp_path,
                                                   journal_backend):
    """The daemon's crash contract end-to-end, on both journal backends:
    wedge a request mid-fleet with a hang fault, ``kill -9`` the daemon,
    restart it — the journaled request re-enqueues, already-journaled
    archives are skipped, and the outputs are byte-identical to an
    uninterrupted batch CLI run.  ``.icar`` outputs are raw little-endian
    arrays, so byte comparison is exact.  The segmented variant runs
    with a 10 KB seal threshold, so the crash leaves sealed segments plus
    a torn active tail for the restart to heal."""
    geoms = [(6, 16, 32)] * 2 + [(8, 16, 32)] * 2
    paths = _write_fleet(tmp_path, geoms, ext=".icar")
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    ref_paths = _write_fleet(ref_dir, geoms, ext=".icar")
    _run_batch_reference(ref_dir, ref_paths)
    if journal_backend == "segmented":
        jpath = str(tmp_path / "journal.d")
        jflags = ["--journal", "journal.d" + os.sep,
                  "--journal-segment-mb", "0.01"]
    else:
        jpath = str(tmp_path / "serve.journal.jsonl")
        jflags = []

    # daemon 1: the 3rd load hangs 600s -> first bucket (2 archives)
    # completes and journals, then the pipeline wedges
    proc, out = _start_daemon(tmp_path,
                              extra=["--faults", "load:hang@3", *jflags],
                              ICLEAN_FAULT_HANG_S="600")
    _daemon_port(proc, out)
    _spool_submit(str(tmp_path / "spool"), "big",
                  {"paths": [os.path.basename(p) for p in paths]})
    deadline = time.time() + 180
    while time.time() < deadline:
        if len(_count_done_lines(jpath)) >= 2:
            break
        if proc.poll() is not None:
            pytest.fail("daemon exited early (rc %s):\n%s"
                        % (proc.returncode, open(out).read()[-3000:]))
        time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("journal never showed per-archive progress")
    os.kill(proc.pid, signal.SIGKILL)
    assert proc.wait(timeout=60) == -signal.SIGKILL
    assert len(_count_done_lines(jpath)) == 2

    # daemon 2: same cwd, no faults — recovery re-runs the journaled
    # request; the two journaled archives must not re-clean
    proc2, out2 = _start_daemon(tmp_path, extra=jflags)
    _daemon_port(proc2, out2)
    assert _wait_request_done(jpath, "big", proc2) == "done"
    assert _sigterm_and_wait(proc2) == 0

    done = _count_done_lines(jpath)
    assert len(done) == 4 and len(set(done)) == 4  # exactly once each
    states = FleetJournal(jpath).request_states()
    assert states["big"]["state"] == "done"
    assert states["big"]["n_skipped"] == 2  # resumed, not re-cleaned
    assert states["big"]["n_cleaned"] == 2
    _assert_outputs_bit_equal(paths, ref_paths, ".icar")
    assert "serve: recovered 1 journaled request" in open(out2).read()
    # whatever the kill -9 left behind, the journal fscks clean
    from iterative_cleaner_tpu.analysis.journal_fsck import fsck_journal

    report = fsck_journal(jpath)
    assert report.ok, [i.render() for i in report.issues]


def test_serve_sigterm_drains_gracefully(tmp_path):
    """SIGTERM during an active clean: the request finishes and journals,
    mid-drain spool submissions stay untouched, exit code 0."""
    paths = _write_fleet(tmp_path, [(6, 16, 32)] * 2, ext=".icar")
    jpath = str(tmp_path / "serve.journal.jsonl")
    proc, out = _start_daemon(tmp_path)
    _daemon_port(proc, out)
    spool = str(tmp_path / "spool")
    _spool_submit(spool, "work",
                  {"paths": [os.path.basename(p) for p in paths]})
    deadline = time.time() + 120
    while time.time() < deadline:
        if '"state": "running"' in (open(jpath).read()
                                    if os.path.exists(jpath) else ""):
            break
        if proc.poll() is not None:
            pytest.fail("daemon exited early:\n" + open(out).read()[-3000:])
        time.sleep(0.05)
    else:
        proc.kill()
        pytest.fail("request never started running")
    proc.send_signal(signal.SIGTERM)
    _spool_submit(spool, "mid_drain", {"paths": ["whatever.icar"]})
    assert proc.wait(timeout=120) == 0
    # the active request finished and journaled before exit
    states = FleetJournal(jpath).request_states()
    assert states["work"]["state"] in ("done", "failed")
    # a mid-drain submission is left for the next daemon start
    assert "mid_drain.json" in os.listdir(spool)
    assert "drained" in open(out).read()


def test_serve_second_sigterm_forces_nonzero_exit(tmp_path):
    """A wedged drain stays killable: the first SIGTERM starts the drain,
    the second force-exits non-zero without waiting."""
    from iterative_cleaner_tpu.serve.daemon import FORCE_EXIT_CODE

    paths = _write_fleet(tmp_path, [(6, 16, 32)], ext=".icar")
    jpath = str(tmp_path / "serve.journal.jsonl")
    proc, out = _start_daemon(tmp_path,
                              extra=["--faults", "execute:hang@1",
                                     "--stage-timeout", "0"],
                              ICLEAN_FAULT_HANG_S="600")
    _daemon_port(proc, out)
    _spool_submit(str(tmp_path / "spool"), "stuck",
                  {"paths": [os.path.basename(paths[0])]})
    deadline = time.time() + 120
    while time.time() < deadline:
        if '"state": "running"' in (open(jpath).read()
                                    if os.path.exists(jpath) else ""):
            break
        time.sleep(0.05)
    else:
        proc.kill()
        pytest.fail("request never started running")
    time.sleep(0.5)  # let the execute hang actually begin
    proc.send_signal(signal.SIGTERM)
    time.sleep(1.0)
    assert proc.poll() is None  # draining, wedged, still alive
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=60) == FORCE_EXIT_CODE


@pytest.mark.slow
def test_serve_fault_soak_masks_bit_equal(tmp_path):
    """Deterministic serve-layer fault soak: intake, scheduler, load and
    execute faults all fire; the daemon never wedges, keeps answering
    /healthz, every request ends terminal, and the masks stay
    bit-identical to a fault-free batch CLI run."""
    geoms = [(6, 16, 32), (6, 16, 32), (8, 16, 32)]
    paths = _write_fleet(tmp_path, geoms, ext=".icar")
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    ref_paths = _write_fleet(ref_dir, geoms, ext=".icar")
    _run_batch_reference(ref_dir, ref_paths)
    jpath = str(tmp_path / "serve.journal.jsonl")

    proc, out = _start_daemon(
        tmp_path,
        extra=["--faults", "intake:err@1,sched:err@2,load:err@2,"
                           "execute:oom@1",
               "--retries", "3"],
        ICLEAN_FAULT_HANG_S="0.01")
    port = _daemon_port(proc, out)
    url = "http://127.0.0.1:%d" % port
    spool = str(tmp_path / "spool")
    for i, p in enumerate(paths):
        _spool_submit(spool, "req%d" % i,
                      {"paths": [os.path.basename(p)], "priority": i})
    for i in range(len(paths)):
        assert _wait_request_done(jpath, "req%d" % i, proc) == "done"
    h = json.load(urllib.request.urlopen(url + "/healthz", timeout=10))
    assert h["status"] == "ok"
    assert h["completed"] == len(paths) and h["failed"] == 0
    text = urllib.request.urlopen(url + "/metrics", timeout=10).read()
    from iterative_cleaner_tpu.telemetry import parse_prometheus_text

    c = parse_prometheus_text(text.decode())
    assert c["icln_serve_accepted_total"] == len(paths)
    assert c["icln_serve_completed_total"] == len(paths)
    assert c.get("icln_serve_retries_total", 0) >= 2  # intake+sched faults
    assert c.get("icln_fleet_retries_total", 0) >= 1  # load transient
    # the OOM lands on whichever group runs first; a multi-archive group
    # splits, a singleton degrades — either way the ladder absorbed it
    assert (c.get("icln_fleet_oom_splits_total", 0)
            + c.get("icln_fleet_degraded_total", 0)) >= 1
    assert _sigterm_and_wait(proc) == 0
    _assert_outputs_bit_equal(paths, ref_paths, ".icar")


def test_serve_warm_repeat_geometry_zero_new_cache_entries(tmp_path):
    """A warm daemon serves a repeat-geometry request from the resident
    AOT executables: fleet_precompile_hits grows and the persistent
    compile cache gains NO new entries."""
    a, b = _write_fleet(tmp_path, [(6, 16, 32), (6, 16, 32)], ext=".npz")
    cache = str(tmp_path / "cache")
    jpath = str(tmp_path / "serve.journal.jsonl")
    proc, out = _start_daemon(tmp_path,
                              extra=["--compile-cache", "cache"])
    port = _daemon_port(proc, out)
    url = "http://127.0.0.1:%d" % port
    from iterative_cleaner_tpu.telemetry import parse_prometheus_text

    def scrape():
        text = urllib.request.urlopen(url + "/metrics", timeout=10).read()
        return parse_prometheus_text(text.decode())

    _spool_submit(str(tmp_path / "spool"), "cold",
                  {"paths": [os.path.basename(a)]})
    assert _wait_request_done(jpath, "cold", proc) == "done"
    hits_cold = scrape().get("icln_fleet_precompile_hits_total", 0)
    entries = sorted(os.listdir(cache))
    assert entries, "cold request wrote no persistent-cache entries"

    _spool_submit(str(tmp_path / "spool"), "warm",
                  {"paths": [os.path.basename(b)]})
    assert _wait_request_done(jpath, "warm", proc) == "done"
    assert (scrape().get("icln_fleet_precompile_hits_total", 0)
            >= hits_cold + 1)
    assert sorted(os.listdir(cache)) == entries, \
        "warm repeat-geometry request wrote new compile-cache entries"
    assert _sigterm_and_wait(proc) == 0


# --------------------------------------------------------- online streams

def test_spool_torn_json_left_for_retry(tmp_path):
    """A truncated submission (producer caught mid-write without an
    atomic rename) must stay ``.json`` for the next scan — NOT be
    renamed ``.rejected`` — and be accepted once the writer finishes.
    Genuinely malformed JSON still rejects."""
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    reg = MetricsRegistry()
    seen = []
    w = SpoolWatcher(spool, on_request=lambda r, _p: seen.append(r),
                     registry=reg)
    for name, half in (("torn", '{"paths": ["/d/a'),
                       ("empty", ""),
                       ("open_list", '{"paths": [')):
        with open(os.path.join(spool, name + ".json"), "w") as f:
            f.write(half)
    assert w.scan_once() == 0
    assert sorted(os.listdir(spool)) == [
        "empty.json", "open_list.json", "torn.json"]   # all left in place
    assert reg.counters["serve_spool_torn"] == 3
    assert "serve_rejected_spool" not in reg.counters
    # the writer finishes: the same file now parses and is accepted
    _spool_submit(spool, "torn", {"paths": ["/d/a.npz"]})
    assert w.scan_once() == 1
    assert [r.request_id for r in seen] == ["torn"]
    assert "torn.json.accepted" in os.listdir(spool)
    # mid-document garbage is malformed, not torn: rejected as before
    with open(os.path.join(spool, "garbage.json"), "w") as f:
        f.write("{half a json")
    w.scan_once()
    assert "garbage.json.rejected" in os.listdir(spool)
    assert reg.counters["serve_rejected_spool"] == 1


def test_requests_index_endpoint(tmp_path):
    """GET /requests: every journaled request (terminal ones included)
    with id/state/kind/tenant, journal-backed so it survives restarts."""
    from iterative_cleaner_tpu.serve.http import make_server

    d = _daemon(tmp_path, max_inflight=4)
    server = make_server(d, 0)
    thr = threading.Thread(target=server.serve_forever,
                           kwargs={"poll_interval": 0.05}, daemon=True)
    thr.start()
    url = "http://127.0.0.1:%d" % server.server_address[1]
    try:
        assert _get(url + "/requests") == {"n": 0, "requests": []}
        _post(url + "/submit", {"paths": ["/d/a.npz"], "id": "r1"})
        _post(url + "/submit", {"paths": ["/d/b.npz"], "id": "r2",
                                "tenant": "vlbi"})
        idx = _get(url + "/requests")
        assert idx["n"] == 2
        assert [r["id"] for r in idx["requests"]] == ["r1", "r2"]
        for row in idx["requests"]:
            assert row["kind"] == "clean"
            assert row["state"] in ("accepted", "queued")
        assert idx["requests"][1]["tenant"] == "vlbi"
    finally:
        server.shutdown()
        server.server_close()
    # a fresh daemon over the same journal serves the same index
    d2 = _daemon(tmp_path)
    idx2 = d2.request_index()
    assert {r["id"] for r in idx2["requests"]} == {"r1", "r2"}


def test_daemon_stream_http_flow_and_parity(tmp_path):
    """The in-process stream lifecycle: open (kind: "stream"), per-subint
    POSTs with seq-dedup, close, worker finalization — and the cleaned
    output's mask bit-equal with the batch clean of the same subints."""
    from iterative_cleaner_tpu.online import StreamMeta

    ar, _ = make_synthetic_archive(nsub=5, nchan=8, nbin=16, seed=41)
    cube = ar.total_intensity()
    chunks = tmp_path / "chunks"
    chunks.mkdir()
    paths = []
    for i in range(5):
        p = str(chunks / ("c%03d.npy" % i))
        __import__("numpy").save(p, cube[i])
        paths.append(p)
    meta = StreamMeta.from_archive(ar)
    d = _daemon(tmp_path)
    t, url = _start(d)
    try:
        got = _post(url + "/submit", {"kind": "stream", "id": "obs",
                                      "meta": meta.to_dict()})
        assert got["accepted"] is True
        for i, p in enumerate(paths):
            got = _post(url + "/stream/obs/subint", {"path": p, "seq": i})
            assert got["ingested"] is True and got["n_subints"] == i + 1
        # a blind client retry of a journaled seq must NOT re-ingest
        got = _post(url + "/stream/obs/subint", {"path": paths[2],
                                                 "seq": 2})
        assert got == {"duplicate": True, "id": "obs", "seq": 2,
                       "n_ingested": 5}
        idx = _get(url + "/requests")
        assert {"id": "obs", "state": "running", "kind": "stream",
                "tenant": "default"} in idx["requests"]
        # unknown stream ids 404; a chunk the daemon cannot load 400s
        assert _get(url + "/stream/ghost/close", expect=404)
        _post(url + "/stream/ghost/close", {}, expect=404)
        _post(url + "/stream/obs/subint",
              {"path": str(chunks / "missing.npy"), "seq": 99},
              expect=400)
        got = _post(url + "/stream/obs/close", {})
        assert got["closed"] is True and got["n_ingested"] == 5
        deadline = time.time() + 120
        while time.time() < deadline:
            state = _get(url + "/requests/obs")
            if state["state"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert state["state"] == "done", state
        assert state["n_subints"] == 5
        assert state["recompiles_steady"] == 0
        out = state["out"]
        assert out == str(chunks / "obs_cleaned.npz")
        # bit-equality with the offline batch path over the same cube
        cleaned = load_archive(out)
        ref = clean_archive(ar, NUMPY_BASE)
        import numpy as np

        np.testing.assert_array_equal(
            cleaned.weights == 0, np.asarray(ref.final_weights) == 0)
        h = _get(url + "/healthz")
        assert h["streams"] == 0      # finalized streams leave the table
        # further subints answer 404: the stream is finished, not open
        _post(url + "/stream/obs/subint", {"path": paths[0], "seq": 0},
              expect=404)
    finally:
        d._on_signal(signal.SIGTERM, None)
        t.join(30)
    assert not t.is_alive()


@pytest.mark.slow
def test_serve_stream_kill9_resume_zero_duplicate_ingests(tmp_path):
    """The stream crash contract: SIGKILL a daemon holding an open stream
    mid-ingest, restart it in the same cwd — the journaled chunks replay
    from disk (counted as replays, not ingests), a client re-POST of an
    already-journaled seq answers duplicate, and the resumed stream
    closes with exactly one ingest per subint, mask bit-equal with
    batch."""
    import numpy as np

    from iterative_cleaner_tpu.online import StreamMeta, assemble_archive

    ar, _ = make_synthetic_archive(nsub=6, nchan=16, nbin=32, seed=47)
    cube = np.asarray(ar.total_intensity(), dtype=np.float64)
    meta = StreamMeta.from_archive(ar)
    chunks = tmp_path / "chunks"
    chunks.mkdir()
    paths = []
    for i in range(6):
        p = str(chunks / ("c%03d.npy" % i))
        np.save(p, cube[i])
        paths.append(p)
    jpath = str(tmp_path / "serve.journal.jsonl")

    proc, out = _start_daemon(tmp_path)
    port = _daemon_port(proc, out)
    url = "http://127.0.0.1:%d" % port
    _post(url + "/submit", {"kind": "stream", "id": "s1",
                            "meta": meta.to_dict()})
    for i in range(3):
        got = _post(url + "/stream/s1/subint",
                    {"path": paths[i], "seq": i})
        assert got["ingested"] is True
    os.kill(proc.pid, signal.SIGKILL)
    assert proc.wait(timeout=60) == -signal.SIGKILL

    proc2, out2 = _start_daemon(tmp_path)
    port2 = _daemon_port(proc2, out2)
    url2 = "http://127.0.0.1:%d" % port2
    try:
        assert "serve: recovered stream s1 (3 chunks replayed)" \
            in open(out2).read()
        # blind client retries of everything already sent: all duplicates
        for i in range(3):
            got = _post(url2 + "/stream/s1/subint",
                        {"path": paths[i], "seq": i})
            assert got["duplicate"] is True, got
        for i in range(3, 6):
            got = _post(url2 + "/stream/s1/subint",
                        {"path": paths[i], "seq": i})
            assert got["ingested"] is True
            assert got["n_ingested"] == i + 1
        got = _post(url2 + "/stream/s1/close", {})
        assert got["closed"] is True and got["n_ingested"] == 6
        assert _wait_request_done(jpath, "s1", proc2) == "done"
        from iterative_cleaner_tpu.telemetry import parse_prometheus_text

        text = urllib.request.urlopen(url2 + "/metrics",
                                      timeout=10).read().decode()
        parsed = parse_prometheus_text(text)
        # replays are replays, retries are duplicates, and every subint
        # was ingested exactly once across both daemon lives
        assert parsed["icln_online_replayed_subints_total"] == 3.0
        assert parsed["icln_online_duplicate_subints_total"] == 3.0
        assert parsed["icln_online_subints_total"] == 6.0
    finally:
        if proc2.poll() is None:
            assert _sigterm_and_wait(proc2) == 0
    view = FleetJournal(jpath).request_states()["s1"]
    assert view["state"] == "done"
    assert view["n_subints"] == 6
    assert view["recompiles_steady"] == 0
    cleaned = load_archive(str(chunks / "s1_cleaned.npz"))
    ref_cfg = CleanConfig(backend="jax", max_iter=3, rotation="roll",
                          fft_mode="dft")
    ref = clean_archive(
        assemble_archive(meta, cube, np.ones((6, 16))), ref_cfg)
    np.testing.assert_array_equal(
        cleaned.weights == 0, np.asarray(ref.final_weights) == 0)

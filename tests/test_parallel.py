"""Sharding / multi-device tests on the 8-device virtual CPU mesh."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iterative_cleaner_tpu.stats.masked_jax import rfft_magnitudes


def test_eight_virtual_devices_present():
    assert len(jax.devices()) >= 8


def test_dft_matches_fft():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 6, 32)))
    np.testing.assert_allclose(
        np.asarray(rfft_magnitudes(x, "dft")),
        np.asarray(rfft_magnitudes(x, "fft")),
        rtol=1e-9, atol=1e-9,
    )
    with pytest.raises(ValueError):
        rfft_magnitudes(x, "welch")


@pytest.mark.parametrize("n", [8, 4, 2])
def test_dryrun_multichip(n, monkeypatch):
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    g.dryrun_multichip(n)


def test_entry_compiles():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, args = g.entry()
    new_w, scores = jax.jit(fn)(*args)
    assert new_w.shape == scores.shape == args[1].shape


def test_sharded_matches_single_device():
    """The sharded full step must produce the same mask as unsharded."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from iterative_cleaner_tpu.engine.loop import (
        clean_dedispersed_jax,
        prepare_cube_jax,
    )
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

    ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=3,
                                   dtype=np.float64)
    cube = jnp.asarray(ar.total_intensity())
    weights = jnp.asarray(ar.weights)
    freqs = jnp.asarray(ar.freqs_mhz)

    def full(cube, weights, freqs):
        ded, shifts = prepare_cube_jax(
            cube, freqs, ar.dm, ar.centre_freq_mhz, ar.period_s,
            baseline_duty=0.15, rotation="roll",
        )
        outs = clean_dedispersed_jax(
            ded, weights, shifts, max_iter=3, chanthresh=5.0,
            subintthresh=5.0, pulse_slice=(0, 0), pulse_scale=1.0,
            pulse_active=False, rotation="roll", fft_mode="dft",
        )
        return outs.final_weights

    single = np.asarray(jax.jit(full)(cube, weights, freqs))

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("sub", "chan"))
    csh = NamedSharding(mesh, P("sub", "chan", None))
    wsh = NamedSharding(mesh, P("sub", "chan"))
    rep = NamedSharding(mesh, P())
    sharded_fn = jax.jit(full, in_shardings=(csh, wsh, rep), out_shardings=wsh)
    with mesh:
        sharded = np.asarray(sharded_fn(
            jax.device_put(cube, csh), jax.device_put(weights, wsh),
            jax.device_put(freqs, rep),
        ))
    np.testing.assert_array_equal(single == 0, sharded == 0)
    np.testing.assert_allclose(single, sharded, rtol=1e-12)

"""Sharding / multi-device tests on the 8-device virtual CPU mesh."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iterative_cleaner_tpu.stats.masked_jax import rfft_magnitudes


def test_eight_virtual_devices_present():
    assert len(jax.devices()) >= 8


def test_dft_matches_fft():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 6, 32)))
    np.testing.assert_allclose(
        np.asarray(rfft_magnitudes(x, "dft")),
        np.asarray(rfft_magnitudes(x, "fft")),
        rtol=1e-9, atol=1e-9,
    )
    with pytest.raises(ValueError):
        rfft_magnitudes(x, "welch")


@pytest.mark.parametrize("n", [
    pytest.param(8, marks=pytest.mark.slow),
    pytest.param(4, marks=pytest.mark.slow), 2])
def test_dryrun_multichip(n, monkeypatch):
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    g.dryrun_multichip(n)


def test_entry_compiles():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, args = g.entry()
    new_w, scores = jax.jit(fn)(*args)
    assert new_w.shape == scores.shape == args[1].shape


def test_sharded_matches_single_device():
    """The sharded full step must produce the same mask as unsharded."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from iterative_cleaner_tpu.engine.loop import (
        clean_dedispersed_jax,
        prepare_cube_jax,
    )
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

    ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=3,
                                   dtype=np.float64)
    cube = jnp.asarray(ar.total_intensity())
    weights = jnp.asarray(ar.weights)
    freqs = jnp.asarray(ar.freqs_mhz)

    def full(cube, weights, freqs):
        ded, shifts = prepare_cube_jax(
            cube, freqs, ar.dm, ar.centre_freq_mhz, ar.period_s,
            baseline_duty=0.15, rotation="roll",
        )
        outs = clean_dedispersed_jax(
            ded, weights, shifts, max_iter=3, chanthresh=5.0,
            subintthresh=5.0, pulse_slice=(0, 0), pulse_scale=1.0,
            pulse_active=False, rotation="roll", fft_mode="dft",
        )
        return outs.final_weights

    single = np.asarray(jax.jit(full)(cube, weights, freqs))

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("sub", "chan"))
    csh = NamedSharding(mesh, P("sub", "chan", None))
    wsh = NamedSharding(mesh, P("sub", "chan"))
    rep = NamedSharding(mesh, P())
    sharded_fn = jax.jit(full, in_shardings=(csh, wsh, rep), out_shardings=wsh)
    with mesh:
        sharded = np.asarray(sharded_fn(
            jax.device_put(cube, csh), jax.device_put(weights, wsh),
            jax.device_put(freqs, rep),
        ))
    np.testing.assert_array_equal(single == 0, sharded == 0)
    np.testing.assert_allclose(single, sharded, rtol=1e-12)


# --- batched / sharded / streaming library paths ---------------------------

def _mk(seed, **kw):
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

    params = dict(nsub=8, nchan=16, nbin=32)
    params.update(kw)
    ar, _ = make_synthetic_archive(seed=seed, **params)
    return ar


def _roll_cfg(**kw):
    from iterative_cleaner_tpu.config import CleanConfig

    return CleanConfig(rotation="roll", fft_mode="dft", dtype="float64", **kw)


def test_batched_matches_individual():
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.parallel import clean_archives_batched

    cfg = _roll_cfg()
    archives = [_mk(s) for s in range(4)]
    batched = clean_archives_batched(archives, cfg)
    for ar, b in zip(archives, batched):
        single = clean_archive(ar.clone(), cfg)
        np.testing.assert_array_equal(single.final_weights, b.final_weights)
        assert single.loops == b.loops
        assert single.converged == b.converged
        np.testing.assert_array_equal(single.loop_diffs, b.loop_diffs)


def test_batched_sharded_with_padding():
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.parallel import batch_mesh, clean_archives_batched

    cfg = _roll_cfg()
    archives = [_mk(10 + s) for s in range(5)]  # 5 archives on 8 devices
    mesh = batch_mesh(8)
    batched = clean_archives_batched(archives, cfg, mesh=mesh)
    assert len(batched) == 5
    for ar, b in zip(archives, batched):
        single = clean_archive(ar.clone(), cfg)
        np.testing.assert_array_equal(single.final_weights, b.final_weights)


def test_batched_pallas_fused_matches_individual():
    """Round 3: the batch path keeps the Pallas kernels — the custom_vmap
    rules fold the batch into each launch's grid.  Explicit pallas median
    + fused stats, batched vs individual, must agree bit-for-bit (both
    float32, both fused)."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel import clean_archives_batched

    cfg = CleanConfig(rotation="roll", fft_mode="dft", dtype="float32",
                      median_impl="pallas", stats_impl="fused")
    archives = [_mk(s) for s in range(3)]
    batched = clean_archives_batched(archives, cfg)
    for ar, b in zip(archives, batched):
        single = clean_archive(ar.clone(), cfg)
        np.testing.assert_array_equal(single.final_weights, b.final_weights)
        np.testing.assert_array_equal(single.scores, b.scores)
        assert single.loops == b.loops


def test_batched_pure_mesh_runs_kernels_hybrid_rejects():
    """Pure ('batch',) meshes shard_map-route the Pallas kernels (each
    device vmap-cleans its local archives, zero collectives) — masks must
    equal the unsharded kernel run.  Hybrid meshes stay GSPMD-routed,
    where explicit pallas/fused must be rejected up front."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel import (
        batch_mesh,
        clean_archives_batched,
        hybrid_batch_cell_mesh,
    )

    archives = [_mk(s) for s in range(3)]  # 3 over 8 devices -> padded
    cfg = CleanConfig(rotation="roll", fft_mode="dft", dtype="float32",
                      median_impl="pallas", stats_impl="fused")
    batched = clean_archives_batched(archives, cfg, mesh=batch_mesh(8))
    for ar, b in zip(archives, batched):
        single = clean_archive(ar.clone(), cfg)
        np.testing.assert_array_equal(single.final_weights, b.final_weights)
        assert single.loops == b.loops

    with pytest.raises(ValueError, match="hybrid"):
        clean_archives_batched(
            archives, cfg, mesh=hybrid_batch_cell_mesh(batch=2))


def test_batched_rejects_ragged_shapes():
    from iterative_cleaner_tpu.parallel import clean_archives_batched

    with pytest.raises(ValueError, match="equal-shaped"):
        clean_archives_batched([_mk(0), _mk(1, nbin=64)], _roll_cfg())


def _mk_dedispersed(seed, **kw):
    """A DEDISP=1 archive: rotated into the aligned frame through the
    state-aware fake's own ``dedisperse`` (tests/fake_psrchive.py)."""
    from tests import fake_psrchive

    ar = _mk(seed, dm=300.0, **kw)  # ~15-bin shifts: a double rotation shows
    fa = fake_psrchive.FakeArchive(ar, rotation="roll")
    fa.dedisperse()
    assert fa._ar.dedispersed
    return fa._ar


def test_dedispersed_flag_reaches_parallel_paths():
    """batch / sharded / streaming must thread ``Archive.dedispersed`` —
    a path that dropped the flag would rotate a second time and silently
    produce the wrong mask while every other test stayed green."""
    import dataclasses

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.parallel import (
        cell_mesh,
        clean_archive_sharded,
        clean_archives_batched,
        clean_streaming,
    )

    cfg = _roll_cfg()
    # seeds chosen so the teeth assertion below holds for the current
    # synthetic generator stream (re-pick if the generator changes)
    archives = [_mk_dedispersed(s) for s in (43, 45)]
    singles = [clean_archive(a.clone(), cfg) for a in archives]

    # teeth: ignoring the flag must change the mask for this fixture
    wrong = clean_archive(
        dataclasses.replace(archives[0].clone(), dedispersed=False), cfg)
    assert (wrong.final_weights != singles[0].final_weights).any()

    batched = clean_archives_batched(archives, cfg)
    for single, b in zip(singles, batched):
        np.testing.assert_array_equal(single.final_weights, b.final_weights)

    sharded = clean_archive_sharded(archives[0].clone(), cfg, cell_mesh(8))
    np.testing.assert_array_equal(singles[0].final_weights,
                                  sharded.final_weights)

    # one full-size tile: tile semantics == whole-archive semantics, so any
    # difference is the flag being dropped on the streaming path
    streamed = clean_streaming(archives[0].clone(),
                               chunk_nsub=archives[0].nsub, config=cfg,
                               mode="online")
    np.testing.assert_array_equal(singles[0].final_weights,
                                  streamed.final_weights)


def test_batched_rejects_mixed_dedispersed_flags():
    from iterative_cleaner_tpu.parallel import clean_archives_batched

    with pytest.raises(ValueError, match="dedispersed"):
        clean_archives_batched([_mk(0), _mk_dedispersed(1)], _roll_cfg())


def test_sharded_library_path_matches_single():
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.parallel import cell_mesh, clean_archive_sharded

    cfg = _roll_cfg()
    ar = _mk(20)
    single = clean_archive(ar.clone(), cfg)
    sharded = clean_archive_sharded(ar.clone(), cfg, cell_mesh(8))
    np.testing.assert_array_equal(single.final_weights, sharded.final_weights)
    assert single.loops == sharded.loops


def test_streaming_single_tile_matches_direct():
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.parallel import clean_streaming

    cfg = _roll_cfg()
    ar = _mk(30)
    direct = clean_archive(ar.clone(), cfg)
    streamed = clean_streaming(ar.clone(), chunk_nsub=ar.nsub, config=cfg,
                               mode="online")
    np.testing.assert_array_equal(direct.final_weights, streamed.final_weights)


def test_streaming_tiles_and_partial_padding():
    from iterative_cleaner_tpu.parallel import StreamingCleaner

    cfg = _roll_cfg()
    ar = _mk(31)  # nsub=8
    sc = StreamingCleaner(6, cfg, ar.freqs_mhz, ar.dm, ar.centre_freq_mhz,
                          ar.period_s)
    cube = ar.total_intensity()
    tiles = list(sc.push(cube[:5], ar.weights[:5]))   # below one tile
    assert tiles == []
    tiles += list(sc.push(cube[5:], ar.weights[5:]))  # fills tile 1
    assert len(tiles) == 1 and tiles[0].n_valid == 6
    tiles += list(sc.finish())                        # padded final tile
    assert len(tiles) == 2
    assert tiles[1].n_valid == 2
    assert tiles[1].weights.shape == (2, ar.nchan)
    assert tiles[0].start_subint == 0 and tiles[1].start_subint == 6


def test_streaming_incremental_equals_bulk():
    from iterative_cleaner_tpu.parallel import StreamingCleaner

    cfg = _roll_cfg()
    ar = _mk(32)
    cube = ar.total_intensity()

    def run(pushes):
        sc = StreamingCleaner(4, cfg, ar.freqs_mhz, ar.dm,
                              ar.centre_freq_mhz, ar.period_s)
        tiles = []
        for lo, hi in pushes:
            tiles += list(sc.push(cube[lo:hi], ar.weights[lo:hi]))
        tiles += list(sc.finish())
        return np.concatenate([t.weights for t in tiles])

    one_shot = run([(0, 8)])
    dribbled = run([(0, 1), (1, 3), (3, 8)])
    np.testing.assert_array_equal(one_shot, dribbled)


def _streaming_drift_worst(cases):
    """Worst whole-vs-tiled mask drift fraction over ``cases`` of
    (seed, nsub, rfi_kwargs); the single comparison protocol both drift
    tests share (numpy backend, 256-subint tiles, diff_masks)."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming
    from iterative_cleaner_tpu.utils.checkpoint import diff_masks

    worst = 0.0
    for seed, nsub, rfi in cases:
        ar, _ = make_synthetic_archive(nsub=nsub, nchan=32, nbin=64,
                                       seed=seed, **rfi)
        cfg = CleanConfig(backend="numpy")
        whole = clean_archive(ar.clone(), cfg)
        tiled = clean_streaming(ar.clone(), chunk_nsub=256, config=cfg,
                                mode="online")
        d = diff_masks(whole.final_weights, tiled.final_weights)
        worst = max(worst, d["changed"] / d["cells"])
    return worst


def test_streaming_vs_whole_mask_drift_bounded():
    """Config-5 trust gap (VERDICT r1): per-tile scaler medians see only the
    tile's subints, so tiled masks can drift from whole-archive cleaning.
    Quantify it on a long observation: measured ~0.01-0.02% of cells across
    seeds; assert the documented <0.1% bound (parallel/streaming.py)."""
    # nsub=1000 on the second seed: the last 256-tile is zero-weight padded,
    # covering the padding-rows-in-the-plain-fft-scaler drift path too
    # (streaming.py module docstring)
    rfi = dict(n_rfi_cells=40, n_rfi_channels=2, n_rfi_subints=8,
               n_prezapped=50)
    worst = _streaming_drift_worst([(5, 1024, rfi), (7, 1000, rfi)])
    assert worst < 1e-3, f"streaming mask drift {worst:.2%} exceeds the bound"
    assert worst > 0  # the populations DO differ; zero would mean a no-op test


@pytest.mark.parametrize("backend,dtype,bmode", [
    ("numpy", None, "integration"),
    pytest.param("jax", "float64", "integration", marks=pytest.mark.slow),
    ("jax", "float32", "integration"), ("numpy", None, "profile"),
    pytest.param("jax", "float64", "profile", marks=pytest.mark.slow)])
def test_streaming_exact_masks_bit_equal_to_whole(backend, dtype, bmode):
    """The two-pass exact mode (VERDICT r2 #4): masks bit-equal to
    whole-archive cleaning on every backend and both baseline estimators
    — including geometries with a padded partial final tile."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming_exact

    kw = {} if dtype is None else {"dtype": dtype}
    for seed, nsub, chunk in ((5, 96, 32), (7, 90, 32), (11, 70, 64)):
        ar, _ = make_synthetic_archive(
            nsub=nsub, nchan=24, nbin=64, seed=seed, n_rfi_cells=12,
            n_rfi_channels=2, n_rfi_subints=3, n_prezapped=20)
        cfg = CleanConfig(backend=backend, baseline_mode=bmode, **kw)
        whole = clean_archive(ar.clone(), cfg)
        ex = clean_streaming_exact(ar.clone(), chunk, cfg)
        np.testing.assert_array_equal(whole.final_weights, ex.final_weights)
        assert whole.loops == ex.loops
        assert whole.converged == ex.converged
        # scores may move slightly (regrouped template reduction; the
        # effect is dtype-ulp-scaled) — the masks above are the contract
        tol = dict(rtol=2e-3, atol=1e-3) if dtype == "float32" \
            else dict(rtol=1e-6, atol=1e-9)
        np.testing.assert_allclose(whole.scores, ex.scores, **tol)


def test_streaming_exact_majority_prezapped_subint():
    """Zero-MAD regression (review find): a subint with most channels
    prezapped drives the plain rFFT scaler's MAD to zero, whose inf/nan
    IEEE flow (quirk 5) must survive tiling — an np.ma-promoted concat
    would turn those lines finite and flip borderline cells."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming_exact

    ar, _ = make_synthetic_archive(nsub=48, nchan=16, nbin=32, seed=23,
                                   n_rfi_cells=6)
    ar.weights[7, :14] = 0.0   # 14/16 channels of one subint dead
    ar.weights[30, :15] = 0.0  # nearly-dead subint in a later tile
    for backend in ("numpy", "jax"):
        cfg = CleanConfig(backend=backend,
                          **({"dtype": "float64"} if backend == "jax"
                             else {}))
        whole = clean_archive(ar.clone(), cfg)
        ex = clean_streaming_exact(ar.clone(), 16, cfg)
        np.testing.assert_array_equal(whole.final_weights, ex.final_weights)
        # the scores must agree where finite AND share inf/nan placement
        np.testing.assert_array_equal(np.isfinite(whole.scores),
                                      np.isfinite(ex.scores))


def test_streaming_exact_mode_via_clean_streaming():
    """mode='exact' routes through clean_streaming; bad-parts sweep runs on
    the reassembled observation like the whole-archive path."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming

    ar, _ = make_synthetic_archive(nsub=48, nchan=20, nbin=32, seed=17,
                                   n_rfi_cells=8, n_prezapped=12)
    ar.weights[5, :16] = 0.0  # mostly-dead subint for the sweep
    cfg = CleanConfig(backend="numpy", bad_subint=0.5)
    whole = clean_archive(ar.clone(), cfg)
    ex = clean_streaming(ar.clone(), 16, cfg, mode="exact")
    np.testing.assert_array_equal(whole.final_weights, ex.final_weights)


def test_streaming_exact_rejections():
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh
    from iterative_cleaner_tpu.parallel.streaming_exact import (
        clean_streaming_exact,
    )

    ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=1)
    with pytest.raises(ValueError, match="jax backend"):
        clean_streaming(ar, 4, CleanConfig(backend="numpy"),
                        mesh=cell_mesh(8), mode="exact")
    with pytest.raises(ValueError, match="divide"):
        clean_streaming_exact(ar, 3, _roll_cfg(), mesh=cell_mesh(8))
    # oversized chunk: the REAL tile is min(chunk, nsub) — a chunk bigger
    # than the archive must still be validated against the actual tile
    ar5, _ = make_synthetic_archive(nsub=5, nchan=16, nbin=32, seed=2)
    with pytest.raises(ValueError, match="divide"):
        clean_streaming_exact(ar5, 8, _roll_cfg(), mesh=cell_mesh(8))
    with pytest.raises(ValueError, match="unload_res"):
        clean_streaming_exact(ar, 4, CleanConfig(backend="numpy",
                                                 unload_res=True))
    with pytest.raises(ValueError, match="mode"):
        clean_streaming(ar, 4, CleanConfig(backend="numpy"), mode="bogus")


def test_streaming_exact_sharded_matches_single_device():
    """Exact streaming over the ('sub','chan') mesh: tile work sharded,
    masks identical to the unsharded exact run (and therefore to
    whole-archive cleaning) — the long-observation x drift-free x
    multi-chip composition."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming_exact
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh

    cfg = _roll_cfg()
    ar, _ = make_synthetic_archive(nsub=24, nchan=16, nbin=32, seed=37,
                                   n_rfi_cells=6, n_prezapped=10)
    whole = clean_archive(ar.clone(), cfg)
    single = clean_streaming_exact(ar.clone(), 8, cfg)
    sharded = clean_streaming_exact(ar.clone(), 8, cfg, mesh=cell_mesh(8))
    np.testing.assert_array_equal(single.final_weights,
                                  sharded.final_weights)
    np.testing.assert_array_equal(whole.final_weights,
                                  sharded.final_weights)
    assert single.loops == sharded.loops


def test_streaming_exact_record_history():
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.parallel import clean_streaming_exact

    ar, _ = make_synthetic_archive(nsub=24, nchan=16, nbin=32, seed=19,
                                   n_rfi_cells=6)
    cfg = CleanConfig(backend="numpy", record_history=True)
    whole = clean_archive(ar.clone(), cfg)
    ex = clean_streaming_exact(ar.clone(), 8, cfg)
    np.testing.assert_array_equal(whole.weight_history, ex.weight_history)


def test_streaming_mostly_padding_final_tile_drift_bounded():
    """Worst-case one-pass padding geometry (ADVICE r2): a final tile that
    is almost all zero-weight padding (10 valid subints in a 256-tile).
    The padding rows enter the plain rFFT scaler populations, so this is
    where the online mode's drift should peak — assert it still honours the
    documented <0.1% bound."""
    rfi = dict(n_rfi_cells=24, n_rfi_channels=2, n_rfi_subints=4,
               n_prezapped=30)
    worst = _streaming_drift_worst([(11, 522, rfi), (13, 522, rfi)])
    assert worst < 1e-3, (
        f"mostly-padding tile drift {worst:.2%} exceeds the bound")


def test_streaming_sharded_matches_single_device():
    """Sharded streaming: every tile cleaned over the ('sub','chan') mesh
    must reproduce the single-device streaming masks exactly (the
    long-observation x multi-chip composition)."""
    from iterative_cleaner_tpu.parallel import clean_streaming
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh

    cfg = _roll_cfg()
    ar = _mk(33)
    single = clean_streaming(ar.clone(), chunk_nsub=4, config=cfg,
                             mode="online")
    sharded = clean_streaming(ar.clone(), chunk_nsub=4, config=cfg,
                              mesh=cell_mesh(8), mode="online")
    np.testing.assert_array_equal(single.final_weights,
                                  sharded.final_weights)
    assert single.loops == sharded.loops

    # with a padded final tile AND the bad-parts sweep enabled: the sweep
    # runs once over the reassembled observation (never per tile, where
    # padding rows would dominate the fractions) — both modes agree
    cfg_sweep = _roll_cfg(bad_chan=0.5, bad_subint=0.5)
    ar2 = _mk(34, nsub=7)  # 7 subints over chunk 4 -> padded final tile
    single2 = clean_streaming(ar2.clone(), chunk_nsub=4, config=cfg_sweep,
                              mode="online")
    sharded2 = clean_streaming(ar2.clone(), chunk_nsub=4, config=cfg_sweep,
                               mesh=cell_mesh(8), mode="online")
    np.testing.assert_array_equal(single2.final_weights,
                                  sharded2.final_weights)
    # a mostly-alive archive must not be wiped by padding-skewed sweeps
    assert (single2.final_weights != 0).any()


def test_streaming_exact_non_f32_weights_loop_count(monkeypatch):
    """ADVICE r3: weights like 0.1 are not exactly float32-representable.
    The exact jax path's convergence history must be seeded with the
    dtype-ROUND-TRIPPED weights (the values the device actually computes
    with); seeding raw float64 weights would make the first-loop cycle
    match impossible and report loops one higher than the whole-archive
    f32 engine whenever nothing gets zapped."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming_exact

    ar, _ = make_synthetic_archive(nsub=48, nchan=16, nbin=32, seed=31,
                                   n_rfi_cells=0, n_rfi_channels=0,
                                   n_rfi_subints=0)
    ar.weights[ar.weights > 0] = 0.1  # f64(0.1) != f64(f32(0.1))
    # thresholds high enough that pure noise never zaps: the mask is
    # unchanged after loop 1, so cycle detection must fire immediately
    cfg = CleanConfig(backend="jax", dtype="float32",
                      chanthresh=50.0, subintthresh=50.0)
    whole = clean_archive(ar.clone(), cfg)
    ex = clean_streaming_exact(ar.clone(), 16, cfg)
    assert whole.converged and ex.converged
    assert whole.loops == 1
    assert ex.loops == whole.loops
    np.testing.assert_array_equal(whole.final_weights, ex.final_weights)

"""Cleaning-quality observables (telemetry/quality).

Two contracts dominate: the drift detector must raise
``quality_drift_alerts`` within the configured window of a mid-stream
occupancy step, and the whole observability layer must be a pure
observer — masks bit-equal with the quality/profiling hooks on and off.
"""

import numpy as np
import pytest

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.engine.loop import iter_quality_series
from iterative_cleaner_tpu.io import make_synthetic_archive
from iterative_cleaner_tpu.online import OnlineSession, StreamMeta
from iterative_cleaner_tpu.telemetry import MetricsRegistry
from iterative_cleaner_tpu.telemetry.quality import (
    DEFAULT_QUALITY_DRIFT,
    DEFAULT_QUALITY_WINDOW,
    QualityMonitor,
    observe_mask,
    observe_result,
    resolve_quality_drift,
    resolve_quality_window,
)


# ------------------------------------------------------------ resolution

def test_quality_knob_resolution_order(monkeypatch):
    monkeypatch.delenv("ICLEAN_QUALITY_WINDOW", raising=False)
    monkeypatch.delenv("ICLEAN_QUALITY_DRIFT", raising=False)
    assert resolve_quality_window(None) == DEFAULT_QUALITY_WINDOW
    assert resolve_quality_drift(None) == DEFAULT_QUALITY_DRIFT
    monkeypatch.setenv("ICLEAN_QUALITY_WINDOW", "7")
    monkeypatch.setenv("ICLEAN_QUALITY_DRIFT", "0.4")
    assert resolve_quality_window(None) == 7
    assert resolve_quality_drift(None) == 0.4
    # explicit config wins over the env mirror
    assert resolve_quality_window(3) == 3
    assert resolve_quality_drift(0.05) == 0.05


def test_monitor_and_config_validation():
    with pytest.raises(ValueError, match="window"):
        QualityMonitor(window=1)
    with pytest.raises(ValueError, match="drift"):
        QualityMonitor(drift=0.0)
    with pytest.raises(ValueError, match="quality_window"):
        CleanConfig(quality_window=1)
    with pytest.raises(ValueError, match="quality_drift"):
        CleanConfig(quality_drift=-0.1)


# ----------------------------------------------------------- drift alerts

def test_drift_alert_fires_within_window_of_occupancy_step():
    reg = MetricsRegistry()
    mon = QualityMonitor(stream="s1", window=4, drift=0.1, registry=reg)
    clean = np.ones(16)
    rfi = np.ones(16)
    rfi[:5] = 0.0                                   # occupancy 0.3125
    for i in range(6):
        assert not mon.observe_subint(clean)
    # the very first stepped subint alerts: |0.3125 - 0| > 0.1
    assert mon.observe_subint(rfi)
    assert mon.alerts == 1
    assert mon.last_alert_subint == 6
    counters = reg.snapshot()["counters"]
    assert counters["quality_drift_alerts{stream=s1}"] == 1.0
    gauges = reg.snapshot()["gauges"]
    assert gauges["quality_zap_frac{stream=s1}"] == pytest.approx(0.3125)
    s = mon.summary()
    assert s["alerts"] == 1 and s["baseline"] == 0.0
    assert s["last_alert_subint"] == 6


def test_no_alert_until_window_fills_or_within_tolerance():
    mon = QualityMonitor(window=4, drift=0.2)
    jumpy = np.ones(10)
    jumpy[:9] = 0.0
    # window not yet full: even a 90% subint is baseline-building, not
    # alert-raising (a stream that STARTS dirty is its own baseline)
    assert not mon.observe_subint(jumpy)
    mild = np.ones(10)
    mild[0] = 0.0
    for _ in range(5):
        assert not mon.observe_subint(mild)
    # within the band: 0.2 departure threshold absorbs 0.1 steps
    drift = np.ones(10)
    drift[:2] = 0.0
    assert not mon.observe_subint(drift)
    assert mon.alerts == 0


def test_ew_template_drift_series():
    reg = MetricsRegistry()
    mon = QualityMonitor(stream="s2", window=2, drift=0.5, registry=reg)
    row = np.ones(8)
    mon.observe_subint(row, template=np.array([1.0, 0.0]))
    assert mon.last_ew_drift == 0.0                 # first template: no step
    mon.observe_subint(row, template=np.array([1.0, 1.0]))
    assert mon.last_ew_drift == pytest.approx(1.0)  # |Δ|/|prev| = 1/1
    assert reg.snapshot()["gauges"][
        "quality_ew_drift{stream=s2}"] == pytest.approx(1.0)


# ------------------------------------------------------ occupancy folding

def test_observe_mask_summary_and_histograms():
    reg = MetricsRegistry()
    w = np.ones((4, 8))
    w[:, 3] = 0.0                                   # one dead channel
    w[2, :] = 0.0                                   # one dead subint
    s = observe_mask(w, reg, stream="s3")
    assert s["worst_channel"] == 3
    assert s["worst_channel_frac"] == 1.0
    assert s["worst_subint"] == 2
    assert s["worst_subint_frac"] == 1.0
    assert s["zap_frac"] == pytest.approx(11 / 32)
    h = reg.snapshot()["histograms"]
    assert h["quality_chan_occupancy{stream=s3}"]["count"] == 8
    assert h["quality_subint_occupancy{stream=s3}"]["count"] == 4
    assert reg.snapshot()["gauges"][
        "quality_zap_frac_final{stream=s3}"] == pytest.approx(11 / 32)


def test_iter_quality_series_shapes_and_scaling():
    im = np.array([[8.0, 8.0, 0.5, 2.0],
                   [10.0, 2.0, 0.4, 2.1]])
    s = iter_quality_series(im, n_cells=100)
    assert s["zap_frac"] == [0.08, 0.10]
    assert s["mask_churn"] == [8.0, 2.0]
    assert s["residual_std"] == [0.5, 0.4]
    assert s["template_peak"] == [2.0, 2.1]
    with pytest.raises(ValueError):
        iter_quality_series(np.zeros((2, 3)), n_cells=10)


def test_observe_result_folds_churn_histogram():
    reg = MetricsRegistry()

    class R:
        final_weights = np.ones((4, 8))
        iter_metrics = np.array([[3.0, 3.0, 0.1, 1.0],
                                 [4.0, 1.0, 0.1, 1.0]])

    summary = observe_result(R(), reg)
    assert summary["zap_frac"] == 0.0
    h = reg.snapshot()["histograms"]
    assert h["quality_iter_churn"]["count"] == 2


# -------------------------------------- live-session acceptance contract

def _stream_cube(nsub=10, nchan=8, nbin=16, seed=21):
    ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                   seed=seed)
    cube = np.asarray(ar.total_intensity(), dtype=np.float64)
    return cube, StreamMeta.from_archive(ar)


def _run_stream(cube, meta, weights_for, registry, **session_kw):
    cfg = CleanConfig(backend="jax", max_iter=2, quality_window=3,
                      quality_drift=0.2, stream_reconcile_every=0)
    s = OnlineSession(meta, cfg, registry=registry, **session_kw)
    for i in range(cube.shape[0]):
        s.ingest(cube[i], weights_for(i))
    return s, s.close()


@pytest.mark.slow  # two full 12-subint sessions (~5s): CI runs it in
# the multi-host step's -m slow pass
def test_online_occupancy_step_alerts_and_masks_stay_bit_equal():
    """The acceptance contract: a stream whose injected RFI occupancy
    steps mid-stream raises quality_drift_alerts within the configured
    window — and the masks are bit-equal with the observability-off
    route."""
    cube, meta = _stream_cube()
    step_at = 6

    def weights_for(i):
        w = np.ones((meta.nchan,))
        if i >= step_at:
            w[: meta.nchan // 2] = 0.0   # upstream flags half the band
        return w

    reg = MetricsRegistry()
    s_on, res_on = _run_stream(cube, meta, weights_for, reg,
                               stream_id="live", profile=True)
    # the first stepped subint departs the trailing median by 0.5 > 0.2,
    # so alerts land from the step onward — within the 3-subint window
    # (later stepped subints keep alerting until the window re-fills,
    # and last_alert_subint tracks the latest of them)
    assert s_on.quality.alerts >= 1
    assert step_at <= s_on.quality.last_alert_subint \
        < step_at + s_on.quality.window
    counters = reg.snapshot()["counters"]
    assert counters["quality_drift_alerts{stream=live}"] >= 1.0

    # observability off: no registry, no monitor, no profiling
    s_off, res_off = _run_stream(cube, meta, weights_for, None)
    assert s_off.quality is None
    np.testing.assert_array_equal(
        np.asarray(res_on.archive.weights),
        np.asarray(res_off.archive.weights))
    np.testing.assert_array_equal(s_on.provisional_weights,
                                  s_off.provisional_weights)


@pytest.mark.slow  # reconciling 8-subint session (~8s): CI runs it in
# the multi-host step's -m slow pass
def test_session_reconcile_and_close_feed_the_churn_series():
    cube, meta = _stream_cube(nsub=8, seed=5)
    cube = cube.copy()
    cube[1, 2] += 40.0                  # hot RFI the reconcile repairs
    reg = MetricsRegistry()
    cfg = CleanConfig(backend="jax", max_iter=2, quality_window=3,
                      quality_drift=0.2)
    s = OnlineSession(meta, cfg, reconcile_every=4, registry=reg,
                      stream_id="churn")
    for i in range(cube.shape[0]):
        s.ingest(cube[i])
    res = s.close()
    # monitor churn equals the session's own drift accounting
    assert s.quality.mask_churn == res.mask_drift + res.final_drift
    gauges = reg.snapshot()["gauges"]
    assert "quality_zap_frac_final{stream=churn}" in gauges
    h = reg.snapshot()["histograms"]
    assert h["quality_chan_occupancy{stream=churn}"]["count"] == meta.nchan

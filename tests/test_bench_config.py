"""Guards for bench.py's recorded-baseline plumbing.

bench.py single-sources its full-size vs_baseline denominator from
BASELINE.md's "Measured baselines" table; this pins the parse so an edit
to the table cannot silently break (or stale-out) the bench at driver
run time.
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_oracle_full_rate_parses_and_matches_record():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # The round-1 record: 273.3 s/iteration.  If the oracle is re-measured,
    # update BASELINE.md and this pin together.
    assert abs(1024 * 4096 / bench.oracle_full_rate() - 273.3) < 0.05

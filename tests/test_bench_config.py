"""Guards for bench.py's recorded-baseline plumbing.

bench.py single-sources its full-size vs_baseline denominator from
BASELINE.md's "Measured baselines" table; this pins the parse so an edit
to the table cannot silently break (or stale-out) the bench at driver
run time.
"""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_oracle_full_rate_parses_and_matches_record():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # The round-1 record: 273.3 s/iteration.  If the oracle is re-measured,
    # update BASELINE.md and this pin together.
    assert abs(1024 * 4096 / bench.oracle_full_rate() - 273.3) < 0.05


def _run_repo_script(rel_path, *argv, extra_env=()):
    """Launch a repo script in a subprocess with the CPU pin and repo
    PYTHONPATH — the shared contract of the driver-facing entry points."""
    import subprocess
    import sys

    # ICLEAN_PLATFORM pinned => the scripts skip their device probes
    from tests.conftest import repo_subprocess_env

    env = repo_subprocess_env(**dict(extra_env))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, rel_path), *argv],
        env=env, capture_output=True, text=True, timeout=600)


@pytest.mark.slow
def test_bench_small_end_to_end_json_schema():
    """The driver runs `python bench.py` unattended at round end; a crash
    or malformed JSON there loses the round's benchmark record.  Run the
    real script in a subprocess (CPU pin, small config) and validate the
    contract: one JSON line with the driver-read keys."""
    import json

    # BENCH_SKIP_MULTIHOST / BENCH_SKIP_ELASTIC / BENCH_SKIP_MESH /
    # BENCH_SKIP_BF16: those rows launch several CLI/daemon processes (or
    # compile the engine/sharded program twice) — more wall-clock than
    # this tier-1 test's budget allows.  test_bench_multihost_row_keys,
    # test_bench_elastic_row_keys, test_bench_mesh_row_keys and
    # test_bench_bf16_row_keys (slow) pin their keys instead; CI's bench
    # smoke runs the full BENCH_SMALL set including them.
    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_SMALL", "1"), ("BENCH_SKIP_MULTIHOST", "1"),
        ("BENCH_SKIP_ELASTIC", "1"), ("BENCH_SKIP_MESH", "1"),
        ("BENCH_SKIP_BF16", "1")))
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    # a stage subprocess that dies non-fatally only logs to stderr and
    # drops its row; carry the log tail into the missing-key message
    err = proc.stderr[-3000:]
    for key in ("metric", "value", "unit", "vs_baseline", "platform",
                "quality", "ms_per_iter", "loops"):
        assert key in out, (key, err)
    assert out["unit"] == "cell-iters/s"
    assert out["value"] > 0 and out["vs_baseline"] > 0
    assert out["quality"]["precision"] is not None
    # streaming row: measured-transfer contract (tile cache H2D counter)
    for key in ("streaming_geometry", "streaming_platform",
                "streaming_tile_passes_per_s", "streaming_eff_gbps",
                "streaming_h2d_bytes", "streaming_vs_whole",
                "streaming_sweep_cube_reads"):
        assert key in out, (key, err)
    # the interim modeled-throughput companion key is retired: every
    # shipped figure is measured
    assert not any(k.startswith("modeled_") for k in out), sorted(out)
    assert out["streaming_h2d_bytes"] > 0      # measured, never modeled
    assert out["streaming_vs_whole"] > 0
    # batch row (equal-shape archives through parallel/batch.py)
    for key in ("batch_n", "batch_geometry", "batch_platform",
                "batch_cell_iters_per_s", "batch_vs_sequential",
                "batch_per_archive_ms", "batch_h2d_bytes"):
        assert key in out, (key, err)
    assert out["batch_n"] >= 8
    assert out["batch_h2d_bytes"] > 0
    assert out["batch_cell_iters_per_s"] > 0
    # segmented-journal row: device-free, so it runs even in the small
    # smoke (BENCH_SMALL shrinks the synthetic pool; the exactly-once
    # fold contract is rc-7-fatal inside the stage)
    for key in ("journal_backend", "journal_members", "journal_requests",
                "journal_admit_fresh_ms", "journal_admit_aged_ms",
                "journal_admit_aged_vs_fresh", "journal_fold_aged_s",
                "journal_segments_total", "journal_compactions"):
        assert key in out, (key, err)
    assert out["journal_backend"] == "segmented"
    assert out["journal_admit_aged_vs_fresh"] > 0
    # fleet row (mixed-shape archives through parallel/fleet.py): the
    # compile-amortization contract is one program per bucket, and the
    # ratio must be a real measurement (parity divergence exits rc 7
    # before any JSON is printed, so reaching here means masks matched)
    for key in ("fleet_n", "fleet_geometries", "fleet_platform",
                "fleet_buckets", "fleet_compiles", "fleet_vs_sequential",
                "fleet_per_archive_ms", "fleet_h2d_bytes",
                "fleet_precompile_hits", "fleet_precompile_misses",
                "fleet_cold_vs_warm", "fleet_warm_compiles",
                "fleet_retries", "fleet_oom_splits"):
        assert key in out, (key, err)
    assert out["fleet_n"] >= 6
    assert out["fleet_buckets"] >= 2
    assert out["fleet_compiles"] == out["fleet_buckets"]
    assert out["fleet_vs_sequential"] > 0
    assert out["fleet_h2d_bytes"] > 0
    # warm-start contract: the in-process warm passes are served from the
    # background precompile pool, and a CLI restart over the shared
    # --compile-cache does zero real compiles and beats the cold process
    assert out["fleet_precompile_hits"] >= 1
    assert out["fleet_warm_compiles"] == 0
    assert 0 < out["fleet_cold_vs_warm"] < 1.0
    # resilience contract: the fault sub-run's injected transient and
    # synthetic OOM both fired and were recovered (rc 0 + bit-equal
    # masks were already asserted inside bench_fleet)
    assert out["fleet_retries"] >= 1
    assert out["fleet_oom_splits"] >= 1
    # serve row (service daemon): submit->done latency measured against a
    # live --serve subprocess, the saturation burst drew real 429
    # backpressure, and the SIGTERM drain was timed (mask parity vs the
    # in-process reference is rc-7-fatal inside the stage)
    for key in ("serve_n", "serve_platform", "serve_cold_ms",
                "serve_submit_to_done_ms", "serve_burst",
                "serve_burst_rejected", "serve_drain_s",
                "serve_span_queue_ms", "serve_span_execute_ms",
                "serve_span_compile_ms"):
        assert key in out, (key, err)
    assert out["serve_submit_to_done_ms"] > 0
    assert out["serve_burst_rejected"] >= 1
    assert out["serve_drain_s"] >= 0
    # trace-derived stage attribution (scraped from GET /trace/<id>):
    # the warm execute time is real work, and the stage split can never
    # exceed the end-to-end latency it decomposes
    assert out["serve_span_execute_ms"] > 0
    assert out["serve_span_queue_ms"] >= 0
    assert out["serve_span_compile_ms"] >= 0
    # online row (online/session.py): bounded per-subint latency, the
    # zero-steady-recompile contract, and close-reconciliation parity
    # with the batch clean (asserted rc-7-fatal inside the stage)
    for key in ("online_n", "online_subint_p50_ms", "online_subint_p99_ms",
                "online_warmup_compiles", "online_recompiles_steady",
                "online_reconciles", "online_mask_drift",
                "online_vs_batch_masks"):
        assert key in out, (key, err)
    assert out["online_n"] >= 8
    assert out["online_subint_p99_ms"] > 0
    assert out["online_recompiles_steady"] == 0
    assert out["online_warmup_compiles"] >= 1
    assert out["online_vs_batch_masks"] == "identical"
    # mux row (online/mux.py): the shared-dispatch multiplexer's burst
    # keys — the zero-steady-recompile contract and per-stream
    # provisional-mask parity are rc-7-fatal inside the stage, so
    # reaching here means both held
    for key in ("mux_n_streams", "mux_n_subints", "mux_max_batch",
                "mux_platform", "mux_aggregate_subints_per_s",
                "mux_vs_sequential", "mux_subint_p99_ms",
                "mux_batch_occupancy", "mux_warmup_compiles",
                "mux_recompiles_steady", "mux_vs_sequential_masks"):
        assert key in out, (key, err)
    assert out["mux_n_streams"] >= 8
    assert out["mux_aggregate_subints_per_s"] > 0
    assert out["mux_vs_sequential"] > 0
    assert out["mux_subint_p99_ms"] > 0
    assert 0 < out["mux_batch_occupancy"] <= 1.0
    assert out["mux_recompiles_steady"] == 0
    assert out["mux_warmup_compiles"] >= 1
    assert out["mux_vs_sequential_masks"] == "identical"
    # fused-sweep row: warm best-of-2 timing plus the deterministic
    # contracts (strict program shrink, strict streaming-H2D shrink, and
    # the single-read cube budget — each rc-7 fatal inside the stage, so
    # their mere presence means they held); the sweep_cube_reads keys on
    # the streaming/online rows report the per-iteration budget of the
    # route those rows actually resolved (1 fused, 2 multi-kernel)
    for key in ("fused_geometry", "fused_platform", "fused_vs_unfused",
                "fused_sweep_cube_reads", "fused_eqns",
                "fused_unfused_eqns", "fused_stream_h2d_bytes",
                "fused_unfused_stream_h2d_bytes"):
        assert key in out, (key, err)
    assert out["fused_vs_unfused"] > 0
    assert out["fused_sweep_cube_reads"] == 1
    assert out["fused_eqns"] < out["fused_unfused_eqns"]
    assert 0 < out["fused_stream_h2d_bytes"] \
        < out["fused_unfused_stream_h2d_bytes"]
    assert out["streaming_sweep_cube_reads"] in (1, 2)
    assert out["online_sweep_cube_reads"] in (1, 2)


@pytest.mark.slow
def test_bench_multihost_row_keys():
    """The multi-host fleet row (1 process vs 2 journal-coordinated
    processes + the dead-host steal drill) in isolation: the driver and
    CI read these keys from the headline JSON.  Mask parity and
    duplicate-clean checks are rc-7-fatal inside the stage; the
    beats-single assert is core-count-gated in the stage itself (two
    processes merely timeshare one core)."""
    import json

    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_MULTIHOST_ONLY", json.dumps(
            {"n_archives": 4, "geometries": [[16, 32, 32], [12, 32, 32]],
             "max_iter": 2})),))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    err = proc.stderr[-3000:]
    for key in ("fleet_hosts", "fleet_multihost_vs_single",
                "fleet_multihost_serve_s", "fleet_singlehost_serve_s",
                "fleet_multihost_cores", "fleet_stolen"):
        assert key in out, (key, err)
    assert out["fleet_hosts"] == 2
    assert out["fleet_stolen"] >= 1
    assert out["fleet_multihost_vs_single"] > 0
    if out["fleet_multihost_cores"] >= 2:
        assert out["fleet_multihost_vs_single"] < 1.0


@pytest.mark.slow
def test_bench_elastic_row_keys():
    """The elastic-pool row (two --join daemons, kill -9 on the front
    door, result-cache resubmission) in isolation: the driver and CI read
    these keys from the headline JSON.  Exactly-once, mask parity and
    the cache-hit contract are rc-7-fatal inside the stage."""
    import json

    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_ELASTIC_ONLY", json.dumps(
            {"geometries": [[6, 16, 32], [8, 16, 32], [10, 16, 32]]})),))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    err = proc.stderr[-3000:]
    for key in ("elastic_members", "elastic_platform", "serve_failover_s",
                "members_evicted", "requests_stolen", "elastic_takeover_s",
                "cache_hits", "cache_hit_vs_clean", "cache_clean_s",
                "cache_served_s", "elastic_journal_backend"):
        assert key in out, (key, err)
    assert out["elastic_journal_backend"] == "segmented"
    assert out["elastic_members"] == 2
    assert out["members_evicted"] >= 1
    assert out["requests_stolen"] >= 1
    assert out["serve_failover_s"] > 0
    assert out["cache_hits"] >= 1
    assert out["cache_hit_vs_clean"] > 0


def test_bench_journal_row_keys():
    """The segmented-journal scale row in isolation (small synthetic
    pool — the stage is device-free journal I/O, so it stays in the
    tier-1 run): the driver and CI read these keys from the headline
    JSON.  The exactly-once fold-under-concurrent-compaction contract
    is rc-7-fatal inside the stage."""
    import json

    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_JOURNAL_ONLY", json.dumps(
            {"n_members": 8, "n_requests": 2000, "probe": 100})),))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    err = proc.stderr[-3000:]
    for key in ("journal_backend", "journal_members", "journal_requests",
                "journal_admit_fresh_ms", "journal_admit_aged_ms",
                "journal_admit_aged_vs_fresh", "journal_admit_aged_p99_ms",
                "journal_fold_fresh_s", "journal_fold_aged_s",
                "journal_live_bytes", "journal_segments_total",
                "journal_compactions"):
        assert key in out, (key, err)
    assert out["journal_backend"] == "segmented"
    assert out["journal_requests"] == 2000
    assert out["journal_admit_fresh_ms"] > 0
    assert out["journal_admit_aged_ms"] > 0
    assert out["journal_live_bytes"] > 0
    assert out["journal_segments_total"] >= 1


@pytest.mark.slow
def test_bench_mesh_row_keys():
    """The sharded fused-sweep row (shard_mapped one-launch sweep over a
    forced 4-device CPU cell mesh vs the single-device engine) in
    isolation: the driver and CI read these keys from the headline JSON.
    Mask parity and the per-shard single-cube-read budget are rc-7-fatal
    inside the stage."""
    import json

    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_MESH_ONLY", json.dumps(
            {"nsub": 16, "nchan": 32, "nbin": 64, "max_iter": 2})),
        ("XLA_FLAGS", "--xla_force_host_platform_device_count=4")))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    err = proc.stderr[-3000:]
    for key in ("mesh_geometry", "mesh_platform", "mesh_devices",
                "mesh_vs_single", "mesh_sweep_cube_reads"):
        assert key in out, (key, err)
    assert out["mesh_devices"] == 4
    assert out["mesh_vs_single"] > 0
    assert out["mesh_sweep_cube_reads"] == 1


@pytest.mark.slow
def test_bench_bf16_row_keys():
    """The mixed-precision row (--compute-dtype bfloat16 vs the fp32
    default through the fused-sweep engine) in isolation: the driver and
    CI read these keys from the headline JSON.  Mask parity on the
    bf16-exact archive and the probe's bf16 eligibility are rc-7-fatal
    inside the stage; the cube-bytes ratio is a deterministic
    trace-level measure (half the bytes per read site)."""
    import json

    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_BF16_ONLY", json.dumps(
            {"nsub": 16, "nchan": 32, "nbin": 64, "max_iter": 2})),))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    err = proc.stderr[-3000:]
    for key in ("bf16_geometry", "bf16_platform", "bf16_vs_fp32",
                "bf16_cube_bytes_ratio", "bf16_cube_read_bytes",
                "bf16_fp32_cube_read_bytes"):
        assert key in out, (key, err)
    assert out["bf16_vs_fp32"] > 0
    assert 0 < out["bf16_cube_bytes_ratio"] <= 0.6
    assert out["bf16_cube_read_bytes"] > 0


@pytest.mark.slow
def test_bench_mux_row_keys():
    """The full mux row (100-stream burst through one StreamMux) in
    isolation: the >= 10x aggregate-throughput contract vs N independent
    sessions holds on the CPU row, with zero steady recompiles and
    full-rung occupancy.  Per-stream provisional-mask parity is
    rc-7-fatal inside the stage."""
    import json

    proc = _run_repo_script("bench.py", extra_env=(
        ("BENCH_MUX_ONLY", json.dumps(
            {"n_streams": 100, "n_subints": 8, "nchan": 8, "nbin": 32,
             "max_batch": 100})),))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    err = proc.stderr[-3000:]
    assert out["mux_n_streams"] == 100
    assert out["mux_n_subints"] == 800
    assert out["mux_recompiles_steady"] == 0, err
    assert out["mux_batch_occupancy"] == 1.0
    assert out["mux_vs_sequential"] >= 10.0, (out, err)
    assert out["mux_vs_sequential_masks"] == "identical"


@pytest.mark.slow
def test_profile_stages_small_end_to_end():
    """profile_stages.py is step 3 of the queued hardware pass; a crash
    there (e.g. a stage signature drifting from the engine) would waste a
    live-tunnel window.  Run it small on CPU and require every expected
    stage row to appear (timed, below-noise, or explicitly skipped)."""
    proc = _run_repo_script(
        os.path.join("benchmarks", "profile_stages.py"),
        "--nsub", "16", "--nchan", "32", "--nbin", "32",
        "--chain", "2", "--repeats", "1")
    assert proc.returncode == 0, proc.stderr[-2000:]
    for row in ("weighted_template", "fit_template_amplitudes",
                "cell diagnostics (xla)", "scale_and_combine (sort)",
                "baseline correction (integration)",
                "iteration_step (xla/sort)", "preamble: prepare_cube"):
        assert row in proc.stdout, (row, proc.stdout)


def test_tpu_validation_pass_script_parses():
    """The queued hardware script must at least be valid sh — a typo there
    would burn the first live-tunnel window."""
    import subprocess

    proc = subprocess.run(
        ["sh", "-n", os.path.join(REPO, "benchmarks",
                                  "tpu_validation_pass.sh")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr


def test_cube_passes_model_tracks_engine_routes():
    """The bytes-moved model must mirror the engine's actual route
    selection: 2 passes only when the Pallas marginal kernel is eligible,
    3 on its dual-dot fallback, 6 for the XLA twin, and the non-default
    configs unchanged."""
    spec = importlib.util.spec_from_file_location(
        "bench2", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    from iterative_cleaner_tpu.stats.pallas_kernels import (
        marginals_pallas_eligible,
    )

    full = (1024, 4096, 128)
    assert marginals_pallas_eligible(*full)
    assert bench._cube_passes("fused", "dispersed", shape=full) == 2.0
    big = (1024, 4096, 1024)           # beyond the marginal kernel's cap
    assert not marginals_pallas_eligible(*big)
    assert bench._cube_passes("fused", "dispersed", shape=big) == 3.0
    assert bench._cube_passes("fused", "dispersed", shape=None) == 3.0
    assert bench._cube_passes("xla", "dispersed", shape=full) == 6.0
    assert bench._cube_passes("fused", "dedispersed") == 3.0
    assert bench._cube_passes("fused", "dispersed", "profile") == 3.0
    assert bench._cube_passes("xla", "dispersed", "profile") == 6.0


def test_sweep_cube_reads_tracks_route_selection():
    """The bench rows' per-iteration sweep read budget must mirror the
    engine's actual route: 1 where the fused sweep engages (proven by
    tracing the kernel through the --selfcheck contract counter), 2 on
    the multi-kernel route (residual write + diagnostics read), and the
    nsub=1 online step must still prove 1 despite the counter's
    cell-table shape heuristic."""
    spec = importlib.util.spec_from_file_location(
        "bench3", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    from iterative_cleaner_tpu.config import CleanConfig

    fused = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                        fft_mode="dft", fused_sweep="on")
    assert bench._sweep_cube_reads(fused, 16, 32, 64) == 1
    assert bench._sweep_cube_reads(fused, 1, 32, 64) == 1   # online step
    off = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                      fft_mode="dft", fused_sweep="off")
    assert bench._sweep_cube_reads(off, 16, 32, 64) == 2
    # geometry past the VMEM gate falls back to the multi-kernel route
    assert bench._sweep_cube_reads(fused, 20000, 4096, 64) == 2

"""Multi-host layer on the 8-virtual-device CPU mesh: bootstrap context,
hybrid DCN x ICI mesh construction, and hybrid batch+cell-grid cleaning
parity against the single-device engine."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from iterative_cleaner_tpu.backends import clean_archive  # noqa: E402
from iterative_cleaner_tpu.config import CleanConfig  # noqa: E402
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive  # noqa: E402
from iterative_cleaner_tpu.parallel import distributed  # noqa: E402


def test_initialize_single_process_noop():
    ctx = distributed.initialize()
    assert ctx.process_index == 0
    assert ctx.process_count == 1
    assert ctx.is_coordinator
    assert ctx.global_devices == len(jax.devices())


def test_initialize_rejects_cluster_args_without_coordinator():
    """num_processes/process_id without a coordinator must error, not
    silently degrade to a 1-process run (duplicate-work hazard)."""
    with pytest.raises(ValueError, match="coordinator"):
        distributed.initialize(num_processes=4, process_id=2)


def test_batched_specs_length_checked():
    from jax.sharding import PartitionSpec as P

    from iterative_cleaner_tpu.parallel.batch import clean_archives_batched
    from iterative_cleaner_tpu.parallel.mesh import batch_mesh

    archives = [make_synthetic_archive(nsub=4, nchan=8, nbin=16, seed=0)[0]]
    with pytest.raises(ValueError, match="specs"):
        clean_archives_batched(
            archives, CleanConfig(backend="jax", max_iter=1),
            batch_mesh(2), specs=(P("batch"),),
        )


@pytest.mark.parametrize("batch,shape", [(2, (2, 2)), (4, (1, 2)), (1, (2, 4))])
def test_hybrid_mesh_shapes(batch, shape):
    mesh = distributed.hybrid_batch_cell_mesh(batch=batch)
    assert mesh.axis_names == ("batch", "sub", "chan")
    assert mesh.shape["batch"] == batch
    assert (mesh.shape["sub"], mesh.shape["chan"]) == shape


def test_hybrid_mesh_rejects_nondivisible():
    with pytest.raises(ValueError):
        distributed.hybrid_batch_cell_mesh(batch=3)


def test_hybrid_clean_matches_single_device():
    """3 archives over a ('batch'=2, 'sub'=2, 'chan'=2) mesh (one padded
    archive) must reproduce the single-device masks exactly."""
    archives = [
        make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=s)[0]
        for s in (0, 1, 2)
    ]
    # roll+dft: XLA:CPU's fft thunk rejects sharded layouts (same caveat as
    # the 2-D sharded engine); on TPU all modes work.
    cfg = CleanConfig(backend="jax", max_iter=3, rotation="roll",
                      fft_mode="dft")
    mesh = distributed.hybrid_batch_cell_mesh(batch=2)
    results = distributed.clean_archives_hybrid(archives, cfg, mesh)
    assert len(results) == len(archives)
    for ar, res in zip(archives, results):
        single = clean_archive(ar, cfg)
        np.testing.assert_array_equal(res.final_weights,
                                      single.final_weights)
        assert res.loops == single.loops

"""Pallas radix-bisection masked median vs the sort path and np.ma.median.

The kernel (stats/pallas_kernels.py) must agree with the sort-based
masked_median bit-for-bit — that equality is what lets median_impl='pallas'
keep final-mask parity with the numpy oracle.  Runs in interpreter mode on
the CPU test devices; the same kernel compiles via Mosaic on TPU.
"""

import numpy as np
import numpy.ma as ma
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from iterative_cleaner_tpu.stats.masked_jax import masked_median  # noqa: E402
from iterative_cleaner_tpu.stats.pallas_kernels import (  # noqa: E402
    masked_median_pallas,
)


def _both(v, m, axis):
    a = np.asarray(masked_median_pallas(jnp.asarray(v), jnp.asarray(m), axis))
    b = np.asarray(masked_median(jnp.asarray(v), jnp.asarray(m), axis))
    return a, b


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("shape,maskfrac", [
    ((17, 33), 0.3),     # odd/even mixed counts, unaligned lanes
    ((64, 128), 0.0),    # no masking, lane-aligned
    ((9, 5), 0.9),       # mostly masked, tiny tile
    ((8, 130), 0.5),     # non-multiple of the 128 lane tile
])
def test_pallas_matches_sort_bitwise(axis, shape, maskfrac):
    rng = np.random.default_rng(0)
    v = rng.standard_normal(shape).astype(np.float32)
    m = rng.random(shape) < maskfrac
    m[:, 0] = True          # a fully-masked line
    v[:, 1] = 1.5           # exact ties
    a, b = _both(v, m, axis)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("axis", [0, 1])
def test_pallas_adversarial_values(axis):
    """Signed zeros, +-inf, the np.ma 1e20 fill, single-survivor lines."""
    rng = np.random.default_rng(1)
    v = rng.standard_normal((24, 40)).astype(np.float32)
    m = rng.random(v.shape) < 0.2
    v[::7] = np.float32(1e20)
    v[3, :] = -np.inf
    v[:, 3] = np.inf
    v[5, 5] = -0.0
    a, b = _both(v, m, axis)
    np.testing.assert_array_equal(a, b)

    m_one = np.ones_like(m)
    m_one[0, :] = False      # exactly one valid entry per column
    a, b = _both(v, m_one, axis)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("axis", [0, 1])
def test_pallas_nan_parity(axis):
    """Valid NaNs mixed with masked cells: both implementations share the
    total order reals < inf == masked-sentinel < NaN, so results stay
    bit-identical (including inf/NaN medians)."""
    rng = np.random.default_rng(3)
    v = rng.standard_normal((12, 20)).astype(np.float32)
    m = rng.random(v.shape) < 0.3
    v[1, :] = np.nan         # a valid NaN in most lines
    v[:, 1] = np.nan
    m[1, ::2] = True         # and NaNs under the mask
    a, b = _both(v, m, axis)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 8])
def test_pallas_matches_numpy_ma(n):
    """Direct np.ma.median check over odd/even valid counts."""
    rng = np.random.default_rng(2)
    v = rng.standard_normal((8, 16)).astype(np.float32)
    m = np.zeros(v.shape, bool)
    m[n:, :] = True          # n valid entries per column
    got = np.asarray(masked_median_pallas(jnp.asarray(v), jnp.asarray(m), 0))
    want = ma.median(ma.MaskedArray(v, m), axis=0).filled(0.0)
    np.testing.assert_allclose(got[0], want.astype(np.float32), rtol=0,
                               atol=0)


def test_pallas_rejects_float64():
    v = jnp.zeros((4, 4), jnp.float64)
    m = jnp.zeros((4, 4), bool)
    with pytest.raises(TypeError):
        masked_median_pallas(v, m, 0)


def test_full_clean_parity_sort_vs_pallas():
    """End-to-end: the whole cleaning program produces identical weights and
    loop counts with either median implementation."""
    from iterative_cleaner_tpu.backends.jax_backend import clean_cube
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

    ar, _ = make_synthetic_archive(nsub=12, nchan=24, nbin=64, seed=5,
                                   dtype=np.float32)
    args = (ar.total_intensity(), ar.weights, ar.freqs_mhz, ar.dm,
            ar.centre_freq_mhz, ar.period_s)
    res = {}
    for impl in ("sort", "pallas"):
        cfg = CleanConfig(backend="jax", median_impl=impl, dtype="float32")
        res[impl] = clean_cube(*args, cfg)
    np.testing.assert_array_equal(res["sort"].final_weights,
                                  res["pallas"].final_weights)
    np.testing.assert_array_equal(res["sort"].scores, res["pallas"].scores)
    assert res["sort"].loops == res["pallas"].loops


def test_scaled_sides_multi_tile_and_tier():
    """The fused scaler's grid path beyond one lane tile, and the shrunken
    lane tier for long reduction axes: (1030, 260) forces tile index >= 1
    AND the T=64 tier's chunked reshape — bit-parity with the sort route
    must hold through both."""
    import jax

    from iterative_cleaner_tpu.stats.masked_jax import scale_and_combine

    rng = np.random.default_rng(11)
    nsub, nchan = 1030, 260
    diags = tuple(rng.normal(size=(nsub, nchan)).astype(np.float32)
                  for _ in range(4))
    mask = rng.random((nsub, nchan)) < 0.15
    mask[:, 7] = True            # dead channel
    a = np.asarray(jax.jit(lambda d, m: scale_and_combine(
        d, m, 5.0, 5.0, "sort"))(diags, mask))
    b = np.asarray(jax.jit(lambda d, m: scale_and_combine(
        d, m, 5.0, 5.0, "pallas"))(diags, mask))
    np.testing.assert_array_equal(a, b)


def test_scale_and_combine_batched_pallas_adversarial():
    """The pallas route fuses each orientation's four scalers into one
    launch (masked_jax._scaled_sides_fused_pallas -> pallas_kernels.
    scaled_sides_pallas); its in-kernel epilogue must stay bit-identical
    to the sort route on the nasty lines: fully-masked channels/subints,
    zero-MAD (constant) lines, and NaN-bearing rFFT lines (where the
    plain path must propagate NaN, quirks 5-8)."""
    from iterative_cleaner_tpu.stats.masked_jax import scale_and_combine

    rng = np.random.default_rng(7)
    nsub, nchan = 24, 48
    diags = [rng.normal(size=(nsub, nchan)).astype(np.float32)
             for _ in range(4)]
    diags[0][:, 5] = 3.25          # zero-MAD channel in the std diagnostic
    diags[2][7, :] = -1.5          # zero-MAD subint in the ptp diagnostic
    diags[3][3, 9] = np.nan        # NaN reaches the plain rFFT path
    mask = rng.random((nsub, nchan)) < 0.2
    mask[:, 11] = True             # fully-masked channel
    mask[4, :] = True              # fully-masked subint
    args = (tuple(jnp.asarray(d) for d in diags), jnp.asarray(mask),
            5.0, 3.0)
    want = np.asarray(jax.jit(
        lambda d, m: scale_and_combine(d, m, 5.0, 3.0, "sort"))(*args[:2]))
    got = np.asarray(jax.jit(
        lambda d, m: scale_and_combine(d, m, 5.0, 3.0, "pallas"))(*args[:2]))
    np.testing.assert_array_equal(want, got)


class TestFusedCellDiagnostics:
    """The fused Pallas diagnostics kernel vs the XLA path: same masked-cell
    patches, near-identical floats (MXU DFT vs jnp reductions), and —
    through the engine — identical final masks."""

    def _setup(self, nsub=12, nchan=20, nbin=32, seed=5):
        from iterative_cleaner_tpu.engine.loop import (
            dispersed_residual_base, prepare_cube_jax)
        from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

        ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                       n_prezapped=7, seed=seed,
                                       dtype=np.float64)
        cube = jnp.asarray(ar.total_intensity(), dtype=jnp.float32)
        weights = jnp.asarray(ar.weights, dtype=jnp.float32)
        freqs = jnp.asarray(ar.freqs_mhz, dtype=jnp.float32)
        ded, shifts = prepare_cube_jax(
            cube, freqs, ar.dm, ar.centre_freq_mhz, ar.period_s,
            baseline_duty=0.15, rotation="fourier")
        base = dispersed_residual_base(
            ded, shifts, pulse_slice=(0, 0), pulse_scale=1.0,
            pulse_active=False, rotation="fourier")
        return ded, base, weights, shifts

    def test_fused_matches_xla_diagnostics(self):
        from iterative_cleaner_tpu.ops.dsp import (
            fit_template_amplitudes, rotate_bins, weighted_template)
        from iterative_cleaner_tpu.stats.masked_jax import cell_diagnostics_jax
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            cell_diagnostics_pallas)

        ded, base, weights, shifts = self._setup()
        nchan, nbin = ded.shape[1], ded.shape[2]
        cell_mask = weights == 0
        template = weighted_template(ded, weights, jnp) * 10000.0
        rot_t = rotate_bins(jnp.broadcast_to(template, (nchan, nbin)), shifts,
                            jnp, method="fourier")
        amps = fit_template_amplitudes(ded, template, jnp)
        weighted = (amps[:, :, None] * rot_t[None] - base) * weights[:, :, None]
        want = cell_diagnostics_jax(weighted, cell_mask, fft_mode="dft")
        got = cell_diagnostics_pallas(ded, base, rot_t, template, weights,
                                      cell_mask)
        for g, w, name in zip(got, want, ("std", "mean", "ptp", "fft")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-5, atol=2e-4, err_msg=name)
        # masked-cell patches exact
        m = np.asarray(cell_mask)
        assert (np.asarray(got[0])[m] == 0).all()
        assert (np.asarray(got[1])[m] == 0).all()
        assert (np.asarray(got[2])[m] == np.float32(1e20)).all()

    def test_fused_engine_masks_match_xla_engine(self):
        from iterative_cleaner_tpu.engine.loop import clean_dedispersed_jax

        ded, base, weights, shifts = self._setup(nsub=16, nchan=24, nbin=64)
        kw = dict(max_iter=4, chanthresh=5.0, subintthresh=5.0,
                  pulse_slice=(0, 0), pulse_scale=1.0, pulse_active=False,
                  rotation="fourier", fft_mode="dft", median_impl="sort")
        a = clean_dedispersed_jax(ded, weights, shifts, stats_impl="xla", **kw)
        b = clean_dedispersed_jax(ded, weights, shifts, stats_impl="fused",
                                  **kw)
        np.testing.assert_array_equal(np.asarray(a.final_weights),
                                      np.asarray(b.final_weights))
        assert int(a.loops) == int(b.loops)

    @pytest.mark.parametrize("nbin", [
        pytest.param(512, marks=pytest.mark.slow), 1024, 2048,
        pytest.param(4096, marks=pytest.mark.slow)])
    def test_fused_long_profiles_match_xla(self, nbin):
        """VERDICT r1 weak item 2: BASELINE config 1 (512 bins) and common
        1024-bin archives must run fused instead of silently falling back.
        The scaffold shrinks the channel block (_cell_blocks) to keep VMEM
        flat, and past 1024 bins sweeps the DFT spectrum over a third grid
        dimension (_k_chunk); diagnostics must still match the XLA path."""
        from iterative_cleaner_tpu.ops.dsp import (
            fit_template_amplitudes, rotate_bins, weighted_template)
        from iterative_cleaner_tpu.stats.masked_jax import cell_diagnostics_jax
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            FUSED_STATS_MAX_NBIN, cell_diagnostics_pallas)

        assert nbin <= FUSED_STATS_MAX_NBIN
        ded, base, weights, shifts = self._setup(nsub=10, nchan=36, nbin=nbin,
                                                 seed=8)
        nchan = ded.shape[1]
        cell_mask = weights == 0
        template = weighted_template(ded, weights, jnp) * 10000.0
        rot_t = rotate_bins(jnp.broadcast_to(template, (nchan, nbin)), shifts,
                            jnp, method="fourier")
        amps = fit_template_amplitudes(ded, template, jnp)
        weighted = (amps[:, :, None] * rot_t[None] - base) * weights[:, :, None]
        want = cell_diagnostics_jax(weighted, cell_mask, fft_mode="dft")
        got = cell_diagnostics_pallas(ded, base, rot_t, template, weights,
                                      cell_mask)
        for g, w, name in zip(got, want, ("std", "mean", "ptp", "fft")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-5, atol=2e-4, err_msg=name)

    @pytest.mark.slow
    def test_fused_engine_masks_match_xla_512bins(self):
        from iterative_cleaner_tpu.engine.loop import clean_dedispersed_jax

        ded, base, weights, shifts = self._setup(nsub=16, nchan=32, nbin=512,
                                                 seed=9)
        kw = dict(max_iter=3, chanthresh=5.0, subintthresh=5.0,
                  pulse_slice=(0, 0), pulse_scale=1.0, pulse_active=False,
                  rotation="fourier", fft_mode="dft", median_impl="sort")
        a = clean_dedispersed_jax(ded, weights, shifts, stats_impl="xla", **kw)
        b = clean_dedispersed_jax(ded, weights, shifts, stats_impl="fused",
                                  **kw)
        np.testing.assert_array_equal(np.asarray(a.final_weights),
                                      np.asarray(b.final_weights))
        assert int(a.loops) == int(b.loops)

    def test_fused_rejects_float64(self):
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            cell_diagnostics_pallas)

        x = jnp.zeros((4, 4, 8), dtype=jnp.float64)
        w = jnp.ones((4, 4), dtype=jnp.float64)
        with pytest.raises(TypeError):
            cell_diagnostics_pallas(x, x, jnp.zeros((4, 8)), jnp.zeros(8), w,
                                    w == 0)


@pytest.mark.parametrize("axis", [0, 1])
def test_plain_median_pallas_matches_jnp_median(axis):
    """scale_lines_plain's pallas routing: bit-identical to jnp.median,
    including NaN propagation and +-inf ordering."""
    from iterative_cleaner_tpu.stats.masked_jax import _plain_median

    rng = np.random.default_rng(3)
    v = rng.standard_normal((33, 18)).astype(np.float32)
    v[0, 0] = np.nan
    v[1, 1] = np.inf
    v[2, 2] = -np.inf
    v[3, :] = 2.5  # exact ties
    a = np.asarray(_plain_median(jnp.asarray(v), axis, "pallas"))
    b = np.asarray(_plain_median(jnp.asarray(v), axis, "sort"))
    np.testing.assert_array_equal(a, b)


class TestFusedAdversarial:
    """Fused kernel vs XLA diagnostics on hostile inputs."""

    def _diag_pair(self, ded, base, weights, shifts):
        from iterative_cleaner_tpu.ops.dsp import (
            fit_template_amplitudes, rotate_bins, weighted_template)
        from iterative_cleaner_tpu.stats.masked_jax import cell_diagnostics_jax
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            cell_diagnostics_pallas)

        nchan, nbin = ded.shape[1], ded.shape[2]
        cell_mask = weights == 0
        template = weighted_template(ded, weights, jnp) * 10000.0
        rot_t = rotate_bins(jnp.broadcast_to(template, (nchan, nbin)), shifts,
                            jnp, method="roll")
        amps = fit_template_amplitudes(ded, template, jnp)
        weighted = (amps[:, :, None] * rot_t[None] - base) * weights[:, :, None]
        want = cell_diagnostics_jax(weighted, cell_mask, fft_mode="dft")
        got = cell_diagnostics_pallas(ded, base, rot_t, template, weights,
                                      cell_mask)
        return got, want

    def test_constant_rows_and_zero_template(self):
        # all-constant data -> zero-variance cells; zero template -> amp=1
        ded = jnp.full((8, 8, 16), 3.0, dtype=jnp.float32)
        base = ded
        w = jnp.ones((8, 8), dtype=jnp.float32)
        shifts = jnp.zeros(8, dtype=jnp.float32)
        got, want = self._diag_pair(ded * 0.0, base * 0.0, w, shifts)
        for g, x in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(x),
                                       rtol=1e-6, atol=1e-6)

    def test_nan_and_inf_cells_propagate_like_xla(self):
        rng = np.random.default_rng(9)
        d = rng.normal(size=(8, 8, 16)).astype(np.float32)
        d[0, 0, 3] = np.nan
        d[1, 2, :] = np.inf
        d[2, 3, 5] = -np.inf
        ded = jnp.asarray(d)
        base = jnp.asarray(rng.normal(size=(8, 8, 16)).astype(np.float32))
        w = jnp.ones((8, 8), dtype=jnp.float32)
        w = w.at[4, 4].set(0.0)  # masked cell
        shifts = jnp.asarray(rng.integers(-5, 5, size=8).astype(np.float32))
        got, want = self._diag_pair(ded, base, w, shifts)
        for g, x, name in zip(got, want, ("std", "mean", "ptp", "fft")):
            g, x = np.asarray(g), np.asarray(x)
            np.testing.assert_array_equal(np.isnan(g), np.isnan(x),
                                          err_msg=name)
            np.testing.assert_array_equal(np.isinf(g), np.isinf(x),
                                          err_msg=name)
            fin = np.isfinite(x)
            np.testing.assert_allclose(g[fin], x[fin], rtol=1e-4, atol=1e-4,
                                       err_msg=name)

    def test_pulse_window_active_engine_parity(self):
        from iterative_cleaner_tpu.engine.loop import clean_dedispersed_jax
        from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
        from iterative_cleaner_tpu.engine.loop import prepare_cube_jax

        ar, _ = make_synthetic_archive(nsub=10, nchan=12, nbin=64, seed=11,
                                       dtype=np.float64)
        cube = jnp.asarray(ar.total_intensity(), dtype=jnp.float32)
        weights = jnp.asarray(ar.weights, dtype=jnp.float32)
        freqs = jnp.asarray(ar.freqs_mhz, dtype=jnp.float32)
        ded, shifts = prepare_cube_jax(
            cube, freqs, ar.dm, ar.centre_freq_mhz, ar.period_s,
            baseline_duty=0.15, rotation="fourier")
        kw = dict(max_iter=3, chanthresh=5.0, subintthresh=5.0,
                  pulse_slice=(10, 30), pulse_scale=0.25, pulse_active=True,
                  rotation="fourier", fft_mode="dft", median_impl="sort")
        a = clean_dedispersed_jax(ded, weights, shifts, stats_impl="xla", **kw)
        b = clean_dedispersed_jax(ded, weights, shifts, stats_impl="fused",
                                  **kw)
        np.testing.assert_array_equal(np.asarray(a.final_weights),
                                      np.asarray(b.final_weights))


class TestSublaneTier:
    """The ICLEAN_FUSED_TIER=sublane block strategy (VERDICT r3 #4): the
    channel block stays one full 128-lane tile and the subint block sheds
    the VMEM instead.  Interpret mode proves parity at every tier; only
    hardware can prove the lowering + measure the 512-bin falloff the
    strategy exists to attack (tpu_validation_pass.sh step 5b)."""

    def _diag_parity(self, nbin):
        from iterative_cleaner_tpu.ops.dsp import (
            fit_template_amplitudes, rotate_bins, weighted_template)
        from iterative_cleaner_tpu.stats.masked_jax import cell_diagnostics_jax
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            cell_diagnostics_pallas)

        setup = TestFusedCellDiagnostics()._setup(nsub=10, nchan=36,
                                                  nbin=nbin, seed=8)
        ded, base, weights, shifts = setup
        nchan = ded.shape[1]
        cell_mask = weights == 0
        template = weighted_template(ded, weights, jnp) * 10000.0
        rot_t = rotate_bins(jnp.broadcast_to(template, (nchan, nbin)),
                            shifts, jnp, method="fourier")
        amps = fit_template_amplitudes(ded, template, jnp)
        weighted = (amps[:, :, None] * rot_t[None] - base) \
            * weights[:, :, None]
        want = cell_diagnostics_jax(weighted, cell_mask, fft_mode="dft")
        got = cell_diagnostics_pallas(ded, base, rot_t, template, weights,
                                      cell_mask)
        for g, w, name in zip(got, want, ("std", "mean", "ptp", "fft")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-5, atol=2e-4, err_msg=name)

    def test_tier_blocks(self, monkeypatch):
        """The strategy's published block table: full lane tile, VMEM shed
        on the subint axis, cells-per-step never above the cell tier's
        (the budget the hardware has validated) except where documented."""
        from iterative_cleaner_tpu.stats import pallas_kernels as pk

        monkeypatch.setattr(pk, "_TIER", "sublane")
        assert pk._cell_blocks(128) == (8, 128)
        assert pk._cell_blocks(512) == (4, 128)
        assert pk._cell_blocks(1024) == (2, 128)
        assert pk._cell_blocks(2048) == (1, 128)
        assert pk._cell_blocks(4096) == (1, 64)
        monkeypatch.setattr(pk, "_S_BLK", "2")
        assert pk._cell_blocks(512) == (2, 128)

    @pytest.mark.parametrize("nbin", [
        64, pytest.param(512, marks=pytest.mark.slow), 2048])
    def test_sublane_diagnostics_match_xla(self, nbin, monkeypatch):
        from iterative_cleaner_tpu.stats import pallas_kernels as pk

        monkeypatch.setattr(pk, "_TIER", "sublane")
        assert pk._cell_blocks(nbin)[1] in (64, 128)
        self._diag_parity(nbin)

    def test_sublane_engine_masks_match_xla(self, monkeypatch):
        from iterative_cleaner_tpu.engine.loop import clean_dedispersed_jax
        from iterative_cleaner_tpu.stats import pallas_kernels as pk

        monkeypatch.setattr(pk, "_TIER", "sublane")
        ded, base, weights, shifts = TestFusedCellDiagnostics()._setup(
            nsub=16, nchan=24, nbin=64, seed=9)
        kw = dict(max_iter=3, chanthresh=5.0, subintthresh=5.0,
                  pulse_slice=(0, 0), pulse_scale=1.0, pulse_active=False,
                  rotation="fourier", fft_mode="dft", median_impl="sort")
        a = clean_dedispersed_jax(ded, weights, shifts, stats_impl="xla",
                                  **kw)
        b = clean_dedispersed_jax(ded, weights, shifts, stats_impl="fused",
                                  **kw)
        np.testing.assert_array_equal(np.asarray(a.final_weights),
                                      np.asarray(b.final_weights))
        assert int(a.loops) == int(b.loops)


class TestWeightedMarginalsKernel:
    """One-read dual-marginal kernel vs the XLA dual-dot form
    (ops.dsp.weighted_marginal_totals): same math, regrouped accumulation
    — allclose at f32 ulp scale, exact zero handling, odd shapes padded
    correctly, vmap falls back to the XLA form."""

    def _check(self, nsub, nchan, nbin, seed=0):
        import jax.numpy as jnp

        from iterative_cleaner_tpu.ops.dsp import weighted_marginal_totals
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            weighted_marginals_pallas,
        )

        rng = np.random.default_rng(seed)
        disp = jnp.asarray(
            rng.normal(size=(nsub, nchan, nbin)).astype(np.float32))
        w = jnp.asarray((rng.random((nsub, nchan)) > 0.2).astype(np.float32)
                        * rng.random((nsub, nchan)).astype(np.float32))
        a_k, t1_k = weighted_marginals_pallas(disp, w)
        a_x, t1_x = weighted_marginal_totals(disp, w, jnp)
        assert a_k.shape == (nchan, nbin) and t1_k.shape == (nsub, nbin)
        np.testing.assert_allclose(np.asarray(a_k), np.asarray(a_x),
                                   rtol=2e-6, atol=2e-5)
        np.testing.assert_allclose(np.asarray(t1_k), np.asarray(t1_x),
                                   rtol=2e-6, atol=2e-5)

    def test_block_aligned(self):
        self._check(16, 256, 32)

    def test_odd_shapes_padded(self):
        # neither axis a block multiple: padded rows/cols carry zero
        # weight and must not leak into either marginal
        self._check(11, 150, 32, seed=3)

    def test_zero_weights_zero_marginals(self):
        import jax.numpy as jnp

        from iterative_cleaner_tpu.stats.pallas_kernels import (
            weighted_marginals_pallas,
        )

        disp = jnp.ones((9, 140, 16), jnp.float32)
        a, t1 = weighted_marginals_pallas(disp, jnp.zeros((9, 140),
                                                          jnp.float32))
        np.testing.assert_array_equal(np.asarray(a), 0.0)
        np.testing.assert_array_equal(np.asarray(t1), 0.0)

    def test_vmap_falls_back_to_xla_form(self):
        import jax
        import jax.numpy as jnp

        from iterative_cleaner_tpu.ops.dsp import weighted_marginal_totals
        from iterative_cleaner_tpu.stats.pallas_kernels import (
            weighted_marginals_pallas,
        )

        rng = np.random.default_rng(5)
        disp = jnp.asarray(
            rng.normal(size=(3, 8, 130, 16)).astype(np.float32))
        w = jnp.asarray(rng.random((3, 8, 130)).astype(np.float32))
        a_b, t1_b = jax.vmap(weighted_marginals_pallas)(disp, w)
        a_x, t1_x = jax.vmap(
            lambda d, ww: weighted_marginal_totals(d, ww, jnp))(disp, w)
        np.testing.assert_array_equal(np.asarray(a_b), np.asarray(a_x))
        np.testing.assert_array_equal(np.asarray(t1_b), np.asarray(t1_x))

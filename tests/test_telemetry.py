"""Telemetry subsystem tests: registry/exporter round-trips, the locked
clean.log append, the JSON-lines event log, the on-device iteration
history (jit-compatibility + numpy-oracle parity), per-shard aggregation,
and the CLI --metrics-json acceptance path."""

import datetime
import json
import os
import threading

import numpy as np
import pytest

from iterative_cleaner_tpu.backends import clean_archive
from iterative_cleaner_tpu.backends.base import CleanResult
from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io import make_synthetic_archive, save_archive
from iterative_cleaner_tpu.telemetry import (
    EVENT_SCHEMA,
    ITER_METRIC_FIELDS,
    METRICS_SCHEMA,
    MetricsRegistry,
    PhaseTimer,
    RunEventLog,
    RunTelemetry,
    iter_metrics_dict,
)
from iterative_cleaner_tpu.telemetry.events import read_events
from iterative_cleaner_tpu.telemetry.exporters import (
    metrics_to_json,
    metrics_to_prometheus,
    parse_prometheus_text,
    write_metrics_json,
    write_prometheus_textfile,
)
from iterative_cleaner_tpu.utils.logging import append_clean_log, locked_append


# ---------------------------------------------------------------------------
# registry + exporters
# ---------------------------------------------------------------------------

def _populated_registry():
    r = MetricsRegistry()
    r.counter_inc("archives_cleaned", 3)
    r.counter_inc("cells_zapped", 120)
    r.gauge_set("last_rfi_fraction", 0.25)
    for v in (1, 2, 2, 7):
        r.histogram_observe("loops_per_archive", v)
    with r.phase("clean"):
        pass
    with r.phase("load"):
        pass
    return r


def test_registry_snapshot_sections():
    snap = _populated_registry().snapshot()
    assert snap["counters"] == {"archives_cleaned": 3, "cells_zapped": 120}
    assert snap["gauges"] == {"last_rfi_fraction": 0.25}
    h = snap["histograms"]["loops_per_archive"]
    assert h["count"] == 4 and h["sum"] == 12
    # cumulative_counts covers every bucket plus +Inf
    assert len(h["cumulative_counts"]) == len(h["buckets"]) + 1
    assert h["cumulative_counts"][-1] == 4
    assert set(snap["phases_s"]) == {"clean", "load"}


def test_counter_rejects_negative_and_keys_sorted():
    r = MetricsRegistry()
    with pytest.raises(ValueError):
        r.counter_inc("x", -1)
    r.counter_inc("zeta")
    r.counter_inc("alpha")
    assert list(r.snapshot()["counters"]) == ["alpha", "zeta"]


def test_json_export_round_trip():
    snap = _populated_registry().snapshot()
    doc = json.loads(metrics_to_json(snap, extra={"schema": METRICS_SCHEMA}))
    assert doc["schema"] == METRICS_SCHEMA
    assert doc["counters"] == snap["counters"]
    assert doc["histograms"]["loops_per_archive"]["count"] == 4
    # byte-stable for identical inputs
    assert metrics_to_json(snap) == metrics_to_json(dict(snap))


def test_json_export_file_round_trip(tmp_path):
    snap = _populated_registry().snapshot()
    path = str(tmp_path / "m.json")
    write_metrics_json(path, snap)
    with open(path) as f:
        assert json.load(f)["counters"] == snap["counters"]
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


def test_prometheus_export_round_trip(tmp_path):
    snap = _populated_registry().snapshot()
    path = str(tmp_path / "m.prom")
    write_prometheus_textfile(path, snap)
    parsed = parse_prometheus_text(open(path).read())
    assert parsed["icln_archives_cleaned_total"] == 3.0
    assert parsed["icln_cells_zapped_total"] == 120.0
    assert parsed["icln_last_rfi_fraction"] == 0.25
    assert parsed["icln_loops_per_archive_sum"] == 12.0
    assert parsed["icln_loops_per_archive_count"] == 4.0
    assert parsed['icln_loops_per_archive_bucket{le="+Inf"}'] == 4.0
    # phase timings export as labelled counter samples
    assert any(k.startswith('icln_phase_seconds_total{phase="clean"}')
               for k in parsed)


def test_prometheus_buckets_cumulative():
    r = MetricsRegistry()
    for v in (1, 3, 100):
        r.histogram_observe("h", v, buckets=(2.0, 10.0))
    text = metrics_to_prometheus(r.snapshot())
    parsed = parse_prometheus_text(text)
    assert parsed['icln_h_bucket{le="2.0"}'] == 1.0
    assert parsed['icln_h_bucket{le="10.0"}'] == 2.0
    assert parsed['icln_h_bucket{le="+Inf"}'] == 3.0


def test_phase_timer_report_sorted_deterministic():
    t = PhaseTimer()
    for name in ("write", "clean", "load"):
        with t.phase(name):
            pass
    rep = t.report()
    assert rep == t.report()  # deterministic
    assert rep.index("clean") < rep.index("load") < rep.index("write")
    assert rep.startswith("Timing: ") and "total" in rep


# ---------------------------------------------------------------------------
# clean.log: explicit timestamp + concurrent appends
# ---------------------------------------------------------------------------

def test_append_clean_log_timestamp_byte_format(tmp_path):
    path = str(tmp_path / "clean.log")
    ts = datetime.datetime(2026, 8, 5, 12, 0, 1, 500000)
    append_clean_log("obs.npz", "Namespace(x=1)", 4, log_path=path,
                     timestamp=ts)
    text = open(path).read()
    assert text == ("\n %s: Cleaned obs.npz with Namespace(x=1), "
                    "required loops=4" % ts)


def test_locked_append_concurrent_lines_intact(tmp_path):
    path = str(tmp_path / "shared.log")
    n_threads, n_lines = 8, 40

    def writer(i):
        for j in range(n_lines):
            locked_append(path, f"t{i}:{j}:{'x' * 64}\n")

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = open(path).read().splitlines()
    assert len(lines) == n_threads * n_lines
    assert all(line.endswith("x" * 64) for line in lines)


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

def test_event_log_emit_and_read(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = RunEventLog(path)
    log.emit("run_start", n_archives=2, ts="2026-08-05T00:00:00")
    log.emit("iteration", iteration=0, zap_count=5)
    events = read_events(path)
    assert [e["event"] for e in events] == ["run_start", "iteration"]
    assert all(e["schema"] == EVENT_SCHEMA for e in events)
    assert events[0]["ts"] == "2026-08-05T00:00:00"  # pinned
    assert "ts" in events[1]  # auto-stamped
    assert events[1]["zap_count"] == 5


def test_iter_metrics_dict_contract():
    im = np.array([[10.0, 9.0, 1.5, 2.5],
                   [11.0, 1.0, 1.4, 2.6]], dtype=np.float32)
    d = iter_metrics_dict(im)
    assert list(d) == list(ITER_METRIC_FIELDS)
    assert d["zap_count"] == [10, 11] and d["mask_churn"] == [9, 1]
    assert isinstance(d["zap_count"][0], int)
    assert d["residual_std"] == pytest.approx([1.5, 1.4])
    assert iter_metrics_dict(None) == {}


# ---------------------------------------------------------------------------
# RunTelemetry
# ---------------------------------------------------------------------------

def _fake_result(loops=2):
    w = np.ones((4, 4))
    w[0, :2] = 0
    return CleanResult(
        final_weights=w, scores=np.zeros((4, 4)), loops=loops,
        converged=True,
        iter_metrics=np.array([[2, 2, 1.0, 3.0], [2, 0, 0.9, 3.1]],
                              dtype=np.float32),
    )


def test_run_telemetry_report_and_finalize(tmp_path):
    mj = str(tmp_path / "out.json")
    mp = str(tmp_path / "out.prom")
    ev = str(tmp_path / "ev.jsonl")
    tel = RunTelemetry(metrics_json=mj, prom_textfile=mp,
                       events=RunEventLog(ev))
    tel.record_archive("a.npz", _fake_result())
    tel.finalize()

    doc = json.load(open(mj))
    assert doc["schema"] == METRICS_SCHEMA
    assert doc["counters"]["archives_cleaned"] == 1
    assert doc["counters"]["cells_zapped"] == 2
    assert doc["counters"]["iterations_total"] == 2
    arch = doc["archives"][0]
    assert arch["path"] == "a.npz" and arch["loops"] == 2
    assert arch["iter_history"]["zap_count"] == [2, 2]
    # final zap row equals the returned weights' zapped-cell count
    assert arch["iter_history"]["zap_count"][-1] == arch["cells_zapped"]

    parsed = parse_prometheus_text(open(mp).read())
    assert parsed["icln_archives_cleaned_total"] == 1.0

    kinds = [e["event"] for e in read_events(ev)]
    assert kinds == ["iteration", "iteration", "archive", "run_end"]


def test_run_telemetry_failure_counts(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    tel = RunTelemetry(events=RunEventLog(ev))
    tel.record_failure("bad.npz", RuntimeError("boom"))
    tel.finalize()
    events = read_events(ev)
    assert events[0]["event"] == "error" and "boom" in events[0]["error"]
    assert events[-1] == {**events[-1], "event": "run_end", "ok": 0,
                          "failed": 1}


def test_from_args_normalises_empty_strings():
    import argparse

    ns = argparse.Namespace(metrics_json="", prom_textfile="",
                            event_log="", log_format="text")
    tel = RunTelemetry.from_args(ns)
    assert not tel.enabled
    ns.log_format = "json"
    assert RunTelemetry.from_args(ns).events is not None


# ---------------------------------------------------------------------------
# engine iteration history: jit compatibility + oracle parity
# ---------------------------------------------------------------------------

def _prepared_cube(seed=0, nsub=8, nchan=16, nbin=32):
    rng = np.random.default_rng(seed)
    cube = rng.normal(size=(nsub, nchan, nbin)).astype(np.float64)
    cube[2, 3] += 40.0  # one hot cell so the loop actually zaps
    weights = np.ones((nsub, nchan))
    shifts = np.zeros(nchan, dtype=np.int32)
    return cube, weights, shifts


def test_iteration_history_jit_compatible_no_callbacks():
    """The acceptance invariant 'zero extra device-to-host transfers inside
    the iteration loop': the whole clean program (history recording
    included) must stage into one jaxpr with no host-callback or
    infeed/outfeed primitives anywhere."""
    import jax

    from iterative_cleaner_tpu.engine.loop import clean_dedispersed_jax

    cube, weights, shifts = _prepared_cube()

    def run(c, w, s):
        return clean_dedispersed_jax(
            c, w, s, max_iter=3, chanthresh=5.0, subintthresh=5.0,
            pulse_slice=(0, 0), pulse_scale=1.0, pulse_active=False,
            rotation="roll", fft_mode="dft")

    jaxpr = jax.make_jaxpr(run)(cube, weights, shifts)
    forbidden = ("callback", "infeed", "outfeed", "io_callback",
                 "debug_callback")
    prims = {eqn.primitive.name for eqn in jaxpr.jaxpr.eqns}

    def walk(jxp):
        for eqn in jxp.eqns:
            prims.add(eqn.primitive.name)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr)
                elif isinstance(v, (list, tuple)):
                    for u in v:
                        if hasattr(u, "jaxpr"):
                            walk(u.jaxpr)

    walk(jaxpr.jaxpr)
    bad = {p for p in prims if any(f in p for f in forbidden)}
    assert not bad, f"host-transfer primitives in clean program: {bad}"
    # and the history output really is there, device-shaped
    outs = jax.jit(run)(cube, weights, shifts)
    assert outs.iter_metrics.shape == (3, 4)


def test_iteration_history_matches_numpy_oracle():
    """zap_count/mask_churn recomputed by the jax-free numpy oracle must
    match the on-device history row-for-row (float64 = exact parity
    regime, same as test_backend_parity)."""
    ar, _ = make_synthetic_archive(seed=11, nsub=8, nchan=16, nbin=64,
                                   n_rfi_cells=3)
    res_np = clean_archive(ar.clone(),
                           CleanConfig(backend="numpy", dtype="float64"))
    res_jx = clean_archive(ar.clone(),
                           CleanConfig(backend="jax", dtype="float64"))
    assert res_np.iter_metrics is not None
    assert res_jx.iter_metrics is not None
    assert res_np.iter_metrics.shape == res_jx.iter_metrics.shape
    # integer columns: exact
    np.testing.assert_array_equal(res_np.iter_metrics[:, :2],
                                  res_jx.iter_metrics[:, :2])
    # final zap count == zapped cells in the returned weights (both stacks)
    for res in (res_np, res_jx):
        assert int(res.iter_metrics[-1, 0]) == int(
            np.sum(res.final_weights == 0))
    # churn sums to total mask movement: first row counts the first zaps
    assert res_jx.iter_metrics[0, 1] == res_jx.iter_metrics[0, 0] - np.sum(
        ar.weights == 0)


def test_iteration_history_zap_matches_weight_history():
    """Cross-check against the independently-recorded weight-history
    feature: per-iteration zero counts of the history matrices equal the
    zap_count column."""
    ar, _ = make_synthetic_archive(seed=12)
    res = clean_archive(ar.clone(),
                        CleanConfig(backend="jax", dtype="float64",
                                    record_history=True))
    assert res.weight_history is not None
    for i in range(res.loops):
        assert int(res.iter_metrics[i, 0]) == int(
            np.sum(res.weight_history[i + 1] == 0))
        assert int(res.iter_metrics[i, 1]) == int(
            np.sum((res.weight_history[i + 1] == 0)
                   != (res.weight_history[i] == 0)))


# ---------------------------------------------------------------------------
# streaming + distributed aggregation + checkpoint round-trip
# ---------------------------------------------------------------------------

def test_combine_tile_iter_metrics():
    from iterative_cleaner_tpu.parallel.streaming import (
        StreamTileResult,
        combine_tile_iter_metrics,
    )

    def tile(n_valid, rows):
        w = np.ones((4, 2))
        return StreamTileResult(
            start_subint=0, n_valid=n_valid,
            result=CleanResult(final_weights=w, scores=w, loops=len(rows),
                               converged=True,
                               iter_metrics=np.asarray(rows, np.float32)))

    # tile B is the padded final tile (2 valid of 4 -> 4 padding cells in
    # every row) and converged one iteration early
    a = tile(4, [[3, 3, 1.0, 10.0], [5, 2, 0.8, 11.0]])
    b = tile(2, [[6, 2, 2.0, 9.0]])
    out = combine_tile_iter_metrics([a, b], nchan=2, chunk_nsub=4)
    assert out.shape == (2, 4)
    np.testing.assert_allclose(out[:, 0], [3 + (6 - 4), 5 + (6 - 4)])
    np.testing.assert_allclose(out[:, 1], [5, 2])  # churn: zeros tail
    np.testing.assert_allclose(out[0, 2], (1.0 * 4 + 2.0 * 2) / 6)
    np.testing.assert_allclose(out[:, 3], [10.0, 11.0])


def test_streaming_result_carries_iter_metrics():
    from iterative_cleaner_tpu.parallel.streaming import clean_streaming

    ar, _ = make_synthetic_archive(seed=13, nsub=8, nchan=16, nbin=64)
    cfg = CleanConfig(backend="jax", dtype="float64", max_iter=3)
    for mode in ("online", "exact"):
        res = clean_streaming(ar.clone(), 4, cfg, mode=mode)
        assert res.iter_metrics is not None, mode
        assert res.iter_metrics.shape[1] == 4
        assert res.iter_metrics.shape[0] == res.loops or mode == "online"


def test_aggregate_metrics_single_process_noop():
    from iterative_cleaner_tpu.parallel.distributed import (
        aggregate_metrics_across_processes,
    )

    counters = {"b": 2.0, "a": 1.0}
    out = aggregate_metrics_across_processes(counters)
    assert out == counters and out is not counters


def test_checkpoint_round_trips_iter_metrics(tmp_path):
    from iterative_cleaner_tpu.utils.checkpoint import (
        load_clean_checkpoint,
        save_clean_checkpoint,
    )

    res = _fake_result()
    path = str(tmp_path / "c.ckpt.npz")
    save_clean_checkpoint(path, res, CleanConfig(), "fp")
    loaded, fp, _ = load_clean_checkpoint(path)
    np.testing.assert_array_equal(loaded.iter_metrics, res.iter_metrics)
    # absent stays absent
    res2 = _fake_result()
    res2.iter_metrics = None
    save_clean_checkpoint(path, res2, CleanConfig(), "fp")
    loaded2, _, _ = load_clean_checkpoint(path)
    assert loaded2.iter_metrics is None


# ---------------------------------------------------------------------------
# CLI acceptance
# ---------------------------------------------------------------------------

def test_cli_metrics_json_acceptance(tmp_path, monkeypatch):
    """ISSUE acceptance: --metrics-json produces a report whose
    per-iteration arrays exist and whose final zap total equals the
    written archive's zapped-cell count."""
    from iterative_cleaner_tpu.cli import main
    from iterative_cleaner_tpu.io import load_archive

    monkeypatch.chdir(tmp_path)
    ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=64, seed=0)
    save_archive(ar, "obs.npz")
    main(["-q", "-l", "--metrics-json", "out.json", "--prom-textfile",
          "out.prom", "--log-format", "json", "obs.npz"])

    doc = json.load(open("out.json"))
    assert doc["schema"] == METRICS_SCHEMA
    hist = doc["archives"][0]["iter_history"]
    for field in ITER_METRIC_FIELDS:
        assert len(hist[field]) == doc["archives"][0]["loops"]
    cleaned = load_archive("obs.npz_cleaned.npz")
    assert hist["zap_count"][-1] == int(np.sum(cleaned.weights == 0))

    parsed = parse_prometheus_text(open("out.prom").read())
    assert parsed["icln_archives_cleaned_total"] == 1.0
    events = read_events("clean.events.jsonl")
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert "archive" in kinds and "iteration" in kinds


def test_cli_underscore_flag_aliases():
    from iterative_cleaner_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["--metrics_json", "a.json", "--prom_textfile", "b.prom",
         "--log_format", "json", "--event_log", "e.jsonl", "x.npz"])
    assert args.metrics_json == "a.json"
    assert args.prom_textfile == "b.prom"
    assert args.log_format == "json" and args.event_log == "e.jsonl"


def test_counters_mark_and_since_delta():
    """counters_mark/counters_since: the long-lived-process idiom — a
    monotonic registry yields per-interval figures as deltas against a
    mark (how the serve daemon attributes fleet_* counts to a request)."""
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter_inc("fleet_compiles", 3)
    reg.counter_inc("fleet_cleaned", 5)
    mark = reg.counters_mark()
    assert reg.counters_since(mark) == {}          # nothing happened yet
    reg.counter_inc("fleet_cleaned", 2)
    reg.counter_inc("fleet_precompile_hits")        # absent from the mark
    delta = reg.counters_since(mark)
    assert delta == {"fleet_cleaned": 2.0, "fleet_precompile_hits": 1.0}
    # unchanged counters are omitted: the delta reads as the interval
    assert "fleet_compiles" not in delta
    # the mark is a plain copy, immune to later increments
    assert mark["fleet_cleaned"] == 5.0
    # marks compose: a second interval counts from its own baseline
    mark2 = reg.counters_mark()
    reg.counter_inc("fleet_cleaned")
    assert reg.counters_since(mark2) == {"fleet_cleaned": 1.0}

"""Full-size (1024 x 4096 x 128) mask-parity regression gate (VERDICT r3 #2).

The committed golden (`tests/goldens/fullsize_mask_golden.json`) pins the
float64 oracle's final mask at BASELINE config-3 scale; the gated test
reruns the float32 jax path against it.  The full-size run needs minutes
(not CI seconds), so it only runs with ``ICLEAN_RUN_FULLSIZE=1`` —
regenerate/validate by hand with ``python benchmarks/fullsize_golden.py``.

The ungated tests keep the golden file itself honest: present, well-formed,
and pinned to the geometry the harness generates.
"""

import json
import os

import pytest

from benchmarks.fullsize_golden import golden_paths


def _load(mode="integration"):
    with open(golden_paths(mode)[0]) as f:
        return json.load(f)


@pytest.mark.parametrize("mode", ["integration", "profile"])
def test_golden_committed_and_wellformed(mode):
    from iterative_cleaner_tpu.io.synthetic import bench_rfi_density

    g = _load(mode)
    # recomputing the density rules here means a bench_rfi_density() tune
    # that would silently change the generated archive fails THIS cheap
    # test instead of only the rarely-run full-size check
    assert g["config"] == {"nsub": 1024, "nchan": 4096, "nbin": 128,
                           "seed": 0, "disperse": True,
                           "baseline_mode": mode,
                           "rfi": bench_rfi_density(1024, 4096)}
    assert len(g["mask_hash"]) == 32 and len(g["weights_hash"]) == 32
    assert 1 <= g["loops"] <= 5 and g["converged"] is True
    # density sanity: the injected RFI (~bench rules) zaps a small but
    # nonzero fraction of the 4.2M cells
    assert 0 < g["zap_cells"] < 1024 * 4096 // 4
    # the borderline band `check` tolerates flips in must stay tiny and
    # every member must actually be within eps of the threshold
    assert g["borderline_eps"] == 0.05
    assert 0 < len(g["borderline"]) < 1000
    for _i, _c, s in g["borderline"]:
        assert abs(s - 1.0) < g["borderline_eps"]
    # the packed oracle mask golden must decode and match the JSON's counts
    import numpy as np

    with np.load(golden_paths(mode)[1]) as z:
        zap = np.unpackbits(z["zap"])[: 1024 * 4096]
    assert int(zap.sum()) == g["zap_cells"]


@pytest.mark.parametrize("mode", ["integration", "profile"])
def test_flip_verdict_bounds_the_allowance(mode):
    """VERDICT r4 weak #3: the borderline band must be a CONTRACT, not an
    allowance — a synthetic regression that flips every band cell (or any
    decisive cell, or a wide-band cell) must be rejected."""
    from benchmarks.fullsize_golden import (
        FLIP_NOISE_ENV,
        MAX_BORDERLINE_FLIPS,
        flip_verdict,
    )

    g = _load(mode)
    assert MAX_BORDERLINE_FLIPS <= 10 and FLIP_NOISE_ENV <= 0.01
    # no flips: ok
    assert flip_verdict([], g, "float32")["ok"]
    # the observed-benign shape: a couple of flips well inside the
    # noise envelope
    tight = [[i, c] for i, c, s in g["borderline"]
             if abs(s - 1.0) <= FLIP_NOISE_ENV][:2]
    if tight:
        assert flip_verdict(tight, g, "float32")["ok"]
        # float64 tolerates NOTHING, not even the tightest band cell
        assert not flip_verdict(tight, g, "float64")["ok"]
    # mass flip of the whole band: over the cap, rejected
    all_band = [[i, c] for i, c, _ in g["borderline"]]
    assert len(all_band) > MAX_BORDERLINE_FLIPS
    v = flip_verdict(all_band, g, "float32")
    assert v["over_cap"] and not v["ok"]
    # a decisively-scored cell (not in the band): rogue, rejected
    band_keys = {(i, c) for i, c, _ in g["borderline"]}
    rogue_cell = next([i, c] for i in range(1024) for c in range(4096)
                      if (i, c) not in band_keys)
    v = flip_verdict([rogue_cell], g, "float32")
    assert v["rogue"] and not v["ok"]
    # a band cell OUTSIDE the noise envelope: wider noise than ever
    # measured, rejected
    wide = [[i, c] for i, c, s in g["borderline"]
            if abs(s - 1.0) > FLIP_NOISE_ENV][:1]
    if wide:
        v = flip_verdict(wide, g, "float32")
        assert v["wide"] and not v["ok"]


@pytest.mark.skipif(not os.environ.get("ICLEAN_RUN_FULLSIZE"),
                    reason="full-size run takes minutes; set "
                           "ICLEAN_RUN_FULLSIZE=1 to enable")
# xla only: the fused/pallas kernels run in INTERPRET mode off-TPU, which
# is impractically slow at 1024x4096x128 — those variants are checked on
# hardware by benchmarks/tpu_validation_pass.sh step 6.  float32 passes
# via the borderline-band allowance; float64 must match the oracle
# EXACTLY (verified 2026-07-30: bit-identical — the remaining f32
# divergence is dtype-only, not algorithmic).
@pytest.mark.parametrize("variant,frame,dtype,mode", [
    ("xla", "dispersed", "float32", "integration"),
    ("xla", "dispersed", "float64", "integration"),
    ("xla", "dispersed", "float32", "profile")])
def test_fullsize_mask_parity(variant, frame, dtype, mode):
    import subprocess
    import sys

    from tests.conftest import repo_subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks",
                                      "fullsize_golden.py"),
         "check", "--variant", variant, "--stats_frame", frame,
         "--dtype", dtype, "--baseline_mode", mode],
        env=repo_subprocess_env(), capture_output=True, timeout=3600)
    assert out.returncode == 0, (out.stdout.decode()[-2000:]
                                 + out.stderr.decode()[-2000:])

"""Segmented journal backend: crash-safety matrix, fold equivalence,
manifest/orphan semantics, telemetry, and directory fsck.

The centerpiece is the seeded-crash matrix: every ``os.replace`` call a
seal/compact workload makes is a kill -9 boundary, and for each boundary
we crash exactly there, reopen the directory cold, and require the folds
to equal a reference journal that replayed the same operations without
crashing.  This is the on-disk complement to the scheduler-level model
checker in analysis/interleave.py.
"""

import json
import os
import re

import pytest

from iterative_cleaner_tpu.analysis.journal_fsck import (
    fsck_journal,
    record_fsck,
)
from iterative_cleaner_tpu.parallel.distributed import stable_shard
from iterative_cleaner_tpu.resilience.journal import FleetJournal, entry_key
from iterative_cleaner_tpu.resilience.segmented import (
    MANIFEST_NAME,
    MANIFEST_SCHEMA,
    SegmentedLog,
    compacted_name,
    sealed_name,
)
from iterative_cleaner_tpu.serve.membership import PoolMembership
from iterative_cleaner_tpu.telemetry.recorder import (
    FlightRecorder,
    set_active,
)
from iterative_cleaner_tpu.telemetry.registry import MetricsRegistry


CFG = "cfg-seg-test"

# lease timestamps sit far in the future so mid-workload compactions
# (which age out lapsed leases against the wall clock) are fold-neutral
# — the crash matrix then has ONE legitimate fold answer per boundary
T0 = 4.0e9


def _seg_dir(tmp_path, name="journal.d", **kwargs):
    kwargs.setdefault("segment_mb", 0.0008)   # ~800 B: seals constantly
    return FleetJournal(str(tmp_path / name) + os.sep, **kwargs)


def _write_pair(tmp_path):
    a = tmp_path / "in.icar"
    b = tmp_path / "out.icar"
    a.write_bytes(b"input-bytes")
    b.write_bytes(b"output-bytes")
    return str(a), str(b)


def _workload_ops(a, b):
    """A deterministic op tape exercising all six event kinds, with
    seals and compactions interleaved.  Each element is (kind, fn);
    ``seal``/``compact`` ops mutate storage only, every other op
    appends exactly one line."""
    ops = []
    for i in range(6):
        ops.append(("req", lambda j, i=i: j.record_request(
            "r%03d" % i, "accepted", paths=["/in/%d" % i])))
        ops.append(("claim", lambda j, i=i: j.record_claim(
            "bucket-%d" % i, host=i % 3, nonce="n%d" % i, ttl_s=60.0,
            now=T0 + i)))
    ops.append(("seal", lambda j: j.seal()))
    for i in range(3):
        ops.append(("member", lambda j, i=i: j.record_member(
            "m%d" % i, "join", host=i, ttl_s=60.0, now=T0 + i)))
        ops.append(("stats", lambda j, i=i: j.record_host_stats(
            i, {"cleaned": float(i)})))
    ops.append(("done", lambda j: j.record_done(
        a, config_hash=CFG, out_path=b)))
    ops.append(("cache", lambda j: j.record_cache(
        a, config_hash=CFG, out_path=b)))
    ops.append(("compact", lambda j: j.compact()))
    for i in range(6):
        ops.append(("req", lambda j, i=i: j.record_request(
            "r%03d" % i, "done")))
        ops.append(("claim", lambda j, i=i: j.record_claim(
            "bucket-%d" % i, host=i % 3, nonce="n%d" % i, ttl_s=0.0,
            state="release", now=T0 + 200.0 + i)))
    ops.append(("seal", lambda j: j.seal()))
    ops.append(("compact", lambda j: j.compact()))
    for i in range(3):
        ops.append(("req", lambda j, i=i: j.record_request(
            "s%d" % i, "accepted", paths=["/late/%d" % i])))
    return ops


def _folds(j, now=T0 + 30.0):
    return {
        "requests": j.request_states(),
        "claims": j.claim_table(now=now),
        "members": j.member_table(now=now),
        "stats": j.host_stats(),
        "completed": j.completed(CFG),
        "cache": j.cache_index(),
    }


class _Boom(RuntimeError):
    """The injected crash — deliberately NOT an OSError, so no heal /
    retry path in the journal can swallow it."""


def _run_ops(j, ops, crash_at=None):
    """Execute the op tape against ``j`` with ``os.replace`` counted and
    (optionally) crashed at call number ``crash_at``.  Returns (ops that
    put a line on disk, replace-call count, crashed?).  Append ops are
    recorded BEFORE execution: the flocked append lands before any seal
    rename, so a crash mid-op still leaves the line durable."""
    real = os.replace
    calls = {"n": 0}

    def patched(src, dst, *args, **kwargs):
        calls["n"] += 1
        if crash_at is not None and calls["n"] == crash_at:
            raise _Boom("injected at os.replace #%d" % calls["n"])
        return real(src, dst, *args, **kwargs)

    durable = []
    crashed = False
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(os, "replace", patched)
        try:
            for kind, fn in ops:
                if kind not in ("seal", "compact"):
                    durable.append((kind, fn))
                fn(j)
        except _Boom:
            crashed = True
    return durable, calls["n"], crashed


def test_crash_matrix_every_replace_boundary(tmp_path):
    """kill -9 at EVERY os.replace boundary of a seal/compact workload
    leaves a directory a cold reopen folds identically to a journal
    that never crashed, and that fsck passes."""
    a, b = _write_pair(tmp_path)
    ops = _workload_ops(a, b)

    # dry run: count the replace boundaries this workload crosses
    dry = _seg_dir(tmp_path, "dry.d")
    _, n_boundaries, crashed = _run_ops(dry, ops)
    assert not crashed
    assert n_boundaries >= 15, \
        "workload too tame to exercise seal/compact boundaries"

    for k in range(1, n_boundaries + 1):
        root = tmp_path / ("crash-%03d" % k)
        root.mkdir()
        j = _seg_dir(root)
        durable, _, crashed = _run_ops(j, ops, crash_at=k)
        assert crashed, f"boundary {k}: workload outran the dry-run count"

        # replay exactly the durable prefix on a plain single-file journal
        ref = FleetJournal(str(root / "ref.jsonl"))
        for _, fn in durable:
            fn(ref)

        j2 = FleetJournal(str(root / "journal.d"))   # cold reopen
        assert j2.backend == "segmented"
        assert _folds(j2) == _folds(ref), f"boundary {k}: folds diverge"
        report = fsck_journal(j2.path)
        assert report.ok, f"boundary {k}: fsck: {report.render_text()}"

        # the survivor keeps working: seal + compact heal any leftover
        # orphans/dead entries.  Compaction ages out expired leases on
        # BOTH backends (live_lines is shared), so compact the
        # reference too before comparing.
        j2.seal()
        j2.compact()
        ref.compact()
        assert _folds(j2) == _folds(ref), \
            f"boundary {k}: post-recovery compaction changed folds"
        assert fsck_journal(j2.path).ok


def test_fold_equivalence_file_vs_segmented(tmp_path):
    """The same op tape folds identically through both backends, before
    and after seal/compaction."""
    a, b = _write_pair(tmp_path)
    ops = _workload_ops(a, b)
    jf = FleetJournal(str(tmp_path / "ref.jsonl"))
    js = _seg_dir(tmp_path)
    for _, fn in ops:
        fn(jf)
        fn(js)
    assert _folds(js) == _folds(jf)
    assert js.seal() >= 0 and js.compact()
    assert jf.compact()
    assert _folds(js) == _folds(jf)


def test_manifest_n_shards_persists_across_reopen(tmp_path):
    j = _seg_dir(tmp_path, n_shards=4)
    for i in range(10):
        j.record_request("r%d" % i, "accepted")
    j2 = FleetJournal(j.path, n_shards=16)   # constructor loses
    assert j2.n_shards() == 4
    assert len(j2.request_states()) == 10


def test_sealed_orphan_is_adopted_and_seq_stays_monotone(tmp_path):
    """A crashed seal (rename landed, manifest update did not) leaves a
    ``seg-`` orphan that folds still read and whose sequence number the
    next seal skips past."""
    j = _seg_dir(tmp_path)
    j.record_request("orphan-req", "accepted")
    assert j.seal() == 1
    man_path = os.path.join(j.path, MANIFEST_NAME)
    man = json.loads(open(man_path).read())
    (shard_key, ent), = [(k, v) for k, v in man["shards"].items()
                         if v["segments"]]
    (orphan_name,) = ent["segments"]
    ent["segments"] = []                      # simulate the crashed seal
    with open(man_path, "w") as f:
        json.dump(man, f)

    j2 = FleetJournal(j.path)
    assert j2.request_states()["orphan-req"]["state"] == "accepted"
    j2.record_request("orphan-req", "running")
    assert j2.seal() == 1
    seq_of = lambda n: int(re.search(r"-(\d+)\.jsonl$", n).group(1))
    names = [n for n in os.listdir(j2.path)
             if n.startswith("seg-%02d" % int(shard_key))]
    assert orphan_name in names
    assert max(seq_of(n) for n in names) > seq_of(orphan_name)
    assert j2.request_states()["orphan-req"]["state"] == "running"


def test_compacted_orphan_is_never_adopted(tmp_path):
    """A ``cmp-`` file the manifest does not list is a crashed
    compactor's unpublished output — reading it would double-count, so
    folds must ignore it."""
    j = _seg_dir(tmp_path)
    j.record_request("real", "accepted")
    shard = stable_shard("req:ghost", j.n_shards())
    ghost = {"schema": "icln-fleet-journal/1", "event": "req",
             "req": "ghost", "state": "accepted"}
    with open(os.path.join(j.path, compacted_name(shard, 99)), "w") as f:
        f.write(json.dumps(ghost) + "\n")
    states = FleetJournal(j.path).request_states()
    assert "real" in states and "ghost" not in states


def test_dead_listed_file_is_excluded_then_gced(tmp_path):
    """A file on the dead list is invisible to folds even while it still
    exists (crash between manifest swap and unlink), and the next
    compaction pass actually removes it and clears the entry."""
    j = _seg_dir(tmp_path)
    j.record_request("keep", "accepted")
    shard = stable_shard("req:keep", j.n_shards())
    assert j.seal() == 1
    man_path = os.path.join(j.path, MANIFEST_NAME)
    man = json.loads(open(man_path).read())
    ent = man["shards"][str(shard)]
    (seg,) = ent["segments"]
    # fake a finished compaction whose retirement crashed mid-way: the
    # cmp output is listed, the input is dead but still on disk
    cmp_name = compacted_name(shard, 1)
    with open(os.path.join(j.path, cmp_name), "w") as f:
        f.write(json.dumps({"schema": "icln-fleet-journal/1",
                            "event": "req", "req": "keep",
                            "state": "done"}) + "\n")
    ent["segments"] = [cmp_name]
    ent["dead"] = [seg]
    with open(man_path, "w") as f:
        json.dump(man, f)

    j2 = FleetJournal(j.path)
    assert j2.request_states()["keep"]["state"] == "done"
    j2.compact()                              # drives _gc_dead
    assert not os.path.exists(os.path.join(j2.path, seg))
    man = json.loads(open(man_path).read())
    assert man["shards"][str(shard)]["dead"] == []
    assert j2.request_states()["keep"]["state"] == "done"


def test_torn_tail_heal_counts_and_leaves_flight_event(make_journal):
    """A torn active tail is healed on the next append — and is COUNTED
    (journal_torn_heals) and flight-recorded, never silent."""
    reg = MetricsRegistry()
    rec = FlightRecorder()
    set_active(rec)
    try:
        j = make_journal(registry=reg)
        j.record_request("t1", "accepted")
        if j.backend == "segmented":
            victim = j.log._active_path(
                stable_shard("req:t1", j.n_shards()))
        else:
            victim = j.path
        with open(victim, "rb+") as f:
            f.truncate(os.path.getsize(victim) - 3)   # tear the tail
        j.record_request("t1", "running")
        assert reg.snapshot()["counters"]["journal_torn_heals"] == 1
        events = rec.snapshot("test")["rings"].get("journal", [])
        assert any(e.get("name") == "torn_heal"
                   and e.get("backend") == j.backend for e in events)
        # the torn line is gone, the healed append is authoritative
        assert j.request_states()["t1"]["state"] == "running"
        assert fsck_journal(j.path).ok
    finally:
        set_active(None)


def test_fold_timer_and_compaction_counter(make_journal):
    reg = MetricsRegistry()
    j = make_journal(registry=reg)
    for i in range(5):
        j.record_request("r%d" % i, "accepted")
    j.request_states()
    snap = reg.snapshot()
    assert snap["histograms"]["journal_fold_s"]["count"] >= 1
    j.seal()
    assert j.compact()
    assert reg.snapshot()["counters"]["journal_compactions"] == 1


def test_segment_counts_and_size_bytes(tmp_path):
    j = _seg_dir(tmp_path, segment_mb=0.0001)   # 100 B: seal every line
    for i in range(12):
        j.record_request("r%d" % i, "accepted", paths=["/x/%d" % i])
    counts = j.segment_counts()
    assert sum(counts.values()) >= 2
    assert set(counts) == set(range(j.n_shards()))
    assert j.size_bytes() > 0
    assert j.seal() >= 0 and j.compact()
    assert sum(j.segment_counts().values()) <= sum(counts.values())
    assert len(j.request_states()) == 12


def test_maintenance_lease_is_exclusive(tmp_path):
    """Two members race for one shard's maint lease: exactly one wins,
    and release hands it over."""
    j = _seg_dir(tmp_path)
    m1 = PoolMembership(j, ttl_s=30.0, member_id="m1", host=1)
    m2 = PoolMembership(j, ttl_s=30.0, member_id="m2", host=2)
    assert m1.claim_maintenance(3, now=100.0)
    assert not m2.claim_maintenance(3, now=101.0)
    m1.release_maintenance(3, now=102.0)
    assert m2.claim_maintenance(3, now=103.0)
    # distinct shards are independent
    assert m1.claim_maintenance(4, now=103.0)


# ------------------------------------------------------- directory fsck

def test_fsck_dir_green_and_counts_segments(tmp_path):
    a, b = _write_pair(tmp_path)
    j = _seg_dir(tmp_path)
    for _, fn in _workload_ops(a, b):
        fn(j)
    report = fsck_journal(j.path)
    assert report.ok
    assert report.n_segments > 0
    assert "segment" in report.render_text()
    reg = MetricsRegistry()
    record_fsck(reg, report)
    snap = reg.snapshot()
    assert snap["gauges"]["journal_fsck_segments"] == report.n_segments
    # single-file journals report zero segments
    ref = FleetJournal(str(tmp_path / "ref.jsonl"))
    ref.record_request("r", "accepted")
    assert fsck_journal(ref.path).n_segments == 0


def test_fsck_dir_missing_manifest_is_error(tmp_path):
    d = tmp_path / "bare.d"
    d.mkdir()
    report = fsck_journal(str(d))
    assert not report.ok
    assert any(i.kind == "manifest" for i in report.issues)


def test_fsck_dir_bad_manifest_schema_is_error(tmp_path):
    d = tmp_path / "bad.d"
    d.mkdir()
    (d / MANIFEST_NAME).write_text(json.dumps(
        {"schema": "icln-journal/999", "n_shards": 8, "shards": {}}))
    report = fsck_journal(str(d))
    assert not report.ok
    assert any(i.kind == "manifest" and "schema" in i.message
               for i in report.issues)
    assert MANIFEST_SCHEMA in " ".join(i.message for i in report.issues)


def test_fsck_dir_listed_segment_missing_is_error(tmp_path):
    j = _seg_dir(tmp_path)
    j.record_request("r0", "accepted")
    assert j.seal() == 1
    man_path = os.path.join(j.path, MANIFEST_NAME)
    man = json.loads(open(man_path).read())
    (name,) = [n for ent in man["shards"].values()
               for n in ent["segments"]]
    os.unlink(os.path.join(j.path, name))
    report = fsck_journal(j.path)
    assert not report.ok
    assert any(i.kind == "manifest" and name in i.message
               for i in report.issues)


def test_fsck_dir_flags_misrouted_line(tmp_path):
    j = _seg_dir(tmp_path)
    j.record_request("r0", "accepted")
    entry = {"schema": "icln-fleet-journal/1", "event": "req",
             "req": "misrouted", "state": "accepted"}
    home = stable_shard(entry_key(entry), j.n_shards())
    wrong = (home + 1) % j.n_shards()
    with open(j.log._active_path(wrong), "a") as f:
        f.write(json.dumps(entry) + "\n")
    report = fsck_journal(j.path)
    assert not report.ok
    assert any(i.kind == "shard-routing" for i in report.issues)


def test_fsck_dir_heals_torn_segment_tail(tmp_path):
    """A torn tail inside a sealed segment is the heal-aware warning,
    not an error — exactly the single-file torn-tail contract."""
    j = _seg_dir(tmp_path)
    j.record_request("r0", "accepted")
    j.record_request("r0", "running")
    shard = stable_shard("req:r0", j.n_shards())
    victim = j.log._active_path(shard)
    with open(victim, "rb+") as f:
        f.truncate(os.path.getsize(victim) - 4)
    report = fsck_journal(j.path)
    assert report.ok
    assert any(i.severity == "warning" and i.kind == "torn-line"
               for i in report.issues)

"""Differential tests against the *actual* upstream reference script.

`/root/reference/iterative_cleaner.py` is imported and executed literally,
with ``psrchive`` replaced by the fake archive backend
(tests/fake_psrchive.py) whose DSP methods share this framework's operator
definitions (ops/dsp.py).  Both paths therefore see identical
baseline/dedispersion/scrunch semantics, and the diff isolates everything
the framework re-implements: the per-cell MINPACK fit (closed form here,
reference :275-288), the surgical-scrub statistics (:181-256), weight
application (:291-305), the convergence loop (:83-146) and the bad-parts
sweep (:308-335).

These tests are the strongest parity evidence in the suite: they do not
re-express the reference's semantics, they *run* the reference.  Skipped
when the reference checkout is absent (the framework itself never depends
on it).
"""

import argparse
import importlib.util
import os
import sys
import types

import numpy as np
import pytest

from tests import fake_psrchive
from iterative_cleaner_tpu.backends import clean_archive
from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

REF_PATH = "/root/reference/iterative_cleaner.py"

pytestmark = pytest.mark.skipif(
    not os.path.exists(REF_PATH), reason="upstream reference checkout not present"
)


@pytest.fixture(scope="module")
def upstream():
    """Import the upstream script with psrchive shimmed to the fake."""
    # reference-only dependencies (the framework itself needs neither)
    matplotlib = pytest.importorskip("matplotlib")
    pytest.importorskip("scipy")
    matplotlib.use("Agg", force=True)
    shim = types.ModuleType("psrchive")
    shim.Archive_load = fake_psrchive.Archive_load
    saved = sys.modules.get("psrchive")
    sys.modules["psrchive"] = shim
    try:
        spec = importlib.util.spec_from_file_location(
            "upstream_iterative_cleaner", REF_PATH
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        if saved is None:
            sys.modules.pop("psrchive", None)
        else:
            sys.modules["psrchive"] = saved
    return mod


def ref_args(**kw):
    """An argparse namespace with the reference's flag surface (:16-42).

    Deviations from the upstream argparse defaults: quiet/no_log are on
    (keep test output clean), and ``pscrunch=True`` so the reference's
    post-loop ``Archive_load`` reload (:149-150) is skipped — the reload
    branch is exercised separately by
    :func:`test_fullpol_reload_branch_matches_upstream` with a real file.
    """
    d = dict(
        archive=["synthetic.ar"], chanthresh=5.0, subintthresh=5.0, max_iter=5,
        print_zap=False, unload_res=False, pscrunch=True, quiet=True,
        no_log=True, pulse_region=[0, 0, 1], output="", memory=False,
        bad_chan=1, bad_subint=1,
    )
    d.update(kw)
    return argparse.Namespace(**d)


class _CapturingArchive(fake_psrchive.FakeArchive):
    """Capture unload() targets in memory (the residual path writes `.ar`,
    which the npz container deliberately refuses)."""

    captured = None  # set per-test: list of (path, Archive)

    def unload(self, path):
        type(self).captured.append((path, self._ar))


def run_upstream(upstream, ar, args, **fake_kw):
    fa = fake_psrchive.FakeArchive(ar.clone(), "synthetic.ar", **fake_kw)
    out = upstream.clean(fa, args, "synthetic.ar")
    return out.get_weights()


def _config_from_args(args, **extra):
    kw = dict(
        backend="numpy", dtype="float64",
        chanthresh=args.chanthresh, subintthresh=args.subintthresh,
        max_iter=args.max_iter, pulse_region=tuple(args.pulse_region),
        bad_chan=args.bad_chan, bad_subint=args.bad_subint,
    )
    kw.update(extra)  # may override backend/dtype
    return CleanConfig(**kw)


CASES = [
    ("default", dict(seed=0), dict()),
    ("prezapped", dict(seed=1, n_prezapped=10), dict()),
    ("small", dict(seed=2, nsub=8, nchan=12, nbin=64, n_rfi_cells=3), dict()),
    ("thresholds", dict(seed=3, n_rfi_channels=2), dict(chanthresh=4.0, subintthresh=6.5)),
    ("max_iter_1", dict(seed=4), dict(max_iter=1)),
    ("pulse_region", dict(seed=5), dict(pulse_region=[0.25, 30, 50])),
    # degenerate geometries: single-line scalers, tiny bin counts
    ("one_subint", dict(seed=3, nsub=1, nchan=8, nbin=32, n_rfi_cells=2,
                        n_rfi_channels=0, n_rfi_subints=0), dict()),
    ("one_channel", dict(seed=3, nsub=6, nchan=1, nbin=32, n_rfi_cells=2,
                         n_rfi_channels=0, n_rfi_subints=0), dict()),
    ("one_cell", dict(seed=3, nsub=1, nchan=1, nbin=32, n_rfi_cells=0,
                      n_rfi_channels=0, n_rfi_subints=0), dict()),
    ("tiny_bins", dict(seed=3, nsub=4, nchan=6, nbin=4, n_rfi_cells=2,
                       n_rfi_channels=0, n_rfi_subints=0), dict()),
]


@pytest.mark.parametrize("name,gen_kw,arg_kw", CASES, ids=[c[0] for c in CASES])
def test_final_weights_match_upstream(upstream, name, gen_kw, arg_kw):
    ar, _ = make_synthetic_archive(**gen_kw)
    args = ref_args(**arg_kw)
    ref_weights = run_upstream(upstream, ar, args)
    res = clean_archive(ar.clone(), _config_from_args(args))
    np.testing.assert_array_equal(res.final_weights, ref_weights)


def test_profile_baseline_mode_matches_upstream(upstream):
    """The legacy per-profile baseline mode, end to end against the
    upstream script with a profile-mode fake.  Regression for the round-3
    find that FakeArchive.clone() silently dropped baseline_mode — the
    reference's loop works entirely on clones, so the dropped knob made
    every 'profile' differential secretly mixed-mode."""
    for seed in (31, 32, 33):
        ar, _ = make_synthetic_archive(seed=seed, n_prezapped=6)
        args = ref_args()
        ref_weights = run_upstream(upstream, ar, args,
                                   baseline_mode="profile")
        res = clean_archive(
            ar.clone(), _config_from_args(args, baseline_mode="profile"))
        np.testing.assert_array_equal(res.final_weights, ref_weights)


def test_roll_rotation_matches_upstream(upstream):
    """Non-default DSP knob: nearest-bin roll dedispersion on both sides."""
    ar, _ = make_synthetic_archive(seed=13)
    args = ref_args()
    ref_weights = run_upstream(upstream, ar, args, rotation="roll")
    res = clean_archive(ar.clone(), _config_from_args(args, rotation="roll"))
    np.testing.assert_array_equal(res.final_weights, ref_weights)


def test_nan_data_matches_upstream(upstream):
    """NaN bins poison the template and every score; NaN never zaps (quirk 8)
    and both paths must agree on that."""
    ar, _ = make_synthetic_archive(nsub=8, nchan=10, nbin=32, seed=11,
                                   n_rfi_cells=3)
    ar.data[2, 0, 3, 5] = np.nan
    args = ref_args()
    ref_weights = run_upstream(upstream, ar, args)
    res = clean_archive(ar.clone(), _config_from_args(args))
    np.testing.assert_array_equal(res.final_weights, ref_weights)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_dedispersed_input_matches_upstream(upstream, backend):
    """DEDISP=1 archives: PSRCHIVE's ``dedisperse`` is state-aware and no-ops
    on an already-dedispersed archive (reference :91,:100), while
    ``dededisperse`` (:104) still rotates the residual into the dispersed
    frame.  Construct the input through the state-aware fake's own
    ``dedisperse`` and require identical final masks — a backend that
    rotated a second time would fail this."""
    # dm=300 spans ~15 bins across the band so a spurious second rotation
    # visibly smears the pulse (the default dm's shifts are sub-bin)
    ar, _ = make_synthetic_archive(seed=21, nsub=10, nchan=12, nbin=64,
                                   n_rfi_cells=4, dm=300.0)
    fa = fake_psrchive.FakeArchive(ar.clone(), "ded.ar")
    fa.dedisperse()  # rotates into the aligned frame and sets the flag
    ded_ar = fa._ar
    assert ded_ar.dedispersed
    args = ref_args()
    ref_weights = run_upstream(upstream, ded_ar, args)
    kw = dict(backend=backend)
    if backend == "jax":
        kw["dtype"] = "float64"
    res = clean_archive(ded_ar.clone(), _config_from_args(args, **kw))
    np.testing.assert_array_equal(res.final_weights, ref_weights)


def test_jax_backend_matches_upstream(upstream):
    ar, _ = make_synthetic_archive(seed=6)
    args = ref_args()
    ref_weights = run_upstream(upstream, ar, args)
    res = clean_archive(ar.clone(), _config_from_args(args, backend="jax"))
    np.testing.assert_array_equal(res.final_weights, ref_weights)


@pytest.mark.parametrize("pscrunch,memory", [
    (True, False),   # pscrunched in memory, no reload: single-pol output
    (False, False),  # pscrunched in memory, RELOADED post-loop (:149-150)
    (False, True),   # --memory without -p: never pscrunched, never reloaded
    (True, True),    # --memory with -p: pscrunched in memory, no reload
], ids=["p", "neither", "m", "pm"])
def test_memory_pscrunch_matrix_matches_upstream(upstream, tmp_path,
                                                 pscrunch, memory):
    """The full --pscrunch x --memory matrix (reference :67-70,:149-150,
    quirk 12) on a 4-pol archive.  Observable contract: the final weights
    are combination-invariant and match the framework, and the output stays
    full-pol exactly when -p is off (via the disk reload when --memory is
    off, via never scrunching when it is on).  The reload branch gets a
    real file; the no-reload branches get a nonexistent path, so an
    unexpected reload fails loudly."""
    from iterative_cleaner_tpu.io import save_archive

    ar, _ = make_synthetic_archive(seed=12, nsub=8, nchan=10, nbin=32,
                                   npol=4, n_rfi_cells=3)
    reloads = not pscrunch and not memory
    if reloads:
        path = str(tmp_path / "fullpol.npz")
        save_archive(ar, path)
    else:
        path = "nonexistent-path.ar"

    fa = fake_psrchive.FakeArchive(ar.clone(), path)
    args = ref_args(archive=[path], pscrunch=pscrunch, memory=memory)
    out = upstream.clean(fa, args, path)
    assert out.get_npol() == (1 if pscrunch else 4)

    # the framework: --memory is a documented no-op (the engine never
    # mutates its input, cli.py), so one config covers both memory settings
    res = clean_archive(ar.clone(), _config_from_args(args))
    np.testing.assert_array_equal(res.final_weights, out.get_weights())


def test_bad_parts_sweep_matches_upstream(upstream):
    # pre-zap most of one subint and one channel so the sweeps fire
    ar, _ = make_synthetic_archive(seed=7, nsub=12, nchan=20)
    ar.weights[3, :16] = 0.0    # 16/20 channels of subint 3 gone
    ar.weights[:9, 11] = 0.0    # 9/12 subints of channel 11 gone
    args = ref_args(bad_subint=0.5, bad_chan=0.5)
    ref_weights = run_upstream(upstream, ar, args)
    res = clean_archive(ar.clone(), _config_from_args(args))
    np.testing.assert_array_equal(res.final_weights, ref_weights)
    assert (res.final_weights[3] == 0).all()
    assert (res.final_weights[:, 11] == 0).all()


def test_residual_matches_upstream(upstream):
    ar, _ = make_synthetic_archive(seed=8)
    args = ref_args(unload_res=True)
    captured = []
    _CapturingArchive.captured = captured
    fa = _CapturingArchive(ar.clone(), "synthetic.ar")
    upstream.clean(fa, args, "synthetic.ar")
    assert len(captured) == 1
    resid_path, resid_ar = captured[0]
    res = clean_archive(
        ar.clone(), _config_from_args(args, unload_res=True)
    )
    # filename encodes the loop count: "<name>_residual_<loops>.ar" (ref :162)
    assert resid_path == "synthetic.ar_residual_%d.ar" % res.loops
    # the residual cube: identical up to MINPACK-vs-closed-form amp rounding
    np.testing.assert_allclose(
        np.asarray(res.residual), resid_ar.data[:, 0], rtol=1e-6, atol=1e-6
    )


def test_stats_functions_match_upstream(upstream):
    """Function-level differential on the detection math (reference
    :181-256) over random and adversarial masked inputs."""
    from iterative_cleaner_tpu.stats.masked_numpy import surgical_scores_numpy

    rng = np.random.default_rng(42)
    for trial in range(5):
        nsub, nchan, nbin = 10, 14, 32
        cube = rng.normal(size=(nsub, nchan, nbin))
        cube[1, 2] += 25.0
        mask2 = rng.random((nsub, nchan)) < 0.2
        if trial == 3:
            mask2[:, 4] = True   # fully-masked channel
            mask2[6, :] = True   # fully-masked subint
        if trial == 4:
            cube[:, 5, :] = 7.0  # constant channel: zero MAD
        cube[mask2] = 0.0
        mask3 = np.broadcast_to(mask2[:, :, None], cube.shape)
        masked = np.ma.masked_array(cube, mask=mask3)
        args = ref_args(chanthresh=4.5, subintthresh=5.5)
        want = upstream.comprehensive_stats(masked, args, axis=2)
        got = surgical_scores_numpy(cube, mask2, 4.5, 5.5)
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_fit_matches_upstream_leastsq(upstream):
    """Per-cell differential of the closed-form amplitude fit against the
    upstream MINPACK path, including the pulse-region suppression quirk
    (reference :275-288; SURVEY.md 2.4 quirk 3)."""
    from iterative_cleaner_tpu.ops.dsp import (
        fit_template_amplitudes, template_residuals)

    rng = np.random.default_rng(7)
    nbin = 64
    template = np.exp(-0.5 * ((np.arange(nbin) / nbin - 0.4) / 0.03) ** 2) * 1e4
    cube = rng.normal(0, 1, size=(3, 4, nbin)) + 2.5 * template / 1e4
    pulse_region = [0.3, 10, 40]
    amps = fit_template_amplitudes(cube, template, np)
    resid = template_residuals(
        cube, template, amps, (10, 40), 0.3, np, apply_pulse_region=True
    )
    for s in range(3):
        for c in range(4):
            (_, _), ref_resid = upstream.remove_profile1d(
                cube[s, c], s, c, template, pulse_region
            )
            np.testing.assert_allclose(resid[s, c], ref_resid,
                                       rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("trial", range(12))
def test_randomized_upstream_fuzz(upstream, trial):
    """Property sweep: random geometry, RFI mix, thresholds, pulse regions —
    the upstream script and the numpy oracle must produce identical final
    weights on every draw."""
    rng = np.random.default_rng(5000 + trial)
    nsub = int(rng.integers(2, 14))
    nchan = int(rng.integers(2, 18))
    nbin = int(rng.choice([8, 16, 32, 64]))
    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin,
        n_rfi_cells=int(rng.integers(0, 5)),
        n_rfi_channels=int(rng.integers(0, 2)),
        n_rfi_subints=int(rng.integers(0, 2)),
        n_prezapped=int(rng.integers(0, max(1, nsub * nchan // 4))),
        rfi_strength=float(rng.uniform(10, 80)),
        pulse_snr=float(rng.uniform(3, 50)),
        seed=int(rng.integers(0, 2 ** 31)),
    )
    pulse_region = [0, 0, 1]
    if rng.random() < 0.4:
        a, b = sorted(rng.integers(0, nbin, size=2).tolist())
        pulse_region = [float(rng.uniform(0, 1)), float(a), float(b)]
    args = ref_args(
        chanthresh=float(rng.uniform(2.5, 8)),
        subintthresh=float(rng.uniform(2.5, 8)),
        max_iter=int(rng.integers(1, 7)),
        pulse_region=pulse_region,
        bad_chan=float(rng.choice([1.0, rng.uniform(0.2, 0.9)])),
        bad_subint=float(rng.choice([1.0, rng.uniform(0.2, 0.9)])),
    )
    ref_weights = run_upstream(upstream, ar, args)
    res = clean_archive(ar.clone(), _config_from_args(args))
    np.testing.assert_array_equal(res.final_weights, ref_weights)


def test_cli_output_naming_matches_upstream_main(upstream, tmp_path, monkeypatch):
    """End-to-end through the upstream ``main``: the fake archive loads from
    the framework's npz container, the default and 'std' output-name rules
    (reference :48-58) must match the framework CLI's (cli.py:output_name)."""
    from iterative_cleaner_tpu.cli import output_name
    from iterative_cleaner_tpu.io import save_archive

    ar, _ = make_synthetic_archive(seed=9, nsub=6, nchan=8, nbin=32,
                                   n_rfi_cells=2)
    path = str(tmp_path / "obs1.npz")
    save_archive(ar, path)
    monkeypatch.chdir(tmp_path)

    written = []
    monkeypatch.setattr(fake_psrchive.FakeArchive, "unload",
                        lambda self, p: written.append(p))
    for output in ("", "std"):
        args = ref_args(archive=[path], output=output)
        upstream.main(args)

    loaded = fake_psrchive.Archive_load(path)._ar
    assert written[0] == path + "_cleaned.ar"
    assert written[1] == "%s.%.3f.%f.ar" % (
        loaded.source, loaded.centre_freq_mhz, loaded.mjd_mid)
    # the framework CLI applies the same rules, with the container extension
    # instead of .ar (it cannot write .ar without psrchive)
    for upstream_name, output in zip(written, ("", "std")):
        ours = output_name(loaded, ref_args(archive=[path], output=output), path)
        assert ours == upstream_name[: -len(".ar")] + ".npz"

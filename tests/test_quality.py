"""Cleaning-quality regression gate (VERDICT r2 #6).

The parity suite proves the framework matches the reference; these tests
prove the cleaning is *good*: zap precision and per-morphology recall
against the synthetic generator's injected truth
(iterative_cleaner_tpu/utils/quality.py), asserted as floors for both
models on both backends.  The reference relied on external thesis
validation for this (SURVEY.md §4); the framework gates it in CI.

Floors are set from measured behaviour (2026-07-30): at the default
40-sigma injections every model/backend scores 1.0 across the board; at
5-sigma the detector starts missing borderline cells (worst measured
recall ~0.82).  The floors leave slack so the gate catches detector
regressions, not noise.
"""

import numpy as np
import pytest

from iterative_cleaner_tpu.backends import clean_archive
from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
from iterative_cleaner_tpu.models.quicklook import clean_archive_quicklook
from iterative_cleaner_tpu.utils.quality import zap_quality

MODELS = {
    "surgical_scrub": clean_archive,
    "quicklook": clean_archive_quicklook,
}


def _quality(model, backend, seed, **gen_kw):
    ar, truth = make_synthetic_archive(
        nsub=32, nchan=64, nbin=128, seed=seed, n_rfi_cells=20,
        n_rfi_channels=3, n_rfi_subints=2, n_prezapped=30, **gen_kw)
    cfg = CleanConfig(backend=backend,
                      **({"dtype": "float64"} if backend == "jax" else {}))
    res = MODELS[model](ar.clone(), cfg)
    return zap_quality(res.final_weights, truth)


@pytest.mark.parametrize("model", sorted(MODELS))
@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_quality_floors_strong_rfi(model, backend):
    """Default-strength injections: every morphology essentially fully
    zapped, nothing clean lost."""
    for seed in (0, 1):
        q = _quality(model, backend, seed)
        assert q["precision"] >= 0.95, q
        assert q["recall_cell"] >= 0.95, q
        assert q["recall_channel"] >= 0.95, q
        assert q["recall_subint"] >= 0.95, q
        assert q["false_zap_frac"] <= 0.01, q


def test_quality_floors_borderline_rfi_surgical():
    """5-sigma injections sit at the detection edge: the gate demands the
    flagship iterative model still catches a solid majority without false
    zaps.  quicklook is deliberately excluded here — its single template-
    free pass leaves the pulse inflating the scaler populations, so
    borderline RFI is out of its design envelope (measured recall collapses
    below ~8 sigma; that triage tradeoff is documented in models/quicklook)
    — its gate is the strong-RFI test above."""
    for seed in (0, 1):
        q = _quality("surgical_scrub", "numpy", seed, rfi_strength=5.0)
        assert q["precision"] >= 0.9, q
        assert q["recall_cell"] >= 0.6, q
        assert q["recall_channel"] >= 0.6, q
        assert q["recall_subint"] >= 0.6, q
        assert q["false_zap_frac"] <= 0.02, q


def test_quality_excludes_prezapped_cells():
    """Prezapped cells stay out of both sides of every metric: an archive
    whose only 'zaps' are the prezaps scores no precision hit."""
    ar, truth = make_synthetic_archive(nsub=8, nchan=8, nbin=32, seed=3,
                                       n_rfi_cells=0, n_rfi_channels=0,
                                       n_rfi_subints=0, n_prezapped=10)
    q = zap_quality(ar.weights, truth)  # uncleaned: only prezaps are zero
    assert q["precision"] is None       # no live cells zapped at all
    assert q["recall_cell"] is None and q["recall_channel"] is None
    assert q["false_zap_frac"] == 0.0

"""Cleaning-quality regression gate (VERDICT r2 #6).

The parity suite proves the framework matches the reference; these tests
prove the cleaning is *good*: zap precision and per-morphology recall
against the synthetic generator's injected truth
(iterative_cleaner_tpu/utils/quality.py), asserted as floors for both
models on both backends.  The reference relied on external thesis
validation for this (SURVEY.md §4); the framework gates it in CI.

Floors are set from measured behaviour (2026-07-30): at the default
40-sigma injections every model/backend scores 1.0 across the board; at
5-sigma the detector starts missing borderline cells (worst measured
recall ~0.82).  The floors leave slack so the gate catches detector
regressions, not noise.
"""

import json
import os

import numpy as np
import pytest

from iterative_cleaner_tpu.backends import clean_archive
from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
from iterative_cleaner_tpu.models.quicklook import clean_archive_quicklook
from iterative_cleaner_tpu.utils.quality import zap_quality

MODELS = {
    "surgical_scrub": clean_archive,
    "quicklook": clean_archive_quicklook,
}


def _quality(model, backend, seed, **gen_kw):
    ar, truth = make_synthetic_archive(
        nsub=32, nchan=64, nbin=128, seed=seed, n_rfi_cells=20,
        n_rfi_channels=3, n_rfi_subints=2, n_prezapped=30, **gen_kw)
    cfg = CleanConfig(backend=backend,
                      **({"dtype": "float64"} if backend == "jax" else {}))
    res = MODELS[model](ar.clone(), cfg)
    return zap_quality(res.final_weights, truth)


@pytest.mark.parametrize("model", sorted(MODELS))
@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_quality_floors_strong_rfi(model, backend):
    """Default-strength injections: every morphology essentially fully
    zapped, nothing clean lost."""
    for seed in (0, 1):
        q = _quality(model, backend, seed)
        assert q["precision"] >= 0.95, q
        assert q["recall_cell"] >= 0.95, q
        assert q["recall_channel"] >= 0.95, q
        assert q["recall_subint"] >= 0.95, q
        assert q["false_zap_frac"] <= 0.01, q


def test_quality_floors_borderline_rfi_surgical():
    """5-sigma injections sit at the detection edge: the gate demands the
    flagship iterative model still catches a solid majority without false
    zaps.  quicklook is deliberately excluded here — its single template-
    free pass leaves the pulse inflating the scaler populations, so
    borderline RFI is out of its design envelope (measured recall collapses
    below ~8 sigma; that triage tradeoff is documented in models/quicklook)
    — its gate is the strong-RFI test above."""
    for seed in (0, 1):
        q = _quality("surgical_scrub", "numpy", seed, rfi_strength=5.0)
        assert q["precision"] >= 0.9, q
        assert q["recall_cell"] >= 0.6, q
        assert q["recall_channel"] >= 0.6, q
        assert q["recall_subint"] >= 0.6, q
        assert q["false_zap_frac"] <= 0.02, q


def test_quality_excludes_prezapped_cells():
    """Prezapped cells stay out of both sides of every metric: an archive
    whose only 'zaps' are the prezaps scores no precision hit."""
    ar, truth = make_synthetic_archive(nsub=8, nchan=8, nbin=32, seed=3,
                                       n_rfi_cells=0, n_rfi_channels=0,
                                       n_rfi_subints=0, n_prezapped=10)
    q = zap_quality(ar.weights, truth)  # uncleaned: only prezaps are zero
    assert q["precision"] is None       # no live cells zapped at all
    assert q["recall_cell"] is None and q["recall_channel"] is None
    assert q["false_zap_frac"] == 0.0


# --- borderline recall curve (VERDICT r3 #8) -------------------------------

# 4.25/4.5/4.75/5.5 (VERDICT r4 #5) sample the sigmoid's steep section
# around the 5-sigma operating point — the strengths where a borderline-
# behaviour shift from a kernel change would actually bite.
CURVE_STRENGTHS = (3.0, 4.0, 4.25, 4.5, 4.75, 5.0, 5.5, 6.0, 8.0, 40.0)
CURVE_GOLDEN = os.path.join(os.path.dirname(__file__), "goldens",
                            "quality_recall_curve.json")


def _recall_curve():
    """Per-morphology recall vs injected strength, averaged over 2 seeds
    (numpy backend: deterministic, platform-independent — the jax engines
    are tied to it by the parity suite)."""
    curve = {}
    for s in CURVE_STRENGTHS:
        qs = [_quality("surgical_scrub", "numpy", seed, rfi_strength=s)
              for seed in (0, 1)]
        curve[str(s)] = {
            k: round(float(np.mean([q[k] for q in qs])), 4)
            for k in ("precision", "recall_cell", "recall_channel",
                      "recall_subint", "false_zap_frac")}
    return curve


def test_borderline_recall_curve():
    """Sweep injected strength across the 5-sigma detection threshold and
    pin the whole recall curve exactly (the committed artifact,
    regenerate with ICLEAN_REGEN_GOLDENS=1): a kernel/semantics change
    that shifts *borderline* behaviour — invisible to the strong-RFI
    floors — moves one of these integer-ratio recalls and fails here
    visibly.  Measured shape (2026-07-30): sigmoid from
    recall_cell 0.39 @ 3-sigma through 0.92 @ 5 to 1.0 @ >= 6, channel
    recall the slowest riser (0.11 @ 3), precision 1.0 with zero false
    zaps at EVERY strength."""
    curve = _recall_curve()

    # shape: recall never decreases with injection strength...
    for k in ("recall_cell", "recall_channel", "recall_subint"):
        vals = [curve[str(s)][k] for s in CURVE_STRENGTHS]
        assert all(b >= a for a, b in zip(vals, vals[1:])), (k, vals)
        assert vals[-1] >= 0.999, (k, vals)
    # ...and surgical precision costs nothing at any strength
    for s in CURVE_STRENGTHS:
        assert curve[str(s)]["precision"] == 1.0, curve[str(s)]
        assert curve[str(s)]["false_zap_frac"] == 0.0, curve[str(s)]

    if os.environ.get("ICLEAN_REGEN_GOLDENS"):
        os.makedirs(os.path.dirname(CURVE_GOLDEN), exist_ok=True)
        with open(CURVE_GOLDEN, "w") as f:
            json.dump(curve, f, indent=1, sort_keys=True)
            f.write("\n")
    with open(CURVE_GOLDEN) as f:
        want = json.load(f)
    assert curve == want, "recall curve moved; if intentional, regenerate " \
        "with ICLEAN_REGEN_GOLDENS=1 and commit the diff"

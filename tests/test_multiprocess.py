"""Real multi-process distributed execution (SURVEY.md section 5,
"Distributed communication backend"; VERDICT round-1 row 30).

Round 1 only ever exercised the jax.distributed bootstrap and the hybrid
mesh on virtual devices inside ONE process.  This test launches two
actual OS processes, each owning 4 virtual CPU devices, bootstraps them
through :func:`iterative_cleaner_tpu.parallel.distributed.initialize`
(coordinator on localhost), runs the sharded cleaning program over the
8-device *global* mesh — so the scaler-median reductions really cross the
process boundary through the distributed runtime — and checks each
process's addressable shards of the final mask against a single-process
reference clean.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from iterative_cleaner_tpu.parallel.distributed import initialize
from iterative_cleaner_tpu.engine.loop import (
    clean_dedispersed_jax, prepare_cube_jax)
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
from jax.sharding import Mesh

port, pid = sys.argv[1], int(sys.argv[2])
ctx = initialize(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=2, process_id=pid)
assert ctx.process_count == 2, ctx
assert ctx.local_devices == 4, ctx
assert ctx.global_devices == 8, ctx

# identical archive in both processes (same seed)
ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=11,
                               dtype=np.float64)
cube = jnp.asarray(ar.total_intensity())
weights = jnp.asarray(ar.weights)
freqs = jnp.asarray(ar.freqs_mhz)

def full(cube, weights, freqs):
    ded, shifts = prepare_cube_jax(
        cube, freqs, ar.dm, ar.centre_freq_mhz, ar.period_s,
        baseline_duty=0.15, rotation="roll")
    outs = clean_dedispersed_jax(
        ded, weights, shifts, max_iter=3, chanthresh=5.0, subintthresh=5.0,
        pulse_slice=(0, 0), pulse_scale=1.0, pulse_active=False,
        rotation="roll", fft_mode="dft")
    return outs.final_weights

# single-process reference on this process's local devices only
ref = np.asarray(jax.jit(full)(cube, weights, freqs))

mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("sub", "chan"))
csh = NamedSharding(mesh, P("sub", "chan", None))
wsh = NamedSharding(mesh, P("sub", "chan"))
rep = NamedSharding(mesh, P())
fn = jax.jit(full, in_shardings=(csh, wsh, rep), out_shardings=wsh)
with mesh:
    out = fn(jax.device_put(cube, csh), jax.device_put(weights, wsh),
             jax.device_put(freqs, rep))
    out.block_until_ready()

# compare only this process's addressable shards against the reference
n_checked = 0
for shard in out.addressable_shards:
    got = np.asarray(shard.data)
    r0, c0 = (idx.start or 0 for idx in shard.index)
    want = ref[r0:r0 + got.shape[0], c0:c0 + got.shape[1]]
    assert np.array_equal(got == 0, want == 0), (pid, shard.index)
    assert np.allclose(got, want, rtol=1e-12), (pid, shard.index)
    n_checked += 1
assert n_checked == 4, n_checked
print(f"WORKER_OK pid={pid} shards={n_checked}", flush=True)
"""


_HYBRID_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
from iterative_cleaner_tpu.parallel.distributed import (
    clean_archives_hybrid, hybrid_batch_cell_mesh, initialize)
from iterative_cleaner_tpu.parallel.sharding import clean_cube_sharded
from iterative_cleaner_tpu.parallel.mesh import cell_mesh
from iterative_cleaner_tpu.backends.jax_backend import clean_cube

port, pid = sys.argv[1], int(sys.argv[2])
ctx = initialize(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=2, process_id=pid)
assert ctx.global_devices == 8, ctx

cfg = CleanConfig(max_iter=2, rotation="roll", fft_mode="dft")
archives = [make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=s,
                                   dtype=np.float64)[0] for s in (1, 2, 3)]

# library path 1: one big archive over the global ('sub','chan') mesh
ar = archives[0]
args = (ar.total_intensity(), ar.weights, ar.freqs_mhz, ar.dm,
        ar.centre_freq_mhz, ar.period_s)
ref = clean_cube(*args, cfg)  # local single-process reference
res = clean_cube_sharded(*args, cfg, cell_mesh(8))
assert np.array_equal(ref.final_weights, res.final_weights), "sharded"
assert ref.loops == res.loops

# library path 2: 3 archives (one padded) over the hybrid batch x cell mesh
hmesh = hybrid_batch_cell_mesh(batch=2)
results = clean_archives_hybrid(archives, cfg, hmesh)
assert len(results) == 3
for a, r in zip(archives, results):
    args = (a.total_intensity(), a.weights, a.freqs_mhz, a.dm,
            a.centre_freq_mhz, a.period_s)
    want = clean_cube(*args, cfg)
    assert np.array_equal(want.final_weights, r.final_weights), "hybrid"
    assert want.loops == r.loops
print(f"WORKER_OK pid={pid}", flush=True)
"""


def _run_two_process(worker_src):
    import socket

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers pin their own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(port), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"WORKER_OK pid={pid}" in out, out[-2000:]


def test_two_process_sharded_clean(tmp_path):
    _run_two_process(_WORKER)


def test_two_process_library_paths(tmp_path):
    """The production library entry points themselves — clean_cube_sharded
    over the global cell mesh and clean_archives_hybrid over the
    batch x cell hybrid mesh — must work across real process boundaries:
    outputs sharded over both processes gather via
    parallel.distributed.host_fetch before host reads."""
    _run_two_process(_HYBRID_WORKER)

"""Journal fsck (analysis/journal_fsck.py): the protocol state machine
over on-disk journals.

Every journal the FleetJournal API itself produces must fsck clean —
including after compaction and after a torn-tail heal (a warning, never
an error).  Synthetic corruptions exercise each checker: grammar
(foreign schema, unknown events, missing typed fields, mis-keyed cache
lines), the request lifecycle state machine (after-terminal, duplicate
terminal, rank regression — the admit-ordering hazard), and lease
monotonicity for claim/member stamps.
"""

import json

import pytest

from iterative_cleaner_tpu.analysis.journal_fsck import (
    FsckReport,
    fsck_journal,
    fsck_text,
    record_fsck,
)
from iterative_cleaner_tpu.resilience.journal import SCHEMA, FleetJournal
from iterative_cleaner_tpu.telemetry.registry import MetricsRegistry


def _line(**fields) -> str:
    entry = {"schema": SCHEMA}
    entry.update(fields)
    return json.dumps(entry) + "\n"


def _kinds(issues):
    return sorted({i.kind for i in issues})


# ------------------------------------------------------ API-produced text

def _real_journal(tmp_path) -> FleetJournal:
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    j.record_request("r1", "accepted", paths=["/a.npz"], tenant="t")
    j.record_request("r1", "running")
    j.try_claim("req:r1", host=1, nonce="n1", ttl_s=30.0)
    j.heartbeat("req:r1", host=1, nonce="n1", ttl_s=30.0)
    j.record_member("mA", "join", host=1, ttl_s=30.0)
    j.record_member("mA", "hb", host=1, ttl_s=30.0)
    j.record_request("r1", "done")
    j.release("req:r1", host=1, nonce="n1")
    j.record_host_stats(1, {"cleaned": 3})
    return j


def test_api_produced_journal_fscks_clean(tmp_path):
    j = _real_journal(tmp_path)
    report = fsck_journal(j.path)
    assert report.ok and not report.issues
    assert report.counts["req"] == 3
    assert report.counts["claim"] == 3
    assert report.counts["member"] == 2
    assert report.counts["stats"] == 1


def test_compacted_journal_still_fscks_clean(tmp_path):
    j = _real_journal(tmp_path)
    j.compact()
    report = fsck_journal(j.path)
    assert report.ok, [i.render() for i in report.issues]


def test_torn_tail_is_a_warning_not_an_error(tmp_path):
    j = _real_journal(tmp_path)
    with open(j.path, "a") as f:
        f.write('{"schema": "icln-fleet-journal/1", "event": "mem')
    report = fsck_journal(j.path)
    assert report.ok  # warnings never fail the gate
    assert [i.kind for i in report.warnings] == ["torn-line"]
    assert "torn tail" in report.warnings[0].message
    # the next append heals it; a healed mid-file torn line still warns
    j.record_member("mB", "join", host=2, ttl_s=30.0)
    report = fsck_journal(j.path)
    assert report.ok
    assert "healed" in report.warnings[0].message


def test_missing_journal_is_an_error(tmp_path):
    report = fsck_journal(str(tmp_path / "never-written.jsonl"))
    assert not report.ok


# ------------------------------------------------- request state machine

def test_state_after_terminal_is_flagged():
    text = (_line(event="req", req="x", state="done")
            + _line(event="req", req="x", state="accepted"))
    issues, _, _ = fsck_text(text)
    assert _kinds(issues) == ["state-machine"]
    assert "after terminal" in issues[0].message


def test_duplicate_terminal_is_flagged():
    text = (_line(event="req", req="x", state="failed")
            + _line(event="req", req="x", state="failed"))
    issues, _, _ = fsck_text(text)
    assert "duplicate terminal" in issues[0].message


def test_rank_regression_names_the_admit_ordering_hazard():
    text = (_line(event="req", req="x", state="running")
            + _line(event="req", req="x", state="accepted"))
    issues, _, _ = fsck_text(text)
    assert _kinds(issues) == ["state-machine"]
    assert "admit-ordering" in issues[0].message


def test_normal_lifecycle_and_idempotent_running_are_clean():
    text = (_line(event="req", req="x", state="accepted")
            + _line(event="req", req="x", state="running")
            + _line(event="req", req="x", state="running")  # re-poll
            + _line(event="req", req="x", state="done"))
    issues, _, _ = fsck_text(text)
    assert issues == []


# ------------------------------------------------------------- grammar

@pytest.mark.parametrize("text,expect", [
    ('["not", "an", "object"]\n', "not an object"),
    (_line(event="req", req="x", state="accepted").replace(
        SCHEMA, "someone-elses/9"), "foreign or missing schema"),
    (_line(event="wat"), "unknown event"),
    (_line(event="req", req="x", state="paused"), "not one of"),
    (_line(event="claim", work="w", host="one", nonce="n",
           state="claim", t=1.0, ttl=1.0), "host"),
    (_line(event="claim", work="w", host=1, nonce="n", state="claim",
           t=1.0, ttl=-2.0), "negative"),
    (_line(event="done", path="/a", sig="s", config="c", out="/o"),
        "out_sig"),
    (_line(event="stats", host=1, counters={"n": True}), "not numeric"),
    (_line(event="cache", key="wrong", path="/a", sig="s", config="c",
           out="/o", out_sig="os"), "mis-keyed"),
])
def test_grammar_violations_are_errors(text, expect):
    issues, _, _ = fsck_text(text)
    errors = [i for i in issues if i.severity == "error"]
    assert errors, f"expected an error mentioning {expect!r}"
    assert any(expect in i.message for i in errors)


def test_blank_lines_are_ignored():
    text = ("\n\n" + _line(event="req", req="x", state="accepted") + "\n")
    issues, counts, _ = fsck_text(text)
    assert issues == [] and counts["req"] == 1


# ----------------------------------------------------- lease monotonicity

def test_backwards_lease_stamp_is_flagged():
    text = (_line(event="claim", work="w", host=1, nonce="a",
                  state="claim", t=100.0, ttl=30.0)
            + _line(event="claim", work="w", host=2, nonce="b",
                    state="claim", t=90.0, ttl=30.0))
    issues, _, _ = fsck_text(text)
    assert _kinds(issues) == ["lease-monotonicity"]


def test_skew_tolerance_allows_small_backwards_stamps():
    text = (_line(event="member", member="m", host=1, state="join",
                  t=100.0, ttl=30.0)
            + _line(event="member", member="m", host=1, state="hb",
                    t=99.5, ttl=30.0))
    issues, _, _ = fsck_text(text, skew_s=1.0)
    assert issues == []
    issues, _, _ = fsck_text(text)
    assert _kinds(issues) == ["lease-monotonicity"]


# --------------------------------------------------------------- surfaces

def test_report_render_and_dict_roundtrip(tmp_path):
    j = _real_journal(tmp_path)
    report = fsck_journal(j.path)
    assert "ok" in report.render_text()
    d = report.to_dict()
    assert d["ok"] and d["n_lines"] == report.n_lines


def test_record_fsck_publishes_metrics(tmp_path):
    j = _real_journal(tmp_path)
    with open(j.path, "a") as f:
        f.write('{"schema": "icln-fleet-journal/1", "event": "mem')
    reg = MetricsRegistry()
    record_fsck(reg, fsck_journal(j.path))
    assert reg.gauges["journal_fsck_ok"] == 1
    assert reg.gauges["journal_fsck_lines"] > 0
    assert reg.counters["journal_fsck_warnings{kind=torn-line}"] == 1

    bad = FsckReport(path="x")
    bad.issues, bad.counts, bad.n_lines = fsck_text(
        _line(event="req", req="x", state="done")
        + _line(event="req", req="x", state="running"))
    record_fsck(reg, bad)
    assert reg.gauges["journal_fsck_ok"] == 0
    assert reg.counters["journal_fsck_errors{kind=state-machine}"] == 1

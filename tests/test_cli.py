"""CLI surface tests: flags, naming rules, log, plot, residual output
(reference /root/reference/iterative_cleaner.py:16-62,148-177)."""

import os

import numpy as np
import pytest

from iterative_cleaner_tpu.cli import build_parser, clean_one, main
from iterative_cleaner_tpu.io import load_archive, make_synthetic_archive, save_archive


@pytest.fixture()
def archive_file(tmp_path):
    ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=64, seed=0)
    path = tmp_path / "obs.npz"
    save_archive(ar, str(path))
    return str(path)


def test_flag_surface_defaults():
    args = build_parser().parse_args(["x.npz"])
    assert args.chanthresh == 5 and args.subintthresh == 5
    assert args.max_iter == 5
    assert args.pulse_region == [0, 0, 1]
    assert args.output == ""
    assert args.bad_chan == 1 and args.bad_subint == 1
    assert not args.print_zap and not args.unload_res and not args.pscrunch
    assert not args.quiet and not args.no_log and not args.memory
    assert args.backend == "jax"


def test_short_flags_parse():
    args = build_parser().parse_args(
        ["-c", "3", "-s", "4", "-m", "2", "-z", "-u", "-p", "-q", "-l",
         "-r", "0.5", "10", "20", "-o", "out.npz", "a.npz", "b.npz"]
    )
    assert args.chanthresh == 3 and args.subintthresh == 4
    assert args.max_iter == 2 and args.pulse_region == [0.5, 10, 20]
    assert args.archive == ["a.npz", "b.npz"]


def test_default_output_naming(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "--backend", "numpy", archive_file])
    out = archive_file + "_cleaned.npz"
    assert os.path.exists(out)
    cleaned = load_archive(out)
    assert cleaned.data.shape == load_archive(archive_file).data.shape


def test_std_output_naming(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    ar = load_archive(archive_file)
    main(["-q", "-l", "--backend", "numpy", "-o", "std", archive_file])
    expect = "%s.%.3f.%f%s" % (ar.source, ar.centre_freq_mhz, ar.mjd_mid, ".npz")
    assert os.path.exists(os.path.join(str(tmp_path), expect))


def test_explicit_output_and_log(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "--backend", "numpy", "-o", "c.npz", archive_file])
    assert os.path.exists("c.npz")
    assert os.path.exists("clean.log")
    text = open("clean.log").read()
    assert "Cleaned" in text and "required loops=" in text


def test_no_log_flag(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "-l", "--backend", "numpy", archive_file])
    assert not os.path.exists("clean.log")


def test_zap_plot(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "-l", "-z", "--backend", "numpy", archive_file])
    pngs = [f for f in os.listdir(".") if f.endswith(".png")]
    assert len(pngs) == 1
    # argparse leaves the untouched default as int 5, so the reference's
    # "%s_%s_%s.png" pattern yields "_5_5.png"
    assert pngs[0].endswith("_5_5.png")


def test_residual_unload(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "-l", "-u", "--backend", "numpy", archive_file])
    residuals = [f for f in os.listdir(".") if "_residual_" in f]
    assert len(residuals) == 1
    res = load_archive(residuals[0])
    assert res.npol == 1


def test_progress_output(archive_file, tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    main(["-l", "--backend", "numpy", archive_file])
    out = capsys.readouterr().out
    assert "Total number of profiles: 128" in out
    assert "Loop: 1" in out
    assert "Differences to previous weights:" in out
    assert ("RFI removal stops after" in out
            or "Cleaning was interrupted" in out)
    assert "Cleaned archive:" in out


def test_quiet_suppresses_output(archive_file, tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    main(["-q", "-l", "--backend", "numpy", archive_file])
    assert capsys.readouterr().out == ""


def test_weights_written_back(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "-l", "--backend", "jax", "-o", "j.npz", archive_file])
    cleaned = load_archive("j.npz")
    original = load_archive(archive_file)
    # data unchanged, weights zapped somewhere
    np.testing.assert_allclose(cleaned.data, original.data, rtol=1e-6)
    assert (cleaned.weights == 0).sum() > 0


def test_prefetch_matches_sequential(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    paths = []
    for i in range(3):
        ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=i)
        p = tmp_path / f"obs{i}.npz"
        save_archive(ar, str(p))
        paths.append(str(p))
    assert main(["-q", "-l", "--prefetch", "2"] + paths) == 0
    pre = [np.asarray(load_archive(p + "_cleaned.npz").weights) for p in paths]
    for p in paths:
        os.remove(p + "_cleaned.npz")
    assert main(["-q", "-l"] + paths) == 0
    seq = [np.asarray(load_archive(p + "_cleaned.npz").weights) for p in paths]
    for a, b in zip(pre, seq):
        np.testing.assert_array_equal(a, b)


def test_prefetch_keep_going_isolates_bad_archive(tmp_path, monkeypatch,
                                                  capsys):
    monkeypatch.chdir(tmp_path)
    ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=0)
    good1, good2 = str(tmp_path / "a.npz"), str(tmp_path / "c.npz")
    save_archive(ar, good1)
    save_archive(ar, good2)
    bad = str(tmp_path / "b.npz")
    with open(bad, "wb") as f:
        f.write(b"not an archive")
    rc = main(["-q", "-l", "--prefetch", "1", "--keep_going",
               good1, bad, good2])
    assert rc == 1
    assert os.path.exists(good1 + "_cleaned.npz")
    assert os.path.exists(good2 + "_cleaned.npz")
    assert "ERROR cleaning" in capsys.readouterr().err


def test_compile_cache_populates_and_cross_process_reload(tmp_path,
                                                          monkeypatch):
    """--compile_cache DIR: the first run writes compiled programs into
    the persistent cache, and a FRESH PROCESS reloading from it (the
    whole point — in-process runs would hit the jit cache anyway)
    produces identical masks.  On a real TPU the reload skips the 20-40s
    remote compiles."""
    import subprocess
    import sys

    monkeypatch.chdir(tmp_path)
    ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=0)
    save_archive(ar, "o.npz")
    cache = str(tmp_path / "jitcache")

    # both legs run in FRESH processes: in-process, jax's in-memory jit
    # cache (warmed by earlier tests compiling these very shapes) would
    # skip compilation entirely and never touch the persistent cache —
    # and an in-process jax.config.update would leak into later tests
    from tests.conftest import repo_subprocess_env

    env = repo_subprocess_env()

    def run(out_name):
        return subprocess.run(
            [sys.executable, "-m", "iterative_cleaner_tpu", "-q", "-l",
             "--compile_cache", cache, "-o", out_name, "o.npz"],
            env=env, cwd=str(tmp_path), capture_output=True, text=True,
            timeout=300)

    proc = run("first.npz")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.listdir(cache), "persistent compilation cache stayed empty"
    proc = run("second.npz")
    assert proc.returncode == 0, proc.stderr[-2000:]
    np.testing.assert_array_equal(
        np.asarray(load_archive("second.npz").weights),
        np.asarray(load_archive("first.npz").weights))


def test_platform_env_override(tmp_path, monkeypatch):
    """ICLEAN_PLATFORM forces the jax platform (no-op here since conftest
    already pinned cpu, but the path must parse and clean successfully)."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("ICLEAN_PLATFORM", "cpu")
    ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=0)
    save_archive(ar, str(tmp_path / "o.npz"))
    assert main(["-q", "-l", str(tmp_path / "o.npz")]) == 0


def test_batch_matches_sequential(tmp_path, monkeypatch):
    """--batch groups equal-shaped runs; masks must equal the sequential
    path even across a shape change mid-list."""
    monkeypatch.chdir(tmp_path)
    paths = []
    for i in range(3):  # same shape
        ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=i)
        p = str(tmp_path / f"s{i}.npz")
        save_archive(ar, p)
        paths.append(p)
    ar, _ = make_synthetic_archive(nsub=8, nchan=12, nbin=32, seed=7)
    p = str(tmp_path / "big.npz")
    save_archive(ar, p)
    paths.append(p)
    assert main(["-q", "-l", "--batch", "2"] + paths) == 0
    batched = [np.asarray(load_archive(p + "_cleaned.npz").weights)
               for p in paths]
    for p in paths:
        os.remove(p + "_cleaned.npz")
    assert main(["-q", "-l"] + paths) == 0
    for p, b in zip(paths, batched):
        np.testing.assert_array_equal(
            b, np.asarray(load_archive(p + "_cleaned.npz").weights))


def test_batch_buckets_interleaved_shapes(tmp_path, monkeypatch):
    """VERDICT r4 #6: an interleaved input list (a.6x10, b.8x12, a.6x10,
    b.8x12) must be bucketed globally — one full group per shape — not
    split at every consecutive shape change into four under-filled
    single-archive programs."""
    from iterative_cleaner_tpu.parallel import batch as batch_mod

    monkeypatch.chdir(tmp_path)
    paths = []
    for i, (ns, nc) in enumerate([(6, 10), (8, 12), (6, 10), (8, 12)]):
        ar, _ = make_synthetic_archive(nsub=ns, nchan=nc, nbin=32, seed=i)
        p = str(tmp_path / f"i{i}.npz")
        save_archive(ar, p)
        paths.append(p)
    groups = []
    real = batch_mod.clean_archives_batched

    def spy(ars, cfg, mesh=None, **kw):
        groups.append([(a.nsub, a.nchan) for a in ars])
        return real(ars, cfg, mesh, **kw)

    monkeypatch.setattr(batch_mod, "clean_archives_batched", spy)
    assert main(["-q", "-l", "--batch", "2"] + paths) == 0
    assert groups == [[(6, 10), (6, 10)], [(8, 12), (8, 12)]]
    # per-archive outputs all present despite the reordering
    for p in paths:
        assert os.path.exists(p + "_cleaned.npz")


def test_bucket_by_shape_prepass(tmp_path):
    """Stable bucketing: first-appearance bucket order, per-shape input
    order preserved, unreadable paths kept (at the end) for the load loop
    to surface."""
    from iterative_cleaner_tpu.cli import _bucket_by_shape

    mk = {}
    for name, (ns, nc) in [("a0", (6, 10)), ("b0", (8, 12)),
                           ("a1", (6, 10)), ("b1", (8, 12))]:
        ar, _ = make_synthetic_archive(nsub=ns, nchan=nc, nbin=32, seed=0)
        p = str(tmp_path / f"{name}.npz")
        save_archive(ar, p)
        mk[name] = p
    bad = str(tmp_path / "bad.npz")
    with open(bad, "wb") as f:
        f.write(b"not a zip")
    got = _bucket_by_shape([mk["a0"], bad, mk["b0"], mk["a1"], mk["b1"]])
    assert got == [mk["a0"], mk["a1"], mk["b0"], mk["b1"], bad]


def test_batch_incompatible_flags(tmp_path):
    with pytest.raises(SystemExit):
        main(["--batch", "2", "-u", str(tmp_path / "x.npz")])


def test_batch_rejects_numpy_backend(tmp_path):
    with pytest.raises(SystemExit):
        main(["--batch", "2", "--backend", "numpy", str(tmp_path / "x.npz")])


def test_fft_mode_flag_masks_match(archive_file, tmp_path, monkeypatch):
    """--fft_mode dft + the explicit fused/pallas impls must reproduce the
    default path's mask (the dft spectra are mathematically identical)."""
    monkeypatch.chdir(tmp_path)
    main(["-q", archive_file])
    main(["-q", "--fft_mode", "dft", "--stats_impl", "fused",
          "--median_impl", "pallas", "-o", str(tmp_path / "dft.npz"),
          archive_file])
    a = load_archive(archive_file + "_cleaned.npz")
    b = load_archive(str(tmp_path / "dft.npz"))
    np.testing.assert_array_equal(a.weights == 0, b.weights == 0)


def test_mesh_cell_masks_match_default(archive_file, tmp_path, monkeypatch):
    """--mesh cell shards one archive over all 8 virtual devices; the mask
    must match the single-device clean (CPU meshes need roll+dft)."""
    monkeypatch.chdir(tmp_path)
    main(["-q", "--rotation", "roll", "--fft_mode", "dft", archive_file])
    main(["-q", "--mesh", "cell", "--rotation", "roll", "--fft_mode", "dft",
          "-o", str(tmp_path / "meshed.npz"), archive_file])
    a = load_archive(archive_file + "_cleaned.npz")
    b = load_archive(str(tmp_path / "meshed.npz"))
    np.testing.assert_array_equal(a.weights, b.weights)


def test_mesh_batch_masks_match_plain_batch(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from iterative_cleaner_tpu.io import make_synthetic_archive, save_archive

    paths = []
    for s in range(3):
        ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=s)
        p = str(tmp_path / f"m{s}.npz")
        save_archive(ar, p)
        paths.append(p)
    main(["-q", "--batch", "3", "--rotation", "roll"] + paths)
    plain = [load_archive(p + "_cleaned.npz").weights for p in paths]
    main(["-q", "--batch", "3", "--rotation", "roll", "--mesh", "batch"]
         + paths)
    for p, w in zip(paths, plain):
        np.testing.assert_array_equal(
            load_archive(p + "_cleaned.npz").weights, w)


def test_mesh_incompatible_flags(tmp_path):
    for bad in (["--mesh", "cell", "--batch", "2"],
                ["--mesh", "cell", "-u"],
                ["--mesh", "cell", "--backend", "numpy"],
                ["--mesh", "batch"],                      # needs --batch
                ["--mesh", "cell", "--model", "quicklook"]):
        with pytest.raises(SystemExit):
            main(bad + [str(tmp_path / "x.npz")])


def test_stream_flag_matches_library_streaming(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io import make_synthetic_archive, save_archive
    from iterative_cleaner_tpu.parallel.streaming import clean_streaming

    ar, _ = make_synthetic_archive(nsub=24, nchan=16, nbin=32, seed=6)
    p = str(tmp_path / "long.npz")
    save_archive(ar, p)
    main(["-q", "--stream", "8", "--stream_mode", "online", "--rotation",
          "roll", "--fft_mode", "dft", p])
    want = clean_streaming(
        ar, 8, CleanConfig(rotation="roll", fft_mode="dft"), mode="online")
    got = load_archive(p + "_cleaned.npz")
    np.testing.assert_array_equal(got.weights == 0,
                                  want.final_weights == 0)


def test_stream_with_cell_mesh(tmp_path, monkeypatch):
    """--stream 8 --mesh cell: every tile sharded over the 8 virtual
    devices; masks match the unsharded streaming run."""
    monkeypatch.chdir(tmp_path)
    from iterative_cleaner_tpu.io import make_synthetic_archive, save_archive

    ar, _ = make_synthetic_archive(nsub=32, nchan=16, nbin=32, seed=7)
    p = str(tmp_path / "long2.npz")
    save_archive(ar, p)
    main(["-q", "--stream", "8", "--stream_mode", "online", "--rotation",
          "roll", "--fft_mode", "dft", p])
    plain = load_archive(p + "_cleaned.npz").weights
    main(["-q", "--stream", "8", "--stream_mode", "online", "--mesh", "cell",
          "--rotation", "roll", "--fft_mode", "dft",
          "-o", str(tmp_path / "meshed.npz"), p])
    np.testing.assert_array_equal(
        load_archive(str(tmp_path / "meshed.npz")).weights, plain)


def test_stream_exact_default_matches_whole(tmp_path, monkeypatch):
    """--stream's default mode is drift-free: masks identical to the
    whole-archive run, with and without --mesh cell."""
    monkeypatch.chdir(tmp_path)
    from iterative_cleaner_tpu.io import make_synthetic_archive, save_archive

    ar, _ = make_synthetic_archive(nsub=32, nchan=16, nbin=32, seed=7)
    p = str(tmp_path / "long3.npz")
    save_archive(ar, p)
    main(["-q", "--backend", "numpy", p])
    whole = load_archive(p + "_cleaned.npz").weights
    main(["-q", "--backend", "numpy", "--stream", "8",
          "-o", str(tmp_path / "exact.npz"), p])
    np.testing.assert_array_equal(
        load_archive(str(tmp_path / "exact.npz")).weights, whole)
    # exact + cell mesh: sharded tile work, same drift-free masks (mask
    # level: the sharded path runs float32 vs the float64 oracle above)
    main(["-q", "--stream", "8", "--mesh", "cell", "--rotation", "roll",
          "--fft_mode", "dft", "-o", str(tmp_path / "exact_mesh.npz"), p])
    meshed = load_archive(str(tmp_path / "exact_mesh.npz")).weights
    main(["-q", "--stream", "8", "--rotation", "roll", "--fft_mode", "dft",
          "-o", str(tmp_path / "exact_nomesh.npz"), p])
    np.testing.assert_array_equal(
        load_archive(str(tmp_path / "exact_nomesh.npz")).weights, meshed)


def test_stream_incompatible_flags(tmp_path):
    for bad in (["--stream", "8", "--batch", "2"],
                ["--stream", "8", "-u"],
                ["--stream", "8", "--record_history"],
                ["--stream", "8", "--model", "quicklook"],
                ["--stream", "8", "--checkpoint", str(tmp_path)]):
        with pytest.raises(SystemExit):
            main(bad + [str(tmp_path / "x.npz")])


def test_model_quicklook_cleans(archive_file, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    main(["-q", "--model", "quicklook", archive_file])
    out = archive_file + "_cleaned.npz"
    assert os.path.exists(out)
    cleaned = load_archive(out)
    orig = load_archive(archive_file)
    # single pass only ever zeroes weights, never restores
    pre = orig.weights == 0
    assert ((cleaned.weights == 0) & pre).sum() == pre.sum()
    np.testing.assert_array_equal(cleaned.data, orig.data)


def test_model_quicklook_incompatible_flags(tmp_path):
    for bad in (["--model", "quicklook", "--batch", "2"],
                ["--model", "quicklook", "-u"],
                ["--model", "quicklook", "--checkpoint", str(tmp_path)]):
        with pytest.raises(SystemExit):
            main(bad + [str(tmp_path / "x.npz")])


def test_model_quicklook_numpy_backend_matches_jax(archive_file, tmp_path,
                                                   monkeypatch):
    """quicklook has a float64 numpy oracle twin; at float64 the two
    backends must produce identical masks (the flagship's parity rule)."""
    monkeypatch.chdir(tmp_path)
    main(["-q", "--model", "quicklook", "--backend", "numpy",
          "-o", str(tmp_path / "np.npz"), archive_file])
    main(["-q", "--model", "quicklook", archive_file])
    a = load_archive(str(tmp_path / "np.npz"))
    b = load_archive(archive_file + "_cleaned.npz")
    np.testing.assert_array_equal(a.weights == 0, b.weights == 0)


def test_batch_keep_going_isolates_bad_archive(tmp_path, monkeypatch,
                                               capsys):
    monkeypatch.chdir(tmp_path)
    good = []
    for i in range(2):
        ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=i)
        p = str(tmp_path / f"g{i}.npz")
        save_archive(ar, p)
        good.append(p)
    bad = str(tmp_path / "bad.npz")
    with open(bad, "wb") as f:
        f.write(b"junk")
    rc = main(["-q", "-l", "--batch", "2", "--keep_going",
               good[0], bad, good[1]])
    assert rc == 1
    for p in good:
        assert os.path.exists(p + "_cleaned.npz")
    assert "ERROR cleaning" in capsys.readouterr().err


class TestTools:
    def test_selftest_passes(self, capsys, monkeypatch):
        from iterative_cleaner_tpu.tools import main as tools_main

        # skip the dead-tunnel subprocess probe (the suite is pinned to
        # CPU anyway; without this the probe burns its full timeout when
        # the machine's accelerator tunnel is down)
        monkeypatch.setenv("ICLEAN_PLATFORM", "cpu")
        assert tools_main(["selftest"]) == 0
        out = capsys.readouterr().out
        assert "OK: masks bit-identical" in out

    def test_info_and_convert_and_diff(self, tmp_path, monkeypatch, capsys):
        import json

        from iterative_cleaner_tpu.tools import main as tools_main

        monkeypatch.chdir(tmp_path)
        ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=0,
                                       dtype=np.float32)  # .icar stores f32
        save_archive(ar, "a.npz")
        assert tools_main(["info", "a.npz"]) == 0
        meta = json.loads(capsys.readouterr().out)
        assert (meta["nsub"], meta["nchan"], meta["nbin"]) == (6, 10, 32)

        assert tools_main(["convert", "a.npz", "a.icar"]) == 0
        b = load_archive("a.icar")
        np.testing.assert_array_equal(np.asarray(b.data), np.asarray(ar.data))

        # identical masks -> exit 0; after zapping a cell -> exit 1
        assert tools_main(["diff", "a.npz", "a.icar"]) == 0
        capsys.readouterr()
        ar2 = load_archive("a.npz")
        ar2.weights[0, 0] = 0.0
        save_archive(ar2, "b.npz")
        assert tools_main(["diff", "a.npz", "b.npz"]) == 1
        d = json.loads(capsys.readouterr().out)
        assert d["changed"] == 1 and d["newly_zapped"] == 1

    def test_sweep_grid(self, tmp_path, monkeypatch, capsys):
        """tools sweep: one JSON row per grid point; zap fraction is
        monotone non-increasing in the thresholds (a sanity property of
        the detector) and every row matches a direct clean."""
        import json

        from iterative_cleaner_tpu.backends import clean_archive
        from iterative_cleaner_tpu.config import CleanConfig
        from iterative_cleaner_tpu.tools import main as tools_main

        monkeypatch.chdir(tmp_path)
        ar, _ = make_synthetic_archive(nsub=8, nchan=16, nbin=32, seed=5,
                                       n_rfi_cells=4, n_prezapped=6)
        save_archive(ar, "o.npz")
        assert tools_main(["sweep", "o.npz", "--backend", "numpy",
                           "-c", "3", "8", "-s", "4"]) == 0
        rows = [json.loads(ln) for ln in
                capsys.readouterr().out.strip().splitlines()]
        assert len(rows) == 2
        assert rows[0]["rfi_frac"] >= rows[1]["rfi_frac"]  # c=3 vs c=8
        want = clean_archive(
            ar.clone(), CleanConfig(backend="numpy", chanthresh=8.0,
                                    subintthresh=4.0))
        assert rows[1]["rfi_frac"] == round(
            float((want.final_weights == 0).mean()), 6)
        assert rows[1]["loops"] == want.loops

    def test_diff_checkpoints(self, tmp_path, monkeypatch, capsys):
        import json

        monkeypatch.chdir(tmp_path)
        ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=0)
        save_archive(ar, "o.npz")
        assert main(["-q", "-l", "--checkpoint", "ck1", "o.npz"]) == 0
        assert main(["-q", "-l", "-o", "out2.npz", "--checkpoint", "ck2",
                     "o.npz"]) == 0
        from iterative_cleaner_tpu.tools import main as tools_main
        from iterative_cleaner_tpu.utils.checkpoint import checkpoint_path

        rc = tools_main(["diff", checkpoint_path("ck1", "o.npz"),
                         checkpoint_path("ck2", "o.npz")])
        assert rc == 0
        d = json.loads(capsys.readouterr().out)
        assert d["changed"] == 0 and d["same_input"]



def test_tools_borderline(tmp_path, monkeypatch, capsys):
    """tools borderline: the per-cell rows agree with a direct clean's
    scores (same band, same zap side), and the summary's counts add up."""
    import json

    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.tools import main as tools_main

    monkeypatch.chdir(tmp_path)
    ar, _ = make_synthetic_archive(nsub=32, nchan=64, nbin=128, seed=0,
                                   n_rfi_cells=20, rfi_strength=5.0,
                                   n_prezapped=40)
    save_archive(ar, "b.npz")
    assert tools_main(["borderline", "b.npz", "--eps", "0.05",
                       "--backend", "numpy"]) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    rows, summary = lines[:-1], lines[-1]
    assert summary["borderline"] == len(rows) > 0
    assert summary["zapped_borderline"] == sum(r["zapped"] for r in rows)

    res = clean_archive(load_archive("b.npz"), CleanConfig(backend="numpy"))
    s = np.asarray(res.scores)
    prezap = np.asarray(ar.weights) == 0
    want = np.argwhere(np.isfinite(s) & (np.abs(s - 1.0) < 0.05) & ~prezap)
    assert {(r["isub"], r["ichan"]) for r in rows} \
        == {(int(i), int(c)) for i, c in want}
    final_zap = np.asarray(res.final_weights) == 0
    for r in rows:
        assert abs(r["score"] - s[r["isub"], r["ichan"]]) < 1e-5
        # "zapped" is the OUTPUT mask, and pre-zapped cells (always zapped
        # regardless of score) never appear as rows
        assert r["zapped"] == bool(final_zap[r["isub"], r["ichan"]])
        assert not prezap[r["isub"], r["ichan"]]


class TestServeValidation:
    """--serve argument-contract checks: every conflict fails at parse
    time with a parser error (exit 2), before any device or daemon work."""

    @pytest.fixture(autouse=True)
    def _no_serve_env(self, monkeypatch):
        # the env mirrors would silently satisfy the intake requirement
        for var in ("ICLEAN_SPOOL", "ICLEAN_HTTP_PORT",
                    "ICLEAN_MAX_INFLIGHT", "ICLEAN_SERVE_QUEUE"):
            monkeypatch.delenv(var, raising=False)

    def _err(self, argv, capsys):
        with pytest.raises(SystemExit) as ei:
            main(argv)
        assert ei.value.code == 2
        return capsys.readouterr().err

    def test_serve_rejects_archive_paths(self, tmp_path, capsys):
        err = self._err(["--serve", "--http-port", "0",
                         str(tmp_path / "x.npz")], capsys)
        assert "--serve" in err and "archive" in err

    @pytest.mark.parametrize("flags", [
        ["--fleet"], ["--precompile"],
        ["--resume", "--journal", "j.jsonl"],
        ["--checkpoint", "c.json"], ["--stream", "2"], ["--unload_res"],
        ["--batch", "2"], ["--prefetch", "1"], ["--output", "out.npz"],
        ["--model", "selfcal"],
    ])
    def test_serve_rejects_batch_only_flags(self, flags, capsys):
        err = self._err(["--serve", "--http-port", "0", *flags], capsys)
        assert "--serve" in err

    def test_serve_rejects_numpy_backend(self, capsys):
        err = self._err(["--serve", "--http-port", "0",
                         "--backend", "numpy"], capsys)
        assert "backend" in err

    def test_serve_requires_an_intake(self, capsys):
        err = self._err(["--serve"], capsys)
        assert "--spool" in err and "--http-port" in err

    @pytest.mark.parametrize("flags", [
        ["--spool", "spool", "x.npz"],
        ["--http-port", "0", "x.npz"],
        ["--max-inflight", "4", "x.npz"],
    ])
    def test_serve_flags_require_serve(self, flags, capsys):
        err = self._err(flags, capsys)
        assert "--serve" in err

    def test_no_archives_and_no_serve(self, capsys):
        err = self._err([], capsys)
        assert "archive" in err and "--serve" in err

    def test_resume_requires_explicit_journal(self, capsys):
        err = self._err(["--fleet", "--resume", "x.npz"], capsys)
        assert "--journal" in err

    def test_serve_env_intake_satisfies_requirement(self, monkeypatch):
        # an env-mirrored intake parses past validation; a bad port then
        # fails as a --serve error, proving ServeConfig saw the env value
        monkeypatch.setenv("ICLEAN_HTTP_PORT", "99999999")
        with pytest.raises(SystemExit) as ei:
            main(["--serve"])
        assert ei.value.code == 2

"""Mixed-precision hot path (``--compute-dtype bfloat16``): knob
resolution, parity, and fallback contracts.

The tentpole promise is narrow and checkable: bf16 is a STORAGE format
for cube-sized operands only — every accumulation, the float32-bit-
pattern-keyed kth-select, scalers and thresholds stay fp32 — so on a
bf16-exact cube (every sample on the bfloat16 grid, zero channel
shifts, rotation='roll', exactly-zero baseline window) the downcast is
lossless and the masks must be BIT-EQUAL to the fp32 run on every
route: engine, batch, streaming-exact, online, mux, forced-4-device
mesh.  Where the backend cannot honour that (wide dtype, parity-probe
mismatch) the resolve helper downgrades the stage to fp32 with a
labeled counter — never an error, and never a checkpoint-identity
change.
"""

import os

import numpy as np
import pytest

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io import make_synthetic_archive

pytestmark = pytest.mark.skipif(
    os.environ.get("ICLEAN_SKIP_JAX") == "1", reason="jax-only suite")


def _bf16_exact_archive(nsub=8, nchan=16, nbin=32, seed=0):
    """An archive whose whole engine pipeline is bf16-lossless: samples
    on the bf16 grid, dm=0 (zero shifts), the last quarter of every
    profile exactly zero (with non-negative samples the min-mean
    baseline window lands there, so the subtracted baseline is exactly
    0), RFI spikes confined to the first half."""
    import jax.numpy as jnp

    ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                   seed=seed, dtype=np.float32, dm=0.0,
                                   disperse=False)
    rng = np.random.default_rng(seed)
    phase = (np.arange(nbin) + 0.5) / nbin
    profile = np.exp(-0.5 * ((phase - 0.3) / 0.05) ** 2)
    spectrum = 1.0 + 0.5 * np.arange(nchan) / nchan
    gain = 1.0 + 0.3 * np.arange(nsub) / max(1, nsub)
    cube = (30.0 * gain[:, None, None] * spectrum[None, :, None]
            * profile[None, None, :]).astype(np.float32)
    cube[:, :, 3 * nbin // 4:] = 0.0
    cells = rng.choice(nsub * nchan, size=max(4, nsub * nchan // 24),
                       replace=False)
    for s, c in zip(*np.unravel_index(cells, (nsub, nchan))):
        bins = rng.integers(0, nbin // 2, size=max(1, nbin // 16))
        cube[s, c, bins] += 40.0
    ar.data[:, 0] = np.asarray(
        jnp.asarray(cube, jnp.bfloat16).astype(jnp.float32))
    ar.dm = 0.0
    return ar


def _cfg(**kw):
    kw.setdefault("backend", "jax")
    kw.setdefault("dtype", "float32")
    kw.setdefault("rotation", "roll")
    kw.setdefault("max_iter", 3)
    return CleanConfig(**kw)


# ------------------------------------------------- knob resolution


def test_config_rejects_unknown_and_wide_compute_dtype():
    with pytest.raises(ValueError, match="unknown compute dtype"):
        CleanConfig(backend="jax", compute_dtype="float16")
    # f64 compute was never offered; the rejection is unchanged
    with pytest.raises(ValueError, match="unknown compute dtype"):
        CleanConfig(backend="jax", compute_dtype="float64")
    with pytest.raises(ValueError, match="requires dtype='float32'"):
        CleanConfig(backend="jax", dtype="float64",
                    compute_dtype="bfloat16")


def test_resolve_compute_dtype_default_env_and_validation(monkeypatch):
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_compute_dtype,
    )

    monkeypatch.delenv("ICLEAN_COMPUTE_DTYPE", raising=False)
    assert resolve_compute_dtype(None, jnp.float32) == "float32"
    assert resolve_compute_dtype("float32", jnp.float64) == "float32"
    with pytest.raises(ValueError, match="unknown compute dtype"):
        resolve_compute_dtype("float16", jnp.float32)
    # the env mirror only fills an unset knob; explicit wins
    monkeypatch.setenv("ICLEAN_COMPUTE_DTYPE", "bfloat16")
    assert resolve_compute_dtype("float32", jnp.float32) == "float32"
    assert resolve_compute_dtype(None, jnp.float32) in ("bfloat16",
                                                        "float32")


def test_resolve_downgrades_wide_dtype_with_counter():
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        compute_dtype_ineligible_counts,
        resolve_compute_dtype,
    )
    from iterative_cleaner_tpu.telemetry import MetricsRegistry
    from iterative_cleaner_tpu.telemetry.registry import labeled

    key = labeled("compute_dtype_ineligible", stage="t_wide",
                  reason="dtype")
    before = compute_dtype_ineligible_counts().get(key, 0)
    reg = MetricsRegistry()
    out = resolve_compute_dtype("bfloat16", jnp.float64, stage="t_wide",
                                registry=reg)
    assert out == "float32"
    assert compute_dtype_ineligible_counts().get(key, 0) == before + 1
    assert reg.counters.get(key) == 1


def test_forced_probe_mismatch_downgrades_per_stage(monkeypatch):
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends import jax_backend as jb

    monkeypatch.setitem(jb._COMPUTE_DTYPE_PROBE_CACHE, "parity", False)
    from iterative_cleaner_tpu.telemetry import MetricsRegistry
    from iterative_cleaner_tpu.telemetry.registry import labeled

    reg = MetricsRegistry()
    out = jb.resolve_compute_dtype("bfloat16", jnp.float32,
                                   stage="t_probe", registry=reg)
    assert out == "float32"
    key = labeled("compute_dtype_ineligible", stage="t_probe",
                  reason="parity_probe")
    assert reg.counters.get(key) == 1
    # the downgrade is a rung, not an error: the engine still cleans
    res = None
    from iterative_cleaner_tpu.backends import clean_archive

    res = clean_archive(_bf16_exact_archive(4, 8, 32),
                        _cfg(compute_dtype="bfloat16", max_iter=2))
    assert res.final_weights.shape == (4, 8)


def test_probe_passes_on_this_backend():
    """The CPU/TPU backends this repo targets convert bf16<->fp32
    IEEE-correctly; the cached probe must agree or every other parity
    test below would be vacuously comparing fp32 against fp32."""
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_compute_dtype,
    )

    assert resolve_compute_dtype("bfloat16", jnp.float32) == "bfloat16"


def test_checkpoint_identity_excludes_compute_dtype():
    from iterative_cleaner_tpu.utils.checkpoint import (
        config_hash,
        config_identity,
    )

    a = _cfg(compute_dtype="float32")
    b = _cfg(compute_dtype="bfloat16")
    assert config_identity(a) == config_identity(b)
    assert config_hash(a) == config_hash(b)


# --------------------------------------------------- route parity


def _final_weights(ar, cfg):
    from iterative_cleaner_tpu.backends import clean_archive

    return np.asarray(clean_archive(ar.clone(), cfg).final_weights)


@pytest.mark.parametrize("route", [
    dict(median_impl="sort", stats_impl="xla"),
    dict(median_impl="pallas", stats_impl="fused", fft_mode="dft",
         fused_sweep="on"),
])
def test_engine_masks_bit_equal_on_bf16_exact_cube(route):
    ar = _bf16_exact_archive()
    w32 = _final_weights(ar, _cfg(compute_dtype="float32", **route))
    w16 = _final_weights(ar, _cfg(compute_dtype="bfloat16", **route))
    np.testing.assert_array_equal(w16, w32)
    assert np.sum(w16 == 0) > 0          # the zap actually fired


def test_batch_masks_bit_equal_on_bf16_exact_cubes():
    from iterative_cleaner_tpu.parallel import clean_archives_batched

    ars = [_bf16_exact_archive(seed=s) for s in (0, 1, 2)]
    outs = {}
    for mode in ("float32", "bfloat16"):
        cfg = _cfg(compute_dtype=mode, max_iter=2)
        outs[mode] = clean_archives_batched([a.clone() for a in ars], cfg)
    for r16, r32 in zip(outs["bfloat16"], outs["float32"]):
        np.testing.assert_array_equal(r16.final_weights, r32.final_weights)


def test_streaming_masks_bit_equal_and_h2d_halves():
    from iterative_cleaner_tpu.parallel import clean_streaming_exact
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    ar = _bf16_exact_archive()
    res, h2d, peak = {}, {}, {}
    for mode in ("float32", "bfloat16"):
        reg = MetricsRegistry()
        res[mode] = clean_streaming_exact(
            ar.clone(), 2, _cfg(compute_dtype=mode, max_iter=2),
            registry=reg)
        h2d[mode] = int(reg.counters.get("stream_h2d_bytes", 0))
        peak[mode] = int(reg.gauges.get("stream_cache_peak_bytes", 0))
    np.testing.assert_array_equal(res["bfloat16"].final_weights,
                                  res["float32"].final_weights)
    # cube-SIZED traffic exactly halves (plane-sized operands and their
    # uploads stay fp32 in both runs, so the saving is precisely half
    # the fp32 cube bytes) and cache residency follows: the same
    # stream_hbm_mb budget therefore pins twice the tiles
    cube_f32_bytes = ar.nsub * ar.nchan * ar.nbin * 4
    assert h2d["float32"] - h2d["bfloat16"] == cube_f32_bytes // 2, h2d
    assert 0 < peak["bfloat16"] < peak["float32"], peak


def test_streaming_integration_mode_masks_bit_equal():
    from iterative_cleaner_tpu.parallel import clean_streaming_exact

    ar = _bf16_exact_archive()
    res = {}
    for mode in ("float32", "bfloat16"):
        res[mode] = clean_streaming_exact(
            ar.clone(), 2, _cfg(compute_dtype=mode, max_iter=2,
                                baseline_mode="integration"))
    np.testing.assert_array_equal(res["bfloat16"].final_weights,
                                  res["float32"].final_weights)


def test_online_step_masks_bit_equal_and_key_carries_dtype():
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.online.step import (
        build_subint_step,
        step_build_key,
    )

    ar = _bf16_exact_archive(4, 8, 32)
    cube = np.asarray(ar.total_intensity(), np.float32)
    freqs = np.asarray(ar.freqs_mhz, np.float32)
    outs = {}
    for mode in ("float32", "bfloat16"):
        cfg = _cfg(compute_dtype=mode, max_iter=2)
        step, dtype = build_subint_step(cfg, 8, 32, False, 0.125)
        step = jax.jit(step)
        tmpl = jnp.zeros((32,), dtype)
        outs[mode] = step(
            jnp.asarray(cube[:1], dtype), jnp.ones((1, 8), dtype),
            jnp.asarray(freqs, dtype), jnp.asarray(0.0, dtype),
            jnp.asarray(ar.centre_freq_mhz, dtype),
            jnp.asarray(ar.period_s, dtype), tmpl,
            jnp.asarray(0, jnp.int32))
    for a, b in zip(outs["bfloat16"], outs["float32"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    k32 = step_build_key(_cfg(compute_dtype="float32"), 8, 32, False, 0.1)
    k16 = step_build_key(_cfg(compute_dtype="bfloat16"), 8, 32, False, 0.1)
    assert k32 != k16                     # distinct compile buckets
    assert "bfloat16" in k16


def test_mux_masks_bit_equal_with_fp32_solo_sessions():
    from iterative_cleaner_tpu.online import OnlineSession, StreamMeta
    from iterative_cleaner_tpu.online.mux import StreamMux

    n_sub = 4
    streams = []
    for s in range(2):
        ar = _bf16_exact_archive(n_sub, 8, 32, seed=50 + s)
        streams.append((StreamMeta.from_archive(ar),
                        np.asarray(ar.total_intensity(), np.float64)))
    cfg16 = _cfg(compute_dtype="bfloat16", max_iter=2,
                 stream_reconcile_every=0)
    cfg32 = _cfg(compute_dtype="float32", max_iter=2,
                 stream_reconcile_every=0)
    refs = []
    for meta, cube in streams:
        sess = OnlineSession(meta, cfg32)
        for i in range(n_sub):
            sess.ingest(cube[i])
        refs.append(np.asarray(sess.provisional_weights))
    mux = StreamMux(max_batch=2, max_wait_ms=0.0)
    for k, (meta, _) in enumerate(streams):
        mux.open(f"s{k}", meta, cfg16)
    for i in range(n_sub):
        for k, (_, cube) in enumerate(streams):
            mux.ingest(f"s{k}", cube[i])
        mux.pump(force=True)
    for k, ref in enumerate(refs):
        np.testing.assert_array_equal(
            np.asarray(mux.session(f"s{k}").provisional_weights), ref)


def test_mesh_masks_bit_equal_on_forced_mesh():
    import jax

    from iterative_cleaner_tpu.backends.jax_backend import clean_cube
    from iterative_cleaner_tpu.parallel.mesh import cell_mesh
    from iterative_cleaner_tpu.parallel.sharding import clean_cube_sharded

    if len(jax.devices()) < 4:
        pytest.skip("needs the forced multi-device CPU platform")
    mesh = cell_mesh(4)
    ar = _bf16_exact_archive()
    args_of = lambda: (ar.total_intensity(), ar.weights, ar.freqs_mhz,
                       ar.dm, ar.centre_freq_mhz, ar.period_s)
    w = {}
    for mode in ("float32", "bfloat16"):
        cfg = _cfg(compute_dtype=mode, max_iter=2)
        w[mode, "single"] = np.asarray(
            clean_cube(*args_of(), cfg).final_weights)
        w[mode, "mesh"] = np.asarray(
            clean_cube_sharded(*args_of(), cfg, mesh).final_weights)
    np.testing.assert_array_equal(w["bfloat16", "mesh"],
                                  w["float32", "mesh"])
    np.testing.assert_array_equal(w["bfloat16", "mesh"],
                                  w["bfloat16", "single"])


# ------------------------------------------------------- CLI wiring


def test_cli_flag_parses_into_config(tmp_path):
    from iterative_cleaner_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args(
        ["x.npz", "--backend", "jax", "--compute-dtype", "bfloat16"])
    cfg = config_from_args(args)
    assert cfg.compute_dtype == "bfloat16"
    args = build_parser().parse_args(["x.npz", "--backend", "jax"])
    assert config_from_args(args).compute_dtype is None

"""End-to-end backend parity: the jax engine must reproduce the numpy
oracle's final RFI mask bit-for-bit (the north star in BASELINE.md), plus
detection-quality checks against the synthetic ground truth."""

import numpy as np
import pytest

from iterative_cleaner_tpu.backends import clean_archive, get_backend
from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive


def _run_both(ar, **cfg_kwargs):
    res_np = clean_archive(ar.clone(), CleanConfig(backend="numpy", **cfg_kwargs))
    res_jx = clean_archive(ar.clone(), CleanConfig(backend="jax", **cfg_kwargs))
    return res_np, res_jx


@pytest.mark.parametrize("seed,kwargs", [
    (0, dict()),
    (1, dict(n_prezapped=8)),
    (2, dict(nsub=8, nchan=16, nbin=64, n_rfi_cells=3)),
    (3, dict(n_rfi_channels=2, n_rfi_subints=0)),
])
def test_final_mask_bit_identical(seed, kwargs):
    ar, _ = make_synthetic_archive(seed=seed, **kwargs)
    res_np, res_jx = _run_both(ar, dtype="float64")
    np.testing.assert_array_equal(res_np.zap_mask(), res_jx.zap_mask())
    assert res_np.loops == res_jx.loops
    assert res_np.converged == res_jx.converged
    np.testing.assert_array_equal(res_np.final_weights, res_jx.final_weights)


def test_final_mask_float32_jax_path():
    # the production dtype: mask parity still expected on well-separated RFI
    ar, _ = make_synthetic_archive(seed=4, rfi_strength=60.0)
    res_np, res_jx = _run_both(ar, dtype="float32")
    np.testing.assert_array_equal(res_np.zap_mask(), res_jx.zap_mask())


def test_detects_impulsive_cells_and_keeps_prezapped():
    ar, truth = make_synthetic_archive(seed=5, n_prezapped=6, rfi_strength=80.0)
    res = clean_archive(ar.clone(), CleanConfig(backend="jax"))
    zap = res.zap_mask()
    # every injected impulsive cell is zapped
    for s, c in truth.rfi_cells:
        assert zap[s, c], f"missed injected RFI at ({s},{c})"
    # originally-zapped cells stay zapped (weights only ever go to zero)
    assert zap[truth.prezapped].all()


def test_clean_data_mostly_survives():
    ar, truth = make_synthetic_archive(seed=6, n_rfi_cells=4,
                                       n_rfi_channels=1, n_rfi_subints=1)
    res = clean_archive(ar.clone(), CleanConfig(backend="jax"))
    zap = res.zap_mask()
    good = ~truth.expected_zap(ar.nsub, ar.nchan)
    false_pos = (zap & good).sum() / good.sum()
    assert false_pos < 0.05, f"false-positive rate {false_pos:.3f}"


def test_loop_telemetry_shapes():
    ar, _ = make_synthetic_archive(seed=7)
    res = clean_archive(ar.clone(), CleanConfig(backend="jax"))
    assert res.loop_diffs is not None and len(res.loop_diffs) == res.loops
    assert res.loop_rfi_frac is not None and len(res.loop_rfi_frac) == res.loops
    assert 0.0 <= res.rfi_fraction <= 1.0


def test_residual_output():
    ar, _ = make_synthetic_archive(seed=8)
    cfg = CleanConfig(backend="jax", unload_res=True)
    res = clean_archive(ar.clone(), cfg)
    assert res.residual is not None
    assert res.residual.shape == (ar.nsub, ar.nchan, ar.nbin)
    res_np = clean_archive(ar.clone(), CleanConfig(backend="numpy",
                                                   unload_res=True,
                                                   dtype="float64"))
    # residual is the pulse-free cube: pulse energy mostly removed
    resid_power = np.abs(res.residual[res_np.final_weights > 0]).mean()
    raw_power = np.abs(ar.total_intensity()[res_np.final_weights > 0]).mean()
    assert resid_power < raw_power


def test_nonbinary_weights_preserved():
    # weights are values, not booleans: survivors keep their original weight
    ar, _ = make_synthetic_archive(seed=9)
    ar.weights[:] = 0.5
    ar.weights[0, 0] = 0.0
    res = clean_archive(ar.clone(), CleanConfig(backend="numpy", dtype="float64"))
    kept = res.final_weights[~res.zap_mask()]
    assert np.all(kept == 0.5)


def test_max_iter_cap():
    ar, _ = make_synthetic_archive(seed=10)
    for backend in ("numpy", "jax"):
        res = clean_archive(ar.clone(), CleanConfig(backend=backend, max_iter=1))
        assert res.loops == 1


def test_bad_parts_sweep():
    from iterative_cleaner_tpu.backends.base import sweep_bad_lines

    w = np.ones((4, 6))
    w[1, :5] = 0.0   # 5/6 channels of subint 1 zapped
    w[:3, 2] = 0.0   # 3/4 subints of channel 2 zapped
    out, nbs, nbc = sweep_bad_lines(w, bad_subint=0.5, bad_chan=0.5)
    assert nbs == 1 and nbc == 1
    assert (out[1] == 0).all() and (out[:, 2] == 0).all()
    # strict '>' with thresholds of 1.0 disables the sweep (quirk 10)
    out2, nbs2, nbc2 = sweep_bad_lines(w, bad_subint=1.0, bad_chan=1.0)
    assert nbs2 == 0 and nbc2 == 0
    np.testing.assert_array_equal(out2, w)


def test_backend_registry():
    assert get_backend("numpy").__name__.endswith("numpy_backend")
    assert get_backend("jax").__name__.endswith("jax_backend")
    with pytest.raises(ValueError):
        get_backend("torch")


def test_resolve_stats_impl_guards():
    """'auto' must fall back to xla off-TPU, for big nbin, and for fft mode;
    explicit choices pass through."""
    import jax.numpy as jnp

    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_fft_mode, resolve_stats_impl)
    from iterative_cleaner_tpu.stats.pallas_kernels import FUSED_STATS_MAX_NBIN

    # CPU test devices: auto never picks the TPU kernels
    assert resolve_fft_mode("auto", jnp.float32) == "fft"
    assert resolve_stats_impl("auto", jnp.float32, 128, "dft") == "xla"
    assert resolve_stats_impl("xla", jnp.float32, 128, "dft") == "xla"
    assert resolve_stats_impl("fused", jnp.float32, 128, "dft") == "fused"
    # the nbin guard applies regardless of platform
    big = FUSED_STATS_MAX_NBIN + 1
    assert resolve_stats_impl("auto", jnp.float32, big, "dft") == "xla"


def test_config_rejects_fused_with_fft():
    from iterative_cleaner_tpu.config import CleanConfig

    with pytest.raises(ValueError, match="fused"):
        CleanConfig(stats_impl="fused", fft_mode="fft")
    CleanConfig(stats_impl="fused", fft_mode="dft")  # ok
    CleanConfig(stats_impl="fused")                  # auto fft: ok


@pytest.mark.parametrize("trial", range(10))
def test_randomized_config_mask_parity(trial):
    """Property sweep: random archive geometry, RFI mix, thresholds, pulse
    regions and rotation modes — the float64 jax engine must reproduce the
    oracle's final mask bit-for-bit on every draw."""
    rng = np.random.default_rng(1000 + trial)
    nsub = int(rng.integers(4, 24))
    nchan = int(rng.integers(6, 40))
    nbin = int(rng.choice([16, 32, 64, 128]))
    ar, _ = make_synthetic_archive(
        nsub=nsub, nchan=nchan, nbin=nbin,
        n_rfi_cells=int(rng.integers(0, 6)),
        n_rfi_channels=int(rng.integers(0, 3)),
        n_rfi_subints=int(rng.integers(0, 2)),
        n_prezapped=int(rng.integers(0, nsub * nchan // 4)),
        rfi_strength=float(rng.uniform(15, 80)),
        pulse_snr=float(rng.uniform(5, 60)),
        seed=int(rng.integers(0, 2**31)),
    )
    pulse_region = (0.0, 0.0, 1.0)
    if rng.random() < 0.4:
        a, b = sorted(rng.integers(0, nbin, size=2).tolist())
        pulse_region = (float(rng.uniform(0, 1)), float(a), float(b))
    cfg = dict(
        chanthresh=float(rng.uniform(3, 8)),
        subintthresh=float(rng.uniform(3, 8)),
        max_iter=int(rng.integers(1, 6)),
        pulse_region=pulse_region,
        rotation=str(rng.choice(["fourier", "roll"])),
        dtype="float64",
    )
    res_np, res_jx = _run_both(ar, **cfg)
    np.testing.assert_array_equal(res_np.zap_mask(), res_jx.zap_mask())
    assert res_np.loops == res_jx.loops

"""Fused sweep kernel (stats/pallas_kernels.fused_sweep_pallas*): the
template-subtract -> robust-stats -> threshold/zap iteration tail as ONE
Pallas launch, reading each cube tile exactly once.

The central contract: masks and scores are BIT-EQUAL to the multi-kernel
route (cell diagnostics + scale_and_combine + zap) at every setting —
`--fused-sweep on|auto` may change launch count and transfer volume,
never a single mask bit.  Everything here runs the kernels in interpret
mode on CPU (the conftest platform pin), which is the same numerics path
Mosaic compiles on TPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from iterative_cleaner_tpu.stats import pallas_kernels as pk
from iterative_cleaner_tpu.stats.masked_jax import scale_and_combine

CH, ST = 4.0, 4.0


def _case(rng, nsub, nchan, nbin, zap_frac=0.2, nan_template=False):
    cube = rng.normal(size=(nsub, nchan, nbin)).astype(np.float32)
    t = rng.normal(size=(nbin,)).astype(np.float32)
    if nan_template:
        t[3] = np.nan
    w = rng.uniform(0.5, 2.0, size=(nsub, nchan)).astype(np.float32)
    w[rng.uniform(size=(nsub, nchan)) < zap_frac] = 0.0
    m = w == 0
    return jnp.asarray(cube), jnp.asarray(t), jnp.asarray(w), jnp.asarray(m)


# ------------------------------------------------------- kernel-level parity

def test_median4_matches_jnp_median_bitwise():
    """The in-kernel 4-way median network vs jnp.median, including the
    NaN-propagation and signed-zero cases the scorer leans on."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4, 2000)).astype(np.float32)
    x[0, :10] = np.nan
    x[1, 10:20] = np.inf
    x[2, 20:30] = -np.inf
    x[3, 30:40] = -0.0
    x[0, 40:50] = 0.0
    got = np.asarray(pk._median4(*(jnp.asarray(x[i]) for i in range(4))))
    want = np.asarray(jnp.median(jnp.asarray(x), axis=0))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("nsub,nchan,nbin,kw", [
    pytest.param(12, 10, 32, {}, marks=pytest.mark.slow),
    pytest.param(8, 128, 64, {},           # lane-exact channel count
                 marks=pytest.mark.slow),
    (3, 5, 16, {}),                        # heavy sublane+lane padding
    (12, 10, 32, {"zap_frac": 0.9}),       # nearly-dead plane
    (12, 10, 32, {"nan_template": True}),  # NaN propagation
])
def test_fused_sweep_dedispersed_bit_equal(nsub, nchan, nbin, kw):
    rng = np.random.default_rng(11)
    ded, t, w, m = _case(rng, nsub, nchan, nbin, **kw)
    win = jnp.ones((nbin,), jnp.float32)
    diags = pk.cell_diagnostics_pallas_dedisp(ded, t, win, w, m)
    scores_ref = scale_and_combine(diags, m, CH, ST, median_impl="pallas")
    neww_ref = jnp.where(scores_ref >= 1.0, 0.0, w)
    neww, scores, dstd = pk.fused_sweep_pallas_dedisp(
        ded, t, win, w, m, CH, ST)
    np.testing.assert_array_equal(np.asarray(dstd), np.asarray(diags[0]))
    np.testing.assert_array_equal(np.asarray(scores),
                                  np.asarray(scores_ref))
    np.testing.assert_array_equal(np.asarray(neww), np.asarray(neww_ref))


@pytest.mark.parametrize("nsub,nchan,nbin,apply_nyq,kw", [
    (12, 10, 32, False, {}),
    (12, 10, 32, True, {}),
    (3, 5, 16, True, {"nan_template": True}),
])
def test_fused_sweep_dispersed_bit_equal(nsub, nchan, nbin, apply_nyq, kw):
    rng = np.random.default_rng(13)
    disp, t, w, m = _case(rng, nsub, nchan, nbin, **kw)
    rot_t = jnp.asarray(rng.normal(size=(nchan, nbin)).astype(np.float32))
    nyq_row = None
    if apply_nyq:
        nyq_row = jnp.asarray(
            (rng.normal(size=(nchan, nbin)) * 0.01).astype(np.float32))
    diags = pk.cell_diagnostics_pallas_disp(disp, rot_t, nyq_row, t, w, m)
    scores_ref = scale_and_combine(diags, m, CH, ST, median_impl="pallas")
    neww_ref = jnp.where(scores_ref >= 1.0, 0.0, w)
    neww, scores, dstd = pk.fused_sweep_pallas(
        disp, rot_t, nyq_row, t, w, m, CH, ST)
    np.testing.assert_array_equal(np.asarray(dstd), np.asarray(diags[0]))
    np.testing.assert_array_equal(np.asarray(scores),
                                  np.asarray(scores_ref))
    np.testing.assert_array_equal(np.asarray(neww), np.asarray(neww_ref))


def test_fused_sweep_vmap_folds_batch_bit_equal():
    """The custom_vmap rule folds the batch into the subint grid axis of a
    single launch; every batch element must match its unbatched call."""
    rng = np.random.default_rng(17)
    batch, nsub, nchan, nbin = 2, 6, 7, 32
    cases = [_case(rng, nsub, nchan, nbin) for _ in range(batch)]
    ded, t, w, m = (jnp.stack([c[k] for c in cases]) for k in range(4))
    win = jnp.ones((nbin,), jnp.float32)
    f = jax.vmap(lambda d, tt, wgt, msk: pk.fused_sweep_pallas_dedisp(
        d, tt, win, wgt, msk, CH, ST))
    neww_b, scores_b, dstd_b = f(ded, t, w, m)
    for b in range(batch):
        neww, scores, dstd = pk.fused_sweep_pallas_dedisp(
            ded[b], t[b], win, w[b], m[b], CH, ST)
        np.testing.assert_array_equal(np.asarray(neww_b[b]),
                                      np.asarray(neww))
        np.testing.assert_array_equal(np.asarray(scores_b[b]),
                                      np.asarray(scores))
        np.testing.assert_array_equal(np.asarray(dstd_b[b]),
                                      np.asarray(dstd))


def test_fused_combine_bit_equal_and_rejects_f64():
    """The standalone one-launch tail (exact streaming's combine) vs the
    scaler + median + threshold composition, on already-computed planes."""
    rng = np.random.default_rng(19)
    ded, t, w, m = _case(rng, 12, 10, 32)
    win = jnp.ones((32,), jnp.float32)
    diags = pk.cell_diagnostics_pallas_dedisp(ded, t, win, w, m)
    scores_ref = scale_and_combine(diags, m, CH, ST, median_impl="pallas")
    neww_ref = jnp.where(scores_ref >= 1.0, 0.0, w)
    neww, scores = pk.fused_combine_pallas(diags, m, w, CH, ST)
    np.testing.assert_array_equal(np.asarray(scores),
                                  np.asarray(scores_ref))
    np.testing.assert_array_equal(np.asarray(neww), np.asarray(neww_ref))
    with pytest.raises(TypeError, match="float32"):
        pk.fused_combine_pallas(
            tuple(d.astype(jnp.float64) for d in diags), m, w, CH, ST)


def test_fused_sweep_eligibility_gate():
    assert pk.fused_sweep_eligible(12, 10, 32)
    assert pk.fused_sweep_eligible(64, 128, 256)
    # scratch budget: 12 planes of (s_pad, nc) f32 must fit the cap
    assert not pk.fused_sweep_eligible(20000, 4096, 64)
    # nbin beyond the fused cell-stats ceiling disqualifies outright
    assert not pk.fused_sweep_eligible(8, 8, 4 * pk.FUSED_STATS_MAX_NBIN)


# --------------------------------------------------- knob resolution wiring

def test_config_validates_fused_sweep_values():
    from iterative_cleaner_tpu.config import CleanConfig

    for v in (None, "auto", "on", "off"):
        assert CleanConfig(fused_sweep=v).fused_sweep == v
    with pytest.raises(ValueError, match="fused sweep"):
        CleanConfig(fused_sweep="bogus")


def test_resolve_fused_sweep_env_and_auto(monkeypatch):
    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_fused_sweep,
    )

    monkeypatch.delenv("ICLEAN_FUSED_SWEEP", raising=False)
    assert resolve_fused_sweep("on", "xla") == "on"
    assert resolve_fused_sweep("off", "fused") == "off"
    # auto follows the RESOLVED stats_impl: fused kernels -> sweep on
    assert resolve_fused_sweep("auto", "fused") == "on"
    assert resolve_fused_sweep("auto", "xla") == "off"
    assert resolve_fused_sweep(None, "fused") == "on"
    monkeypatch.setenv("ICLEAN_FUSED_SWEEP", "off")
    assert resolve_fused_sweep(None, "fused") == "off"
    monkeypatch.setenv("ICLEAN_FUSED_SWEEP", "junk")
    with pytest.raises(ValueError, match="fused sweep"):
        resolve_fused_sweep(None, "fused")


def test_checkpoint_identity_excludes_fused_sweep():
    from iterative_cleaner_tpu.utils.checkpoint import _IDENTITY_EXCLUDE

    assert "fused_sweep" in _IDENTITY_EXCLUDE


# ----------------------------------------------------- engine-level parity

def _engine_case():
    rng = np.random.default_rng(11)
    nsub, nchan, nbin = 12, 16, 64
    cube = rng.normal(size=(nsub, nchan, nbin)).astype(np.float32)
    cube[3, 5] += 40.0
    cube[:, 9] += 10.0
    w = np.ones((nsub, nchan), np.float32)
    w[0, 0] = 0.0
    freqs = np.linspace(1500.0, 1200.0, nchan)
    return cube, w, (freqs, 26.0, 1400.0, 0.005)


@pytest.mark.parametrize("stats_frame", [
    pytest.param("auto", marks=pytest.mark.slow), "dedispersed"])
def test_engine_fused_sweep_masks_bit_equal(stats_frame):
    """clean_cube with --fused-sweep on/auto vs off: final weights,
    scores, loop count and per-iteration metrics all bit-equal — `off` is
    the escape hatch, never a different answer."""
    from iterative_cleaner_tpu.backends.jax_backend import clean_cube
    from iterative_cleaner_tpu.config import CleanConfig

    cube, w, args = _engine_case()

    def run(fused_sweep):
        cfg = CleanConfig(
            backend="jax", stats_impl="fused", fft_mode="dft",
            median_impl="sort", fused_sweep=fused_sweep,
            stats_frame=stats_frame, max_iter=4, chanthresh=2.0,
            subintthresh=2.0)
        return clean_cube(cube.copy(), w.copy(), *args, config=cfg)

    off = run("off")
    assert int((np.asarray(off.final_weights) == 0).sum()) > 1
    for fused_sweep in ("on", "auto"):  # auto: stats_impl fused -> on
        got = run(fused_sweep)
        np.testing.assert_array_equal(got.final_weights, off.final_weights)
        np.testing.assert_array_equal(got.scores, off.scores)
        assert got.loops == off.loops and got.converged == off.converged
        np.testing.assert_array_equal(got.iter_metrics, off.iter_metrics)


def test_cli_fused_sweep_flag_round_trips():
    """--fused-sweep lands on CleanConfig; bad values die in argparse."""
    from iterative_cleaner_tpu.cli import build_parser, config_from_args

    parser = build_parser()
    args = parser.parse_args(["in.ar", "--fused-sweep", "on"])
    assert config_from_args(args).fused_sweep == "on"
    assert config_from_args(parser.parse_args(["in.ar"])).fused_sweep \
        is None
    with pytest.raises(SystemExit):
        parser.parse_args(["in.ar", "--fused-sweep", "sideways"])


# ----------------------------------------- streaming / online route parity

@pytest.mark.slow
def test_streaming_exact_fused_combine_bit_equal_and_fewer_h2d_bytes():
    """Exact streaming with the fused one-launch combine: masks/scores
    bit-equal to the compact-scaler route, and per-run stream_h2d_bytes
    strictly lower (the four diagnostic planes are never re-uploaded)."""
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
    from iterative_cleaner_tpu.parallel import clean_streaming_exact
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    def run(fused_sweep, nsub=20, chunk=8):
        ar, _ = make_synthetic_archive(nsub=nsub, nchan=16, nbin=32,
                                       seed=7, n_rfi_cells=8,
                                       n_prezapped=6)
        cfg = CleanConfig(backend="jax", dtype="float32",
                          stats_impl="fused", fft_mode="dft",
                          median_impl="sort", fused_sweep=fused_sweep,
                          chanthresh=2.5, subintthresh=2.5, max_iter=4)
        reg = MetricsRegistry()
        res = clean_streaming_exact(ar, chunk, cfg, registry=reg)
        return res, reg.snapshot()["counters"].get("stream_h2d_bytes", 0)

    off, h2d_off = run("off")
    on, h2d_on = run("on")
    np.testing.assert_array_equal(off.final_weights, on.final_weights)
    np.testing.assert_array_equal(off.scores, on.scores)
    assert off.loops == on.loops and off.converged == on.converged
    assert h2d_on < h2d_off
    # single-tile degenerate geometry
    off1, _ = run("off", nsub=6, chunk=8)
    on1, _ = run("on", nsub=6, chunk=8)
    np.testing.assert_array_equal(off1.final_weights, on1.final_weights)
    np.testing.assert_array_equal(off1.scores, on1.scores)


@pytest.mark.slow
def test_online_session_fused_sweep_reconciles_bit_equal():
    """Per-subint fused sweep step: the provisional mask may change
    flavour (DFT-flavoured diagnostics), but the contractual reconcile
    masks stay bit-equal to the batch clean and to the unfused session,
    with zero steady-state recompiles."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.io import make_synthetic_archive
    from iterative_cleaner_tpu.online import OnlineSession, StreamMeta

    ar, _ = make_synthetic_archive(nsub=6, nchan=8, nbin=16, seed=21)
    cube = np.asarray(ar.total_intensity(), dtype=np.float64).copy()
    cube[1, 2, ::2] += 40.0  # structured RFI (survives baseline removal)
    meta = StreamMeta.from_archive(ar)

    def run(fused_sweep):
        cfg = CleanConfig(backend="jax", dtype="float32",
                          stats_impl="fused", fft_mode="dft",
                          median_impl="sort", max_iter=2,
                          fused_sweep=fused_sweep,
                          stream_reconcile_every=0)
        s = OnlineSession(meta, cfg)
        for i in range(cube.shape[0]):
            s.ingest(cube[i])
        assembled = s.assembled()
        return assembled, cfg, s.close()

    _, _, off = run("off")
    assembled, cfg, on = run("on")
    np.testing.assert_array_equal(off.archive.weights, on.archive.weights)
    ref = clean_archive(assembled, cfg)
    np.testing.assert_array_equal(on.archive.weights == 0,
                                  np.asarray(ref.final_weights) == 0)
    assert on.recompiles_steady == 0
    assert on.warmup_compiles >= 1


def test_fused_sweep_hot_program_contract_green():
    """The registered fused_sweep contract: program strictly smaller than
    the multi-kernel route AND a single cube-tile read per sweep kernel
    (the bandwidth budget --selfcheck guards)."""
    from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
        verify_hot_programs,
    )

    (report,) = verify_hot_programs(["fused_sweep"])
    # the pytest session runs x64-on (conftest), which weak-promotes
    # python scalars and trips no-f64 on EVERY hot program; that contract
    # is guarded in the deployment config (x64 off) by the selfcheck CLI
    # subprocess test in test_analysis.py.  Here: the fused-specific ones.
    bad = [v for v in report.violations if v.contract != "no-f64"]
    assert not bad, [v.render() for v in bad]
    assert report.eqn_count > 0

"""Closed-form template-amplitude fit vs the reference's MINPACK call.

The reference fits err(amp) = amp*template - prof per cell with
scipy.optimize.leastsq (/root/reference/iterative_cleaner.py:277-278); the
model is linear, so the closed form <t,p>/<t,t> must agree to solver
tolerance (SURVEY.md section 7, hard part 4)."""

import numpy as np
import pytest
import scipy.optimize

from iterative_cleaner_tpu.ops.dsp import (
    fit_template_amplitudes,
    template_residuals,
)


def minpack_amp(template, prof):
    params, status = scipy.optimize.leastsq(
        lambda amp: amp * template - prof, [1.0]
    )
    assert status in (1, 2, 3, 4)
    return float(params[0])


def test_matches_minpack_on_random_profiles():
    rng = np.random.default_rng(7)
    nbin = 64
    template = np.exp(-0.5 * ((np.arange(nbin) - 20) / 4.0) ** 2) * 1e4
    cube = rng.normal(size=(3, 5, nbin)) + 2.0 * np.exp(
        -0.5 * ((np.arange(nbin) - 20) / 4.0) ** 2
    )
    amps = fit_template_amplitudes(cube, template, np)
    for s in range(3):
        for c in range(5):
            assert amps[s, c] == pytest.approx(
                minpack_amp(template, cube[s, c]), rel=1e-6, abs=1e-12
            )


def test_residual_sign_convention():
    # stored residual is amp*template - profile (reference :277,:279)
    template = np.array([0.0, 1.0, 0.0, 0.0])
    cube = np.array([[[1.0, 3.0, 1.0, 1.0]]])
    amps = fit_template_amplitudes(cube, template, np)
    assert amps[0, 0] == pytest.approx(3.0)
    resid = template_residuals(cube, template, amps, (0, 0), 1.0, np, False)
    np.testing.assert_allclose(resid[0, 0], [-1.0, 0.0, -1.0, -1.0])


def test_pulse_region_uses_reference_argument_order():
    # -r FACTOR START END in effect (SURVEY.md 2.4 quirk 3): region bins are
    # scaled by pulse_region[0] over [int(pr[1]), int(pr[2])).
    template = np.zeros(8)
    cube = np.ones((1, 1, 8))
    amps = np.ones((1, 1))
    resid = template_residuals(cube, template, amps, (2, 5), 0.5, np, True)
    expect = -np.ones(8)
    expect[2:5] *= 0.5
    np.testing.assert_allclose(resid[0, 0], expect)


def test_zero_template_returns_unit_amplitude():
    # MINPACK returns the initial guess 1.0 on a flat objective; the closed
    # form reproduces that instead of 0/0.
    cube = np.ones((2, 2, 4))
    amps = fit_template_amplitudes(cube, np.zeros(4), np)
    np.testing.assert_array_equal(amps, 1.0)

"""DSP primitive tests: rotation, dispersion, baseline, scrunching, shared
between the numpy and jax instantiations of ops/dsp.py."""

import jax.numpy as jnp
import numpy as np
import pytest

from iterative_cleaner_tpu.archive import KDM_S
from iterative_cleaner_tpu.ops.dsp import (
    baseline_offsets,
    dedisperse_cube,
    dispersion_shift_bins,
    remove_baseline,
    rotate_bins,
    weighted_template,
)


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestRotate:
    def test_integer_shift_matches_roll(self, xp):
        rng = np.random.default_rng(0)
        x = xp.asarray(rng.normal(size=(3, 4, 16)))
        for s in (0, 1, 5, -3, 16, 21):
            got = np.asarray(rotate_bins(x, float(s), xp, method="fourier"))
            want = np.roll(np.asarray(x), s, axis=-1)
            np.testing.assert_allclose(got, want, atol=1e-9)
            got_roll = np.asarray(rotate_bins(x, float(s), xp, method="roll"))
            np.testing.assert_allclose(got_roll, want, atol=0)

    def test_per_channel_shifts(self, xp):
        rng = np.random.default_rng(1)
        x = xp.asarray(rng.normal(size=(2, 3, 32)))
        shifts = xp.asarray([0.0, 4.0, -7.0])
        got = np.asarray(rotate_bins(x, shifts, xp, method="roll"))
        base = np.asarray(x)
        for c, s in enumerate([0, 4, -7]):
            np.testing.assert_array_equal(got[:, c], np.roll(base[:, c], s, axis=-1))

    def test_roll_jax_matmul_bitexact_vs_numpy_gather(self, xp):
        """The jax roll path (one-hot permutation matmul, MXU-shaped) must be
        bit-identical to the numpy gather path for every dtype/shift shape."""
        if xp is np:
            pytest.skip("cross-path comparison, driven from the jax id")
        rng = np.random.default_rng(7)
        for dtype in (np.float32, np.float64):
            x = rng.normal(size=(5, 9, 32)).astype(dtype)
            for shifts in (np.float64(3.0), np.float64(-11.0),
                           rng.normal(scale=10, size=9)):
                want = rotate_bins(x, shifts, np, method="roll")
                got = np.asarray(rotate_bins(
                    jnp.asarray(x), jnp.asarray(shifts), jnp, method="roll"))
                assert got.dtype == want.dtype
                np.testing.assert_array_equal(got, want)

    def test_fractional_rotation_invertible(self, xp):
        # exact on band-limited profiles (the Nyquist bin of a fractionally
        # rotated real signal attenuates by cos(pi*s); see rotate_bins)
        rng = np.random.default_rng(2)
        raw = rng.normal(size=(4, 64))
        spec = np.fft.rfft(raw, axis=-1)
        spec[..., -1] = 0.0
        x = xp.asarray(np.fft.irfft(spec, n=64, axis=-1))
        s = 2.37
        back = rotate_bins(rotate_bins(x, s, xp), -s, xp)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-9)

    def test_fractional_rotation_nyquist_attenuation(self, xp):
        nbin = 16
        x = xp.asarray(np.cos(np.pi * np.arange(nbin))[None])  # pure Nyquist
        s = 0.5
        out = np.asarray(rotate_bins(x, s, xp))
        np.testing.assert_allclose(
            out, np.asarray(x) * np.cos(np.pi * s), atol=1e-9
        )


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestDispersion:
    def test_shift_sign_and_magnitude(self, xp):
        freqs = xp.asarray([1300.0, 1400.0, 1500.0])
        nbin, period, dm = 256, 0.5, 30.0
        shifts = np.asarray(
            dispersion_shift_bins(freqs, dm, 1400.0, period, nbin, xp)
        )
        assert shifts[1] == pytest.approx(0.0)
        assert shifts[0] > 0  # below the reference frequency arrives later
        assert shifts[2] < 0
        expect0 = KDM_S * dm * (1300.0 ** -2 - 1400.0 ** -2) / period * nbin
        assert shifts[0] == pytest.approx(expect0)

    def test_dispersion_constant_is_tempo_convention(self, xp):
        """The delay constant is PSRCHIVE/tempo's 1/2.41e-4 s MHz^2 per
        pc cm^-3 (the value the reference's dedisperse inherits), not the
        CODATA derivation 4148.808.  Golden: DM=100 across 400->1400 MHz
        delays by 1/2.41e-4 * 100 * (400^-2 - 1400^-2) s."""
        assert KDM_S == pytest.approx(4149.377593360996, abs=1e-9)
        freqs = xp.asarray([400.0, 1400.0])
        shifts = np.asarray(
            dispersion_shift_bins(freqs, 100.0, 1400.0, 1.0, 1, xp))
        assert shifts[0] == pytest.approx(2.381658057413837, rel=1e-9)

    def test_dedisperse_aligns_dispersed_pulse(self, xp):
        nchan, nbin = 8, 128
        freqs = np.linspace(1300.0, 1500.0, nchan)
        period, dm = 0.7, 50.0
        profile = np.exp(-0.5 * ((np.arange(nbin) - 40) / 3.0) ** 2)
        cube = np.broadcast_to(profile, (2, nchan, nbin)).copy()
        dispersed = dedisperse_cube(
            xp.asarray(cube), xp.asarray(freqs), dm, 1400.0, period, xp,
            forward=False,
        )
        restored = dedisperse_cube(
            dispersed, xp.asarray(freqs), dm, 1400.0, period, xp, forward=True
        )
        np.testing.assert_allclose(np.asarray(restored), cube, atol=1e-8)
        # and the dispersed cube really is misaligned across channels
        peaks = np.argmax(np.asarray(dispersed)[0], axis=-1)
        assert len(np.unique(peaks)) > 1


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestBaseline:
    def test_flat_profile_baseline_is_level(self, xp):
        x = xp.asarray(np.full((3, 32), 7.5))
        off = np.asarray(baseline_offsets(x, xp))
        np.testing.assert_allclose(off, 7.5)

    def test_pulse_ignored_by_min_window(self, xp):
        nbin = 100
        prof = np.full(nbin, 2.0)
        prof[40:50] += 50.0  # pulse
        off = float(np.asarray(baseline_offsets(xp.asarray(prof[None]), xp))[0])
        assert off == pytest.approx(2.0)
        removed = np.asarray(remove_baseline(xp.asarray(prof[None]), xp))[0]
        assert removed[0] == pytest.approx(0.0)
        assert removed[45] == pytest.approx(50.0)

    def test_cyclic_window(self, xp):
        # the quiet region wraps around the end of the profile
        nbin = 64
        prof = np.full(nbin, 1.0)
        prof[10:58] += 100.0
        off = float(np.asarray(baseline_offsets(xp.asarray(prof[None]), xp))[0])
        assert off == pytest.approx(1.0)


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_weighted_template(xp):
    cube = np.zeros((2, 3, 4))
    cube[0, 0] = [1, 2, 3, 4]
    cube[1, 2] = [10, 20, 30, 40]
    w = np.zeros((2, 3))
    w[0, 0] = 1.0
    w[1, 2] = 3.0
    t = np.asarray(weighted_template(xp.asarray(cube), xp.asarray(w), xp))
    want = (np.array([1, 2, 3, 4]) + 3 * np.array([10, 20, 30, 40])) / 4.0
    np.testing.assert_allclose(t, want)
    # all-zero weights must not divide by zero
    t0 = np.asarray(weighted_template(xp.asarray(cube), xp.zeros((2, 3)), xp))
    np.testing.assert_array_equal(t0, 0.0)

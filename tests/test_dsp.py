"""DSP primitive tests: rotation, dispersion, baseline, scrunching, shared
between the numpy and jax instantiations of ops/dsp.py."""

import jax.numpy as jnp
import numpy as np
import pytest

from iterative_cleaner_tpu.archive import KDM_S
from iterative_cleaner_tpu.ops.dsp import (
    baseline_offsets,
    dedisperse_cube,
    dispersion_shift_bins,
    remove_baseline,
    rotate_bins,
    weighted_template,
)


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestRotate:
    def test_integer_shift_matches_roll(self, xp):
        rng = np.random.default_rng(0)
        x = xp.asarray(rng.normal(size=(3, 4, 16)))
        for s in (0, 1, 5, -3, 16, 21):
            got = np.asarray(rotate_bins(x, float(s), xp, method="fourier"))
            want = np.roll(np.asarray(x), s, axis=-1)
            np.testing.assert_allclose(got, want, atol=1e-9)
            got_roll = np.asarray(rotate_bins(x, float(s), xp, method="roll"))
            np.testing.assert_allclose(got_roll, want, atol=0)

    def test_per_channel_shifts(self, xp):
        rng = np.random.default_rng(1)
        x = xp.asarray(rng.normal(size=(2, 3, 32)))
        shifts = xp.asarray([0.0, 4.0, -7.0])
        got = np.asarray(rotate_bins(x, shifts, xp, method="roll"))
        base = np.asarray(x)
        for c, s in enumerate([0, 4, -7]):
            np.testing.assert_array_equal(got[:, c], np.roll(base[:, c], s, axis=-1))

    def test_roll_jax_matmul_bitexact_vs_numpy_gather(self, xp):
        """The jax roll path (one-hot permutation matmul, MXU-shaped) must be
        bit-identical to the numpy gather path for every dtype/shift shape."""
        if xp is np:
            pytest.skip("cross-path comparison, driven from the jax id")
        rng = np.random.default_rng(7)
        for dtype in (np.float32, np.float64):
            x = rng.normal(size=(5, 9, 32)).astype(dtype)
            for shifts in (np.float64(3.0), np.float64(-11.0),
                           rng.normal(scale=10, size=9)):
                want = rotate_bins(x, shifts, np, method="roll")
                got = np.asarray(rotate_bins(
                    jnp.asarray(x), jnp.asarray(shifts), jnp, method="roll"))
                assert got.dtype == want.dtype
                np.testing.assert_array_equal(got, want)

    def test_fractional_rotation_invertible(self, xp):
        # exact on band-limited profiles (the Nyquist bin of a fractionally
        # rotated real signal attenuates by cos(pi*s); see rotate_bins)
        rng = np.random.default_rng(2)
        raw = rng.normal(size=(4, 64))
        spec = np.fft.rfft(raw, axis=-1)
        spec[..., -1] = 0.0
        x = xp.asarray(np.fft.irfft(spec, n=64, axis=-1))
        s = 2.37
        back = rotate_bins(rotate_bins(x, s, xp), -s, xp)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-9)

    def test_fractional_rotation_nyquist_attenuation(self, xp):
        nbin = 16
        x = xp.asarray(np.cos(np.pi * np.arange(nbin))[None])  # pure Nyquist
        s = 0.5
        out = np.asarray(rotate_bins(x, s, xp))
        np.testing.assert_allclose(
            out, np.asarray(x) * np.cos(np.pi * s), atol=1e-9
        )


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestDispersion:
    def test_shift_sign_and_magnitude(self, xp):
        freqs = xp.asarray([1300.0, 1400.0, 1500.0])
        nbin, period, dm = 256, 0.5, 30.0
        shifts = np.asarray(
            dispersion_shift_bins(freqs, dm, 1400.0, period, nbin, xp)
        )
        assert shifts[1] == pytest.approx(0.0)
        assert shifts[0] > 0  # below the reference frequency arrives later
        assert shifts[2] < 0
        expect0 = KDM_S * dm * (1300.0 ** -2 - 1400.0 ** -2) / period * nbin
        assert shifts[0] == pytest.approx(expect0)

    def test_dispersion_constant_is_tempo_convention(self, xp):
        """The delay constant is PSRCHIVE/tempo's 1/2.41e-4 s MHz^2 per
        pc cm^-3 (the value the reference's dedisperse inherits), not the
        CODATA derivation 4148.808.  Golden: DM=100 across 400->1400 MHz
        delays by 1/2.41e-4 * 100 * (400^-2 - 1400^-2) s."""
        assert KDM_S == pytest.approx(4149.377593360996, abs=1e-9)
        freqs = xp.asarray([400.0, 1400.0])
        shifts = np.asarray(
            dispersion_shift_bins(freqs, 100.0, 1400.0, 1.0, 1, xp))
        assert shifts[0] == pytest.approx(2.381658057413837, rel=1e-9)

    def test_dedisperse_aligns_dispersed_pulse(self, xp):
        nchan, nbin = 8, 128
        freqs = np.linspace(1300.0, 1500.0, nchan)
        period, dm = 0.7, 50.0
        profile = np.exp(-0.5 * ((np.arange(nbin) - 40) / 3.0) ** 2)
        cube = np.broadcast_to(profile, (2, nchan, nbin)).copy()
        dispersed = dedisperse_cube(
            xp.asarray(cube), xp.asarray(freqs), dm, 1400.0, period, xp,
            forward=False,
        )
        restored = dedisperse_cube(
            dispersed, xp.asarray(freqs), dm, 1400.0, period, xp, forward=True
        )
        np.testing.assert_allclose(np.asarray(restored), cube, atol=1e-8)
        # and the dispersed cube really is misaligned across channels
        peaks = np.argmax(np.asarray(dispersed)[0], axis=-1)
        assert len(np.unique(peaks)) > 1


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestBaseline:
    def test_flat_profile_baseline_is_level(self, xp):
        x = xp.asarray(np.full((3, 32), 7.5))
        off = np.asarray(baseline_offsets(x, xp))
        np.testing.assert_allclose(off, 7.5)

    def test_pulse_ignored_by_min_window(self, xp):
        nbin = 100
        prof = np.full(nbin, 2.0)
        prof[40:50] += 50.0  # pulse
        off = float(np.asarray(baseline_offsets(xp.asarray(prof[None]), xp))[0])
        assert off == pytest.approx(2.0)
        removed = np.asarray(remove_baseline(xp.asarray(prof[None]), xp))[0]
        assert removed[0] == pytest.approx(0.0)
        assert removed[45] == pytest.approx(50.0)

    def test_cyclic_window(self, xp):
        # the quiet region wraps around the end of the profile
        nbin = 64
        prof = np.full(nbin, 1.0)
        prof[10:58] += 100.0
        off = float(np.asarray(baseline_offsets(xp.asarray(prof[None]), xp))[0])
        assert off == pytest.approx(1.0)


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_weighted_template(xp):
    cube = np.zeros((2, 3, 4))
    cube[0, 0] = [1, 2, 3, 4]
    cube[1, 2] = [10, 20, 30, 40]
    w = np.zeros((2, 3))
    w[0, 0] = 1.0
    w[1, 2] = 3.0
    t = np.asarray(weighted_template(xp.asarray(cube), xp.asarray(w), xp))
    want = (np.array([1, 2, 3, 4]) + 3 * np.array([10, 20, 30, 40])) / 4.0
    np.testing.assert_allclose(t, want)
    # all-zero weights must not divide by zero
    t0 = np.asarray(weighted_template(xp.asarray(cube), xp.zeros((2, 3)), xp))
    np.testing.assert_array_equal(t0, 0.0)


# --- dispersed-frame iteration identities (engine/loop.py disp_iteration) --


class TestDispIterationIdentities:
    """The three algebraic identities the dispersed-frame fast path rests
    on, pinned numerically so a rotate_bins change that breaks one fails
    HERE and not as an unexplained parity drift."""

    def _fixture(self, nbin=64):
        rng = np.random.default_rng(7)
        x = rng.normal(size=(3, 5, nbin))
        s = rng.uniform(-10, 10, size=5)     # fractional per-channel shifts
        t = rng.normal(size=nbin)
        w = rng.random((3, 5))
        return x, s, t, w

    @pytest.mark.parametrize("nbin", [64, 63])
    def test_fourier_roundtrip_is_rank_one_nyquist(self, nbin):
        """R(s)R(-s)x = x + (cos^2(pi s) - 1) * nyq(x): the fourier
        round trip attenuates exactly the Nyquist component (even nbin);
        odd nbin round-trips exactly (no Nyquist bin)."""
        from iterative_cleaner_tpu.ops.dsp import rotate_bins

        x, s, _, _ = self._fixture(nbin)
        back = rotate_bins(rotate_bins(x, -s, np, method="fourier"), s, np,
                           method="fourier")
        if nbin % 2:
            np.testing.assert_allclose(back, x, rtol=0, atol=1e-12)
            return
        alt = (-1.0) ** np.arange(nbin)
        nyq = (x @ alt)[..., None] * alt / nbin
        pred = x + (np.cos(np.pi * s)[None, :, None] ** 2 - 1.0) * nyq
        np.testing.assert_allclose(back, pred, rtol=0, atol=1e-12)

    @pytest.mark.parametrize("method", ["fourier", "roll"])
    def test_fit_adjoint_identity(self, method):
        """<R(-s)x, t> == <x, R(s)t> EXACTLY (to fp): rotation is
        self-adjoint up to shift sign, Nyquist attenuation included — the
        dispersed-frame fit needs NO correction term."""
        from iterative_cleaner_tpu.ops.dsp import rotate_bins

        x, s, t, _ = self._fixture()
        if method == "roll":
            s = np.round(s)
        ded = rotate_bins(x, -s, np, method=method)
        rot_t = rotate_bins(np.broadcast_to(t, (5, len(t))), s, np,
                            method=method)
        lhs = np.einsum("scb,b->sc", ded, t)
        rhs = np.einsum("scb,cb->sc", x, rot_t)
        np.testing.assert_allclose(lhs, rhs, rtol=1e-10, atol=1e-10)

    @pytest.mark.parametrize("method", ["fourier", "roll"])
    def test_template_marginal_identity(self, method):
        """sum_{s,c} w * R(-s)disp == sum_c R_c(-s)(A_c) where A is the
        per-channel weighted marginal — the template never needs the
        dedispersed cube."""
        from iterative_cleaner_tpu.ops.dsp import (
            rotate_bins,
            template_numerator_from_channel_profiles,
            weighted_marginal_totals,
        )

        x, s, _, w = self._fixture()
        if method == "roll":
            s = np.round(s)
        ded = rotate_bins(x, -s, np, method=method)
        direct = np.einsum("sc,scb->b", w, ded)
        a, t1 = weighted_marginal_totals(x, w, np)
        via_a = template_numerator_from_channel_profiles(a, s, method, np)
        np.testing.assert_allclose(via_a, direct, rtol=1e-12, atol=1e-12)
        # and the sibling marginal is the correction's per-subint totals
        np.testing.assert_allclose(t1, np.einsum("sc,scb->sb", w, x),
                                   rtol=1e-13)

    def test_disp_iteration_scores_match_faithful_path(self):
        """End-to-end teeth: the dispersed-frame engine's SCORES (not just
        masks) reproduce the faithful double-rotation path to fp-noise
        level on the default fourier config."""
        import jax.numpy as jnp

        from iterative_cleaner_tpu.engine.loop import clean_dedispersed_jax
        from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive
        from iterative_cleaner_tpu.ops.dsp import (
            prepare_cube_with_correction,
        )

        ar, _ = make_synthetic_archive(nsub=10, nchan=14, nbin=64, seed=3,
                                       n_rfi_cells=4, n_prezapped=6,
                                       dtype=np.float64)
        cube = jnp.asarray(ar.total_intensity(), dtype=jnp.float64)
        w = jnp.asarray(ar.weights, dtype=jnp.float64)
        f = jnp.asarray(ar.freqs_mhz, dtype=jnp.float64)
        ded, shifts, corr = prepare_cube_with_correction(
            cube, w, f, ar.dm, ar.centre_freq_mhz, ar.period_s, jnp,
            baseline_duty=0.15, rotation="fourier",
            baseline_mode="integration")
        kw = dict(max_iter=3, chanthresh=5.0, subintthresh=5.0,
                  pulse_slice=(0, 0), pulse_scale=1.0, pulse_active=False,
                  rotation="fourier", baseline_corr=corr)
        old = clean_dedispersed_jax(ded, w, shifts, disp_iteration=False,
                                    **kw)
        new = clean_dedispersed_jax(ded, w, shifts, disp_iteration=True,
                                    **kw)
        np.testing.assert_array_equal(np.asarray(old.final_weights) == 0,
                                      np.asarray(new.final_weights) == 0)
        assert int(old.loops) == int(new.loops)
        np.testing.assert_allclose(np.asarray(new.scores),
                                   np.asarray(old.scores),
                                   rtol=1e-11, atol=1e-11)


def test_fourier_2d_matmul_branch_f32():
    """Direct numeric pin of the float32 2-D fourier MATMUL branch (the
    rfft->phase->irfft three-matmul decomposition): the conftest enables
    x64, so the engine-level tests run float64 and route to the FFT path
    — this is the only test that drives the branch itself.  Checked
    against the float64 FFT reference AND the 3-D operator-tensor route
    (same branch family, independently constructed)."""
    import jax
    import jax.numpy as jnp

    from iterative_cleaner_tpu.ops.dsp import (
        _use_matmul_rotation,
        rotate_bins,
    )

    rng = np.random.default_rng(0)
    for nchan, nbin in [(150, 64), (37, 63)]:  # even + odd nbin
        x = rng.normal(size=(nchan, nbin)).astype(np.float32)
        s = rng.uniform(-9, 9, nchan).astype(np.float32)
        xj, sj = jnp.asarray(x), jnp.asarray(s)
        assert _use_matmul_rotation(xj, sj, jnp, "fourier")
        y2 = np.asarray(jax.jit(
            lambda a, b: rotate_bins(a, b, jnp, "fourier"))(xj, sj))
        yf = rotate_bins(x.astype(np.float64), s.astype(np.float64), np,
                         "fourier")
        y3 = np.asarray(jax.jit(
            lambda a, b: rotate_bins(a, b, jnp, "fourier"))(
            xj[None], sj))[0]
        scale = np.abs(yf).max()
        assert np.abs(y2 - yf).max() < 5e-5 * scale
        assert np.abs(y2 - y3).max() < 5e-5 * scale
        # integer shifts must be numerically exact rotations (Nyquist
        # attenuation cos(pi*s) == +-1)
        si = jnp.asarray(np.round(s))
        yi = np.asarray(jax.jit(
            lambda a, b: rotate_bins(a, b, jnp, "fourier"))(xj, si))
        want = rotate_bins(x.astype(np.float64), np.round(s), np, "roll")
        assert np.abs(yi - want).max() < 5e-5 * scale

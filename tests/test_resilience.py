"""Resilience-ladder tests (resilience/ + parallel/fleet.py wiring):
fault-injector units, error classification, retry/backoff, watchdog
deadlines, journal semantics, atomic output writes, a seeded multi-site
fault soak with bit-equal masks and exactly-once accounting, OOM
degradation to the numpy backend, journaled resume (in-process and after
a real ``kill -9``), and the CLI flag contracts."""

import dataclasses
import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io import (
    load_archive,
    make_synthetic_archive,
    save_archive,
)
from iterative_cleaner_tpu.io.atomic import atomic_output
from iterative_cleaner_tpu.parallel.fleet import clean_fleet
from iterative_cleaner_tpu.resilience import (
    OOM,
    PERMANENT,
    TIMEOUT,
    TRANSIENT,
    FaultInjector,
    FaultSpecError,
    FleetJournal,
    InjectedFault,
    InjectedPermanentFault,
    ResiliencePlan,
    RetryPolicy,
    StageTimeout,
    SyntheticResourceExhausted,
    call_with_deadline,
    classify_error,
    entry_is_current,
    parse_fault_spec,
    resolve_retries,
    resolve_stage_timeout,
    run_with_retries,
)
from iterative_cleaner_tpu.telemetry import MetricsRegistry
from iterative_cleaner_tpu.utils.checkpoint import config_hash
from tests.conftest import repo_subprocess_env

CFG = CleanConfig(backend="jax", rotation="roll", fft_mode="dft",
                  dtype="float64", max_iter=3)
# a fast policy for tests that exercise retries: real backoff times would
# dominate the suite
FAST = RetryPolicy(max_retries=3, backoff_base_s=0.001, backoff_cap_s=0.01)


def _write_fleet(tmp_path, geometries, ext=".npz"):
    paths = []
    for i, (nsub, nchan, nbin) in enumerate(geometries):
        ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                       seed=40 + i)
        p = str(tmp_path / ("fleet_%02d%s" % (i, ext)))
        save_archive(ar, p)
        paths.append(p)
    return paths


# ------------------------------------------------------------- fault spec

def test_parse_fault_spec_grammar():
    rules = parse_fault_spec("load:0.1,exec:oom@2,write:once,compile:err,"
                             "peek:perm@3,execute:hang@1")
    by = {(r.site, r.kind): r for r in rules}
    assert by[("load", "err")].prob == pytest.approx(0.1)
    assert by[("execute", "oom")].at == 2          # exec aliases execute
    assert by[("write", "err")].at == 1            # once == err@1
    assert by[("compile", "err")].at == 0          # bare kind: every call
    assert by[("peek", "perm")].at == 3
    assert by[("execute", "hang")].at == 1
    assert parse_fault_spec("") == ()
    assert parse_fault_spec(" , ") == ()


@pytest.mark.parametrize("bad", [
    "load", "load:", "bogus:err", "load:maybe", "load:2.0", "load:0",
    "load:err@0", "load:err@x", "load:0.5@2",
])
def test_parse_fault_spec_rejects(bad):
    with pytest.raises(FaultSpecError):
        parse_fault_spec(bad)


def test_injector_at_n_fires_exactly_once():
    inj = FaultInjector("load:err@3", seed=0)
    inj.fire("load")
    inj.fire("load")
    with pytest.raises(InjectedFault):
        inj.fire("load")
    inj.fire("load")                               # call 4: rule is spent
    assert inj.calls["load"] == 4
    assert inj.injected["load"] == 1


def test_injector_kinds_and_counters():
    reg = MetricsRegistry()
    inj = FaultInjector("load:oom@1,write:perm@1,peek:hang@1",
                        seed=0, hang_s=0.01, registry=reg)
    with pytest.raises(SyntheticResourceExhausted) as ei:
        inj.fire("load")
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    with pytest.raises(InjectedPermanentFault):
        inj.fire("write")
    t0 = time.perf_counter()
    inj.fire("peek")                               # hang: sleeps, no raise
    assert time.perf_counter() - t0 >= 0.01
    assert reg.counters["fault_injected"] == 3


def test_injector_probability_draws_are_functional():
    # same (seed, site, kind, call index) -> same verdict, whatever order
    # racing workers reach their calls in; a different seed reshuffles
    def verdicts(seed):
        inj = FaultInjector("load:0.5", seed=seed)
        out = []
        for _ in range(64):
            try:
                inj.fire("load")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    a, b = verdicts(7), verdicts(7)
    assert a == b
    assert any(a) and not all(a)
    assert verdicts(8) != a


def test_injector_from_env(monkeypatch):
    monkeypatch.delenv("ICLEAN_FAULTS", raising=False)
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("ICLEAN_FAULTS", "load:err@1")
    monkeypatch.setenv("ICLEAN_FAULT_SEED", "9")
    inj = FaultInjector.from_env()
    assert inj is not None and inj.seed == 9
    plan = ResiliencePlan.from_env(CFG)
    assert plan.faults is not None


# ----------------------------------------------------- classify and retry

def test_classify_error():
    assert classify_error(SyntheticResourceExhausted(
        "RESOURCE_EXHAUSTED: injected")) == OOM
    assert classify_error(RuntimeError(
        "XlaRuntimeError: RESOURCE_EXHAUSTED: Out of memory")) == OOM
    assert classify_error(RuntimeError("device out of memory")) == OOM
    assert classify_error(StageTimeout("t")) == TIMEOUT
    assert classify_error(ValueError("corrupt")) == PERMANENT
    assert classify_error(InjectedPermanentFault("x")) == PERMANENT
    assert classify_error(OSError("flaky fs")) == TRANSIENT
    assert classify_error(InjectedFault("x")) == TRANSIENT


def test_retry_policy_backoff_bounded():
    pol = RetryPolicy(max_retries=5, backoff_base_s=0.05,
                      backoff_factor=2.0, backoff_cap_s=0.15)
    assert [pol.backoff(k) for k in range(4)] == \
        pytest.approx([0.05, 0.10, 0.15, 0.15])
    with pytest.raises(ValueError):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError):
        RetryPolicy(backoff_factor=0.5)


def test_run_with_retries_absorbs_transients():
    reg = MetricsRegistry()
    slept = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    out = run_with_retries(flaky, stage="load", policy=FAST, registry=reg,
                           sleep=slept.append)
    assert out == "ok" and calls["n"] == 3
    assert reg.counters["fleet_retries"] == 2
    assert slept == pytest.approx([FAST.backoff(0), FAST.backoff(1)])


def test_run_with_retries_permanent_and_oom_propagate():
    for exc in (ValueError("corrupt"),
                SyntheticResourceExhausted("RESOURCE_EXHAUSTED: x")):
        calls = {"n": 0}

        def once(exc=exc):
            calls["n"] += 1
            raise exc

        with pytest.raises(type(exc)):
            run_with_retries(once, stage="load", policy=FAST,
                             sleep=lambda s: None)
        assert calls["n"] == 1                    # never retried


def test_run_with_retries_budget_exhausts():
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError("transient")

    with pytest.raises(OSError):
        run_with_retries(always, stage="load",
                         policy=RetryPolicy(max_retries=2,
                                            backoff_base_s=0.0),
                         sleep=lambda s: None)
    assert calls["n"] == 3                        # 1 try + 2 retries


def test_call_with_deadline():
    assert call_with_deadline(lambda: 5, None, "x") == 5
    assert call_with_deadline(lambda: 5, 0, "x") == 5   # 0 = off, inline
    reg = MetricsRegistry()
    with pytest.raises(StageTimeout):
        call_with_deadline(lambda: time.sleep(2.0), 0.05, "execute",
                           registry=reg)
    assert reg.counters["fleet_watchdog_trips"] == 1
    with pytest.raises(KeyError):                 # errors pass through
        call_with_deadline(lambda: {}[1], 1.0, "x")


def test_resolve_env_mirrors(monkeypatch):
    monkeypatch.delenv("ICLEAN_RETRIES", raising=False)
    monkeypatch.delenv("ICLEAN_STAGE_TIMEOUT", raising=False)
    assert resolve_retries() == 2
    assert resolve_retries(5) == 5
    assert resolve_stage_timeout() is None
    assert resolve_stage_timeout(0) is None
    assert resolve_stage_timeout(1.5) == 1.5
    monkeypatch.setenv("ICLEAN_RETRIES", "7")
    monkeypatch.setenv("ICLEAN_STAGE_TIMEOUT", "2.5")
    assert resolve_retries() == 7
    assert resolve_stage_timeout() == 2.5
    assert resolve_retries(1) == 1                # explicit beats env
    with pytest.raises(ValueError):
        resolve_retries(-1)
    with pytest.raises(ValueError):
        resolve_stage_timeout(-1.0)


# ---------------------------------------------------------------- journal

def test_journal_roundtrip_and_staleness(tmp_path):
    paths = _write_fleet(tmp_path, [(6, 16, 32), (8, 16, 32)])
    out = str(tmp_path / "out.npz")
    save_archive(load_archive(paths[0]), out)
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    h = config_hash(CFG)
    j.record_done(paths[0], config_hash=h, out_path=out)
    j.record_done(paths[1], config_hash=h)
    done = j.completed(h)
    assert set(done) == {os.path.abspath(p) for p in paths}
    assert all(entry_is_current(e) for e in done.values())
    # a different config hash sees nothing
    assert j.completed("feedbeef") == {}
    # rewritten input -> stale
    ar, _ = make_synthetic_archive(nsub=6, nchan=16, nbin=32, seed=99)
    save_archive(ar, paths[0])
    assert not entry_is_current(j.completed(h)[os.path.abspath(paths[0])])
    # missing recorded output -> stale
    j.record_done(paths[0], config_hash=h, out_path=out)
    os.remove(out)
    assert not entry_is_current(j.completed(h)[os.path.abspath(paths[0])])


def test_journal_skips_torn_tail(tmp_path):
    paths = _write_fleet(tmp_path, [(6, 16, 32)])
    j = FleetJournal(str(tmp_path / "j.jsonl"))
    h = config_hash(CFG)
    j.record_done(paths[0], config_hash=h)
    with open(j.path, "a") as f:
        f.write('{"schema": "icln-fleet-journal/1", "event": "done", "pa')
    done = j.completed(h)                          # torn line: skipped
    assert set(done) == {os.path.abspath(paths[0])}
    # config identity excludes the resilience knobs: a resume under a
    # different retry budget still matches
    assert config_hash(dataclasses.replace(
        CFG, fleet_retries=9, stage_timeout_s=1.0)) == h


def test_atomic_output_never_leaves_partials(tmp_path):
    path = str(tmp_path / "out.bin")
    with open(path, "wb") as f:
        f.write(b"old")
    with pytest.raises(RuntimeError):
        with atomic_output(path) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"partial")
            raise RuntimeError("crash mid-write")
    assert open(path, "rb").read() == b"old"       # target untouched
    with atomic_output(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(b"new")
    assert open(path, "rb").read() == b"new"
    assert glob.glob(str(tmp_path / "*.tmp")) == []
    ar, _ = make_synthetic_archive(nsub=4, nchan=8, nbin=16, seed=1)
    for ext in (".npz", ".icar"):
        save_archive(ar, str(tmp_path / ("a" + ext)))
    assert glob.glob(str(tmp_path / "*.tmp")) == []


# ----------------------------------------------------------- fleet wiring

def test_on_error_callback_exception_is_swallowed(tmp_path):
    paths = _write_fleet(tmp_path, [(6, 16, 32), (8, 16, 32)])
    reg = MetricsRegistry()

    def bad_callback(path, exc, stage):
        raise RuntimeError("broken telemetry hook")

    rep = clean_fleet(paths + [str(tmp_path / "missing.npz")], CFG,
                      registry=reg, io_workers=1, on_error=bad_callback,
                      resilience=ResiliencePlan(retry=FAST))
    assert len(rep.results) == 2                   # fleet survived
    assert len(rep.failures) == 1
    assert reg.counters["fleet_callback_errors"] == 1


@pytest.mark.slow
def test_fault_soak_bit_equal_and_exactly_once(tmp_path):
    """12 mixed-geometry archives under deterministic faults at every
    site: the run terminates well inside a global deadline, recovers
    every archive, accounts each path exactly once, and all surviving
    masks are bit-equal to the fault-free serve."""
    geoms = [(6 + 2 * (i % 3), 16, 32) for i in range(12)]
    paths = _write_fleet(tmp_path, geoms)
    base = clean_fleet(paths, CFG, io_workers=1, group_size=2)
    assert base.ok

    inj = FaultInjector(
        "peek:err@3,load:err@2,load:err@7,compile:err@1,"
        "execute:err@2,execute:oom@4,write:err@3", seed=0)
    jpath = str(tmp_path / "soak.jsonl")
    reg = MetricsRegistry()
    plan = ResiliencePlan(faults=inj, retry=FAST,
                          journal=FleetJournal(jpath))
    wrote = []
    lock = threading.Lock()

    def write_fn(path, ar, result):
        with lock:
            wrote.append(path)

    rep = call_with_deadline(
        lambda: clean_fleet(paths, CFG, registry=reg, io_workers=1,
                            group_size=2, resilience=plan,
                            write_fn=write_fn),
        60.0, "soak")                              # the no-hang guarantee
    assert rep.ok, rep.failures
    # exactly-once: every path lands in exactly one bucket of the report
    assert sorted(rep.results) == sorted(paths)
    assert rep.skipped == [] and rep.failures == []
    assert sorted(wrote) == sorted(paths)          # one write per archive
    assert len(plan.journal.completed(config_hash(CFG))) == len(paths)
    # the drills actually fired and were absorbed
    assert reg.counters["fault_injected"] >= 6
    assert rep.n_retries >= 4                      # peek+load+exec+write
    assert rep.n_oom_splits >= 1
    assert rep.n_degraded == 0                     # splits absorbed the OOM
    for p in paths:
        assert np.array_equal(base.results[p].final_weights,
                              rep.results[p].final_weights), p


def test_oom_degrades_to_numpy_bit_equal(tmp_path):
    """Every execute OOMs: the ladder splits to singletons, the singleton
    still OOMs, and each archive degrades to the numpy backend — same
    masks, nothing lost."""
    paths = _write_fleet(tmp_path, [(6, 16, 32), (8, 16, 32),
                                    (6, 16, 32)])
    base = clean_fleet(paths, CFG, io_workers=1, group_size=2)
    reg = MetricsRegistry()
    rep = clean_fleet(paths, CFG, registry=reg, io_workers=1, group_size=2,
                      resilience=ResiliencePlan(
                          faults=FaultInjector("execute:oom", seed=0),
                          retry=FAST))
    assert rep.ok, rep.failures
    assert rep.n_degraded == len(paths)
    assert rep.n_oom_splits >= 1
    assert reg.counters["fleet_degraded"] == len(paths)
    for p in paths:
        assert np.array_equal(base.results[p].final_weights,
                              rep.results[p].final_weights), p


def test_watchdog_fails_hung_execute(tmp_path):
    paths = _write_fleet(tmp_path, [(6, 16, 32), (6, 16, 32)])
    reg = MetricsRegistry()
    rep = clean_fleet(paths, CFG, registry=reg, io_workers=1, group_size=2,
                      resilience=ResiliencePlan(
                          faults=FaultInjector("execute:hang@1", seed=0,
                                               hang_s=1.5),
                          retry=FAST, stage_timeout_s=0.2))
    assert rep.n_watchdog_trips >= 1
    assert reg.counters["fleet_watchdog_trips"] >= 1
    # the hung group failed, the fleet did not wedge: every path is
    # accounted (hang@1 wedges the single group both archives share)
    assert {p for p, stage, _ in rep.failures} == set(paths)
    assert all(stage == "clean" for _, stage, _ in rep.failures)
    assert isinstance(rep.failures[0][2], StageTimeout)


def test_write_failure_keeps_result_and_failure(tmp_path):
    paths = _write_fleet(tmp_path, [(6, 16, 32)])

    def write_fn(path, ar, result):
        raise InjectedPermanentFault("disk full")  # permanent: no retries

    rep = clean_fleet(paths, CFG, io_workers=1,
                      resilience=ResiliencePlan(retry=FAST),
                      write_fn=write_fn)
    # the clean is real, only the output is missing: both recorded
    assert paths[0] in rep.results
    assert [(p, s) for p, s, _ in rep.failures] == [(paths[0], "write")]


def test_resume_skips_journaled_and_recleans_modified(tmp_path):
    paths = _write_fleet(tmp_path, [(6, 16, 32), (8, 16, 32),
                                    (6, 16, 32)])
    jpath = str(tmp_path / "j.jsonl")

    def out_path(p):
        return p + "_cleaned.npz"

    def write_fn(p, ar, result):
        out = dataclasses.replace(
            ar, weights=np.asarray(result.final_weights,
                                   dtype=ar.weights.dtype))
        save_archive(out, out_path(p))

    plan = ResiliencePlan(retry=FAST, journal=FleetJournal(jpath))
    rep1 = clean_fleet(paths, CFG, io_workers=1, group_size=2,
                       resilience=plan, write_fn=write_fn,
                       out_path_fn=out_path)
    assert rep1.ok and len(rep1.results) == 3

    # resume over an untouched fleet: everything skips, nothing re-cleans
    reg = MetricsRegistry()
    rep2 = clean_fleet(paths, CFG, registry=reg, io_workers=1, group_size=2,
                       resilience=ResiliencePlan(
                           retry=FAST, journal=FleetJournal(jpath),
                           resume=True),
                       write_fn=write_fn, out_path_fn=out_path)
    assert rep2.ok and rep2.results == {}
    assert sorted(rep2.skipped) == sorted(paths)
    assert reg.counters["fleet_resumed_skips"] == 3

    # a rewritten input invalidates only its own entry
    ar, _ = make_synthetic_archive(nsub=6, nchan=16, nbin=32, seed=77)
    save_archive(ar, paths[1])
    rep3 = clean_fleet(paths, CFG, io_workers=1, group_size=2,
                       resilience=ResiliencePlan(
                           retry=FAST, journal=FleetJournal(jpath),
                           resume=True),
                       write_fn=write_fn, out_path_fn=out_path)
    assert rep3.ok
    assert list(rep3.results) == [paths[1]]
    assert sorted(rep3.skipped) == sorted([paths[0], paths[2]])
    # a resume under a different config hash trusts nothing
    rep4 = clean_fleet(paths, dataclasses.replace(CFG, max_iter=2),
                       io_workers=1, group_size=2,
                       resilience=ResiliencePlan(
                           retry=FAST, journal=FleetJournal(jpath),
                           resume=True))
    assert rep4.skipped == []


# ----------------------------------------------------- CLI and kill-resume

def test_cli_resilience_flags_require_fleet():
    from iterative_cleaner_tpu.cli import main

    for argv in (["--resume", "x.npz"],
                 ["--retries", "3", "x.npz"],
                 ["--stage-timeout", "5", "x.npz"],
                 ["--faults", "load:once", "x.npz"],
                 ["--journal", "j.jsonl", "x.npz"]):
        with pytest.raises(SystemExit) as ei:
            main(argv)
        assert ei.value.code == 2
    with pytest.raises(SystemExit) as ei:
        main(["--fleet", "--faults", "bogus:xyz", "x.npz"])
    assert ei.value.code == 2
    with pytest.raises(SystemExit) as ei:
        main(["--fleet", "--retries", "-1", "x.npz"])
    assert ei.value.code == 2


def _run_cli(args, tmp_path, **env):
    return subprocess.run(
        [sys.executable, "-m", "iterative_cleaner_tpu", *args],
        env=repo_subprocess_env(ICLEAN_PROBE_TIMEOUT="0", **env),
        cwd=str(tmp_path), capture_output=True, text=True, timeout=240)


@pytest.mark.slow
def test_kill9_then_resume_no_duplicate_cleans(tmp_path):
    """The crash-safety contract end-to-end through the real CLI: wedge a
    fleet run mid-serve with a hang fault, ``kill -9`` it, rerun with
    ``--resume`` — every archive cleans exactly once across the two runs
    and the final outputs are byte-identical to an uninterrupted serve.
    ``.icar`` outputs are raw little-endian arrays (no container
    timestamps), so byte comparison is exact."""
    geoms = [(6, 16, 32)] * 8
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    paths = _write_fleet(tmp_path, geoms, ext=".icar")
    ref_paths = _write_fleet(ref_dir, geoms, ext=".icar")
    base = ["--fleet", "--batch", "2", "--io-workers", "1",
            "--rotation", "roll", "--fft_mode", "dft", "--max_iter", "3",
            "-q"]

    # reference: one uninterrupted run
    r = _run_cli(base + [os.path.basename(p) for p in ref_paths], ref_dir)
    assert r.returncode == 0, r.stderr[-2000:]

    # run 1: the 5th load call hangs for 600s -> the pipeline wedges
    # after two groups; SIGKILL once the journal shows progress
    proc = subprocess.Popen(
        [sys.executable, "-m", "iterative_cleaner_tpu", *base,
         "--journal", "j.jsonl", "--faults", "load:hang@5",
         *[os.path.basename(p) for p in paths]],
        env=repo_subprocess_env(ICLEAN_PROBE_TIMEOUT="0",
                                ICLEAN_FAULT_HANG_S="600"),
        cwd=str(tmp_path), stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    # exactly 4 archives (groups 0-1) complete before load 5 wedges the
    # single IO thread; once their 4 journal lines land the journal is
    # quiescent, so the SIGKILL below cannot race an in-flight append
    jpath = tmp_path / "j.jsonl"
    deadline = time.time() + 180
    while time.time() < deadline:
        text = jpath.read_text() if jpath.exists() else ""
        if text.endswith("\n") and len(text.strip().splitlines()) >= 4:
            break
        if proc.poll() is not None:
            pytest.fail("wedged CLI run exited early (rc %s)"
                        % proc.returncode)
        time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("journal never showed progress before the deadline")
    os.kill(proc.pid, signal.SIGKILL)
    assert proc.wait(timeout=60) == -signal.SIGKILL
    pre = [json.loads(ln) for ln in jpath.read_text().strip().splitlines()
           if ln.strip()]
    assert len(pre) == 4                           # partial, crash-safe

    # run 2: --resume over the same journal, no faults
    r2 = _run_cli(base[:-1] + ["--journal", "j.jsonl", "--resume",
                               *[os.path.basename(p) for p in paths]],
                  tmp_path)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert ("resumed: %d archive" % len(pre)) in r2.stdout, r2.stdout

    # exactly-once: each path appears once in the final journal, and the
    # resumed run re-cleaned only the missing archives
    entries = [json.loads(ln)
               for ln in jpath.read_text().strip().splitlines()
               if ln.strip()]
    assert len(entries) == 8
    assert len({e["path"] for e in entries}) == 8
    # outputs byte-identical to the uninterrupted reference serve
    for p, rp in zip(paths, ref_paths):
        out, ref_out = p + "_cleaned.icar", rp + "_cleaned.icar"
        assert os.path.exists(out), out
        with open(out, "rb") as a, open(ref_out, "rb") as b:
            assert a.read() == b.read(), os.path.basename(out)

"""Interleaving model checker (analysis/interleave.py): the journal-lease
protocol under systematic schedule exploration.

The contract has two halves.  Soundness: every seeded-bug scenario — an
in-memory revert of a known fix (the PR-12 admit-ordering and pool-count
fixes among them) — must be CAUGHT, with a minimized counterexample
schedule that replays deterministically.  Completeness-in-the-small: the
clean scenarios explore exhaustively (DFS terminates before the
schedule cap) and come back green, and partial-order reduction shrinks
the schedule count without losing any bug.

Also here: the one-line regression tests for the shared-state fixes the
thread rules surfaced in this PR (membership join stamping the
heartbeat throttle under the lock; the serve daemon's ``_state_lock``).
"""

import threading

import pytest

from iterative_cleaner_tpu.analysis.interleave import (
    SCENARIOS,
    build_scenario,
    explore,
    render_counterexample,
    run_schedule,
)

ALL_BUGS = [(name, bug) for name in sorted(SCENARIOS)
            for bug in SCENARIOS[name]]


# ------------------------------------------------------------ soundness

@pytest.mark.parametrize("name,bug", ALL_BUGS,
                         ids=[f"{n}--{b}" for n, b in ALL_BUGS])
def test_seeded_bug_is_caught_with_minimized_counterexample(name, bug):
    res = explore(build_scenario(name, bug=bug), max_schedules=5000,
                  budget_s=60.0)
    assert not res.ok, f"seeded bug {name}/{bug} escaped the checker"
    cx = res.counterexample
    assert cx is not None and cx.failure is not None
    # the minimized schedule must REPLAY to the same failure
    replay = run_schedule(build_scenario(name, bug=bug), cx.choices)
    assert replay.failure is not None
    assert replay.failure["type"] == cx.failure["type"]
    # and render as a numbered, human-replayable trace
    text = render_counterexample(cx)
    assert "step" in text and "schedule=" in text


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_clean_scenario_explores_exhaustively_green(name):
    res = explore(build_scenario(name), max_schedules=5000,
                  budget_s=90.0)
    assert res.ok, res.render()
    assert not res.budget_exhausted, \
        f"{name} did not finish its exhaustive sweep: {res.render()}"
    assert res.schedules > 1  # a real exploration, not a single run


# ---------------------------------------------------------- determinism

def test_same_prefix_replays_the_same_schedule():
    scenario = build_scenario("claim-race")
    a = run_schedule(scenario, ())
    b = run_schedule(build_scenario("claim-race"), a.choices)
    assert a.choices == b.choices
    assert [d.op for d in a.decisions] == [d.op for d in b.decisions]


def test_random_mode_is_seed_deterministic():
    runs = []
    for _ in range(2):
        res = explore(build_scenario("admit-order", bug="admit-order"),
                      mode="random", seed=7, max_schedules=200,
                      budget_s=60.0)
        assert not res.ok
        runs.append(res.counterexample.choices)
    assert runs[0] == runs[1]


# -------------------------------------------------- POR: sound + smaller

def test_por_prunes_schedules_without_losing_the_race():
    full = explore(build_scenario("claim-race", bug="no-readback"),
                   por=False, max_schedules=5000, budget_s=60.0)
    pruned = explore(build_scenario("claim-race", bug="no-readback"),
                     por=True, max_schedules=5000, budget_s=60.0)
    assert not full.ok and not pruned.ok  # both find the bug
    clean_full = explore(build_scenario("claim-race"), por=False,
                         max_schedules=5000, budget_s=60.0)
    clean_pruned = explore(build_scenario("claim-race"), por=True,
                           max_schedules=5000, budget_s=60.0)
    assert clean_full.ok and clean_pruned.ok
    assert clean_pruned.schedules < clean_full.schedules


# ------------------------------------------------------------- bounds

def test_budget_bounds_the_sweep():
    res = explore(build_scenario("pool-count"), max_schedules=5000,
                  budget_s=0.0)
    assert res.ok and res.budget_exhausted
    assert res.schedules <= 1


def test_max_schedules_bounds_the_sweep():
    res = explore(build_scenario("pool-count"), max_schedules=3,
                  budget_s=60.0)
    assert res.ok and res.budget_exhausted
    assert res.schedules == 3


def test_max_steps_aborts_a_runaway_schedule():
    res = run_schedule(build_scenario("eviction-edge"), max_steps=2)
    assert res.failure is not None
    assert "max_steps" in res.failure["message"]


def test_unknown_scenario_and_bug_are_rejected():
    with pytest.raises(ValueError, match="unknown scenario"):
        build_scenario("nope")
    with pytest.raises(ValueError, match="no seeded bug"):
        build_scenario("claim-race", bug="admit-order")


# ------------------------------------- regression: this PR's audit fixes

def test_membership_join_stamps_throttle_under_the_lock(tmp_path):
    """join() must publish the throttle stamp atomically with _joined:
    an auto-beat thread racing join must never see a torn pair (joined
    but stamp 0.0 → immediate spurious double-beat)."""
    from iterative_cleaner_tpu.resilience.journal import FleetJournal
    from iterative_cleaner_tpu.serve.membership import PoolMembership

    j = FleetJournal(str(tmp_path / "j.jsonl"))
    m = PoolMembership(j, ttl_s=30.0, member_id="m1", host=1)
    m.join(now=100.0)
    assert m.heartbeat(now=100.0 + 30.0 / 3 - 0.01) is False  # throttled
    assert m.heartbeat(now=100.0 + 30.0 / 3 + 0.01) is True


def test_daemon_guards_its_cross_thread_maps_with_one_lock(tmp_path):
    """The HTTP handler threads and the worker loop share _streams /
    _root_spans / _pool_fold / _journal_read_ts; every write goes
    through the single leaf _state_lock."""
    from iterative_cleaner_tpu.config import CleanConfig, ServeConfig
    from iterative_cleaner_tpu.serve.daemon import ServeDaemon

    cfg = ServeConfig(journal_path=str(tmp_path / "j.jsonl"),
                      http_port=0, flight_recorder="")
    d = ServeDaemon(cfg, CleanConfig(backend="numpy", max_iter=2),
                    quiet=True)
    assert isinstance(d._state_lock, type(threading.Lock()))

"""Pod-scale sharded fused sweep (parallel/shard_sweep.py) on the forced
8-device virtual CPU mesh: the one-launch iteration tail sharded over the
('sub', 'chan') cell grid, per-shard diagnostics staged through the
double-buffered HBM→VMEM DMA pipeline, cross-device combine as
tree-reduced kth-select merges.

The central contract is inherited from the single-device sweep
(tests/test_fused_sweep.py) and extended across the mesh: masks and
scores are BIT-EQUAL to the single-device fused sweep — and so to the
multi-kernel route — at every mesh shape, frame, and Nyquist mode.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from iterative_cleaner_tpu.parallel.mesh import cell_mesh
from iterative_cleaner_tpu.parallel.shard_sweep import (
    sharded_fused_sweep,
    sharded_fused_sweep_dedisp,
    sharded_sweep_eligible,
    sweep_downgrade_reason,
)
from iterative_cleaner_tpu.stats import pallas_kernels as pk

CH, ST = 4.0, 4.0


def _case(nsub=8, nchan=16, nbin=32, seed=3):
    rng = np.random.default_rng(seed)
    f32 = np.float32
    ded = jnp.asarray(rng.normal(size=(nsub, nchan, nbin)).astype(f32))
    disp = jnp.asarray(rng.normal(size=(nsub, nchan, nbin)).astype(f32))
    rot_t = jnp.asarray(rng.normal(size=(nchan, nbin)).astype(f32))
    nyq = jnp.asarray((rng.normal(size=(nchan, nbin)) * 0.01).astype(f32))
    t = jnp.asarray(rng.normal(size=(nbin,)).astype(f32))
    win = jnp.asarray((np.arange(nbin) < nbin // 3).astype(f32))
    w = rng.uniform(0.5, 2.0, size=(nsub, nchan)).astype(f32)
    w[rng.uniform(size=(nsub, nchan)) < 0.2] = 0.0
    m = w == 0
    return ded, disp, rot_t, nyq, t, win, jnp.asarray(w), jnp.asarray(m)


def _assert_triple_equal(got, want):
    for name, g, e in zip(("new_weights", "scores", "d_std"), got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e),
                                      err_msg=name)


# ------------------------------------------------ kernel-level mesh parity

@pytest.mark.parametrize("ndev", [2, 8])
def test_sharded_sweep_dedispersed_bit_equal(ndev):
    """Dedispersed-frame sharded sweep vs the single-device one-launch
    kernel, both jitted (the flavour the engine always runs)."""
    ded, _, _, _, t, win, w, m = _case()
    want = jax.jit(lambda *a: pk.fused_sweep_pallas_dedisp(*a, CH, ST))(
        ded, t, win, w, m)
    mesh = cell_mesh(ndev)
    assert sharded_sweep_eligible(mesh, *ded.shape)
    got = jax.jit(lambda *a: sharded_fused_sweep_dedisp(mesh, *a, CH, ST))(
        ded, t, win, w, m)
    _assert_triple_equal(got, want)


@pytest.mark.parametrize("apply_nyq", [False, True])
def test_sharded_sweep_dispersed_bit_equal(apply_nyq):
    """Dispersed-frame sharded sweep (per-channel rotated template +
    optional Nyquist rows riding the 'chan' axis) vs single-device."""
    _, disp, rot_t, nyq, t, _, w, m = _case(seed=5)
    nyq_row = nyq if apply_nyq else None
    want = jax.jit(lambda *a: pk.fused_sweep_pallas(
        a[0], a[1], nyq_row, a[2], a[3], a[4], CH, ST))(disp, rot_t, t, w, m)
    mesh = cell_mesh(8)  # (2, 4): both axes genuinely sharded
    got = jax.jit(lambda *a: sharded_fused_sweep(
        mesh, a[0], a[1], nyq_row, a[2], a[3], a[4], CH, ST))(
        disp, rot_t, t, w, m)
    _assert_triple_equal(got, want)


# ------------------------------------------- DMA pipeline vs BlockSpec route

def test_shard_diags_dma_matches_blockspec():
    """The manual double-buffered HBM→VMEM fetch computes on exactly the
    tiles the BlockSpec pipeline would deliver: all four diagnostic
    planes bit-equal with ICLEAN_SWEEP_DMA on vs off, both frames."""
    ded, disp, rot_t, nyq, t, win, w, m = _case(seed=9)
    on = pk.sweep_shard_diags_dedisp(ded, t, win, w, m, dma=True)
    off = pk.sweep_shard_diags_dedisp(ded, t, win, w, m, dma=False)
    for k, (a, b) in enumerate(zip(on, off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"dedisp diag {k}")
    on = pk.sweep_shard_diags_disp(disp, rot_t, nyq, t, w, m, dma=True)
    off = pk.sweep_shard_diags_disp(disp, rot_t, nyq, t, w, m, dma=False)
    for k, (a, b) in enumerate(zip(on, off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"disp diag {k}")


def test_sweep_dma_env_mirror(monkeypatch):
    from iterative_cleaner_tpu.stats.pallas_kernels import _sweep_dma_default

    monkeypatch.delenv("ICLEAN_SWEEP_DMA", raising=False)
    assert _sweep_dma_default() is True            # auto -> DMA pipeline
    assert _sweep_dma_default("on") is True
    assert _sweep_dma_default("off") is False      # escape hatch
    monkeypatch.setenv("ICLEAN_SWEEP_DMA", "off")
    assert _sweep_dma_default() is False
    monkeypatch.setenv("ICLEAN_SWEEP_DMA", "sideways")
    with pytest.raises(ValueError, match="ICLEAN_SWEEP_DMA"):
        _sweep_dma_default()


# ------------------------------------------------------- eligibility ladder

def test_sweep_downgrade_reasons():
    mesh = cell_mesh(8)  # (2, 4)
    assert sweep_downgrade_reason(mesh, 8, 16, 32) is None
    assert sharded_sweep_eligible(mesh, 8, 16, 32)
    # a mesh axis that does not divide its grid dimension
    assert sweep_downgrade_reason(mesh, 9, 16, 32) == "mesh_indivisible"
    assert sweep_downgrade_reason(mesh, 8, 18, 32) == "mesh_indivisible"
    # divisible, but the LOCAL shard busts the single-device budget
    assert not pk.fused_sweep_eligible(20000, 4096, 64)
    assert sweep_downgrade_reason(cell_mesh(1), 20000, 4096, 64) \
        == "shard_geometry"
    assert not sharded_sweep_eligible(cell_mesh(1), 20000, 4096, 64)


def test_resolve_fused_sweep_mesh_rung(monkeypatch):
    """'auto' resolves 'off' when the mesh rung fails — the program never
    requests what the engine would refuse; explicit 'on' passes through
    (the engine downgrades, the CLI surfaces it)."""
    from iterative_cleaner_tpu.backends.jax_backend import (
        resolve_fused_sweep,
    )

    monkeypatch.delenv("ICLEAN_FUSED_SWEEP", raising=False)
    mesh = cell_mesh(8)
    good, bad = (8, 16, 32), (9, 16, 32)
    assert resolve_fused_sweep("auto", "fused", mesh=mesh,
                               shape=good) == "on"
    assert resolve_fused_sweep("auto", "fused", mesh=mesh,
                               shape=bad) == "off"
    assert resolve_fused_sweep("on", "fused", mesh=mesh, shape=bad) == "on"
    assert resolve_fused_sweep("auto", "xla", mesh=mesh, shape=good) \
        == "off"


def test_cli_downgrade_notice(capsys):
    """--fused-sweep on over an ineligible mesh: one visible line + the
    fused_sweep_ineligible{reason=} counter; 'auto' stays silent."""
    from iterative_cleaner_tpu.cli import _notice_sweep_downgrade
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.telemetry import MetricsRegistry

    class _Tel:
        registry = MetricsRegistry()

    tel = _Tel()
    mesh = cell_mesh(8)
    reason = _notice_sweep_downgrade(
        CleanConfig(fused_sweep="on"), mesh, (9, 16, 32),
        quiet=False, telemetry=tel)
    assert reason == "mesh_indivisible"
    out = capsys.readouterr().out
    assert "fused sweep ineligible" in out and "mesh_indivisible" in out
    counters = tel.registry.snapshot()["counters"]
    assert counters[
        'fused_sweep_ineligible{reason=mesh_indivisible}'] == 1
    # auto never promised the sweep: no notice, no counter
    assert _notice_sweep_downgrade(
        CleanConfig(fused_sweep="auto"), mesh, (9, 16, 32),
        quiet=False, telemetry=tel) is None
    assert capsys.readouterr().out == ""
    # eligible geometry: quiet regardless of knob
    assert _notice_sweep_downgrade(
        CleanConfig(fused_sweep="on"), mesh, (8, 16, 32),
        quiet=False, telemetry=tel) is None
    assert capsys.readouterr().out == ""


# ------------------------------------------------------ engine-level parity

def _archive(nsub=8, nchan=16, nbin=64, seed=23, **kw):
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

    ar, _ = make_synthetic_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                   seed=seed, dtype=np.float32, **kw)
    return ar


def test_sharded_engine_sweep_masks_bit_equal():
    """clean_cube_sharded with the sweep engaged (stats_impl='fused',
    --fused-sweep on) vs the single-device fused-sweep engine: final
    weights and loop count bit-equal — the acceptance contract of the
    sharded sweep in one run.  Scores may move at float32 ulp scale
    (the sharded engine's template comes from a psum whose summation
    order regroups — same caveat as test_parallel.py's exact mode);
    masks must not."""
    from iterative_cleaner_tpu.backends.jax_backend import clean_cube
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel.sharding import clean_cube_sharded

    ar = _archive()
    cfg = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                      fft_mode="dft", median_impl="pallas",
                      fused_sweep="on", rotation="roll", max_iter=3,
                      stats_frame="dedispersed")
    single = clean_cube(ar.total_intensity(), ar.weights, ar.freqs_mhz,
                        ar.dm, ar.centre_freq_mhz, ar.period_s, cfg)
    sharded = clean_cube_sharded(ar.total_intensity(), ar.weights,
                                 ar.freqs_mhz, ar.dm, ar.centre_freq_mhz,
                                 ar.period_s, cfg, cell_mesh(8))
    np.testing.assert_array_equal(single.final_weights,
                                  sharded.final_weights)
    np.testing.assert_allclose(single.scores, sharded.scores,
                               rtol=1e-4, atol=1e-6)
    assert sharded.loops == single.loops
    assert sharded.converged == single.converged


@pytest.mark.slow
def test_sharded_engine_sweep_dispersed_frame_bit_equal():
    """The dispersed-frame (disp_iteration) sharded sweep through the
    full engine — the production default-config route at pod scale."""
    from iterative_cleaner_tpu.backends.jax_backend import clean_cube
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel.sharding import clean_cube_sharded

    ar = _archive(seed=29)
    cfg = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                      fft_mode="dft", median_impl="pallas",
                      fused_sweep="on", rotation="roll", max_iter=3)
    single = clean_cube(ar.total_intensity(), ar.weights, ar.freqs_mhz,
                        ar.dm, ar.centre_freq_mhz, ar.period_s, cfg)
    sharded = clean_cube_sharded(ar.total_intensity(), ar.weights,
                                 ar.freqs_mhz, ar.dm, ar.centre_freq_mhz,
                                 ar.period_s, cfg, cell_mesh(8))
    np.testing.assert_array_equal(single.final_weights,
                                  sharded.final_weights)
    np.testing.assert_allclose(single.scores, sharded.scores,
                               rtol=1e-4, atol=1e-6)


# -------------------------------------------------- streamed-shard parity

def test_streamed_shard_fused_combine_bit_equal():
    """The >HBM route: exact streaming over a cell mesh with the fused
    one-launch combine engaged — masks bit-equal with the streamed
    single-device route (which is itself bit-equal with whole-archive
    cleaning)."""
    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel.streaming import clean_streaming

    ar = _archive(nsub=16, nchan=16, nbin=32, seed=31, n_rfi_cells=8,
                  n_prezapped=4)
    cfg = CleanConfig(backend="jax", dtype="float32", stats_impl="fused",
                      fft_mode="dft", median_impl="sort",
                      fused_sweep="on", rotation="roll",
                      chanthresh=2.5, subintthresh=2.5, max_iter=3)
    single = clean_streaming(ar, 8, cfg, None, mode="exact")
    sharded = clean_streaming(ar, 8, cfg, cell_mesh(4), mode="exact")
    np.testing.assert_array_equal(single.final_weights,
                                  sharded.final_weights)
    np.testing.assert_array_equal(single.scores, sharded.scores)
    assert sharded.loops == single.loops


# --------------------------------------------------------- jaxpr contracts

@pytest.mark.slow
def test_sharded_sweep_hot_program_contract_green():
    """The registered sharded_sweep contract: callback-free, donation
    realized on the sharded program, and ONE cube read per per-shard
    kernel — counted through the DMA pipeline's destination buffers."""
    from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
        verify_hot_programs,
    )

    (report,) = verify_hot_programs(["sharded_sweep"])
    # x64 is on under pytest (conftest): filter no-f64 exactly as the
    # fused_sweep contract test does; the deployment flavour is covered
    # by the selfcheck CLI subprocess test.
    bad = [v for v in report.violations if v.contract != "no-f64"]
    assert not bad, [v.render() for v in bad]
    assert report.eqn_count > 0


def test_dma_kernel_single_cube_read_counts():
    """Both per-shard DMA kernels stage the cube tile through exactly ONE
    VMEM scratch destination (the single-read budget, proven on the
    traced jaxpr through the cond-nested dma_start sites)."""
    from iterative_cleaner_tpu.analysis.jaxpr_contracts import (
        _count_cube_ref_reads,
    )

    f32 = jnp.float32
    ns, nc, nb = 4, 8, 32
    cube = jax.ShapeDtypeStruct((ns, nc, nb), f32)
    plane = jax.ShapeDtypeStruct((ns, nc), f32)
    mask = jax.ShapeDtypeStruct((ns, nc), jnp.bool_)
    row = jax.ShapeDtypeStruct((nb,), f32)
    rows = jax.ShapeDtypeStruct((nc, nb), f32)
    ded = jax.make_jaxpr(lambda d, t, win, w, m: pk.sweep_shard_diags_dedisp(
        d, t, win, w, m, dma=True))(cube, row, row, plane, mask)
    assert _count_cube_ref_reads(ded) == [1]
    disp = jax.make_jaxpr(lambda d, rt, nq, t, w, m: pk.sweep_shard_diags_disp(
        d, rt, nq, t, w, m, dma=True))(cube, rows, rows, row, plane, mask)
    assert _count_cube_ref_reads(disp) == [1]

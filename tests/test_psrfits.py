"""PSRFITS fold-mode container: writer/reader roundtrips, native C++ reader
parity, period resolution, format dispatch, and rejection of unsupported
layouts (iterative_cleaner_tpu/io/psrfits.py + native/psrfits_io.cpp).

This is the framework's replacement for the reference's PSRCHIVE dependency
on modern ``.ar`` files (/root/reference/iterative_cleaner.py:13,47,60):
fold-mode PSRFITS read/written without psrchive or cfitsio.
"""

import os

import numpy as np
import pytest

from iterative_cleaner_tpu.io import load_archive, save_archive
from iterative_cleaner_tpu.io import psrfits
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive


def _archive(npol=1, pol_state=None, **kw):
    defaults = dict(nsub=6, nchan=8, nbin=32, seed=1, n_prezapped=3)
    defaults.update(kw)
    ar, truth = make_synthetic_archive(npol=npol, **defaults)
    if pol_state:
        ar.pol_state = pol_state
    return ar, truth


@pytest.mark.parametrize("nbits,rel_tol", [(32, 1e-7), (16, 1e-3)])
def test_roundtrip(tmp_path, nbits, rel_tol):
    ar, _ = _archive(npol=4, pol_state="Stokes")
    path = str(tmp_path / f"t{nbits}.sf")
    psrfits.save_psrfits(ar, path, nbits=nbits)
    back = psrfits.load_psrfits(path)
    assert back.data.shape == ar.data.shape
    rel = np.abs(back.data - ar.data).max() / np.abs(ar.data).max()
    assert rel < rel_tol
    np.testing.assert_array_equal(back.weights, ar.weights)
    np.testing.assert_allclose(back.freqs_mhz, ar.freqs_mhz, atol=2e-4)
    assert abs(back.period_s - ar.period_s) < 1e-9
    assert abs(back.dm - ar.dm) < 1e-9
    assert back.centre_freq_mhz == ar.centre_freq_mhz
    assert back.source == ar.source
    assert back.pol_state == "Stokes"
    assert abs(back.mjd_start - ar.mjd_start) < 2e-5  # STT_* second precision
    assert abs((back.mjd_end - back.mjd_start)
               - (ar.mjd_end - ar.mjd_start)) < 1e-9


def test_float32_cube_exact(tmp_path):
    ar, _ = _archive(dtype=np.float32, n_prezapped=0)
    path = str(tmp_path / "f32.sf")
    psrfits.save_psrfits(ar, path, nbits=32)
    back = psrfits.load_psrfits(path)
    np.testing.assert_array_equal(back.data, ar.data.astype(np.float64))


def test_native_reader_bit_identical(tmp_path):
    from iterative_cleaner_tpu.io import native

    if not native.native_available() or psrfits._psrfits_lib() is None:
        pytest.skip("native library unavailable")
    for nbits in (16, 32):
        ar, _ = _archive(npol=2, pol_state="Coherence", seed=7)
        path = str(tmp_path / f"n{nbits}.sf")
        psrfits.save_psrfits(ar, path, nbits=nbits)
        nat = psrfits._load_psrfits_native(path)
        assert nat is not None, "native open failed on a file we wrote"
        pure = psrfits.load_psrfits(path, prefer_native=False)
        np.testing.assert_array_equal(nat.data, pure.data)
        np.testing.assert_array_equal(nat.weights, pure.weights)
        np.testing.assert_array_equal(nat.freqs_mhz, pure.freqs_mhz)
        for f in ("period_s", "dm", "centre_freq_mhz", "mjd_start", "mjd_end",
                  "source", "pol_state", "dedispersed"):
            assert getattr(nat, f) == getattr(pure, f), f


def _strip_card(path, key):
    raw = open(path, "rb").read()
    idx = raw.find(key.ljust(8).encode() + b"= ")
    assert idx >= 0
    return raw[:idx] + b"COMMENT stripped".ljust(80) + raw[idx + 80:]


def test_period_fallback_tbin(tmp_path):
    ar, _ = _archive()
    path = str(tmp_path / "p.sf")
    psrfits.save_psrfits(ar, path)
    patched = str(tmp_path / "nop.sf")
    with open(patched, "wb") as f:
        f.write(_strip_card(path, "PERIOD"))
    back = psrfits.load_psrfits(patched, prefer_native=False)
    assert abs(back.period_s - ar.period_s) < 1e-9  # TBIN * NBIN
    nat = psrfits._load_psrfits_native(patched)
    if nat is not None:
        assert abs(nat.period_s - ar.period_s) < 1e-9


def test_period_fallback_polyco(tmp_path):
    """No PERIOD key + a POLYCO table: period = 1/REF_F0 of the last row."""
    import struct

    ar, _ = _archive()
    path = str(tmp_path / "p.sf")
    psrfits.save_psrfits(ar, path)
    f0 = 2.5  # Hz
    polyco_hdr = psrfits._end_pad([
        psrfits._card("XTENSION", "BINTABLE"),
        psrfits._card("BITPIX", 8),
        psrfits._card("NAXIS", 2),
        psrfits._card("NAXIS1", 8),
        psrfits._card("NAXIS2", 2),
        psrfits._card("PCOUNT", 0),
        psrfits._card("GCOUNT", 1),
        psrfits._card("TFIELDS", 1),
        psrfits._card("EXTNAME", "POLYCO"),
        psrfits._card("TTYPE1", "REF_F0"),
        psrfits._card("TFORM1", "1D"),
    ])
    rows = struct.pack(">d", 1.0) + struct.pack(">d", f0)
    rows += b"\x00" * ((-len(rows)) % psrfits.BLOCK)
    patched = str(tmp_path / "polyco.sf")
    with open(patched, "wb") as f:
        f.write(_strip_card(path, "PERIOD"))
        f.write(polyco_hdr)
        f.write(rows)
    back = psrfits.load_psrfits(patched, prefer_native=False)
    assert abs(back.period_s - 1.0 / f0) < 1e-12
    nat = psrfits._load_psrfits_native(patched)
    if nat is not None:
        assert abs(nat.period_s - 1.0 / f0) < 1e-12


def test_ar_extension_dispatch(tmp_path):
    """.ar files carry FITS magic -> the PSRFITS path handles them without
    psrchive, both directions (the reference needs PSRCHIVE for any .ar)."""
    ar, _ = _archive()
    path = str(tmp_path / "obs.ar")
    save_archive(ar, path)
    with open(path, "rb") as f:
        assert f.read(6) == b"SIMPLE"
    back = load_archive(path)
    np.testing.assert_array_equal(back.weights, ar.weights)
    assert back.filename == path


def test_non_fits_ar_gives_actionable_conversion_error(tmp_path):
    """A TIMER-format .ar without psrchive must fail with the documented
    actionable message naming the psrconv/pam conversion (VERDICT r1
    missing item 3), not a bare ImportError."""
    path = str(tmp_path / "legacy.ar")
    with open(path, "wb") as f:
        f.write(b"TIMER archive, not FITS" * 10)
    with pytest.raises(ValueError) as ei:  # no psrchive in the test env
        load_archive(path)
    msg = str(ei.value)
    assert "TIMER" in msg and "psrconv" in msg and "pam" in msg
    assert "legacy.ar" in msg


def test_cli_end_to_end_psrfits(tmp_path, monkeypatch):
    from iterative_cleaner_tpu.cli import main

    ar, truth = _archive(n_rfi_cells=4, n_prezapped=0, rfi_strength=60.0)
    path = str(tmp_path / "obs.sf")
    save_archive(ar, path)
    monkeypatch.chdir(tmp_path)
    assert main([path, "-q", "-l", "--backend", "numpy"]) == 0
    out = load_archive(path + "_cleaned.sf")
    zap = out.weights == 0
    for s, c in truth.rfi_cells:
        assert zap[s, c]
    # weights quantise exactly (float32 holds 0/1); data within int16 scaling
    assert np.abs(out.data - ar.data).max() / np.abs(ar.data).max() < 1e-3


def test_rejects_unsupported(tmp_path):
    ar, _ = _archive()
    good = str(tmp_path / "g.sf")
    psrfits.save_psrfits(ar, good)

    bad = str(tmp_path / "notfits.sf")
    with open(bad, "wb") as f:
        f.write(b"\x00" * 5760)
    with pytest.raises(ValueError, match="not a FITS"):
        psrfits.load_psrfits(bad, prefer_native=False)
    assert psrfits._load_psrfits_native(bad) is None

    raw = open(good, "rb").read()
    searchmode = raw.replace(b"'PSR     '", b"'SEARCH  '", 1)
    sm = str(tmp_path / "search.sf")
    open(sm, "wb").write(searchmode)
    with pytest.raises(ValueError, match="fold-mode"):
        psrfits.load_psrfits(sm, prefer_native=False)
    assert psrfits._load_psrfits_native(sm) is None

    nodata = raw.replace(b"'DATA    '", b"'NOPE    '", 1)
    nd = str(tmp_path / "nodata.sf")
    open(nd, "wb").write(nodata)
    with pytest.raises(ValueError, match="DATA"):
        psrfits.load_psrfits(nd, prefer_native=False)
    assert psrfits._load_psrfits_native(nd) is None

    truncated = str(tmp_path / "trunc.sf")
    open(truncated, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(Exception):
        psrfits.load_psrfits(truncated, prefer_native=False)


def test_period_zero_treated_as_unset(tmp_path):
    """PERIOD=0 (tools write it when unset) must fall through to TBIN*NBIN,
    matching the native reader."""
    ar, _ = _archive()
    path = str(tmp_path / "p0.sf")
    psrfits.save_psrfits(ar, path)
    raw = open(path, "rb").read()
    idx = raw.find(b"PERIOD  = ")
    zeroed = psrfits._card("PERIOD", 0.0, "unset")
    open(path, "wb").write(raw[:idx] + zeroed + raw[idx + 80:])
    back = psrfits.load_psrfits(path, prefer_native=False)
    assert abs(back.period_s - ar.period_s) < 1e-9
    nat = psrfits._load_psrfits_native(path)
    if nat is not None:
        assert abs(nat.period_s - ar.period_s) < 1e-9


def test_int16_error_bound_with_large_baseline(tmp_path):
    """Round-trip error must stay ~span/65534 even when the baseline offset
    is many orders larger than the per-profile span (DAT_SCL/DAT_OFFS are
    float32; quantisation uses the float32-rounded values)."""
    ar, _ = _archive(n_prezapped=0, baseline_level=1.0e6, noise_sigma=0.5,
                     rfi_strength=5.0, pulse_snr=5.0)
    path = str(tmp_path / "big.sf")
    psrfits.save_psrfits(ar, path, nbits=16)
    back = psrfits.load_psrfits(path, prefer_native=False)
    span = (ar.data.max(axis=3) - ar.data.min(axis=3))[..., None]
    centre = np.abs(ar.data.max(axis=3) + ar.data.min(axis=3))[..., None] / 2
    # half a quantum, with the float32 scl/offs rounding accounted for
    bound = ((span / 2 + centre * 2.0 ** -23) / 32767.0).max() * 0.51
    assert np.abs(back.data - ar.data).max() <= bound


def test_tools_info_and_diff_on_psrfits(tmp_path, capsys):
    import json

    from iterative_cleaner_tpu.tools import main as tools_main

    ar, _ = _archive(npol=2, pol_state="Coherence")
    a = str(tmp_path / "a.sf")
    b = str(tmp_path / "b.sf")
    psrfits.save_psrfits(ar, a)
    ar.weights[0, 0] = 0.0
    psrfits.save_psrfits(ar, b)

    assert tools_main(["info", a]) == 0
    info = json.loads(capsys.readouterr().out)
    assert (info["nsub"], info["npol"], info["nchan"], info["nbin"]) == \
        (ar.nsub, 2, ar.nchan, ar.nbin)
    assert info["pol_state"] == "Coherence"
    assert abs(info["period_s"] - ar.period_s) < 1e-9
    assert abs(info["dm"] - ar.dm) < 1e-9

    assert tools_main(["diff", a, b]) == 1  # masks differ
    out = json.loads(capsys.readouterr().out)
    assert out["changed"] == 1

    assert tools_main(["diff", a, a]) == 0


def test_aabb_pol_type_maps_to_coherence(tmp_path):
    """POL_TYPE='AABB' (two-product coherence): total intensity must be
    AA + BB, not just AA — both readers map it to Coherence."""
    ar, _ = _archive(npol=2, pol_state="Coherence")
    path = str(tmp_path / "aabb.sf")
    psrfits.save_psrfits(ar, path)
    raw = open(path, "rb").read().replace(b"'AABBCRCI'", b"'AABB    '", 1)
    open(path, "wb").write(raw)
    back = psrfits.load_psrfits(path, prefer_native=False)
    assert back.pol_state == "Coherence"
    nat = psrfits._load_psrfits_native(path)
    if nat is not None:
        assert nat.pol_state == "Coherence"


def test_nonfinite_cube_stored_float32(tmp_path):
    """int16 scaling is undefined for NaN/Inf; the writer upgrades to
    float32 and the values round-trip."""
    ar, _ = _archive(dtype=np.float32)
    ar.data[1, 0, 2, 3] = np.nan
    ar.data[2, 0, 1, 0] = np.inf
    path = str(tmp_path / "nan.sf")
    psrfits.save_psrfits(ar, path, nbits=16)  # silently upgraded
    back = psrfits.load_psrfits(path, prefer_native=False)
    np.testing.assert_array_equal(back.data, ar.data.astype(np.float64))
    nat = psrfits._load_psrfits_native(path)
    if nat is not None:
        np.testing.assert_array_equal(nat.data, back.data)


def test_no_period_anywhere_is_an_error(tmp_path):
    ar, _ = _archive()
    path = str(tmp_path / "nop.sf")
    psrfits.save_psrfits(ar, path)
    raw = _strip_card(path, "PERIOD")
    open(path, "wb").write(raw)
    raw = _strip_card(path, "TBIN")
    open(path, "wb").write(raw)
    with pytest.raises(ValueError, match="folding period"):
        psrfits.load_psrfits(path, prefer_native=False)
    assert psrfits._load_psrfits_native(path) is None  # native stays in sync


def test_roundtrip_preserves_source_encoding(tmp_path):
    """A float32-DATA archive re-saved by default stays float32 (no silent
    int16 quantisation of cleaned outputs); int16 sources stay int16."""
    ar, _ = _archive(dtype=np.float32, n_prezapped=0)
    p32 = str(tmp_path / "src32.sf")
    psrfits.save_psrfits(ar, p32, nbits=32)
    back = psrfits.load_psrfits(p32)
    assert back.psrfits_nbits == 32
    out = str(tmp_path / "out.sf")
    psrfits.save_psrfits(back, out)  # default follows the source encoding
    again = psrfits.load_psrfits(out, prefer_native=False)
    np.testing.assert_array_equal(again.data, back.data)

    p16 = str(tmp_path / "src16.sf")
    psrfits.save_psrfits(ar, p16, nbits=16)
    b16 = psrfits.load_psrfits(p16, prefer_native=False)
    assert b16.psrfits_nbits == 16
    nat = psrfits._load_psrfits_native(p16)
    if nat is not None:
        assert nat.psrfits_nbits == 16

    # the marker survives the other containers, so .sf -> .npz/.icar -> .sf
    # keeps fidelity too
    for ext in ("npz", "icar"):
        mid = str(tmp_path / f"mid.{ext}")
        save_archive(back, mid)
        assert load_archive(mid).psrfits_nbits == 32, ext


def test_fresh_lib_copy_loads_with_symbols():
    """The stale-library recovery path loads a unique-path copy (glibc
    caches dlopen by path, so an in-place rebuild is invisible otherwise)."""
    from iterative_cleaner_tpu.io import native

    if not native.native_available():
        pytest.skip("native library unavailable")
    lib = psrfits._load_fresh_copy()
    psrfits._configure_psrfits(lib)  # raises AttributeError if symbols absent
    assert lib.psrfits_open is not None


@pytest.mark.parametrize("reader", ["native", "pure"])
def test_corruption_fuzz_never_crashes(tmp_path, reader):
    """Truncations, bitflip bursts and garbage blocks: the native parser
    must reject or parse without crashing the process, the pure parser must
    raise cleanly — neither may hang (seeded; 60 draws per reader)."""
    if reader == "native" and psrfits._psrfits_lib() is None:
        pytest.skip("native library unavailable")
    ar, _ = _archive(nsub=4, nchan=6, nbin=16)
    good = tmp_path / "g.sf"
    psrfits.save_psrfits(ar, str(good))
    raw = good.read_bytes()
    rng = np.random.default_rng(0 if reader == "native" else 1)
    bad_file = tmp_path / "bad.sf"
    bad = str(bad_file)
    for trial in range(60):
        buf = bytearray(raw)
        kind = trial % 3
        if kind == 0:
            buf = buf[: int(rng.integers(1, len(buf)))]
        elif kind == 1:
            for _ in range(int(rng.integers(1, 50))):
                i = int(rng.integers(0, len(buf)))
                buf[i] ^= int(rng.integers(1, 256))
        else:
            i = int(rng.integers(0, len(buf)))
            n = int(rng.integers(1, 2880))
            buf[i: i + n] = bytes(rng.integers(0, 256, size=n,
                                               dtype=np.uint8))
        bad_file.write_bytes(bytes(buf))
        with np.errstate(invalid="ignore"):
            try:
                if reader == "native":
                    psrfits._load_psrfits_native(bad)  # None or Archive
                else:
                    psrfits.load_psrfits(bad, prefer_native=False)
            except Exception:
                pass  # clean rejection is fine; crashes/hangs are not


def test_negative_naxis_rejected_not_hung(tmp_path):
    """A crafted HDU with negative NAXISn must raise cleanly: the old walk
    computed a negative data size and moved the HDU offset *backwards*,
    revisiting offsets forever (ADVICE r1).  Native must reject (None)."""
    ar, _ = _archive(nsub=4, nchan=6, nbin=16)
    good = str(tmp_path / "g.sf")
    psrfits.save_psrfits(ar, good)
    raw = open(good, "rb").read()
    # splice an evil extension between the primary HDU and SUBINT
    evil = psrfits._end_pad([
        psrfits._card("XTENSION", "BINTABLE"),
        psrfits._card("BITPIX", 8),
        psrfits._card("NAXIS", 2),
        psrfits._card("NAXIS1", -5760),
        psrfits._card("NAXIS2", 1),
        psrfits._card("PCOUNT", 0),
        psrfits._card("GCOUNT", 1),
        psrfits._card("TFIELDS", 0),
        psrfits._card("EXTNAME", "EVIL"),
    ])
    end = raw.find(b"END" + b" " * 77)  # primary END card
    assert end >= 0
    prim_len = (end // psrfits.BLOCK + 1) * psrfits.BLOCK
    bad = str(tmp_path / "evil.sf")
    with open(bad, "wb") as f:
        f.write(raw[:prim_len] + evil + raw[prim_len:])
    with pytest.raises(ValueError, match="negative NAXIS"):
        psrfits.load_psrfits(bad, prefer_native=False)
    if psrfits._psrfits_lib() is not None:
        assert psrfits._load_psrfits_native(bad) is None


def test_truncated_polyco_falls_back_to_tbin(tmp_path):
    """POLYCO REF_F0 pointing past EOF: no struct.error — both readers treat
    the truncated table as 'no usable POLYCO' and resolve the period from
    TBIN*NBIN (ADVICE r1: pure reader matches the native bounds check)."""
    import struct

    ar, _ = _archive()
    path = str(tmp_path / "p.sf")
    psrfits.save_psrfits(ar, path)
    polyco_hdr = psrfits._end_pad([
        psrfits._card("XTENSION", "BINTABLE"),
        psrfits._card("BITPIX", 8),
        psrfits._card("NAXIS", 2),
        psrfits._card("NAXIS1", 8),
        psrfits._card("NAXIS2", 2),
        psrfits._card("PCOUNT", 0),
        psrfits._card("GCOUNT", 1),
        psrfits._card("TFIELDS", 1),
        psrfits._card("EXTNAME", "POLYCO"),
        psrfits._card("TTYPE1", "REF_F0"),
        psrfits._card("TFORM1", "1D"),
    ])
    truncated = str(tmp_path / "trunc.sf")
    with open(truncated, "wb") as f:
        f.write(_strip_card(path, "PERIOD"))
        f.write(polyco_hdr)
        f.write(struct.pack(">d", 1.0))  # row 1 only; row 2 missing
    pure = psrfits.load_psrfits(truncated, prefer_native=False)
    assert abs(pure.period_s - ar.period_s) < 1e-9  # TBIN * NBIN
    nat = psrfits._load_psrfits_native(truncated)
    if nat is not None:
        assert abs(nat.period_s - ar.period_s) < 1e-9


def test_dat_freq_float64_roundtrip_exact(tmp_path):
    """DAT_FREQ is written as 'D' (float64): channel frequencies survive a
    round-trip bit-exactly instead of being squeezed through float32
    (ADVICE r1); pure and native readers agree."""
    ar, _ = _archive(n_prezapped=0)
    ar.freqs_mhz = ar.freqs_mhz + 1e-7  # not representable in float32
    path = str(tmp_path / "f64.sf")
    psrfits.save_psrfits(ar, path)
    pure = psrfits.load_psrfits(path, prefer_native=False)
    np.testing.assert_array_equal(pure.freqs_mhz, ar.freqs_mhz)
    nat = psrfits._load_psrfits_native(path)
    if nat is not None:
        np.testing.assert_array_equal(nat.freqs_mhz, ar.freqs_mhz)


def test_info_pol_state_matches_load_for_unknown_pol_type(tmp_path):
    """`tools info` must report the pol_state an actual load would produce:
    both fall back npol-aware on an unknown POL_TYPE (ADVICE r1)."""
    ar, _ = _archive(npol=4, pol_state="Stokes")
    path = str(tmp_path / "u.sf")
    psrfits.save_psrfits(ar, path)
    raw = bytearray(open(path, "rb").read())
    i = raw.find(b"POL_TYPE= ")
    assert i >= 0
    val = raw.find(b"'", i)
    raw[val: val + 6] = b"'WAT' "  # unknown POL_TYPE, quote-terminated
    patched = str(tmp_path / "unknown.sf")
    open(patched, "wb").write(bytes(raw))
    loaded = psrfits.load_psrfits(patched, prefer_native=False)
    meta, _ = psrfits.read_psrfits_info(patched)
    assert loaded.pol_state == meta["pol_state"] == "Stokes"


def test_is_fits(tmp_path):
    ar, _ = _archive()
    p = str(tmp_path / "x.sf")
    psrfits.save_psrfits(ar, p)
    assert psrfits.is_fits(p)
    q = str(tmp_path / "y.bin")
    open(q, "wb").write(b"nope")
    assert not psrfits.is_fits(q)
    assert not psrfits.is_fits(str(tmp_path / "missing"))


# --- foreign-writer variants (VERDICT r3 #5) -------------------------------

def _write_foreign_variant(ar, path, *, order=None, tdim="std",
                           data_code="E", period="key",
                           leading_hdu=False, trailing_hdu=False,
                           long_string=False):
    """Emit ``ar`` as a fold-mode PSRFITS file the way a FOREIGN writer
    might: float32 DAT_FREQ ('E' — the common layout; this repo's writer
    emits 'D'), arbitrary column order, assorted TDIM spellings, extra
    non-SUBINT HDUs.  ``data_code='B'`` writes 8-bit DATA (valid FITS,
    outside the supported matrix — must reject actionably)."""
    import struct

    nsub, npol, nchan, nbin = ar.nsub, ar.npol, ar.nchan, ar.nbin
    ncell = npol * nchan
    cube32 = np.ascontiguousarray(ar.data, dtype=np.float32)
    tsub = ((ar.mjd_end - ar.mjd_start) * 86400.0 / nsub) if nsub else 0.0

    def col_bytes(name, isub):
        if name == "TSUBINT":
            return struct.pack(">d", tsub)
        if name == "OFFS_SUB":
            return struct.pack(">d", (isub + 0.5) * tsub)
        if name == "DAT_FREQ":
            return np.asarray(ar.freqs_mhz, dtype=">f4").tobytes()
        if name == "DAT_WTS":
            return np.asarray(ar.weights[isub], dtype=">f4").tobytes()
        if name in ("DAT_SCL", "DAT_OFFS"):
            fill = 1.0 if name == "DAT_SCL" else 0.0
            return np.full(ncell, fill, dtype=">f4").tobytes()
        assert name == "DATA"
        if data_code == "B":
            return np.clip(ar.data[isub], 0, 255).astype(">u1").tobytes()
        return cube32[isub].astype(">f4").tobytes()

    tforms = {"TSUBINT": "1D", "OFFS_SUB": "1D", "DAT_FREQ": f"{nchan}E",
              "DAT_WTS": f"{nchan}E", "DAT_SCL": f"{ncell}E",
              "DAT_OFFS": f"{ncell}E",
              "DATA": f"{ncell * nbin}{data_code}"}
    order = list(order or tforms)
    assert sorted(order) == sorted(tforms)
    row_bytes = sum(len(col_bytes(n, 0)) for n in order)

    cards = [
        psrfits._card("XTENSION", "BINTABLE"),
        psrfits._card("BITPIX", 8), psrfits._card("NAXIS", 2),
        psrfits._card("NAXIS1", row_bytes), psrfits._card("NAXIS2", nsub),
        psrfits._card("PCOUNT", 0), psrfits._card("GCOUNT", 1),
        psrfits._card("TFIELDS", len(order)),
        psrfits._card("EXTNAME", "SUBINT"),
        psrfits._card("NBIN", nbin), psrfits._card("NCHAN", nchan),
        psrfits._card("NPOL", npol), psrfits._card("POL_TYPE", "INTEN"),
        psrfits._card("CHAN_DM", float(ar.dm)),
        psrfits._card("DEDISP", 0),
        psrfits._card("TBIN", ar.period_s / nbin),
    ]
    if period == "key":
        cards.append(psrfits._card("PERIOD", float(ar.period_s)))
    for i, name in enumerate(order, 1):
        cards.append(psrfits._card(f"TTYPE{i}", name))
        cards.append(psrfits._card(f"TFORM{i}", tforms[name]))
        if name == "DATA" and tdim != "none":
            spelling = (f"({nbin},{nchan},{npol})" if tdim == "std"
                        else f"( {nbin} , {nchan} , {npol} )")
            cards.append(psrfits._card(f"TDIM{i}", spelling))

    def aux_hdu(extname):
        # a minimal foreign auxiliary table (e.g. psrchive's HISTORY /
        # PSRPARAM) the reader must skip over without tripping
        hdr = psrfits._end_pad([
            psrfits._card("XTENSION", "BINTABLE"),
            psrfits._card("BITPIX", 8), psrfits._card("NAXIS", 2),
            psrfits._card("NAXIS1", 16), psrfits._card("NAXIS2", 1),
            psrfits._card("PCOUNT", 0), psrfits._card("GCOUNT", 1),
            psrfits._card("TFIELDS", 1),
            psrfits._card("EXTNAME", extname),
            psrfits._card("TTYPE1", "NOTE"),
            psrfits._card("TFORM1", "16A"),
        ])
        rows = b"foreign writer  "
        return hdr + rows + b"\x00" * ((-len(rows)) % psrfits.BLOCK)

    primary_cards = [
        psrfits._card("SIMPLE", True), psrfits._card("BITPIX", 8),
        psrfits._card("NAXIS", 0), psrfits._card("EXTEND", True),
        psrfits._card("FITSTYPE", "PSRFITS"),
        psrfits._card("OBS_MODE", "PSR"),
        psrfits._card("SRC_NAME", ar.source[:24]),
        psrfits._card("OBSFREQ", float(ar.centre_freq_mhz)),
        psrfits._card("STT_IMJD", int(ar.mjd_start)),
        psrfits._card("STT_SMJD",
                      int((ar.mjd_start - int(ar.mjd_start)) * 86400.0)),
    ]
    if long_string:
        # the FITS long-string convention: '&'-terminated value + CONTINUE
        # cards (CONTINUE has no '= ' — hand-built, _card can't emit it)
        primary_cards += [
            psrfits._card("OBSERVER",
                          "an observer name long enough to need tw&"),
            b"CONTINUE  'o continuation cards in the primar&'".ljust(
                psrfits.CARD),
            b"CONTINUE  'y header'".ljust(psrfits.CARD),
        ]
    primary = psrfits._end_pad(primary_cards)
    with open(path, "wb") as f:
        f.write(primary)
        if leading_hdu:
            f.write(aux_hdu("PSRPARAM"))
        f.write(psrfits._end_pad(cards))
        for isub in range(nsub):
            for name in order:
                f.write(col_bytes(name, isub))
        f.write(b"\x00" * ((-f.tell()) % psrfits.BLOCK))
        if trailing_hdu:
            f.write(aux_hdu("HISTORY"))


class TestForeignWriterVariants:
    """Adversarial-but-valid writer variants: every layout here is legal
    PSRFITS an observatory toolchain could emit; the reader must either
    load it to the same Archive or reject with an actionable message
    (io/psrfits.py "Supported PSRFITS matrix")."""

    def _archive(self):
        ar, _ = make_synthetic_archive(nsub=4, nchan=6, nbin=16, seed=11,
                                       n_rfi_cells=2)
        # float32-representable cube so the f32 DATA/DAT_FREQ round-trips
        ar.data = np.asarray(ar.data, dtype=np.float32).astype(np.float64)
        ar.freqs_mhz = np.asarray(
            ar.freqs_mhz, dtype=np.float32).astype(np.float64)
        return ar

    def _assert_loads_equal(self, ar, path):
        for native in (False, True):
            back = psrfits.load_psrfits(path, prefer_native=native)
            np.testing.assert_array_equal(back.data, ar.data)
            np.testing.assert_array_equal(back.weights, ar.weights)
            np.testing.assert_array_equal(back.freqs_mhz, ar.freqs_mhz)
            assert abs(back.period_s - ar.period_s) < 1e-9
            assert back.dm == ar.dm

    def test_reversed_column_order(self, tmp_path):
        ar = self._archive()
        p = str(tmp_path / "rev.sf")
        _write_foreign_variant(ar, p, order=[
            "DATA", "DAT_OFFS", "DAT_SCL", "DAT_WTS", "DAT_FREQ",
            "OFFS_SUB", "TSUBINT"])
        self._assert_loads_equal(ar, p)

    @pytest.mark.parametrize("tdim", ["none", "spaces"])
    def test_tdim_spellings(self, tmp_path, tdim):
        ar = self._archive()
        p = str(tmp_path / f"tdim_{tdim}.sf")
        _write_foreign_variant(ar, p, tdim=tdim)
        self._assert_loads_equal(ar, p)

    def test_extra_hdus_and_everything_at_once(self, tmp_path):
        """The kitchen sink a real observatory file looks like: PSRPARAM
        before SUBINT, HISTORY after it, shuffled columns, spaced TDIM,
        no PERIOD key (TBIN identity resolves it)."""
        ar = self._archive()
        p = str(tmp_path / "sink.sf")
        _write_foreign_variant(
            ar, p, order=["DAT_WTS", "TSUBINT", "DATA", "DAT_FREQ",
                          "DAT_SCL", "OFFS_SUB", "DAT_OFFS"],
            tdim="spaces", period="tbin", leading_hdu=True,
            trailing_hdu=True)
        self._assert_loads_equal(ar, p)

    def test_8bit_data_rejected_actionably(self, tmp_path):
        ar = self._archive()
        p = str(tmp_path / "b8.sf")
        _write_foreign_variant(ar, p, data_code="B")
        with pytest.raises(ValueError, match="DATA column type"):
            psrfits.load_psrfits(p, prefer_native=False)
        # the native reader must not silently misread it either: None
        # (fall back) is acceptable, a loaded Archive is not
        assert psrfits._load_psrfits_native(p) is None

    # --- structural hostiles this repo's writer cannot emit (VERDICT r4 #7)

    def test_continue_long_string_cards(self, tmp_path):
        """FITS long-string convention: a quoted value ending '&' extended
        by CONTINUE cards (psrchive writes long PSRPARAM values this way).
        The file must load identically, and the pure parser must
        reconstruct the full string."""
        ar = self._archive()
        p = str(tmp_path / "cont.sf")
        _write_foreign_variant(ar, p, long_string=True)
        self._assert_loads_equal(ar, p)
        with open(p, "rb") as f:
            cards, _ = psrfits._parse_header(memoryview(f.read()), 0)
        assert cards["OBSERVER"] == (
            "an observer name long enough to need two continuation "
            "cards in the primary header")

    def test_second_subint_hdu_first_wins(self, tmp_path):
        """Two SUBINT HDUs (a multi-HDU ordering no sane writer emits, but
        legal FITS): the FIRST is authoritative for both readers — the
        decoy's conflicting NBIN/NCHAN must not leak into the load."""
        ar = self._archive()
        p = str(tmp_path / "twosub.sf")
        _write_foreign_variant(ar, p)
        decoy_hdr = psrfits._end_pad([
            psrfits._card("XTENSION", "BINTABLE"),
            psrfits._card("BITPIX", 8), psrfits._card("NAXIS", 2),
            psrfits._card("NAXIS1", 8), psrfits._card("NAXIS2", 1),
            psrfits._card("PCOUNT", 0), psrfits._card("GCOUNT", 1),
            psrfits._card("TFIELDS", 1),
            psrfits._card("EXTNAME", "SUBINT"),
            psrfits._card("NBIN", 2), psrfits._card("NCHAN", 1),
            psrfits._card("NPOL", 1),
            psrfits._card("TTYPE1", "DATA"),
            psrfits._card("TFORM1", "2E"),
        ])
        rows = np.zeros(2, dtype=">f4").tobytes()
        with open(p, "ab") as f:
            f.write(decoy_hdr + rows
                    + b"\x00" * ((-len(rows)) % psrfits.BLOCK))
        self._assert_loads_equal(ar, p)

    def test_trailing_garbage_blocks(self, tmp_path):
        """Non-FITS bytes after the last HDU (junk some toolchains leave).
        period='tbin' forces the period resolver's full-file POLYCO walk —
        the walk must stop at the junk instead of raising, and the TBIN
        identity must still resolve the period."""
        ar = self._archive()
        p = str(tmp_path / "junk.sf")
        _write_foreign_variant(ar, p, period="tbin")
        with open(p, "ab") as f:
            f.write(b"\xde\xad\xbe\xef" * (2 * psrfits.BLOCK // 4))
        self._assert_loads_equal(ar, p)

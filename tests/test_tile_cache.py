"""Tile cache policy + exact-streaming residency contract.

Unit half: TileCache is policy-only (parallel/tile_cache.py module
docstring), so every admission/eviction/accounting rule is tested with an
injected fake ``upload`` — no device, no jax arrays.

Integration half: the residency CONTRACT the tentpole promises —
under budget, iterations >= 2 perform zero constant cube uploads (prep
pays the one-cube cost once); at budget 0 the engine degrades to the
classic streaming behaviour whose device residency stays a small multiple
of one tile's inputs, never the whole cube; and in both regimes the masks
stay bit-equal to whole-archive cleaning.
"""

import numpy as np
import pytest

from iterative_cleaner_tpu.parallel.tile_cache import (
    FALLBACK_BUDGET_BYTES,
    TileCache,
    pipelined_sweep,
    resolve_budget_bytes,
)
from iterative_cleaner_tpu.telemetry import MetricsRegistry


def _arr(n_bytes):
    return np.zeros(n_bytes, dtype=np.uint8)


def _cache(budget, registry=None):
    uploads = []

    def upload(a):
        uploads.append(a.nbytes)
        return ("dev", id(a))  # distinct handle per upload

    c = TileCache(budget, registry=registry, upload=upload)
    return c, uploads


# --- budget resolution ------------------------------------------------------

def test_resolve_budget_precedence(monkeypatch):
    monkeypatch.setenv("ICLEAN_STREAM_HBM_MB", "16")
    # explicit config wins over the env
    assert resolve_budget_bytes(8) == 8 * 2 ** 20
    assert resolve_budget_bytes(0) == 0
    # env wins over device defaults
    assert resolve_budget_bytes(None) == 16 * 2 ** 20
    monkeypatch.setenv("ICLEAN_STREAM_HBM_MB", "0")
    assert resolve_budget_bytes(None) == 0
    with pytest.raises(ValueError, match=">= 0"):
        resolve_budget_bytes(-1)
    monkeypatch.setenv("ICLEAN_STREAM_HBM_MB", "-4")
    with pytest.raises(ValueError, match="ICLEAN_STREAM_HBM_MB"):
        resolve_budget_bytes(None)


def test_resolve_budget_device_fraction_and_fallback(monkeypatch):
    monkeypatch.delenv("ICLEAN_STREAM_HBM_MB", raising=False)

    class Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    assert resolve_budget_bytes(None, Dev({"bytes_limit": 100 * 2 ** 20})) \
        == int(100 * 2 ** 20 * 0.4)
    # backends reporting no stats (CPU) get the conservative constant
    assert resolve_budget_bytes(None, Dev({})) == FALLBACK_BUDGET_BYTES
    assert resolve_budget_bytes(None, Dev(None)) == FALLBACK_BUDGET_BYTES


# --- cache policy (no device) ----------------------------------------------

def test_hit_returns_pinned_handle_without_upload():
    c, uploads = _cache(1000)
    a = _arr(100)
    h1 = c.get(("k",), a)
    h2 = c.get(("k",), a)
    assert h1 is h2
    assert len(uploads) == 1
    assert c.stats["hits"] == 1 and c.stats["misses"] == 1
    assert c.stats["hit_bytes"] == 100
    assert c.resident_bytes == 100


def test_lru_eviction_under_budget_pressure():
    c, uploads = _cache(250)
    c.get(("a",), _arr(100))
    c.get(("b",), _arr(100))
    c.get(("a",), _arr(100))          # refresh a: b is now LRU
    c.get(("c",), _arr(100))          # needs room -> evicts b
    assert c.stats["evictions"] == 1
    assert c.resident_bytes == 200
    n_before = len(uploads)
    c.get(("a",), _arr(100))          # a survived the eviction
    assert len(uploads) == n_before
    c.get(("b",), _arr(100))          # b did not: re-upload (miss)
    assert len(uploads) == n_before + 1


def test_oversized_and_keyless_stay_transient():
    c, uploads = _cache(100)
    c.get(("big",), _arr(200))        # over budget: never pinned
    c.get(None, _arr(50))             # keyless: per-iteration varying data
    assert c.resident_bytes == 0
    assert len(uploads) == 2
    assert c.peak_bytes == 250        # both still in flight pre-sync
    c.mark_sync()
    c.get(None, _arr(10))
    assert c.peak_bytes == 250        # sync reclaimed the transients


def test_plan_admission_first_fit():
    c, uploads = _cache(250)
    # only the first two fit: plan() must say not-everything-fits
    assert c.plan([(("a",), 100), (("b",), 100), (("c",), 100)]) is False
    assert c.plan_covers(("a",)) and c.plan_covers(("b",))
    assert not c.plan_covers(("c",))
    c.get(("c",), _arr(100))          # unplanned key streams transient
    assert c.resident_bytes == 0
    c.get(("a",), _arr(100))
    assert c.resident_bytes == 100
    # a plan that fully fits is the all-resident signal
    assert c.plan([(("a",), 100), (("b",), 100)]) is True


def test_adopt_pins_without_h2d():
    c, _ = _cache(100)
    assert c.adopt(("d",), "handle", 80) is True
    assert c.resident_bytes == 80
    assert c.stats["h2d_bytes"] == 0 and c.stats["adopted_bytes"] == 80
    assert c.get(("d",), _arr(80)) == "handle"   # hit, still no upload
    assert c.stats["h2d_bytes"] == 0
    assert c.adopt(("too-big",), "x", 200) is False  # caller lets it go


def test_registry_mirrors_measured_transfers():
    reg = MetricsRegistry()
    c, _ = _cache(150)
    c.registry = reg                   # _cache built it without one
    c.get(("cube", 0), _arr(100), cube=True)
    c.get(("w", 0), _arr(20))
    c.get(("cube", 0), _arr(100), cube=True)   # hit: no new bytes
    c.get(("cube", 1), _arr(100), cube=True)   # 120+100 > 150: evicts both
    c.count_d2h(8)
    c.flush_stats()
    snap = reg.counters
    assert snap["stream_h2d_bytes"] == 220
    assert snap["stream_h2d_cube_bytes"] == 200
    assert snap["stream_h2d_uploads"] == 3
    assert snap["stream_cache_evictions"] == 2
    assert snap["stream_cache_hits"] == 1
    assert snap["stream_cache_misses"] == 3
    assert snap["stream_d2h_bytes"] == 8
    assert reg.gauges["stream_cache_peak_bytes"] == c.peak_bytes


def test_budget_zero_pins_nothing_but_still_meters():
    reg = MetricsRegistry()
    c = TileCache(0, registry=reg, upload=lambda a: "h")
    c.get(("k",), _arr(100), cube=True)
    c.get(("k",), _arr(100), cube=True)
    assert c.resident_bytes == 0 and c.stats["hits"] == 0
    assert c.stats["h2d_bytes"] == 200  # every pass re-streams, measured
    with pytest.raises(ValueError, match=">= 0"):
        TileCache(-1)


# --- pipelined sweep scheduling --------------------------------------------

def _sweep_trace(n_tiles, depth):
    events = []
    pipelined_sweep(
        n_tiles,
        put=lambda i: events.append(("put", i)) or i,
        run=lambda i, ins: events.append(("run", i)) or i,
        drain=lambda i, out: events.append(("drain", i)),
        depth=depth, on_sync=lambda: events.append(("sync", None)))
    return events


def test_sweep_depth1_is_one_tile_lookahead():
    ev = _sweep_trace(4, depth=1)
    # tile i+1 is staged before tile i drains (overlap), but tile i MUST
    # drain before tile i+2 runs — the two-tile residency bound
    for i in range(2, 4):
        assert ev.index(("drain", i - 2)) < ev.index(("run", i))
    assert [e for e in ev if e[0] == "drain"] == \
        [("drain", i) for i in range(4)]
    # every drain is a sync point (the cache's transient reclaim)
    assert sum(1 for e in ev if e[0] == "sync") == 4


def test_sweep_full_depth_dispatches_whole_pass_first():
    ev = _sweep_trace(4, depth=4)
    # all runs precede all drains; drain order still tile order, so the
    # host-side accumulation (and the masks) cannot move with depth
    assert max(ev.index(("run", i)) for i in range(4)) < \
        ev.index(("drain", 0))
    assert [e for e in ev if e[0] == "drain"] == \
        [("drain", i) for i in range(4)]


def test_sweep_trivial_sizes():
    assert _sweep_trace(0, depth=1) == []
    ev = _sweep_trace(1, depth=3)   # depth beyond n_tiles is clamped by use
    assert [e[0] for e in ev] == ["put", "run", "drain", "sync"]


# --- residency contract (integration, CPU jax) -----------------------------

def _residency_fixture():
    from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

    ar, _ = make_synthetic_archive(nsub=32, nchan=16, nbin=32, seed=29,
                                   n_rfi_cells=8, n_rfi_channels=2,
                                   n_prezapped=10)
    return ar


def _clean_with_budget(ar, budget_mb):
    import dataclasses

    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.parallel import clean_streaming_exact

    cfg = dataclasses.replace(CleanConfig(backend="jax", dtype="float64"),
                              stream_hbm_mb=budget_mb)
    reg = MetricsRegistry()
    res = clean_streaming_exact(ar.clone(), 8, cfg, registry=reg)
    return res, reg


def test_streaming_under_budget_uploads_cube_once():
    """The tentpole contract: with the tile set resident, the constant
    cube crosses H2D exactly once (prep), however many iterations run —
    and the masks still match whole-archive cleaning bit-for-bit."""
    from iterative_cleaner_tpu.backends import clean_archive
    from iterative_cleaner_tpu.config import CleanConfig

    ar = _residency_fixture()
    whole = clean_archive(ar.clone(),
                          CleanConfig(backend="jax", dtype="float64"))
    res, reg = _clean_with_budget(ar, 64.0)
    np.testing.assert_array_equal(whole.final_weights, res.final_weights)
    assert res.loops >= 2, "fixture must iterate for the contract to bite"
    cube_bytes = 32 * 16 * 32 * 8  # nsub*nchan*nbin float64: ONE cube
    assert reg.counters["stream_h2d_cube_bytes"] == cube_bytes
    assert reg.counters["stream_cache_hits"] > 0
    assert reg.counters["stream_h2d_bytes"] > 0  # measured, non-zero


def test_streaming_budget_zero_degrades_to_tile_residency():
    """Budget 0 (config or ICLEAN_STREAM_HBM_MB=0): nothing pins, cube
    tiles re-stream every pass, yet peak device residency stays a small
    multiple of one tile's inputs — far under the whole cube — and masks
    are unchanged.  This is the >HBM-observation guarantee."""
    ar = _residency_fixture()
    res_cached, _ = _clean_with_budget(ar, 64.0)
    res0, reg0 = _clean_with_budget(ar, 0.0)
    np.testing.assert_array_equal(res_cached.final_weights,
                                  res0.final_weights)
    assert res_cached.loops == res0.loops
    cube_bytes = 32 * 16 * 32 * 8
    assert reg0.counters["stream_h2d_cube_bytes"] > cube_bytes
    assert reg0.gauges["stream_cache_resident_bytes"] == 0
    # the classic streaming bound: peak residency well under the cube
    # (4 tiles of 8 subints; lookahead holds ~2 tiles' inputs + planes)
    assert reg0.gauges["stream_cache_peak_bytes"] < cube_bytes


def test_streaming_env_budget_knob(monkeypatch):
    """ICLEAN_STREAM_HBM_MB drives the default (config None) budget."""
    ar = _residency_fixture()
    monkeypatch.setenv("ICLEAN_STREAM_HBM_MB", "0")
    res_env, reg_env = _clean_with_budget(ar, None)
    assert reg_env.gauges["stream_cache_budget_bytes"] == 0
    monkeypatch.delenv("ICLEAN_STREAM_HBM_MB")
    res_def, reg_def = _clean_with_budget(ar, None)
    assert reg_def.gauges["stream_cache_budget_bytes"] > 0
    np.testing.assert_array_equal(res_env.final_weights,
                                  res_def.final_weights)

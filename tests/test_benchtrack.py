"""Bench-history regression gate (telemetry/benchtrack, icln-bench).

The committed BENCH_r*.json series must pass its own gate (the CI
invariant), and a seeded regression must fire it — the test that proves
the gate is not vacuously green.
"""

import json

from iterative_cleaner_tpu.telemetry import MetricsRegistry
from iterative_cleaner_tpu.telemetry.benchtrack import (
    TRACKED,
    check_history,
    default_history_dir,
    export_verdicts,
    load_history,
    main,
)


def _round_file(d, n, parsed, rc=0):
    doc = {"n": n, "cmd": "bench", "rc": rc, "tail": "", "parsed": parsed}
    (d / ("BENCH_r%02d.json" % n)).write_text(json.dumps(doc))


def _parsed(**kw):
    base = {"platform": "cpu"}
    base.update(kw)
    return base


# ------------------------------------------------- the committed series

def test_committed_history_passes_its_own_gate():
    history = load_history(default_history_dir())
    assert len(history) >= 2
    result = check_history(history)
    assert result.ok, [v for v in result.verdicts if v.status == "fail"]
    # the flagship throughput key must actually be compared, not "new"
    statuses = {v.key: v.status for v in result.verdicts}
    assert statuses["value"] == "pass"
    # every tracked key produced a verdict row
    assert set(statuses) == set(TRACKED)


# ------------------------------------------------------- seeded regressions

def test_seeded_throughput_regression_fires(tmp_path):
    for n in (1, 2, 3):
        _round_file(tmp_path, n, _parsed(value=100.0 + n))
    _round_file(tmp_path, 4, _parsed(value=50.0))   # -51% >> tol 35%
    result = check_history(load_history(str(tmp_path)))
    assert not result.ok
    fail = {v.key: v for v in result.verdicts}["value"]
    assert fail.status == "fail"
    assert fail.baseline == 102.0                   # median of 101,102,103
    assert fail.latest == 50.0

    reg = MetricsRegistry()
    export_verdicts(result, reg)
    snap = reg.snapshot()["gauges"]
    assert snap["bench_regressions{key=value}"] == 1.0
    assert snap["bench_regressions_total"] == 1.0
    assert snap["bench_rounds_checked"] == 4.0

    assert main(["--check", "--history", str(tmp_path)]) == 1


def test_latency_key_regresses_upward(tmp_path):
    # "lower" direction: ms_per_iter growing past baseline*(1+tol) fails
    for n in (1, 2):
        _round_file(tmp_path, n, _parsed(ms_per_iter=10.0))
    _round_file(tmp_path, 3, _parsed(ms_per_iter=20.0))
    result = check_history(load_history(str(tmp_path)))
    fail = {v.key: v for v in result.verdicts}["ms_per_iter"]
    assert fail.status == "fail" and fail.bound == 13.5


def test_wobble_within_band_passes(tmp_path):
    # the committed series wobbles ~15% round to round; the median
    # baseline plus the loose band must absorb that
    for n, v in enumerate((100.0, 87.0, 113.0, 95.0), start=1):
        _round_file(tmp_path, n, _parsed(value=v))
    result = check_history(load_history(str(tmp_path)))
    assert {v.key: v for v in result.verdicts}["value"].status == "pass"
    assert main(["--check", "--history", str(tmp_path)]) == 0


# ------------------------------------------------ qualification and hygiene

def test_platform_change_resets_the_baseline(tmp_path):
    # TPU rounds never gate a CPU fallback round (and vice versa)
    for n in (1, 2):
        _round_file(tmp_path, n, _parsed(value=100000.0, platform="tpu v4"))
    _round_file(tmp_path, 3, _parsed(value=90.0, platform="cpu"))
    result = check_history(load_history(str(tmp_path)))
    v = {v.key: v for v in result.verdicts}["value"]
    assert v.status == "new" and result.ok


def test_failed_and_unparsed_rounds_are_skipped(tmp_path):
    _round_file(tmp_path, 1, _parsed(value=100.0))
    _round_file(tmp_path, 2, _parsed(value=1.0), rc=1)     # failed run
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"n": 3, "rc": 0, "parsed": None}))     # no payload
    _round_file(tmp_path, 4, _parsed(value=95.0))
    history = load_history(str(tmp_path))
    assert [n for n, _ in history] == [1, 4]
    assert check_history(history).ok


def test_untracked_keys_never_gate(tmp_path):
    _round_file(tmp_path, 1, _parsed(value=100.0, brand_new_metric=5.0))
    _round_file(tmp_path, 2, _parsed(value=100.0, brand_new_metric=0.01))
    result = check_history(load_history(str(tmp_path)))
    assert result.ok
    assert "brand_new_metric" not in {v.key for v in result.verdicts}


def test_cli_exit_codes_for_empty_and_usage(tmp_path):
    assert main(["--check", "--history", str(tmp_path)]) == 2  # no history
    assert main(["--history", str(tmp_path)]) == 2             # no --check

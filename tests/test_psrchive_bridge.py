"""psrchive_bridge against the fake PSRCHIVE backend (tests/fake_psrchive.py):
the bridge's load/write-back paths run without real PSRCHIVE bindings
(SURVEY.md section 4)."""

import sys

import numpy as np
import pytest

from iterative_cleaner_tpu.io import load_archive, save_archive
from iterative_cleaner_tpu.io import psrchive_bridge as bridge
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

from . import fake_psrchive


@pytest.fixture(autouse=True)
def _install_fake(monkeypatch):
    monkeypatch.setitem(sys.modules, "psrchive", fake_psrchive)


@pytest.fixture()
def ar_file(tmp_path):
    ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=3,
                                   n_prezapped=4)
    path = str(tmp_path / "obs.npz")  # the fake reads the npz container
    save_archive(ar, path)
    return path, ar


def test_load_ar_roundtrips_model(ar_file):
    path, ar = ar_file
    got = bridge.load_ar(path)
    np.testing.assert_array_equal(got.data, np.asarray(ar.data))
    np.testing.assert_array_equal(got.weights, ar.weights)
    np.testing.assert_allclose(got.freqs_mhz, ar.freqs_mhz)
    assert got.source == ar.source
    assert got.dm == ar.dm
    assert got.period_s == ar.period_s
    assert got.centre_freq_mhz == ar.centre_freq_mhz
    assert got.mjd_start == ar.mjd_start and got.mjd_end == ar.mjd_end
    assert got.pol_state == ar.pol_state
    assert got.filename == path


def test_apply_weights_to_ar(ar_file, tmp_path):
    path, ar = ar_file
    new_w = ar.weights.copy()
    new_w[2, 3] = 0.0
    new_w[5, 7] = 0.0
    out = str(tmp_path / "out.npz")
    bridge.apply_weights_to_ar(path, out, new_w)
    np.testing.assert_array_equal(load_archive(out).weights, new_w)


def test_map_state():
    assert bridge._map_state("Intensity", 1) == "Intensity"
    assert bridge._map_state("Coherence", 4) == "Coherence"
    assert bridge._map_state("PPQQ", 2) == "Coherence"
    assert bridge._map_state("Stokes", 4) == "Stokes"


def test_save_ar_roundtrips_weights_and_data(ar_file, tmp_path):
    """Clone-and-set write path (reference :60): cleaned weights and edited
    amplitudes land in the output; untouched metadata rides the source."""
    path, _ = ar_file
    model = bridge.load_ar(path)
    model.weights[1, 2] = 0.0
    model.data[0, 0, 1, :] = 7.25  # e.g. a residual write-back
    out = str(tmp_path / "saved.npz")
    bridge.save_ar(model, out)
    got = load_archive(out)
    np.testing.assert_array_equal(got.weights, model.weights)
    np.testing.assert_array_equal(got.data, model.data)
    assert got.source == model.source


def test_save_ar_pscrunched_model_writes_pscrunched_archive(tmp_path):
    """A pscrunched model of a multi-pol source writes a pscrunched archive
    (the reference's -p output is single-pol): save_ar scrunches the
    reload so the model's amplitudes line up and write through."""
    src, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, npol=2,
                                    seed=9, n_prezapped=2)
    path = str(tmp_path / "obs.npz")
    save_archive(src, path)
    model = bridge.load_ar(path)
    model.pscrunch()
    assert model.npol == 1 and src.npol == 2
    new_w = model.weights.copy()
    new_w[3, 4] = 0.0
    model.weights[:] = new_w
    out = str(tmp_path / "saved2.npz")
    bridge.save_ar(model, out)
    got = load_archive(out)
    assert got.npol == 1
    np.testing.assert_array_equal(got.weights, new_w)
    np.testing.assert_array_equal(got.data, model.data)


def test_save_ar_reshaped_bins_keep_source_amplitudes(tmp_path):
    """A model whose bin axis no longer matches the source cannot write
    amplitudes back: weights write through, data stays the source's."""
    import dataclasses

    src, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=9)
    path = str(tmp_path / "obs.npz")
    save_archive(src, path)
    model = bridge.load_ar(path)
    model = dataclasses.replace(model, data=model.data[:, :, :, :16])
    new_w = model.weights.copy()
    new_w[1, 1] = 0.0
    model.weights[:] = new_w
    out = str(tmp_path / "saved3.npz")
    bridge.save_ar(model, out)
    got = load_archive(out)
    np.testing.assert_array_equal(got.weights, new_w)
    np.testing.assert_array_equal(got.data, np.asarray(src.data))


def test_save_ar_needs_source_file():
    ar, _ = make_synthetic_archive(nsub=2, nchan=4, nbin=8)
    assert ar.filename == ""
    with pytest.raises(ValueError, match="filename"):
        bridge.save_ar(ar, "x.ar")


def test_save_ar_rejects_reshaped_cell_grid(ar_file, tmp_path):
    path, _ = ar_file
    model = bridge.load_ar(path)
    import dataclasses

    model = dataclasses.replace(model, data=model.data[:-1],
                                weights=model.weights[:-1])
    with pytest.raises(ValueError, match="cell grid"):
        bridge.save_ar(model, str(tmp_path / "bad.npz"))


def test_save_archive_routes_timer_source_via_bridge(tmp_path, monkeypatch):
    """io.save_archive keeps a TIMER-sourced .ar in TIMER format: the
    reference's unload writes the source's own format class (ref :60)."""
    import dataclasses

    src = tmp_path / "src.ar"
    src.write_bytes(b"not a FITS file")  # no FITS magic => TIMER-format
    ar, _ = make_synthetic_archive(nsub=2, nchan=4, nbin=8)
    ar = dataclasses.replace(ar, filename=str(src))
    calls = {}
    monkeypatch.setattr(bridge, "save_ar",
                        lambda a, p: calls.setdefault("path", p))
    out = str(tmp_path / "out.ar")
    save_archive(ar, out)
    assert calls["path"] == out


def test_save_archive_fits_ar_stays_psrfits(tmp_path):
    """A PSRFITS-sourced (or source-less) .ar write keeps the built-in
    PSRFITS layout — the bridge is only for TIMER sources."""
    ar, _ = make_synthetic_archive(nsub=2, nchan=4, nbin=8)
    out = str(tmp_path / "out.ar")
    save_archive(ar, out)
    from iterative_cleaner_tpu.io import psrfits

    assert psrfits.is_fits(out)


def test_clear_error_without_psrchive(monkeypatch, ar_file):
    monkeypatch.setitem(sys.modules, "psrchive", None)
    with pytest.raises(ImportError, match="psrchive"):
        bridge.load_ar(ar_file[0])

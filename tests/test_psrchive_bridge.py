"""psrchive_bridge against the fake PSRCHIVE backend (tests/fake_psrchive.py):
the bridge's load/write-back paths run without real PSRCHIVE bindings
(SURVEY.md section 4)."""

import sys

import numpy as np
import pytest

from iterative_cleaner_tpu.io import load_archive, save_archive
from iterative_cleaner_tpu.io import psrchive_bridge as bridge
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

from . import fake_psrchive


@pytest.fixture(autouse=True)
def _install_fake(monkeypatch):
    monkeypatch.setitem(sys.modules, "psrchive", fake_psrchive)


@pytest.fixture()
def ar_file(tmp_path):
    ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, seed=3,
                                   n_prezapped=4)
    path = str(tmp_path / "obs.npz")  # the fake reads the npz container
    save_archive(ar, path)
    return path, ar


def test_load_ar_roundtrips_model(ar_file):
    path, ar = ar_file
    got = bridge.load_ar(path)
    np.testing.assert_array_equal(got.data, np.asarray(ar.data))
    np.testing.assert_array_equal(got.weights, ar.weights)
    np.testing.assert_allclose(got.freqs_mhz, ar.freqs_mhz)
    assert got.source == ar.source
    assert got.dm == ar.dm
    assert got.period_s == ar.period_s
    assert got.centre_freq_mhz == ar.centre_freq_mhz
    assert got.mjd_start == ar.mjd_start and got.mjd_end == ar.mjd_end
    assert got.pol_state == ar.pol_state
    assert got.filename == path


def test_apply_weights_to_ar(ar_file, tmp_path):
    path, ar = ar_file
    new_w = ar.weights.copy()
    new_w[2, 3] = 0.0
    new_w[5, 7] = 0.0
    out = str(tmp_path / "out.npz")
    bridge.apply_weights_to_ar(path, out, new_w)
    np.testing.assert_array_equal(load_archive(out).weights, new_w)


def test_map_state():
    assert bridge._map_state("Intensity", 1) == "Intensity"
    assert bridge._map_state("Coherence", 4) == "Coherence"
    assert bridge._map_state("PPQQ", 2) == "Coherence"
    assert bridge._map_state("Stokes", 4) == "Stokes"


def test_save_ar_refuses():
    ar, _ = make_synthetic_archive(nsub=2, nchan=4, nbin=8)
    with pytest.raises(NotImplementedError):
        bridge.save_ar(ar, "x.ar")


def test_clear_error_without_psrchive(monkeypatch, ar_file):
    monkeypatch.setitem(sys.modules, "psrchive", None)
    with pytest.raises(ImportError, match="psrchive"):
        bridge.load_ar(ar_file[0])

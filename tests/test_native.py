"""Native C++ ICAR loader (native/archive_io.cpp) vs the pure-Python path.

Builds libicar.so on demand (skipped when no C++ toolchain is available) and
checks byte-level roundtrip equality between the two implementations, plus
rejection of corrupt files.
"""

import numpy as np
import pytest

from iterative_cleaner_tpu.io import native as native_mod
from iterative_cleaner_tpu.io.native import load_icar, save_icar
from iterative_cleaner_tpu.io.synthetic import make_synthetic_archive

@pytest.fixture(scope="module")
def native_lib():
    if not native_mod.build_native():
        pytest.skip("C++ toolchain unavailable; native path untested")
    assert native_mod.native_available()
    return native_mod._load_lib()


def _roundtrip(ar, path, use_native):
    """save+load with the native path forced on or off."""
    orig = native_mod.native_available
    native_mod.native_available = lambda: use_native
    try:
        save_icar(ar, path)
        return load_icar(path)
    finally:
        native_mod.native_available = orig


def test_native_roundtrip_matches_python(native_lib, tmp_path):
    ar, _ = make_synthetic_archive(nsub=6, nchan=10, nbin=32, npol=2, seed=3)
    p_native = str(tmp_path / "n.icar")
    p_python = str(tmp_path / "p.icar")

    back_n = _roundtrip(ar, p_native, use_native=True)
    back_p = _roundtrip(ar, p_python, use_native=False)

    # identical bytes on disk from both writers
    with open(p_native, "rb") as f1, open(p_python, "rb") as f2:
        assert f1.read() == f2.read()

    for a, b in ((back_n, back_p), (back_n, ar)):
        np.testing.assert_array_equal(a.data, np.asarray(b.data, np.float32))
        np.testing.assert_array_equal(a.weights,
                                      np.asarray(b.weights, np.float32))
        np.testing.assert_array_equal(a.freqs_mhz, b.freqs_mhz)
        assert a.source == b.source
        assert a.period_s == b.period_s
        assert a.dm == b.dm
        assert a.pol_state == b.pol_state


def test_native_cross_reader(native_lib, tmp_path):
    """Python-written file read by the native loader and vice versa."""
    ar, _ = make_synthetic_archive(nsub=4, nchan=8, nbin=16, seed=1)
    path = str(tmp_path / "x.icar")
    _roundtrip(ar, path, use_native=False)  # python writer
    # read the python-written file through the native loader directly
    back = native_mod._load_icar_native(path)
    np.testing.assert_array_equal(back.data, np.asarray(ar.data, np.float32))
    np.testing.assert_array_equal(back.weights,
                                  np.asarray(ar.weights, np.float32))


def test_native_rejects_corrupt(native_lib, tmp_path):
    bad = tmp_path / "bad.icar"
    bad.write_bytes(b"NOTICAR!" + b"\x00" * 200)
    with pytest.raises(OSError):
        native_mod._load_icar_native(str(bad))

    trunc = tmp_path / "trunc.icar"
    ar, _ = make_synthetic_archive(nsub=4, nchan=8, nbin=16, seed=1)
    full = tmp_path / "full.icar"
    save_icar(ar, str(full))
    trunc.write_bytes(full.read_bytes()[:200])  # header ok, arrays missing
    with pytest.raises(OSError):
        native_mod._load_icar_native(str(trunc))


def test_native_write_reports_errors(native_lib):
    ar, _ = make_synthetic_archive(nsub=2, nchan=4, nbin=8, seed=0)
    orig = native_mod.native_available
    native_mod.native_available = lambda: True
    try:
        with pytest.raises(OSError):
            save_icar(ar, "/nonexistent-dir/x.icar")
    finally:
        native_mod.native_available = orig
